"""Independent pandas oracles for the TPC-DS corpus (answer validation).

Each ``qNN(T)`` transcribes ``benchmarking/tpcds`` query NN directly from
its SQL text into pandas and returns ``(expected_df, meta)`` where meta
carries the ORDER BY spec so the checker can honor LIMIT-with-ties:

    meta = {"keys": [...], "asc": [...], "limit": N or None,
            "approx": [float cols], "unordered": bool}

The oracles deliberately use a different execution substrate (pandas
merges/groupbys) than the engine (its own planner + kernels), so a
planner/lowering bug shows as a mismatch rather than being mirrored.
Reference analogue: ``benchmarking/tpch/answers.py`` +
``tests/integration/test_tpch.py`` validate TPC-H the same way.

NULL-sum semantics: SQL SUM over an empty/all-NULL set is NULL, pandas
``sum()`` is 0 — transcriptions use ``_sum`` (min_count=1) wherever the
distinction can surface.
"""

import numpy as np
import pandas as pd


class Tables:
    """Lazy pandas view over the generated TPC-DS dataset."""

    def __init__(self, get_df):
        self._get = get_df
        self._cache = {}

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        if name not in self._cache:
            self._cache[name] = self._get(name).to_pandas()
        return self._cache[name]


def _sum(s):
    return s.sum(min_count=1)


def sql_sort(df, keys, ascending):
    """Engine semantics: ASC → NULLS LAST, DESC → NULLS FIRST."""
    out = df
    for k, asc in reversed(list(zip(keys, ascending))):
        out = out.sort_values(k, ascending=asc, kind="stable",
                              na_position="last" if asc else "first")
    return out.reset_index(drop=True)


def meta(keys=(), asc=None, limit=100, approx=(), unordered=False):
    keys = list(keys)
    return {"keys": keys,
            "asc": list(asc) if asc is not None else [True] * len(keys),
            "limit": limit, "approx": list(approx), "unordered": unordered}


# ---------------------------------------------------------------- helpers

def _star(ss, *joins):
    """Inner-merge a fact frame through (dim_frame, left_key, right_key)."""
    out = ss
    for dim, lk, rk in joins:
        out = out.merge(dim, left_on=lk, right_on=rk)
    return out


def _dates_between(dd, lo, hi):
    d = pd.to_datetime(dd.d_date)
    return dd[(d >= pd.Timestamp(lo)) & (d <= pd.Timestamp(hi))]


# ---------------------------------------------------------------- oracles

def q3(T):
    j = _star(T.store_sales,
              (T.date_dim, "ss_sold_date_sk", "d_date_sk"),
              (T.item, "ss_item_sk", "i_item_sk"))
    j = j[(j.i_manufact_id == 128) & (j.d_moy == 11)]
    out = (j.groupby(["d_year", "i_brand_id", "i_brand"], as_index=False)
           .agg(sum_agg=("ss_ext_sales_price", _sum)))
    return out, meta(["d_year", "sum_agg", "i_brand_id"],
                     [True, False, True], 100, ["sum_agg"])


def q7(T):
    j = _star(T.store_sales,
              (T.date_dim, "ss_sold_date_sk", "d_date_sk"),
              (T.item, "ss_item_sk", "i_item_sk"),
              (T.customer_demographics, "ss_cdemo_sk", "cd_demo_sk"),
              (T.promotion, "ss_promo_sk", "p_promo_sk"))
    j = j[(j.cd_gender == "M") & (j.cd_marital_status == "S")
          & (j.cd_education_status == "College")
          & ((j.p_channel_email == "N") | (j.p_channel_event == "N"))
          & (j.d_year == 2000)]
    out = (j.groupby("i_item_id", as_index=False)
           .agg(agg1=("ss_quantity", "mean"),
                agg2=("ss_list_price", "mean"),
                agg3=("ss_coupon_amt", "mean"),
                agg4=("ss_sales_price", "mean")))
    return out, meta(["i_item_id"], None, 100,
                     ["agg1", "agg2", "agg3", "agg4"])


def q19(T):
    j = _star(T.store_sales,
              (T.date_dim, "ss_sold_date_sk", "d_date_sk"),
              (T.item, "ss_item_sk", "i_item_sk"),
              (T.customer, "ss_customer_sk", "c_customer_sk"),
              (T.customer_address, "c_current_addr_sk", "ca_address_sk"),
              (T.store, "ss_store_sk", "s_store_sk"))
    j = j[(j.i_manager_id.between(1, 40)) & (j.d_moy == 11)
          & (j.d_year == 1999)]
    out = (j.groupby(["i_brand_id", "i_brand", "i_manufact_id"],
                     as_index=False)
           .agg(ext_price=("ss_ext_sales_price", _sum)))
    return out, meta(["ext_price", "i_brand_id", "i_manufact_id"],
                     [False, True, True], 100, ["ext_price"])


def q26(T):
    j = _star(T.store_sales,
              (T.date_dim, "ss_sold_date_sk", "d_date_sk"),
              (T.item, "ss_item_sk", "i_item_sk"),
              (T.customer_demographics, "ss_cdemo_sk", "cd_demo_sk"),
              (T.promotion, "ss_promo_sk", "p_promo_sk"))
    j = j[(j.cd_gender == "F") & (j.cd_marital_status == "W")
          & (j.cd_education_status == "Primary")
          & ((j.p_channel_email == "N") | (j.p_channel_event == "N"))
          & (j.d_year == 2000)]
    out = (j.groupby("i_item_id", as_index=False)
           .agg(agg1=("ss_quantity", "mean"),
                agg2=("ss_list_price", "mean"),
                agg3=("ss_coupon_amt", "mean"),
                agg4=("ss_sales_price", "mean")))
    return out, meta(["i_item_id"], None, 100,
                     ["agg1", "agg2", "agg3", "agg4"])


def q42(T):
    j = _star(T.store_sales,
              (T.date_dim, "ss_sold_date_sk", "d_date_sk"),
              (T.item, "ss_item_sk", "i_item_sk"))
    j = j[(j.i_manager_id == 1) & (j.d_moy == 11) & (j.d_year == 2000)]
    out = (j.groupby(["d_year", "i_category_id", "i_category"],
                     as_index=False)
           .agg(sum_sales=("ss_ext_sales_price", _sum)))
    return out, meta(["sum_sales", "d_year", "i_category_id", "i_category"],
                     [False, True, True, True], 100, ["sum_sales"])


def q52(T):
    j = _star(T.store_sales,
              (T.date_dim, "ss_sold_date_sk", "d_date_sk"),
              (T.item, "ss_item_sk", "i_item_sk"))
    j = j[(j.i_manager_id == 1) & (j.d_moy == 11) & (j.d_year == 2000)]
    out = (j.groupby(["d_year", "i_brand_id", "i_brand"], as_index=False)
           .agg(ext_price=("ss_ext_sales_price", _sum)))
    return out, meta(["d_year", "ext_price", "i_brand_id"],
                     [True, False, True], 100, ["ext_price"])


def q55(T):
    j = _star(T.store_sales,
              (T.date_dim, "ss_sold_date_sk", "d_date_sk"),
              (T.item, "ss_item_sk", "i_item_sk"))
    j = j[(j.i_manager_id == 28) & (j.d_moy == 11) & (j.d_year == 1999)]
    out = (j.groupby(["i_brand_id", "i_brand"], as_index=False)
           .agg(ext_price=("ss_ext_sales_price", _sum)))
    return out, meta(["ext_price", "i_brand_id"], [False, True], 100,
                     ["ext_price"])


def q96(T):
    j = _star(T.store_sales,
              (T.household_demographics, "ss_hdemo_sk", "hd_demo_sk"),
              (T.time_dim, "ss_sold_time_sk", "t_time_sk"),
              (T.store, "ss_store_sk", "s_store_sk"))
    n = len(j[(j.t_hour == 20) & (j.t_minute >= 30) & (j.hd_dep_count == 7)])
    return pd.DataFrame({"cnt": [n]}), meta([], None, 100)


def q13(T):
    j = _star(T.store_sales,
              (T.store, "ss_store_sk", "s_store_sk"),
              (T.date_dim, "ss_sold_date_sk", "d_date_sk"),
              (T.customer_demographics, "ss_cdemo_sk", "cd_demo_sk"),
              (T.household_demographics, "ss_hdemo_sk", "hd_demo_sk"),
              (T.customer_address, "ss_addr_sk", "ca_address_sk"))
    j = j[j.d_year == 2001]
    demo = (((j.cd_marital_status == "M")
             & (j.cd_education_status == "Advanced Degree")
             & j.ss_sales_price.between(100.0, 150.0)
             & (j.hd_dep_count == 3))
            | ((j.cd_marital_status == "S")
               & (j.cd_education_status == "College")
               & j.ss_sales_price.between(50.0, 100.0)
               & (j.hd_dep_count == 1))
            | ((j.cd_marital_status == "W")
               & (j.cd_education_status == "Secondary")
               & j.ss_sales_price.between(150.0, 200.0)
               & (j.hd_dep_count == 1)))
    addr = ((j.ca_country == "United States")
            & ((j.ca_state.isin(["TX", "OR", "WA"])
                & j.ss_net_profit.between(100, 200))
               | (j.ca_state.isin(["CA", "NY", "TN"])
                  & j.ss_net_profit.between(150, 300))
               | (j.ca_state.isin(["SD", "GA", "KY"])
                  & j.ss_net_profit.between(50, 250))))
    j = j[demo & addr]
    out = pd.DataFrame({
        "avg_q": [j.ss_quantity.mean() if len(j) else None],
        "avg_esp": [j.ss_ext_sales_price.mean() if len(j) else None],
        "avg_ewc": [j.ss_ext_wholesale_cost.mean() if len(j) else None],
        "sum_ewc": [_sum(j.ss_ext_wholesale_cost) if len(j) else None]})
    return out, meta([], None, None,
                     ["avg_q", "avg_esp", "avg_ewc", "sum_ewc"])


def q48(T):
    j = _star(T.store_sales,
              (T.store, "ss_store_sk", "s_store_sk"),
              (T.date_dim, "ss_sold_date_sk", "d_date_sk"),
              (T.customer_demographics, "ss_cdemo_sk", "cd_demo_sk"),
              (T.customer_address, "ss_addr_sk", "ca_address_sk"))
    j = j[j.d_year == 2000]
    demo = (((j.cd_marital_status == "M")
             & (j.cd_education_status == "College")
             & j.ss_sales_price.between(100.0, 150.0))
            | ((j.cd_marital_status == "D")
               & (j.cd_education_status == "Primary")
               & j.ss_sales_price.between(50.0, 100.0))
            | ((j.cd_marital_status == "W")
               & (j.cd_education_status == "Secondary")
               & j.ss_sales_price.between(150.0, 200.0)))
    addr = ((j.ca_country == "United States")
            & ((j.ca_state.isin(["TX", "NM", "OR"])
                & j.ss_net_profit.between(0, 2000))
               | (j.ca_state.isin(["CA", "NY", "WA"])
                  & j.ss_net_profit.between(150, 3000))
               | (j.ca_state.isin(["TN", "GA", "KY"])
                  & j.ss_net_profit.between(50, 25000))))
    j = j[demo & addr]
    return pd.DataFrame({"total_q": [_sum(j.ss_quantity)]}), \
        meta([], None, None, ["total_q"])


def q43(T):
    j = _star(T.store_sales,
              (T.date_dim, "ss_sold_date_sk", "d_date_sk"),
              (T.store, "ss_store_sk", "s_store_sk"))
    j = j[(j.d_year == 2000) & (j.s_gmt_offset == -5.0)]
    days = {"sun_sales": "Sunday", "mon_sales": "Monday",
            "fri_sales": "Friday", "sat_sales": "Saturday"}
    gb = j.groupby(["s_store_name", "s_store_sk"])
    out = gb.size().reset_index().drop(columns=0)
    for cname, day in days.items():
        s = (j[j.d_day_name == day]
             .groupby(["s_store_name", "s_store_sk"])["ss_sales_price"]
             .apply(_sum).rename(cname).reset_index())
        out = out.merge(s, on=["s_store_name", "s_store_sk"], how="left")
    return out, meta(["s_store_name", "s_store_sk"], None, 100,
                     list(days))


def q34(T):
    j = _star(T.store_sales,
              (T.date_dim, "ss_sold_date_sk", "d_date_sk"),
              (T.store, "ss_store_sk", "s_store_sk"),
              (T.household_demographics, "ss_hdemo_sk", "hd_demo_sk"))
    j = j[j.d_dom.between(1, 3) & (j.hd_vehicle_count > 0)
          & (j.d_year == 2000)]
    t = (j.groupby(["ss_ticket_number", "ss_customer_sk"], as_index=False)
         .size().rename(columns={"size": "cnt"}))
    t = t[t.cnt.between(15, 20)]
    out = t.merge(T.customer, left_on="ss_customer_sk",
                  right_on="c_customer_sk")
    out = out[["c_last_name", "c_first_name", "ss_ticket_number", "cnt"]]
    return out, meta(["c_last_name", "c_first_name", "ss_ticket_number"],
                     [True, True, False], None)


def q73(T):
    j = _star(T.store_sales,
              (T.date_dim, "ss_sold_date_sk", "d_date_sk"),
              (T.store, "ss_store_sk", "s_store_sk"),
              (T.household_demographics, "ss_hdemo_sk", "hd_demo_sk"))
    j = j[j.d_dom.between(1, 2)
          & j.hd_buy_potential.isin([">10000", "Unknown"])
          & (j.hd_vehicle_count > 0) & (j.d_year == 2000)]
    t = (j.groupby(["ss_ticket_number", "ss_customer_sk"], as_index=False)
         .size().rename(columns={"size": "cnt"}))
    t = t[t.cnt.between(1, 5)]
    out = t.merge(T.customer, left_on="ss_customer_sk",
                  right_on="c_customer_sk")
    out = out[["c_last_name", "c_first_name", "ss_ticket_number", "cnt"]]
    return out, meta(["cnt", "c_last_name"], [False, True], None)


def q15(T):
    j = _star(T.catalog_sales,
              (T.customer, "cs_bill_customer_sk", "c_customer_sk"),
              (T.customer_address, "c_current_addr_sk", "ca_address_sk"),
              (T.date_dim, "cs_sold_date_sk", "d_date_sk"))
    zips = ("85669", "86197", "88274", "83405", "86475", "85392", "85460",
            "80348", "81792")
    j = j[(j.ca_zip.astype(str).str[:5].isin(zips)
           | j.ca_state.isin(["CA", "WA", "GA"]) | (j.cs_sales_price > 500))
          & (j.d_qoy == 2) & (j.d_year == 2000)]
    out = (j.groupby("ca_zip", as_index=False)
           .agg(total_sales=("cs_sales_price", _sum)))
    return out, meta(["ca_zip"], None, 100, ["total_sales"])


def q45(T):
    it = T.item
    wanted_ids = set(it[it.i_item_sk.isin(
        [2, 3, 5, 7, 11, 13, 17, 19, 23, 29])].i_item_id)
    j = _star(T.web_sales,
              (T.customer, "ws_bill_customer_sk", "c_customer_sk"),
              (T.customer_address, "c_current_addr_sk", "ca_address_sk"),
              (it, "ws_item_sk", "i_item_sk"),
              (T.date_dim, "ws_sold_date_sk", "d_date_sk"))
    zips = ("85669", "86197", "88274", "83405", "86475", "85392", "85460",
            "80348", "81792")
    j = j[(j.ca_zip.astype(str).str[:5].isin(zips)
           | j.i_item_id.isin(wanted_ids))
          & (j.d_qoy == 2) & (j.d_year == 2000)]
    out = (j.groupby(["ca_zip", "ca_city"], as_index=False)
           .agg(total_sales=("ws_sales_price", _sum)))
    return out, meta(["ca_zip", "ca_city"], None, 100, ["total_sales"])


def q61(T):
    base = _star(T.store_sales,
                 (T.date_dim, "ss_sold_date_sk", "d_date_sk"),
                 (T.store, "ss_store_sk", "s_store_sk"),
                 (T.customer, "ss_customer_sk", "c_customer_sk"),
                 (T.customer_address, "c_current_addr_sk", "ca_address_sk"),
                 (T.item, "ss_item_sk", "i_item_sk"))
    base = base[(base.ca_gmt_offset == -5) & (base.s_gmt_offset == -5)
                & (base.i_category == "Jewelry") & (base.d_year == 2000)
                & (base.d_moy == 11)]
    promo = base.merge(T.promotion, left_on="ss_promo_sk",
                       right_on="p_promo_sk")
    promo = promo[(promo.p_channel_dmail == "Y")
                  | (promo.p_channel_email == "Y")
                  | (promo.p_channel_tv == "Y")]
    p = _sum(promo.ss_ext_sales_price)
    t = _sum(base.ss_ext_sales_price)
    out = pd.DataFrame({"promotions": [p], "total": [t],
                        "ratio": [float(p) / float(t) * 100
                                  if t and not pd.isna(t) else None]})
    return out, meta([], None, 100, ["promotions", "total", "ratio"])


def q88(T):
    j = _star(T.store_sales,
              (T.household_demographics, "ss_hdemo_sk", "hd_demo_sk"),
              (T.time_dim, "ss_sold_time_sk", "t_time_sk"),
              (T.store, "ss_store_sk", "s_store_sk"))
    j = j[(((j.hd_dep_count == 4) & (j.hd_vehicle_count <= 6))
           | ((j.hd_dep_count == 2) & (j.hd_vehicle_count <= 4))
           | ((j.hd_dep_count == 0) & (j.hd_vehicle_count <= 2)))
          & (j.s_store_name == "ese")]
    out = pd.DataFrame({
        "h8_30_to_9": [len(j[(j.t_hour == 8) & (j.t_minute >= 30)])],
        "h9_to_9_30": [len(j[(j.t_hour == 9) & (j.t_minute < 30)])],
        "h9_30_to_10": [len(j[(j.t_hour == 9) & (j.t_minute >= 30)])],
        "h10_to_10_30": [len(j[(j.t_hour == 10) & (j.t_minute < 30)])]})
    return out, meta([], None, None)


def q90(T):
    j = _star(T.web_sales,
              (T.household_demographics, "ws_ship_hdemo_sk", "hd_demo_sk"),
              (T.time_dim, "ws_sold_time_sk", "t_time_sk"),
              (T.web_page, "ws_web_page_sk", "wp_web_page_sk"))
    j = j[(j.hd_dep_count == 6) & j.wp_char_count.between(5000, 5200)]
    amc = len(j[j.t_hour.between(8, 9)])
    pmc = len(j[j.t_hour.between(19, 20)])
    out = pd.DataFrame(
        {"am_pm_ratio": [float(amc) / float(pmc) if pmc else None]})
    return out, meta([], None, 100, ["am_pm_ratio"])


def q9(T):
    ss = T.store_sales
    vals = []
    for lo, hi in ((1, 20), (21, 40), (41, 60), (61, 80), (81, 100)):
        b = ss[ss.ss_quantity.between(lo, hi)]
        vals.append(b.ss_ext_discount_amt.mean() if len(b) > 1000
                    else b.ss_net_paid.mean())
    out = pd.DataFrame({f"bucket{i + 1}": [v] for i, v in enumerate(vals)})
    return out, meta([], None, None, [f"bucket{i}" for i in range(1, 6)])


def q28(T):
    ss = T.store_sales
    specs = [((0, 5), (8, 18), (459, 1459), (57, 77)),
             ((6, 10), (90, 100), (2323, 3323), (31, 51)),
             ((11, 15), (142, 152), (12214, 13214), (79, 99)),
             ((16, 20), (135, 145), (6071, 7071), (38, 58)),
             ((21, 25), (122, 132), (836, 1836), (17, 37)),
             ((26, 30), (154, 164), (7326, 8326), (7, 27))]
    cols = {}
    for i, (q, lp, cp, wc) in enumerate(specs, 1):
        b = ss[ss.ss_quantity.between(*q)
               & (ss.ss_list_price.between(*lp)
                  | ss.ss_coupon_amt.between(*cp)
                  | ss.ss_wholesale_cost.between(*wc))]
        cols[f"b{i}_lp"] = [b.ss_list_price.mean()]
        cols[f"b{i}_cnt"] = [int(b.ss_list_price.count())]
        cols[f"b{i}_cntd"] = [int(b.ss_list_price.nunique())]
    return pd.DataFrame(cols), meta(
        [], None, 100, [f"b{i}_lp" for i in range(1, 7)])


def q62(T):
    j = _star(T.web_sales,
              (T.date_dim, "ws_ship_date_sk", "d_date_sk"),
              (T.warehouse, "ws_warehouse_sk", "w_warehouse_sk"),
              (T.ship_mode, "ws_ship_mode_sk", "sm_ship_mode_sk"),
              (T.web_site, "ws_web_site_sk", "web_site_sk"))
    j = j[j.d_month_seq.between(1212, 1223)]
    j = j.assign(wh=j.w_warehouse_name.astype(str).str[:20],
                 lag=j.ws_ship_date_sk - j.ws_sold_date_sk)
    out = (j.groupby(["wh", "sm_type", "web_name"], as_index=False)
           .agg(days_30=("lag", lambda s: int((s <= 30).sum())),
                days_31_60=("lag", lambda s: int(((s > 30) & (s <= 60)).sum())),
                days_61_90=("lag", lambda s: int(((s > 60) & (s <= 90)).sum())),
                days_91_120=("lag",
                             lambda s: int(((s > 90) & (s <= 120)).sum())),
                days_over_120=("lag", lambda s: int((s > 120).sum()))))
    return out, meta(["wh", "sm_type", "web_name"], None, 100)


def q99(T):
    j = _star(T.catalog_sales,
              (T.date_dim, "cs_ship_date_sk", "d_date_sk"),
              (T.warehouse, "cs_warehouse_sk", "w_warehouse_sk"),
              (T.ship_mode, "cs_ship_mode_sk", "sm_ship_mode_sk"),
              (T.call_center, "cs_call_center_sk", "cc_call_center_sk"))
    j = j[j.d_month_seq.between(1212, 1223)]
    j = j.assign(wh=j.w_warehouse_name.astype(str).str[:20],
                 lag=j.cs_ship_date_sk - j.cs_sold_date_sk)
    out = (j.groupby(["wh", "sm_type", "cc_name"], as_index=False)
           .agg(days_30=("lag", lambda s: int((s <= 30).sum())),
                days_31_60=("lag", lambda s: int(((s > 30) & (s <= 60)).sum())),
                days_61_90=("lag", lambda s: int(((s > 60) & (s <= 90)).sum())),
                days_91_120=("lag",
                             lambda s: int(((s > 90) & (s <= 120)).sum())),
                days_over_120=("lag", lambda s: int((s > 120).sum()))))
    return out, meta(["wh", "sm_type", "cc_name"], None, 100)


def q50(T):
    ss = T.store_sales
    sr = T.store_returns
    j = ss.merge(sr, left_on=["ss_ticket_number", "ss_item_sk",
                              "ss_customer_sk"],
                 right_on=["sr_ticket_number", "sr_item_sk",
                           "sr_customer_sk"])
    d2 = T.date_dim[(T.date_dim.d_year == 2000) & (T.date_dim.d_moy == 8)]
    j = j.merge(d2, left_on="sr_returned_date_sk", right_on="d_date_sk")
    j = j.merge(T.store, left_on="ss_store_sk", right_on="s_store_sk")
    j = j.assign(lag=j.sr_returned_date_sk - j.ss_sold_date_sk)
    out = (j.groupby(["s_store_name", "s_company_id", "s_street_number",
                      "s_street_name"], as_index=False)
           .agg(days_30=("lag", lambda s: int((s <= 30).sum())),
                days_31_60=("lag", lambda s: int(((s > 30) & (s <= 60)).sum())),
                days_61_90=("lag", lambda s: int(((s > 60) & (s <= 90)).sum())),
                days_91_120=("lag",
                             lambda s: int(((s > 90) & (s <= 120)).sum())),
                days_over_120=("lag", lambda s: int((s > 120).sum()))))
    return out, meta(["s_store_name", "s_company_id"], None, 100)


def q41(T):
    it = T.item
    inner = it[((it.i_category == "Women")
                & it.i_color.isin(["powder", "orchid"])
                & it.i_units.isin(["Oz", "Each"])
                & it.i_size.isin(["medium", "N/A"]))
               | ((it.i_category == "Men")
                  & it.i_color.isin(["slate", "navy"])
                  & it.i_units.isin(["Bunch", "Ton"])
                  & it.i_size.isin(["large", "petite"]))]
    manufs = set(inner.i_manufact)
    j = it[it.i_manufact_id.between(70, 110) & it.i_manufact.isin(manufs)]
    out = pd.DataFrame(
        {"i_product_name": sorted(j.i_product_name.unique())})
    return out, meta(["i_product_name"], None, 100)


def q93(T):
    ss = T.store_sales
    sr = T.store_returns
    j = ss.merge(sr, left_on=["ss_item_sk", "ss_ticket_number"],
                 right_on=["sr_item_sk", "sr_ticket_number"], how="left")
    j = j.merge(T.reason, left_on="sr_reason_sk", right_on="r_reason_sk")
    j = j[j.r_reason_desc == "reason 1"]
    act = np.where(j.sr_return_quantity.notna(),
                   (j.ss_quantity - j.sr_return_quantity) * j.ss_sales_price,
                   j.ss_quantity * j.ss_sales_price)
    j = j.assign(act_sales=act)
    out = (j.groupby("ss_customer_sk", as_index=False, dropna=False)
           .agg(sumsales=("act_sales", _sum)))
    out = out[["ss_customer_sk", "sumsales"]]
    return out, meta(["sumsales", "ss_customer_sk"], None, 100,
                     ["sumsales"])


def q84(T):
    j = T.customer.merge(T.customer_address[
        T.customer_address.ca_city == "hilltop"],
        left_on="c_current_addr_sk", right_on="ca_address_sk")
    ib = T.income_band[(T.income_band.ib_lower_bound >= 30000)
                       & (T.income_band.ib_upper_bound <= 80000)]
    hd = T.household_demographics.merge(
        ib, left_on="hd_income_band_sk", right_on="ib_income_band_sk")
    j = j.merge(hd, left_on="c_current_hdemo_sk", right_on="hd_demo_sk")
    j = j.merge(T.customer_demographics, left_on="c_current_cdemo_sk",
                right_on="cd_demo_sk")
    j = j.merge(T.store_returns, left_on="cd_demo_sk",
                right_on="sr_cdemo_sk")
    out = pd.DataFrame({
        "customer_id": j.c_customer_id,
        "customername": j.c_last_name + ", " + j.c_first_name})
    return out, meta(["customer_id"], None, 100)


def q91(T):
    j = _star(T.catalog_returns,
              (T.call_center, "cr_call_center_sk", "cc_call_center_sk"),
              (T.date_dim, "cr_returned_date_sk", "d_date_sk"),
              (T.customer, "cr_returning_customer_sk", "c_customer_sk"),
              (T.customer_demographics, "c_current_cdemo_sk", "cd_demo_sk"),
              (T.household_demographics, "c_current_hdemo_sk",
               "hd_demo_sk"))
    j = j[(j.d_year == 2000) & (j.d_moy == 11)
          & (((j.cd_marital_status == "M")
              & (j.cd_education_status == "Unknown"))
             | ((j.cd_marital_status == "W")
                & (j.cd_education_status == "Advanced Degree")))
          & j.hd_buy_potential.astype(str).str.startswith("Unknown")]
    out = (j.groupby(["cc_call_center_id", "cc_name", "cc_manager",
                      "cd_marital_status", "cd_education_status"],
                     as_index=False)
           .agg(returns_loss=("cr_net_loss", _sum)))
    out = out.rename(columns={"cc_call_center_id": "call_center",
                              "cc_name": "center_name",
                              "cc_manager": "manager"})
    out = out[["call_center", "center_name", "manager", "returns_loss"]]
    return out, meta(["returns_loss"], [False], None, ["returns_loss"])
