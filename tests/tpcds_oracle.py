"""Independent pandas oracles for the TPC-DS corpus (answer validation).

Each ``qNN(T)`` transcribes ``benchmarking/tpcds`` query NN directly from
its SQL text into pandas and returns ``(expected_df, meta)`` where meta
carries the ORDER BY spec so the checker can honor LIMIT-with-ties:

    meta = {"keys": [...], "asc": [...], "limit": N or None,
            "approx": [float cols], "unordered": bool}

The oracles deliberately use a different execution substrate (pandas
merges/groupbys) than the engine (its own planner + kernels), so a
planner/lowering bug shows as a mismatch rather than being mirrored.
Reference analogue: ``benchmarking/tpch/answers.py`` +
``tests/integration/test_tpch.py`` validate TPC-H the same way.

NULL-sum semantics: SQL SUM over an empty/all-NULL set is NULL, pandas
``sum()`` is 0 — transcriptions use ``_sum`` (min_count=1) wherever the
distinction can surface.
"""

import numpy as np
import pandas as pd


class Tables:
    """Lazy pandas view over the generated TPC-DS dataset."""

    def __init__(self, get_df):
        self._get = get_df
        self._cache = {}

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        if name not in self._cache:
            self._cache[name] = self._get(name).to_pandas()
        return self._cache[name]


def _sum(s):
    return s.sum(min_count=1)


def sql_sort(df, keys, ascending):
    """Engine semantics: ASC → NULLS LAST, DESC → NULLS FIRST."""
    out = df
    for k, asc in reversed(list(zip(keys, ascending))):
        out = out.sort_values(k, ascending=asc, kind="stable",
                              na_position="last" if asc else "first")
    return out.reset_index(drop=True)


def meta(keys=(), asc=None, limit=100, approx=(), unordered=False):
    keys = list(keys)
    return {"keys": keys,
            "asc": list(asc) if asc is not None else [True] * len(keys),
            "limit": limit, "approx": list(approx), "unordered": unordered}


# ---------------------------------------------------------------- helpers

def _star(ss, *joins):
    """Inner-merge a fact frame through (dim_frame, left_key, right_key)."""
    out = ss
    for dim, lk, rk in joins:
        out = out.merge(dim, left_on=lk, right_on=rk)
    return out


def _dates_between(dd, lo, hi):
    d = pd.to_datetime(dd.d_date)
    return dd[(d >= pd.Timestamp(lo)) & (d <= pd.Timestamp(hi))]


# ---------------------------------------------------------------- oracles

def q3(T):
    j = _star(T.store_sales,
              (T.date_dim, "ss_sold_date_sk", "d_date_sk"),
              (T.item, "ss_item_sk", "i_item_sk"))
    j = j[(j.i_manufact_id == 128) & (j.d_moy == 11)]
    out = (j.groupby(["d_year", "i_brand_id", "i_brand"], as_index=False)
           .agg(sum_agg=("ss_ext_sales_price", _sum)))
    return out, meta(["d_year", "sum_agg", "i_brand_id"],
                     [True, False, True], 100, ["sum_agg"])


def q7(T):
    j = _star(T.store_sales,
              (T.date_dim, "ss_sold_date_sk", "d_date_sk"),
              (T.item, "ss_item_sk", "i_item_sk"),
              (T.customer_demographics, "ss_cdemo_sk", "cd_demo_sk"),
              (T.promotion, "ss_promo_sk", "p_promo_sk"))
    j = j[(j.cd_gender == "M") & (j.cd_marital_status == "S")
          & (j.cd_education_status == "College")
          & ((j.p_channel_email == "N") | (j.p_channel_event == "N"))
          & (j.d_year == 2000)]
    out = (j.groupby("i_item_id", as_index=False)
           .agg(agg1=("ss_quantity", "mean"),
                agg2=("ss_list_price", "mean"),
                agg3=("ss_coupon_amt", "mean"),
                agg4=("ss_sales_price", "mean")))
    return out, meta(["i_item_id"], None, 100,
                     ["agg1", "agg2", "agg3", "agg4"])


def q19(T):
    j = _star(T.store_sales,
              (T.date_dim, "ss_sold_date_sk", "d_date_sk"),
              (T.item, "ss_item_sk", "i_item_sk"),
              (T.customer, "ss_customer_sk", "c_customer_sk"),
              (T.customer_address, "c_current_addr_sk", "ca_address_sk"),
              (T.store, "ss_store_sk", "s_store_sk"))
    j = j[(j.i_manager_id.between(1, 40)) & (j.d_moy == 11)
          & (j.d_year == 1999)]
    out = (j.groupby(["i_brand_id", "i_brand", "i_manufact_id"],
                     as_index=False)
           .agg(ext_price=("ss_ext_sales_price", _sum)))
    return out, meta(["ext_price", "i_brand_id", "i_manufact_id"],
                     [False, True, True], 100, ["ext_price"])


def q26(T):
    j = _star(T.store_sales,
              (T.date_dim, "ss_sold_date_sk", "d_date_sk"),
              (T.item, "ss_item_sk", "i_item_sk"),
              (T.customer_demographics, "ss_cdemo_sk", "cd_demo_sk"),
              (T.promotion, "ss_promo_sk", "p_promo_sk"))
    j = j[(j.cd_gender == "F") & (j.cd_marital_status == "W")
          & (j.cd_education_status == "Primary")
          & ((j.p_channel_email == "N") | (j.p_channel_event == "N"))
          & (j.d_year == 2000)]
    out = (j.groupby("i_item_id", as_index=False)
           .agg(agg1=("ss_quantity", "mean"),
                agg2=("ss_list_price", "mean"),
                agg3=("ss_coupon_amt", "mean"),
                agg4=("ss_sales_price", "mean")))
    return out, meta(["i_item_id"], None, 100,
                     ["agg1", "agg2", "agg3", "agg4"])


def q42(T):
    j = _star(T.store_sales,
              (T.date_dim, "ss_sold_date_sk", "d_date_sk"),
              (T.item, "ss_item_sk", "i_item_sk"))
    j = j[(j.i_manager_id == 1) & (j.d_moy == 11) & (j.d_year == 2000)]
    out = (j.groupby(["d_year", "i_category_id", "i_category"],
                     as_index=False)
           .agg(sum_sales=("ss_ext_sales_price", _sum)))
    return out, meta(["sum_sales", "d_year", "i_category_id", "i_category"],
                     [False, True, True, True], 100, ["sum_sales"])


def q52(T):
    j = _star(T.store_sales,
              (T.date_dim, "ss_sold_date_sk", "d_date_sk"),
              (T.item, "ss_item_sk", "i_item_sk"))
    j = j[(j.i_manager_id == 1) & (j.d_moy == 11) & (j.d_year == 2000)]
    out = (j.groupby(["d_year", "i_brand_id", "i_brand"], as_index=False)
           .agg(ext_price=("ss_ext_sales_price", _sum)))
    return out, meta(["d_year", "ext_price", "i_brand_id"],
                     [True, False, True], 100, ["ext_price"])


def q55(T):
    j = _star(T.store_sales,
              (T.date_dim, "ss_sold_date_sk", "d_date_sk"),
              (T.item, "ss_item_sk", "i_item_sk"))
    j = j[(j.i_manager_id == 28) & (j.d_moy == 11) & (j.d_year == 1999)]
    out = (j.groupby(["i_brand_id", "i_brand"], as_index=False)
           .agg(ext_price=("ss_ext_sales_price", _sum)))
    return out, meta(["ext_price", "i_brand_id"], [False, True], 100,
                     ["ext_price"])


def q96(T):
    j = _star(T.store_sales,
              (T.household_demographics, "ss_hdemo_sk", "hd_demo_sk"),
              (T.time_dim, "ss_sold_time_sk", "t_time_sk"),
              (T.store, "ss_store_sk", "s_store_sk"))
    n = len(j[(j.t_hour == 20) & (j.t_minute >= 30) & (j.hd_dep_count == 7)])
    return pd.DataFrame({"cnt": [n]}), meta([], None, 100)


def q13(T):
    j = _star(T.store_sales,
              (T.store, "ss_store_sk", "s_store_sk"),
              (T.date_dim, "ss_sold_date_sk", "d_date_sk"),
              (T.customer_demographics, "ss_cdemo_sk", "cd_demo_sk"),
              (T.household_demographics, "ss_hdemo_sk", "hd_demo_sk"),
              (T.customer_address, "ss_addr_sk", "ca_address_sk"))
    j = j[j.d_year == 2001]
    demo = (((j.cd_marital_status == "M")
             & (j.cd_education_status == "Advanced Degree")
             & j.ss_sales_price.between(100.0, 150.0)
             & (j.hd_dep_count == 3))
            | ((j.cd_marital_status == "S")
               & (j.cd_education_status == "College")
               & j.ss_sales_price.between(50.0, 100.0)
               & (j.hd_dep_count == 1))
            | ((j.cd_marital_status == "W")
               & (j.cd_education_status == "Secondary")
               & j.ss_sales_price.between(150.0, 200.0)
               & (j.hd_dep_count == 1)))
    addr = ((j.ca_country == "United States")
            & ((j.ca_state.isin(["TX", "OR", "WA"])
                & j.ss_net_profit.between(100, 200))
               | (j.ca_state.isin(["CA", "NY", "TN"])
                  & j.ss_net_profit.between(150, 300))
               | (j.ca_state.isin(["SD", "GA", "KY"])
                  & j.ss_net_profit.between(50, 250))))
    j = j[demo & addr]
    out = pd.DataFrame({
        "avg_q": [j.ss_quantity.mean() if len(j) else None],
        "avg_esp": [j.ss_ext_sales_price.mean() if len(j) else None],
        "avg_ewc": [j.ss_ext_wholesale_cost.mean() if len(j) else None],
        "sum_ewc": [_sum(j.ss_ext_wholesale_cost) if len(j) else None]})
    return out, meta([], None, None,
                     ["avg_q", "avg_esp", "avg_ewc", "sum_ewc"])


def q48(T):
    j = _star(T.store_sales,
              (T.store, "ss_store_sk", "s_store_sk"),
              (T.date_dim, "ss_sold_date_sk", "d_date_sk"),
              (T.customer_demographics, "ss_cdemo_sk", "cd_demo_sk"),
              (T.customer_address, "ss_addr_sk", "ca_address_sk"))
    j = j[j.d_year == 2000]
    demo = (((j.cd_marital_status == "M")
             & (j.cd_education_status == "College")
             & j.ss_sales_price.between(100.0, 150.0))
            | ((j.cd_marital_status == "D")
               & (j.cd_education_status == "Primary")
               & j.ss_sales_price.between(50.0, 100.0))
            | ((j.cd_marital_status == "W")
               & (j.cd_education_status == "Secondary")
               & j.ss_sales_price.between(150.0, 200.0)))
    addr = ((j.ca_country == "United States")
            & ((j.ca_state.isin(["TX", "NM", "OR"])
                & j.ss_net_profit.between(0, 2000))
               | (j.ca_state.isin(["CA", "NY", "WA"])
                  & j.ss_net_profit.between(150, 3000))
               | (j.ca_state.isin(["TN", "GA", "KY"])
                  & j.ss_net_profit.between(50, 25000))))
    j = j[demo & addr]
    return pd.DataFrame({"total_q": [_sum(j.ss_quantity)]}), \
        meta([], None, None, ["total_q"])


def q43(T):
    j = _star(T.store_sales,
              (T.date_dim, "ss_sold_date_sk", "d_date_sk"),
              (T.store, "ss_store_sk", "s_store_sk"))
    j = j[(j.d_year == 2000) & (j.s_gmt_offset == -5.0)]
    days = {"sun_sales": "Sunday", "mon_sales": "Monday",
            "fri_sales": "Friday", "sat_sales": "Saturday"}
    gb = j.groupby(["s_store_name", "s_store_sk"])
    out = gb.size().reset_index().drop(columns=0)
    for cname, day in days.items():
        s = (j[j.d_day_name == day]
             .groupby(["s_store_name", "s_store_sk"])["ss_sales_price"]
             .apply(_sum).rename(cname).reset_index())
        out = out.merge(s, on=["s_store_name", "s_store_sk"], how="left")
    return out, meta(["s_store_name", "s_store_sk"], None, 100,
                     list(days))


def q34(T):
    j = _star(T.store_sales,
              (T.date_dim, "ss_sold_date_sk", "d_date_sk"),
              (T.store, "ss_store_sk", "s_store_sk"),
              (T.household_demographics, "ss_hdemo_sk", "hd_demo_sk"))
    j = j[j.d_dom.between(1, 3) & (j.hd_vehicle_count > 0)
          & (j.d_year == 2000)]
    t = (j.groupby(["ss_ticket_number", "ss_customer_sk"], as_index=False)
         .size().rename(columns={"size": "cnt"}))
    t = t[t.cnt.between(15, 20)]
    out = t.merge(T.customer, left_on="ss_customer_sk",
                  right_on="c_customer_sk")
    out = out[["c_last_name", "c_first_name", "ss_ticket_number", "cnt"]]
    return out, meta(["c_last_name", "c_first_name", "ss_ticket_number"],
                     [True, True, False], None)


def q73(T):
    j = _star(T.store_sales,
              (T.date_dim, "ss_sold_date_sk", "d_date_sk"),
              (T.store, "ss_store_sk", "s_store_sk"),
              (T.household_demographics, "ss_hdemo_sk", "hd_demo_sk"))
    j = j[j.d_dom.between(1, 2)
          & j.hd_buy_potential.isin([">10000", "Unknown"])
          & (j.hd_vehicle_count > 0) & (j.d_year == 2000)]
    t = (j.groupby(["ss_ticket_number", "ss_customer_sk"], as_index=False)
         .size().rename(columns={"size": "cnt"}))
    t = t[t.cnt.between(1, 5)]
    out = t.merge(T.customer, left_on="ss_customer_sk",
                  right_on="c_customer_sk")
    out = out[["c_last_name", "c_first_name", "ss_ticket_number", "cnt"]]
    return out, meta(["cnt", "c_last_name"], [False, True], None)


def q15(T):
    j = _star(T.catalog_sales,
              (T.customer, "cs_bill_customer_sk", "c_customer_sk"),
              (T.customer_address, "c_current_addr_sk", "ca_address_sk"),
              (T.date_dim, "cs_sold_date_sk", "d_date_sk"))
    zips = ("85669", "86197", "88274", "83405", "86475", "85392", "85460",
            "80348", "81792")
    j = j[(j.ca_zip.astype(str).str[:5].isin(zips)
           | j.ca_state.isin(["CA", "WA", "GA"]) | (j.cs_sales_price > 500))
          & (j.d_qoy == 2) & (j.d_year == 2000)]
    out = (j.groupby("ca_zip", as_index=False)
           .agg(total_sales=("cs_sales_price", _sum)))
    return out, meta(["ca_zip"], None, 100, ["total_sales"])


def q45(T):
    it = T.item
    wanted_ids = set(it[it.i_item_sk.isin(
        [2, 3, 5, 7, 11, 13, 17, 19, 23, 29])].i_item_id)
    j = _star(T.web_sales,
              (T.customer, "ws_bill_customer_sk", "c_customer_sk"),
              (T.customer_address, "c_current_addr_sk", "ca_address_sk"),
              (it, "ws_item_sk", "i_item_sk"),
              (T.date_dim, "ws_sold_date_sk", "d_date_sk"))
    zips = ("85669", "86197", "88274", "83405", "86475", "85392", "85460",
            "80348", "81792")
    j = j[(j.ca_zip.astype(str).str[:5].isin(zips)
           | j.i_item_id.isin(wanted_ids))
          & (j.d_qoy == 2) & (j.d_year == 2000)]
    out = (j.groupby(["ca_zip", "ca_city"], as_index=False)
           .agg(total_sales=("ws_sales_price", _sum)))
    return out, meta(["ca_zip", "ca_city"], None, 100, ["total_sales"])


def q61(T):
    base = _star(T.store_sales,
                 (T.date_dim, "ss_sold_date_sk", "d_date_sk"),
                 (T.store, "ss_store_sk", "s_store_sk"),
                 (T.customer, "ss_customer_sk", "c_customer_sk"),
                 (T.customer_address, "c_current_addr_sk", "ca_address_sk"),
                 (T.item, "ss_item_sk", "i_item_sk"))
    base = base[(base.ca_gmt_offset == -5) & (base.s_gmt_offset == -5)
                & (base.i_category == "Jewelry") & (base.d_year == 2000)
                & (base.d_moy == 11)]
    promo = base.merge(T.promotion, left_on="ss_promo_sk",
                       right_on="p_promo_sk")
    promo = promo[(promo.p_channel_dmail == "Y")
                  | (promo.p_channel_email == "Y")
                  | (promo.p_channel_tv == "Y")]
    p = _sum(promo.ss_ext_sales_price)
    t = _sum(base.ss_ext_sales_price)
    out = pd.DataFrame({"promotions": [p], "total": [t],
                        "ratio": [float(p) / float(t) * 100
                                  if t and not pd.isna(t) else None]})
    return out, meta([], None, 100, ["promotions", "total", "ratio"])


def q88(T):
    j = _star(T.store_sales,
              (T.household_demographics, "ss_hdemo_sk", "hd_demo_sk"),
              (T.time_dim, "ss_sold_time_sk", "t_time_sk"),
              (T.store, "ss_store_sk", "s_store_sk"))
    j = j[(((j.hd_dep_count == 4) & (j.hd_vehicle_count <= 6))
           | ((j.hd_dep_count == 2) & (j.hd_vehicle_count <= 4))
           | ((j.hd_dep_count == 0) & (j.hd_vehicle_count <= 2)))
          & (j.s_store_name == "ese")]
    out = pd.DataFrame({
        "h8_30_to_9": [len(j[(j.t_hour == 8) & (j.t_minute >= 30)])],
        "h9_to_9_30": [len(j[(j.t_hour == 9) & (j.t_minute < 30)])],
        "h9_30_to_10": [len(j[(j.t_hour == 9) & (j.t_minute >= 30)])],
        "h10_to_10_30": [len(j[(j.t_hour == 10) & (j.t_minute < 30)])]})
    return out, meta([], None, None)


def q90(T):
    j = _star(T.web_sales,
              (T.household_demographics, "ws_ship_hdemo_sk", "hd_demo_sk"),
              (T.time_dim, "ws_sold_time_sk", "t_time_sk"),
              (T.web_page, "ws_web_page_sk", "wp_web_page_sk"))
    j = j[(j.hd_dep_count == 6) & j.wp_char_count.between(5000, 5200)]
    amc = len(j[j.t_hour.between(8, 9)])
    pmc = len(j[j.t_hour.between(19, 20)])
    # float division by a zero count is +inf in the engine (IEEE), not an
    # error — match it so sparse datagen scales stay comparable
    ratio = float(amc) / float(pmc) if pmc else \
        (float("inf") if amc else None)
    out = pd.DataFrame({"am_pm_ratio": [ratio]})
    return out, meta([], None, 100, ["am_pm_ratio"])


def q9(T):
    ss = T.store_sales
    vals = []
    for lo, hi in ((1, 20), (21, 40), (41, 60), (61, 80), (81, 100)):
        b = ss[ss.ss_quantity.between(lo, hi)]
        vals.append(b.ss_ext_discount_amt.mean() if len(b) > 1000
                    else b.ss_net_paid.mean())
    out = pd.DataFrame({f"bucket{i + 1}": [v] for i, v in enumerate(vals)})
    return out, meta([], None, None, [f"bucket{i}" for i in range(1, 6)])


def q28(T):
    ss = T.store_sales
    specs = [((0, 5), (8, 18), (459, 1459), (57, 77)),
             ((6, 10), (90, 100), (2323, 3323), (31, 51)),
             ((11, 15), (142, 152), (12214, 13214), (79, 99)),
             ((16, 20), (135, 145), (6071, 7071), (38, 58)),
             ((21, 25), (122, 132), (836, 1836), (17, 37)),
             ((26, 30), (154, 164), (7326, 8326), (7, 27))]
    cols = {}
    for i, (q, lp, cp, wc) in enumerate(specs, 1):
        b = ss[ss.ss_quantity.between(*q)
               & (ss.ss_list_price.between(*lp)
                  | ss.ss_coupon_amt.between(*cp)
                  | ss.ss_wholesale_cost.between(*wc))]
        cols[f"b{i}_lp"] = [b.ss_list_price.mean()]
        cols[f"b{i}_cnt"] = [int(b.ss_list_price.count())]
        cols[f"b{i}_cntd"] = [int(b.ss_list_price.nunique())]
    return pd.DataFrame(cols), meta(
        [], None, 100, [f"b{i}_lp" for i in range(1, 7)])


def q62(T):
    j = _star(T.web_sales,
              (T.date_dim, "ws_ship_date_sk", "d_date_sk"),
              (T.warehouse, "ws_warehouse_sk", "w_warehouse_sk"),
              (T.ship_mode, "ws_ship_mode_sk", "sm_ship_mode_sk"),
              (T.web_site, "ws_web_site_sk", "web_site_sk"))
    j = j[j.d_month_seq.between(1212, 1223)]
    j = j.assign(wh=j.w_warehouse_name.astype(str).str[:20],
                 lag=j.ws_ship_date_sk - j.ws_sold_date_sk)
    out = (j.groupby(["wh", "sm_type", "web_name"], as_index=False)
           .agg(days_30=("lag", lambda s: int((s <= 30).sum())),
                days_31_60=("lag", lambda s: int(((s > 30) & (s <= 60)).sum())),
                days_61_90=("lag", lambda s: int(((s > 60) & (s <= 90)).sum())),
                days_91_120=("lag",
                             lambda s: int(((s > 90) & (s <= 120)).sum())),
                days_over_120=("lag", lambda s: int((s > 120).sum()))))
    return out, meta(["wh", "sm_type", "web_name"], None, 100)


def q99(T):
    j = _star(T.catalog_sales,
              (T.date_dim, "cs_ship_date_sk", "d_date_sk"),
              (T.warehouse, "cs_warehouse_sk", "w_warehouse_sk"),
              (T.ship_mode, "cs_ship_mode_sk", "sm_ship_mode_sk"),
              (T.call_center, "cs_call_center_sk", "cc_call_center_sk"))
    j = j[j.d_month_seq.between(1212, 1223)]
    j = j.assign(wh=j.w_warehouse_name.astype(str).str[:20],
                 lag=j.cs_ship_date_sk - j.cs_sold_date_sk)
    out = (j.groupby(["wh", "sm_type", "cc_name"], as_index=False)
           .agg(days_30=("lag", lambda s: int((s <= 30).sum())),
                days_31_60=("lag", lambda s: int(((s > 30) & (s <= 60)).sum())),
                days_61_90=("lag", lambda s: int(((s > 60) & (s <= 90)).sum())),
                days_91_120=("lag",
                             lambda s: int(((s > 90) & (s <= 120)).sum())),
                days_over_120=("lag", lambda s: int((s > 120).sum()))))
    return out, meta(["wh", "sm_type", "cc_name"], None, 100)


def q50(T):
    ss = T.store_sales
    sr = T.store_returns
    j = ss.merge(sr, left_on=["ss_ticket_number", "ss_item_sk",
                              "ss_customer_sk"],
                 right_on=["sr_ticket_number", "sr_item_sk",
                           "sr_customer_sk"])
    d2 = T.date_dim[(T.date_dim.d_year == 2000) & (T.date_dim.d_moy == 8)]
    j = j.merge(d2, left_on="sr_returned_date_sk", right_on="d_date_sk")
    j = j.merge(T.store, left_on="ss_store_sk", right_on="s_store_sk")
    j = j.assign(lag=j.sr_returned_date_sk - j.ss_sold_date_sk)
    out = (j.groupby(["s_store_name", "s_company_id", "s_street_number",
                      "s_street_name"], as_index=False)
           .agg(days_30=("lag", lambda s: int((s <= 30).sum())),
                days_31_60=("lag", lambda s: int(((s > 30) & (s <= 60)).sum())),
                days_61_90=("lag", lambda s: int(((s > 60) & (s <= 90)).sum())),
                days_91_120=("lag",
                             lambda s: int(((s > 90) & (s <= 120)).sum())),
                days_over_120=("lag", lambda s: int((s > 120).sum()))))
    return out, meta(["s_store_name", "s_company_id"], None, 100)


def q41(T):
    it = T.item
    inner = it[((it.i_category == "Women")
                & it.i_color.isin(["powder", "orchid"])
                & it.i_units.isin(["Oz", "Each"])
                & it.i_size.isin(["medium", "N/A"]))
               | ((it.i_category == "Men")
                  & it.i_color.isin(["slate", "navy"])
                  & it.i_units.isin(["Bunch", "Ton"])
                  & it.i_size.isin(["large", "petite"]))]
    manufs = set(inner.i_manufact)
    j = it[it.i_manufact_id.between(70, 110) & it.i_manufact.isin(manufs)]
    out = pd.DataFrame(
        {"i_product_name": sorted(j.i_product_name.unique())})
    return out, meta(["i_product_name"], None, 100)


def q93(T):
    ss = T.store_sales
    sr = T.store_returns
    j = ss.merge(sr, left_on=["ss_item_sk", "ss_ticket_number"],
                 right_on=["sr_item_sk", "sr_ticket_number"], how="left")
    j = j.merge(T.reason, left_on="sr_reason_sk", right_on="r_reason_sk")
    j = j[j.r_reason_desc == "reason 1"]
    act = np.where(j.sr_return_quantity.notna(),
                   (j.ss_quantity - j.sr_return_quantity) * j.ss_sales_price,
                   j.ss_quantity * j.ss_sales_price)
    j = j.assign(act_sales=act)
    out = (j.groupby("ss_customer_sk", as_index=False, dropna=False)
           .agg(sumsales=("act_sales", _sum)))
    out = out[["ss_customer_sk", "sumsales"]]
    return out, meta(["sumsales", "ss_customer_sk"], None, 100,
                     ["sumsales"])


def q84(T):
    j = T.customer.merge(T.customer_address[
        T.customer_address.ca_city == "hilltop"],
        left_on="c_current_addr_sk", right_on="ca_address_sk")
    ib = T.income_band[(T.income_band.ib_lower_bound >= 30000)
                       & (T.income_band.ib_upper_bound <= 80000)]
    hd = T.household_demographics.merge(
        ib, left_on="hd_income_band_sk", right_on="ib_income_band_sk")
    j = j.merge(hd, left_on="c_current_hdemo_sk", right_on="hd_demo_sk")
    j = j.merge(T.customer_demographics, left_on="c_current_cdemo_sk",
                right_on="cd_demo_sk")
    j = j.merge(T.store_returns, left_on="cd_demo_sk",
                right_on="sr_cdemo_sk")
    out = pd.DataFrame({
        "customer_id": j.c_customer_id,
        "customername": j.c_last_name + ", " + j.c_first_name})
    return out, meta(["customer_id"], None, 100)


def q91(T):
    j = _star(T.catalog_returns,
              (T.call_center, "cr_call_center_sk", "cc_call_center_sk"),
              (T.date_dim, "cr_returned_date_sk", "d_date_sk"),
              (T.customer, "cr_returning_customer_sk", "c_customer_sk"),
              (T.customer_demographics, "c_current_cdemo_sk", "cd_demo_sk"),
              (T.household_demographics, "c_current_hdemo_sk",
               "hd_demo_sk"))
    j = j[(j.d_year == 2000) & (j.d_moy == 11)
          & (((j.cd_marital_status == "M")
              & (j.cd_education_status == "Unknown"))
             | ((j.cd_marital_status == "W")
                & (j.cd_education_status == "Advanced Degree")))
          & j.hd_buy_potential.astype(str).str.startswith("Unknown")]
    out = (j.groupby(["cc_call_center_id", "cc_name", "cc_manager",
                      "cd_marital_status", "cd_education_status"],
                     as_index=False)
           .agg(returns_loss=("cr_net_loss", _sum)))
    out = out.rename(columns={"cc_call_center_id": "call_center",
                              "cc_name": "center_name",
                              "cc_manager": "manager"})
    out = out[["call_center", "center_name", "manager", "returns_loss"]]
    return out, meta(["returns_loss"], [False], None, ["returns_loss"])


# ------------------------------------------------- windows / ratios

def _q47_v1(T):
    j = _star(T.store_sales,
              (T.item, "ss_item_sk", "i_item_sk"),
              (T.date_dim, "ss_sold_date_sk", "d_date_sk"),
              (T.store, "ss_store_sk", "s_store_sk"))
    j = j[(j.d_year == 2000) | ((j.d_year == 1999) & (j.d_moy == 12))
          | ((j.d_year == 2001) & (j.d_moy == 1))]
    keys = ["i_category", "i_brand", "s_store_name", "s_company_name"]
    v1 = (j.groupby(keys + ["d_year", "d_moy"], as_index=False)
          .agg(sum_sales=("ss_sales_price", _sum)))
    v1["avg_monthly_sales"] = v1.groupby(keys + ["d_year"])[
        "sum_sales"].transform("mean")
    v1 = v1.sort_values(keys + ["d_year", "d_moy"], kind="stable")
    v1["rn"] = v1.groupby(keys).cumcount() + 1
    return v1, keys


def q47(T):
    v1, keys = _q47_v1(T)
    lag = v1[keys + ["rn", "sum_sales"]].assign(rn=v1.rn + 1) \
        .rename(columns={"sum_sales": "psum"})
    lead = v1[keys + ["rn", "sum_sales"]].assign(rn=v1.rn - 1) \
        .rename(columns={"sum_sales": "nsum"})
    v2 = v1.merge(lag, on=keys + ["rn"]).merge(lead, on=keys + ["rn"])
    v2 = v2[(v2.d_year == 2000) & (v2.avg_monthly_sales > 0)]
    dev = (v2.sum_sales - v2.avg_monthly_sales).abs() / v2.avg_monthly_sales
    v2 = v2[dev > 0.1]
    out = v2[keys + ["d_year", "d_moy", "avg_monthly_sales", "sum_sales",
                     "psum", "nsum"]].copy()
    out["__delta"] = out.sum_sales - out.avg_monthly_sales
    return out, meta(
        ["__delta"] + keys + ["d_year", "d_moy"], None, 100,
        ["avg_monthly_sales", "sum_sales", "psum", "nsum", "__delta"])


def q57(T):
    j = _star(T.catalog_sales,
              (T.item, "cs_item_sk", "i_item_sk"),
              (T.date_dim, "cs_sold_date_sk", "d_date_sk"),
              (T.call_center, "cs_call_center_sk", "cc_call_center_sk"))
    j = j[(j.d_year == 2000) | ((j.d_year == 1999) & (j.d_moy == 12))
          | ((j.d_year == 2001) & (j.d_moy == 1))]
    keys = ["i_category", "i_brand", "cc_name"]
    v1 = (j.groupby(keys + ["d_year", "d_moy"], as_index=False)
          .agg(sum_sales=("cs_sales_price", _sum)))
    v1["avg_monthly_sales"] = v1.groupby(keys + ["d_year"])[
        "sum_sales"].transform("mean")
    v1 = v1.sort_values(keys + ["d_year", "d_moy"], kind="stable")
    v1["rn"] = v1.groupby(keys).cumcount() + 1
    lag = v1[keys + ["rn", "sum_sales"]].assign(rn=v1.rn + 1) \
        .rename(columns={"sum_sales": "psum"})
    lead = v1[keys + ["rn", "sum_sales"]].assign(rn=v1.rn - 1) \
        .rename(columns={"sum_sales": "nsum"})
    v2 = v1.merge(lag, on=keys + ["rn"]).merge(lead, on=keys + ["rn"])
    v2 = v2[(v2.d_year == 2000) & (v2.avg_monthly_sales > 0)]
    dev = (v2.sum_sales - v2.avg_monthly_sales).abs() / v2.avg_monthly_sales
    v2 = v2[dev > 0.1]
    out = v2[keys + ["d_year", "d_moy", "avg_monthly_sales", "sum_sales",
                     "psum", "nsum"]].copy()
    out["__delta"] = out.sum_sales - out.avg_monthly_sales
    return out, meta(["__delta", "cc_name"], None, 100,
                     ["avg_monthly_sales", "sum_sales", "psum", "nsum",
                      "__delta"])


def q63(T):
    j = _star(T.store_sales,
              (T.item, "ss_item_sk", "i_item_sk"),
              (T.date_dim, "ss_sold_date_sk", "d_date_sk"),
              (T.store, "ss_store_sk", "s_store_sk"))
    j = j[j.d_month_seq.isin(range(1200, 1212))]
    g1 = (j.i_category.isin(["Books", "Children", "Electronics"])
          & j.i_class.isin(["personal", "portable", "reference",
                            "self-help"]))
    g2 = (j.i_category.isin(["Women", "Music", "Men"])
          & j.i_class.isin(["accessories", "classical", "fragrances",
                            "pants"]))
    j = j[g1 | g2]
    m = (j.groupby(["i_manager_id", "d_moy"], as_index=False)
         .agg(sum_sales=("ss_sales_price", _sum)))
    m["avg_monthly_sales"] = m.groupby("i_manager_id")[
        "sum_sales"].transform("mean")
    dev = (m.sum_sales - m.avg_monthly_sales).abs() / m.avg_monthly_sales
    m = m[(m.avg_monthly_sales > 0) & (dev > 0.1)]
    out = m[["i_manager_id", "sum_sales", "avg_monthly_sales"]]
    return out, meta(["i_manager_id", "avg_monthly_sales", "sum_sales"],
                     None, 100, ["sum_sales", "avg_monthly_sales"])


def q89(T):
    j = _star(T.store_sales,
              (T.item, "ss_item_sk", "i_item_sk"),
              (T.date_dim, "ss_sold_date_sk", "d_date_sk"),
              (T.store, "ss_store_sk", "s_store_sk"))
    j = j[j.d_year == 2000]
    g1 = (j.i_category.isin(["Books", "Electronics", "Sports"])
          & j.i_class.isin(["computers", "stereo", "football"]))
    g2 = (j.i_category.isin(["Men", "Jewelry", "Women"])
          & j.i_class.isin(["shirts", "birdal", "dresses"]))
    j = j[g1 | g2]
    keys = ["i_category", "i_class", "i_brand", "s_store_name",
            "s_company_name"]
    m = (j.groupby(keys + ["d_moy"], as_index=False)
         .agg(sum_sales=("ss_sales_price", _sum)))
    m["avg_monthly_sales"] = m.groupby(keys)["sum_sales"].transform("mean")
    dev = (m.sum_sales - m.avg_monthly_sales).abs() / m.avg_monthly_sales
    m = m[(m.avg_monthly_sales != 0) & (dev > 0.1)]
    out = m[keys + ["d_moy", "sum_sales", "avg_monthly_sales"]].copy()
    out["__delta"] = out.sum_sales - out.avg_monthly_sales
    return out, meta(
        ["__delta", "s_store_name", "i_category", "i_class", "i_brand",
         "d_moy"], None, 100,
        ["sum_sales", "avg_monthly_sales", "__delta"])


def q53(T):
    j = _star(T.store_sales,
              (T.item, "ss_item_sk", "i_item_sk"),
              (T.date_dim, "ss_sold_date_sk", "d_date_sk"))
    j = j[(j.d_year == 2000)
          & j.i_category.isin(["Books", "Home", "Electronics"])]
    q = (j.groupby(["i_manufact_id", "d_qoy"], as_index=False)
         .agg(sum_sales=("ss_sales_price", _sum)))
    q["avg_quarterly_sales"] = q.groupby("i_manufact_id")[
        "sum_sales"].transform("mean")
    out = q[["i_manufact_id", "sum_sales", "avg_quarterly_sales"]]
    return out, meta(["avg_quarterly_sales", "sum_sales", "i_manufact_id"],
                     [False, True, True], 100,
                     ["sum_sales", "avg_quarterly_sales"])


def _revenue_ratio(j, price_col, limit):
    rev = (j.groupby(["i_item_id", "i_item_desc", "i_category", "i_class",
                      "i_current_price"], as_index=False)
           .agg(itemrevenue=(price_col, _sum)))
    rev["revenueratio"] = rev.itemrevenue * 100.0 / rev.groupby(
        "i_class")["itemrevenue"].transform("sum")
    return rev, meta(["i_category", "i_class", "i_item_id", "i_item_desc",
                      "revenueratio"], None, limit,
                     ["itemrevenue", "revenueratio"])


def q98(T):
    j = _star(T.store_sales,
              (T.item, "ss_item_sk", "i_item_sk"),
              (T.date_dim, "ss_sold_date_sk", "d_date_sk"))
    j = j[j.i_category.isin(["Sports", "Books", "Home"])
          & (j.d_year == 2000) & j.d_moy.between(2, 4)]
    return _revenue_ratio(j, "ss_ext_sales_price", None)


def q20(T):
    j = _star(T.catalog_sales,
              (T.item, "cs_item_sk", "i_item_sk"),
              (T.date_dim, "cs_sold_date_sk", "d_date_sk"))
    j = j[j.i_category.isin(["Sports", "Books", "Home"])]
    d = pd.to_datetime(j.d_date)
    j = j[(d >= "1999-02-22") & (d <= "1999-03-24")]
    return _revenue_ratio(j, "cs_ext_sales_price", 100)


def q12(T):
    j = _star(T.web_sales,
              (T.item, "ws_item_sk", "i_item_sk"),
              (T.date_dim, "ws_sold_date_sk", "d_date_sk"))
    j = j[j.i_category.isin(["Sports", "Books", "Home"])]
    d = pd.to_datetime(j.d_date)
    j = j[(d >= "1999-02-22") & (d <= "1999-03-24")]
    return _revenue_ratio(j, "ws_ext_sales_price", 100)


# ------------------------------------------- correlated scalar subqueries

def q1(T):
    j = T.store_returns.merge(T.date_dim, left_on="sr_returned_date_sk",
                              right_on="d_date_sk")
    j = j[j.d_year == 2000]
    ctr = (j.groupby(["sr_customer_sk", "sr_store_sk"], as_index=False)
           .agg(ctr_total_return=("sr_return_amt", _sum)))
    ctr["avg_r"] = ctr.groupby("sr_store_sk")[
        "ctr_total_return"].transform("mean")
    ctr = ctr[ctr.ctr_total_return > ctr.avg_r * 1.2]
    ctr = ctr.merge(T.store[T.store.s_state == "TN"],
                    left_on="sr_store_sk", right_on="s_store_sk")
    ctr = ctr.merge(T.customer, left_on="sr_customer_sk",
                    right_on="c_customer_sk")
    return ctr[["c_customer_id"]], meta(["c_customer_id"], None, 100)


def q30(T):
    j = _star(T.web_returns,
              (T.date_dim, "wr_returned_date_sk", "d_date_sk"),
              (T.customer_address, "wr_returning_addr_sk", "ca_address_sk"))
    j = j[j.d_year == 2000]
    ctr = (j.groupby(["wr_returning_customer_sk", "ca_state"],
                     as_index=False)
           .agg(ctr_total_return=("wr_return_amt", _sum)))
    ctr["avg_r"] = ctr.groupby("ca_state")[
        "ctr_total_return"].transform("mean")
    ctr = ctr[ctr.ctr_total_return > ctr.avg_r * 1.2]
    cu = T.customer.merge(
        T.customer_address[T.customer_address.ca_state == "CA"],
        left_on="c_current_addr_sk", right_on="ca_address_sk")
    out = ctr.merge(cu, left_on="wr_returning_customer_sk",
                    right_on="c_customer_sk")
    cols = ["c_customer_id", "c_salutation", "c_first_name", "c_last_name",
            "c_preferred_cust_flag", "c_birth_day", "c_birth_month",
            "c_birth_year", "c_birth_country", "c_login", "c_email_address",
            "ctr_total_return"]
    return out[cols], meta(
        ["c_customer_id", "c_salutation", "c_first_name", "c_last_name"],
        None, 100, ["ctr_total_return"])


def q81(T):
    j = _star(T.catalog_returns,
              (T.date_dim, "cr_returned_date_sk", "d_date_sk"),
              (T.customer_address, "cr_returning_addr_sk", "ca_address_sk"))
    j = j[j.d_year == 2000]
    ctr = (j.groupby(["cr_returning_customer_sk", "ca_state"],
                     as_index=False)
           .agg(ctr_total_return=("cr_return_amt_inc_tax", _sum)))
    ctr["avg_r"] = ctr.groupby("ca_state")[
        "ctr_total_return"].transform("mean")
    ctr = ctr[ctr.ctr_total_return > ctr.avg_r * 1.2]
    # drop ctr's grouping state before merging: the OUTPUT address
    # columns come from the customer's current address, and a colliding
    # ca_state would suffix both away
    ctr = ctr.drop(columns="ca_state")
    ca = T.customer_address[T.customer_address.ca_state == "CA"]
    cu = T.customer.merge(ca, left_on="c_current_addr_sk",
                          right_on="ca_address_sk")
    out = ctr.merge(cu, left_on="cr_returning_customer_sk",
                    right_on="c_customer_sk")
    cols = ["c_customer_id", "c_salutation", "c_first_name", "c_last_name",
            "ca_street_number", "ca_street_name", "ca_street_type",
            "ca_suite_number", "ca_city", "ca_county", "ca_state", "ca_zip",
            "ca_country", "ca_gmt_offset", "ca_location_type",
            "ctr_total_return"]
    return out[cols], meta(
        ["c_customer_id", "c_salutation", "c_first_name", "c_last_name"],
        None, 100, ["ctr_total_return"])


def q32(T):
    dd = _dates_between(T.date_dim, "2000-01-27", "2000-04-26")
    j = _star(T.catalog_sales,
              (T.item, "cs_item_sk", "i_item_sk"),
              (dd, "cs_sold_date_sk", "d_date_sk"))
    per_item = j.groupby("cs_item_sk")["cs_ext_discount_amt"] \
        .transform("mean")
    j = j[(j.i_manufact_id == 77) & (j.cs_ext_discount_amt > 1.3 * per_item)]
    return pd.DataFrame(
        {"excess_discount_amount": [_sum(j.cs_ext_discount_amt)]}), \
        meta([], None, 100, ["excess_discount_amount"])


def q92(T):
    dd = _dates_between(T.date_dim, "2000-01-27", "2000-04-26")
    j = _star(T.web_sales,
              (T.item, "ws_item_sk", "i_item_sk"),
              (dd, "ws_sold_date_sk", "d_date_sk"))
    per_item = j.groupby("ws_item_sk")["ws_ext_discount_amt"] \
        .transform("mean")
    j = j[(j.i_manufact_id == 77) & (j.ws_ext_discount_amt > 1.3 * per_item)]
    return pd.DataFrame(
        {"excess_discount_amount": [_sum(j.ws_ext_discount_amt)]}), \
        meta([], None, 100, ["excess_discount_amount"])


def q6(T):
    dd = T.date_dim
    m = dd[(dd.d_year == 2000) & (dd.d_moy == 1)].d_month_seq.iloc[0]
    it = T.item.copy()
    cat_avg = it.groupby("i_category")["i_current_price"].transform("mean")
    hot = set(it[it.i_current_price > 1.2 * cat_avg].i_item_sk)
    j = _star(T.store_sales,
              (dd[dd.d_month_seq == m], "ss_sold_date_sk", "d_date_sk"),
              (T.customer, "ss_customer_sk", "c_customer_sk"),
              (T.customer_address, "c_current_addr_sk", "ca_address_sk"))
    j = j[j.ss_item_sk.isin(hot)]
    out = (j.groupby("ca_state", dropna=False, as_index=False)
           .size().rename(columns={"size": "cnt", "ca_state": "state"}))
    out = out[out.cnt >= 10]
    return out, meta(["cnt", "state"], None, 100)


def q65(T):
    j = T.store_sales.merge(
        T.date_dim[T.date_dim.d_month_seq.between(1200, 1211)],
        left_on="ss_sold_date_sk", right_on="d_date_sk")
    sa = (j.groupby(["ss_store_sk", "ss_item_sk"], as_index=False)
          .agg(revenue=("ss_sales_price", _sum)))
    sb = sa.groupby("ss_store_sk", as_index=False) \
        .agg(ave=("revenue", "mean"))
    m = sa.merge(sb, on="ss_store_sk")
    m = m[m.revenue <= 0.1 * m.ave]
    m = m.merge(T.store, left_on="ss_store_sk", right_on="s_store_sk")
    m = m.merge(T.item, left_on="ss_item_sk", right_on="i_item_sk")
    out = m[["s_store_name", "i_item_desc", "revenue", "i_current_price",
             "i_wholesale_cost", "i_brand"]]
    return out, meta(["s_store_name", "i_item_desc"], None, 100,
                     ["revenue"])


# ----------------------------------------------- EXISTS / set operations

def _active_customers(T, year, cond):
    dd = T.date_dim
    days = set(dd[(dd.d_year == year) & cond(dd)].d_date_sk)
    ss = set(T.store_sales[T.store_sales.ss_sold_date_sk.isin(days)]
             .ss_customer_sk)
    ws = set(T.web_sales[T.web_sales.ws_sold_date_sk.isin(days)]
             .ws_bill_customer_sk)
    cs = set(T.catalog_sales[T.catalog_sales.cs_sold_date_sk.isin(days)]
             .cs_ship_customer_sk)
    return ss, ws, cs


def q10(T):
    ss, ws, cs = _active_customers(
        T, 2001, lambda d: d.d_moy.between(1, 4))
    j = T.customer.merge(T.customer_address, left_on="c_current_addr_sk",
                         right_on="ca_address_sk")
    j = j[j.ca_county.isin(["Ziebach County", "Williamson County",
                            "Walker County"])]
    j = j.merge(T.customer_demographics, left_on="c_current_cdemo_sk",
                right_on="cd_demo_sk")
    j = j[j.c_customer_sk.isin(ss)
          & (j.c_customer_sk.isin(ws) | j.c_customer_sk.isin(cs))]
    keys = ["cd_gender", "cd_marital_status", "cd_education_status",
            "cd_purchase_estimate", "cd_credit_rating", "cd_dep_count",
            "cd_dep_employed_count", "cd_dep_college_count"]
    out = j.groupby(keys, dropna=False, as_index=False).size()
    for c in ("cnt1", "cnt2", "cnt3", "cnt4", "cnt5", "cnt6"):
        out[c] = out["size"]
    out = out.drop(columns="size")
    cols = ["cd_gender", "cd_marital_status", "cd_education_status",
            "cnt1", "cd_purchase_estimate", "cnt2", "cd_credit_rating",
            "cnt3", "cd_dep_count", "cnt4", "cd_dep_employed_count",
            "cnt5", "cd_dep_college_count", "cnt6"]
    return out[cols], meta(keys, None, 100)


def q35(T):
    ss, ws, cs = _active_customers(T, 2001, lambda d: d.d_qoy < 4)
    j = T.customer.merge(T.customer_address, left_on="c_current_addr_sk",
                         right_on="ca_address_sk")
    j = j.merge(T.customer_demographics, left_on="c_current_cdemo_sk",
                right_on="cd_demo_sk")
    j = j[j.c_customer_sk.isin(ss)
          & (j.c_customer_sk.isin(ws) | j.c_customer_sk.isin(cs))]
    keys = ["ca_state", "cd_gender", "cd_marital_status", "cd_dep_count",
            "cd_dep_employed_count", "cd_dep_college_count"]
    g = j.groupby(keys, dropna=False, as_index=False)
    out = g.size().rename(columns={"size": "cnt1"})
    for src, (mn, mx, av) in (("cd_dep_count", ("min1", "max1", "avg1")),
                              ("cd_dep_employed_count",
                               ("min2", "max2", "avg2")),
                              ("cd_dep_college_count",
                               ("min3", "max3", "avg3"))):
        agg = g.agg(**{mn: (src, "min"), mx: (src, "max"),
                       av: (src, "mean")})
        out = out.merge(agg, on=keys)
    out["cnt2"] = out.cnt1
    out["cnt3"] = out.cnt1
    cols = ["ca_state", "cd_gender", "cd_marital_status", "cd_dep_count",
            "cnt1", "min1", "max1", "avg1", "cd_dep_employed_count",
            "cnt2", "min2", "max2", "avg2", "cd_dep_college_count",
            "cnt3", "min3", "max3", "avg3"]
    return out[cols], meta(keys, None, 100, ["avg1", "avg2", "avg3"])


def q69(T):
    ss, ws, cs = _active_customers(
        T, 2000, lambda d: d.d_moy.between(1, 3))
    j = T.customer.merge(T.customer_address, left_on="c_current_addr_sk",
                         right_on="ca_address_sk")
    j = j[j.ca_state.isin(["CA", "TX", "NY"])]
    j = j.merge(T.customer_demographics, left_on="c_current_cdemo_sk",
                right_on="cd_demo_sk")
    j = j[j.c_customer_sk.isin(ss) & ~j.c_customer_sk.isin(ws)
          & ~j.c_customer_sk.isin(cs)]
    keys = ["cd_gender", "cd_marital_status", "cd_education_status",
            "cd_purchase_estimate", "cd_credit_rating"]
    out = j.groupby(keys, dropna=False, as_index=False).size()
    out["cnt1"] = out["size"]
    out["cnt2"] = out["size"]
    out["cnt3"] = out["size"]
    out = out.drop(columns="size")
    cols = ["cd_gender", "cd_marital_status", "cd_education_status",
            "cnt1", "cd_purchase_estimate", "cnt2", "cd_credit_rating",
            "cnt3"]
    return out[cols], meta(keys, None, 100)


def q8(T):
    ca = T.customer_address
    z5 = ca.ca_zip.astype(str).str[:5]
    a = set(z5[ca.ca_zip.astype(str).str[:2].isin(
        ["10", "22", "35", "47", "58", "63"])])
    pref = T.customer[T.customer.c_preferred_cust_flag == "Y"]
    b = set(ca.merge(pref, left_on="ca_address_sk",
                     right_on="c_current_addr_sk")
            .ca_zip.astype(str).str[:5])
    two = {z[:2] for z in (a & b)}
    j = _star(T.store_sales,
              (T.date_dim, "ss_sold_date_sk", "d_date_sk"),
              (T.store, "ss_store_sk", "s_store_sk"))
    j = j[(j.d_qoy == 2) & (j.d_year == 2000)
          & j.s_zip.astype(str).str[:2].isin(two)]
    out = (j.groupby("s_store_name", as_index=False)
           .agg(profit=("ss_net_profit", _sum)))
    return out, meta(["s_store_name"], None, 100, ["profit"])


def _channel_daysets(T):
    dd = T.date_dim[T.date_dim.d_month_seq.between(1200, 1211)]
    ss = (T.store_sales.merge(dd, left_on="ss_sold_date_sk",
                              right_on="d_date_sk")
          .merge(T.customer, left_on="ss_customer_sk",
                 right_on="c_customer_sk"))
    cs = (T.catalog_sales.merge(dd, left_on="cs_sold_date_sk",
                                right_on="d_date_sk")
          .merge(T.customer, left_on="cs_bill_customer_sk",
                 right_on="c_customer_sk"))
    ws = (T.web_sales.merge(dd, left_on="ws_sold_date_sk",
                            right_on="d_date_sk")
          .merge(T.customer, left_on="ws_bill_customer_sk",
                 right_on="c_customer_sk"))
    key = ["c_last_name", "c_first_name", "d_date"]
    return (set(map(tuple, ss[key].drop_duplicates().itertuples(index=False))),
            set(map(tuple, cs[key].drop_duplicates().itertuples(index=False))),
            set(map(tuple, ws[key].drop_duplicates().itertuples(index=False))))


def q38(T):
    s, c, w = _channel_daysets(T)
    return pd.DataFrame({"cnt": [len(s & c & w)]}), meta([], None, 100)


def q87(T):
    s, c, w = _channel_daysets(T)
    return pd.DataFrame({"cnt": [len((s - c) - w)]}), meta([], None, None)


# --------------------------------------------- cross-channel aggregates

def _by_cat_sales(T, fact, item_col, date_col, addr_col, price_col,
                  key_src, keys, moy):
    it = T.item
    wanted = set(it[key_src(it)][keys])
    dd = T.date_dim[(T.date_dim.d_year == 2000) & (T.date_dim.d_moy == moy)]
    ca = T.customer_address[T.customer_address.ca_gmt_offset == -5]
    j = _star(fact, (it, item_col, "i_item_sk"),
              (dd, date_col, "d_date_sk"),
              (ca, addr_col, "ca_address_sk"))
    j = j[j[keys].isin(wanted)]
    return (j.groupby(keys, as_index=False)
            .agg(total_sales=(price_col, _sum)))


def q33(T):
    src = lambda it: it.i_category.isin(["Books"])
    parts = [
        _by_cat_sales(T, T.store_sales, "ss_item_sk", "ss_sold_date_sk",
                      "ss_addr_sk", "ss_ext_sales_price", src,
                      "i_manufact_id", 1),
        _by_cat_sales(T, T.catalog_sales, "cs_item_sk", "cs_sold_date_sk",
                      "cs_bill_addr_sk", "cs_ext_sales_price", src,
                      "i_manufact_id", 1),
        _by_cat_sales(T, T.web_sales, "ws_item_sk", "ws_sold_date_sk",
                      "ws_bill_addr_sk", "ws_ext_sales_price", src,
                      "i_manufact_id", 1)]
    out = (pd.concat(parts).groupby("i_manufact_id", as_index=False)
           .agg(total_sales=("total_sales", _sum)))
    return out, meta(["total_sales"], None, 100, ["total_sales"])


def _q56ish(T, colors_or_cat, moy, order_keys, asc=None):
    src = colors_or_cat
    parts = [
        _by_cat_sales(T, T.store_sales, "ss_item_sk", "ss_sold_date_sk",
                      "ss_addr_sk", "ss_ext_sales_price", src,
                      "i_item_id", moy),
        _by_cat_sales(T, T.catalog_sales, "cs_item_sk", "cs_sold_date_sk",
                      "cs_bill_addr_sk", "cs_ext_sales_price", src,
                      "i_item_id", moy),
        _by_cat_sales(T, T.web_sales, "ws_item_sk", "ws_sold_date_sk",
                      "ws_bill_addr_sk", "ws_ext_sales_price", src,
                      "i_item_id", moy)]
    out = (pd.concat(parts).groupby("i_item_id", as_index=False)
           .agg(total_sales=("total_sales", _sum)))
    return out, meta(order_keys, asc, 100, ["total_sales"])


def q56(T):
    return _q56ish(
        T, lambda it: it.i_color.isin(["slate", "blanched", "burnished"]),
        2, ["total_sales", "i_item_id"])


def q60(T):
    return _q56ish(T, lambda it: it.i_category.isin(["Music"]), 9,
                   ["i_item_id", "total_sales"])


def q71(T):
    dd = T.date_dim[(T.date_dim.d_moy == 11) & (T.date_dim.d_year == 2000)]
    pieces = []
    for fact, price, date_sk, item_sk, time_sk in (
            (T.web_sales, "ws_ext_sales_price", "ws_sold_date_sk",
             "ws_item_sk", "ws_sold_time_sk"),
            (T.catalog_sales, "cs_ext_sales_price", "cs_sold_date_sk",
             "cs_item_sk", "cs_sold_time_sk"),
            (T.store_sales, "ss_ext_sales_price", "ss_sold_date_sk",
             "ss_item_sk", "ss_sold_time_sk")):
        p = fact.merge(dd, left_on=date_sk, right_on="d_date_sk")
        pieces.append(pd.DataFrame({
            "ext_price": p[price], "sold_item_sk": p[item_sk],
            "time_sk": p[time_sk]}))
    u = pd.concat(pieces)
    it = T.item[T.item.i_manager_id == 1]
    td = T.time_dim[T.time_dim.t_meal_time.isin(["breakfast", "dinner"])]
    j = (u.merge(it, left_on="sold_item_sk", right_on="i_item_sk")
         .merge(td, left_on="time_sk", right_on="t_time_sk"))
    out = (j.groupby(["i_brand", "i_brand_id", "t_hour", "t_minute"],
                     as_index=False)
           .agg(ext_price=("ext_price", _sum)))
    out = out.rename(columns={"i_brand_id": "brand_id", "i_brand": "brand"})
    out = out[["brand_id", "brand", "t_hour", "t_minute", "ext_price"]]
    return out, meta(["ext_price", "brand_id"], [False, True], None,
                     ["ext_price"])


def q76(T):
    pieces = []
    for fact, chan, cname, null_col, date_sk, item_sk, price in (
            (T.store_sales, "store", "ss_store_sk", "ss_store_sk",
             "ss_sold_date_sk", "ss_item_sk", "ss_ext_sales_price"),
            (T.web_sales, "web", "ws_ship_customer_sk",
             "ws_ship_customer_sk", "ws_sold_date_sk", "ws_item_sk",
             "ws_ext_sales_price"),
            (T.catalog_sales, "catalog", "cs_ship_addr_sk",
             "cs_ship_addr_sk", "cs_sold_date_sk", "cs_item_sk",
             "cs_ext_sales_price")):
        p = fact[fact[null_col].isna()]
        p = _star(p, (T.item, item_sk, "i_item_sk"),
                  (T.date_dim, date_sk, "d_date_sk"))
        pieces.append(pd.DataFrame({
            "channel": chan, "col_name": cname, "d_year": p.d_year,
            "d_qoy": p.d_qoy, "i_category": p.i_category,
            "ext_sales_price": p[price]}))
    u = pd.concat(pieces)
    g = u.groupby(["channel", "col_name", "d_year", "d_qoy", "i_category"],
                  dropna=False, as_index=False)
    out = g.agg(sales_cnt=("ext_sales_price", "size"),
                sales_amt=("ext_sales_price", _sum))
    return out, meta(["channel", "col_name", "d_year", "d_qoy",
                      "i_category"], None, 100, ["sales_amt"])


def q2(T):
    u = pd.concat([
        pd.DataFrame({"sold_date_sk": T.web_sales.ws_sold_date_sk,
                      "sales_price": T.web_sales.ws_ext_sales_price}),
        pd.DataFrame({"sold_date_sk": T.catalog_sales.cs_sold_date_sk,
                      "sales_price": T.catalog_sales.cs_ext_sales_price})])
    j = u.merge(T.date_dim, left_on="sold_date_sk", right_on="d_date_sk")
    days = ["Sunday", "Monday", "Tuesday", "Wednesday", "Thursday",
            "Friday", "Saturday"]
    piv = {}
    for d in days:
        piv[d] = (j[j.d_day_name == d].groupby("d_week_seq")
                  ["sales_price"].apply(_sum))
    wk = pd.DataFrame(piv)
    dd = T.date_dim
    # the SQL joins wswscs × date_dim ON week_seq alone, so each week row
    # duplicates once per calendar DAY of that week inside the year — the
    # faithful oracle carries that multiplicity (m1 × m2 per week pair)
    m1 = dd[dd.d_year == 1999].groupby("d_week_seq").size()
    m2 = dd[dd.d_year == 2000].groupby("d_week_seq").size()
    y = wk.loc[wk.index.isin(set(m1.index))]
    z = wk.loc[wk.index.isin(set(m2.index))].copy()
    mult2 = m2.copy()
    mult2.index = mult2.index - 52
    z.index = z.index - 52
    m = y.join(z, how="inner", lsuffix="_1", rsuffix="_2")
    dup = (m1.reindex(m.index).fillna(0)
           * mult2.reindex(m.index).fillna(0)).astype(int)
    out = pd.DataFrame({"d_week_seq1": m.index})
    for d, nm in zip(days, ["r_sun", "r_mon", "r_tue", "r_wed", "r_thu",
                            "r_fri", "r_sat"]):
        out[nm] = (m[f"{d}_1"] / m[f"{d}_2"]).round(2).values
    out = out.loc[out.index.repeat(dup.values)]
    return out.reset_index(drop=True), meta(
        ["d_week_seq1"], None, None,
        ["r_sun", "r_mon", "r_tue", "r_wed", "r_thu", "r_fri", "r_sat"])


def q59(T):
    j = T.store_sales.merge(T.date_dim, left_on="ss_sold_date_sk",
                            right_on="d_date_sk")
    days = ["Sunday", "Monday", "Tuesday", "Wednesday", "Thursday",
            "Friday", "Saturday"]
    piv = {}
    for d in days:
        piv[d] = (j[j.d_day_name == d]
                  .groupby(["d_week_seq", "ss_store_sk"])["ss_sales_price"]
                  .apply(_sum))
    wss = pd.DataFrame(piv).reset_index()
    dd = T.date_dim
    # join multiplicity: wss × date_dim ON week_seq duplicates per
    # calendar day of the week inside each month_seq window (cf. q2)
    m1 = dd[dd.d_month_seq.between(1200, 1211)].groupby("d_week_seq").size()
    m2 = dd[dd.d_month_seq.between(1212, 1223)].groupby("d_week_seq").size()
    st = T.store
    y = wss[wss.d_week_seq.isin(set(m1.index))].merge(
        st, left_on="ss_store_sk", right_on="s_store_sk")
    y = y.loc[y.index.repeat(m1.reindex(y.d_week_seq).values)]
    x = wss[wss.d_week_seq.isin(set(m2.index))].merge(
        st, left_on="ss_store_sk", right_on="s_store_sk")
    x = x.loc[x.index.repeat(m2.reindex(x.d_week_seq).values)]
    x = x.assign(join_seq=x.d_week_seq - 52)
    m = y.merge(x, left_on=["s_store_id", "d_week_seq"],
                right_on=["s_store_id", "join_seq"],
                suffixes=("_1", "_2"))
    out = pd.DataFrame({
        "s_store_name1": m.s_store_name_1, "s_store_id1": m.s_store_id,
        "d_week_seq1": m.d_week_seq_1})
    for d, nm in zip(days, ["r_sun", "r_mon", "r_tue", "r_wed", "r_thu",
                            "r_fri", "r_sat"]):
        out[nm] = (m[f"{d}_1"] / m[f"{d}_2"]).values
    return out, meta(["s_store_name1", "s_store_id1", "d_week_seq1"],
                     None, 100, ["r_sun", "r_mon", "r_tue", "r_wed",
                                 "r_thu", "r_fri", "r_sat"])


def _dn_ticket(T, cities):
    j = _star(T.store_sales,
              (T.date_dim, "ss_sold_date_sk", "d_date_sk"),
              (T.store, "ss_store_sk", "s_store_sk"),
              (T.household_demographics, "ss_hdemo_sk", "hd_demo_sk"),
              (T.customer_address, "ss_addr_sk", "ca_address_sk"))
    j = j[((j.hd_dep_count == 4) | (j.hd_vehicle_count == 3))
          & (j.d_year == 2000) & j.s_city.isin(cities)]
    return j


def q46(T):
    j = _dn_ticket(T, ["rivertown", "lakeside"])
    j = j[j.d_dow.isin([5, 6])]
    dn = (j.groupby(["ss_ticket_number", "ss_customer_sk", "ca_city"],
                    as_index=False)
          .agg(amt=("ss_coupon_amt", _sum),
               profit=("ss_net_profit", _sum))
          .rename(columns={"ca_city": "bought_city"}))
    out = (dn.merge(T.customer, left_on="ss_customer_sk",
                    right_on="c_customer_sk")
           .merge(T.customer_address, left_on="c_current_addr_sk",
                  right_on="ca_address_sk"))
    out = out[out.ca_city != out.bought_city]
    out = out.rename(columns={"ca_city": "current_city"})
    out = out[["c_last_name", "c_first_name", "current_city",
               "bought_city", "ss_ticket_number", "amt", "profit"]]
    return out, meta(["c_last_name", "c_first_name", "current_city",
                      "bought_city", "ss_ticket_number"], None, 100,
                     ["amt", "profit"])


def q68(T):
    j = _dn_ticket(T, ["rivertown", "hilltop"])
    j = j[j.d_dom.between(1, 2)]
    dn = (j.groupby(["ss_ticket_number", "ss_customer_sk", "ca_city"],
                    as_index=False)
          .agg(extended_price=("ss_ext_sales_price", _sum),
               list_price=("ss_ext_list_price", _sum),
               extended_tax=("ss_ext_tax", _sum))
          .rename(columns={"ca_city": "bought_city"}))
    out = (dn.merge(T.customer, left_on="ss_customer_sk",
                    right_on="c_customer_sk")
           .merge(T.customer_address, left_on="c_current_addr_sk",
                  right_on="ca_address_sk"))
    out = out[out.ca_city != out.bought_city]
    out = out.rename(columns={"ca_city": "current_city"})
    out = out[["c_last_name", "c_first_name", "current_city",
               "bought_city", "ss_ticket_number", "extended_price",
               "extended_tax", "list_price"]]
    return out, meta(["c_last_name", "ss_ticket_number"], None, 100,
                     ["extended_price", "extended_tax", "list_price"])


def q79(T):
    j = _star(T.store_sales,
              (T.date_dim, "ss_sold_date_sk", "d_date_sk"),
              (T.store, "ss_store_sk", "s_store_sk"),
              (T.household_demographics, "ss_hdemo_sk", "hd_demo_sk"))
    j = j[((j.hd_dep_count == 6) | (j.hd_vehicle_count > 2))
          & (j.d_dow == 1) & j.d_year.isin([1999, 2000, 2001])
          & j.s_number_employees.between(200, 295)]
    ms = (j.groupby(["ss_ticket_number", "ss_customer_sk", "ss_addr_sk",
                     "s_city"], dropna=False, as_index=False)
          .agg(amt=("ss_coupon_amt", _sum),
               profit=("ss_net_profit", _sum)))
    out = ms.merge(T.customer, left_on="ss_customer_sk",
                   right_on="c_customer_sk")
    out = out.assign(city=out.s_city.astype(str).str[:30])
    out = out[["c_last_name", "c_first_name", "city", "ss_ticket_number",
               "amt", "profit"]]
    return out, meta(["c_last_name", "c_first_name", "city", "profit"],
                     None, 100, ["amt", "profit"])


# ----------------------------------------------------- inventory family

def q21(T):
    dd = _dates_between(T.date_dim, "2000-02-10", "2000-04-10")
    j = _star(T.inventory,
              (T.warehouse, "inv_warehouse_sk", "w_warehouse_sk"),
              (T.item, "inv_item_sk", "i_item_sk"),
              (dd, "inv_date_sk", "d_date_sk"))
    j = j[j.i_current_price.between(0.99, 1.49)]
    before = pd.to_datetime(j.d_date) < pd.Timestamp("2000-03-11")
    j = j.assign(
        inv_before=np.where(before, j.inv_quantity_on_hand, 0),
        inv_after=np.where(~before, j.inv_quantity_on_hand, 0))
    g = (j.groupby(["w_warehouse_name", "i_item_id"], as_index=False)
         .agg(inv_before=("inv_before", "sum"),
              inv_after=("inv_after", "sum")))
    ratio = np.where(g.inv_before > 0, g.inv_after / g.inv_before, np.nan)
    g = g[(ratio >= 0.666667) & (ratio <= 1.5)]
    return g, meta(["w_warehouse_name", "i_item_id"], None, 100)


def q37(T):
    dd = _dates_between(T.date_dim, "2000-02-01", "2000-04-01")
    j = _star(T.inventory,
              (T.item, "inv_item_sk", "i_item_sk"),
              (dd, "inv_date_sk", "d_date_sk"))
    j = j[j.i_current_price.between(20, 50)
          & j.i_manufact_id.isin([100, 120, 140, 160])
          & j.inv_quantity_on_hand.between(100, 500)]
    j = j[j.i_item_sk.isin(set(T.catalog_sales.cs_item_sk))]
    out = j[["i_item_id", "i_item_desc", "i_current_price"]] \
        .drop_duplicates()
    return out, meta(["i_item_id"], None, 100, ["i_current_price"])


def q82(T):
    dd = _dates_between(T.date_dim, "2000-05-25", "2000-07-24")
    j = _star(T.inventory,
              (T.item, "inv_item_sk", "i_item_sk"),
              (dd, "inv_date_sk", "d_date_sk"))
    j = j[j.i_current_price.between(30, 60)
          & j.i_manufact_id.isin([50, 70, 90, 110])
          & j.inv_quantity_on_hand.between(100, 500)]
    j = j[j.i_item_sk.isin(set(T.store_sales.ss_item_sk))]
    out = j[["i_item_id", "i_item_desc", "i_current_price"]] \
        .drop_duplicates()
    return out, meta(["i_item_id"], None, 100, ["i_current_price"])


def q39(T):
    j = _star(T.inventory,
              (T.item, "inv_item_sk", "i_item_sk"),
              (T.warehouse, "inv_warehouse_sk", "w_warehouse_sk"),
              (T.date_dim, "inv_date_sk", "d_date_sk"))
    j = j[j.d_year == 2000]
    g = (j.groupby(["w_warehouse_name", "w_warehouse_sk", "i_item_sk",
                    "d_moy"], as_index=False)
         .agg(stdev=("inv_quantity_on_hand", lambda s: s.std(ddof=0)),
              mean=("inv_quantity_on_hand", "mean")))
    cov_f = np.where(g["mean"] == 0, 0, g.stdev / g["mean"])
    g = g[cov_f > 1].copy()
    g["cov"] = np.where(g["mean"] == 0, np.nan, g.stdev / g["mean"])
    inv1 = g[g.d_moy == 1]
    inv2 = g[g.d_moy == 2]
    m = inv1.merge(inv2, on=["i_item_sk", "w_warehouse_sk"],
                   suffixes=("_1", "_2"))
    out = pd.DataFrame({
        "wsk1": m.w_warehouse_sk, "isk1": m.i_item_sk, "moy1": m.d_moy_1,
        "mean1": m.mean_1, "cov1": m.cov_1, "wsk2": m.w_warehouse_sk,
        "isk2": m.i_item_sk, "moy2": m.d_moy_2, "mean2": m.mean_2,
        "cov2": m.cov_2})
    return out, meta(["wsk1", "isk1", "moy1", "mean1", "cov1"], None, 100,
                     ["mean1", "cov1", "mean2", "cov2"])


def q40(T):
    dd = _dates_between(T.date_dim, "2000-02-10", "2000-04-10")
    j = T.catalog_sales.merge(
        T.catalog_returns[["cr_order_number", "cr_item_sk",
                           "cr_refunded_cash"]],
        left_on=["cs_order_number", "cs_item_sk"],
        right_on=["cr_order_number", "cr_item_sk"], how="left")
    j = _star(j, (T.warehouse, "cs_warehouse_sk", "w_warehouse_sk"),
              (T.item, "cs_item_sk", "i_item_sk"),
              (dd, "cs_sold_date_sk", "d_date_sk"))
    j = j[j.i_current_price.between(0.99, 1.49)]
    before = pd.to_datetime(j.d_date) < pd.Timestamp("2000-03-11")
    val = j.cs_sales_price - j.cr_refunded_cash.fillna(0)
    j = j.assign(sales_before=np.where(before, val, 0.0),
                 sales_after=np.where(~before, val, 0.0))
    out = (j.groupby(["w_state", "i_item_id"], as_index=False)
           .agg(sales_before=("sales_before", "sum"),
                sales_after=("sales_after", "sum")))
    return out, meta(["w_state", "i_item_id"], None, 100,
                     ["sales_before", "sales_after"])


# ------------------------------------------------- returns / shipments

def _returns_trio(T, d1_cond, d2_cond, d3_cond, aggs):
    j = T.store_sales.merge(
        T.date_dim[d1_cond(T.date_dim)].add_prefix("d1_"),
        left_on="ss_sold_date_sk", right_on="d1_d_date_sk")
    j = j.merge(T.store_returns,
                left_on=["ss_customer_sk", "ss_item_sk",
                         "ss_ticket_number"],
                right_on=["sr_customer_sk", "sr_item_sk",
                          "sr_ticket_number"])
    j = j.merge(T.date_dim[d2_cond(T.date_dim)].add_prefix("d2_"),
                left_on="sr_returned_date_sk", right_on="d2_d_date_sk")
    j = j.merge(T.catalog_sales,
                left_on=["sr_customer_sk", "sr_item_sk"],
                right_on=["cs_bill_customer_sk", "cs_item_sk"])
    j = j.merge(T.date_dim[d3_cond(T.date_dim)].add_prefix("d3_"),
                left_on="cs_sold_date_sk", right_on="d3_d_date_sk")
    j = j.merge(T.store, left_on="ss_store_sk", right_on="s_store_sk")
    j = j.merge(T.item, left_on="ss_item_sk", right_on="i_item_sk")
    g = j.groupby(["i_item_id", "i_item_desc", "s_store_id",
                   "s_store_name"], as_index=False)
    return g.agg(**aggs)


def q25(T):
    out = _returns_trio(
        T, lambda d: (d.d_moy == 4) & (d.d_year == 2000),
        lambda d: d.d_moy.between(4, 10) & (d.d_year == 2000),
        lambda d: d.d_moy.between(4, 10) & (d.d_year == 2000),
        dict(store_sales_profit=("ss_net_profit", _sum),
             store_returns_loss=("sr_net_loss", _sum),
             catalog_sales_profit=("cs_net_profit", _sum)))
    return out, meta(["i_item_id", "i_item_desc", "s_store_id",
                      "s_store_name"], None, 100,
                     ["store_sales_profit", "store_returns_loss",
                      "catalog_sales_profit"])


def q29(T):
    out = _returns_trio(
        T, lambda d: (d.d_moy == 4) & (d.d_year == 1999),
        lambda d: d.d_moy.between(4, 7) & (d.d_year == 1999),
        lambda d: d.d_year.isin([1999, 2000, 2001]),
        dict(store_sales_quantity=("ss_quantity", _sum),
             store_returns_quantity=("sr_return_quantity", _sum),
             catalog_sales_quantity=("cs_quantity", _sum)))
    return out, meta(["i_item_id", "i_item_desc", "s_store_id",
                      "s_store_name"], None, 100)


def q17(T):
    j = T.store_sales.merge(
        T.date_dim[T.date_dim.d_quarter_name == "2000Q1"].add_prefix("d1_"),
        left_on="ss_sold_date_sk", right_on="d1_d_date_sk")
    j = j.merge(T.store_returns,
                left_on=["ss_customer_sk", "ss_item_sk",
                         "ss_ticket_number"],
                right_on=["sr_customer_sk", "sr_item_sk",
                          "sr_ticket_number"])
    q123 = ["2000Q1", "2000Q2", "2000Q3"]
    j = j.merge(T.date_dim[T.date_dim.d_quarter_name.isin(q123)]
                .add_prefix("d2_"),
                left_on="sr_returned_date_sk", right_on="d2_d_date_sk")
    j = j.merge(T.catalog_sales,
                left_on=["sr_customer_sk", "sr_item_sk"],
                right_on=["cs_bill_customer_sk", "cs_item_sk"])
    j = j.merge(T.date_dim[T.date_dim.d_quarter_name.isin(q123)]
                .add_prefix("d3_"),
                left_on="cs_sold_date_sk", right_on="d3_d_date_sk")
    j = j.merge(T.store, left_on="ss_store_sk", right_on="s_store_sk")
    j = j.merge(T.item, left_on="ss_item_sk", right_on="i_item_sk")
    g = j.groupby(["i_item_id", "i_item_desc", "s_state"], as_index=False)

    def block(col, prefix):
        # ddof=0: the engine's STDDEV is population (sum/sumsq formula),
        # matching the reference's kernel
        return {f"{prefix}count": (col, "count"),
                f"{prefix}ave": (col, "mean"),
                f"{prefix}stdev": (col, lambda s: s.std(ddof=0))}

    out = g.agg(**block("ss_quantity", "store_sales_quantity"),
                **block("sr_return_quantity", "store_returns_quantity"),
                **block("cs_quantity", "catalog_sales_quantity"))
    out["store_sales_quantitycov"] = \
        out.store_sales_quantitystdev / out.store_sales_quantityave
    out["store_returns_quantitycov"] = \
        out.store_returns_quantitystdev / out.store_returns_quantityave
    out["catalog_sales_quantitycov"] = \
        out.catalog_sales_quantitystdev / out.catalog_sales_quantityave
    cols = ["i_item_id", "i_item_desc", "s_state",
            "store_sales_quantitycount", "store_sales_quantityave",
            "store_sales_quantitystdev", "store_sales_quantitycov",
            "store_returns_quantitycount", "store_returns_quantityave",
            "store_returns_quantitystdev", "store_returns_quantitycov",
            "catalog_sales_quantitycount", "catalog_sales_quantityave",
            "catalog_sales_quantitystdev", "catalog_sales_quantitycov"]
    return out[cols], meta(["i_item_id", "i_item_desc", "s_state"], None,
                           100, cols[3:])


def q16(T):
    dd = _dates_between(T.date_dim, "2000-02-01", "2000-04-01")
    cs1 = _star(T.catalog_sales,
                (dd, "cs_ship_date_sk", "d_date_sk"),
                (T.customer_address[T.customer_address.ca_state == "CA"],
                 "cs_ship_addr_sk", "ca_address_sk"),
                (T.call_center, "cs_call_center_sk", "cc_call_center_sk"))
    wh_count = T.catalog_sales.groupby("cs_order_number")[
        "cs_warehouse_sk"].nunique()
    multi = set(wh_count[wh_count > 1].index)
    returned = set(T.catalog_returns.cr_order_number)
    cs1 = cs1[cs1.cs_order_number.isin(multi)
              & ~cs1.cs_order_number.isin(returned)]
    out = pd.DataFrame({
        "order_count": [cs1.cs_order_number.nunique()],
        "total_shipping_cost": [_sum(cs1.cs_ext_ship_cost)],
        "total_net_profit": [_sum(cs1.cs_net_profit)]})
    return out, meta([], None, 100,
                     ["total_shipping_cost", "total_net_profit"])


def q94(T):
    dd = _dates_between(T.date_dim, "2000-02-01", "2000-04-01")
    ws1 = _star(T.web_sales,
                (dd, "ws_ship_date_sk", "d_date_sk"),
                (T.customer_address[T.customer_address.ca_state == "CA"],
                 "ws_ship_addr_sk", "ca_address_sk"),
                (T.web_site[T.web_site.web_company_name == "pri"],
                 "ws_web_site_sk", "web_site_sk"))
    wh_count = T.web_sales.groupby("ws_order_number")[
        "ws_warehouse_sk"].nunique()
    multi = set(wh_count[wh_count > 1].index)
    returned = set(T.web_returns.wr_order_number)
    ws1 = ws1[ws1.ws_order_number.isin(multi)
              & ~ws1.ws_order_number.isin(returned)]
    out = pd.DataFrame({
        "order_count": [ws1.ws_order_number.nunique()],
        "total_shipping_cost": [_sum(ws1.ws_ext_ship_cost)],
        "total_net_profit": [_sum(ws1.ws_net_profit)]})
    return out, meta([], None, 100,
                     ["total_shipping_cost", "total_net_profit"])


def q95(T):
    dd = _dates_between(T.date_dim, "2000-02-01", "2000-04-01")
    ws1 = _star(T.web_sales,
                (dd, "ws_ship_date_sk", "d_date_sk"),
                (T.customer_address[T.customer_address.ca_state == "CA"],
                 "ws_ship_addr_sk", "ca_address_sk"),
                (T.web_site[T.web_site.web_company_name == "pri"],
                 "ws_web_site_sk", "web_site_sk"))
    wh_count = T.web_sales.groupby("ws_order_number")[
        "ws_warehouse_sk"].nunique()
    multi = set(wh_count[wh_count > 1].index)
    returned_multi = set(T.web_returns[
        T.web_returns.wr_order_number.isin(multi)].wr_order_number)
    ws1 = ws1[ws1.ws_order_number.isin(multi)
              & ws1.ws_order_number.isin(returned_multi)]
    out = pd.DataFrame({
        "order_count": [ws1.ws_order_number.nunique()],
        "total_shipping_cost": [_sum(ws1.ws_ext_ship_cost)],
        "total_net_profit": [_sum(ws1.ws_net_profit)]})
    return out, meta([], None, 100,
                     ["total_shipping_cost", "total_net_profit"])


def q97(T):
    dd = T.date_dim[T.date_dim.d_month_seq.between(1200, 1211)]
    ssci = (T.store_sales.merge(dd, left_on="ss_sold_date_sk",
                                right_on="d_date_sk")
            [["ss_customer_sk", "ss_item_sk"]].drop_duplicates())
    csci = (T.catalog_sales.merge(dd, left_on="cs_sold_date_sk",
                                  right_on="d_date_sk")
            [["cs_bill_customer_sk", "cs_item_sk"]].drop_duplicates())
    m = ssci.merge(csci, left_on=["ss_customer_sk", "ss_item_sk"],
                   right_on=["cs_bill_customer_sk", "cs_item_sk"],
                   how="outer")
    out = pd.DataFrame({
        "store_only": [int((m.ss_customer_sk.notna()
                            & m.cs_bill_customer_sk.isna()).sum())],
        "catalog_only": [int((m.ss_customer_sk.isna()
                              & m.cs_bill_customer_sk.notna()).sum())],
        "store_and_catalog": [int((m.ss_customer_sk.notna()
                                   & m.cs_bill_customer_sk.notna()).sum())]})
    return out, meta([], None, 100)


# ------------------------------------------------------- ROLLUP family

def _rollup(df, keys, aggspec):
    """GROUP BY ROLLUP(keys): one grouped frame per prefix level, rolled
    keys as NaN/None."""
    pieces = []
    for lvl in range(len(keys), -1, -1):
        ks = keys[:lvl]
        if ks:
            g = df.groupby(ks, dropna=False, as_index=False).agg(**aggspec)
        else:
            g = pd.DataFrame({k: [v[1](df[v[0]]) if callable(v[1])
                                  else getattr(df[v[0]], v[1])()]
                              for k, v in aggspec.items()})
        for k in keys[lvl:]:
            g[k] = None
        g["__lvl"] = len(keys) - lvl
        pieces.append(g)
    return pd.concat(pieces, ignore_index=True)


def _agg_call(df, col, how):
    if how == "sum":
        return _sum(df[col])
    return getattr(df[col], how)()


def q18(T):
    j = _star(T.catalog_sales,
              (T.date_dim, "cs_sold_date_sk", "d_date_sk"),
              (T.item, "cs_item_sk", "i_item_sk"),
              (T.customer_demographics.add_prefix("cd1_"),
               "cs_bill_cdemo_sk", "cd1_cd_demo_sk"),
              (T.customer, "cs_bill_customer_sk", "c_customer_sk"),
              (T.customer_demographics.add_prefix("cd2_"),
               "c_current_cdemo_sk", "cd2_cd_demo_sk"),
              (T.customer_address, "c_current_addr_sk", "ca_address_sk"))
    j = j[(j.cd1_cd_gender == "F") & (j.cd1_cd_education_status == "Unknown")
          & j.c_birth_month.isin([1, 6, 8, 9, 12, 2]) & (j.d_year == 2000)
          & j.ca_state.isin(["CA", "NY", "TX", "WA", "OR", "TN", "SD"])]
    spec = {f"agg{i + 1}": (c, "mean") for i, c in enumerate(
        ["cs_quantity", "cs_list_price", "cs_coupon_amt", "cs_sales_price",
         "cs_net_profit", "c_birth_year", "cd1_cd_dep_count"])}
    out = _rollup(j, ["i_item_id", "ca_country", "ca_state", "ca_county"],
                  spec).drop(columns="__lvl")
    return out, meta(["ca_country", "ca_state", "ca_county", "i_item_id"],
                     None, 100, [f"agg{i}" for i in range(1, 8)])


def q22(T):
    j = _star(T.inventory,
              (T.date_dim, "inv_date_sk", "d_date_sk"),
              (T.item, "inv_item_sk", "i_item_sk"))
    j = j[j.d_month_seq.between(1212, 1223)]
    out = _rollup(j, ["i_product_name", "i_brand", "i_class", "i_category"],
                  dict(qoh=("inv_quantity_on_hand", "mean"))) \
        .drop(columns="__lvl")
    return out, meta(["qoh", "i_product_name", "i_brand", "i_class",
                      "i_category"], None, 100, ["qoh"])


def q27(T):
    j = _star(T.store_sales,
              (T.customer_demographics, "ss_cdemo_sk", "cd_demo_sk"),
              (T.date_dim, "ss_sold_date_sk", "d_date_sk"),
              (T.store, "ss_store_sk", "s_store_sk"),
              (T.item, "ss_item_sk", "i_item_sk"))
    j = j[(j.cd_gender == "M") & (j.cd_marital_status == "S")
          & (j.cd_education_status == "College") & (j.d_year == 2000)
          & j.s_state.isin(["TN", "SD", "CA"])]
    spec = {f"agg{i + 1}": (c, "mean") for i, c in enumerate(
        ["ss_quantity", "ss_list_price", "ss_coupon_amt",
         "ss_sales_price"])}
    out = _rollup(j, ["i_item_id", "s_state"], spec)
    out["g_state"] = (out.__lvl >= 1).astype(int)
    out = out.drop(columns="__lvl")
    cols = ["i_item_id", "s_state", "g_state"] + [f"agg{i}"
                                                  for i in range(1, 5)]
    return out[cols], meta(["i_item_id", "s_state"], None, 100,
                           [f"agg{i}" for i in range(1, 5)])


def q5(T):
    lo, hi = "2000-08-23", "2000-09-06"
    dd = _dates_between(T.date_dim, lo, hi)
    ss = pd.concat([
        pd.DataFrame({"store_sk": T.store_sales.ss_store_sk,
                      "date_sk": T.store_sales.ss_sold_date_sk,
                      "sales_price": T.store_sales.ss_ext_sales_price,
                      "profit": T.store_sales.ss_net_profit,
                      "return_amt": 0.0, "net_loss": 0.0}),
        pd.DataFrame({"store_sk": T.store_returns.sr_store_sk,
                      "date_sk": T.store_returns.sr_returned_date_sk,
                      "sales_price": 0.0, "profit": 0.0,
                      "return_amt": T.store_returns.sr_return_amt,
                      "net_loss": T.store_returns.sr_net_loss})])
    ssr = (_star(ss, (dd, "date_sk", "d_date_sk"),
                 (T.store, "store_sk", "s_store_sk"))
           .groupby("s_store_id", as_index=False)
           .agg(sales=("sales_price", _sum), profit=("profit", _sum),
                returns_=("return_amt", _sum),
                profit_loss=("net_loss", _sum)))
    cs = pd.concat([
        pd.DataFrame({"page_sk": T.catalog_sales.cs_catalog_page_sk,
                      "date_sk": T.catalog_sales.cs_sold_date_sk,
                      "sales_price": T.catalog_sales.cs_ext_sales_price,
                      "profit": T.catalog_sales.cs_net_profit,
                      "return_amt": 0.0, "net_loss": 0.0}),
        pd.DataFrame({"page_sk": T.catalog_returns.cr_catalog_page_sk,
                      "date_sk": T.catalog_returns.cr_returned_date_sk,
                      "sales_price": 0.0, "profit": 0.0,
                      "return_amt": T.catalog_returns.cr_return_amount,
                      "net_loss": T.catalog_returns.cr_net_loss})])
    csr = (_star(cs, (dd, "date_sk", "d_date_sk"),
                 (T.catalog_page, "page_sk", "cp_catalog_page_sk"))
           .groupby("cp_catalog_page_id", as_index=False)
           .agg(sales=("sales_price", _sum), profit=("profit", _sum),
                returns_=("return_amt", _sum),
                profit_loss=("net_loss", _sum)))
    wr_j = T.web_returns.merge(
        T.web_sales[["ws_item_sk", "ws_order_number", "ws_web_site_sk"]],
        left_on=["wr_item_sk", "wr_order_number"],
        right_on=["ws_item_sk", "ws_order_number"], how="left")
    ws = pd.concat([
        pd.DataFrame({"site_sk": T.web_sales.ws_web_site_sk,
                      "date_sk": T.web_sales.ws_sold_date_sk,
                      "sales_price": T.web_sales.ws_ext_sales_price,
                      "profit": T.web_sales.ws_net_profit,
                      "return_amt": 0.0, "net_loss": 0.0}),
        pd.DataFrame({"site_sk": wr_j.ws_web_site_sk,
                      "date_sk": wr_j.wr_returned_date_sk,
                      "sales_price": 0.0, "profit": 0.0,
                      "return_amt": wr_j.wr_return_amt,
                      "net_loss": wr_j.wr_net_loss})])
    wsr = (_star(ws, (dd, "date_sk", "d_date_sk"),
                 (T.web_site, "site_sk", "web_site_sk"))
           .groupby("web_site_id", as_index=False)
           .agg(sales=("sales_price", _sum), profit=("profit", _sum),
                returns_=("return_amt", _sum),
                profit_loss=("net_loss", _sum)))
    u = pd.concat([
        pd.DataFrame({"channel": "store channel",
                      "id": "store" + ssr.s_store_id.astype(str),
                      "sales": ssr.sales, "returns_": ssr.returns_,
                      "profit": ssr.profit - ssr.profit_loss}),
        pd.DataFrame({"channel": "catalog channel",
                      "id": "catalog_page"
                      + csr.cp_catalog_page_id.astype(str),
                      "sales": csr.sales, "returns_": csr.returns_,
                      "profit": csr.profit - csr.profit_loss}),
        pd.DataFrame({"channel": "web channel",
                      "id": "web_site" + wsr.web_site_id.astype(str),
                      "sales": wsr.sales, "returns_": wsr.returns_,
                      "profit": wsr.profit - wsr.profit_loss})])
    out = _rollup(u, ["channel", "id"],
                  dict(sales=("sales", "sum"), returns_=("returns_", "sum"),
                       profit=("profit", "sum"))).drop(columns="__lvl")
    return out, meta(["channel", "id"], None, 100,
                     ["sales", "returns_", "profit"])


def q77(T):
    lo, hi = "2000-08-23", "2000-09-22"
    dd = _dates_between(T.date_dim, lo, hi)
    ss = (_star(T.store_sales, (dd, "ss_sold_date_sk", "d_date_sk"),
                (T.store, "ss_store_sk", "s_store_sk"))
          .groupby("s_store_sk", as_index=False)
          .agg(sales=("ss_ext_sales_price", _sum),
               profit=("ss_net_profit", _sum)))
    sr = (_star(T.store_returns, (dd, "sr_returned_date_sk", "d_date_sk"),
                (T.store, "sr_store_sk", "s_store_sk"))
          .groupby("sr_store_sk", as_index=False)
          .agg(returns_=("sr_return_amt", _sum),
               profit_loss=("sr_net_loss", _sum)))
    store = ss.merge(sr, left_on="s_store_sk", right_on="sr_store_sk",
                     how="left")
    cs = (T.catalog_sales.merge(dd, left_on="cs_sold_date_sk",
                                right_on="d_date_sk")
          .groupby("cs_call_center_sk", as_index=False)
          .agg(sales=("cs_ext_sales_price", _sum),
               profit=("cs_net_profit", _sum)))
    cr = (T.catalog_returns.merge(dd, left_on="cr_returned_date_sk",
                                  right_on="d_date_sk")
          .groupby("cr_call_center_sk", as_index=False)
          .agg(returns_=("cr_return_amount", _sum),
               profit_loss=("cr_net_loss", _sum)))
    cat = cs.merge(cr, left_on="cs_call_center_sk",
                   right_on="cr_call_center_sk", how="left")
    ws = (_star(T.web_sales, (dd, "ws_sold_date_sk", "d_date_sk"),
                (T.web_page, "ws_web_page_sk", "wp_web_page_sk"))
          .groupby("wp_web_page_sk", as_index=False)
          .agg(sales=("ws_ext_sales_price", _sum),
               profit=("ws_net_profit", _sum)))
    wr = (_star(T.web_returns, (dd, "wr_returned_date_sk", "d_date_sk"),
                (T.web_page, "wr_web_page_sk", "wp_web_page_sk"))
          .groupby("wp_web_page_sk", as_index=False)
          .agg(returns_=("wr_return_amt", _sum),
               profit_loss=("wr_net_loss", _sum)))
    web = ws.merge(wr, on="wp_web_page_sk", how="left",
                   suffixes=("", "_r"))
    u = pd.concat([
        pd.DataFrame({"channel": "store channel", "id": store.s_store_sk,
                      "sales": store.sales,
                      "returns_": store.returns_.fillna(0),
                      "profit": store.profit
                      - store.profit_loss.fillna(0)}),
        pd.DataFrame({"channel": "catalog channel",
                      "id": cat.cs_call_center_sk, "sales": cat.sales,
                      "returns_": cat.returns_.fillna(0),
                      "profit": cat.profit - cat.profit_loss.fillna(0)}),
        pd.DataFrame({"channel": "web channel", "id": web.wp_web_page_sk,
                      "sales": web.sales,
                      "returns_": web.returns_.fillna(0),
                      "profit": web.profit - web.profit_loss.fillna(0)})])
    out = _rollup(u, ["channel", "id"],
                  dict(sales=("sales", "sum"), returns_=("returns_", "sum"),
                       profit=("profit", "sum"))).drop(columns="__lvl")
    return out, meta(["channel", "id"], None, 100,
                     ["sales", "returns_", "profit"])


def q80(T):
    lo, hi = "2000-08-23", "2000-09-22"
    dd = _dates_between(T.date_dim, lo, hi)
    promo = T.promotion[T.promotion.p_channel_tv == "N"]
    hot_items = T.item[T.item.i_current_price > 50]

    def chan(fact, ret, sale_keys, ret_keys, date_col, store_join, price,
             profit, ret_amt, ret_loss, group_id):
        j = fact.merge(ret[ret_keys + [ret_amt, ret_loss]],
                       left_on=sale_keys, right_on=ret_keys, how="left")
        j = j.merge(dd, left_on=date_col, right_on="d_date_sk")
        j = j.merge(store_join[0], left_on=store_join[1],
                    right_on=store_join[2])
        j = j.merge(hot_items, left_on=sale_keys[0], right_on="i_item_sk")
        j = j.merge(promo, left_on=group_id[2], right_on="p_promo_sk")
        g = j.groupby(group_id[0], as_index=False).agg(
            sales=(price, _sum),
            returns_=(ret_amt, lambda s: s.fillna(0).sum()),
            profit_amt=(profit, _sum),
            loss=(ret_loss, lambda s: s.fillna(0).sum()))
        g["profit"] = g.profit_amt - g.loss
        return g

    ssr = chan(T.store_sales, T.store_returns,
               ["ss_item_sk", "ss_ticket_number"],
               ["sr_item_sk", "sr_ticket_number"], "ss_sold_date_sk",
               (T.store, "ss_store_sk", "s_store_sk"),
               "ss_ext_sales_price", "ss_net_profit", "sr_return_amt",
               "sr_net_loss", ("s_store_id", None, "ss_promo_sk"))
    csr = chan(T.catalog_sales, T.catalog_returns,
               ["cs_item_sk", "cs_order_number"],
               ["cr_item_sk", "cr_order_number"], "cs_sold_date_sk",
               (T.catalog_page, "cs_catalog_page_sk",
                "cp_catalog_page_sk"),
               "cs_ext_sales_price", "cs_net_profit", "cr_return_amount",
               "cr_net_loss", ("cp_catalog_page_id", None, "cs_promo_sk"))
    wsr = chan(T.web_sales, T.web_returns,
               ["ws_item_sk", "ws_order_number"],
               ["wr_item_sk", "wr_order_number"], "ws_sold_date_sk",
               (T.web_site, "ws_web_site_sk", "web_site_sk"),
               "ws_ext_sales_price", "ws_net_profit", "wr_return_amt",
               "wr_net_loss", ("web_site_id", None, "ws_promo_sk"))
    u = pd.concat([
        pd.DataFrame({"channel": "store channel",
                      "id": "store" + ssr.s_store_id.astype(str),
                      "sales": ssr.sales, "returns_": ssr.returns_,
                      "profit": ssr.profit}),
        pd.DataFrame({"channel": "catalog channel",
                      "id": "catalog_page"
                      + csr.cp_catalog_page_id.astype(str),
                      "sales": csr.sales, "returns_": csr.returns_,
                      "profit": csr.profit}),
        pd.DataFrame({"channel": "web channel",
                      "id": "web_site" + wsr.web_site_id.astype(str),
                      "sales": wsr.sales, "returns_": wsr.returns_,
                      "profit": wsr.profit})])
    out = _rollup(u, ["channel", "id"],
                  dict(sales=("sales", "sum"), returns_=("returns_", "sum"),
                       profit=("profit", "sum"))).drop(columns="__lvl")
    return out, meta(["channel", "id"], None, 100,
                     ["sales", "returns_", "profit"])


def q67(T):
    j = _star(T.store_sales,
              (T.date_dim, "ss_sold_date_sk", "d_date_sk"),
              (T.store, "ss_store_sk", "s_store_sk"),
              (T.item, "ss_item_sk", "i_item_sk"))
    j = j[j.d_month_seq.between(1212, 1223)]
    j = j.assign(val=(j.ss_sales_price * j.ss_quantity).fillna(0))
    keys = ["i_category", "i_class", "i_brand", "i_product_name", "d_year",
            "d_qoy", "d_moy", "s_store_id"]
    dw1 = _rollup(j, keys, dict(sumsales=("val", "sum"))) \
        .drop(columns="__lvl")
    dw1["rk"] = dw1.groupby("i_category", dropna=False)["sumsales"] \
        .rank(method="min", ascending=False).astype(int)
    out = dw1[dw1.rk <= 100]
    return out[keys + ["sumsales", "rk"]], meta(
        keys + ["sumsales", "rk"], None, 100, ["sumsales"])


def q86(T):
    j = _star(T.web_sales,
              (T.date_dim, "ws_sold_date_sk", "d_date_sk"),
              (T.item, "ws_item_sk", "i_item_sk"))
    j = j[j.d_month_seq.between(1200, 1211)]
    r = _rollup(j, ["i_category", "i_class"],
                dict(total_sum=("ws_net_paid", "sum")))
    r["lochierarchy"] = r.__lvl
    r["rank_within_parent"] = r.groupby(
        ["__lvl", np.where(r.__lvl == 0, r.i_category, None)],
        dropna=False)["total_sum"] \
        .rank(method="min", ascending=False).astype(int)
    out = r[["total_sum", "i_category", "i_class", "lochierarchy",
             "rank_within_parent"]]
    return out, meta([], None, 100, ["total_sum"], unordered=True)


def q70(T):
    dd = T.date_dim[T.date_dim.d_month_seq.between(1200, 1211)]
    base = _star(T.store_sales, (dd, "ss_sold_date_sk", "d_date_sk"),
                 (T.store, "ss_store_sk", "s_store_sk"))
    # top-5 states by profit (rank within a single-state partition is
    # always 1, so every state with sales qualifies — spec quirk kept)
    st_rank = base.groupby("s_state")["ss_net_profit"].sum()
    states = set(st_rank.index)
    j = base[base.s_state.isin(states)]
    r = _rollup(j, ["s_state", "s_county"],
                dict(total_sum=("ss_net_profit", "sum")))
    r["lochierarchy"] = r.__lvl
    r["rank_within_parent"] = r.groupby(
        ["__lvl", np.where(r.__lvl == 0, r.s_state, None)],
        dropna=False)["total_sum"] \
        .rank(method="min", ascending=False).astype(int)
    out = r[["total_sum", "s_state", "s_county", "lochierarchy",
             "rank_within_parent"]]
    return out, meta([], None, 100, ["total_sum"], unordered=True)


def q36(T):
    j = _star(T.store_sales,
              (T.date_dim, "ss_sold_date_sk", "d_date_sk"),
              (T.item, "ss_item_sk", "i_item_sk"),
              (T.store, "ss_store_sk", "s_store_sk"))
    j = j[(j.d_year == 2000) & (j.s_state == "TN")]
    res = (j.groupby(["i_category", "i_class"], as_index=False)
           .agg(np_=("ss_net_profit", "sum"),
                esp=("ss_ext_sales_price", "sum")))
    lvl0 = pd.DataFrame({
        "gross_margin": res.np_ / res.esp, "i_category": res.i_category,
        "i_class": res.i_class, "lochierarchy": 0})
    bycat = res.groupby("i_category", as_index=False).agg(
        np_=("np_", "sum"), esp=("esp", "sum"))
    lvl1 = pd.DataFrame({
        "gross_margin": bycat.np_ / bycat.esp,
        "i_category": bycat.i_category, "i_class": None, "lochierarchy": 1})
    lvl2 = pd.DataFrame({
        "gross_margin": [res.np_.sum() / res.esp.sum()],
        "i_category": [None], "i_class": [None], "lochierarchy": [2]})
    r = pd.concat([lvl0, lvl1, lvl2], ignore_index=True)
    r["rank_within_parent"] = r.groupby(
        ["lochierarchy", np.where(r.lochierarchy == 0, r.i_category,
                                  None)], dropna=False)["gross_margin"] \
        .rank(method="min", ascending=True).astype(int)
    return r, meta([], None, 100, ["gross_margin"], unordered=True)


def q14(T):
    dd3 = T.date_dim[T.date_dim.d_year.between(1999, 2001)]

    def chan_keys(fact, item_sk, date_sk):
        j = _star(fact, (T.item, item_sk, "i_item_sk"),
                  (dd3, date_sk, "d_date_sk"))
        return set(map(tuple, j[["i_brand_id", "i_class_id",
                                 "i_category_id"]]
                       .drop_duplicates().itertuples(index=False)))

    common = (chan_keys(T.store_sales, "ss_item_sk", "ss_sold_date_sk")
              & chan_keys(T.catalog_sales, "cs_item_sk", "cs_sold_date_sk")
              & chan_keys(T.web_sales, "ws_item_sk", "ws_sold_date_sk"))
    it = T.item
    cross_items = set(it[[tuple(r) in common for r in
                          zip(it.i_brand_id, it.i_class_id,
                              it.i_category_id)]].i_item_sk)
    avg_parts = []
    for fact, q, lp, date_sk in (
            (T.store_sales, "ss_quantity", "ss_list_price",
             "ss_sold_date_sk"),
            (T.catalog_sales, "cs_quantity", "cs_list_price",
             "cs_sold_date_sk"),
            (T.web_sales, "ws_quantity", "ws_list_price",
             "ws_sold_date_sk")):
        p = fact.merge(dd3, left_on=date_sk, right_on="d_date_sk")
        avg_parts.append(p[q] * p[lp])
    average_sales = pd.concat(avg_parts).mean()
    pieces = []
    for fact, chan, item_sk, q, lp, date_sk in (
            (T.store_sales, "store", "ss_item_sk", "ss_quantity",
             "ss_list_price", "ss_sold_date_sk"),
            (T.catalog_sales, "catalog", "cs_item_sk", "cs_quantity",
             "cs_list_price", "cs_sold_date_sk"),
            (T.web_sales, "web", "ws_item_sk", "ws_quantity",
             "ws_list_price", "ws_sold_date_sk")):
        p = fact[fact[item_sk].isin(cross_items)]
        p = _star(p, (T.item, item_sk, "i_item_sk"),
                  (T.date_dim, date_sk, "d_date_sk"))
        p = p[(p.d_year == 2001) & (p.d_moy == 11)]
        p = p.assign(val=p[q] * p[lp])
        g = (p.groupby(["i_brand_id", "i_class_id", "i_category_id"],
                       as_index=False)
             .agg(sales=("val", _sum), number_sales=("val", "size")))
        g = g[g.sales > average_sales]
        g.insert(0, "channel", chan)
        pieces.append(g)
    u = pd.concat(pieces, ignore_index=True)
    out = _rollup(u, ["channel", "i_brand_id", "i_class_id",
                      "i_category_id"],
                  dict(sum_sales=("sales", "sum"),
                       sum_number_sales=("number_sales", "sum"))) \
        .drop(columns="__lvl")
    return out, meta(["channel", "i_brand_id", "i_class_id",
                      "i_category_id"], None, 100, ["sum_sales"])


# --------------------------------------------- customer-growth self-joins

def _year_total(T, fact, cust_col, date_sk, val_fn, sale_type):
    j = _star(fact, (T.customer, cust_col, "c_customer_sk"),
              (T.date_dim, date_sk, "d_date_sk"))
    j = j.assign(__v=val_fn(j))
    g = (j.groupby(["c_customer_id", "c_first_name", "c_last_name",
                    "c_preferred_cust_flag", "d_year"], dropna=False,
                   as_index=False)
         .agg(year_total=("__v", _sum)))
    g["sale_type"] = sale_type
    return g


def _growth(yt, chans, y1=2000, y2=2001):
    """Customers whose chanB ratio (y2/y1) beats the chanA (store) ratio
    for every non-store channel in ``chans``."""
    frames = {}
    for st in {c for pair in chans for c in pair}:
        sub = yt[yt.sale_type == st]
        frames[(st, y1)] = sub[sub.d_year == y1].set_index("c_customer_id")
        frames[(st, y2)] = sub[sub.d_year == y2].set_index("c_customer_id")
    s1, s2 = frames[("s", y1)], frames[("s", y2)]
    ids = set(s1[s1.year_total > 0].index) & set(s2.index)
    ok = []
    for cid in ids:
        s_ratio = s2.year_total.get(cid, np.nan) / s1.year_total[cid]
        good = True
        for (other, _) in chans:
            if other == "s":
                continue
            o1, o2 = frames[(other, y1)], frames[(other, y2)]
            if cid not in o1.index or o1.year_total[cid] <= 0 \
                    or cid not in o2.index:
                good = False
                break
            o_ratio = o2.year_total[cid] / o1.year_total[cid]
            if not (o_ratio > s_ratio):
                good = False
                break
        if good:
            ok.append(cid)
    out = s2.loc[sorted(ok)].reset_index()
    return out


def q11(T):
    yt = pd.concat([
        _year_total(T, T.store_sales, "ss_customer_sk", "ss_sold_date_sk",
                    lambda j: j.ss_ext_list_price - j.ss_ext_discount_amt,
                    "s"),
        _year_total(T, T.web_sales, "ws_bill_customer_sk",
                    "ws_sold_date_sk",
                    lambda j: j.ws_ext_list_price - j.ws_ext_discount_amt,
                    "w")])
    out = _growth(yt, [("s", "s"), ("w", "w")])
    out = out[["c_customer_id", "c_first_name", "c_last_name",
               "c_preferred_cust_flag"]]
    out.columns = ["customer_id", "customer_first_name",
                   "customer_last_name", "customer_preferred_cust_flag"]
    return out, meta(["customer_id", "customer_first_name",
                      "customer_last_name",
                      "customer_preferred_cust_flag"], None, 100)


def q74(T):
    yt = pd.concat([
        _year_total(T, T.store_sales[
            T.store_sales.ss_sold_date_sk.isin(
                set(T.date_dim[T.date_dim.d_year.isin([2000, 2001])]
                    .d_date_sk))],
            "ss_customer_sk", "ss_sold_date_sk",
            lambda j: j.ss_net_paid, "s"),
        _year_total(T, T.web_sales[
            T.web_sales.ws_sold_date_sk.isin(
                set(T.date_dim[T.date_dim.d_year.isin([2000, 2001])]
                    .d_date_sk))],
            "ws_bill_customer_sk", "ws_sold_date_sk",
            lambda j: j.ws_net_paid, "w")])
    out = _growth(yt, [("s", "s"), ("w", "w")])
    out = out[["c_customer_id", "c_first_name", "c_last_name"]]
    out.columns = ["customer_id", "customer_first_name",
                   "customer_last_name"]
    return out, meta(["customer_id"], None, 100)


def q4(T):
    def val_s(j):
        return ((j.ss_ext_list_price - j.ss_ext_wholesale_cost
                 - j.ss_ext_discount_amt) + j.ss_ext_sales_price) / 2

    def val_c(j):
        return ((j.cs_ext_list_price - j.cs_ext_wholesale_cost
                 - j.cs_ext_discount_amt) + j.cs_ext_sales_price) / 2

    def val_w(j):
        return ((j.ws_ext_list_price - j.ws_ext_wholesale_cost
                 - j.ws_ext_discount_amt) + j.ws_ext_sales_price) / 2

    yt = pd.concat([
        _year_total(T, T.store_sales, "ss_customer_sk", "ss_sold_date_sk",
                    val_s, "s"),
        _year_total(T, T.catalog_sales, "cs_bill_customer_sk",
                    "cs_sold_date_sk", val_c, "c"),
        _year_total(T, T.web_sales, "ws_bill_customer_sk",
                    "ws_sold_date_sk", val_w, "w")])
    # c ratio > s ratio AND c ratio > w ratio, with s/c/w firstyear > 0
    f = {}
    for st in "scw":
        sub = yt[yt.sale_type == st]
        f[(st, 2000)] = sub[sub.d_year == 2000].set_index("c_customer_id")
        f[(st, 2001)] = sub[sub.d_year == 2001].set_index("c_customer_id")
    ids = set(f[("s", 2000)].index) & set(f[("s", 2001)].index) \
        & set(f[("c", 2000)].index) & set(f[("c", 2001)].index) \
        & set(f[("w", 2000)].index) & set(f[("w", 2001)].index)
    ok = []
    for cid in ids:
        s1 = f[("s", 2000)].year_total[cid]
        c1 = f[("c", 2000)].year_total[cid]
        w1 = f[("w", 2000)].year_total[cid]
        if not (s1 > 0 and c1 > 0 and w1 > 0):
            continue
        c_ratio = f[("c", 2001)].year_total[cid] / c1
        s_ratio = f[("s", 2001)].year_total[cid] / s1
        w_ratio = f[("w", 2001)].year_total[cid] / w1
        if c_ratio > s_ratio and c_ratio > w_ratio:
            ok.append(cid)
    out = f[("s", 2001)].loc[sorted(ok)].reset_index()
    out = out[["c_customer_id", "c_first_name", "c_last_name",
               "c_preferred_cust_flag"]]
    out.columns = ["customer_id", "customer_first_name",
                   "customer_last_name", "customer_preferred_cust_flag"]
    return out, meta(["customer_id", "customer_first_name",
                      "customer_last_name",
                      "customer_preferred_cust_flag"], None, 100)


# ----------------------------------------------------------- the rest

def q23(T):
    dd3 = T.date_dim[T.date_dim.d_year.isin([1999, 2000, 2001])]
    j = _star(T.store_sales, (dd3, "ss_sold_date_sk", "d_date_sk"),
              (T.item, "ss_item_sk", "i_item_sk"))
    j = j.assign(itemdesc=j.i_item_desc.astype(str).str[:30])
    freq = (j.groupby(["itemdesc", "i_item_sk", "d_date"], as_index=False)
            .size())
    # one row per qualifying (item, sold-date): the SQL inner join FANS
    # OUT sales of an item that was frequent on several days — keep the
    # frame, not a set, so the oracle fans out identically
    freq_rows = freq[freq["size"] > 4][["i_item_sk"]].rename(
        columns={"i_item_sk": "freq_item_sk"})
    sales_by_cust = (T.store_sales.merge(
        dd3, left_on="ss_sold_date_sk", right_on="d_date_sk")
        .merge(T.customer, left_on="ss_customer_sk",
               right_on="c_customer_sk"))
    sales_by_cust = sales_by_cust.assign(
        csales=sales_by_cust.ss_quantity * sales_by_cust.ss_sales_price)
    cmax = sales_by_cust.groupby("c_customer_sk")["csales"].sum().max()
    all_cust = (T.store_sales.merge(
        T.customer, left_on="ss_customer_sk", right_on="c_customer_sk"))
    all_cust = all_cust.assign(
        ssales=all_cust.ss_quantity * all_cust.ss_sales_price)
    tot = all_cust.groupby("c_customer_sk")["ssales"].sum()
    best = set(tot[tot > 0.5 * cmax].index)
    dd_feb = T.date_dim[(T.date_dim.d_year == 2000)
                        & (T.date_dim.d_moy == 2)]
    pieces = []
    for fact, cust, item, date_sk, q, lp in (
            (T.catalog_sales, "cs_bill_customer_sk", "cs_item_sk",
             "cs_sold_date_sk", "cs_quantity", "cs_list_price"),
            (T.web_sales, "ws_bill_customer_sk", "ws_item_sk",
             "ws_sold_date_sk", "ws_quantity", "ws_list_price")):
        p = fact.merge(dd_feb, left_on=date_sk, right_on="d_date_sk")
        p = p[p[cust].isin(best)]
        p = p.merge(freq_rows, left_on=item, right_on="freq_item_sk")
        p = p.merge(T.customer, left_on=cust, right_on="c_customer_sk")
        p = p.assign(val=p[q] * p[lp])
        g = (p.groupby(["c_last_name", "c_first_name"], dropna=False,
                       as_index=False).agg(sales=("val", _sum)))
        pieces.append(g)
    out = pd.concat(pieces, ignore_index=True)
    return out, meta(["c_last_name", "c_first_name", "sales"], None, 100,
                     ["sales"])


def q24(T):
    j = T.store_sales.merge(
        T.store_returns, left_on=["ss_ticket_number", "ss_item_sk"],
        right_on=["sr_ticket_number", "sr_item_sk"])
    j = j.merge(T.customer, left_on="ss_customer_sk",
                right_on="c_customer_sk")
    j = j.merge(T.item, left_on="ss_item_sk", right_on="i_item_sk")
    j = j.merge(T.store[T.store.s_market_id == 8], left_on="ss_store_sk",
                right_on="s_store_sk")
    j = j.merge(T.customer_address, left_on="c_current_addr_sk",
                right_on="ca_address_sk")
    j = j[j.c_birth_country != j.ca_country.astype(str).str.upper()]
    keys = ["c_last_name", "c_first_name", "s_store_name", "ca_state",
            "s_state", "i_color", "i_current_price", "i_manager_id",
            "i_units", "i_size"]
    ssales = (j.groupby(keys, dropna=False, as_index=False)
              .agg(netpaid=("ss_net_paid", _sum)))
    thresh = 0.05 * ssales.netpaid.mean()
    peach = ssales[ssales.i_color == "peach"]
    out = (peach.groupby(["c_last_name", "c_first_name", "s_store_name"],
                         dropna=False, as_index=False)
           .agg(paid=("netpaid", _sum)))
    out = out[out.paid > thresh]
    return out, meta(["c_last_name", "c_first_name", "s_store_name"],
                     None, None, ["paid"])


def q31(T):
    ss = (_star(T.store_sales, (T.date_dim, "ss_sold_date_sk", "d_date_sk"),
                (T.customer_address, "ss_addr_sk", "ca_address_sk"))
          .groupby(["ca_county", "d_qoy", "d_year"], as_index=False)
          .agg(store_sales=("ss_ext_sales_price", _sum)))
    ws = (_star(T.web_sales, (T.date_dim, "ws_sold_date_sk", "d_date_sk"),
                (T.customer_address, "ws_bill_addr_sk", "ca_address_sk"))
          .groupby(["ca_county", "d_qoy", "d_year"], as_index=False)
          .agg(web_sales=("ws_ext_sales_price", _sum)))

    def pick(df, col, q):
        p = df[(df.d_qoy == q) & (df.d_year == 2000)]
        return p.set_index("ca_county")[col]

    s1, s2, s3 = (pick(ss, "store_sales", q) for q in (1, 2, 3))
    w1, w2, w3 = (pick(ws, "web_sales", q) for q in (1, 2, 3))
    counties = (set(s1.index) & set(s2.index) & set(s3.index)
                & set(w1.index) & set(w2.index) & set(w3.index))
    rows = []
    for c in sorted(counties):
        wg1 = w2[c] / w1[c] if w1[c] > 0 else np.nan
        sg1 = s2[c] / s1[c] if s1[c] > 0 else np.nan
        wg2 = w3[c] / w2[c] if w2[c] > 0 else np.nan
        sg2 = s3[c] / s2[c] if s2[c] > 0 else np.nan
        if not (np.isnan(wg1) or np.isnan(sg1)) and wg1 > sg1 \
                and not (np.isnan(wg2) or np.isnan(sg2)) and wg2 > sg2:
            rows.append((c, 2000, wg1, sg1, wg2, sg2))
    out = pd.DataFrame(rows, columns=[
        "ca_county", "d_year", "web_q1_q2_increase",
        "store_q1_q2_increase", "web_q2_q3_increase",
        "store_q2_q3_increase"])
    return out, meta(["ca_county"], None, None,
                     ["web_q1_q2_increase", "store_q1_q2_increase",
                      "web_q2_q3_increase", "store_q2_q3_increase"])


def q44(T):
    ss4 = T.store_sales[T.store_sales.ss_store_sk == 4]
    base = ss4[ss4.ss_addr_sk.isna()].ss_net_profit.mean()
    byitem = (ss4.groupby("ss_item_sk", as_index=False)
              .agg(rank_col=("ss_net_profit", "mean")))
    byitem = byitem[byitem.rank_col > 0.9 * base]
    asc = byitem.sort_values("rank_col", ascending=True, kind="stable")
    asc = asc.assign(rnk=byitem.rank_col.rank(method="min"))
    desc = byitem.assign(
        rnk=byitem.rank_col.rank(method="min", ascending=False))
    a = asc[asc.rnk < 11].merge(T.item, left_on="ss_item_sk",
                                right_on="i_item_sk")
    d = desc[desc.rnk < 11].merge(T.item, left_on="ss_item_sk",
                                  right_on="i_item_sk")
    m = a.merge(d, on="rnk", suffixes=("_a", "_d"))
    out = pd.DataFrame({
        "rnk": m.rnk.astype(int),
        "best_performing": m.i_product_name_a,
        "worst_performing": m.i_product_name_d})
    return out, meta(["rnk"], None, 100)


def q49(T):
    dd = T.date_dim[(T.date_dim.d_year == 2000) & (T.date_dim.d_moy == 12)]
    pieces = []
    for chan, fact, ret, sk, rk, q, rq, amt, paid, profit, date_sk in (
            ("web", T.web_sales, T.web_returns,
             ["ws_order_number", "ws_item_sk"],
             ["wr_order_number", "wr_item_sk"], "ws_quantity",
             "wr_return_quantity", "wr_return_amt", "ws_net_paid",
             "ws_net_profit", "ws_sold_date_sk"),
            ("catalog", T.catalog_sales, T.catalog_returns,
             ["cs_order_number", "cs_item_sk"],
             ["cr_order_number", "cr_item_sk"], "cs_quantity",
             "cr_return_quantity", "cr_return_amount", "cs_net_paid",
             "cs_net_profit", "cs_sold_date_sk"),
            ("store", T.store_sales, T.store_returns,
             ["ss_ticket_number", "ss_item_sk"],
             ["sr_ticket_number", "sr_item_sk"], "ss_quantity",
             "sr_return_quantity", "sr_return_amt", "ss_net_paid",
             "ss_net_profit", "ss_sold_date_sk")):
        j = fact.merge(ret[rk + [rq, amt]], left_on=sk, right_on=rk,
                       how="left")
        j = j.merge(dd, left_on=date_sk, right_on="d_date_sk")
        j = j[(j[amt] > 100) & (j[profit] > 1) & (j[paid] > 0)
              & (j[q] > 0)]
        item_col = sk[1]
        g = (j.groupby(item_col, as_index=False)
             .agg(rq_sum=(rq, lambda s: s.fillna(0).sum()),
                  q_sum=(q, lambda s: s.fillna(0).sum()),
                  amt_sum=(amt, lambda s: s.fillna(0).sum()),
                  paid_sum=(paid, lambda s: s.fillna(0).sum())))
        g["return_ratio"] = g.rq_sum / g.q_sum
        g["currency_ratio"] = g.amt_sum / g.paid_sum
        g["return_rank"] = g.return_ratio.rank(method="min")
        g["currency_rank"] = g.currency_ratio.rank(method="min")
        g = g[(g.return_rank <= 10) | (g.currency_rank <= 10)]
        out = pd.DataFrame({
            "channel": chan, "item": g[item_col],
            "return_ratio": g.return_ratio,
            "return_rank": g.return_rank.astype(int),
            "currency_rank": g.currency_rank.astype(int)})
        pieces.append(out)
    u = pd.concat(pieces, ignore_index=True).drop_duplicates()
    return u, meta(["channel", "return_rank", "currency_rank", "item"],
                   None, 100, ["return_ratio"])


def q51(T):
    dd = T.date_dim[T.date_dim.d_month_seq.between(1200, 1211)]

    def cume(fact, item, date_sk, price):
        j = fact[fact[item].notna()].merge(dd, left_on=date_sk,
                                           right_on="d_date_sk")
        g = (j.groupby([item, "d_date"], as_index=False)
             .agg(s=(price, _sum)))
        g = g.sort_values([item, "d_date"], kind="stable")
        g["cume_sales"] = g.groupby(item)["s"].cumsum()
        return g.rename(columns={item: "item_sk"})[
            ["item_sk", "d_date", "cume_sales"]]

    web = cume(T.web_sales, "ws_item_sk", "ws_sold_date_sk",
               "ws_sales_price")
    store = cume(T.store_sales, "ss_item_sk", "ss_sold_date_sk",
                 "ss_sales_price")
    m = web.merge(store, on=["item_sk", "d_date"], how="outer",
                  suffixes=("_w", "_s"))
    m = m.rename(columns={"cume_sales_w": "web_sales",
                          "cume_sales_s": "store_sales"})
    m = m.sort_values(["item_sk", "d_date"], kind="stable")
    # SQL MAX() OVER ignores NULLs: a date with no web row still carries
    # the running max — pandas cummax leaves NaN, so forward-fill per item
    m["web_cumulative"] = m.groupby("item_sk")["web_sales"].cummax()
    m["web_cumulative"] = m.groupby("item_sk")["web_cumulative"].ffill()
    m["store_cumulative"] = m.groupby("item_sk")["store_sales"].cummax()
    m["store_cumulative"] = m.groupby("item_sk")[
        "store_cumulative"].ffill()
    out = m[m.web_cumulative > m.store_cumulative]
    out = out[["item_sk", "d_date", "web_sales", "store_sales",
               "web_cumulative", "store_cumulative"]]
    return out, meta(["item_sk", "d_date"], None, 100,
                     ["web_sales", "store_sales", "web_cumulative",
                      "store_cumulative"])


def q54(T):
    dd = T.date_dim
    u = pd.concat([
        pd.DataFrame({"sold_date_sk": T.catalog_sales.cs_sold_date_sk,
                      "customer_sk": T.catalog_sales.cs_bill_customer_sk,
                      "item_sk": T.catalog_sales.cs_item_sk}),
        pd.DataFrame({"sold_date_sk": T.web_sales.ws_sold_date_sk,
                      "customer_sk": T.web_sales.ws_bill_customer_sk,
                      "item_sk": T.web_sales.ws_item_sk})])
    it = T.item[(T.item.i_category == "Women")
                & (T.item.i_class == "dresses")]
    dd_dec = dd[(dd.d_moy == 12) & (dd.d_year == 1999)]
    j = (u.merge(it, left_on="item_sk", right_on="i_item_sk")
         .merge(dd_dec, left_on="sold_date_sk", right_on="d_date_sk")
         .merge(T.customer, left_on="customer_sk",
                right_on="c_customer_sk"))
    my_customers = j[["c_customer_sk", "c_current_addr_sk"]] \
        .drop_duplicates()
    mseq = dd_dec.d_month_seq.iloc[0]
    dd_win = dd[dd.d_month_seq.between(mseq + 1, mseq + 3)]
    rev = (my_customers
           .merge(T.customer_address, left_on="c_current_addr_sk",
                  right_on="ca_address_sk")
           .merge(T.store, left_on=["ca_county", "ca_state"],
                  right_on=["s_county", "s_state"])
           .merge(T.store_sales, left_on="c_customer_sk",
                  right_on="ss_customer_sk")
           .merge(dd_win, left_on="ss_sold_date_sk",
                  right_on="d_date_sk"))
    g = (rev.groupby("c_customer_sk", as_index=False)
         .agg(revenue=("ss_ext_sales_price", _sum)))
    seg = (g.revenue / 50).round().astype(int)
    out = (pd.DataFrame({"segment": seg}).groupby("segment",
                                                  as_index=False)
           .size().rename(columns={"size": "num_customers"}))
    out["segment_base"] = out.segment * 50
    return out, meta(["segment", "num_customers", "segment_base"],
                     None, 100)


def q58(T):
    dd = T.date_dim
    wk = dd[dd.d_date.astype(str) == "2000-01-03"].d_week_seq.iloc[0]
    days = set(dd[dd.d_week_seq == wk].d_date)

    def rev(fact, item_sk, date_sk, price, name):
        j = _star(fact, (T.item, item_sk, "i_item_sk"),
                  (dd[dd.d_date.isin(days)], date_sk, "d_date_sk"))
        return (j.groupby("i_item_id", as_index=False)
                .agg(**{name: (price, _sum)}))

    s = rev(T.store_sales, "ss_item_sk", "ss_sold_date_sk",
            "ss_ext_sales_price", "ss_item_rev")
    c = rev(T.catalog_sales, "cs_item_sk", "cs_sold_date_sk",
            "cs_ext_sales_price", "cs_item_rev")
    w = rev(T.web_sales, "ws_item_sk", "ws_sold_date_sk",
            "ws_ext_sales_price", "ws_item_rev")
    m = s.merge(c, on="i_item_id").merge(w, on="i_item_id")
    m = m[m.ss_item_rev.between(0.9 * m.cs_item_rev, 1.1 * m.cs_item_rev)
          & m.ss_item_rev.between(0.9 * m.ws_item_rev, 1.1 * m.ws_item_rev)
          & m.cs_item_rev.between(0.9 * m.ss_item_rev, 1.1 * m.ss_item_rev)
          & m.cs_item_rev.between(0.9 * m.ws_item_rev, 1.1 * m.ws_item_rev)
          & m.ws_item_rev.between(0.9 * m.ss_item_rev, 1.1 * m.ss_item_rev)
          & m.ws_item_rev.between(0.9 * m.cs_item_rev,
                                  1.1 * m.cs_item_rev)]
    avg3 = (m.ss_item_rev + m.cs_item_rev + m.ws_item_rev) / 3
    out = pd.DataFrame({
        "item_id": m.i_item_id, "ss_item_rev": m.ss_item_rev,
        "ss_dev": m.ss_item_rev / avg3 * 100, "cs_item_rev": m.cs_item_rev,
        "cs_dev": m.cs_item_rev / avg3 * 100, "ws_item_rev": m.ws_item_rev,
        "ws_dev": m.ws_item_rev / avg3 * 100, "average": avg3})
    return out, meta(["item_id", "ss_item_rev"], None, 100,
                     ["ss_item_rev", "ss_dev", "cs_item_rev", "cs_dev",
                      "ws_item_rev", "ws_dev", "average"])


def q83(T):
    dd = T.date_dim
    wks = set(dd[dd.d_date.astype(str).isin(
        ["2000-06-30", "2000-09-27", "2000-11-17"])].d_week_seq)
    days = set(dd[dd.d_week_seq.isin(wks)].d_date)

    def qty(ret, item_sk, date_sk, col, name):
        j = _star(ret, (T.item, item_sk, "i_item_sk"),
                  (dd[dd.d_date.isin(days)], date_sk, "d_date_sk"))
        return (j.groupby("i_item_id", as_index=False)
                .agg(**{name: (col, _sum)}))

    s = qty(T.store_returns, "sr_item_sk", "sr_returned_date_sk",
            "sr_return_quantity", "sr_item_qty")
    c = qty(T.catalog_returns, "cr_item_sk", "cr_returned_date_sk",
            "cr_return_quantity", "cr_item_qty")
    w = qty(T.web_returns, "wr_item_sk", "wr_returned_date_sk",
            "wr_return_quantity", "wr_item_qty")
    m = s.merge(c, on="i_item_id").merge(w, on="i_item_id")
    tot = m.sr_item_qty + m.cr_item_qty + m.wr_item_qty
    out = pd.DataFrame({
        "item_id": m.i_item_id, "sr_item_qty": m.sr_item_qty,
        "sr_dev": m.sr_item_qty / tot / 3.0 * 100,
        "cr_item_qty": m.cr_item_qty,
        "cr_dev": m.cr_item_qty / tot / 3.0 * 100,
        "wr_item_qty": m.wr_item_qty,
        "wr_dev": m.wr_item_qty / tot / 3.0 * 100,
        "average": tot / 3.0})
    return out, meta(["item_id", "sr_item_qty"], None, 100,
                     ["sr_dev", "cr_dev", "wr_dev", "average"])


def q66(T):
    td = T.time_dim[T.time_dim.t_time.between(30838, 30838 + 28800)]
    sm = T.ship_mode[T.ship_mode.sm_carrier.isin(["DHL", "UPS"])]
    dd = T.date_dim[T.date_dim.d_year == 2000]

    def chan(fact, wh_sk, date_sk, time_sk, mode_sk, price, net, q):
        j = _star(fact, (T.warehouse, wh_sk, "w_warehouse_sk"),
                  (dd, date_sk, "d_date_sk"), (td, time_sk, "t_time_sk"),
                  (sm, mode_sk, "sm_ship_mode_sk"))
        j = j.assign(val=j[price] * j[q], net=j[net] * j[q])
        keys = ["w_warehouse_name", "w_warehouse_sq_ft", "w_city",
                "w_county", "w_state", "w_country"]
        spec = {}
        for m_ in range(1, 13):
            nm = ["jan", "feb", "mar", "apr", "may", "jun", "jul", "aug",
                  "sep", "oct", "nov", "dec"][m_ - 1]
            j[f"{nm}_sales"] = np.where(j.d_moy == m_, j.val, 0.0)
            spec[f"{nm}_sales"] = (f"{nm}_sales", "sum")
        j["jan_net"] = np.where(j.d_moy == 1, j.net, 0.0)
        j["dec_net"] = np.where(j.d_moy == 12, j.net, 0.0)
        spec["jan_net"] = ("jan_net", "sum")
        spec["dec_net"] = ("dec_net", "sum")
        g = j.groupby(keys, dropna=False, as_index=False).agg(**spec)
        g["year_"] = 2000
        g["ship_carriers"] = "DHL,UPS"
        return g

    w = chan(T.web_sales, "ws_warehouse_sk", "ws_sold_date_sk",
             "ws_sold_time_sk", "ws_ship_mode_sk", "ws_ext_sales_price",
             "ws_net_paid", "ws_quantity")
    c = chan(T.catalog_sales, "cs_warehouse_sk", "cs_sold_date_sk",
             "cs_sold_time_sk", "cs_ship_mode_sk", "cs_sales_price",
             "cs_net_paid_inc_tax", "cs_quantity")
    u = pd.concat([w, c], ignore_index=True)
    keys = ["w_warehouse_name", "w_warehouse_sq_ft", "w_city", "w_county",
            "w_state", "w_country", "ship_carriers", "year_"]
    months = ["jan", "feb", "mar", "apr", "may", "jun", "jul", "aug",
              "sep", "oct", "nov", "dec"]
    u["jan_per_sqft"] = u.jan_sales / u.w_warehouse_sq_ft
    u["dec_per_sqft"] = u.dec_sales / u.w_warehouse_sq_ft
    spec = {f"{m_}_sales": (f"{m_}_sales", "sum") for m_ in months}
    spec.update(jan_sales_per_sq_foot=("jan_per_sqft", "sum"),
                dec_sales_per_sq_foot=("dec_per_sqft", "sum"),
                jan_net=("jan_net", "sum"), dec_net=("dec_net", "sum"))
    out = u.groupby(keys, dropna=False, as_index=False).agg(**spec)
    return out, meta(["w_warehouse_name"], None, 100,
                     [f"{m_}_sales" for m_ in months]
                     + ["jan_sales_per_sq_foot", "dec_sales_per_sq_foot",
                        "jan_net", "dec_net"])


def q72(T):
    j = T.catalog_sales.merge(T.inventory, left_on="cs_item_sk",
                              right_on="inv_item_sk")
    j = j.merge(T.warehouse, left_on="inv_warehouse_sk",
                right_on="w_warehouse_sk")
    j = j.merge(T.item, left_on="cs_item_sk", right_on="i_item_sk")
    j = j.merge(T.customer_demographics[
        T.customer_demographics.cd_marital_status == "D"],
        left_on="cs_bill_cdemo_sk", right_on="cd_demo_sk")
    j = j.merge(T.household_demographics[
        T.household_demographics.hd_buy_potential == ">10000"],
        left_on="cs_bill_hdemo_sk", right_on="hd_demo_sk")
    d1 = T.date_dim.add_prefix("d1_")
    d2 = T.date_dim.add_prefix("d2_")
    d3 = T.date_dim.add_prefix("d3_")
    j = j.merge(d1[d1.d1_d_year == 2000], left_on="cs_sold_date_sk",
                right_on="d1_d_date_sk")
    j = j.merge(d2, left_on="inv_date_sk", right_on="d2_d_date_sk")
    j = j.merge(d3, left_on="cs_ship_date_sk", right_on="d3_d_date_sk")
    j = j[(j.d1_d_week_seq == j.d2_d_week_seq)
          & (j.inv_quantity_on_hand < j.cs_quantity)
          & (pd.to_datetime(j.d3_d_date)
             > pd.to_datetime(j.d1_d_date) + pd.Timedelta(days=5))]
    j = j.merge(T.promotion, left_on="cs_promo_sk", right_on="p_promo_sk",
                how="left")
    j = j.merge(T.catalog_returns[["cr_item_sk", "cr_order_number"]],
                left_on=["cs_item_sk", "cs_order_number"],
                right_on=["cr_item_sk", "cr_order_number"], how="left")
    g = (j.groupby(["i_item_desc", "w_warehouse_name", "d1_d_week_seq"],
                   as_index=False)
         .agg(no_promo=("p_promo_sk", lambda s: int(s.isna().sum())),
              promo=("p_promo_sk", lambda s: int(s.notna().sum())),
              total_cnt=("p_promo_sk", "size")))
    g = g.rename(columns={"d1_d_week_seq": "d_week_seq"})
    return g, meta(["total_cnt", "i_item_desc", "w_warehouse_name",
                    "d_week_seq"], [False, True, True, True], 100)


def q75(T):
    def chan(fact, ret, item_sk, date_sk, sale_keys, ret_keys, q, price,
             rq, ramt):
        j = fact.merge(T.item[T.item.i_category == "Books"],
                       left_on=item_sk, right_on="i_item_sk")
        j = j.merge(T.date_dim, left_on=date_sk, right_on="d_date_sk")
        j = j.merge(ret[ret_keys + [rq, ramt]], left_on=sale_keys,
                    right_on=ret_keys, how="left")
        out = pd.DataFrame({
            "d_year": j.d_year, "i_brand_id": j.i_brand_id,
            "i_class_id": j.i_class_id, "i_category_id": j.i_category_id,
            "i_manufact_id": j.i_manufact_id,
            "sales_cnt": j[q] - j[rq].fillna(0),
            "sales_amt": j[price] - j[ramt].fillna(0.0)})
        return out.drop_duplicates()

    u = pd.concat([
        chan(T.catalog_sales, T.catalog_returns, "cs_item_sk",
             "cs_sold_date_sk", ["cs_order_number", "cs_item_sk"],
             ["cr_order_number", "cr_item_sk"], "cs_quantity",
             "cs_ext_sales_price", "cr_return_quantity",
             "cr_return_amount"),
        chan(T.store_sales, T.store_returns, "ss_item_sk",
             "ss_sold_date_sk", ["ss_ticket_number", "ss_item_sk"],
             ["sr_ticket_number", "sr_item_sk"], "ss_quantity",
             "ss_ext_sales_price", "sr_return_quantity", "sr_return_amt"),
        chan(T.web_sales, T.web_returns, "ws_item_sk", "ws_sold_date_sk",
             ["ws_order_number", "ws_item_sk"],
             ["wr_order_number", "wr_item_sk"], "ws_quantity",
             "ws_ext_sales_price", "wr_return_quantity",
             "wr_return_amt")]).drop_duplicates()
    g = (u.groupby(["d_year", "i_brand_id", "i_class_id", "i_category_id",
                    "i_manufact_id"], dropna=False, as_index=False)
         .agg(sales_cnt=("sales_cnt", "sum"),
              sales_amt=("sales_amt", "sum")))
    cur = g[g.d_year == 2001]
    prev = g[g.d_year == 2000]
    m = cur.merge(prev, on=["i_brand_id", "i_class_id", "i_category_id",
                            "i_manufact_id"], suffixes=("_c", "_p"))
    m = m[m.sales_cnt_c / m.sales_cnt_p < 0.9]
    out = pd.DataFrame({
        "prev_year": m.d_year_p, "year_": m.d_year_c,
        "i_brand_id": m.i_brand_id, "i_class_id": m.i_class_id,
        "i_category_id": m.i_category_id, "i_manufact_id": m.i_manufact_id,
        "prev_yr_cnt": m.sales_cnt_p, "curr_yr_cnt": m.sales_cnt_c,
        "sales_cnt_diff": m.sales_cnt_c - m.sales_cnt_p,
        "sales_amt_diff": m.sales_amt_c - m.sales_amt_p})
    return out, meta(["sales_cnt_diff", "sales_amt_diff"], None, 100,
                     ["sales_amt_diff"])


def q78(T):
    def chan(fact, ret, sale_keys, ret_key_cols, date_sk, cust, item, q,
             wc, sp, prefix):
        j = fact.merge(ret[ret_key_cols], left_on=sale_keys,
                       right_on=ret_key_cols, how="left")
        j = j[j[ret_key_cols[0]].isna()]
        j = j.merge(T.date_dim, left_on=date_sk, right_on="d_date_sk")
        g = (j.groupby(["d_year", item, cust], dropna=False,
                       as_index=False)
             .agg(**{f"{prefix}_qty": (q, _sum),
                     f"{prefix}_wc": (wc, _sum),
                     f"{prefix}_sp": (sp, _sum)}))
        return g

    ss = chan(T.store_sales, T.store_returns,
              ["ss_ticket_number", "ss_item_sk"],
              ["sr_ticket_number", "sr_item_sk"], "ss_sold_date_sk",
              "ss_customer_sk", "ss_item_sk", "ss_quantity",
              "ss_wholesale_cost", "ss_sales_price", "ss")
    ws = chan(T.web_sales, T.web_returns,
              ["ws_order_number", "ws_item_sk"],
              ["wr_order_number", "wr_item_sk"], "ws_sold_date_sk",
              "ws_bill_customer_sk", "ws_item_sk", "ws_quantity",
              "ws_wholesale_cost", "ws_sales_price", "ws")
    cs = chan(T.catalog_sales, T.catalog_returns,
              ["cs_order_number", "cs_item_sk"],
              ["cr_order_number", "cr_item_sk"], "cs_sold_date_sk",
              "cs_bill_customer_sk", "cs_item_sk", "cs_quantity",
              "cs_wholesale_cost", "cs_sales_price", "cs")
    m = ss.merge(ws, left_on=["d_year", "ss_item_sk", "ss_customer_sk"],
                 right_on=["d_year", "ws_item_sk",
                           "ws_bill_customer_sk"], how="left")
    m = m.merge(cs, left_on=["d_year", "ss_item_sk", "ss_customer_sk"],
                right_on=["d_year", "cs_item_sk",
                          "cs_bill_customer_sk"], how="left")
    m = m[(m.ws_qty.fillna(0) > 0) | (m.cs_qty.fillna(0) > 0)]
    m = m[m.d_year == 2000]
    other_qty = m.ws_qty.fillna(0) + m.cs_qty.fillna(0)
    out = pd.DataFrame({
        "ss_sold_year": m.d_year, "ss_item_sk": m.ss_item_sk,
        "ss_customer_sk": m.ss_customer_sk,
        "ratio": (m.ss_qty / other_qty).round(2),
        "store_qty": m.ss_qty, "store_wholesale_cost": m.ss_wc,
        "store_sales_price": m.ss_sp, "other_chan_qty": other_qty,
        "other_chan_wholesale_cost": m.ws_wc.fillna(0) + m.cs_wc.fillna(0),
        "other_chan_sales_price": m.ws_sp.fillna(0) + m.cs_sp.fillna(0)})
    return out, meta(
        ["ss_sold_year", "ss_item_sk", "ss_customer_sk", "store_qty",
         "store_wholesale_cost", "store_sales_price"],
        [True, True, True, False, False, False], 100,
        ["ratio", "store_wholesale_cost", "store_sales_price",
         "other_chan_wholesale_cost", "other_chan_sales_price"])


def q85(T):
    j = T.web_sales.merge(
        T.web_returns, left_on=["ws_item_sk", "ws_order_number"],
        right_on=["wr_item_sk", "wr_order_number"])
    j = j.merge(T.web_page, left_on="ws_web_page_sk",
                right_on="wp_web_page_sk")
    j = j.merge(T.date_dim[T.date_dim.d_year == 2000],
                left_on="ws_sold_date_sk", right_on="d_date_sk")
    cd1 = T.customer_demographics.add_prefix("cd1_")
    cd2 = T.customer_demographics.add_prefix("cd2_")
    j = j.merge(cd1, left_on="wr_refunded_cdemo_sk",
                right_on="cd1_cd_demo_sk")
    j = j.merge(cd2, left_on="wr_returning_cdemo_sk",
                right_on="cd2_cd_demo_sk")
    j = j.merge(T.customer_address, left_on="wr_refunded_addr_sk",
                right_on="ca_address_sk")
    j = j.merge(T.reason, left_on="wr_reason_sk", right_on="r_reason_sk")
    same = ((j.cd1_cd_marital_status == j.cd2_cd_marital_status)
            & (j.cd1_cd_education_status == j.cd2_cd_education_status))
    demo = same & (
        ((j.cd1_cd_marital_status == "M")
         & (j.cd1_cd_education_status == "Advanced Degree")
         & j.ws_sales_price.between(100.0, 150.0))
        | ((j.cd1_cd_marital_status == "S")
           & (j.cd1_cd_education_status == "College")
           & j.ws_sales_price.between(50.0, 100.0))
        | ((j.cd1_cd_marital_status == "W")
           & (j.cd1_cd_education_status == "2 yr Degree")
           & j.ws_sales_price.between(150.0, 200.0)))
    addr = ((j.ca_country == "United States")
            & ((j.ca_state.isin(["CA", "TX", "NY"])
                & j.ws_net_profit.between(100, 200))
               | (j.ca_state.isin(["WA", "OR", "TN"])
                  & j.ws_net_profit.between(150, 300))
               | (j.ca_state.isin(["SD", "GA", "NM"])
                  & j.ws_net_profit.between(50, 250))))
    j = j[demo & addr]
    g = (j.groupby("r_reason_desc", as_index=False)
         .agg(avg_q=("ws_quantity", "mean"),
              avg_cash=("wr_refunded_cash", "mean"),
              avg_fee=("wr_fee", "mean")))
    g.insert(0, "reason_desc", g.r_reason_desc.astype(str).str[:20])
    g = g.drop(columns="r_reason_desc")
    return g, meta(["reason_desc", "avg_q", "avg_cash", "avg_fee"],
                   None, 100, ["avg_q", "avg_cash", "avg_fee"])


def q64(T):
    cr = T.catalog_returns
    csj = T.catalog_sales.merge(
        cr[["cr_item_sk", "cr_order_number", "cr_refunded_cash",
            "cr_reversed_charge", "cr_store_credit"]],
        left_on=["cs_item_sk", "cs_order_number"],
        right_on=["cr_item_sk", "cr_order_number"])
    csj = csj.assign(ref=csj.cr_refunded_cash + csj.cr_reversed_charge
                     + csj.cr_store_credit)
    cs_ui = (csj.groupby("cs_item_sk", as_index=False)
             .agg(sale=("cs_ext_list_price", _sum), refund=("ref", _sum)))
    cs_ui = cs_ui[cs_ui.sale > 2 * cs_ui.refund]
    j = T.store_sales.merge(T.store_returns[
        ["sr_item_sk", "sr_ticket_number"]],
        left_on=["ss_item_sk", "ss_ticket_number"],
        right_on=["sr_item_sk", "sr_ticket_number"])
    j = j[j.ss_item_sk.isin(set(cs_ui.cs_item_sk))]
    j = j.merge(T.store, left_on="ss_store_sk", right_on="s_store_sk")
    j = j.merge(T.customer, left_on="ss_customer_sk",
                right_on="c_customer_sk")
    d1 = T.date_dim.add_prefix("d1_")
    d2 = T.date_dim.add_prefix("d2_")
    d3 = T.date_dim.add_prefix("d3_")
    j = j.merge(d1, left_on="ss_sold_date_sk", right_on="d1_d_date_sk")
    j = j.merge(d2, left_on="c_first_sales_date_sk",
                right_on="d2_d_date_sk")
    j = j.merge(d3, left_on="c_first_shipto_date_sk",
                right_on="d3_d_date_sk")
    cd1 = T.customer_demographics.add_prefix("cd1_")
    cd2 = T.customer_demographics.add_prefix("cd2_")
    j = j.merge(cd1, left_on="ss_cdemo_sk", right_on="cd1_cd_demo_sk")
    j = j.merge(cd2, left_on="c_current_cdemo_sk",
                right_on="cd2_cd_demo_sk")
    j = j[j.cd1_cd_marital_status != j.cd2_cd_marital_status]
    hd1 = T.household_demographics.add_prefix("hd1_")
    hd2 = T.household_demographics.add_prefix("hd2_")
    ib1 = T.income_band.add_prefix("ib1_")
    ib2 = T.income_band.add_prefix("ib2_")
    j = j.merge(hd1, left_on="ss_hdemo_sk", right_on="hd1_hd_demo_sk")
    j = j.merge(hd2, left_on="c_current_hdemo_sk",
                right_on="hd2_hd_demo_sk")
    j = j.merge(ib1, left_on="hd1_hd_income_band_sk",
                right_on="ib1_ib_income_band_sk")
    j = j.merge(ib2, left_on="hd2_hd_income_band_sk",
                right_on="ib2_ib_income_band_sk")
    ad1 = T.customer_address.add_prefix("ad1_")
    ad2 = T.customer_address.add_prefix("ad2_")
    j = j.merge(ad1, left_on="ss_addr_sk", right_on="ad1_ca_address_sk")
    j = j.merge(ad2, left_on="c_current_addr_sk",
                right_on="ad2_ca_address_sk")
    j = j.merge(T.promotion, left_on="ss_promo_sk", right_on="p_promo_sk")
    j = j.merge(T.item, left_on="ss_item_sk", right_on="i_item_sk")
    j = j[j.i_color.isin(["powder", "orchid", "slate", "peach", "smoke",
                          "sienna"])
          & j.i_current_price.between(40, 70)]
    keys = ["i_product_name", "i_item_sk", "s_store_name", "s_zip",
            "ad1_ca_street_number", "ad1_ca_street_name", "ad1_ca_city",
            "ad1_ca_zip", "ad2_ca_street_number", "ad2_ca_street_name",
            "ad2_ca_city", "ad2_ca_zip", "d1_d_year", "d2_d_year",
            "d3_d_year"]
    cs = (j.groupby(keys, dropna=False, as_index=False)
          .agg(cnt=("ss_wholesale_cost", "size"),
               s1=("ss_wholesale_cost", _sum),
               s2=("ss_list_price", _sum), s3=("ss_coupon_amt", _sum)))
    y1 = cs[cs.d1_d_year == 1999]
    y2 = cs[cs.d1_d_year == 2000]
    m = y1.merge(y2, on=["i_item_sk", "s_store_name", "s_zip"],
                 suffixes=("_1", "_2"))
    m = m[m.cnt_2 <= m.cnt_1]
    out = pd.DataFrame({
        "product_name": m.i_product_name_1, "store_name": m.s_store_name,
        "store_zip": m.s_zip, "b_street_number": m.ad1_ca_street_number_1,
        "b_street_name": m.ad1_ca_street_name_1, "b_city": m.ad1_ca_city_1,
        "b_zip": m.ad1_ca_zip_1, "c_street_number":
        m.ad2_ca_street_number_1, "c_street_name": m.ad2_ca_street_name_1,
        "c_city": m.ad2_ca_city_1, "c_zip": m.ad2_ca_zip_1,
        "cs1syear": m.d1_d_year_1, "cs1cnt": m.cnt_1, "s11": m.s1_1,
        "s21": m.s2_1, "s31": m.s3_1, "s12": m.s1_2, "s22": m.s2_2,
        "s32": m.s3_2, "syear": m.d1_d_year_2, "cnt": m.cnt_2})
    return out, meta(["product_name", "store_name", "cnt", "s11", "s12"],
                     None, None, ["s11", "s21", "s31", "s12", "s22",
                                  "s32"])
