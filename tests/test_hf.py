"""HuggingFace Hub source against a mock hub server (reference:
``src/daft-io/src/huggingface.rs`` — resolve downloads + tree listing)."""

import http.server
import json
import threading
import urllib.parse

import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import daft_tpu
from daft_tpu.io.hf import HFSource, _parse_hf_url


class _MockHubHandler(http.server.BaseHTTPRequestHandler):
    files = {}  # (repo_id, rev, path) -> bytes

    def log_message(self, *a):
        pass

    def _send(self, status, body=b""):
        self.send_response(status)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        u = urllib.parse.urlparse(self.path)
        parts = u.path.strip("/").split("/")
        if parts[0] == "api":  # /api/datasets/org/repo/tree/rev[/sub]
            repo_id = "/".join(parts[2:4])
            rev = parts[5]
            sub = "/".join(parts[6:])
            entries = [
                {"type": "file", "path": p, "size": len(b)}
                for (r, rv, p), b in self.files.items()
                if r == repo_id and rv == rev and p.startswith(sub)]
            self._send(200, json.dumps(entries).encode())
            return
        # /datasets/org/repo/resolve/rev/path
        repo_id = "/".join(parts[1:3])
        rev = parts[4]
        path = "/".join(parts[5:])
        data = self.files.get((repo_id, rev, path))
        if data is None:
            self._send(404)
            return
        rng = self.headers.get("Range")
        if rng:
            s, e = rng.split("=")[1].split("-")
            self._send(206, data[int(s):int(e) + 1])
            return
        self._send(200, data)

    def do_HEAD(self):
        u = urllib.parse.urlparse(self.path)
        parts = u.path.strip("/").split("/")
        repo_id = "/".join(parts[1:3])
        data = self.files.get((repo_id, parts[4], "/".join(parts[5:])))
        if data is None:
            self._send(404)
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()


@pytest.fixture(scope="module")
def hub(tmp_path_factory):
    t = pa.table({"a": [1, 2, 3], "b": ["x", "y", "z"]})
    p = tmp_path_factory.mktemp("hf") / "part.parquet"
    pq.write_table(t, p)
    _MockHubHandler.files = {
        ("org/repo", "main", "data/part-0.parquet"): p.read_bytes(),
        ("org/repo", "main", "data/part-1.parquet"): p.read_bytes(),
        ("org/repo", "main", "README.md"): b"# hi",
        ("org/repo", "v2", "data/part-0.parquet"): p.read_bytes(),
    }
    server = http.server.ThreadingHTTPServer(("127.0.0.1", 0),
                                             _MockHubHandler)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{server.server_port}"
    server.shutdown()


@pytest.fixture
def hf(hub, monkeypatch):
    monkeypatch.setenv("HF_ENDPOINT", hub)
    from daft_tpu.io import object_io
    monkeypatch.setattr(object_io, "_default_client", None)
    return HFSource()


def test_url_parsing():
    assert _parse_hf_url("hf://datasets/org/repo/a/b.parquet") == \
        ("datasets", "org/repo", "main", "a/b.parquet")
    assert _parse_hf_url("hf://org/repo/a.parquet") == \
        ("datasets", "org/repo", "main", "a.parquet")
    assert _parse_hf_url("hf://datasets/org/repo@v2/a.parquet") == \
        ("datasets", "org/repo", "v2", "a.parquet")


def test_get_and_size(hf):
    data = hf.get("hf://datasets/org/repo/README.md")
    assert data == b"# hi"
    assert hf.get_size("hf://datasets/org/repo/README.md") == 4


def test_glob_and_ls(hf):
    hits = hf.glob("hf://datasets/org/repo/data/*.parquet")
    assert hits == ["hf://datasets/org/repo/data/part-0.parquet",
                    "hf://datasets/org/repo/data/part-1.parquet"]
    listed = dict(hf.ls("hf://datasets/org/repo/data"))
    assert len(listed) == 2


def test_revision_pinning(hf):
    hits = hf.glob("hf://datasets/org/repo@v2/data/*.parquet")
    assert hits == ["hf://datasets/org/repo@v2/data/part-0.parquet"]
    assert hf.get("hf://datasets/org/repo@v2/data/part-0.parquet")


def test_read_parquet_end_to_end(hf, monkeypatch):
    df = daft_tpu.read_parquet("hf://datasets/org/repo/data/*.parquet")
    out = df.to_pydict()
    assert out["a"] == [1, 2, 3, 1, 2, 3]
