"""Serving plane: scheduler fairness/admission/cancellation, plan+result
caches with fingerprint invalidation, concurrent-stats isolation, and the
Spark Connect operation-retention sweep."""

import http.server
import os
import threading
import time
import urllib.parse

import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import daft_tpu as dt
from daft_tpu import DataType, col, serving, udf
from daft_tpu.execution.cancellation import (CancelToken, QueryCancelled,
                                             cancel_scope, current_token)
from daft_tpu.execution.memory import MemoryManager
from daft_tpu.logical.fingerprint import fingerprint
from daft_tpu.serving import AdmissionRejected, QueryScheduler


def mkdf(d):
    return dt.from_pydict(d)


@pytest.fixture
def sched():
    s = QueryScheduler(concurrency=2, queue_timeout_s=20.0)
    yield s
    s.shutdown()


@pytest.fixture
def parquet_table(tmp_path):
    """A small parquet table on local disk (stat-able → cacheable)."""
    root = tmp_path / "t"
    mkdf({"k": list(range(2000)),
          "g": [i % 7 for i in range(2000)],
          "v": [float(i % 31) for i in range(2000)]}) \
        .write_parquet(str(root))
    return str(root / "*.parquet")


def _agg_query(glob):
    return dt.read_parquet(glob).groupby("g") \
        .agg(col("v").sum().alias("s")).sort("g")


# ------------------------------------------------------------- scheduler

def test_submit_returns_correct_results(sched, parquet_table):
    expected = _agg_query(parquet_table).to_pydict()
    hs = [sched.submit(_agg_query(parquet_table), session=f"s{i % 3}")
          for i in range(6)]
    for h in hs:
        assert h.result(60).to_recordbatch().to_pydict() == expected
        assert h.state == "done"
    assert sched.admission.outstanding == 0


def test_concurrent_stress_mixed_sessions(parquet_table):
    """≥8 mixed queries across ≥3 sessions concurrently: correct results,
    no admission leak, zero lock-order cycles when the sanitizer is armed.
    (The CI sanitizer job runs this whole suite under DAFT_TPU_SANITIZE=1.)
    """
    sched = QueryScheduler(concurrency=4)
    try:
        shapes = {
            "agg": lambda: _agg_query(parquet_table),
            "topk": lambda: dt.read_parquet(parquet_table)
            .sort("v", desc=True).limit(5).select("k", "v"),
            "lookup": lambda: dt.read_parquet(parquet_table)
            .where(col("k") == 123).select("k", "g"),
            "mem_join": lambda: mkdf({"a": [1, 2, 3], "b": [10, 20, 30]})
            .join(mkdf({"a": [2, 3, 4], "c": [5, 6, 7]}), on="a"),
        }
        expected = {name: fac().to_pydict() for name, fac in shapes.items()}
        names = ["agg", "topk", "lookup", "mem_join"] * 3  # 12 queries
        hs = [(n, sched.submit(shapes[n](), session=f"sess-{i % 3}"))
              for i, n in enumerate(names)]
        for n, h in hs:
            got = h.result(120).to_recordbatch().to_pydict()
            assert got == expected[n], f"{n} diverged under concurrency"
        assert sched.admission.outstanding == 0
        from daft_tpu.analysis import lock_sanitizer
        if lock_sanitizer.is_enabled():
            assert int(lock_sanitizer.counters_snapshot()
                       .get("graph_cycles", 0)) == 0
    finally:
        sched.shutdown()


def _gated_query(gate: threading.Event, started: threading.Event = None,
                 tag=None, order=None):
    """An in-memory query whose single morsel blocks on ``gate`` (and
    optionally records ``tag`` into ``order`` when it runs)."""

    @udf(return_dtype=DataType.int64())
    def block(s):
        if started is not None:
            started.set()
        if order is not None:
            order.append(tag)
        gate.wait(30)
        return s.to_pylist()

    return mkdf({"a": [1]}).select(block(col("a")))


def test_weighted_fair_share_ordering():
    """concurrency=1: queued sessions drain by stride — weight 2 gets two
    dispatches for every one of weight 1; FIFO within a session."""
    sched = QueryScheduler(concurrency=1, queue_timeout_s=60.0)
    try:
        gate0 = threading.Event()
        started = threading.Event()
        blocker = sched.submit(_gated_query(gate0, started), session="z")
        assert started.wait(20)  # worker is now pinned; queue builds below
        order = []
        done_gate = threading.Event()
        done_gate.set()  # queued queries don't block, only record
        hs = []
        for i in range(6):
            hs.append(sched.submit(
                _gated_query(done_gate, tag="A", order=order),
                session="A", weight=2.0))
        for i in range(3):
            hs.append(sched.submit(
                _gated_query(done_gate, tag="B", order=order),
                session="B", weight=1.0))
        gate0.set()
        blocker.result(60)
        for h in hs:
            h.result(60)
        # stride with weights 2:1 → in any prefix of 3k dispatches, A has
        # ~2k; check the first 6 recorded dispatches carry 4 A / 2 B
        first6 = order[:6]
        assert first6.count("A") == 4 and first6.count("B") == 2, order
        # FIFO within a session is positional: all hs per session resolve
        assert all(h.state == "done" for h in hs)
    finally:
        sched.shutdown()


def test_priority_dispatches_first():
    sched = QueryScheduler(concurrency=1, queue_timeout_s=60.0)
    try:
        gate0 = threading.Event()
        started = threading.Event()
        blocker = sched.submit(_gated_query(gate0, started), session="z")
        assert started.wait(20)
        order = []
        open_gate = threading.Event()
        open_gate.set()
        lo = sched.submit(_gated_query(open_gate, tag="lo", order=order),
                          session="s", priority=0)
        hi = sched.submit(_gated_query(open_gate, tag="hi", order=order),
                          session="s2", priority=5)
        gate0.set()
        blocker.result(60)
        lo.result(60)
        hi.result(60)
        assert order == ["hi", "lo"]
    finally:
        sched.shutdown()


def test_cancel_running_query_releases_admission():
    sched = QueryScheduler(concurrency=1, queue_timeout_s=60.0)
    try:
        gate = threading.Event()
        started = threading.Event()
        h = sched.submit(_gated_query(gate, started), session="s")
        assert started.wait(20)
        assert sched.admission.outstanding > 0  # admitted while running
        h.cancel("test cancel")
        gate.set()  # morsel finishes; executor sees the token next
        with pytest.raises(QueryCancelled):
            h.result(60)
        assert h.state == "cancelled"
        deadline = time.time() + 10
        while sched.admission.outstanding and time.time() < deadline:
            time.sleep(0.02)
        assert sched.admission.outstanding == 0  # admission released
    finally:
        sched.shutdown()


def test_cancel_queued_query_is_immediate():
    sched = QueryScheduler(concurrency=1, queue_timeout_s=60.0)
    try:
        gate = threading.Event()
        started = threading.Event()
        blocker = sched.submit(_gated_query(gate, started), session="s")
        assert started.wait(20)
        queued = sched.submit(mkdf({"a": [1]}).select(col("a")),
                              session="s")
        queued.cancel()
        with pytest.raises(QueryCancelled):
            queued.result(5)
        assert queued.state == "cancelled"
        gate.set()
        blocker.result(60)
        assert sched.admission.outstanding == 0
    finally:
        sched.shutdown()


def test_queue_timeout_rejects_without_admission():
    sched = QueryScheduler(concurrency=1, queue_timeout_s=60.0)
    try:
        gate = threading.Event()
        started = threading.Event()
        blocker = sched.submit(_gated_query(gate, started), session="s")
        assert started.wait(20)
        held = sched.admission.outstanding
        late = sched.submit(mkdf({"a": [1]}).select(col("a")),
                            session="s", timeout_s=0.3)
        with pytest.raises(AdmissionRejected) as ei:
            late.result(30)
        assert ei.value.kind == "queue_timeout"
        assert late.state == "rejected"
        assert sched.admission.outstanding == held  # never admitted
        gate.set()
        blocker.result(60)
        assert sched.admission.outstanding == 0
    finally:
        sched.shutdown()


def test_queue_full_rejection():
    sched = QueryScheduler(concurrency=1, queue_depth=1,
                           queue_timeout_s=60.0)
    try:
        gate = threading.Event()
        started = threading.Event()
        blocker = sched.submit(_gated_query(gate, started), session="s")
        assert started.wait(20)
        q1 = sched.submit(mkdf({"a": [1]}).select(col("a")), session="s")
        q2 = sched.submit(mkdf({"a": [1]}).select(col("a")), session="s")
        with pytest.raises(AdmissionRejected) as ei:
            q2.result(5)
        assert ei.value.kind == "queue_full"
        gate.set()
        blocker.result(60)
        q1.result(60)
    finally:
        sched.shutdown()


def test_memory_rejection_is_structured():
    sched = QueryScheduler(concurrency=1, memory_budget=1 << 20)
    try:
        h = sched.submit(mkdf({"a": [1]}).select(col("a")),
                         est_bytes=10 << 20)
        with pytest.raises(AdmissionRejected) as ei:
            h.result(30)
        assert ei.value.kind == "memory"
        assert ei.value.est_bytes == 10 << 20
        assert ei.value.budget == 1 << 20
        assert sched.admission.outstanding == 0
    finally:
        sched.shutdown()


def test_memory_manager_try_acquire_deadline_and_cancel():
    m = MemoryManager(budget=100)
    m.acquire(80)
    t0 = time.monotonic()
    assert m.try_acquire(50, deadline=time.monotonic() + 0.3) is False
    assert time.monotonic() - t0 < 5
    tok = CancelToken()
    tok.set()
    assert m.try_acquire(50, cancel=tok) is False
    m.release(80)
    assert m.try_acquire(50, deadline=time.monotonic() + 0.3) is True
    assert m.outstanding == 50
    m.release(50)
    assert m.outstanding == 0


def test_cancel_scope_threads_token_into_executor():
    tok = CancelToken()
    with cancel_scope(tok):
        assert current_token() is tok
        from daft_tpu.execution.pipeline import PushExecutor
        ex = PushExecutor()
        assert ex.cancel_token is tok
    assert current_token() is None


# ---------------------------------------------------------------- caches

def test_result_cache_hit_and_source_invalidation(tmp_path):
    root = tmp_path / "t"
    mkdf({"g": [1, 1, 2], "v": [1.0, 2.0, 3.0]}).write_parquet(str(root))
    glob = str(root / "*.parquet")
    sched = QueryScheduler(concurrency=1)
    try:
        h1 = sched.submit(_agg_query(glob))
        r1 = h1.result(60).to_recordbatch().to_pydict()
        assert h1.stats.serving["result_cache"] == "miss"
        h2 = sched.submit(_agg_query(glob))
        r2 = h2.result(60).to_recordbatch().to_pydict()
        assert h2.stats.serving["result_cache"] == "hit"
        assert r1 == r2
        # rewrite the source (content AND stat change) → both caches bust
        time.sleep(0.02)  # ensure a distinct mtime_ns even on coarse fs
        mkdf({"g": [1, 1, 2], "v": [10.0, 20.0, 30.0]}) \
            .write_parquet(str(root), write_mode="overwrite")
        h3 = sched.submit(_agg_query(glob))
        r3 = h3.result(60).to_recordbatch().to_pydict()
        assert h3.stats.serving["result_cache"] == "miss"
        assert r3["s"] == [30.0, 30.0]
    finally:
        sched.shutdown()


def test_plan_cache_hit_when_result_cache_disabled(parquet_table):
    sched = QueryScheduler(concurrency=1, result_cache_bytes=0)
    try:
        h1 = sched.submit(_agg_query(parquet_table))
        h1.result(60)
        assert h1.stats.serving["plan_cache"] == "miss"
        h2 = sched.submit(_agg_query(parquet_table))
        h2.result(60)
        assert h2.stats.serving["plan_cache"] == "hit"
        assert h2.stats.serving["result_cache"] == "bypass"
        snap = sched.counters_snapshot()
        assert snap["plan_cache_hits"] >= 1
    finally:
        sched.shutdown()


def test_config_change_busts_plan_cache(parquet_table):
    from daft_tpu.context import execution_config_ctx
    sched = QueryScheduler(concurrency=1, result_cache_bytes=0)
    try:
        sched.submit(_agg_query(parquet_table)).result(60)
        with execution_config_ctx(default_morsel_size=999):
            h = sched.submit(_agg_query(parquet_table))
            h.result(60)
            assert h.stats.serving["plan_cache"] == "miss"
    finally:
        sched.shutdown()


def test_fingerprint_literal_stripping_and_volatility(tmp_path,
                                                      parquet_table):
    from daft_tpu.context import get_context
    cfg = get_context().execution_config
    b1 = dt.read_parquet(parquet_table).where(col("v") > 5)._builder.plan
    b2 = dt.read_parquet(parquet_table).where(col("v") > 9)._builder.plan
    f1, f2 = fingerprint(b1, cfg), fingerprint(b2, cfg)
    assert f1 is not None and f2 is not None
    assert f1.structure == f2.structure       # literal-stripped shape
    assert f1.params != f2.params             # bound-parameter vector
    assert f1.key != f2.key
    # identical text → identical key
    b3 = dt.read_parquet(parquet_table).where(col("v") > 5)._builder.plan
    assert fingerprint(b3, cfg).key == f1.key
    # in-memory sources are uncacheable (pinning + id-reuse hazards)
    assert fingerprint(mkdf({"a": [1]}).select(col("a"))._builder.plan,
                       cfg) is None
    # UDF callables are uncacheable (repr address reuse)
    @udf(return_dtype=DataType.int64())
    def f(s):
        return s.to_pylist()
    assert fingerprint(
        dt.read_parquet(parquet_table).select(f(col("k")))._builder.plan,
        cfg) is None


def test_lru_byte_budget_evicts():
    from daft_tpu.serving.caches import _LRUCache
    c = _LRUCache(100)
    c.put(("a",), 1, 40)
    c.put(("b",), 2, 40)
    c.put(("c",), 3, 40)           # evicts ("a",)
    assert c.get(("a",)) is None
    assert c.get(("b",)) == 2
    assert c.stats()["evictions"] == 1
    c.put(("huge",), 4, 200)       # over budget → not stored
    assert c.get(("huge",)) is None


def test_serving_block_rendered_in_explain(parquet_table):
    sched = QueryScheduler(concurrency=1)
    try:
        h = sched.submit(_agg_query(parquet_table), session="render-s",
                         priority=2)
        h.result(60)
        text = h.stats.render()
        assert "serving (query scheduler):" in text
        assert "session=render-s" in text
        assert "priority=2" in text
        # a result-cache hit still renders a serving block
        h2 = sched.submit(_agg_query(parquet_table), session="render-s")
        h2.result(60)
        assert "result cache: hit" in h2.stats.render()
    finally:
        sched.shutdown()


# ------------------------------------------- concurrent stats isolation

class _Store(http.server.BaseHTTPRequestHandler):
    store = {}

    def log_message(self, *a):
        pass

    def _key(self):
        return urllib.parse.urlparse(self.path).path.lstrip("/")

    def do_HEAD(self):
        data = self.store.get(self._key())
        if data is None:
            self.send_response(404)
            self.end_headers()
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()

    def do_GET(self):
        data = self.store.get(self._key())
        if data is None:
            self.send_response(404)
            self.end_headers()
            return
        rng = self.headers.get("Range")
        if rng:
            a, b = rng.split("=")[1].split("-")
            start, end = int(a), min(int(b), len(data) - 1)
            chunk = data[start:end + 1]
            self.send_response(206)
        else:
            chunk = data
            self.send_response(200)
        self.send_header("Content-Length", str(len(chunk)))
        self.end_headers()
        self.wfile.write(chunk)


@pytest.fixture
def http_parquet():
    import io as _io
    buf = _io.BytesIO()
    pq.write_table(pa.table({
        "g": pa.array([i % 5 for i in range(4000)]),
        "v": pa.array([float(i) for i in range(4000)]),
    }), buf, row_group_size=500)
    _Store.store = {"ds/p.parquet": buf.getvalue()}
    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), _Store)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{srv.server_port}/ds/p.parquet"
    srv.shutdown()


def test_two_concurrent_queries_have_isolated_io_stats(http_parquet):
    """The r11 bugfix: per-query io/shuffle/recovery stats were diffed
    from process-wide counters, so two overlapping queries read each
    other's traffic. With context attribution, a pure in-memory query
    must show ZERO io no matter what scans run concurrently."""
    sched = QueryScheduler(concurrency=4)
    stop = threading.Event()
    scan_handles, mem_handles = [], []
    try:
        def scanner():
            while not stop.is_set() and len(scan_handles) < 6:
                h = sched.submit(
                    dt.read_parquet(http_parquet).groupby("g")
                    .agg(col("v").sum()), session="scan-sess")
                h.result(60)
                scan_handles.append(h)

        t = threading.Thread(target=scanner, daemon=True)
        t.start()
        for _ in range(6):
            h = sched.submit(
                mkdf({"x": [1, 2, 3, 4]}).agg(col("x").sum()),
                session="mem-sess")
            h.result(60)
            mem_handles.append(h)
        stop.set()
        t.join(timeout=90)
        assert scan_handles, "scanner never completed a query"
        # the scanning queries observed real io traffic…
        assert any(h.stats.io.get("gets", 0) > 0 for h in scan_handles)
        # …and the in-memory queries observed NONE of it
        for h in mem_handles:
            assert h.stats.io.get("gets", 0) == 0, h.stats.io
            assert h.stats.io.get("bytes_fetched", 0) == 0
    finally:
        stop.set()
        sched.shutdown()


# ------------------------------------------------- connect op retention

def test_operation_retention_ttl_and_byte_sweep(monkeypatch):
    grpc = pytest.importorskip("grpc")  # noqa: F841 — server needs it
    from daft_tpu.connect.server import SparkConnectServer, _Operation

    srv = SparkConnectServer()
    try:
        st = srv._session("sweep-sess")

        class _Resp:
            def __init__(self, n):
                self._n = n
                self.response_id = f"r{n}"

            def ByteSize(self):
                return self._n

        def finished_op(op_id, nbytes, age_s):
            op = _Operation(op_id, (), reattachable=True)
            op.record(_Resp(nbytes))
            op.finish()
            op.finished_at = time.monotonic() - age_s
            st.operations[op_id] = op
            return op

        # TTL sweep: an old finished op is dropped, a fresh one kept
        monkeypatch.setenv("DAFT_TPU_SERVE_OP_TTL", "100")
        finished_op("old", 10, age_s=1000)
        finished_op("fresh", 10, age_s=1)
        srv._session("sweep-sess")
        assert "old" not in st.operations
        assert "fresh" in st.operations

        # byte-budget sweep: newest kept first, the rest dropped
        st.operations.pop("fresh")  # would otherwise occupy the budget
        monkeypatch.setenv("DAFT_TPU_SERVE_OP_RETAIN_BYTES", "25")
        finished_op("b1", 20, age_s=30)
        finished_op("b2", 20, age_s=20)
        finished_op("b3", 20, age_s=10)
        srv._session("sweep-sess")
        kept = set(st.operations)
        assert "b3" in kept and "b1" not in kept and "b2" not in kept

        # a RUNNING operation is never swept, regardless of budget
        running = _Operation("running", (), reattachable=True)
        running.record(_Resp(1000))
        st.operations["running"] = running
        srv._session("sweep-sess")
        assert "running" in st.operations
    finally:
        srv.stop()


def test_operation_cancel_callbacks_fire():
    from daft_tpu.connect.server import _Operation
    op = _Operation("x", (), reattachable=False)
    fired = []
    op.bind_cancel(lambda: fired.append(1))
    op.request_cancel()
    assert fired == [1]
    # late binding on an already-cancelled op fires immediately
    op.bind_cancel(lambda: fired.append(2))
    assert fired == [1, 2]


def test_projection_compile_is_single_flight(monkeypatch):
    """N concurrent cold queries tracing the SAME projection must compile
    once: the losers wait on the winner's event instead of burning
    duplicate (multi-second on TPU) trace+lowering work."""
    from daft_tpu.device import runtime as drt
    from daft_tpu.schema import Field, Schema

    calls = []
    call_lock = threading.Lock()

    class _FakeCompiled:
        needs_cols = ()

    def slow_compile(exprs, schema):
        with call_lock:
            calls.append(1)
        time.sleep(0.2)
        return _FakeCompiled()

    monkeypatch.setattr(drt.compiler, "compile_projection", slow_compile)
    schema = Schema([Field("serve_sf_test", DataType.int64())])
    exprs = [(col("serve_sf_test") + 1).alias("out")]
    results = []

    def run():
        results.append(drt._get_compiled(exprs, schema))

    threads = [threading.Thread(target=run) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(20)
    assert len(calls) == 1, f"{len(calls)} duplicate compiles"
    assert len(results) == 8
    assert all(r is results[0] for r in results)  # one shared program


def test_live_view_shape(sched):
    view = sched.live_view()
    assert view["concurrency"] == 2
    assert "admitted_bytes" in view and "counters" in view
    assert isinstance(view["sessions"], dict)


# ----------------------------------------------- review-hardening fixes

def test_serve_memory_zero_disables_admission(monkeypatch):
    """DAFT_TPU_SERVE_MEMORY=0 must disable admission outright, not fall
    back to the engine memory limit inside MemoryManager."""
    monkeypatch.setenv("DAFT_TPU_SERVE_MEMORY", "0")
    monkeypatch.setenv("DAFT_TPU_MEMORY_LIMIT", "1GiB")
    s = QueryScheduler(concurrency=1)
    try:
        assert s.admission.budget is None
        assert s.admission.try_acquire(1 << 50)  # nothing gates
        assert s.admission.outstanding == 0
    finally:
        s.shutdown()


def test_estimate_runs_outside_scheduler_lock(sched, monkeypatch):
    """The cost-model footprint estimate can do real IO (remote footer
    reads); submit() must not hold the scheduler condition across it."""
    in_estimate = threading.Event()
    release = threading.Event()

    def slow_estimate(self, builder):
        in_estimate.set()
        assert release.wait(10), "estimator never released"
        return 1 << 20

    monkeypatch.setattr(QueryScheduler, "_estimate_bytes", slow_estimate)
    hs = []
    t = threading.Thread(
        target=lambda: hs.append(sched.submit(mkdf({"a": [1]}))),
        daemon=True)
    t.start()
    assert in_estimate.wait(10)
    # while the submitter sits in the estimator, the scheduler lock must
    # be free for workers / the sweep / the dashboard
    acquired = sched._cond.acquire(timeout=2.0)
    try:
        assert acquired, "submit held the scheduler lock across the " \
            "footprint estimate"
    finally:
        if acquired:
            sched._cond.release()
    release.set()
    t.join(20)
    assert hs and hs[0].result(30).to_recordbatch().to_pydict() == \
        {"a": [1]}


def test_idle_sessions_are_swept(monkeypatch):
    """Session queues are client-keyed (Connect mints one UUID per
    session); drained sessions must not accumulate forever."""
    from daft_tpu.serving import scheduler as sched_mod
    s = QueryScheduler(concurrency=2)
    try:
        hs = [s.submit(mkdf({"a": [i]}), session=f"uuid-{i}")
              for i in range(6)]
        for h in hs:
            h.result(60)
        monkeypatch.setattr(sched_mod, "_SESSION_IDLE_TTL_S", 0.0)
        with s._cond:
            s._sweep_expired_locked()   # marks empties idle
        time.sleep(0.01)
        with s._cond:
            s._sweep_expired_locked()   # TTL elapsed → dropped
            assert s._sessions == {}
        # a returning session is simply re-created
        h = s.submit(mkdf({"a": [9]}), session="uuid-0")
        assert h.result(60).to_recordbatch().to_pydict() == {"a": [9]}
    finally:
        s.shutdown()


def test_unstable_literal_is_uncacheable():
    """Literals key the result cache, so only faithful-repr types may
    fingerprint; a truncated/recycled repr (numpy-style) must bypass."""
    import datetime
    import decimal

    from daft_tpu.logical.fingerprint import _Uncacheable, _canon_lit

    class Truncates:  # reprs like a numpy array: plausible, lossy
        def __repr__(self):
            return "[0, 1, ..., 1999]"

    assert _canon_lit(7) == "7"
    assert _canon_lit([1, "x", None]) == "[1,'x',None]"
    assert _canon_lit({"b": 2, "a": 1}) == "{'a':1,'b':2}"
    assert "2026" in _canon_lit(datetime.date(2026, 8, 3))
    assert "3.14" in _canon_lit(decimal.Decimal("3.14"))
    for bad in (Truncates(), [1, Truncates()], {"k": Truncates()},
                object(), lambda: 1):
        with pytest.raises(_Uncacheable):
            _canon_lit(bad)


def test_attributed_device_kernels_isolated():
    """Two attributed contexts must each see only their own dispatches,
    not a diff of the shared ledger spanning both."""
    from daft_tpu import observability as obs
    from daft_tpu.device import costmodel

    c1, c2 = obs.RuntimeStatsContext(), obs.RuntimeStatsContext()
    with obs.attributed(c1):
        costmodel.ledger_record("serve_test_argsort", rows=10,
                                nbytes=1e6, seconds=0.01)
    with obs.attributed(c2):
        costmodel.ledger_record("serve_test_join", rows=5,
                                nbytes=2e6, flops=1e6, seconds=0.02)
    c1.finish()
    c2.finish()
    assert set(c1.device_kernels) == {"serve_test_argsort"}
    assert set(c2.device_kernels) == {"serve_test_join"}
    assert c1.device_kernels["serve_test_argsort"]["rows"] == 10
    assert c2.device_kernels["serve_test_join"]["dispatches"] == 1
    assert "mfu_pct" in c2.device_kernels["serve_test_join"]


def test_cancel_unwinds_noncacheable_runner_drain(monkeypatch):
    """Distributed/AQE runners bypass the caches and don't thread the
    CancelToken into their workers; the scheduler's drain loop must
    check it per partition so INTERRUPT releases admission mid-query."""
    import daft_tpu.context as ctx_mod
    from daft_tpu.micropartition import MicroPartition

    first_part = threading.Event()
    proceed = threading.Event()

    class _FakeRunner:  # not a NativeRunner → non-cacheable path
        def run_iter(self, builder):
            yield MicroPartition.from_pydict({"a": [1]})
            first_part.set()
            proceed.wait(20)
            yield MicroPartition.from_pydict({"a": [2]})

    monkeypatch.setattr(ctx_mod.get_context(), "get_or_create_runner",
                        lambda: _FakeRunner())
    s = QueryScheduler(concurrency=1, memory_budget=1 << 30)
    try:
        h = s.submit(mkdf({"a": [0]}), est_bytes=1 << 20)
        assert first_part.wait(20)
        h.cancel()
        proceed.set()
        with pytest.raises(QueryCancelled):
            h.result(20)
        assert h.state == "cancelled"
        deadline = time.monotonic() + 10
        while s.admission.outstanding and time.monotonic() < deadline:
            time.sleep(0.01)
        assert s.admission.outstanding == 0
    finally:
        s.shutdown()


# ----------------------- r14 lifecycle regressions (daft-lint flow pass)

def test_admission_released_when_prerun_bookkeeping_raises(
        parquet_table, monkeypatch):
    """r14 regression (found by daft-lint memory-admission-leak): an
    exception between a successful try_acquire and the run-worker's
    try-block — here the handle's running transition — used to leak the
    admitted bytes AND the worker's running slot for the process
    lifetime (the worker thread died, so the handle never completed)."""
    from daft_tpu.serving import scheduler as sched_mod
    sched = QueryScheduler(concurrency=1, memory_budget=1 << 30,
                           queue_timeout_s=30.0)
    try:
        orig = sched_mod.QueryHandle._mark_running

        def boom(self):
            raise RuntimeError("bookkeeping exploded")

        monkeypatch.setattr(sched_mod.QueryHandle, "_mark_running", boom)
        h = sched.submit(_agg_query(parquet_table))
        with pytest.raises(RuntimeError, match="bookkeeping exploded"):
            h.result(30)
        assert h.state == "failed"
        assert sched.admission.outstanding == 0
        # the worker slot survived: a healthy query still runs on it
        monkeypatch.setattr(sched_mod.QueryHandle, "_mark_running", orig)
        h2 = sched.submit(_agg_query(parquet_table))
        assert h2.result(30).to_recordbatch().to_pydict() \
            == _agg_query(parquet_table).to_pydict()
        assert sched.admission.outstanding == 0
    finally:
        sched.shutdown()


def test_breaker_drain_polls_cancellation():
    """r14 regression (daft-lint uncancellable-loop): a pipeline
    breaker's consume loop (sort sampling, bucket stores) drains its
    whole child before yielding — without the in-loop poll, INTERRUPT
    ran the drain to completion while holding admission."""
    from daft_tpu.execution.executor import LocalExecutor
    from daft_tpu.micropartition import MicroPartition

    tok = CancelToken()
    with cancel_scope(tok):
        ex = LocalExecutor()  # captures the scope's token
    mp = MicroPartition.from_pydict({"x": [1.0, 2.0, 3.0]})
    seen = {"n": 0}

    def stream():
        for _ in range(100):
            seen["n"] += 1
            if seen["n"] == 3:
                tok.set("client interrupt")
            yield mp

    with pytest.raises(QueryCancelled):
        ex._consume_sampling(stream(), [col("x")])
    assert seen["n"] <= 4, "drain kept running after the token fired"

    # the bucket-store drain polls too
    tok2 = CancelToken()
    with cancel_scope(tok2):
        ex2 = LocalExecutor()
    seen["n"] = 0

    def stream2():
        for _ in range(100):
            seen["n"] += 1
            if seen["n"] == 3:
                tok2.set("client interrupt")
            yield mp

    with pytest.raises(QueryCancelled):
        ex2._key_bucket_store(stream2(), [col("x")], 4)
    assert seen["n"] <= 4
