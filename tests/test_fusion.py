"""Round 21 whole-query device compilation (FusedRegion) tests: planner
pattern matching, three-way bit parity (fused region vs per-operator
device vs host), overflow ladder re-dispatch (chain width + join_agg's
dual W/out_cap ladder), cancellation admission hygiene, the
fusion-region contract, AOT warm-up over the region library, and the
``region`` ledger family."""

import numpy as np
import pytest

import daft_tpu as daft
from daft_tpu import col
from daft_tpu.device import costmodel as cm
from daft_tpu.device import fragment
from daft_tpu.physical import fusion as pfusion
from daft_tpu.physical import plan as pp
from daft_tpu.physical.translate import translate


@pytest.fixture(autouse=True)
def _fused(monkeypatch):
    monkeypatch.setenv("DAFT_TPU_DEVICE", "1")
    monkeypatch.setenv("DAFT_TPU_DEVICE_FORCE", "1")
    monkeypatch.setenv("DAFT_TPU_FUSION", "1")
    yield


def _data(n=4000, seed=7, ndv=50):
    rng = np.random.default_rng(seed)
    return {
        "a": rng.integers(0, 100, n).astype(np.int64),
        "b": rng.normal(size=n),
        "k": rng.integers(0, ndv, n).astype(np.int64),
        "s": rng.choice(["x", "y", "z"], n).tolist(),
    }


def _build_df(rng, nkeys=40):
    return daft.from_pydict({
        "k2": np.arange(0, nkeys, dtype=np.int64),
        "w": rng.normal(size=nkeys),
        "g": (np.arange(nkeys, dtype=np.int64) % 5),
    })


def _regions(plan):
    found = []
    seen = set()

    def walk(n):
        if id(n) in seen:
            return
        seen.add(id(n))
        if isinstance(n, pp.FusedRegion):
            found.append(n)
        for c in n.children:
            walk(c)

    walk(plan)
    return found


def _chain_query(df):
    return df.where(col("a") > 30).select(
        (col("b") * 2.0).alias("b2"), col("a"))


def _topk_query(df):
    return (df.where(col("a") > 10).select(col("a"), col("b"))
            .sort(col("b"), desc=True).limit(9))


def _join_agg_query(probe, build):
    j = probe.where(col("a") > 20).join(
        build, left_on=col("k"), right_on=col("k2"), how="inner")
    return j.groupby(col("g")).agg(
        (col("b") * col("w")).sum().alias("rev"),
        col("b").count().alias("n"))


# ------------------------------------------------------------- planner


def test_planner_fuses_filter_project_chain():
    df = _chain_query(daft.from_pydict(_data()))
    regions = _regions(translate(df._builder.optimize().plan))
    assert [r.shape for r in regions] == ["chain"]
    assert len(regions[0].fused_ops) >= 2


def test_planner_fuses_topk_tail():
    df = _topk_query(daft.from_pydict(_data()))
    regions = _regions(translate(df._builder.optimize().plan))
    assert "topk" in [r.shape for r in regions]
    r = next(r for r in regions if r.shape == "topk")
    assert r.limit == 9


def test_planner_fuses_join_agg_spine():
    rng = np.random.default_rng(1)
    q = _join_agg_query(daft.from_pydict(_data()), _build_df(rng))
    regions = _regions(translate(q._builder.optimize().plan))
    assert "join_agg" in [r.shape for r in regions]
    r = next(r for r in regions if r.shape == "join_agg")
    assert r.mode == "partial" and r.build is not None


def test_fusion_off_is_identity(monkeypatch):
    monkeypatch.setenv("DAFT_TPU_FUSION", "0")
    df = _chain_query(daft.from_pydict(_data()))
    assert _regions(translate(df._builder.optimize().plan)) == []


def test_planner_declines_string_group_keys():
    df = daft.from_pydict(_data())
    rng = np.random.default_rng(1)
    q = df.join(_build_df(rng), left_on=col("k"), right_on=col("k2"),
                how="inner").groupby(col("s")).agg(
        col("b").sum().alias("sb"))
    regions = _regions(translate(q._builder.optimize().plan))
    assert "join_agg" not in [r.shape for r in regions]


def test_max_region_ops_cap(monkeypatch):
    monkeypatch.setenv("DAFT_TPU_FUSION_MAX_OPS", "2")
    df = daft.from_pydict(_data())
    q = (df.where(col("a") > 5).where(col("b") > -10.0)
         .select((col("b") + 1).alias("b1"), col("a"))
         .select((col("b1") * 2).alias("b2"), col("a")))
    regions = _regions(translate(q._builder.optimize().plan))
    for r in regions:
        # the cap bounds absorbed chain OPERATORS; the trailing "scan"
        # marker names the source, it is not an absorbed operator
        assert len([o for o in r.fused_ops if o != "scan"]) <= 2


# ------------------------------------------------- three-way bit parity


def _three_way(make_query, monkeypatch):
    """Run the query fused, per-operator device, and pure host."""
    outs = {}
    for name, env in (
            ("fused", {"DAFT_TPU_FUSION": "1"}),
            ("device", {"DAFT_TPU_FUSION": "0"}),
            ("host", {"DAFT_TPU_FUSION": "0", "DAFT_TPU_DEVICE": "0",
                      "DAFT_TPU_DEVICE_FORCE": "0"})):
        for k, v in env.items():
            monkeypatch.setenv(k, v)
        outs[name] = make_query().to_pydict()
        monkeypatch.setenv("DAFT_TPU_DEVICE", "1")
        monkeypatch.setenv("DAFT_TPU_DEVICE_FORCE", "1")
    return outs


def _assert_same(a, b, sort_cols=None):
    assert set(a.keys()) == set(b.keys())
    if sort_cols:
        ka = np.lexsort([np.asarray(a[c]) for c in sort_cols[::-1]])
        kb = np.lexsort([np.asarray(b[c]) for c in sort_cols[::-1]])
    for k in a:
        va, vb = list(a[k]), list(b[k])
        if sort_cols:
            va = [va[i] for i in ka]
            vb = [vb[i] for i in kb]
        if va and isinstance(va[0], float):
            np.testing.assert_allclose(np.asarray(va), np.asarray(vb),
                                       rtol=1e-9, atol=1e-9)
        else:
            assert va == vb, k


def test_chain_parity_three_ways(monkeypatch):
    d = _data(seed=11)
    outs = _three_way(
        lambda: _chain_query(daft.from_pydict(d)), monkeypatch)
    _assert_same(outs["fused"], outs["host"])
    _assert_same(outs["device"], outs["host"])


def test_topk_parity_three_ways(monkeypatch):
    d = _data(seed=12)
    outs = _three_way(
        lambda: _topk_query(daft.from_pydict(d)), monkeypatch)
    _assert_same(outs["fused"], outs["host"])
    _assert_same(outs["device"], outs["host"])


def test_join_agg_parity_three_ways(monkeypatch):
    d = _data(seed=13)
    rng = np.random.default_rng(13)
    b = _build_df(rng)
    outs = _three_way(
        lambda: _join_agg_query(daft.from_pydict(d), b), monkeypatch)
    _assert_same(outs["fused"], outs["host"], sort_cols=["g"])
    _assert_same(outs["device"], outs["host"], sort_cols=["g"])


def test_chain_parity_with_strings_and_nulls(monkeypatch):
    n = 3000
    rng = np.random.default_rng(5)
    a = rng.integers(0, 100, n).astype(np.int64)
    b = [None if i % 17 == 0 else float(x)
         for i, x in enumerate(rng.normal(size=n))]
    s = [None if i % 23 == 0 else v
         for i, v in enumerate(rng.choice(["p", "q"], n).tolist())]
    d = {"a": a, "b": b, "s": s}
    outs = _three_way(
        lambda: daft.from_pydict(d).where(col("a") > 40).select(
            (col("b") + 0.5).alias("b1"), col("s"), col("a")),
        monkeypatch)
    _assert_same(outs["fused"], outs["host"])


# ------------------------------------------------------ overflow ladders


def test_chain_width_ladder_overflow(monkeypatch):
    """A ~95%-selective predicate overflows the quarter-capacity first
    rung; the re-dispatch must still return every survivor."""
    d = _data(n=20000, seed=3)
    df = daft.from_pydict(d)
    got = df.where(col("a") >= 5).select(
        (col("b") + 1.0).alias("b1"), col("a")).to_pydict()
    m = d["a"] >= 5
    np.testing.assert_allclose(np.asarray(got["b1"]), d["b"][m] + 1.0)
    assert np.array_equal(np.asarray(got["a"]), d["a"][m])


def test_join_agg_pair_width_ladder(monkeypatch):
    """Build-side key duplication fans each probe row out 6x: the true
    pair total overflows W=probe-capacity and the dual ladder regrows."""
    d = _data(n=20000, seed=4, ndv=8)
    rng = np.random.default_rng(4)
    dup = 6
    bk = np.repeat(np.arange(0, 8, dtype=np.int64), dup)
    b = daft.from_pydict({"k2": bk, "w": rng.normal(size=len(bk)),
                          "g": (np.arange(len(bk), dtype=np.int64) % 4)})
    got = _join_agg_query(daft.from_pydict(d), b).to_pydict()

    import pandas as pd
    pdf = pd.DataFrame({k: v for k, v in d.items() if k != "s"})
    bdf = pd.DataFrame({"k2": bk, "w": b.to_pydict()["w"],
                        "g": np.arange(len(bk)) % 4})
    ref = pdf[pdf.a > 20].merge(bdf, left_on="k", right_on="k2")
    ref["rev"] = ref.b * ref.w
    rg = (ref.groupby("g").agg(rev=("rev", "sum"), n=("b", "count"))
          .reset_index().sort_values("g").reset_index(drop=True))
    gdf = (pd.DataFrame({k: list(v) for k, v in got.items()})
           .sort_values("g").reset_index(drop=True))
    assert np.array_equal(gdf["g"].values, rg["g"].values)
    np.testing.assert_allclose(gdf["rev"].values, rg["rev"].values)
    assert np.array_equal(gdf["n"].values, rg["n"].values)


def test_join_agg_group_bucket_ladder(monkeypatch):
    """Near-unique group keys overflow the _OUT_CAP0 group bucket; the
    out_cap rung of the dual ladder regrows and every group survives."""
    n = 6000
    rng = np.random.default_rng(9)
    d = {"a": np.full(n, 50, dtype=np.int64),
         "b": rng.normal(size=n),
         "k": np.arange(n, dtype=np.int64) % 2000}
    b = daft.from_pydict({
        "k2": np.arange(2000, dtype=np.int64),
        "w": np.ones(2000),
        "g": np.arange(2000, dtype=np.int64)})  # one group per key
    got = _join_agg_query(daft.from_pydict(d), b).to_pydict()
    assert len(got["g"]) == 2000
    assert sum(got["n"]) == n


# ------------------------------------------- cancellation / admission


def test_cancellation_mid_region_releases_admission(monkeypatch):
    """Closing the output stream mid-query must release every in-flight
    region slot's admission (same hygiene as the r17 fragment path)."""
    monkeypatch.setenv("DAFT_TPU_DEVICE_INFLIGHT", "2")
    from daft_tpu.execution.executor import LocalExecutor
    d = _data(n=30000, seed=6)
    df = _chain_query(daft.from_pydict(d))
    ex = LocalExecutor()
    gen = ex.run(translate(df._builder.optimize().plan))
    next(gen)
    gen.close()
    assert ex.mem.outstanding == 0


# --------------------------------------------------- contract + warmup


def test_fusion_region_contract_clean():
    from daft_tpu.analysis import rule_jit
    assert rule_jit.check_fusion_region_contracts() == []


def test_warmup_regions_compiles_library():
    """Warm-start satellite: after one fused run, the region library
    AOT-compiles over a size-class grid with zero errors."""
    from daft_tpu.device import warmup
    d = _data(seed=21)
    _chain_query(daft.from_pydict(d)).to_pydict()
    rng = np.random.default_rng(21)
    _join_agg_query(daft.from_pydict(d), _build_df(rng)).to_pydict()
    progs = fragment.fused_region_programs()
    assert progs
    stats = warmup.warmup_regions([1 << 12, 1 << 13], progs)
    assert stats["errors"] == 0
    assert stats["programs"] > 0


def test_region_ledger_family(monkeypatch):
    """Fused dispatches land in the ``region`` ledger family with the
    fused-op count, round-trips eliminated, and a fusion_x ratio."""
    cm.ledger_reset()
    d = _data(seed=30)
    _chain_query(daft.from_pydict(d)).to_pydict()
    snap = cm.ledger_snapshot()
    assert "region" in snap
    fam = snap["region"]
    assert fam["dispatches"] >= 1
    assert fam.get("fused_ops", 0) >= 2
    assert fam.get("round_trips_saved", 0) >= 1
    assert fam.get("fusion_x", 0) > 0
