"""TPC-H correctness: all 22 queries run; a subset is cross-checked against an
independent pandas implementation on the same generated data
(reference model: ``tests/integration/test_tpch.py`` vs dbgen answers).
"""

import os

# the real-device opt-in pass runs XLA on the TPU, where f64 downcasts to
# f32 by design: numeric comparisons against f64 pandas need f32-scale
# tolerance there (this is exactly the numerics delta the pass exists to
# surface — and bound)
_REL = 1e-4 if os.environ.get("DAFT_TPU_REAL_DEVICE") == "1" else 1e-9

import datetime
import sys

import numpy as np
import pandas as pd
import pytest

sys.path.insert(0, "/root/repo")

import daft_tpu as dt
from benchmarking.tpch import queries as Q
from benchmarking.tpch.datagen import generate_tpch


@pytest.fixture(scope="module")
def tpch(tmp_path_factory):
    root = tmp_path_factory.mktemp("tpch")
    generate_tpch(str(root), scale_factor=0.003, num_parts=3)
    dfs = {}

    def get_df(name: str):
        if name not in dfs:
            dfs[name] = dt.read_parquet(f"{root}/{name}/*.parquet")
        return dfs[name]
    return get_df


@pytest.fixture(scope="module")
def pdf(tpch):
    return {name: tpch(name).to_pandas()
            for name in ["lineitem", "orders", "customer", "supplier",
                         "part", "partsupp", "nation", "region"]}


@pytest.mark.parametrize("qnum", list(range(1, 23)))
def test_queries_run(tpch, qnum):
    out = Q.ALL[qnum](tpch).to_pydict()
    assert isinstance(out, dict)


def test_q1_vs_pandas(tpch, pdf):
    got = Q.q1(tpch).to_pandas()
    li = pdf["lineitem"]
    f = li[li.l_shipdate <= pd.Timestamp(1998, 9, 2).date()].copy()
    f["disc_price"] = f.l_extendedprice * (1 - f.l_discount)
    f["charge"] = f.disc_price * (1 + f.l_tax)
    exp = (f.groupby(["l_returnflag", "l_linestatus"], as_index=False)
           .agg(sum_qty=("l_quantity", "sum"),
                sum_base_price=("l_extendedprice", "sum"),
                sum_disc_price=("disc_price", "sum"),
                sum_charge=("charge", "sum"),
                avg_qty=("l_quantity", "mean"),
                avg_price=("l_extendedprice", "mean"),
                avg_disc=("l_discount", "mean"),
                count_order=("l_quantity", "count"))
           .sort_values(["l_returnflag", "l_linestatus"])
           .reset_index(drop=True))
    assert list(got.l_returnflag) == list(exp.l_returnflag)
    for c in ["sum_qty", "sum_base_price", "sum_disc_price", "sum_charge",
              "avg_qty", "avg_price", "avg_disc"]:
        np.testing.assert_allclose(got[c], exp[c], rtol=_REL)
    assert list(got.count_order) == list(exp.count_order)


def test_q3_vs_pandas(tpch, pdf):
    got = Q.q3(tpch).to_pandas()
    c = pdf["customer"]
    o = pdf["orders"]
    l = pdf["lineitem"]
    c = c[c.c_mktsegment == "BUILDING"]
    cutoff = datetime.date(1995, 3, 15)
    o = o[o.o_orderdate < cutoff]
    l = l[l.l_shipdate > cutoff].copy()
    j = c.merge(o, left_on="c_custkey", right_on="o_custkey") \
         .merge(l, left_on="o_orderkey", right_on="l_orderkey")
    j["volume"] = j.l_extendedprice * (1 - j.l_discount)
    exp = (j.groupby(["o_orderkey", "o_orderdate", "o_shippriority"],
                     as_index=False)
           .agg(revenue=("volume", "sum"))
           .sort_values(["revenue", "o_orderdate"], ascending=[False, True])
           .head(10))
    np.testing.assert_allclose(got.revenue, exp.revenue, rtol=_REL)
    assert list(got.o_orderkey) == list(exp.o_orderkey)


def test_q5_vs_pandas(tpch, pdf):
    got = Q.q5(tpch).to_pandas()
    r = pdf["region"]; n = pdf["nation"]; s = pdf["supplier"]
    li = pdf["lineitem"]; o = pdf["orders"]; c = pdf["customer"]
    j = (r[r.r_name == "ASIA"]
         .merge(n, left_on="r_regionkey", right_on="n_regionkey")
         .merge(s, left_on="n_nationkey", right_on="s_nationkey")
         .merge(li, left_on="s_suppkey", right_on="l_suppkey")
         .merge(o[(o.o_orderdate >= datetime.date(1994, 1, 1))
                  & (o.o_orderdate < datetime.date(1995, 1, 1))],
                left_on="l_orderkey", right_on="o_orderkey")
         .merge(c, left_on=["o_custkey", "s_nationkey"],
                right_on=["c_custkey", "c_nationkey"]))
    j["volume"] = j.l_extendedprice * (1 - j.l_discount)
    exp = (j.groupby("n_name", as_index=False).agg(revenue=("volume", "sum"))
           .sort_values("revenue", ascending=False))
    assert list(got.n_name) == list(exp.n_name)
    np.testing.assert_allclose(got.revenue, exp.revenue, rtol=_REL)


def test_q6_vs_pandas(tpch, pdf):
    got = Q.q6(tpch).to_pydict()["revenue"][0]
    li = pdf["lineitem"]
    f = li[(li.l_shipdate >= datetime.date(1994, 1, 1))
           & (li.l_shipdate < datetime.date(1995, 1, 1))
           & (li.l_discount >= 0.05) & (li.l_discount <= 0.07)
           & (li.l_quantity < 24)]
    exp = (f.l_extendedprice * f.l_discount).sum()
    assert got == pytest.approx(exp, rel=_REL)


def test_q10_vs_pandas(tpch, pdf):
    got = Q.q10(tpch).to_pandas()
    c = pdf["customer"]; o = pdf["orders"]; li = pdf["lineitem"]; n = pdf["nation"]
    j = (c.merge(o[(o.o_orderdate >= datetime.date(1993, 10, 1))
                   & (o.o_orderdate < datetime.date(1994, 1, 1))],
                 left_on="c_custkey", right_on="o_custkey")
         .merge(li[li.l_returnflag == "R"], left_on="o_orderkey",
                right_on="l_orderkey")
         .merge(n, left_on="c_nationkey", right_on="n_nationkey"))
    j["volume"] = j.l_extendedprice * (1 - j.l_discount)
    exp = (j.groupby(["c_custkey"], as_index=False)
           .agg(revenue=("volume", "sum"))
           .sort_values(["revenue", "c_custkey"], ascending=[False, True])
           .head(20))
    assert list(got.c_custkey) == list(exp.c_custkey)
    np.testing.assert_allclose(got.revenue, exp.revenue, rtol=_REL)


def test_q12_vs_pandas(tpch, pdf):
    got = Q.q12(tpch).to_pandas()
    li = pdf["lineitem"]; o = pdf["orders"]
    f = li[li.l_shipmode.isin(["MAIL", "SHIP"])
           & (li.l_commitdate < li.l_receiptdate)
           & (li.l_shipdate < li.l_commitdate)
           & (li.l_receiptdate >= datetime.date(1994, 1, 1))
           & (li.l_receiptdate < datetime.date(1995, 1, 1))]
    j = o.merge(f, left_on="o_orderkey", right_on="l_orderkey")
    j["high"] = j.o_orderpriority.isin(["1-URGENT", "2-HIGH"]).astype(int)
    j["low"] = 1 - j.high
    exp = (j.groupby("l_shipmode", as_index=False)
           .agg(high_line_count=("high", "sum"), low_line_count=("low", "sum"))
           .sort_values("l_shipmode"))
    assert list(got.l_shipmode) == list(exp.l_shipmode)
    assert list(got.high_line_count) == list(exp.high_line_count)
    assert list(got.low_line_count) == list(exp.low_line_count)


def test_q18_vs_pandas(tpch, pdf):
    got = Q.q18(tpch).to_pandas()
    li = pdf["lineitem"]; o = pdf["orders"]; c = pdf["customer"]
    sums = li.groupby("l_orderkey", as_index=False).agg(
        total_quantity=("l_quantity", "sum"))
    big = sums[sums.total_quantity > 300]
    j = (o.merge(big, left_on="o_orderkey", right_on="l_orderkey")
         .merge(c, left_on="o_custkey", right_on="c_custkey"))
    exp = j.sort_values(["o_totalprice", "o_orderdate"],
                        ascending=[False, True]).head(100)
    assert list(got.o_orderkey) == list(exp.o_orderkey)
    np.testing.assert_allclose(got.total_quantity, exp.total_quantity)


def test_q5_distributed_runner_matches_local(tpch):
    """TPC-H Q5 through the distributed runner (stage plan → scheduler →
    workers) must match the local runner, and must actually cross ≥2 stage
    boundaries (VERDICT r1 item 4 done-criterion)."""
    from daft_tpu.distributed import StagePlan
    from daft_tpu.physical.translate import translate
    from daft_tpu.runners.distributed_runner import DistributedRunner
    import daft_tpu.context as ctx

    local = Q.q5(tpch).to_pydict()
    df = Q.q5(tpch)
    sp = StagePlan.from_physical(translate(df._builder.optimize().plan))
    assert len(sp.stages) >= 2

    runner = DistributedRunner(num_workers=2)
    old = ctx.get_context()._runner
    ctx.get_context().set_runner(runner)
    try:
        dist = Q.q5(tpch).to_pydict()
    finally:
        ctx.get_context().set_runner(old)
    assert dist["n_name"] == local["n_name"]
    np.testing.assert_allclose(dist["revenue"], local["revenue"], rtol=_REL)


@pytest.mark.parametrize("qnum", list(range(1, 23)))
def test_queries_device_matches_host(tpch, qnum, monkeypatch):
    """Every TPC-H query must produce identical results on the device tier
    (virtual mesh + fused kernels + mesh exchanges) and the host tier
    (VERDICT r1 weak #9: device answers were never compared to host)."""
    monkeypatch.setenv("DAFT_TPU_DEVICE", "0")
    host = Q.ALL[qnum](tpch).to_pydict()
    monkeypatch.setenv("DAFT_TPU_DEVICE", "1")
    dev = Q.ALL[qnum](tpch).to_pydict()
    assert list(host) == list(dev)
    for k in host:
        hv, dv = host[k], dev[k]
        assert len(hv) == len(dv), (qnum, k, len(hv), len(dv))
        for a, b in zip(hv, dv):
            if isinstance(a, float) and b is not None:
                assert b == pytest.approx(a, rel=max(1e-6, _REL),
                                          abs=1e-9), (qnum, k)
            else:
                assert a == b, (qnum, k, a, b)
