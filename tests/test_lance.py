"""Native Lance dataset tests (VERDICT r2 item 6 done-criterion:
round-trip write_lance/read_lance without the lance SDK).

Reference surface: ``daft/io/_lance.py`` /
``src/daft-writers/src/lance.rs``; native implementation in
``daft_tpu/io/lance.py``."""

import json
import os

import pytest

import daft_tpu as dt
from daft_tpu import col


@pytest.fixture()
def ds(tmp_path):
    uri = str(tmp_path / "ds")
    dt.from_pydict({
        "a": [1, 2, 3, 4],
        "b": ["w", "x", "y", "z"],
        "c": [1.5, 2.5, None, 4.5],
    }).write_lance(uri)
    return uri


def test_roundtrip(ds):
    out = dt.read_lance(ds).sort("a").to_pydict()
    assert out == {"a": [1, 2, 3, 4], "b": ["w", "x", "y", "z"],
                   "c": [1.5, 2.5, None, 4.5]}


def test_append_and_time_travel(ds):
    dt.from_pydict({"a": [5], "b": ["q"], "c": [9.0]}) \
        .write_lance(ds, mode="append")
    assert dt.read_lance(ds).sort("a").to_pydict()["a"] == [1, 2, 3, 4, 5]
    assert dt.read_lance(ds, version=1).sort("a").to_pydict()["a"] \
        == [1, 2, 3, 4]


def test_overwrite_keeps_versions(ds):
    dt.from_pydict({"a": [7], "b": ["r"], "c": [0.0]}) \
        .write_lance(ds, mode="overwrite")
    assert dt.read_lance(ds).to_pydict()["a"] == [7]
    assert dt.read_lance(ds, version=1).sort("a").to_pydict()["a"] \
        == [1, 2, 3, 4]


def test_create_over_existing_raises(ds):
    with pytest.raises(ValueError, match="already exists"):
        dt.from_pydict({"a": [1], "b": ["b"], "c": [1.0]}).write_lance(ds)


def test_projection_reads_only_selected_column_pages(ds, monkeypatch):
    """Column pushdown must fetch only the projected columns' byte
    ranges."""
    from daft_tpu.io import lance as L
    read_cols = []
    orig = L.read_fragment_file

    def spy(uri, io_config, columns=None, limit=None):
        read_cols.append(columns)
        return orig(uri, io_config, columns=columns, limit=limit)

    monkeypatch.setattr(L, "read_fragment_file", spy)
    out = dt.read_lance(ds).select("b").to_pydict()
    assert out["b"] == ["w", "x", "y", "z"]
    assert read_cols and all(list(c) == ["b"] for c in read_cols)


def test_filter_prunes_fragments(tmp_path):
    uri = str(tmp_path / "pruned")
    dt.from_pydict({"k": [1, 2, 3], "v": ["a", "b", "c"]}).write_lance(uri)
    dt.from_pydict({"k": [100, 200], "v": ["x", "y"]}) \
        .write_lance(uri, mode="append")
    from daft_tpu.io import lance as L
    manifest = L._resolve_version(uri, None)
    assert len(manifest["fragments"]) == 2
    # stats-based pruning: k > 50 provably excludes the first fragment
    surviving = [f for f in manifest["fragments"]
                 if L._fragment_survives((col("k") > 50)._unalias(),
                                         f.get("stats", {}))]
    assert len(surviving) == 1
    out = dt.read_lance(uri).where(col("k") > 50).sort("k").to_pydict()
    assert out == {"k": [100, 200], "v": ["x", "y"]}


def test_limit_pushdown(ds):
    out = dt.read_lance(ds).limit(2).to_pydict()
    assert len(out["a"]) == 2


def test_file_footer_magic(ds):
    import glob
    f = glob.glob(os.path.join(ds, "data", "*.lance"))[0]
    with open(f, "rb") as fh:
        fh.seek(-4, os.SEEK_END)
        assert fh.read() == b"LANC"


def test_empty_dataframe_roundtrip(tmp_path):
    uri = str(tmp_path / "empty")
    dt.from_pydict({"a": [1]}).where(col("a") > 5).write_lance(uri)
    out = dt.read_lance(uri).to_pydict()
    assert out == {"a": []}
