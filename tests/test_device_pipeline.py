"""Round 17 async device pipeline tests: pipelined-vs-synchronous
parity, slot admission hygiene (leak / cancellation / exception
unwinding), chaos-serialize degradation, overlap spans + ledger, the
single-transfer download discipline, device-resident hand-off, and the
overlap-aware cost model."""

import numpy as np
import pytest

import daft_tpu as daft
from daft_tpu import col, tracing
from daft_tpu import observability as obs
from daft_tpu.device import costmodel as cm
from daft_tpu.device import column as dcol
from daft_tpu.device import pipeline as dpipe
from daft_tpu.execution.memory import MemoryManager


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    dpipe.reset_counters()
    dpipe.reset_residency()
    yield
    dpipe.reset_counters()
    dpipe.reset_residency()


@pytest.fixture(scope="module")
def pq_dir(tmp_path_factory):
    """A multi-file parquet 'lineitem' so the fragment path takes the
    windowed scan-task route with several windows in flight."""
    import pyarrow as pa
    import pyarrow.parquet as pq
    root = tmp_path_factory.mktemp("devpipe_pq")
    rng = np.random.default_rng(7)
    for i in range(6):
        n = 800
        pq.write_table(
            pa.table({"flag": rng.integers(0, 4, n),
                      "qty": rng.random(n) * 50,
                      "price": rng.random(n) * 1000}),
            str(root / f"part{i}.parquet"))
    return str(root)


def _q1_scan(root):
    return (daft.read_parquet(f"{root}/*.parquet")
            .groupby("flag")
            .agg(col("qty").sum().alias("sum_qty"),
                 col("price").mean().alias("avg_price"),
                 col("qty").count().alias("cnt"))
            .sort(col("flag")))


def _q1_shape(n=4000, ndv=4):
    # bare in-memory source → the fused fragment's per-morsel path
    rng = np.random.default_rng(7)
    return (daft.from_pydict({
        "flag": rng.integers(0, ndv, n),
        "qty": rng.random(n) * 50,
        "price": rng.random(n) * 1000})
        .groupby("flag")
        .agg(col("qty").sum().alias("sum_qty"),
             col("price").mean().alias("avg_price"),
             col("qty").count().alias("cnt"))
        .sort(col("flag")))


def _q6_shape(n=4000):
    rng = np.random.default_rng(11)
    return (daft.from_pydict({
        "qty": rng.random(n) * 50,
        "disc": rng.random(n) * 0.1,
        "price": rng.random(n) * 1000})
        .where(col("qty") < 24)
        .agg((col("price") * col("disc")).sum().alias("revenue")))


def _q3_shape(n=2000, parts=3):
    rng = np.random.default_rng(13)
    orders = daft.from_pydict({
        "okey": np.arange(n), "cust": rng.integers(0, 50, n)})
    items = daft.from_pydict({
        "okey": rng.integers(0, n, 3 * n),
        "rev": rng.random(3 * n) * 100}).into_partitions(parts)
    return (items.join(orders, on="okey")
            .groupby("cust").agg(col("rev").sum().alias("rev"))
            .sort(col("rev"), desc=True).limit(10))


def _run(df):
    from daft_tpu.context import execution_config_ctx
    # tiny scan tasks → one task per parquet file → several windows
    with execution_config_ctx(scan_tasks_min_size_bytes=1):
        return df.to_pydict()


@pytest.mark.parametrize("shape", [_q1_shape, _q6_shape, _q3_shape])
def test_pipelined_matches_synchronous_bit_identical(monkeypatch, shape):
    """Parity gate: the async pipeline must produce byte-identical
    results to the verbatim synchronous chain on q1/q6/q3 shapes."""
    monkeypatch.setenv("DAFT_TPU_DEVICE_FORCE", "1")
    monkeypatch.setenv("DAFT_TPU_DEVICE_INFLIGHT", "2")
    piped = _run(shape())
    monkeypatch.setenv("DAFT_TPU_DEVICE_INFLIGHT", "0")
    sync = _run(shape())
    assert piped == sync


def test_pipelined_scan_windows_match_synchronous(monkeypatch, pq_dir):
    """The windowed scan-task route (several windows in flight) must be
    bit-identical to its synchronous degradation too."""
    monkeypatch.setenv("DAFT_TPU_DEVICE_FORCE", "1")
    monkeypatch.setenv("DAFT_TPU_DEVICE_INFLIGHT", "2")
    piped = _run(_q1_scan(pq_dir))
    monkeypatch.setenv("DAFT_TPU_DEVICE_INFLIGHT", "0")
    sync = _run(_q1_scan(pq_dir))
    assert piped == sync


def test_pipelined_parity_on_forced_overflow_redispatch(monkeypatch):
    """A group count far past the first packed bucket (128) forces the
    overflow ladder to re-dispatch mid-drain — results must still match
    the synchronous path AND the pure host tier."""
    monkeypatch.setenv("DAFT_TPU_DEVICE", "0")
    host = _run(_q1_shape(n=6000, ndv=1500))
    monkeypatch.setenv("DAFT_TPU_DEVICE", "1")
    monkeypatch.setenv("DAFT_TPU_DEVICE_FORCE", "1")
    monkeypatch.setenv("DAFT_TPU_DEVICE_INFLIGHT", "2")
    piped = _run(_q1_shape(n=6000, ndv=1500))
    monkeypatch.setenv("DAFT_TPU_DEVICE_INFLIGHT", "0")
    sync = _run(_q1_shape(n=6000, ndv=1500))
    assert piped == sync
    assert piped["flag"] == host["flag"]
    for a, b in zip(piped["sum_qty"], host["sum_qty"]):
        assert a == pytest.approx(b, rel=1e-9)


# ------------------------------------------------ slot admission hygiene

def test_exception_mid_window_releases_every_slot():
    mem = MemoryManager(budget=1 << 30)

    def submit(item, seq, gate):
        slot = dpipe.acquire_slot(gate, seq, mem, 1000)
        return dpipe.InflightItem(slot, item)

    def drain(ret, seq):
        if seq == 2:
            raise RuntimeError("boom mid-window")
        return ret.token

    with pytest.raises(RuntimeError, match="boom"):
        list(dpipe.run_pipelined(range(8), submit, drain, window=3))
    assert mem.outstanding == 0


def test_cancellation_unwinds_partially_drained_window():
    """Closing the consumer generator mid-stream (cancellation /
    early-limit abandonment) must release every in-flight slot's
    admission and window occupancy."""
    mem = MemoryManager(budget=1 << 30)

    def submit(item, seq, gate):
        slot = dpipe.acquire_slot(gate, seq, mem, 500)
        return dpipe.InflightItem(slot, item)

    def drain(ret, seq):
        return ret.token

    gen = dpipe.run_pipelined(range(16), submit, drain, window=2)
    assert next(gen) == 0
    assert next(gen) == 1
    gen.close()  # partially drained window unwinds here
    assert mem.outstanding == 0


def test_submit_failure_releases_slot_and_propagates():
    mem = MemoryManager(budget=1 << 30)

    def submit(item, seq, gate):
        slot = dpipe.acquire_slot(gate, seq, mem, 100)
        try:
            if seq == 1:
                raise ValueError("encode failed")
        except BaseException:
            dpipe.release_slot(slot)
            raise
        return dpipe.InflightItem(slot, item)

    with pytest.raises(ValueError, match="encode failed"):
        list(dpipe.run_pipelined(range(4), submit, drain=lambda r, s: r.token,
                                 window=2))
    assert mem.outstanding == 0


def test_host_routed_items_bypass_the_window():
    """Host results don't occupy device slots: a host-heavy stream runs
    at pool width, and ordering is still preserved."""
    seen = []

    def submit(item, seq, gate):
        return item * 10  # plain value = host routed

    out = list(dpipe.run_pipelined(range(20), submit,
                                   drain=lambda r, s: seen.append(s) or r,
                                   window=2))
    assert out == [i * 10 for i in range(20)]
    assert seen == list(range(20))


def test_engine_slot_acquire_release_balanced(monkeypatch, pq_dir):
    """End-to-end: every slot a pipelined device query acquires is
    released by the time the query completes (the acquire-on-submit ↔
    release-on-drain contract, observed at the real chokepoint)."""
    monkeypatch.setenv("DAFT_TPU_DEVICE_FORCE", "1")
    monkeypatch.setenv("DAFT_TPU_DEVICE_INFLIGHT", "2")
    monkeypatch.setenv("DAFT_TPU_MEMORY_LIMIT", "1GiB")
    acquired = []
    real_acquire = dpipe.acquire_slot

    def tracking(*args, **kw):
        slot = real_acquire(*args, **kw)
        acquired.append(slot)
        return slot

    monkeypatch.setattr(dpipe, "acquire_slot", tracking)
    _run(_q1_scan(pq_dir))
    assert acquired, "the pipelined device path never engaged"
    assert all(s.released for s in acquired)


# ------------------------------------------- chaos-serialize degradation

def test_chaos_serialize_forces_synchronous_window(monkeypatch):
    monkeypatch.setenv("DAFT_TPU_DEVICE_INFLIGHT", "4")
    assert dpipe.inflight_window() == 4
    monkeypatch.setenv("DAFT_TPU_CHAOS_SERIALIZE", "1")
    assert dpipe.inflight_window() == 0


def test_active_fault_plan_forces_synchronous_window(monkeypatch):
    monkeypatch.setenv("DAFT_TPU_DEVICE_INFLIGHT", "4")
    monkeypatch.setenv("DAFT_TPU_FAULT_SPEC", "task:0.5")
    from daft_tpu.distributed import resilience as rz
    rz.reset_for_tests()
    try:
        assert dpipe.inflight_window() == 0
    finally:
        monkeypatch.delenv("DAFT_TPU_FAULT_SPEC")
        rz.reset_for_tests()


def test_config_field_applies_when_env_unset(monkeypatch):
    from daft_tpu.context import execution_config_ctx
    monkeypatch.delenv("DAFT_TPU_DEVICE_INFLIGHT", raising=False)
    with execution_config_ctx(tpu_device_inflight=7):
        assert dpipe.inflight_window() == 7
    # env override wins over the config field
    monkeypatch.setenv("DAFT_TPU_DEVICE_INFLIGHT", "3")
    with execution_config_ctx(tpu_device_inflight=7):
        assert dpipe.inflight_window() == 3


def test_chaos_serialized_results_match_pipelined(monkeypatch):
    monkeypatch.setenv("DAFT_TPU_DEVICE_FORCE", "1")
    monkeypatch.setenv("DAFT_TPU_DEVICE_INFLIGHT", "2")
    piped = _run(_q1_shape())
    monkeypatch.setenv("DAFT_TPU_CHAOS_SERIALIZE", "1")
    serialized = _run(_q1_shape())
    assert piped == serialized


# ---------------------------------------------------- spans + overlap

def test_pipeline_spans_on_distinct_lanes_with_slot_ids(monkeypatch, pq_dir):
    monkeypatch.setenv("DAFT_TPU_TRACE", "1")
    monkeypatch.setenv("DAFT_TPU_DEVICE_FORCE", "1")
    monkeypatch.setenv("DAFT_TPU_DEVICE_INFLIGHT", "2")
    tracing.reset_for_tests()
    _run(_q1_scan(pq_dir))
    stats = obs.last_query_stats()
    assert stats is not None and stats.trace_ctx is not None
    spans = stats.trace_ctx.recorder.spans()
    by_name = {}
    for s in spans:
        by_name.setdefault(s["name"], []).append(s)
    for name, lane in (("device:upload", "dev:upload"),
                       ("device:compute", "dev:compute"),
                       ("device:download", "dev:download")):
        assert by_name.get(name), f"missing {name} spans"
        for s in by_name[name]:
            assert s["lane"] == lane
            assert "slot" in s.get("attrs", {})
    tracing.reset_for_tests()


def test_span_ids_deterministic_under_chaos_serialize(monkeypatch):
    """r13 discipline: under DAFT_TPU_CHAOS_SERIALIZE=1 (which degrades
    the pipeline to the synchronous path) two identical runs replay
    bit-identical span id sets."""
    monkeypatch.setenv("DAFT_TPU_TRACE", "1")
    monkeypatch.setenv("DAFT_TPU_CHAOS_SERIALIZE", "1")
    monkeypatch.setenv("DAFT_TPU_DEVICE_FORCE", "1")
    monkeypatch.setenv("DAFT_TPU_DEVICE_INFLIGHT", "2")

    def one_run():
        tracing.reset_for_tests()
        _run(_q1_shape())
        stats = obs.last_query_stats()
        assert stats is not None and stats.trace_ctx is not None
        return stats.trace_ctx.recorder.span_ids()

    ids1 = one_run()
    ids2 = one_run()
    assert sorted(ids1) == sorted(ids2)
    tracing.reset_for_tests()


def test_overlap_recorded_in_mfu_ledger(monkeypatch, pq_dir):
    monkeypatch.setenv("DAFT_TPU_DEVICE_FORCE", "1")
    monkeypatch.setenv("DAFT_TPU_DEVICE_INFLIGHT", "2")
    before = cm.ledger_snapshot(raw=True)
    _run(_q1_scan(pq_dir))
    delta = cm.ledger_delta(before, cm.ledger_snapshot(raw=True))
    assert "pipeline" in delta, delta
    row = delta["pipeline"]
    assert row["dispatches"] >= 1
    assert row["serial_equiv_s"] > 0
    assert row["overlap_x"] > 0


# ------------------------------------------- single-transfer downloads

def test_decode_table_is_one_device_get(monkeypatch):
    import jax
    from daft_tpu.recordbatch import RecordBatch
    batch = RecordBatch.from_pydict({
        "a": np.arange(100, dtype=np.int64),
        "b": np.arange(100, dtype=np.float64),
        "c": np.arange(100) % 2 == 0})
    dt = dcol.encode_batch(batch)
    calls = []
    real = jax.device_get

    def counting(tree):
        calls.append(1)
        return real(tree)

    monkeypatch.setattr(jax, "device_get", counting)
    out = dcol.decode_table(dt)
    assert len(calls) == 1, f"{len(calls)} device_get calls for 3 columns"
    assert out.to_pydict()["a"] == list(range(100))


def test_decode_column_batches_data_and_validity(monkeypatch):
    import jax
    from daft_tpu.series import Series
    s = Series.from_numpy(np.arange(64, dtype=np.int64), "x")
    c = dcol.encode_series(s, 64)
    calls = []
    real = jax.device_get

    def counting(tree):
        calls.append(1)
        return real(tree)

    monkeypatch.setattr(jax, "device_get", counting)
    out = dcol.decode_column("x", c, 64)
    assert len(calls) == 1
    assert out.to_pylist() == list(range(64))


# ------------------------------------------- device-resident hand-off

def test_residency_reuse_skips_reencode(monkeypatch):
    """A decoded device column re-entering the device (projection →
    argsort / agg) hits the residency registry instead of re-uploading;
    reused validity is masked to the live rows."""
    monkeypatch.setenv("DAFT_TPU_DEVICE_INFLIGHT", "2")
    from daft_tpu.recordbatch import RecordBatch
    batch = RecordBatch.from_pydict({
        "a": np.arange(128, dtype=np.int64),
        "b": np.arange(128, dtype=np.float64)})
    dt = dcol.encode_batch(batch)
    decoded = dcol.decode_table(dt)  # registers planes (window > 0)
    assert dpipe.residency_counters()["entries"] == 2
    dt2 = dcol.encode_batch(decoded)
    assert dpipe.residency_counters()["hits"] >= 2
    assert dt2.resident, "reused planes must be donation-protected"
    from daft_tpu.device.fragment import _donation_ok
    assert not _donation_ok(dt2)
    # round-trip stays bit-identical
    assert dcol.decode_table(dt2).to_pydict() == decoded.to_pydict()


def test_residency_masks_garbage_validity_beyond_live_rows(monkeypatch):
    monkeypatch.setenv("DAFT_TPU_DEVICE_INFLIGHT", "2")
    import jax.numpy as jnp
    from daft_tpu.series import Series
    s = Series.from_numpy(np.arange(5, dtype=np.int64), "x")
    # capacity-16 planes whose validity beyond the 5 live rows is
    # GARBAGE-true (a kernel output tail)
    data = jnp.arange(16, dtype=jnp.int64)
    validity = jnp.ones(16, dtype=jnp.bool_)
    dpipe.note_decoded(s, data, validity, None, count=5, capacity=16)
    hit = dpipe.resident_planes(s, 5)
    assert hit is not None
    _, masked, _, cap = hit
    assert cap == 16
    host = np.asarray(masked)
    assert host[:5].all() and not host[5:].any()


def test_residency_skipped_when_pipeline_disabled(monkeypatch):
    monkeypatch.setenv("DAFT_TPU_DEVICE_INFLIGHT", "0")
    from daft_tpu.recordbatch import RecordBatch
    batch = RecordBatch.from_pydict({"a": np.arange(32, dtype=np.int64)})
    dcol.decode_table(dcol.encode_batch(batch))
    assert dpipe.residency_counters()["entries"] == 0


def test_residency_lookup_disabled_under_chaos_serialize(monkeypatch):
    """Planes registered BEFORE degradation must not serve reuse hits
    once chaos-serialize forces the verbatim synchronous chain — a hit
    would skip the upload events the replay contract expects."""
    monkeypatch.setenv("DAFT_TPU_DEVICE_INFLIGHT", "2")
    import jax.numpy as jnp
    from daft_tpu.series import Series
    s = Series.from_numpy(np.arange(16, dtype=np.int64), "x")
    dpipe.note_decoded(s, jnp.arange(16, dtype=jnp.int64),
                       jnp.ones(16, dtype=jnp.bool_), None, 16, 16)
    assert dpipe.resident_planes(s, 16) is not None
    monkeypatch.setenv("DAFT_TPU_CHAOS_SERIALIZE", "1")
    assert dpipe.resident_planes(s, 16) is None


def test_residency_registry_is_byte_bounded(monkeypatch):
    monkeypatch.setenv("DAFT_TPU_DEVICE_INFLIGHT", "2")
    monkeypatch.setenv("DAFT_TPU_HBM_CACHE_BYTES", "8192")  # budget = 1KiB
    import jax.numpy as jnp
    from daft_tpu.series import Series
    kept = []
    for i in range(8):
        s = Series.from_numpy(np.arange(16, dtype=np.int64), f"c{i}")
        kept.append(s)
        dpipe.note_decoded(s, jnp.arange(16, dtype=jnp.int64),
                           jnp.ones(16, dtype=jnp.bool_), None, 16, 16)
    c = dpipe.residency_counters()
    assert c["bytes"] <= 1024
    assert c["evictions"] > 0


# ------------------------------------------------- overlap-aware pricing

def test_pipelined_seconds_never_exceeds_serial():
    lp = cm.LinkProfile(rtt_s=0.04, up_bps=40e6, down_bps=40e6)
    serial = lp.device_seconds(8e6, 1e5, 2.0, 0.01)
    piped = lp.pipelined_seconds(8e6, 1e5, 2.0, 0.01)
    assert piped < serial
    assert piped >= max(8e6 / 40e6, 0.01)  # bottleneck stage survives


def test_agg_upload_overlap_pricing_admits_more(monkeypatch):
    """A transfer-bound upload the serial model declines is admitted
    once the pipeline hides the wire behind device compute."""
    monkeypatch.setenv("DAFT_TPU_LINK_RTT_MS", "100")
    monkeypatch.setenv("DAFT_TPU_LINK_UP_MBPS", "40")
    monkeypatch.setenv("DAFT_TPU_LINK_DOWN_MBPS", "40")
    cm.reset_for_tests()
    try:
        # serial: 0.2 s wire + 0.2 s RTTs + kernel ≈ 0.41 s vs a 0.35 s
        # host pass → declines; pipelined: max(wire, kernel) + 1 RTT
        # ≈ 0.30 s → accepts
        up, down, host_b = 8e6, 1e4, 105e6
        assert not cm.agg_upload_wins(up, down, cacheable=False,
                                      host_bytes=host_b)
        assert cm.agg_upload_wins(up, down, cacheable=False,
                                  host_bytes=host_b, window=2)
    finally:
        cm.reset_for_tests()


def test_join_overlap_pricing_admits_more(monkeypatch):
    monkeypatch.setenv("DAFT_TPU_LINK_RTT_MS", "40")
    monkeypatch.setenv("DAFT_TPU_LINK_UP_MBPS", "40")
    monkeypatch.setenv("DAFT_TPU_LINK_DOWN_MBPS", "40")
    cm.reset_for_tests()
    try:
        # host ≈ 0.32 s; serial device ≈ 0.49 s (declines); pipelined
        # ≈ 0.29 s (wire and kernel overlap neighbors → accepts)
        n_l = n_r = 4_000_000
        up, down = 5e6, 5e6
        assert not cm.join_wins(n_l, n_r, up, down)
        assert cm.join_wins(n_l, n_r, up, down, window=2)
    finally:
        cm.reset_for_tests()
