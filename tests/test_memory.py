"""Memory budget + spill-tier tests (reference model: resource_manager.rs,
shuffle_cache.rs spill files)."""

import os
import threading

import pytest

import daft_tpu as daft
from daft_tpu import col
from daft_tpu.execution import memory
from daft_tpu.micropartition import MicroPartition
from daft_tpu.recordbatch import RecordBatch


def _mp(n, base=0):
    return MicroPartition.from_recordbatch(
        RecordBatch.from_pydict({"x": list(range(base, base + n))}))


def test_parse_bytes():
    assert memory.parse_bytes("4GB") == 4 * 10 ** 9
    assert memory.parse_bytes("512MiB") == 512 << 20
    assert memory.parse_bytes("100") == 100
    assert memory.parse_bytes("2k") == 2048


def test_spill_buffer_roundtrip_under_budget():
    buf = memory.SpillBuffer(budget=None)
    for i in range(3):
        buf.append(_mp(10, i * 10))
    assert len(buf) == 3 and buf.bytes_spilled == 0
    vals = [v for p in buf for v in p.to_pydict()["x"]]
    assert vals == list(range(30))


def test_spill_buffer_spills_and_reloads(tmp_path, monkeypatch):
    monkeypatch.setenv("DAFT_TPU_SPILL_DIR", str(tmp_path))
    memory._spill_dir = None  # reset cached dir
    buf = memory.SpillBuffer(budget=1)  # force everything after 1st to disk
    for i in range(4):
        buf.append(_mp(100, i * 100))
    assert buf.bytes_spilled > 0
    assert any(f.endswith(".arrow") for f in os.listdir(tmp_path))
    # multi-pass iteration reloads from disk, order preserved
    for _ in range(2):
        vals = [v for p in buf for v in p.to_pydict()["x"]]
        assert vals == list(range(400))
    # random access incl. slices
    assert buf[2].to_pydict()["x"][0] == 200
    assert [p.to_pydict()["x"][0] for p in buf[1:]] == [100, 200, 300]
    buf.close()
    assert not any(f.endswith(".arrow") for f in os.listdir(tmp_path))


def test_query_with_spill_matches_no_spill(tmp_path, monkeypatch):
    """Sort + hash-exchange query under a tiny budget must give identical
    results to the unbounded run."""
    data = {"k": [i % 13 for i in range(5000)], "v": list(range(5000))}
    expected = (daft.from_pydict(data).repartition(4, "k")
                .groupby("k").agg(col("v").sum().alias("s"))
                .sort("k").to_pydict())

    monkeypatch.setenv("DAFT_TPU_SPILL_DIR", str(tmp_path))
    monkeypatch.setenv("DAFT_TPU_MEMORY_LIMIT", "1KB")
    memory._spill_dir = None
    got = (daft.from_pydict(data).repartition(4, "k")
           .groupby("k").agg(col("v").sum().alias("s"))
           .sort("k").to_pydict())
    assert got == expected


def test_memory_manager_admission():
    mm = memory.MemoryManager(budget=100)
    mm.acquire(60)
    state = {"entered": False}

    def second():
        mm.acquire(60)  # must block until release
        state["entered"] = True
        mm.release(60)

    t = threading.Thread(target=second)
    t.start()
    t.join(timeout=0.2)
    assert not state["entered"]
    mm.release(60)
    t.join(timeout=2)
    assert state["entered"]


def test_memory_manager_oversized_request_admitted_when_idle():
    mm = memory.MemoryManager(budget=10)
    mm.acquire(100)  # larger than budget; nothing held → no deadlock
    mm.release(100)


def test_bad_memory_limit_is_hard_error(monkeypatch):
    monkeypatch.setenv("DAFT_TPU_MEMORY_LIMIT", "lots")
    with pytest.raises(ValueError, match="DAFT_TPU_MEMORY_LIMIT"):
        memory.memory_limit_bytes()


def test_parse_bytes_tb():
    assert memory.parse_bytes("1TB") == 10 ** 12
    assert memory.parse_bytes("1TiB") == 1 << 40


def test_hash_join_mismatched_partition_counts_correct():
    """A partition-count mismatch must never index-pair unrelated
    partitions (VERDICT r1 weak #8). The streaming fallback sizes its
    bucket fanout by BYTES (tiny inputs legitimately collapse to one
    direct join; big ones spill-partition — see
    test_hash_join_fallback_buckets_large) — correctness of the matched
    rows is the invariant."""
    from daft_tpu.execution.executor import LocalExecutor
    from daft_tpu.micropartition import MicroPartition
    from daft_tpu.physical import plan as pp
    from daft_tpu import col

    lparts = [MicroPartition.from_pydict(
        {"k": list(range(i * 10, i * 10 + 10)),
         "x": list(range(i * 10, i * 10 + 10))}) for i in range(4)]
    rparts = [MicroPartition.from_pydict(
        {"k": list(range(0, 40, 2))[i::2],
         "y": list(range(20))[i::2]}) for i in range(2)]
    node = pp.HashJoin(
        pp.InMemorySource(lparts, lparts[0].schema),
        pp.InMemorySource(rparts, rparts[0].schema),
        [col("k")], [col("k")], "inner", None, "hash")
    ex = LocalExecutor()
    out = list(ex.run(node))
    rows = sorted(v for p in out for v in p.to_pydict()["k"])
    assert rows == list(range(0, 40, 2))


def test_hash_join_fallback_buckets_large(monkeypatch):
    """Past the bucket threshold the fallback spill-partitions BOTH sides
    and emits one pair per bucket — parallelism scales with data size,
    independent of input partition counts."""
    from daft_tpu.execution import memory
    from daft_tpu.execution.executor import LocalExecutor
    from daft_tpu.micropartition import MicroPartition
    from daft_tpu.physical import plan as pp
    from daft_tpu import col

    n = 5000
    lparts = [MicroPartition.from_pydict(
        {"k": list(range(n)), "x": list(range(n))})]
    rparts = [MicroPartition.from_pydict(
        {"k": list(range(0, 2 * n, 2)), "y": list(range(n))})]
    # shrink the bucket target so this small fixture exercises the
    # multi-bucket path
    monkeypatch.setattr(memory, "breaker_budget_bytes", lambda: 64 * 1024)
    node = pp.HashJoin(
        pp.InMemorySource(lparts, lparts[0].schema),
        pp.InMemorySource(rparts, rparts[0].schema),
        [col("k")], [col("k")], "inner", None, "hash")
    ex = LocalExecutor()
    out = list(ex.run(node))
    assert len(out) > 1  # bucketed, not gathered
    rows = sorted(v for p in out for v in p.to_pydict()["k"])
    assert rows == list(range(0, n, 2))


def test_scan_load_retries_transient_io(monkeypatch, tmp_path):
    """A transient IO failure during scan-task load retries instead of
    failing the query (reference: per-task retry semantics)."""
    import pyarrow as pa
    import pyarrow.parquet as pq
    import daft_tpu
    from daft_tpu.io.scan import ScanTask

    p = str(tmp_path / "t.parquet")
    pq.write_table(pa.table({"x": [1, 2, 3]}), p)
    df = daft_tpu.read_parquet(p)

    from daft_tpu.io import readers
    calls = {"n": 0}
    orig = readers.iter_scan_task_batches

    def flaky(task):
        calls["n"] += 1
        if calls["n"] == 1:
            raise OSError("transient read failure")
        return orig(task)

    monkeypatch.setattr(readers, "iter_scan_task_batches", flaky)
    assert df.to_pydict() == {"x": [1, 2, 3]}
    assert calls["n"] >= 2
