"""All 22 TPC-H queries as SQL text match their DataFrame forms
(reference ships the SQL set in ``benchmarking/tpch/queries/*.sql``;
here ``benchmarking/tpch/sql_queries.py``). The two frontends share
parameters, so row values must agree exactly (floats to 1e-6)."""

import pytest

import daft_tpu as dt
from benchmarking.tpch import queries as DFQ, sql_queries as SQ
from benchmarking.tpch.datagen import generate_tpch


@pytest.fixture(scope="module")
def tpch(tmp_path_factory):
    root = tmp_path_factory.mktemp("tpch_sql")
    generate_tpch(str(root), 0.05, 2)

    def get_df(name):
        return dt.read_parquet(f"{root}/{name}/*.parquet")

    return get_df


def _rows(d):
    cols = list(d.values())
    return [tuple(c[i] for c in cols) for i in range(len(cols[0]))] \
        if cols else []


def _close(a, b):
    if isinstance(a, float) and isinstance(b, float):
        return a == pytest.approx(b, rel=1e-6, abs=1e-6)
    return a == b


@pytest.mark.parametrize("qnum", sorted(SQ.ALL))
def test_sql_matches_dataframe(tpch, qnum):
    sql_out = SQ.run(qnum, tpch).to_pydict()
    df_out = getattr(DFQ, f"q{qnum}")(tpch).to_pydict()
    srows, drows = _rows(sql_out), _rows(df_out)
    assert len(srows) == len(drows), \
        f"q{qnum}: {len(srows)} SQL rows vs {len(drows)} DataFrame rows"
    # same column COUNT (names may differ; the spec fixes the order)
    for i, (sr, dr) in enumerate(zip(srows, drows)):
        assert len(sr) == len(dr), f"q{qnum} row {i}: width {sr} vs {dr}"
        for a, b in zip(sr, dr):
            assert _close(a, b), f"q{qnum} row {i}: {sr} vs {dr}"
