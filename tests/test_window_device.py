"""Window execution under the device tier (part of the
``DAFT_TPU_REAL_DEVICE=1`` opt-in pass — windows previously only ever ran
under XLA-on-CPU). Small shapes: the real-chip pass is compile-budget
bounded."""

import daft_tpu as dt
from daft_tpu import col
from daft_tpu.functions import rank
from daft_tpu.window import Window


def _df():
    return dt.from_pydict({
        "g": ["a", "a", "a", "b", "b"],
        "v": [3.0, 1.0, 2.0, 10.0, 20.0],
    })


def test_rank_over_partition(device_tier):
    w = Window().partition_by("g").order_by("v")
    out = (_df().select(col("g"), col("v"),
                        rank().over(w).alias("r"))
           .sort(["g", "v"]).to_pydict())
    assert out["r"] == [1, 2, 3, 1, 2]


def test_running_sum_frame(device_tier):
    w = (Window().partition_by("g").order_by("v")
         .rows_between(Window.unbounded_preceding, Window.current_row))
    out = (_df().select(col("g"), col("v"),
                        col("v").sum().over(w).alias("rs"))
           .sort(["g", "v"]).to_pydict())
    assert out["rs"] == [1.0, 3.0, 6.0, 10.0, 30.0]


def test_lag_lead(device_tier):
    w = Window().partition_by("g").order_by("v")
    out = (_df().select(col("g"), col("v"),
                        col("v").lag(1).over(w).alias("p"))
           .sort(["g", "v"]).to_pydict())
    assert out["p"] == [None, 1.0, 2.0, None, 10.0]
