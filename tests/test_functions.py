"""Tests for .binary / .json / .url expression namespaces + object IO.

Reference surfaces: src/daft-functions-binary, src/daft-functions-json,
src/daft-functions-uri, src/daft-io.
"""

import base64

import pytest

import daft_tpu as daft
from daft_tpu import col
from daft_tpu.io.object_io import IOStatsContext, LocalSource, get_io_client


def _one_col(df, name):
    return df.to_pydict()[name]


# -- binary -----------------------------------------------------------------

def test_binary_concat_length_slice():
    df = daft.from_pydict({"a": [b"hello", b"", None], "b": [b"!", b"x", b"y"]})
    out = df.select(
        col("a").binary.concat(col("b")).alias("cat"),
        col("a").binary.length().alias("len"),
        col("a").binary.slice(1, 3).alias("sl"),
    ).to_pydict()
    assert out["cat"] == [b"hello!", b"x", None]
    assert out["len"] == [5, 0, None]
    assert out["sl"] == [b"ell", b"", None]


@pytest.mark.parametrize("codec,plain,coded", [
    ("base64", b"daft", base64.b64encode(b"daft")),
    ("hex", b"\x01\xff", b"01ff"),
])
def test_binary_encode_decode(codec, plain, coded):
    df = daft.from_pydict({"a": [plain]})
    enc = _one_col(df.select(col("a").binary.encode(codec)), "a")
    assert enc == [coded]
    df2 = daft.from_pydict({"a": enc})
    dec = _one_col(df2.select(col("a").binary.decode(codec)), "a")
    assert dec == [plain]


def test_binary_roundtrip_compression():
    data = b"a" * 1000
    df = daft.from_pydict({"a": [data]})
    for codec in ("gzip", "zlib", "deflate"):
        enc = _one_col(df.select(col("a").binary.encode(codec)), "a")
        assert len(enc[0]) < len(data)
        dec = _one_col(daft.from_pydict({"a": enc})
                       .select(col("a").binary.decode(codec)), "a")
        assert dec == [data]


def test_binary_try_decode_null_on_error():
    df = daft.from_pydict({"a": [b"!!!not-base64!!!", base64.b64encode(b"ok")]})
    out = _one_col(df.select(col("a").binary.try_decode("base64")), "a")
    assert out[0] is None
    assert out[1] == b"ok"


# -- json -------------------------------------------------------------------

def test_json_query_paths():
    docs = ['{"a": {"b": 1}, "c": [10, 20, 30]}',
            '{"a": {"b": "x"}, "c": []}',
            None]
    df = daft.from_pydict({"j": docs})
    out = df.select(
        col("j").json.query(".a.b").alias("ab"),
        col("j").json.query(".c[1]").alias("c1"),
        col("j").json.query(".c[]").alias("call"),
    ).to_pydict()
    assert out["ab"] == ["1", "x", None]
    assert out["c1"] == ["20", None, None]
    # iteration always yields a JSON array — "[]" for zero hits (null doc
    # still yields null)
    assert out["call"] == ["[10, 20, 30]", "[]", None]


def test_json_query_iteration_always_array():
    # array iteration must yield a JSON array even for 1-element arrays
    df = daft.from_pydict({"j": ['{"c": [10]}', '{"c": [10, 20]}']})
    out = _one_col(df.select(col("j").json.query(".c[]")), "j")
    assert out == ["[10]", "[10, 20]"]


def test_json_query_pipe():
    df = daft.from_pydict({"j": ['{"a": [{"b": 5}]}']})
    out = _one_col(df.select(col("j").json.query(".a[0] | .b")), "j")
    assert out == ["5"]


# -- url --------------------------------------------------------------------

def test_url_download_local(tmp_path):
    paths = []
    for i in range(3):
        p = tmp_path / f"f{i}.bin"
        p.write_bytes(bytes([i]) * 4)
        paths.append(str(p))
    df = daft.from_pydict({"u": paths + [None]})
    out = _one_col(df.select(col("u").url.download()), "u")
    assert out == [b"\x00" * 4, b"\x01" * 4, b"\x02" * 4, None]


def test_url_download_on_error_null(tmp_path):
    df = daft.from_pydict({"u": [str(tmp_path / "missing.bin")]})
    out = _one_col(df.select(col("u").url.download(on_error="null")), "u")
    assert out == [None]
    with pytest.raises(Exception):
        df.select(col("u").url.download(on_error="raise")).collect()


def test_url_upload_roundtrip(tmp_path):
    df = daft.from_pydict({"data": [b"abc", b"def"]})
    out = _one_col(df.select(col("data").url.upload(str(tmp_path))), "data")
    assert all(p is not None for p in out)
    files = sorted(tmp_path.iterdir())
    assert len(files) == 2
    assert sorted(f.read_bytes() for f in files) == [b"abc", b"def"]


def test_url_parse():
    df = daft.from_pydict({"u": ["https://example.com:8080/p/q?x=1#frag",
                                 "http://host:notaport/x"]})
    out = _one_col(df.select(col("u").url.parse()), "u")
    assert out[0]["scheme"] == "https"
    assert out[0]["host"] == "example.com"
    assert out[0]["port"] == 8080
    assert out[0]["path"] == "/p/q"
    assert out[1] is None  # bad port nulls the row, not the query


def test_url_upload_unique_across_partitions(tmp_path):
    df = (daft.from_pydict({"data": [b"A", b"B", b"C", b"D"]})
          .repartition(2)
          .select(col("data").url.upload(str(tmp_path))))
    df.collect()
    files = list(tmp_path.iterdir())
    assert len(files) == 4
    assert sorted(f.read_bytes() for f in files) == [b"A", b"B", b"C", b"D"]


def test_binary_decode_base64_strict():
    df = daft.from_pydict({"a": [b"####"]})
    with pytest.raises(Exception):
        df.select(col("a").binary.decode("base64")).collect()
    out = _one_col(df.select(col("a").binary.try_decode("base64")), "a")
    assert out == [None]


# -- object IO --------------------------------------------------------------

def test_local_source_get_range_and_stats(tmp_path):
    p = tmp_path / "x.bin"
    p.write_bytes(b"0123456789")
    src = LocalSource()
    stats = IOStatsContext("t")
    assert src.get(str(p), (2, 5), stats) == b"234"
    assert src.get_size(str(p)) == 10
    assert stats.num_gets == 1 and stats.bytes_read == 3


def test_io_client_glob(tmp_path):
    for n in ("a.parquet", "b.parquet", "c.csv"):
        (tmp_path / n).write_bytes(b"")
    client = get_io_client()
    hits = client.glob(str(tmp_path / "*.parquet"))
    assert [h.rsplit("/", 1)[1] for h in hits] == ["a.parquet", "b.parquet"]


def test_image_pipeline_decode_resize_mode_encode_crop():
    """Full image kernel surface (reference: src/daft-image
    decode/encode/resize/crop/to_mode)."""
    import io as _io
    import numpy as np
    from PIL import Image
    imgs = []
    for i in range(2):
        a = (np.arange(100 * 80 * 3) % 255).astype(np.uint8) \
            .reshape(100, 80, 3)
        b = _io.BytesIO()
        Image.fromarray(a).save(b, format="PNG")
        imgs.append(b.getvalue())
    df = daft.from_pydict({"b": imgs, "bbox": [[0, 0, 8, 6]] * 2})
    out = (df.with_column("img", col("b").image.decode())
           .with_column("small", col("img").image.resize(16, 12))
           .with_column("gray", col("small").image.to_mode("L"))
           .with_column("cropped", col("small").image.crop(col("bbox")))
           .with_column("enc", col("gray").image.encode("png"))
           .to_pydict())
    assert out["small"][0].shape == (12, 16, 3)
    assert out["gray"][0].shape == (12, 16)
    assert out["cropped"][0].shape == (6, 8, 3)
    assert out["enc"][0][:4] == b"\x89PNG"


def test_image_resize_batched_device_path_matches_pil(monkeypatch):
    """A uniform-shape batch ≥ the batching floor takes the single-program
    device resize (jax.image.resize over (N,H,W,C)); values stay close to
    the per-image PIL result and null slots survive. The device path is
    spied on so a silent fallback to PIL fails the test. FORCE pins the
    cost gate: a 20 KB test batch rationally stays on PIL (dispatch
    overhead dominates), but this test is about the kernel's parity."""
    import numpy as np
    from PIL import Image

    from daft_tpu.functions import image as img_mod
    monkeypatch.setenv("DAFT_TPU_DEVICE_FORCE", "1")
    calls = []
    orig = img_mod._device_batch_resize

    def spy(imgs, w, h):
        out = orig(imgs, w, h)
        calls.append(out is not None)
        return out

    monkeypatch.setattr(img_mod, "_device_batch_resize", spy)
    base = (np.arange(24 * 32 * 3) % 255).astype(np.uint8).reshape(24, 32, 3)
    imgs = [base.copy() for _ in range(9)] + [None]
    out = daft.from_pydict({"img": imgs}) \
        .select(col("img").image.resize(16, 12)).to_pydict()["img"]
    assert calls == [True], "device batch path did not run"
    assert out[-1] is None
    assert all(o.shape == (12, 16, 3) for o in out[:-1])
    ref = np.asarray(Image.fromarray(base).resize((16, 12)))
    assert np.abs(out[0].astype(int) - ref.astype(int)).mean() < 12


def test_image_resize_uint16_values_preserved():
    """Integer dtypes clamp to their OWN range on the device path — 16-bit
    pixels above 255 survive (regression: an unconditional 0–255 clip)."""
    import numpy as np
    base = np.full((8, 8), 40_000, dtype=np.uint16)
    imgs = [base.copy() for _ in range(10)]
    out = daft.from_pydict({"img": imgs}) \
        .select(col("img").image.resize(4, 4)).to_pydict()["img"]
    assert all(o.dtype == np.uint16 for o in out)
    assert all((o == 40_000).all() for o in out)
