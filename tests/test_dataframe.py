"""DataFrame-level behavioral tests (reference model: ``tests/dataframe/``).

Parametrized over partition counts to exercise single-partition and
exchange-based multi-partition paths (the reference's runner-matrix trick).
"""

import datetime
import os

import numpy as np
import pytest

import daft_tpu as dt
from daft_tpu import DataType, Window, col, lit
from daft_tpu.functions import dense_rank, rank, row_number


@pytest.fixture(params=[1, 3], ids=["p1", "p3"])
def nparts(request):
    return request.param


def mkdf(data, nparts):
    df = dt.from_pydict(data)
    return df.into_partitions(nparts) if nparts > 1 else df


def test_select_with_column(nparts):
    df = mkdf({"a": [1, 2, 3]}, nparts)
    out = df.with_column("b", col("a") * 2).select("b", (col("a") + col("b")).alias("c"))
    assert out.to_pydict() == {"b": [2, 4, 6], "c": [3, 6, 9]}


def test_where_limit(nparts):
    df = mkdf({"a": list(range(100))}, nparts)
    assert df.where(col("a") % 2 == 0).limit(5).to_pydict()["a"] == [0, 2, 4, 6, 8]


def test_groupby_agg(nparts):
    df = mkdf({"g": ["a", "b", "a", "b", "c"], "v": [1, 2, 3, 4, 5]}, nparts)
    out = df.groupby("g").agg(
        col("v").sum().alias("s"),
        col("v").mean().alias("m"),
        col("v").count().alias("c"),
        col("v").min().alias("mn"),
        col("v").max().alias("mx"),
    ).sort("g")
    assert out.to_pydict() == {
        "g": ["a", "b", "c"], "s": [4, 6, 5], "m": [2.0, 3.0, 5.0],
        "c": [2, 2, 1], "mn": [1, 2, 5], "mx": [3, 4, 5]}


def test_global_agg_compound(nparts):
    df = mkdf({"a": [1.0, 2.0, 3.0, 4.0]}, nparts)
    out = df.agg((col("a").sum() / col("a").count()).alias("avg"))
    assert out.to_pydict() == {"avg": [2.5]}


def test_agg_stddev_multipart(nparts):
    df = mkdf({"g": ["x", "x", "y", "y"], "v": [1.0, 3.0, 5.0, 9.0]}, nparts)
    out = df.groupby("g").agg(col("v").stddev().alias("sd")).sort("g")
    assert out.to_pydict()["sd"] == pytest.approx([1.0, 2.0])


def test_agg_list_concat(nparts):
    df = mkdf({"g": ["a", "a", "b"], "v": [1, 2, 3]}, nparts)
    out = df.groupby("g").agg(col("v").agg_list().alias("l")).sort("g")
    d = out.to_pydict()
    assert sorted(d["l"][0]) == [1, 2] and d["l"][1] == [3]


def test_count_distinct(nparts):
    df = mkdf({"g": ["a", "a", "b"], "v": [1, 1, 2]}, nparts)
    out = df.groupby("g").agg(col("v").count_distinct().alias("n")).sort("g")
    assert out.to_pydict()["n"] == [1, 1]


def test_joins(nparts):
    l = mkdf({"k": [1, 2, 3], "v": [10, 20, 30]}, nparts)
    r = mkdf({"k": [2, 3, 4], "w": [200, 300, 400]}, nparts)
    assert l.join(r, on="k").sort("k").to_pydict() == {
        "k": [2, 3], "v": [20, 30], "w": [200, 300]}
    assert l.join(r, on="k", how="left").sort("k").to_pydict()["w"] == \
        [None, 200, 300]
    assert sorted(l.join(r, on="k", how="outer").to_pydict()["k"]) == [1, 2, 3, 4]
    assert l.join(r, on="k", how="anti").to_pydict()["v"] == [10]
    assert l.join(r, on="k", how="semi").sort("k").to_pydict()["v"] == [20, 30]


def test_cross_join(nparts):
    l = mkdf({"a": [1, 2]}, nparts)
    r = dt.from_pydict({"b": ["x", "y"]})
    out = l.join(r, how="cross").sort(["a", "b"])
    assert out.to_pydict() == {"a": [1, 1, 2, 2], "b": ["x", "y", "x", "y"]}


def test_sort_multi_partition(nparts):
    rng = np.random.default_rng(0)
    vals = rng.permutation(1000)
    df = mkdf({"x": vals}, nparts)
    assert df.sort("x").to_pydict()["x"] == list(range(1000))
    assert df.sort("x", desc=True).to_pydict()["x"] == list(range(999, -1, -1))


def test_concat_union(nparts):
    a = mkdf({"x": [1, 2]}, nparts)
    b = dt.from_pydict({"x": [2, 3]})
    assert sorted(a.concat(b).to_pydict()["x"]) == [1, 2, 2, 3]
    assert sorted(a.union(b).to_pydict()["x"]) == [1, 2, 3]
    assert sorted(a.intersect(b).to_pydict()["x"]) == [2]
    assert sorted(a.except_distinct(b).to_pydict()["x"]) == [1]


def test_distinct(nparts):
    df = mkdf({"a": [1, 1, 2, 2, 3]}, nparts)
    assert sorted(df.distinct().to_pydict()["a"]) == [1, 2, 3]


def test_describe_count_rows(nparts):
    df = mkdf({"a": [1, 2, None], "s": ["x", "y", "z"]}, nparts)
    assert df.count_rows() == 3
    d = df.describe().to_pydict()
    assert d["a_count"] == [2] and d["a_mean"] == [1.5]


def test_explode_unpivot(nparts):
    df = mkdf({"i": [1, 2], "l": [[1, 2], [3]]}, nparts)
    assert df.explode("l").sort(["i", "l"]).to_pydict()["l"] == [1, 2, 3]
    df2 = mkdf({"id": [1], "x": [10], "y": [20]}, 1)
    up = df2.unpivot("id").sort("variable")
    assert up.to_pydict() == {"id": [1, 1], "variable": ["x", "y"],
                              "value": [10, 20]}


def test_pivot(nparts):
    df = mkdf({"g": ["a", "a", "b"], "p": ["x", "y", "x"], "v": [1, 2, 3]}, nparts)
    out = df.pivot("g", col("p"), col("v"), "sum").sort("g")
    assert out.to_pydict() == {"g": ["a", "b"], "x": [1, 3], "y": [2, None]}


def test_monotonic_id(nparts):
    df = mkdf({"a": [1, 2, 3, 4]}, nparts)
    ids = df.add_monotonically_increasing_id().to_pydict()["id"]
    assert len(set(ids)) == 4


def test_sample_head(nparts):
    df = mkdf({"a": list(range(100))}, nparts)
    s = df.sample(fraction=0.2, seed=42)
    assert 5 <= len(s.to_pydict()["a"]) <= 40


def test_window_functions(nparts):
    df = mkdf({"g": ["a", "a", "a", "b", "b"],
               "v": [3, 1, 2, 10, 5],
               "s": [1.0, 2.0, 3.0, 4.0, 5.0]}, nparts)
    w = Window().partition_by("g").order_by("v")
    out = df.with_column("rn", row_number().over(w)) \
            .with_column("rk", rank().over(w)) \
            .with_column("rsum", col("s").sum().over(w)) \
            .sort(["g", "v"])
    d = out.to_pydict()
    assert d["rn"] == [1, 2, 3, 1, 2]
    assert d["rk"] == [1, 2, 3, 1, 2]
    # running sum in v-order within group: a→(s=2,3,1), b→(s=5,4)
    assert d["rsum"] == [2.0, 5.0, 6.0, 5.0, 9.0]


def test_window_full_frame(nparts):
    df = mkdf({"g": ["a", "a", "b"], "v": [1.0, 3.0, 10.0]}, nparts)
    w = Window().partition_by("g")
    out = df.with_column("avg", col("v").mean().over(w)).sort(["g", "v"])
    assert out.to_pydict()["avg"] == [2.0, 2.0, 10.0]


def test_udf(nparts):
    @dt.udf(return_dtype=DataType.int64())
    def double_it(s):
        return [v * 2 for v in s.to_pylist()]

    df = mkdf({"a": [1, 2, 3]}, nparts)
    assert df.select(double_it(col("a"))).to_pydict() == {"a": [2, 4, 6]}


def test_stateful_udf(nparts):
    @dt.udf(return_dtype=DataType.int64(), concurrency=2)
    class AddBase:
        def __init__(self, base=100):
            self.base = base

        def __call__(self, s):
            return [v + self.base for v in s.to_pylist()]

    df = mkdf({"a": [1, 2]}, nparts)
    assert df.select(AddBase(col("a"))).to_pydict() == {"a": [101, 102]}


def test_apply(nparts):
    df = mkdf({"a": [1, 2, 3]}, nparts)
    out = df.select(col("a").apply(lambda x: x * 10, DataType.int64()))
    assert out.to_pydict() == {"a": [10, 20, 30]}


def test_iter_rows_and_len(nparts):
    df = mkdf({"a": [1, 2, 3]}, nparts)
    assert list(df.iter_rows()) == [{"a": 1}, {"a": 2}, {"a": 3}]
    assert len(df) == 3


def test_repartition_roundtrip(nparts):
    df = mkdf({"a": list(range(20)), "b": [i % 3 for i in range(20)]}, nparts)
    out = df.repartition(4, "b")
    assert sorted(out.to_pydict()["a"]) == list(range(20))


def test_to_pandas_arrow(nparts):
    df = mkdf({"a": [1, 2]}, nparts)
    assert df.to_arrow().num_rows == 2
    assert list(df.to_pandas()["a"]) == [1, 2]


def test_collect_caches(nparts):
    df = mkdf({"a": [1, 2, 3]}, nparts).collect()
    out = df.where(col("a") > 1)
    assert out.to_pydict() == {"a": [2, 3]}
