"""IO tests: read/write roundtrips, pushdowns, scan-task merging
(reference model: ``tests/io/``)."""

import datetime
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import daft_tpu as dt
from daft_tpu import col


@pytest.fixture
def pq_dir(tmp_path):
    for i in range(4):
        t = pa.table({"a": np.arange(i * 10, (i + 1) * 10),
                      "b": [f"s{j}" for j in range(10)],
                      "d": [datetime.date(2020, 1, 1 + j) for j in range(10)]})
        pq.write_table(t, tmp_path / f"part{i}.parquet")
    return str(tmp_path)


def test_read_parquet_glob(pq_dir):
    df = dt.read_parquet(pq_dir + "/*.parquet")
    assert df.schema().column_names == ["a", "b", "d"]
    assert sorted(df.to_pydict()["a"]) == list(range(40))


def test_read_parquet_dir(pq_dir):
    df = dt.read_parquet(pq_dir)
    assert len(df.to_pydict()["a"]) == 40


def test_column_pushdown(pq_dir):
    df = dt.read_parquet(pq_dir + "/*.parquet").select("a")
    opt = df._builder.optimize()
    from daft_tpu.logical import plan as lp

    def find_source(n):
        if isinstance(n, lp.Source):
            return n
        for c in n.children:
            s = find_source(c)
            if s is not None:
                return s
        return None
    src = find_source(opt.plan)
    assert src.pushdowns.columns == ("a",)
    assert sorted(df.to_pydict()["a"]) == list(range(40))


def test_filter_pushdown_rowgroup_prune(pq_dir):
    df = dt.read_parquet(pq_dir + "/*.parquet").where(col("a") >= 35)
    assert sorted(df.to_pydict()["a"]) == list(range(35, 40))


def test_limit_pushdown(pq_dir):
    df = dt.read_parquet(pq_dir + "/*.parquet").limit(7)
    assert len(df.to_pydict()["a"]) == 7


def test_csv_roundtrip(tmp_path):
    df = dt.from_pydict({"x": [1, 2, 3], "y": ["a", "b", "c"]})
    df.write_csv(str(tmp_path / "out"))
    back = dt.read_csv(str(tmp_path / "out" / "*.csv"))
    assert back.sort("x").to_pydict() == {"x": [1, 2, 3], "y": ["a", "b", "c"]}


def test_json_roundtrip(tmp_path):
    df = dt.from_pydict({"x": [1, 2], "y": [[1, 2], [3]]})
    df.write_json(str(tmp_path / "out"))
    back = dt.read_json(str(tmp_path / "out" / "*.json"))
    assert back.sort("x").to_pydict()["y"] == [[1, 2], [3]]


def test_partitioned_write(tmp_path):
    df = dt.from_pydict({"g": ["a", "a", "b"], "v": [1, 2, 3]})
    df.write_parquet(str(tmp_path / "out"), partition_cols=["g"])
    assert os.path.isdir(tmp_path / "out" / "g=a")
    back = dt.read_parquet(str(tmp_path / "out" / "**" / "*.parquet"),
                           hive_partitioning=True)
    d = back.sort("v").to_pydict()
    assert d["v"] == [1, 2, 3]
    assert d["g"] == ["a", "a", "b"]


def test_write_modes(tmp_path):
    df = dt.from_pydict({"x": [1]})
    df.write_parquet(str(tmp_path / "o"))
    df.write_parquet(str(tmp_path / "o"))  # append
    assert len(dt.read_parquet(str(tmp_path / "o")).to_pydict()["x"]) == 2
    df.write_parquet(str(tmp_path / "o"), write_mode="overwrite")
    assert len(dt.read_parquet(str(tmp_path / "o")).to_pydict()["x"]) == 1


def test_write_returns_paths(tmp_path):
    df = dt.from_pydict({"x": [1, 2]})
    res = df.write_parquet(str(tmp_path / "w"))
    paths = res.to_pydict()["path"]
    assert len(paths) >= 1 and all(p.endswith(".parquet") for p in paths)


def test_from_glob_path(pq_dir):
    df = dt.from_glob_path(pq_dir + "/*.parquet")
    d = df.to_pydict()
    assert len(d["path"]) == 4 and all(s > 0 for s in d["size"])


def test_scan_task_merging(pq_dir):
    from daft_tpu.io.scan import GlobScanOperator, Pushdowns
    op = GlobScanOperator(pq_dir + "/*.parquet", "parquet")
    tasks = op.to_scan_tasks(Pushdowns())
    # 4 tiny files merge into 1 task under the 96MB min-size target
    assert len(tasks) == 1
    assert len(tasks[0].paths) == 4


def test_csv_no_header(tmp_path):
    p = tmp_path / "x.csv"
    p.write_text("1,a\n2,b\n")
    df = dt.read_csv(str(p), has_headers=False)
    assert len(df.to_pydict()) == 2


# -- WARC (reference: src/daft-warc) ----------------------------------------

def _write_warc(path, gz=False):
    import gzip as _gz
    recs = []
    for i, (rtype, body) in enumerate([
            ("warcinfo", b"software: test\r\n"),
            ("request", b"GET / HTTP/1.1\r\nHost: example.com\r\n"),
            ("response", b"HTTP/1.1 200 OK\r\n\r\n<html>hello</html>")]):
        hdr = (f"WARC/1.1\r\n"
               f"WARC-Record-ID: <urn:uuid:0000-{i}>\r\n"
               f"WARC-Type: {rtype}\r\n"
               f"WARC-Date: 2024-01-0{i+1}T00:00:00Z\r\n"
               f"WARC-Target-URI: http://example.com/{i}\r\n"
               f"Content-Length: {len(body)}\r\n\r\n").encode()
        recs.append(hdr + body + b"\r\n\r\n")
    blob = b"".join(recs)
    with open(path, "wb") as f:
        f.write(_gz.compress(blob) if gz else blob)


@pytest.mark.parametrize("gz", [False, True])
def test_read_warc(tmp_path, gz):
    import daft_tpu as daft
    p = str(tmp_path / ("x.warc.gz" if gz else "x.warc"))
    _write_warc(p, gz)
    df = daft.read_warc(p)
    out = df.to_pydict()
    assert out["WARC-Type"] == ["warcinfo", "request", "response"]
    assert out["WARC-Record-ID"] == [f"<urn:uuid:0000-{i}>" for i in range(3)]
    assert out["warc_content"][2] == b"HTTP/1.1 200 OK\r\n\r\n<html>hello</html>"
    assert out["Content-Length"] == [16, 35, 37]
    import json as _json
    hdrs = _json.loads(out["warc_headers"][1])
    assert hdrs["WARC-Target-URI"] == "http://example.com/1"
    assert out["WARC-Date"][0].year == 2024


def test_read_warc_pushdowns(tmp_path):
    import daft_tpu as daft
    from daft_tpu import col
    p = str(tmp_path / "x.warc")
    _write_warc(p)
    out = (daft.read_warc(p)
           .where(col("WARC-Type") == "response")
           .select("WARC-Record-ID")
           .to_pydict())
    assert out == {"WARC-Record-ID": ["<urn:uuid:0000-2>"]}


def test_split_scan_tasks_by_row_group(tmp_path):
    """Oversized parquet files split into per-row-group-range tasks
    (reference: scan_task_iters/split_parquet)."""
    import pyarrow as pa
    import pyarrow.parquet as pq
    import daft_tpu as daft
    from daft_tpu.context import execution_config_ctx

    p = str(tmp_path / "big.parquet")
    t = pa.table({"x": list(range(10000)), "y": [float(i) for i in range(10000)]})
    pq.write_table(t, p, row_group_size=1000)  # 10 row groups

    with execution_config_ctx(scan_tasks_max_size_bytes=20_000,
                              scan_tasks_min_size_bytes=10_000):
        df = daft.read_parquet(p)
        assert df.num_partitions() > 1
        out = df.to_pydict()
    assert out["x"] == list(range(10000))
    # sum over split tasks must match
    with execution_config_ctx(scan_tasks_max_size_bytes=20_000):
        s = daft.read_parquet(p).sum("y").to_pydict()
    assert s["y"] == [sum(float(i) for i in range(10000))]
