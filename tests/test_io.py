"""IO tests: read/write roundtrips, pushdowns, scan-task merging
(reference model: ``tests/io/``)."""

import datetime
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import daft_tpu as dt
from daft_tpu import col


@pytest.fixture
def pq_dir(tmp_path):
    for i in range(4):
        t = pa.table({"a": np.arange(i * 10, (i + 1) * 10),
                      "b": [f"s{j}" for j in range(10)],
                      "d": [datetime.date(2020, 1, 1 + j) for j in range(10)]})
        pq.write_table(t, tmp_path / f"part{i}.parquet")
    return str(tmp_path)


def test_read_parquet_glob(pq_dir):
    df = dt.read_parquet(pq_dir + "/*.parquet")
    assert df.schema().column_names == ["a", "b", "d"]
    assert sorted(df.to_pydict()["a"]) == list(range(40))


def test_read_parquet_dir(pq_dir):
    df = dt.read_parquet(pq_dir)
    assert len(df.to_pydict()["a"]) == 40


def test_column_pushdown(pq_dir):
    df = dt.read_parquet(pq_dir + "/*.parquet").select("a")
    opt = df._builder.optimize()
    from daft_tpu.logical import plan as lp

    def find_source(n):
        if isinstance(n, lp.Source):
            return n
        for c in n.children:
            s = find_source(c)
            if s is not None:
                return s
        return None
    src = find_source(opt.plan)
    assert src.pushdowns.columns == ("a",)
    assert sorted(df.to_pydict()["a"]) == list(range(40))


def test_filter_pushdown_rowgroup_prune(pq_dir):
    df = dt.read_parquet(pq_dir + "/*.parquet").where(col("a") >= 35)
    assert sorted(df.to_pydict()["a"]) == list(range(35, 40))


def test_limit_pushdown(pq_dir):
    df = dt.read_parquet(pq_dir + "/*.parquet").limit(7)
    assert len(df.to_pydict()["a"]) == 7


def test_csv_roundtrip(tmp_path):
    df = dt.from_pydict({"x": [1, 2, 3], "y": ["a", "b", "c"]})
    df.write_csv(str(tmp_path / "out"))
    back = dt.read_csv(str(tmp_path / "out" / "*.csv"))
    assert back.sort("x").to_pydict() == {"x": [1, 2, 3], "y": ["a", "b", "c"]}


def test_json_roundtrip(tmp_path):
    df = dt.from_pydict({"x": [1, 2], "y": [[1, 2], [3]]})
    df.write_json(str(tmp_path / "out"))
    back = dt.read_json(str(tmp_path / "out" / "*.json"))
    assert back.sort("x").to_pydict()["y"] == [[1, 2], [3]]


def test_partitioned_write(tmp_path):
    df = dt.from_pydict({"g": ["a", "a", "b"], "v": [1, 2, 3]})
    df.write_parquet(str(tmp_path / "out"), partition_cols=["g"])
    assert os.path.isdir(tmp_path / "out" / "g=a")
    back = dt.read_parquet(str(tmp_path / "out" / "**" / "*.parquet"),
                           hive_partitioning=True)
    d = back.sort("v").to_pydict()
    assert d["v"] == [1, 2, 3]
    assert d["g"] == ["a", "a", "b"]


def test_write_modes(tmp_path):
    df = dt.from_pydict({"x": [1]})
    df.write_parquet(str(tmp_path / "o"))
    df.write_parquet(str(tmp_path / "o"))  # append
    assert len(dt.read_parquet(str(tmp_path / "o")).to_pydict()["x"]) == 2
    df.write_parquet(str(tmp_path / "o"), write_mode="overwrite")
    assert len(dt.read_parquet(str(tmp_path / "o")).to_pydict()["x"]) == 1


def test_write_returns_paths(tmp_path):
    df = dt.from_pydict({"x": [1, 2]})
    res = df.write_parquet(str(tmp_path / "w"))
    paths = res.to_pydict()["path"]
    assert len(paths) >= 1 and all(p.endswith(".parquet") for p in paths)


def test_from_glob_path(pq_dir):
    df = dt.from_glob_path(pq_dir + "/*.parquet")
    d = df.to_pydict()
    assert len(d["path"]) == 4 and all(s > 0 for s in d["size"])


def test_scan_task_merging(pq_dir):
    from daft_tpu.io.scan import GlobScanOperator, Pushdowns
    op = GlobScanOperator(pq_dir + "/*.parquet", "parquet")
    tasks = op.to_scan_tasks(Pushdowns())
    # 4 tiny files merge into 1 task under the 96MB min-size target
    assert len(tasks) == 1
    assert len(tasks[0].paths) == 4


def test_csv_no_header(tmp_path):
    p = tmp_path / "x.csv"
    p.write_text("1,a\n2,b\n")
    df = dt.read_csv(str(p), has_headers=False)
    assert len(df.to_pydict()) == 2
