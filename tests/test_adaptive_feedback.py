"""Self-tuning engine (round 20): the calibrated cost-model profile
(``device/calibration.py``) and distributed runtime re-planning
(``distributed/replan.py`` + the StageRunner wiring) — EWMA/floor/
persistence, constants-override plumbing, re-plan decision picks
(broadcast demotion, combine flips on mis-estimated NDV, estimate
rewrites), the ``adaptive`` stats block + ``/metrics``, serving
admission seeding from per-fingerprint history, the AdaptivePlanner
history bound, knob-off verbatim-static parity, and the extended
chaos-determinism contract (feedback state frozen, replay
bit-identical)."""

import os

import numpy as np
import pytest

import daft_tpu as dt
import daft_tpu.context as dctx
from daft_tpu import col
from daft_tpu.device import calibration as cal
from daft_tpu.device import costmodel
from daft_tpu.distributed import replan
from daft_tpu.distributed import resilience as rz
from daft_tpu.physical import adaptive
from daft_tpu.runners.distributed_runner import DistributedRunner


@pytest.fixture(autouse=True)
def _fresh_feedback_state():
    cal.reset_for_tests()
    adaptive.counters_reset()
    # pin the config mirrors to their defaults: the process-global
    # context may have been created while another test's env was set,
    # baking tpu_calibration/tpu_adaptive=True into it
    with dctx.execution_config_ctx(tpu_calibration=False,
                                   tpu_adaptive=False,
                                   tpu_calibration_dir=""):
        yield
    cal.reset_for_tests()
    adaptive.counters_reset()


def _run_distributed(q, num_workers=3):
    runner = DistributedRunner(num_workers=num_workers)
    old = dctx.get_context()._runner
    dctx.get_context().set_runner(runner)
    try:
        return q()
    finally:
        dctx.get_context().set_runner(old)
        if runner._manager is not None:
            runner._manager.shutdown()


# ------------------------------------------------------- calibration (a)

def test_ewma_update_and_sample_floor(monkeypatch, tmp_path):
    monkeypatch.setenv("DAFT_TPU_CALIBRATION", "1")
    monkeypatch.setenv("DAFT_TPU_CALIBRATION_MIN_SAMPLES", "3")
    monkeypatch.setenv("DAFT_TPU_CALIBRATION_ALPHA", "0.5")
    # below the floor: the default wins
    cal.observe("DEV_AGG_BPS", 1e9)
    cal.observe("DEV_AGG_BPS", 1e9)
    assert cal.const("DEV_AGG_BPS", 4e9) == 4e9
    cal.observe("DEV_AGG_BPS", 2e9)
    got = cal.const("DEV_AGG_BPS", 4e9)
    assert got != 4e9
    # EWMA with alpha 0.5: 1e9 -> 1e9 -> 1.5e9
    assert got == pytest.approx(1.5e9)
    s = cal.summary()["DEV_AGG_BPS"]
    assert s["active"] and s["samples"] == 3


def test_disabled_by_default_and_observe_noop():
    cal.observe("DEV_AGG_BPS", 1e9)
    assert not cal.enabled()
    assert cal.const("DEV_AGG_BPS", 4e9) == 4e9
    assert cal.summary()["DEV_AGG_BPS"]["samples"] == 0


def test_persistence_roundtrip(monkeypatch, tmp_path):
    monkeypatch.setenv("DAFT_TPU_CALIBRATION", "1")
    monkeypatch.setenv("DAFT_TPU_CALIBRATION_DIR", str(tmp_path))
    monkeypatch.setenv("DAFT_TPU_CALIBRATION_MIN_SAMPLES", "2")
    cal.observe("DEV_SORT_ROWS_PER_S", 9e6)
    cal.observe("DEV_SORT_ROWS_PER_S", 9e6)
    cal.flush()  # the atexit hook's path, invoked deterministically
    files = os.listdir(str(tmp_path))
    assert any(f.startswith("calibration_") and f.endswith(".json")
               for f in files), files
    learned = cal.const("DEV_SORT_ROWS_PER_S", 50e6)
    assert learned == pytest.approx(9e6)
    # a fresh process (reset) reloads the persisted per-backend profile
    cal.reset_for_tests()
    assert cal.const("DEV_SORT_ROWS_PER_S", 50e6) == pytest.approx(9e6)


def test_chaos_serialize_freezes_calibration(monkeypatch, tmp_path):
    monkeypatch.setenv("DAFT_TPU_CALIBRATION", "1")
    monkeypatch.setenv("DAFT_TPU_CALIBRATION_MIN_SAMPLES", "1")
    cal.observe("DEV_AGG_BPS", 1e9)
    assert cal.const("DEV_AGG_BPS", 4e9) == pytest.approx(1e9)
    monkeypatch.setenv("DAFT_TPU_CHAOS_SERIALIZE", "1")
    assert cal.frozen()
    # reads return defaults, observations are dropped
    assert cal.const("DEV_AGG_BPS", 4e9) == 4e9
    cal.observe("DEV_AGG_BPS", 2e9)
    monkeypatch.delenv("DAFT_TPU_CHAOS_SERIALIZE")
    assert cal.summary()["DEV_AGG_BPS"]["samples"] == 1


def test_active_fault_plan_freezes_calibration(monkeypatch):
    monkeypatch.setenv("DAFT_TPU_CALIBRATION", "1")
    monkeypatch.setenv("DAFT_TPU_FAULT_SPEC", "task:0.1")
    rz.reset_for_tests()
    try:
        assert cal.frozen()
        cal.observe("DEV_AGG_BPS", 1e9)
        assert cal.summary()["DEV_AGG_BPS"]["samples"] == 0
    finally:
        rz.reset_for_tests()


def test_ledger_record_feeds_observations(monkeypatch):
    monkeypatch.setenv("DAFT_TPU_CALIBRATION", "1")
    monkeypatch.setenv("DAFT_TPU_CALIBRATION_MIN_SAMPLES", "1")
    costmodel.ledger_record("grouped_agg", rows=1 << 16, nbytes=1 << 24,
                            seconds=0.1, strategy="hash")
    assert cal.const("DEV_AGG_HASH_BPS", 0.0) > 0
    costmodel.ledger_record("argsort", rows=1 << 16, nbytes=1 << 20,
                            seconds=0.05)
    assert cal.const("DEV_SORT_ROWS_PER_S", 0.0) > 0
    # tiny dispatches (RTT-dominated) are skipped
    before = cal.summary()["DEV_SORT_ROWS_PER_S"]["samples"]
    costmodel.ledger_record("argsort", rows=16, nbytes=128, seconds=0.01)
    assert cal.summary()["DEV_SORT_ROWS_PER_S"]["samples"] == before


def test_constants_override_changes_decision(monkeypatch):
    """The override plumbing end to end: a calibrated (much slower)
    device agg rate flips ``agg_upload_wins`` for a borderline dispatch
    that the hard-coded constants accept."""
    monkeypatch.setenv("DAFT_TPU_LINK_RTT_MS", "1")
    monkeypatch.setenv("DAFT_TPU_LINK_UP_MBPS", "1000")
    monkeypatch.setenv("DAFT_TPU_LINK_DOWN_MBPS", "1000")
    costmodel.reset_for_tests()
    try:
        nbytes = 64 << 20
        default_dec = costmodel.agg_upload_wins(nbytes, 1 << 10,
                                                cacheable=False)
        assert default_dec  # fast link + fast kernel: device wins
        monkeypatch.setenv("DAFT_TPU_CALIBRATION", "1")
        monkeypatch.setenv("DAFT_TPU_CALIBRATION_MIN_SAMPLES", "1")
        cal.observe("DEV_AGG_BPS", 1e6)  # observed: kernel is terrible
        assert not costmodel.agg_upload_wins(nbytes, 1 << 10,
                                             cacheable=False)
    finally:
        costmodel.reset_for_tests()


def test_ndv_ratio_damps_footer_evidence(monkeypatch):
    """A calibrated actual/footer NDV ratio flips ``shuffle_combine_wins``
    for footer evidence that reads near-unique but is 10x off."""
    rows, parts = 400_000, 4
    assert not costmodel.shuffle_combine_wins(rows, rows, parts)
    monkeypatch.setenv("DAFT_TPU_CALIBRATION", "1")
    monkeypatch.setenv("DAFT_TPU_CALIBRATION_MIN_SAMPLES", "1")
    cal.observe("NDV_FOOTER_RATIO", 0.05)
    assert costmodel.shuffle_combine_wins(rows, rows, parts)
    # EXACT evidence (measured by the re-planner) is never damped
    assert not costmodel.shuffle_combine_wins(rows, rows, parts,
                                              exact_groups=True)


def test_flight_history_ingest(monkeypatch, tmp_path):
    """A fresh process seeds its profile from the flight recorder's
    device_kernels blocks (the same evidence ledger_record observes
    live, recovered from disk)."""
    import json
    log = tmp_path / "queries.jsonl"
    entry = {"device_kernels": {"grouped_agg": {
        "dispatches": 4, "rows": 1 << 20, "bytes": float(1 << 26),
        "seconds": 0.5, "strategy": "sort"}}}
    log.write_text(json.dumps(entry) + "\n")
    monkeypatch.setenv("DAFT_TPU_QUERY_LOG", str(log))
    monkeypatch.setenv("DAFT_TPU_CALIBRATION", "1")
    monkeypatch.setenv("DAFT_TPU_CALIBRATION_MIN_SAMPLES", "1")
    n = cal.ingest_flight_history()
    assert n == 1
    assert cal.const("DEV_AGG_BPS", 0.0) > 0
    # idempotent: a second call ingests nothing
    assert cal.ingest_flight_history() == 0


# ------------------------------------------- distributed re-planning (b)

def _join_frames(n=60_000, k=1000):
    big = dt.from_pydict({"k": (np.arange(n) % k).tolist(),
                          "v": np.arange(n).tolist()}).into_partitions(4)
    small = dt.from_pydict({"k": list(range(k)),
                            "w": list(range(k))}).into_partitions(2)
    return big, small


def _join_q():
    big, small = _join_frames()
    return (big.join(small, on="k", strategy="hash")
            .groupby("k").agg(col("v").sum(), col("w").sum())
            .sort("k").to_pydict())


def _nearuniq_q(n=60_000):
    d = dt.from_pydict({"k": np.arange(n).tolist(),
                        "v": np.arange(n).tolist()}).into_partitions(4)
    return d.groupby("k").agg(col("v").sum()).sort("k").to_pydict()


def test_knob_off_is_verbatim_static(monkeypatch):
    """DAFT_TPU_ADAPTIVE unset: zero adaptive counters, identical
    results — the static path is untouched."""
    monkeypatch.setenv("DAFT_TPU_DEVICE", "0")
    ref = _run_distributed(_join_q)
    assert adaptive.counters_snapshot() == {}
    monkeypatch.setenv("DAFT_TPU_ADAPTIVE", "0")
    assert _run_distributed(_join_q) == ref
    assert adaptive.counters_snapshot() == {}


def test_broadcast_demotion_small_side(monkeypatch):
    """The measured-small join side demotes its hash boundary to a
    replicated gather — the SMALLER side, join-type gated — with
    identical results."""
    monkeypatch.setenv("DAFT_TPU_DEVICE", "0")
    ref = _run_distributed(_join_q)
    monkeypatch.setenv("DAFT_TPU_ADAPTIVE", "1")
    out = _run_distributed(_join_q)
    assert out == ref
    c = adaptive.counters_snapshot()
    assert c.get("broadcast_demotions") == 1
    assert c.get("est_rewrites", 0) >= 1
    # the decision names the demoted (small, right) side in the history
    hist = adaptive.last_planner().explain_analyze()
    assert "hash→broadcast_right" in hist


def test_no_demotion_for_outer_join_on_probe_side(monkeypatch):
    """A full-outer join tolerates no replicated side: no demotion."""
    monkeypatch.setenv("DAFT_TPU_DEVICE", "0")
    monkeypatch.setenv("DAFT_TPU_ADAPTIVE", "1")

    def q():
        big, small = _join_frames()
        return (big.join(small, on="k", how="outer", strategy="hash")
                .groupby("k").agg(col("v").sum(), col("w").sum())
                .sort("k").to_pydict())

    monkeypatch.delenv("DAFT_TPU_ADAPTIVE")
    ref = _run_distributed(q)
    monkeypatch.setenv("DAFT_TPU_ADAPTIVE", "1")
    out = _run_distributed(q)
    assert out == ref
    assert adaptive.counters_snapshot().get("broadcast_demotions") is None


def test_combine_flip_on_measured_near_unique_keys(monkeypatch):
    """Mis-estimated NDV, measured: with no cardinality evidence the
    static plan default-accepts the map-side combine; the re-planner
    measures the in-memory keys near-unique (exact NDV) and flips it
    OFF — saving the wasted map-side agg pass — with identical
    results."""
    monkeypatch.setenv("DAFT_TPU_DEVICE", "0")
    ref = _run_distributed(_nearuniq_q)
    monkeypatch.setenv("DAFT_TPU_ADAPTIVE", "1")
    costmodel.decision_counts.clear()
    out = _run_distributed(_nearuniq_q)
    assert out == ref
    c = adaptive.counters_snapshot()
    assert c.get("combine_flips") == 1
    assert c.get("ndv_measured", 0) >= 1
    d = costmodel.decision_counts.get("shuffle_combine")
    assert d and d["host"] >= 1  # the evidence-priced decision: decline


def test_est_rewrites_reach_fragment_nodes(monkeypatch):
    """The consumer fragment's HashJoin bytes estimates and Aggregate
    NDV evidence are rewritten from receipts before dispatch (the spill
    fanout and kernel-strategy inputs)."""
    from daft_tpu.distributed.replan import BoundaryActuals, StageReplanner
    from daft_tpu.distributed.stages import StagePlan
    from daft_tpu.physical.translate import translate

    monkeypatch.setenv("DAFT_TPU_DEVICE", "0")
    big, small = _join_frames(n=5000, k=50)
    plan = (big.join(small, on="k", strategy="hash")
            .groupby("k").agg(col("v").sum()))
    pplan = translate(plan._builder.optimize().plan)
    sp = StagePlan.from_physical(pplan)
    join_stage = next(
        s for s in sp.stages if s.boundaries
        and StageReplanner._join_side(s.plan, s.boundaries[0].upstream))
    monkeypatch.setenv("DAFT_TPU_ADAPTIVE", "1")
    rp = StageReplanner(sp)
    acts = {b.upstream: BoundaryActuals(rows=1000, nbytes=4096, ndv=50)
            for b in join_stage.boundaries}
    rp._rewrite_estimates(join_stage, acts)

    import daft_tpu.physical.plan as pp

    def find(n, t):
        if isinstance(n, t):
            return n
        for ch in n.children:
            r = find(ch, t)
            if r is not None:
                return r
        return None

    j = find(join_stage.plan, pp.HashJoin)
    assert j.left_bytes_est == 4096 and j.right_bytes_est == 4096
    assert adaptive.counters_snapshot().get("est_rewrites", 0) >= 2


def test_distributed_aqe_materialize_loop(monkeypatch):
    """``enable_aqe=True`` on the distributed runner runs the native
    runner's materialize-and-reoptimize loop THROUGH the stage runner:
    join inputs materialize distributed, re-plans land in the shared
    history, results match the static run."""
    monkeypatch.setenv("DAFT_TPU_DEVICE", "0")
    ref = _run_distributed(_join_q)
    with dctx.execution_config_ctx(enable_aqe=True):
        out = _run_distributed(_join_q)
    assert out == ref
    hist = adaptive.last_planner().explain_analyze()
    assert "materialized join input distributed" in hist


def test_adaptive_stats_block_and_metrics(monkeypatch):
    monkeypatch.setenv("DAFT_TPU_DEVICE", "0")
    monkeypatch.setenv("DAFT_TPU_ADAPTIVE", "1")
    _run_distributed(_join_q)
    from daft_tpu import observability as obs
    from daft_tpu import tracing
    stats = obs.last_query_stats()
    assert stats.adaptive.get("broadcast_demotions") == 1
    rendered = stats.render()
    assert "adaptive (self-tuning):" in rendered
    assert "broadcast_demotions=1" in rendered
    text = tracing.prometheus_text()
    parsed = tracing.parse_prometheus_text(text)
    assert parsed.get("daft_tpu_adaptive_broadcast_demotions_total",
                      0) >= 1
    # flight-recorder entries carry the block
    entry = obs.flight_entry(stats)
    assert entry["adaptive"].get("broadcast_demotions") == 1


def test_calibrated_constants_listed_in_render(monkeypatch):
    monkeypatch.setenv("DAFT_TPU_CALIBRATION", "1")
    monkeypatch.setenv("DAFT_TPU_CALIBRATION_MIN_SAMPLES", "1")
    cal.observe("DEV_AGG_BPS", 1e9)
    from daft_tpu import observability as obs
    lines = obs.render_adaptive_block({})
    joined = "\n".join(lines)
    assert "calibrated constants" in joined and "DEV_AGG_BPS" in joined
    assert cal.calibrated_names() == ["DEV_AGG_BPS"]


# --------------------------------------- chaos-determinism contract (r20)

def test_feedback_knobs_do_not_perturb_chaos_replay(monkeypatch):
    """The extended chaos contract: with DAFT_TPU_ADAPTIVE=1 and
    DAFT_TPU_CALIBRATION=1 both ON, a chaos-serialized seeded run
    replays the SAME fault events and answer as with them OFF — the
    feedback state is frozen (no observations, no re-plans)."""
    monkeypatch.setenv("DAFT_TPU_DEVICE", "0")
    monkeypatch.setenv("DAFT_TPU_DISTRIBUTED_SHUFFLE", "flight")
    monkeypatch.setenv("DAFT_TPU_FAULT_SPEC", "task:0.08,fetch:0.08")
    monkeypatch.setenv("DAFT_TPU_FAULT_SEED", "7")
    monkeypatch.setenv("DAFT_TPU_RETRY_BACKOFF", "0.01")
    monkeypatch.setenv("DAFT_TPU_CHAOS_SERIALIZE", "1")
    monkeypatch.setenv("DAFT_TPU_SPECULATIVE_MULTIPLIER", "0")

    def one_run(knobs):
        for k, v in knobs.items():
            monkeypatch.setenv(k, v)
        rz.reset_for_tests()
        adaptive.counters_reset()
        out = _run_distributed(_join_q)
        return out, sorted(rz.fault_events())

    out1, ev1 = one_run({"DAFT_TPU_ADAPTIVE": "0",
                         "DAFT_TPU_CALIBRATION": "0"})
    out2, ev2 = one_run({"DAFT_TPU_ADAPTIVE": "1",
                         "DAFT_TPU_CALIBRATION": "1"})
    assert ev1, "the fixed spec/seed injected nothing — tune the seed"
    assert ev1 == ev2
    assert out1 == out2
    # frozen means FROZEN: no observations, no re-plan decisions
    c = adaptive.counters_snapshot()
    assert c.get("calibration_observations") is None
    assert not any(k for k in c
                   if k not in ("replan_frozen",)), c
    rz.reset_for_tests()


def test_replan_disabled_under_active_fault_plan(monkeypatch):
    monkeypatch.setenv("DAFT_TPU_ADAPTIVE", "1")
    monkeypatch.setenv("DAFT_TPU_FAULT_SPEC", "task:0.01")
    rz.reset_for_tests()
    try:
        assert not replan.adaptive_enabled()
        assert adaptive.counters_snapshot().get("replan_frozen") == 1
    finally:
        rz.reset_for_tests()


# -------------------------------------------------- history bound (sat 1)

def test_adaptive_planner_history_is_bounded(monkeypatch):
    monkeypatch.setenv("DAFT_TPU_ADAPTIVE_HISTORY", "5")
    p = adaptive.AdaptivePlanner(dctx.get_context().execution_config)
    for i in range(12):
        p.record_replan(f"decision {i}")
    assert len(p.history) == 5
    assert p.evictions == 7
    assert p.history[0].decision == "decision 7"  # oldest evicted first
    assert adaptive.counters_snapshot().get("history_evictions") == 7
    assert "7 oldest entries evicted" in p.explain_analyze()


def test_history_cap_config_mirror(monkeypatch):
    monkeypatch.delenv("DAFT_TPU_ADAPTIVE_HISTORY", raising=False)
    with dctx.execution_config_ctx(tpu_adaptive_history=3):
        assert adaptive.history_cap() == 3
    monkeypatch.setenv("DAFT_TPU_ADAPTIVE_HISTORY", "9")
    with dctx.execution_config_ctx(tpu_adaptive_history=3):
        assert adaptive.history_cap() == 9  # env overrides


# ------------------------------------------- admission seeding (sat 2/4c)

def test_admission_estimate_seeded_from_history(monkeypatch, tmp_path):
    """ROADMAP 4c (minimal): when the cost model is blind, a repeat
    query's admission estimate comes from the per-fingerprint observed
    result bytes instead of the flat 64 MiB default."""
    from daft_tpu.logical import stats as lstats
    from daft_tpu.serving import QueryScheduler
    from daft_tpu.serving import scheduler as sched_mod

    root = tmp_path / "t"
    dt.from_pydict({"g": [i % 5 for i in range(4000)],
                    "v": [float(i) for i in range(4000)]}) \
        .write_parquet(str(root))
    glob = str(root / "*.parquet")

    def q():
        return dt.read_parquet(glob).groupby("g") \
            .agg(col("v").sum().alias("s")).sort("g")

    # blind the cost model so the history path is the only evidence
    monkeypatch.setattr(lstats, "estimate",
                        lambda plan: lstats.Stats(None, None))
    s = QueryScheduler(concurrency=1, result_cache_bytes=0)
    try:
        h1 = s.submit(q())
        h1.result(60)
        assert h1._fp_hist_key is not None
        # first (cold) submission used the flat default
        assert s.counters_snapshot().get("est_seeded_history") is None
        h2 = s.submit(q())
        h2.result(60)
        assert s.counters_snapshot().get("est_seeded_history") == 1
        # the recorded observation is the real result size, not 64 MiB
        with s._hist_lock:
            (bytes_ewma, wall_us, n) = s._fp_hist[h1._fp_hist_key]
        assert n == 2 and 0 < bytes_ewma < sched_mod._DEFAULT_EST_BYTES
    finally:
        s.shutdown()


def test_exact_rewrite_never_observed_as_footer_ratio(monkeypatch):
    """Review regression: when the re-planner rewrote an Aggregate's NDV
    from EXACT measured evidence (no original footer existed), the
    observed actual/exact ratio ≈ 1.0 must NOT feed NDV_FOOTER_RATIO —
    it would EWMA-erase the learned damping."""
    monkeypatch.setenv("DAFT_TPU_DEVICE", "0")
    monkeypatch.setenv("DAFT_TPU_ADAPTIVE", "1")
    monkeypatch.setenv("DAFT_TPU_CALIBRATION", "1")
    monkeypatch.setenv("DAFT_TPU_CALIBRATION_MIN_SAMPLES", "1")
    # in-memory group-by: no footer evidence, measured-NDV rewrite runs
    _run_distributed(_nearuniq_q)
    assert cal.summary()["NDV_FOOTER_RATIO"]["samples"] == 0


def test_history_key_distinguishes_datasets(monkeypatch, tmp_path):
    """Review regression: same-shape queries over DIFFERENT datasets
    must not share one admission-history key (a small table's observed
    bytes would under-admit the big one)."""
    from daft_tpu.serving.scheduler import _history_fingerprint
    keys = []
    for name, rows in (("a", 100), ("b", 100)):
        root = tmp_path / name
        dt.from_pydict({"g": [i % 5 for i in range(rows)],
                        "v": [float(i) for i in range(rows)]}) \
            .write_parquet(str(root))
        q = dt.read_parquet(str(root / "*.parquet")).groupby("g") \
            .agg(col("v").sum().alias("s"))
        keys.append(_history_fingerprint(q._builder))
    assert keys[0] is not None and keys[1] is not None
    assert keys[0] != keys[1]


def test_admission_history_seeds_from_flight_recorder(monkeypatch,
                                                      tmp_path):
    """A fresh scheduler seeds its per-fingerprint history from
    flight-recorder serving blocks of earlier processes."""
    import json

    from daft_tpu.logical import stats as lstats
    from daft_tpu.serving import QueryScheduler
    log = tmp_path / "q.jsonl"
    key = "abcd1234abcd1234"
    log.write_text(json.dumps({
        "serving": {"fp_hist_key": key, "result_bytes": 5 << 20,
                    "run_us": 1000}}) + "\n")
    monkeypatch.setenv("DAFT_TPU_QUERY_LOG", str(log))
    s = QueryScheduler(concurrency=1)
    try:
        est = s._history_estimate(key)
        assert est == 5 << 20
    finally:
        s.shutdown()
