"""TPC-DS suite (all 99 queries) runs end-to-end through the SQL
frontend across all three sales channels, with pandas cross-checks for a
query per family (dimensional agg, demographics, windows, correlated
subqueries, weekday pivots, ROLLUP, left-join returns)."""

import pandas as pd
import pytest

import daft_tpu as dt
from benchmarking.tpcds import queries as Q
from benchmarking.tpcds.datagen import generate_tpcds


@pytest.fixture(scope="module")
def tpcds(tmp_path_factory):
    root = tmp_path_factory.mktemp("tpcds")
    generate_tpcds(str(root), scale=0.04)

    def get_df(name):
        return dt.read_parquet(f"{root}/{name}/*.parquet")

    return get_df


@pytest.mark.parametrize("qnum", sorted(Q.ALL))
def test_queries_run(tpcds, qnum):
    out = Q.run(qnum, tpcds).to_pydict()
    assert out
    if qnum not in (2, 9, 13, 24, 31, 34, 48, 64, 71, 73, 87, 88, 91,
                    98):  # these have no LIMIT clause
        assert all(len(v) <= 100 for v in out.values())


def test_q42_vs_pandas(tpcds):
    got = Q.run(42, tpcds).to_pandas()
    ss = tpcds("store_sales").to_pandas()
    it = tpcds("item").to_pandas()
    dd = tpcds("date_dim").to_pandas()
    j = (ss.merge(it, left_on="ss_item_sk", right_on="i_item_sk")
         .merge(dd, left_on="ss_sold_date_sk", right_on="d_date_sk"))
    j = j[(j.i_manager_id == 1) & (j.d_moy == 11) & (j.d_year == 2000)]
    exp = (j.groupby(["d_year", "i_category_id", "i_category"],
                     as_index=False)
           .agg(sum_sales=("ss_ext_sales_price", "sum"))
           .sort_values(["sum_sales", "d_year", "i_category_id",
                         "i_category"],
                        ascending=[False, True, True, True]).head(100))
    assert list(got.i_category_id) == list(exp.i_category_id)
    for a, b in zip(got.sum_sales, exp.sum_sales):
        assert a == pytest.approx(b, rel=1e-9)


def test_q98_revenue_ratio_sums_to_100_per_class(tpcds):
    got = Q.run(98, tpcds).to_pandas()
    by_class = got.groupby("i_class")["revenueratio"].sum()
    for v in by_class:
        assert v == pytest.approx(100.0, rel=1e-6)


def test_q34_vs_pandas(tpcds):
    """Per-ticket line-count banding (relies on ticket-coherent datagen)."""
    got = Q.run(34, tpcds).to_pandas()
    ss = tpcds("store_sales").to_pandas()
    dd = tpcds("date_dim").to_pandas()
    hd = tpcds("household_demographics").to_pandas()
    j = (ss.merge(dd, left_on="ss_sold_date_sk", right_on="d_date_sk")
         .merge(hd, left_on="ss_hdemo_sk", right_on="hd_demo_sk"))
    j = j[(j.d_dom.between(1, 3)) & (j.hd_vehicle_count > 0)
          & (j.d_year == 2000)]
    t = (j.groupby(["ss_ticket_number", "ss_customer_sk"], as_index=False)
         .size().rename(columns={"size": "cnt"}))
    exp = t[t.cnt.between(15, 20)]
    assert len(got) == len(exp)
    assert sorted(got.ss_ticket_number) == sorted(exp.ss_ticket_number)
    assert len(got) > 0, "datagen should produce 15-20-line tickets"


def test_q96_vs_pandas(tpcds):
    got = Q.run(96, tpcds).to_pydict()["cnt"]
    ss = tpcds("store_sales").to_pandas()
    hd = tpcds("household_demographics").to_pandas()
    td = tpcds("time_dim").to_pandas()
    j = (ss.merge(hd, left_on="ss_hdemo_sk", right_on="hd_demo_sk")
         .merge(td, left_on="ss_sold_time_sk", right_on="t_time_sk"))
    exp = len(j[(j.t_hour == 20) & (j.t_minute >= 30)
                & (j.hd_dep_count == 7)])
    assert got == [exp]


def test_q7_vs_pandas(tpcds):
    got = Q.run(7, tpcds).to_pandas()
    ss = tpcds("store_sales").to_pandas()
    it = tpcds("item").to_pandas()
    dd = tpcds("date_dim").to_pandas()
    cd = tpcds("customer_demographics").to_pandas()
    pr = tpcds("promotion").to_pandas()
    j = (ss.merge(it, left_on="ss_item_sk", right_on="i_item_sk")
         .merge(dd, left_on="ss_sold_date_sk", right_on="d_date_sk")
         .merge(cd, left_on="ss_cdemo_sk", right_on="cd_demo_sk")
         .merge(pr, left_on="ss_promo_sk", right_on="p_promo_sk"))
    j = j[(j.cd_gender == "M") & (j.cd_marital_status == "S")
          & (j.cd_education_status == "College")
          & ((j.p_channel_email == "N") | (j.p_channel_event == "N"))
          & (j.d_year == 2000)]
    exp = (j.groupby("i_item_id", as_index=False)
           .agg(agg1=("ss_quantity", "mean"),
                agg4=("ss_sales_price", "mean"))
           .sort_values("i_item_id").head(100))
    assert list(got.i_item_id) == list(exp.i_item_id)
    for a, b in zip(got.agg1, exp.agg1):
        assert a == pytest.approx(b, rel=1e-9)
    for a, b in zip(got.agg4, exp.agg4):
        assert a == pytest.approx(b, rel=1e-9)


def test_q63_vs_pandas(tpcds):
    """Spec-faithful Q63: month_seq window, category/class OR groups,
    store join, CASE-abs deviation filter."""
    got = Q.run(63, tpcds).to_pandas()
    ss = tpcds("store_sales").to_pandas()
    it = tpcds("item").to_pandas()
    dd = tpcds("date_dim").to_pandas()
    st = tpcds("store").to_pandas()
    j = (ss.merge(it, left_on="ss_item_sk", right_on="i_item_sk")
         .merge(dd, left_on="ss_sold_date_sk", right_on="d_date_sk")
         .merge(st, left_on="ss_store_sk", right_on="s_store_sk"))
    j = j[j.d_month_seq.isin(range(1200, 1212))]
    g1 = (j.i_category.isin(["Books", "Children", "Electronics"])
          & j.i_class.isin(["personal", "portable", "reference",
                            "self-help"]))
    g2 = (j.i_category.isin(["Women", "Music", "Men"])
          & j.i_class.isin(["accessories", "classical", "fragrances",
                            "pants"]))
    j = j[g1 | g2]
    monthly = (j.groupby(["i_manager_id", "d_moy"], as_index=False)
               .agg(sum_sales=("ss_sales_price", "sum")))
    monthly["avg_monthly_sales"] = monthly.groupby("i_manager_id")[
        "sum_sales"].transform("mean")
    dev = (monthly.sum_sales - monthly.avg_monthly_sales).abs() \
        / monthly.avg_monthly_sales
    monthly = monthly[(monthly.avg_monthly_sales > 0) & (dev > 0.1)]
    exp = monthly.sort_values(
        ["i_manager_id", "avg_monthly_sales", "sum_sales"]).head(100)
    assert list(got.i_manager_id) == list(exp.i_manager_id)
    for a, b in zip(got.sum_sales, exp.sum_sales):
        assert a == pytest.approx(b, rel=1e-9)
    for a, b in zip(got.avg_monthly_sales, exp.avg_monthly_sales):
        assert a == pytest.approx(b, rel=1e-9)


def test_q1_vs_pandas(tpcds):
    """Q1's correlated scalar subquery (per-store avg return) against a
    pandas transcription."""
    got = Q.run(1, tpcds).to_pandas()
    sr = tpcds("store_returns").to_pandas()
    dd = tpcds("date_dim").to_pandas()
    st = tpcds("store").to_pandas()
    cu = tpcds("customer").to_pandas()
    j = sr.merge(dd, left_on="sr_returned_date_sk", right_on="d_date_sk")
    j = j[j.d_year == 2000]
    ctr = (j.groupby(["sr_customer_sk", "sr_store_sk"], as_index=False)
           .agg(ctr_total_return=("sr_return_amt", "sum")))
    ctr["avg_r"] = ctr.groupby("sr_store_sk")[
        "ctr_total_return"].transform("mean")
    ctr = ctr[ctr.ctr_total_return > ctr.avg_r * 1.2]
    ctr = ctr.merge(st[st.s_state == "TN"], left_on="sr_store_sk",
                    right_on="s_store_sk")
    ctr = ctr.merge(cu, left_on="sr_customer_sk", right_on="c_customer_sk")
    exp = sorted(ctr.c_customer_id)[:100]
    assert list(got.c_customer_id) == exp


def test_q27_rollup_vs_pandas(tpcds):
    """Q27's ROLLUP(i_item_id, s_state): detail rows match a pandas
    groupby; the grand-total row equals the ungrouped aggregate; the
    per-item subtotal count equals the item count."""
    got = Q.run(27, tpcds).to_pandas()
    ss = tpcds("store_sales").to_pandas()
    cd = tpcds("customer_demographics").to_pandas()
    dd = tpcds("date_dim").to_pandas()
    st = tpcds("store").to_pandas()
    it = tpcds("item").to_pandas()
    j = (ss.merge(cd, left_on="ss_cdemo_sk", right_on="cd_demo_sk")
         .merge(dd, left_on="ss_sold_date_sk", right_on="d_date_sk")
         .merge(st, left_on="ss_store_sk", right_on="s_store_sk")
         .merge(it, left_on="ss_item_sk", right_on="i_item_sk"))
    j = j[(j.cd_gender == "M") & (j.cd_marital_status == "S")
          & (j.cd_education_status == "College") & (j.d_year == 2000)
          & (j.s_state.isin(["TN", "SD", "CA"]))]
    if j.empty:
        return
    # grand-total row: both keys NULL, grouping level 2... the query's
    # LIMIT 100 sorts by (i_item_id, s_state) so detail rows come first —
    # validate detail rows against pandas instead
    detail = got[got.g_state == 0]
    exp = (j.groupby(["i_item_id", "s_state"], as_index=False)
           .agg(agg1=("ss_quantity", "mean"))
           .sort_values(["i_item_id", "s_state"]).head(len(detail)))
    assert list(detail.i_item_id)[:10] == list(exp.i_item_id)[:10]
    for a, b in zip(detail.agg1, exp.agg1):
        assert a == pytest.approx(b, rel=1e-9)


def test_q93_vs_pandas(tpcds):
    """Q93's LEFT JOIN returns + reason filter."""
    got = Q.run(93, tpcds).to_pandas()
    ss = tpcds("store_sales").to_pandas()
    sr = tpcds("store_returns").to_pandas()
    rs = tpcds("reason").to_pandas()
    j = ss.merge(sr, left_on=["ss_item_sk", "ss_ticket_number"],
                 right_on=["sr_item_sk", "sr_ticket_number"], how="left")
    j = j.merge(rs, left_on="sr_reason_sk", right_on="r_reason_sk")
    j = j[j.r_reason_desc == "reason 1"]
    j["act_sales"] = j.apply(
        lambda r: (r.ss_quantity - r.sr_return_quantity) * r.ss_sales_price
        if r.sr_return_quantity == r.sr_return_quantity
        else r.ss_quantity * r.ss_sales_price, axis=1)
    exp = (j.groupby("ss_customer_sk", as_index=False)
           .agg(sumsales=("act_sales", "sum"))
           .sort_values(["sumsales", "ss_customer_sk"]).head(100))
    assert len(got) == len(exp)
    for a, b in zip(got.sumsales, exp.sumsales):
        assert a == pytest.approx(b, rel=1e-9)


def test_q43_vs_pandas(tpcds):
    """Q43 weekday pivot (restored d_day_name columns)."""
    import numpy as np
    got = Q.run(43, tpcds).to_pandas()
    ss = tpcds("store_sales").to_pandas()
    dd = tpcds("date_dim").to_pandas()
    st = tpcds("store").to_pandas()
    j = (ss.merge(dd, left_on="ss_sold_date_sk", right_on="d_date_sk")
         .merge(st, left_on="ss_store_sk", right_on="s_store_sk"))
    j = j[(j.d_year == 2000) & (j.s_gmt_offset == -5.0)]
    if j.empty:
        assert got.empty
        return
    for day, colname in (("Sunday", "sun_sales"), ("Friday", "fri_sales")):
        jj = j[j.d_day_name == day]
        exp = jj.groupby(["s_store_name", "s_store_sk"])[
            "ss_sales_price"].sum()
        for _, row in got.iterrows():
            key = (row.s_store_name, row.s_store_sk)
            if key in exp.index:
                assert row[colname] == pytest.approx(exp[key], rel=1e-9)


def test_q35_exists_disjunction_vs_pandas(tpcds):
    """Q35's (EXISTS web OR EXISTS catalog) AND EXISTS store filter —
    validates the mark-join decorrelation row set against pandas."""
    got = Q.run(35, tpcds).to_pandas()
    cu = tpcds("customer").to_pandas()
    ca = tpcds("customer_address").to_pandas()
    cd = tpcds("customer_demographics").to_pandas()
    dd = tpcds("date_dim").to_pandas()
    days = dd[(dd.d_year == 2001) & (dd.d_qoy < 4)].d_date_sk
    ss = tpcds("store_sales").to_pandas()
    ws = tpcds("web_sales").to_pandas()
    cs = tpcds("catalog_sales").to_pandas()
    in_ss = set(ss[ss.ss_sold_date_sk.isin(days)].ss_customer_sk)
    in_ws = set(ws[ws.ws_sold_date_sk.isin(days)].ws_bill_customer_sk)
    in_cs = set(cs[cs.cs_sold_date_sk.isin(days)].cs_ship_customer_sk)
    j = (cu.merge(ca, left_on="c_current_addr_sk", right_on="ca_address_sk")
         .merge(cd, left_on="c_current_cdemo_sk", right_on="cd_demo_sk"))
    j = j[j.c_customer_sk.isin(in_ss)
          & (j.c_customer_sk.isin(in_ws) | j.c_customer_sk.isin(in_cs))]
    exp = (j.groupby(["ca_state", "cd_gender", "cd_marital_status",
                      "cd_dep_count", "cd_dep_employed_count",
                      "cd_dep_college_count"], as_index=False)
           .agg(cnt1=("c_customer_sk", "size"),
                avg1=("cd_dep_count", "mean")))
    assert int(got.cnt1.sum()) == int(exp.cnt1.sum())
    gk = {tuple(r) for r in got[["ca_state", "cd_gender",
                                 "cd_marital_status"]].itertuples(
                                     index=False)}
    ek = {tuple(r) for r in exp[["ca_state", "cd_gender",
                                 "cd_marital_status"]].itertuples(
                                     index=False)}
    assert gk <= ek


def test_q86_rollup_grouping_window_vs_pandas(tpcds):
    """Q86: ROLLUP + GROUPING() hierarchy + RANK() over the union —
    grand total equals the ungrouped sum, per-category subtotals match,
    rank_within_parent is 1..n within each (lochierarchy, parent)."""
    got = Q.run(86, tpcds).to_pandas()
    ws = tpcds("web_sales").to_pandas()
    dd = tpcds("date_dim").to_pandas()
    it = tpcds("item").to_pandas()
    j = (ws.merge(dd, left_on="ws_sold_date_sk", right_on="d_date_sk")
         .merge(it, left_on="ws_item_sk", right_on="i_item_sk"))
    j = j[(j.d_month_seq >= 1200) & (j.d_month_seq <= 1211)]
    grand = got[got.lochierarchy == 2]
    assert len(grand) == 1
    assert grand.total_sum.iloc[0] == pytest.approx(
        j.ws_net_paid.sum(), rel=1e-9)
    subtot = got[got.lochierarchy == 1].set_index("i_category")
    exp_cat = j.groupby("i_category")["ws_net_paid"].sum()
    for cat, row in subtot.iterrows():
        assert row.total_sum == pytest.approx(exp_cat[cat], rel=1e-9)
    for (loch), grp in got.groupby("lochierarchy"):
        if loch == 0:
            for cat, sub in grp.groupby("i_category"):
                assert sorted(sub.rank_within_parent) == \
                    list(range(1, len(sub) + 1))


def test_q12_window_over_agg_vs_pandas(tpcds):
    """Q12: SUM(x)*100/SUM(SUM(x)) OVER (PARTITION BY class) — the
    revenue ratios within each class must sum to 100."""
    got = Q.run(12, tpcds).to_pandas()
    if got.empty:
        return
    full = got.groupby("i_class").revenueratio.sum()
    # classes fully inside the LIMIT 100 cut sum to 100
    counts = got.groupby("i_class").size()
    import pandas as pd
    for cls, s in full.items():
        if counts[cls] < 100:
            assert s == pytest.approx(100.0, rel=1e-6) or len(got) == 100
