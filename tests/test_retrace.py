"""Shape discipline at runtime: the retrace sanitizer, the size-class
ladder, exchange program memoization, and AOT warm-up (round 16).

The contract under test: a registered dispatch site re-traces only when
its declared signature changes — two literal-different row counts in one
size class share ONE fragment trace (a structure hit, not a retrace),
and a same-shape mesh exchange re-enters the memoized collective program
with zero new trace events.
"""

import dataclasses

import numpy as np
import pytest

import daft_tpu
from daft_tpu import col
from daft_tpu.analysis import dispatch_registry
from daft_tpu.analysis import retrace_sanitizer as rs


# --------------------------------------------------------- unit: budgets

def _traced_dispatch(san, site, key):
    """Simulate one dispatch that traces once (the first TRACE event in
    a scope charges; nested events don't)."""
    san.push(site, key)
    san.note_event(rs.TRACE_EVENT, 0.01)
    san.note_event(rs.TRACE_EVENT, 0.001)   # nested jit boundary
    san.pop()


def test_budget_violation_detected_and_attributed():
    san = rs.RetraceSanitizer(budget_multiplier=1)
    key = ("prog", 128, "sort")
    _traced_dispatch(san, "fragment.packed", key)
    assert san.summary()["violations"] == []
    # the SAME signature tracing again is the retrace tax
    _traced_dispatch(san, "fragment.packed", key)
    v = san.summary()["violations"]
    assert len(v) == 1
    # attribution names the dispatch site AND its declared contract
    assert "fragment.packed" in v[0]
    assert dispatch_registry.site("fragment.packed").budget in v[0]
    # a third trace doesn't duplicate the violation entry
    _traced_dispatch(san, "fragment.packed", key)
    assert len(san.summary()["violations"]) == 1


def test_distinct_signatures_do_not_violate():
    san = rs.RetraceSanitizer(budget_multiplier=1)
    for cap in (128, 256, 512):
        _traced_dispatch(san, "fragment.packed", ("prog", cap, "sort"))
    assert san.summary()["violations"] == []
    assert san.summary()["site_traces"]["fragment.packed"] == 3


def test_budget_multiplier_relaxes():
    san = rs.RetraceSanitizer(budget_multiplier=2)
    key = ("prog", 128, "sort")
    _traced_dispatch(san, "fragment.packed", key)
    _traced_dispatch(san, "fragment.packed", key)
    assert san.summary()["violations"] == []
    _traced_dispatch(san, "fragment.packed", key)
    assert len(san.summary()["violations"]) == 1


def test_exempt_site_never_violates():
    san = rs.RetraceSanitizer()
    for _ in range(5):
        _traced_dispatch(san, "warmup.aot", ("kernels", 128))
    assert san.summary()["violations"] == []


def test_nested_trace_events_charge_once():
    san = rs.RetraceSanitizer()
    san.push("fragment.packed", ("p", 1))
    for _ in range(20):       # one dispatch tracing through 20 inner jits
        san.note_event(rs.TRACE_EVENT, 0.001)
    san.pop()
    s = san.summary()
    assert s["site_traces"]["fragment.packed"] == 1
    assert s["traces"] == 20
    assert s["violations"] == []


def test_unscoped_traces_counted_not_enforced():
    san = rs.RetraceSanitizer()
    for _ in range(3):
        san.note_event(rs.TRACE_EVENT, 0.001)
    s = san.summary()
    assert s["unscoped_traces"] == 3
    assert s["violations"] == []


def test_compile_events_accumulate_seconds():
    san = rs.RetraceSanitizer()
    san.note_event(rs.COMPILE_EVENT, 1.5)
    san.note_event(rs.COMPILE_EVENT, 0.5)
    s = san.summary()
    assert s["compiles"] == 2
    assert s["compile_seconds"] == pytest.approx(2.0)
    assert "2 XLA compiles" in san.report()


def test_off_by_default_is_allocation_free():
    if rs.is_enabled():
        pytest.skip("retrace sanitizer armed for this session")
    # the disarmed scope is one shared singleton — no per-dispatch
    # allocation on the hot path
    a = rs.dispatch_scope("fragment.packed", ("k", 1))
    b = rs.dispatch_scope("kernels.argsort", ("k", 2))
    assert a is b is rs._NOOP
    assert rs.counters_snapshot() == {}
    assert rs.summary() == {}


# ------------------------------------------------- enable/disable global

def _armed(multiplier=1):
    """Arm the GLOBAL sanitizer for one test, restoring prior state."""
    class _Ctx:
        def __enter__(self):
            self.was = rs.is_enabled()
            if not self.was:
                rs.enable(multiplier)
            return rs.sanitizer()

        def __exit__(self, *exc):
            if not self.was:
                rs.disable()
            return False
    return _Ctx()


def test_enable_hooks_real_jax_traces():
    import jax
    import jax.numpy as jnp
    # deltas, not absolutes: under a session-armed sanitizer the global
    # books already carry every earlier test's dispatches
    with _armed() as san:
        t0 = san.summary()["traces"]
        s0 = san.summary()["site_traces"].get("pipeline.mask", 0)
        v0 = len(san.summary()["violations"])
        fn = jax.jit(lambda x: x + 1)
        with rs.dispatch_scope("pipeline.mask", ("t", 16)):
            fn(jnp.zeros(16))
        mid = san.summary()
        assert mid["traces"] > t0
        assert mid["site_traces"].get("pipeline.mask", 0) == s0 + 1
        # same shapes again: jit cache hit, NO new trace events
        with rs.dispatch_scope("pipeline.mask", ("t", 16)):
            fn(jnp.zeros(16))
        assert san.summary()["site_traces"]["pipeline.mask"] == s0 + 1
        assert len(san.summary()["violations"]) == v0


def test_scoped_callable_charges_after_enable():
    import jax
    import jax.numpy as jnp
    # programs built while DISARMED still get charged once armed
    wrapped = rs.scoped_callable("exchange.shard_map", ("k",),
                                 jax.jit(lambda x: x * 2))
    with _armed() as san:
        before = san.summary()["site_traces"].get("exchange.shard_map", 0)
        wrapped(jnp.ones(8))
        assert san.summary()["site_traces"].get(
            "exchange.shard_map", 0) == before + 1


# ------------------------------------------- exchange memo (satellite 1)

def test_exchange_same_shape_reuses_one_trace():
    """Regression for parallel/exchange.py:49: two same-shape mesh
    exchanges must share ONE trace — the memoized collective program
    re-enters jax's cache instead of re-tracing per call."""
    from daft_tpu.parallel import exchange, mesh as M
    m = M.get_mesh()
    if m is None:
        pytest.skip("no device mesh")
    n = m.shape["data"]
    keys = (np.arange(n * 128, dtype=np.int64) % 7)
    vals = np.ones(n * 128)
    mask = np.ones(n * 128, bool)
    ks = exchange.shard_blocks(m, keys)
    vs = exchange.shard_blocks(m, vals)
    ms = exchange.shard_blocks(m, mask)
    with _armed() as san:
        exchange.sharded_grouped_sum(m, ks, vs, ms)
        t1 = san.summary()["traces"]
        c1 = dict(exchange.exchange_cache_counters())
        exchange.sharded_grouped_sum(m, ks, vs, ms)
        t2 = san.summary()["traces"]
        c2 = exchange.exchange_cache_counters()
    assert t2 == t1, "second same-shape exchange re-traced"
    assert c2["hits"] >= c1["hits"] + 1


def test_exchange_cache_key_covers_closure_params():
    """Different closure captures (op tuples, plane counts) must NOT
    collide in the program cache."""
    from daft_tpu.parallel import exchange

    def mk(npl):
        def f(x):
            return x * npl
        return f

    k1 = exchange._program_key(mk(1), None, ("a",), ("b",), False)
    k2 = exchange._program_key(mk(2), None, ("a",), ("b",), False)
    assert k1 is not None and k2 is not None
    assert k1[1] != k2[1]
    # same code + same captures: equal keys
    k3 = exchange._program_key(mk(1), None, ("a",), ("b",), False)
    assert k1[1] == k3[1]


# -------------------------------- e2e: one trace per size class (sat. 3)

def test_two_row_counts_one_size_class_one_fragment_trace(monkeypatch):
    """Literal-different row counts (100 vs 120) bucket to ONE capacity
    class (128) and must produce ONE fragment trace — the repeat is a
    structure hit on the already-jitted program, not a retrace."""
    monkeypatch.setenv("DAFT_TPU_DEVICE_FORCE", "1")

    def q(n):
        data = {"sd_k": [j % 4 for j in range(n)],
                "sd_v": [float(j) for j in range(n)]}
        df = daft_tpu.from_pydict(data)
        return df.groupby("sd_k").agg(col("sd_v").sum()).to_pydict()

    with _armed() as san:
        out1 = q(100)
        frag1 = san.summary()["site_traces"].get("fragment.packed", 0)
        out2 = q(120)
        s = san.summary()
        frag2 = s["site_traces"].get("fragment.packed", 0)
    assert sorted(out1["sd_k"]) == [0, 1, 2, 3]
    assert len(out2["sd_k"]) == 4
    assert frag1 >= 1, "first query should dispatch the fused fragment"
    assert frag2 == frag1, \
        "literal-different row count in the same size class re-traced"
    assert s["violations"] == []


# ------------------------------------------- size-class ladder + warm-up

def test_bucket_capacity_ladders(monkeypatch):
    from daft_tpu.device import column as dcol
    assert dcol.bucket_capacity(100) == 128
    assert dcol.bucket_capacity(128) == 128
    monkeypatch.setenv("DAFT_TPU_SIZE_CLASSES", "pow4")
    assert dcol.bucket_capacity(100) == 256      # 16, 64, 256 …
    monkeypatch.setenv("DAFT_TPU_SIZE_CLASSES", "1024,8192")
    assert dcol.bucket_capacity(100) == 1024
    assert dcol.bucket_capacity(5000) == 8192
    # above the ladder top: keep doubling (never crash, never truncate)
    assert dcol.bucket_capacity(10000) == 16384
    monkeypatch.setenv("DAFT_TPU_SIZE_CLASSES", "pow2")
    assert dcol.bucket_capacity(100) == 128


def test_size_classes_grid(monkeypatch):
    from daft_tpu.device import column as dcol
    monkeypatch.setenv("DAFT_TPU_SIZE_CLASSES", "pow2")
    assert dcol.size_classes(256, 16) == [16, 32, 64, 128, 256]
    monkeypatch.setenv("DAFT_TPU_SIZE_CLASSES", "pow4")
    assert dcol.size_classes(256, 16) == [16, 64, 256]


def test_warmup_kernels_compiles_grid():
    from daft_tpu.device import warmup
    st = warmup.warmup_kernels([256])
    assert st["errors"] == 0
    assert st["programs"] >= 3


def test_warmup_fragments_and_session(monkeypatch):
    from daft_tpu.device import fragment, warmup
    monkeypatch.setenv("DAFT_TPU_DEVICE_FORCE", "1")
    # fresh fragment library: mid-suite, the shared cache holds every
    # fused program earlier test files compiled, and the session sweep
    # below would AOT-recompile ALL of them x size classes x strategies
    # (minutes of XLA time that tests nothing this test doesn't)
    monkeypatch.setattr(fragment, "_fused_cache", {})
    # populate the fragment library with one program
    data = {"wu_k": [j % 3 for j in range(50)],
            "wu_v": [float(j) for j in range(50)]}
    daft_tpu.from_pydict(data).groupby("wu_k") \
        .agg(col("wu_v").sum()).to_pydict()
    assert fragment.fused_programs()
    st = warmup.warmup_fragments([128, 256])
    assert st["programs"] >= 2
    assert st["errors"] == 0
    # knob-gated session entry: off → None, on → stats. The r21 region
    # grid is exercised by its own test (test_fusion); an empty region
    # cache here keeps this session sweep from re-compiling every region
    # program earlier test files happened to leave behind
    monkeypatch.setattr(fragment, "_region_cache", {})
    monkeypatch.delenv("DAFT_TPU_AOT_WARMUP", raising=False)
    assert warmup.maybe_warmup_session() is None
    monkeypatch.setenv("DAFT_TPU_AOT_WARMUP", "1")
    out = warmup.maybe_warmup_session()
    assert out is not None and out["size_classes"]
    assert out["regions"] == {"programs": 0, "skipped": 0, "errors": 0}


def test_observability_renders_retrace_block():
    from daft_tpu.observability import render_retrace_block
    assert render_retrace_block({}) == []
    lines = render_retrace_block(
        {"traces": 3, "compiles": 2, "compile_seconds": 1.25,
         "unscoped_traces": 1, "violations": 1, "total_violations": 4})
    text = "\n".join(lines)
    assert "shape discipline (retrace sanitizer):" in lines[0]
    assert "3 trace events" in text and "2 XLA compiles" in text
    assert "RETRACE TAX" in text


def test_flight_entry_carries_retrace_block():
    from daft_tpu import observability as obs
    ctx = obs.RuntimeStatsContext()
    ctx.finish()
    ctx.retrace = {"traces": 1.0, "compiles": 1.0}
    entry = obs.flight_entry(ctx)
    assert entry["retrace"] == {"traces": 1.0, "compiles": 1.0}


def test_config_fields_mirror_without_env(monkeypatch):
    """The registry documents tpu_size_classes / tpu_aot_warmup as
    ExecutionConfig mirrors: with the env var unset, the per-query
    config field must actually apply (review finding, pinned)."""
    import daft_tpu.context as ctx
    from daft_tpu.device import column as dcol, warmup
    monkeypatch.delenv("DAFT_TPU_SIZE_CLASSES", raising=False)
    monkeypatch.delenv("DAFT_TPU_AOT_WARMUP", raising=False)
    base = ctx.get_context().execution_config
    monkeypatch.setattr(
        ctx.get_context(), "execution_config",
        dataclasses.replace(base, tpu_size_classes="pow4",
                            tpu_aot_warmup=True))
    assert dcol.bucket_capacity(100) == 256
    assert warmup.warmup_enabled() is True
    # env var (when set) overrides the config field
    monkeypatch.setenv("DAFT_TPU_SIZE_CLASSES", "pow2")
    monkeypatch.setenv("DAFT_TPU_AOT_WARMUP", "0")
    assert dcol.bucket_capacity(100) == 128
    assert warmup.warmup_enabled() is False


def test_exchange_cache_key_covers_defaults():
    """Two mapped fns differing only in a DEFAULT-argument value must
    not collide in the program cache (review finding, pinned)."""
    from daft_tpu.parallel import exchange

    def mk(s):
        def f(x, scale=s):
            return x * scale
        return f

    k1 = exchange._program_key(mk(1), None, ("a",), ("b",), False)
    k2 = exchange._program_key(mk(2), None, ("a",), ("b",), False)
    assert k1 is not None and k2 is not None
    assert k1[1] != k2[1]
