"""daft-lint: the engine-aware static analysis pass + lock sanitizer.

Covers every rule family with fixture snippets (positive + negative +
pragma), the knob-registry round-trip against the live tree, README
knob-table drift, the lock sanitizer's cycle detection, and — the
tier-1 gate — the linter exiting CLEAN on this repo with an empty
baseline.
"""

import os
import re
import threading
import time

import pytest

from daft_tpu.analysis import knobs, lock_sanitizer
from daft_tpu.analysis import framework
from daft_tpu.analysis import (rule_determinism, rule_jit, rule_knobs,
                               rule_locks)
from daft_tpu.analysis.framework import (DEFAULT_SUBDIRS, load_baseline,
                                         repo_root, run_analysis,
                                         walk_sources)

REPO = repo_root()

# fixture literals are SPLIT so this file's own raw text never looks like
# a real knob mention or pragma to the repo-wide scans it tests
BOGUS_KNOB = "DAFT_TPU_" + "BOGUS"
NOT_A_KNOB = "DAFT_TPU_" + "NOT_A_KNOB"
PRAGMA = "# daft-lint: "


def _sources_from(tmp_path, relpath: str, code: str):
    p = tmp_path / relpath
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(code)
    return walk_sources(str(tmp_path), (relpath.split("/")[0],))


# ------------------------------------------------------------ rule: knobs

def test_unregistered_knob_read_is_flagged(tmp_path):
    srcs = _sources_from(
        tmp_path, "daft_tpu/foo.py",
        f'import os\nv = os.environ.get("{BOGUS_KNOB}")\n')
    rules = [f.rule for f in rule_knobs.check(srcs)]
    assert "knob-unregistered" in rules


def test_registered_direct_read_is_flagged(tmp_path):
    srcs = _sources_from(
        tmp_path, "daft_tpu/foo.py",
        'import os\nv = os.environ["DAFT_TPU_MAX_RETRIES"]\n')
    rules = [f.rule for f in rule_knobs.check(srcs)]
    assert "knob-direct-read" in rules


def test_accessor_type_mismatch_is_flagged(tmp_path):
    srcs = _sources_from(
        tmp_path, "daft_tpu/foo.py",
        'from daft_tpu.analysis import knobs\n'
        'v = knobs.env_int("DAFT_TPU_SHUFFLE_COMPRESSION")\n')
    rules = [f.rule for f in rule_knobs.check(srcs)]
    assert "knob-type-mismatch" in rules


def test_correct_accessor_read_is_clean(tmp_path):
    srcs = _sources_from(
        tmp_path, "daft_tpu/foo.py",
        'from daft_tpu.analysis import knobs\n'
        'v = knobs.env_int("DAFT_TPU_MAX_RETRIES")\n'
        'w = knobs.env_str("DAFT_TPU_SHUFFLE_COMPRESSION")\n')
    bad = [f for f in rule_knobs.check(srcs)
           if f.rule in ("knob-direct-read", "knob-type-mismatch",
                         "knob-unregistered")]
    assert bad == []


def test_env_write_is_not_a_read(tmp_path):
    srcs = _sources_from(
        tmp_path, "daft_tpu/foo.py",
        'import os\nos.environ["DAFT_TPU_MAX_RETRIES"] = "5"\n')
    assert [f for f in rule_knobs.check(srcs)
            if f.rule == "knob-direct-read"] == []


def test_pragma_with_reason_suppresses(tmp_path):
    code = ('import os\n'
            'v = os.environ.get("DAFT_TPU_MAX_RETRIES")  '
            + PRAGMA + 'allow(knob-direct-read) -- bootstrap read\n')
    p = tmp_path / "daft_tpu" / "foo.py"
    p.parent.mkdir(parents=True)
    p.write_text(code)
    findings = run_analysis(str(tmp_path), subdirs=("daft_tpu",),
                            contracts=False, readme=False, baseline=[])
    # knob-unused fires for the whole registry on a one-file tree; the
    # rules under test here are the read-site ones
    assert [f for f in findings
            if f.rule in ("knob-direct-read", "pragma-missing-reason")] == []


def test_pragma_without_reason_is_itself_a_finding(tmp_path):
    code = ('import os\n'
            'v = os.environ.get("DAFT_TPU_MAX_RETRIES")  '
            + PRAGMA + 'allow(knob-direct-read)\n')
    p = tmp_path / "daft_tpu" / "foo.py"
    p.parent.mkdir(parents=True)
    p.write_text(code)
    findings = run_analysis(str(tmp_path), subdirs=("daft_tpu",),
                            contracts=False, readme=False, baseline=[])
    rules = [f.rule for f in findings]
    assert "pragma-missing-reason" in rules
    # and the reason-less pragma does NOT suppress the underlying finding
    assert "knob-direct-read" in rules


# ------------------------------------------------ rule: knob round-trip

def test_every_knob_in_the_tree_is_registered():
    """Live-scan round trip: every DAFT_TPU_* name mentioned anywhere in
    the engine/tests/bench/README must be a registered knob (this is the
    check that caught the phantom DAFT_TPU_ENABLE_AQE doc knob)."""
    pat = re.compile(r"DAFT_TPU_[A-Z0-9_]+")
    mentioned = set()
    for sub in ("daft_tpu", "tests", "bench.py", "README.md"):
        base = os.path.join(REPO, sub)
        paths = [base] if os.path.isfile(base) else [
            os.path.join(dp, fn) for dp, dns, fns in os.walk(base)
            if "__pycache__" not in dp
            for fn in fns if fn.endswith((".py", ".md"))]
        for path in paths:
            if path.endswith("test_analysis.py"):
                continue    # this file's fixtures are split, but be safe
            with open(path, encoding="utf-8", errors="ignore") as f:
                mentioned.update(pat.findall(f.read()))
    unregistered = sorted(m for m in mentioned if m not in knobs.REGISTRY)
    assert unregistered == [], \
        f"mentioned but not in the knob registry: {unregistered}"


def test_every_registered_knob_is_used():
    srcs = walk_sources(REPO, DEFAULT_SUBDIRS)
    unused = [f for f in rule_knobs.check(srcs) if f.rule == "knob-unused"]
    assert unused == [], [f.message for f in unused]


def test_stale_registry_entry_is_flagged(tmp_path, monkeypatch):
    """knob-unused actually bites: a registered knob nothing reads."""
    ghost = knobs.Knob("DAFT_TPU_" + "GHOST", "int", 1,
                       "daft_tpu/x.py", "core", "phantom")
    monkeypatch.setitem(knobs.REGISTRY, ghost.name, ghost)
    srcs = _sources_from(tmp_path, "daft_tpu/foo.py", "x = 1\n")
    assert any(f.rule == "knob-unused" and "GHOST" in f.message
               for f in rule_knobs.check(srcs))


def test_unused_prefix_knob_not_masked_by_longer_name(tmp_path):
    """Usage matching is full-token: mentioning DAFT_TPU_DEVICE_FORCE
    must not count as a use of DAFT_TPU_DEVICE (review find: the
    substring match made prefix knobs un-flaggable)."""
    srcs = _sources_from(tmp_path, "daft_tpu/foo.py",
                         'x = "DAFT_TPU_DEVICE_FORCE"\n')
    unused = {f.message.split()[0] for f in rule_knobs.check(srcs)
              if f.rule == "knob-unused"}
    assert "DAFT_TPU_DEVICE" in unused
    assert "DAFT_TPU_DEVICE_FORCE" not in unused


def test_device_force_accepts_documented_spellings(monkeypatch):
    """The registry table documents 1/device and 0/host; the parse site
    must accept exactly those (review find: doc drift introduced by the
    registry meant to prevent it)."""
    from daft_tpu.device import costmodel
    for v, want in [("1", True), ("device", True), ("DEVICE", True),
                    ("0", False), ("host", False), ("unknown", None)]:
        monkeypatch.setenv("DAFT_TPU_DEVICE_FORCE", v)
        assert costmodel._forced() is want, (v, want)
    monkeypatch.delenv("DAFT_TPU_DEVICE_FORCE")
    assert costmodel._forced() is None


def test_registry_types_parse_their_defaults():
    for name, k in knobs.REGISTRY.items():
        assert k.type in ("int", "float", "bool", "str", "bytes"), name
        assert k.doc and k.module and k.group, name
        if k.default is not None and k.type in ("int", "float", "bool"):
            parsed = knobs.parse(name, str(
                int(k.default) if k.type != "float" else k.default))
            assert parsed == k.default or k.type == "bool", name


def test_accessors_parse_and_type_check(monkeypatch):
    monkeypatch.setenv("DAFT_TPU_MAX_RETRIES", "7")
    assert knobs.env_int("DAFT_TPU_MAX_RETRIES") == 7
    monkeypatch.delenv("DAFT_TPU_MAX_RETRIES")
    assert knobs.env_int("DAFT_TPU_MAX_RETRIES") == 3  # registry default
    monkeypatch.setenv("DAFT_TPU_IO_COALESCE_GAP", "2MiB")
    assert knobs.env_bytes("DAFT_TPU_IO_COALESCE_GAP") == 2 << 20
    monkeypatch.setenv("DAFT_TPU_CHAOS_SERIALIZE", "off")
    assert knobs.env_bool("DAFT_TPU_CHAOS_SERIALIZE") is False
    with pytest.raises(knobs.UnknownKnobError):
        knobs.env_int(NOT_A_KNOB)
    with pytest.raises(TypeError):
        knobs.env_int("DAFT_TPU_SHUFFLE_COMPRESSION")  # registered str


# ----------------------------------------------------- rule: determinism

_CRITICAL = "daft_tpu/distributed/worker.py"

def test_unseeded_random_flagged_in_replay_critical(tmp_path):
    srcs = _sources_from(tmp_path, _CRITICAL,
                         "import random\nx = random.random()\n")
    assert [f.rule for f in rule_determinism.check(srcs)] \
        == ["unseeded-random"]


def test_seeded_rng_not_flagged(tmp_path):
    srcs = _sources_from(
        tmp_path, _CRITICAL,
        "import numpy as np\nrng = np.random.default_rng(0)\n")
    assert rule_determinism.check(srcs) == []


def test_wallclock_decision_flagged(tmp_path):
    srcs = _sources_from(
        tmp_path, _CRITICAL,
        "import time\ndeadline = 5\n"
        "def f():\n"
        "    if time.monotonic() > deadline:\n"
        "        return 1\n")
    assert [f.rule for f in rule_determinism.check(srcs)] \
        == ["wallclock-decision"]


def test_wallclock_metric_not_flagged(tmp_path):
    srcs = _sources_from(
        tmp_path, _CRITICAL,
        "import time\n"
        "def f():\n"
        "    t0 = time.perf_counter()\n"
        "    work()\n"
        "    return time.perf_counter() - t0\n")
    assert rule_determinism.check(srcs) == []


def test_as_completed_flagged(tmp_path):
    srcs = _sources_from(
        tmp_path, _CRITICAL,
        "import concurrent.futures as cf\n"
        "def f(futs):\n"
        "    return [x.result() for x in cf.as_completed(futs)]\n")
    assert "unordered-pool-iteration" in \
        [f.rule for f in rule_determinism.check(srcs)]


def test_noncritical_module_exempt(tmp_path):
    srcs = _sources_from(tmp_path, "daft_tpu/somewhere_else.py",
                         "import random\nx = random.random()\n")
    assert rule_determinism.check(srcs) == []


# ----------------------------------------------------------- rule: locks

def test_sleep_under_lock_flagged(tmp_path):
    srcs = _sources_from(
        tmp_path, "daft_tpu/foo.py",
        "import threading, time\n_lock = threading.Lock()\n"
        "def f():\n"
        "    with _lock:\n"
        "        time.sleep(1)\n")
    assert [f.rule for f in rule_locks.check(srcs)] \
        == ["blocking-under-lock"]


def test_blocking_helper_called_under_lock_flagged(tmp_path):
    srcs = _sources_from(
        tmp_path, "daft_tpu/foo.py",
        "import threading\n_lock = threading.Lock()\n"
        "def helper(p):\n"
        "    with open(p) as f:\n"
        "        return f.read()\n"
        "def f(p):\n"
        "    with _lock:\n"
        "        return helper(p)\n")
    found = rule_locks.check(srcs)
    assert [f.rule for f in found] == ["blocking-under-lock"]
    assert "helper" in found[0].message


def test_string_join_under_lock_not_flagged(tmp_path):
    srcs = _sources_from(
        tmp_path, "daft_tpu/foo.py",
        "import threading, os\n_lock = threading.Lock()\n"
        "def f(parts):\n"
        "    with _lock:\n"
        "        return ', '.join(parts) + os.path.join('a', 'b')\n")
    assert rule_locks.check(srcs) == []


def test_unguarded_global_rebind_flagged(tmp_path):
    srcs = _sources_from(
        tmp_path, "daft_tpu/foo.py",
        "_POOL = None\n"
        "def pool():\n"
        "    global _POOL\n"
        "    if _POOL is None:\n"
        "        _POOL = object()\n"
        "    return _POOL\n")
    assert [f.rule for f in rule_locks.check(srcs)] \
        == ["unguarded-global-mutation"]


def test_lock_guarded_global_rebind_clean(tmp_path):
    srcs = _sources_from(
        tmp_path, "daft_tpu/foo.py",
        "import threading\n_POOL = None\n_lock = threading.Lock()\n"
        "def pool():\n"
        "    global _POOL\n"
        "    with _lock:\n"
        "        if _POOL is None:\n"
        "            _POOL = object()\n"
        "        return _POOL\n")
    assert rule_locks.check(srcs) == []


# ------------------------------------------------------------- rule: jit

def test_host_effect_and_np_on_traced_flagged(tmp_path):
    srcs = _sources_from(
        tmp_path, "daft_tpu/device/foo.py",
        "import jax\nimport numpy as np\nfrom functools import partial\n"
        "@partial(jax.jit)\n"
        "def k(x):\n"
        "    print('tracing')\n"
        "    return np.sum(x)\n")
    rules = sorted(f.rule for f in rule_jit.check(srcs))
    assert rules == ["host-effect-in-jit", "np-in-jit"]


def test_static_np_metadata_in_jit_allowed(tmp_path):
    srcs = _sources_from(
        tmp_path, "daft_tpu/device/foo.py",
        "import jax\nimport numpy as np\nfrom functools import partial\n"
        "@partial(jax.jit, static_argnames=('d',))\n"
        "def k(x, d):\n"
        "    bits = np.iinfo(np.int64).bits\n"
        "    n = np.zeros(4)\n"     # untainted np is trace-time constant
        "    return x\n")
    assert rule_jit.check(srcs) == []


def test_wrap_site_jit_detected(tmp_path):
    srcs = _sources_from(
        tmp_path, "daft_tpu/device/foo.py",
        "import jax\n"
        "def impl(x):\n"
        "    print('boom')\n"
        "    return x\n"
        "kernel = jax.jit(impl)\n")
    assert [f.rule for f in rule_jit.check(srcs)] == ["host-effect-in-jit"]


def test_dispatch_contracts_hold():
    """PR 1's kernel contracts re-proven from freshly-built jaxprs."""
    assert rule_jit.check_dispatch_contracts() == []


# -------------------------------------------------------- lock sanitizer

def test_cycle_detection_two_threads_inverted_order():
    san = lock_sanitizer.LockOrderSanitizer()
    la = san.track(threading.Lock(), "daft_tpu/a.py:1")
    lb = san.track(threading.Lock(), "daft_tpu/b.py:1")
    order_ab = threading.Event()

    def t1():
        with la:
            with lb:
                pass
        order_ab.set()

    def t2():
        order_ab.wait(5)
        with lb:
            with la:
                pass

    th1, th2 = threading.Thread(target=t1), threading.Thread(target=t2)
    th1.start(); th2.start(); th1.join(5); th2.join(5)
    s = san.summary()
    assert len(s["cycles"]) == 1
    assert "daft_tpu/a.py:1" in s["cycles"][0] \
        and "daft_tpu/b.py:1" in s["cycles"][0]
    assert "POTENTIAL DEADLOCK" in san.report()


def test_consistent_order_reports_no_cycle():
    san = lock_sanitizer.LockOrderSanitizer()
    la = san.track(threading.Lock(), "daft_tpu/a.py:1")
    lb = san.track(threading.Lock(), "daft_tpu/b.py:1")
    for _ in range(3):
        with la:
            with lb:
                pass
    s = san.summary()
    assert s["cycles"] == [] and s["edges"] == 1 and s["locks"] == 2


def test_rlock_reentrance_is_not_an_edge():
    san = lock_sanitizer.LockOrderSanitizer()
    lr = san.track(threading.RLock(), "daft_tpu/r.py:1")
    with lr:
        with lr:
            pass
    assert san.summary()["edges"] == 0


def test_contention_is_counted():
    san = lock_sanitizer.LockOrderSanitizer()
    lock = san.track(threading.Lock(), "daft_tpu/c.py:1")
    acquired = threading.Event()
    release = threading.Event()

    def holder():
        with lock:
            acquired.set()
            release.wait(5)

    th = threading.Thread(target=holder)
    th.start()
    acquired.wait(5)
    waiter = threading.Thread(target=lambda: lock.acquire() or
                              lock.release())
    waiter.start()
    time.sleep(0.05)   # let the waiter hit the contended probe
    release.set()
    th.join(5); waiter.join(5)
    assert san.summary()["contended"] >= 1


def test_enabled_sanitizer_tracks_engine_locks_and_blocking():
    """enable() wraps locks created by engine code (allocation site under
    daft_tpu/) and records sleep-while-held; foreign locks (created here,
    in tests/) stay untracked."""
    was_enabled = lock_sanitizer.is_enabled()
    lock_sanitizer.enable()
    try:
        from daft_tpu.observability import OperatorStats
        before = lock_sanitizer.counters_snapshot()
        st = OperatorStats("probe")      # engine-created → tracked
        assert type(st.lock).__name__ == "_TrackedLock"
        foreign = threading.Lock()       # test-created → real lock
        assert type(foreign).__name__ != "_TrackedLock"
        with st.lock:
            time.sleep(0.001)
        after = lock_sanitizer.counters_snapshot()
        assert after["acquisitions"] > before["acquisitions"]
        assert after["blocking_while_held"] > before["blocking_while_held"]
    finally:
        if not was_enabled:
            lock_sanitizer.disable()


def test_observability_renders_sanitizer_block():
    was_enabled = lock_sanitizer.is_enabled()
    lock_sanitizer.enable()
    try:
        from daft_tpu.observability import RuntimeStatsContext
        ctx = RuntimeStatsContext()
        from daft_tpu.observability import OperatorStats
        st = OperatorStats("probe")
        with st.lock:
            pass
        ctx.finish()
        out = ctx.render()
        assert "concurrency (lock sanitizer):" in out
        assert "lock sites" in out
    finally:
        if not was_enabled:
            lock_sanitizer.disable()


def test_queue_condition_compat_under_sanitizer():
    """queue.Queue builds Conditions over the (possibly wrapped) lock —
    the proxy must keep put/get working. Regression for the
    _release_save forwarding hazard."""
    was_enabled = lock_sanitizer.is_enabled()
    lock_sanitizer.enable()
    try:
        import queue
        q = queue.Queue(maxsize=2)
        q.put(1); q.put(2)
        assert q.get() == 1 and q.get() == 2
    finally:
        if not was_enabled:
            lock_sanitizer.disable()


# ----------------------------------------------------- repo-level gates

def test_baseline_is_empty():
    """Grandfathering is banned: fix it or pragma-justify it."""
    assert load_baseline() == []


def test_readme_knob_tables_in_sync():
    with open(os.path.join(REPO, "README.md"), encoding="utf-8") as f:
        text = f.read()
    assert knobs.readme_drift(text) == []
    # and a stale edit IS caught (the drift test actually bites)
    broken = text.replace("`DAFT_TPU_SHUFFLE_COMPRESSION`",
                          "`DAFT_TPU_SHUFFLE_" + "COMPRESSON`", 1)
    assert knobs.readme_drift(broken) != []


def test_linter_clean_on_repo_tree():
    """THE tier-1 gate: `python -m daft_tpu.analysis` is clean — every
    finding fixed or pragma-justified, baseline empty, README generated
    tables fresh, dispatch contracts proven."""
    findings = run_analysis(REPO, contracts=True, readme=True)
    assert findings == [], "\n".join(f.render() for f in findings)


# ------------------------------------- burn-down fix regression tests
# genuine findings the linter surfaced, fixed in this PR — these pin the
# fixes down

def test_executor_pool_creation_is_single_under_race():
    """daft-lint unguarded-global-mutation find: two racing first callers
    each built a ThreadPoolExecutor and the loser's worker threads leaked
    for the process lifetime. Creation is lock-guarded now."""
    from daft_tpu.execution import executor as ex
    old = ex._POOL
    ex._POOL = None
    try:
        barrier = threading.Barrier(8)
        got = []

        def go():
            barrier.wait(5)
            got.append(ex._pool())

        threads = [threading.Thread(target=go) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(10)
        assert len(got) == 8 and len({id(p) for p in got}) == 1
    finally:
        created = ex._POOL
        ex._POOL = old
        if created is not None and created is not old:
            created.shutdown(wait=False)


def test_session_singleton_is_single_under_race():
    """daft-lint unguarded-global-mutation find: two racing first callers
    each built a Session — attachments made through the loser silently
    vanished. Creation is lock-guarded now."""
    from daft_tpu import session as se
    old = se._SESSION
    se._SESSION = None
    try:
        barrier = threading.Barrier(8)
        got = []

        def go():
            barrier.wait(5)
            got.append(se._session())

        threads = [threading.Thread(target=go) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(10)
        assert len(got) == 8 and len({id(s) for s in got}) == 1
    finally:
        se._SESSION = old


def test_orphan_sweep_runs_exactly_once_under_race(monkeypatch):
    """daft-lint unguarded-global-mutation find: the startup orphan sweep
    was check-then-set; concurrent first servers each ran the glob+stat
    walk. Now flag-flip is atomic."""
    from daft_tpu.distributed import shuffle_service as ss
    calls = []
    monkeypatch.setattr(ss, "sweep_orphaned_shuffles",
                        lambda: calls.append(1))
    monkeypatch.setattr(ss, "FlightShuffleServer",
                        lambda *a, **k: object(), raising=False)
    monkeypatch.setattr(ss, "ShuffleServer", lambda *a, **k: object())
    monkeypatch.setattr(ss, "_swept_once", False)
    barrier = threading.Barrier(8)

    def go():
        barrier.wait(5)
        ss.make_shuffle_server()

    threads = [threading.Thread(target=go) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(10)
    assert len(calls) == 1


def test_mesh_size_memo_is_reentrant():
    """mesh._size is now computed under the module lock; the lock became
    re-entrant because get_mesh() already holds it around mesh_size()."""
    from daft_tpu.parallel import mesh
    n1 = mesh.mesh_size()
    n2 = mesh.mesh_size()
    assert n1 == n2


def test_cli_knob_docs_prints_all_groups(capsys):
    from daft_tpu.analysis.__main__ import main
    assert main(["--knob-docs"]) == 0
    out = capsys.readouterr().out
    for group in knobs.GROUPS:
        assert f"### {group}" in out
    assert "DAFT_TPU_SANITIZE" in out


# =====================================================================
# v2: flow-sensitive families (dataflow engine + four rule families)

from daft_tpu.analysis import (dataflow, rule_attribution,  # noqa: E402
                               rule_cancellation, rule_donation,
                               rule_resources)


def _rules_of(findings):
    return [f.rule for f in findings]


# ------------------------------------------------- dataflow engine unit

def test_cfg_finally_credits_exception_paths():
    import ast as _ast
    code = (
        "def f(self, est):\n"
        "    self.mem.acquire(est)\n"
        "    try:\n"
        "        return work(est)\n"
        "    finally:\n"
        "        self.mem.release(est)\n")
    fn = _ast.parse(code).body[0]
    cfg = dataflow.CFG(fn)

    def credit(node):
        return node.stmt is not None and "release" in _ast.unparse(
            node.stmt)
    # from the acquire onward, every path (normal return AND work()
    # raising) passes the finally's release — the per-continuation
    # finally instantiation is what makes the exception copy credited
    acquire_stmt = fn.body[0]
    starts = [t for n in cfg.nodes_for(acquire_stmt)
              for t, is_exc in n.succ if not is_exc]
    assert dataflow.find_escape(cfg, starts, credit) is None


def test_cfg_exception_edge_escapes_without_finally():
    import ast as _ast
    code = (
        "def f(self, est):\n"
        "    self.mem.acquire(est)\n"
        "    mid(est)\n"
        "    self.mem.release(est)\n")
    fn = _ast.parse(code).body[0]
    cfg = dataflow.CFG(fn)

    def credit(node):
        return node.stmt is not None and "release" in _ast.unparse(
            node.stmt)
    # mid() raising exits before the release: NOT all paths credited
    assert not dataflow.hits_on_all_paths(cfg, credit)


def test_cfg_except_baseexception_is_catch_all():
    import ast as _ast
    code = (
        "def f(x):\n"
        "    try:\n"
        "        work(x)\n"
        "    except BaseException:\n"
        "        stop(x)\n"
        "        raise\n"
        "    stop(x)\n")
    fn = _ast.parse(code).body[0]
    cfg = dataflow.CFG(fn)

    def credit(node):
        return node.stmt is not None and "stop" in _ast.unparse(node.stmt)
    assert dataflow.find_escape(
        cfg, [cfg.entry], credit, exc_only=True) is None


# -------------------------------------- family: resource pairing (5)

def test_admission_leak_on_exception_edge_flagged(tmp_path):
    srcs = _sources_from(
        tmp_path, "daft_tpu/foo.py",
        "def run(self, est):\n"
        "    self.mem.acquire(est)\n"
        "    do_work(est)\n"
        "    self.mem.release(est)\n")
    assert "memory-admission-leak" in _rules_of(rule_resources.check(srcs))


def test_try_finally_release_is_credited(tmp_path):
    srcs = _sources_from(
        tmp_path, "daft_tpu/foo.py",
        "def run(self, est):\n"
        "    self.mem.acquire(est)\n"
        "    try:\n"
        "        return do_work(est)\n"
        "    finally:\n"
        "        self.mem.release(est)\n")
    assert "memory-admission-leak" not in _rules_of(
        rule_resources.check(srcs))


def test_helper_release_call_summary_is_credited(tmp_path):
    # the helper releases on ALL its paths → calling it credits the
    # caller's exception edges (one-level call summary)
    srcs = _sources_from(
        tmp_path, "daft_tpu/foo.py",
        "def _done(self, est):\n"
        "    self.mem.release(est)\n"
        "\n"
        "def run(self, est):\n"
        "    self.mem.acquire(est)\n"
        "    try:\n"
        "        return do_work(est)\n"
        "    finally:\n"
        "        self._done(est)\n")
    assert "memory-admission-leak" not in _rules_of(
        rule_resources.check(srcs))


def test_helper_that_may_not_release_is_not_credited(tmp_path):
    srcs = _sources_from(
        tmp_path, "daft_tpu/foo.py",
        "def _done(self, est):\n"
        "    if maybe():\n"
        "        self.mem.release(est)\n"
        "\n"
        "def run(self, est):\n"
        "    self.mem.acquire(est)\n"
        "    try:\n"
        "        return do_work(est)\n"
        "    finally:\n"
        "        self._done(est)\n")
    assert "memory-admission-leak" in _rules_of(rule_resources.check(srcs))


def test_conditional_try_acquire_tracks_success_branch(tmp_path):
    # `if not try_acquire(): return` — the reject branch needs no
    # release; the success continuation does (and has one here)
    srcs = _sources_from(
        tmp_path, "daft_tpu/foo.py",
        "def run(self, est):\n"
        "    if not self.admission.try_acquire(est):\n"
        "        return None\n"
        "    try:\n"
        "        return do_work(est)\n"
        "    finally:\n"
        "        self.admission.release(est)\n")
    assert "memory-admission-leak" not in _rules_of(
        rule_resources.check(srcs))


def test_admission_leak_pragma_suppresses(tmp_path):
    code = (
        "def run(self, est):\n"
        "    " + PRAGMA + "allow(memory-admission-leak) -- test dummy\n"
        "    self.mem.acquire(est)\n"
        "    do_work(est)\n"
        "    self.mem.release(est)\n")
    p = tmp_path / "daft_tpu" / "foo.py"
    p.parent.mkdir(parents=True)
    p.write_text(code)
    findings = run_analysis(str(tmp_path), subdirs=("daft_tpu",),
                            contracts=False, readme=False, baseline=[])
    assert "memory-admission-leak" not in _rules_of(findings)


def test_shuffle_cache_ownership_transfer_credits(tmp_path):
    srcs = _sources_from(
        tmp_path, "daft_tpu/foo.py",
        "def task(stream):\n"
        "    cache = ShuffleCache()\n"
        "    try:\n"
        "        for mp in stream:\n"
        "            cache.push(0, mp)\n"
        "        server.register(cache)\n"
        "    except BaseException:\n"
        "        cache.cleanup()\n"
        "        raise\n")
    assert "shuffle-cache-leak" not in _rules_of(rule_resources.check(srcs))


def test_shuffle_cache_leak_on_drain_failure_flagged(tmp_path):
    srcs = _sources_from(
        tmp_path, "daft_tpu/foo.py",
        "def task(stream):\n"
        "    cache = ShuffleCache()\n"
        "    for mp in stream:\n"
        "        cache.push(0, mp)\n"
        "    server.register(cache)\n")
    assert "shuffle-cache-leak" in _rules_of(rule_resources.check(srcs))


def test_collective_lease_leak_flagged(tmp_path):
    # the exchange body can raise — the lease must release on that path
    srcs = _sources_from(
        tmp_path, "daft_tpu/foo.py",
        "def run(stage):\n"
        "    lease = topology.acquire_collective(stage)\n"
        "    do_exchange(stage)\n"
        "    topology.release_collective(lease)\n")
    assert "collective-lease-leak" in _rules_of(rule_resources.check(srcs))


def test_collective_lease_finally_release_clean(tmp_path):
    srcs = _sources_from(
        tmp_path, "daft_tpu/foo.py",
        "def run(stage):\n"
        "    lease = topology.acquire_collective(stage)\n"
        "    try:\n"
        "        do_exchange(stage)\n"
        "    finally:\n"
        "        topology.release_collective(lease)\n")
    assert "collective-lease-leak" not in _rules_of(
        rule_resources.check(srcs))


def test_device_slot_transfer_or_release_is_clean(tmp_path):
    # the r17 pipeline submit shape: release on every decline/error
    # path, hand the slot off whole (InflightItem) on success
    srcs = _sources_from(
        tmp_path, "daft_tpu/foo.py",
        "def submit(gate, seq, mem, rb):\n"
        "    slot = acquire_slot(gate, seq, mem, 100)\n"
        "    try:\n"
        "        tok = dispatch(rb)\n"
        "    except BaseException:\n"
        "        release_slot(slot)\n"
        "        raise\n"
        "    if tok is None:\n"
        "        release_slot(slot)\n"
        "        return host(rb)\n"
        "    return InflightItem(slot, tok)\n")
    assert "device-slot-leak" not in _rules_of(rule_resources.check(srcs))


def test_device_slot_leak_on_decline_path_flagged(tmp_path):
    # the decline path drops the slot on the floor: window occupancy and
    # admission bytes leak until process exit
    srcs = _sources_from(
        tmp_path, "daft_tpu/foo.py",
        "def submit(gate, seq, mem, rb):\n"
        "    slot = acquire_slot(gate, seq, mem, 100)\n"
        "    tok = dispatch(rb)\n"
        "    if tok is None:\n"
        "        return host(rb)\n"
        "    return InflightItem(slot, tok)\n")
    assert "device-slot-leak" in _rules_of(rule_resources.check(srcs))


def test_device_slot_pragma_suppresses(tmp_path):
    code = (
        "def submit(gate, seq, mem, rb):\n"
        "    " + PRAGMA + "allow(device-slot-leak) -- fixture reason\n"
        "    slot = acquire_slot(gate, seq, mem, 100)\n"
        "    return dispatch(rb)\n")
    p = tmp_path / "daft_tpu" / "foo.py"
    p.parent.mkdir(parents=True)
    p.write_text(code)
    findings = run_analysis(str(tmp_path), subdirs=("daft_tpu",),
                            contracts=False, readme=False, baseline=[])
    assert "device-slot-leak" not in _rules_of(findings)


def test_trace_recorder_exception_path_needs_abort(tmp_path):
    bad = _sources_from(
        tmp_path, "daft_tpu/foo.py",
        "def run(builder):\n"
        "    tctx = tracing.maybe_start_trace('q')\n"
        "    plan = builder.optimize()\n"
        "    return execute(plan)\n")
    assert "trace-recorder-leak" in _rules_of(rule_resources.check(bad))


def test_trace_recorder_abort_on_error_path_is_clean(tmp_path):
    # mirrors the fixed NativeRunner.run_iter: everything that can raise
    # before the executor adopts the trace sits under the abort handler
    good = _sources_from(
        tmp_path, "daft_tpu/bar.py",
        "def run(builder):\n"
        "    tctx = tracing.maybe_start_trace('q')\n"
        "    try:\n"
        "        plan = builder.optimize()\n"
        "        it = execute(plan)\n"
        "    except BaseException:\n"
        "        tracing.abort_trace(tctx)\n"
        "        raise\n"
        "    yield from it\n")
    assert "trace-recorder-leak" not in _rules_of(rule_resources.check(good))


def test_pool_with_form_and_attr_escape_are_clean(tmp_path):
    srcs = _sources_from(
        tmp_path, "daft_tpu/foo.py",
        "def a(xs):\n"
        "    with ThreadPoolExecutor(4) as pool:\n"
        "        return [f.result() for f in map(pool.submit, xs)]\n"
        "\n"
        "def b(self):\n"
        "    self._pool = ThreadPoolExecutor(4)\n")
    assert "pool-leak" not in _rules_of(rule_resources.check(srcs))


def test_local_pool_without_shutdown_flagged(tmp_path):
    srcs = _sources_from(
        tmp_path, "daft_tpu/foo.py",
        "def a(xs):\n"
        "    pool = ThreadPoolExecutor(4)\n"
        "    return pool.submit(work).result()\n")
    assert "pool-leak" in _rules_of(rule_resources.check(srcs))


def test_scope_helper_outside_with_flagged(tmp_path):
    srcs = _sources_from(
        tmp_path, "daft_tpu/foo.py",
        "def run(tok, stats):\n"
        "    cancel_scope(tok)\n"
        "    with obs.attributed(stats):\n"
        "        pass\n")
    rules = _rules_of(rule_resources.check(srcs))
    assert rules.count("scope-helper-not-with") == 1


def test_scope_helper_assigned_then_entered_is_clean(tmp_path):
    srcs = _sources_from(
        tmp_path, "daft_tpu/foo.py",
        "def run():\n"
        "    sp = tracing.span('scan', key='k')\n"
        "    with sp:\n"
        "        pass\n")
    assert "scope-helper-not-with" not in _rules_of(
        rule_resources.check(srcs))


# ---------------------------------------- family: donation safety (6)

_DONATING_HELPER = (
    "def _dispatch(prog, dt, out_cap, donate=False):\n"
    "    arrays = {n: c.data for n, c in dt.columns.items()}\n"
    "    valids = {n: c.validity for n, c in dt.columns.items()}\n"
    "    fn = prog.donate_fn() if donate else prog.packed_fn\n"
    "    return fn(arrays, valids, out_cap=out_cap)\n"
    "\n")


def test_donated_then_read_across_one_call_level_flagged(tmp_path):
    # run() donates dt via the _dispatch helper, then reads its planes
    # through a second helper — the solver must catch it one level deep
    srcs = _sources_from(
        tmp_path, "daft_tpu/device/fragment.py",
        _DONATING_HELPER +
        "def _nbytes(dt):\n"
        "    return sum(c.data.nbytes for c in dt.columns.values())\n"
        "\n"
        "def run(prog, dt, donate):\n"
        "    packed = _dispatch(prog, dt, 64, donate)\n"
        "    return packed, _nbytes(dt)\n")
    assert "donated-buffer-read" in _rules_of(rule_donation.check(srcs))


def test_donated_direct_plane_read_flagged(tmp_path):
    srcs = _sources_from(
        tmp_path, "daft_tpu/device/fragment.py",
        _DONATING_HELPER +
        "def run(prog, dt, donate):\n"
        "    packed = _dispatch(prog, dt, 64, donate)\n"
        "    return packed, dt.row_mask\n")
    assert "donated-buffer-read" in _rules_of(rule_donation.check(srcs))


def test_rebind_before_reuse_kills_taint(tmp_path):
    srcs = _sources_from(
        tmp_path, "daft_tpu/device/fragment.py",
        _DONATING_HELPER +
        "def run(prog, dt, donate, reencode):\n"
        "    packed = _dispatch(prog, dt, 64, donate)\n"
        "    if donate:\n"
        "        dt = reencode()\n"
        "    return _dispatch(prog, dt, 128, donate)\n")
    assert "donated-buffer-read" not in _rules_of(rule_donation.check(srcs))


def test_scalar_metadata_read_after_donation_is_clean(tmp_path):
    srcs = _sources_from(
        tmp_path, "daft_tpu/device/fragment.py",
        _DONATING_HELPER +
        "def run(prog, dt, donate):\n"
        "    packed = _dispatch(prog, dt, 64, donate)\n"
        "    return packed, dt.row_count, dt.capacity\n")
    assert "donated-buffer-read" not in _rules_of(rule_donation.check(srcs))


def test_statically_disabled_donation_not_tainted(tmp_path):
    srcs = _sources_from(
        tmp_path, "daft_tpu/device/fragment.py",
        _DONATING_HELPER +
        "def run(prog, dt):\n"
        "    packed = _dispatch(prog, dt, 64)\n"   # donate defaults False
        "    return packed, dt.row_mask\n")
    assert "donated-buffer-read" not in _rules_of(rule_donation.check(srcs))


def test_unguarded_donate_flag_flagged(tmp_path):
    srcs = _sources_from(
        tmp_path, "daft_tpu/device/fragment.py",
        "def run(prog, dt):\n"
        "    donate = fast_mode_enabled()\n"
        "    return dispatch(prog, dt, donate=donate)\n")
    assert "donation-unguarded" in _rules_of(rule_donation.check(srcs))


def test_resident_guarded_donate_flag_clean(tmp_path):
    srcs = _sources_from(
        tmp_path, "daft_tpu/device/fragment.py",
        "def _donation_ok(dt):\n"
        "    return backend.is_accelerator() and not dt.resident\n"
        "\n"
        "def run(prog, dt, reencode):\n"
        "    donate = reencode is not None and _donation_ok(dt)\n"
        "    return dispatch(prog, dt, donate=donate)\n")
    assert "donation-unguarded" not in _rules_of(rule_donation.check(srcs))


def test_donation_pragma_suppresses(tmp_path):
    code = (
        "def run(prog, dt):\n"
        "    " + PRAGMA + "allow(donation-unguarded) -- test dummy\n"
        "    donate = fast_mode_enabled()\n"
        "    return dispatch(prog, dt, donate=donate)\n")
    p = tmp_path / "daft_tpu" / "device"
    p.mkdir(parents=True)
    (p / "fragment.py").write_text(code)
    findings = run_analysis(str(tmp_path), subdirs=("daft_tpu",),
                            contracts=False, readme=False, baseline=[])
    assert "donation-unguarded" not in _rules_of(findings)


# ---------------------------------- family: cancellation checks (7)

def test_uncancellable_drain_loop_flagged(tmp_path):
    srcs = _sources_from(
        tmp_path, "daft_tpu/execution/executor.py",
        "def consume(self, stream):\n"
        "    out = []\n"
        "    for mp in stream:\n"
        "        out.append(mp)\n"
        "    return out\n")
    assert "uncancellable-loop" in _rules_of(rule_cancellation.check(srcs))


def test_polled_drain_loop_clean(tmp_path):
    srcs = _sources_from(
        tmp_path, "daft_tpu/execution/executor.py",
        "def consume(self, stream):\n"
        "    out = []\n"
        "    for mp in stream:\n"
        "        self._poll_cancel()\n"
        "        out.append(mp)\n"
        "    return out\n")
    assert "uncancellable-loop" not in _rules_of(
        rule_cancellation.check(srcs))


def test_yielding_loop_is_boundary_checked(tmp_path):
    # a pipelined loop yields every morsel: the driver checks the token
    # at the yield boundary, no in-loop poll needed
    srcs = _sources_from(
        tmp_path, "daft_tpu/execution/executor.py",
        "def passthrough(self, stream):\n"
        "    for mp in stream:\n"
        "        yield transform(mp)\n")
    assert "uncancellable-loop" not in _rules_of(
        rule_cancellation.check(srcs))


def test_channel_put_loop_is_credited(tmp_path):
    # Channel.put polls the pipeline cancel event on every blocked try
    srcs = _sources_from(
        tmp_path, "daft_tpu/execution/pipeline.py",
        "def dispatch(self, child, out):\n"
        "    for mp in child:\n"
        "        out.put(mp)\n")
    assert "uncancellable-loop" not in _rules_of(
        rule_cancellation.check(srcs))


def test_loop_checking_via_helper_is_credited(tmp_path):
    srcs = _sources_from(
        tmp_path, "daft_tpu/execution/executor.py",
        "def _poll(self):\n"
        "    tok = self.cancel_token\n"
        "    if tok is not None:\n"
        "        tok.check()\n"
        "\n"
        "def consume(self, stream):\n"
        "    for mp in stream:\n"
        "        self._poll()\n"
        "        use(mp)\n")
    assert "uncancellable-loop" not in _rules_of(
        rule_cancellation.check(srcs))


def test_out_of_scope_module_loops_exempt(tmp_path):
    srcs = _sources_from(
        tmp_path, "daft_tpu/io/readers.py",
        "def consume(stream):\n"
        "    return [mp for mp in stream]\n")
    assert rule_cancellation.check(srcs) == []


def test_cancellation_pragma_suppresses(tmp_path):
    code = (
        "def consume(self, stream):\n"
        "    " + PRAGMA + "allow(uncancellable-loop) -- iterator polls\n"
        "    for mp in stream:\n"
        "        use(mp)\n")
    p = tmp_path / "daft_tpu" / "execution"
    p.mkdir(parents=True)
    (p / "executor.py").write_text(code)
    findings = run_analysis(str(tmp_path), subdirs=("daft_tpu",),
                            contracts=False, readme=False, baseline=[])
    assert "uncancellable-loop" not in _rules_of(findings)


# --------------------------------- family: attribution threading (8)

def test_unwrapped_pool_submit_flagged(tmp_path):
    srcs = _sources_from(
        tmp_path, "daft_tpu/execution/executor.py",
        "def fan(pool, fn, xs):\n"
        "    return [pool.submit(fn, x) for x in xs]\n")
    assert "unattributed-worker" in _rules_of(rule_attribution.check(srcs))


def test_wrapped_pool_submit_clean(tmp_path):
    srcs = _sources_from(
        tmp_path, "daft_tpu/execution/executor.py",
        "def fan(pool, fn, xs):\n"
        "    return [pool.submit(obs.run_attributed,\n"
        "                        obs.current_attribution(), fn, x)\n"
        "            for x in xs]\n")
    assert "unattributed-worker" not in _rules_of(
        rule_attribution.check(srcs))


def test_thread_target_installing_attribution_credited(tmp_path):
    # the target installs the scope itself (found transitively)
    srcs = _sources_from(
        tmp_path, "daft_tpu/execution/pipeline.py",
        "def _guard(self, fn):\n"
        "    with obs.attributed(self.stats_ctx):\n"
        "        fn()\n"
        "\n"
        "def spawn(self, fn, name):\n"
        "    t = threading.Thread(target=self._guard, args=(fn,),\n"
        "                         name=name, daemon=True)\n"
        "    t.start()\n")
    assert "unattributed-worker" not in _rules_of(
        rule_attribution.check(srcs))


def test_bare_thread_target_flagged(tmp_path):
    srcs = _sources_from(
        tmp_path, "daft_tpu/serving/scheduler.py",
        "def _loop(self):\n"
        "    while True:\n"
        "        self._step()\n"
        "\n"
        "def start(self):\n"
        "    threading.Thread(target=self._loop, daemon=True).start()\n")
    assert "unattributed-worker" in _rules_of(rule_attribution.check(srcs))


def test_foreign_bound_method_target_skipped(tmp_path):
    srcs = _sources_from(
        tmp_path, "daft_tpu/serving/scheduler.py",
        "def start(self, server):\n"
        "    threading.Thread(target=server.serve_forever,\n"
        "                     daemon=True).start()\n")
    assert rule_attribution.check(srcs) == []


# ------------------------------------------------ pragma hygiene (v2)

def test_pragma_naming_unknown_rule_is_flagged(tmp_path):
    code = ("x = 1  " + PRAGMA + "allow(no-such-" + "rule) -- stale\n")
    p = tmp_path / "daft_tpu" / "foo.py"
    p.parent.mkdir(parents=True)
    p.write_text(code)
    findings = run_analysis(str(tmp_path), subdirs=("daft_tpu",),
                            contracts=False, readme=False, baseline=[])
    assert "pragma-unknown-rule" in _rules_of(findings)


def test_pragma_naming_live_rule_not_flagged(tmp_path):
    code = ("import os\n"
            "v = os.environ.get('DAFT_TPU_MAX_RETRIES')  "
            + PRAGMA + "allow(knob-direct-read) -- bootstrap\n")
    p = tmp_path / "daft_tpu" / "foo.py"
    p.parent.mkdir(parents=True)
    p.write_text(code)
    findings = run_analysis(str(tmp_path), subdirs=("daft_tpu",),
                            contracts=False, readme=False, baseline=[])
    assert "pragma-unknown-rule" not in _rules_of(findings)


def test_every_emitted_rule_is_registered():
    """known_rules() is the pragma-validation registry: every rule id a
    family can emit must be present with a family and a fix hint."""
    rules = framework.known_rules()
    for rid, (family, hint) in rules.items():
        assert family and hint, rid
    for mod in (rule_resources, rule_donation, rule_cancellation,
                rule_attribution):
        for rid in mod.RULE_IDS:
            assert rid in rules


def test_findings_carry_family_and_hint(tmp_path):
    p = tmp_path / "daft_tpu" / "foo.py"
    p.parent.mkdir(parents=True)
    p.write_text("def run(self, est):\n"
                 "    self.mem.acquire(est)\n"
                 "    do_work(est)\n")
    findings = run_analysis(str(tmp_path), subdirs=("daft_tpu",),
                            contracts=False, readme=False, baseline=[])
    leak = [f for f in findings if f.rule == "memory-admission-leak"]
    assert leak and leak[0].family == "resources" and leak[0].hint


# ------------------------------------------------------ CLI additions

def test_cli_rule_filter_and_stats(capsys, monkeypatch, tmp_path):
    from daft_tpu.analysis.__main__ import main
    # unknown rule id → usage error
    assert main(["--rule", "definitely-not-a-rule"]) == 2
    out = capsys.readouterr().out
    assert "unknown rule id" in out


def test_cli_stats_line_on_repo(capsys):
    from daft_tpu.analysis.__main__ import main
    rc = main(["--stats", "--no-contracts", "--no-readme"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "daft-lint stats:" in out
    assert "findings_by_family" in out


def test_cli_json_findings_carry_family_and_hint():
    import json as _json
    import subprocess
    import sys

    # a tree with one planted finding, driven through the real CLI path
    # (runs in a subprocess so repo_root() still resolves; the planted
    # file is passed as an explicit path argument)
    code = ("import os\n"
            "v = os.environ.get('DAFT_TPU_" + "PLANTED')\n")
    import tempfile
    # planted INSIDE daft_tpu/ (the knob rules scope there), removed on
    # exit; the suite runs serially so no other lint test sees it
    with tempfile.TemporaryDirectory(
            dir=os.path.join(REPO, "daft_tpu")) as td:
        rel = os.path.relpath(td, REPO)
        with open(os.path.join(td, "planted.py"), "w") as f:
            f.write(code)
        r = subprocess.run(
            [sys.executable, "-m", "daft_tpu.analysis", "--json",
             "--no-contracts", "--no-readme", rel],
            capture_output=True, text=True, cwd=REPO)
    assert r.returncode == 1, r.stdout + r.stderr
    data = _json.loads(r.stdout)
    planted = [d for d in data if d["rule"] == "knob-unregistered"]
    assert planted and planted[0]["family"] == "knobs" \
        and planted[0]["hint"]


def test_nested_def_in_loop_body_does_not_credit(tmp_path):
    # a callback defined inside the loop body may contain put()/yield,
    # but it runs on some other call — the drain loop itself still
    # never polls the token (review finding, pinned)
    srcs = _sources_from(
        tmp_path, "daft_tpu/execution/executor.py",
        "def consume(self, stream, q):\n"
        "    cbs = []\n"
        "    for mp in stream:\n"
        "        def cb(mp=mp):\n"
        "            q.put(mp)\n"
        "        cbs.append(cb)\n"
        "    return cbs\n")
    assert "uncancellable-loop" in _rules_of(rule_cancellation.check(srcs))


# --------------------------------------- rule family: shapes (round 16)

from daft_tpu.analysis import dispatch_registry, rule_shapes


def test_unregistered_jit_site_flagged(tmp_path):
    srcs = _sources_from(
        tmp_path, "daft_tpu/newmod.py",
        "import jax\n"
        "def f(x):\n"
        "    return x\n"
        "g = jax.jit(f)\n")
    assert "dispatch-site-unregistered" in _rules_of(
        rule_shapes.check_registry(srcs))


def test_unregistered_pallas_site_flagged(tmp_path):
    srcs = _sources_from(
        tmp_path, "daft_tpu/newmod.py",
        "from jax.experimental import pallas as pl\n"
        "def build(kernel, C, B):\n"
        "    return pl.pallas_call(kernel, grid=(C // B,))\n")
    assert "dispatch-site-unregistered" in _rules_of(
        rule_shapes.check_registry(srcs))


def test_registered_site_is_clean(tmp_path):
    # same (module, function) coordinates as a live registry entry
    srcs = _sources_from(
        tmp_path, "daft_tpu/device/fragment.py",
        "import jax\n"
        "_fused_cache = {}\n"
        "def get_fused_agg(key, run):\n"
        "    prog = jax.jit(run)\n"
        "    _fused_cache[key] = prog\n"
        "    return prog\n"
        "def donate_fn(self):\n"
        "    self._d = jax.jit(self.run)\n"
        "    return self._d\n")
    assert "dispatch-site-unregistered" not in _rules_of(
        rule_shapes.check_registry(srcs))


def test_stale_registry_entry_flagged(tmp_path):
    # a scanned module the registry claims sites in, with none present
    srcs = _sources_from(
        tmp_path, "daft_tpu/functions/image.py",
        "def _get_resize_jit():\n"
        "    return None\n")
    assert "dispatch-site-stale" in _rules_of(
        rule_shapes.check_registry(srcs))


def test_jit_not_memoized_flagged(tmp_path):
    srcs = _sources_from(
        tmp_path, "daft_tpu/device/newmod.py",
        "import jax\n"
        "def dispatch(f, x):\n"
        "    return jax.jit(f)(x)\n")
    assert "jit-not-memoized" in _rules_of(
        rule_shapes.check_jit_memo(srcs))


def test_jit_memo_store_patterns_are_clean(tmp_path):
    # the sanctioned memo-store (pipeline._mask_cache) shapes: dict store (direct and via a
    # wrapping constructor), attribute store, declared-global store
    srcs = _sources_from(
        tmp_path, "daft_tpu/device/newmod.py",
        "import jax\n"
        "_cache = {}\n"
        "_memo = None\n"
        "def a(key, f):\n"
        "    fn = jax.jit(f)\n"
        "    _cache[key] = fn\n"
        "    return fn\n"
        "def b(key, f):\n"
        "    prog = Wrapper(jax.jit(f), f)\n"
        "    _cache[key] = prog\n"
        "    return prog\n"
        "def c(self, f):\n"
        "    self._fn = jax.jit(f)\n"
        "    return self._fn\n"
        "def d(f):\n"
        "    global _memo\n"
        "    _memo = jax.jit(f)\n"
        "    return _memo\n")
    assert "jit-not-memoized" not in _rules_of(
        rule_shapes.check_jit_memo(srcs))


def test_jit_memo_pragma_suppresses(tmp_path):
    srcs = _sources_from(
        tmp_path, "daft_tpu/device/newmod.py",
        "import jax\n"
        "def compile_it(f):\n"
        "    " + PRAGMA + "allow(jit-not-memoized) -- caller memoizes\n"
        "    return jax.jit(f)\n")
    from daft_tpu.analysis.framework import run_analysis
    findings = run_analysis(str(tmp_path), subdirs=("daft_tpu",),
                            contracts=False, readme=False, baseline=[])
    assert "jit-not-memoized" not in [f.rule for f in findings]


def test_shape_unbucketed_rowcount_flagged(tmp_path):
    srcs = _sources_from(
        tmp_path, "daft_tpu/device/newmod.py",
        "import jax.numpy as jnp\n"
        "def encode(batch, kernel):\n"
        "    n = len(batch)\n"
        "    mask = jnp.zeros(n)\n"
        "    return kernel(mask, out_cap=n)\n")
    rules = _rules_of(rule_shapes.check_shape_taint(srcs))
    assert rules.count("shape-unbucketed") == 2


def test_shape_bucketed_chokepoint_is_clean(tmp_path):
    srcs = _sources_from(
        tmp_path, "daft_tpu/device/newmod.py",
        "import jax.numpy as jnp\n"
        "from .column import bucket_capacity\n"
        "def encode(batch, kernel):\n"
        "    cap = bucket_capacity(len(batch))\n"
        "    mask = jnp.zeros(cap)\n"
        "    return kernel(mask, out_cap=min(cap, 1024))\n")
    assert "shape-unbucketed" not in _rules_of(
        rule_shapes.check_shape_taint(srcs))


def test_shape_taint_does_not_cross_kernel_calls(tmp_path):
    # a kernel RESULT computed from a tainted plane is not itself a raw
    # row count (the exchange closures' fk/ok blocks)
    srcs = _sources_from(
        tmp_path, "daft_tpu/device/newmod.py",
        "import jax.numpy as jnp\n"
        "def run(keys, kernel):\n"
        "    nk = len(keys)\n"
        "    ok = kernel(keys, nk)\n"
        "    return jnp.arange(ok[0].shape[0])\n")
    assert "shape-unbucketed" not in _rules_of(
        rule_shapes.check_shape_taint(srcs))


def test_shape_taint_scopes_nested_defs_separately(tmp_path):
    # the outer fn taints `fk`; the closure REBINDS fk from a kernel
    # result — the inner binding must not inherit the outer taint
    srcs = _sources_from(
        tmp_path, "daft_tpu/device/newmod.py",
        "import jax.numpy as jnp\n"
        "def outer(keys, flat, kernel):\n"
        "    nk = len(keys)\n"
        "    fk = flat[:nk]\n"
        "    def run(args):\n"
        "        fk = kernel(args)\n"
        "        return jnp.arange(fk[0].shape[0])\n"
        "    return run, fk\n")
    assert "shape-unbucketed" not in _rules_of(
        rule_shapes.check_shape_taint(srcs))


def test_shape_unbucketed_pragma_suppresses(tmp_path):
    srcs = _sources_from(
        tmp_path, "daft_tpu/device/newmod.py",
        "import jax.numpy as jnp\n"
        "def encode(batch):\n"
        "    " + PRAGMA + "allow(shape-unbucketed) -- one-shot debug\n"
        "    return jnp.zeros(len(batch))\n")
    from daft_tpu.analysis.framework import run_analysis
    findings = run_analysis(str(tmp_path), subdirs=("daft_tpu",),
                            contracts=False, readme=False, baseline=[])
    assert "shape-unbucketed" not in [f.rule for f in findings]


def test_dispatch_registry_matches_tree():
    """The registry neither under- nor over-claims on the REAL tree:
    zero unregistered construction sites, zero stale entries."""
    srcs = walk_sources(REPO, ("daft_tpu",))
    assert _rules_of(rule_shapes.check_registry(srcs)) == []


def test_registry_budgets_resolve():
    for site in dispatch_registry.SITES:
        b = dispatch_registry.budget_for(site.id)
        assert (b is None) == site.exempt
        assert site.signature and site.budget
    assert dispatch_registry.budget_for("nope") is None
    assert dispatch_registry.memo_owner(
        "daft_tpu/device/compiler.py", "compile_projection") == "caller"
    assert dispatch_registry.memo_owner(
        "daft_tpu/device/mfu.py", "measure_join") == "exempt"
