"""daft-lint: the engine-aware static analysis pass + lock sanitizer.

Covers every rule family with fixture snippets (positive + negative +
pragma), the knob-registry round-trip against the live tree, README
knob-table drift, the lock sanitizer's cycle detection, and — the
tier-1 gate — the linter exiting CLEAN on this repo with an empty
baseline.
"""

import os
import re
import threading
import time

import pytest

from daft_tpu.analysis import knobs, lock_sanitizer
from daft_tpu.analysis import framework
from daft_tpu.analysis import (rule_determinism, rule_jit, rule_knobs,
                               rule_locks)
from daft_tpu.analysis.framework import (DEFAULT_SUBDIRS, load_baseline,
                                         repo_root, run_analysis,
                                         walk_sources)

REPO = repo_root()

# fixture literals are SPLIT so this file's own raw text never looks like
# a real knob mention or pragma to the repo-wide scans it tests
BOGUS_KNOB = "DAFT_TPU_" + "BOGUS"
NOT_A_KNOB = "DAFT_TPU_" + "NOT_A_KNOB"
PRAGMA = "# daft-lint: "


def _sources_from(tmp_path, relpath: str, code: str):
    p = tmp_path / relpath
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(code)
    return walk_sources(str(tmp_path), (relpath.split("/")[0],))


# ------------------------------------------------------------ rule: knobs

def test_unregistered_knob_read_is_flagged(tmp_path):
    srcs = _sources_from(
        tmp_path, "daft_tpu/foo.py",
        f'import os\nv = os.environ.get("{BOGUS_KNOB}")\n')
    rules = [f.rule for f in rule_knobs.check(srcs)]
    assert "knob-unregistered" in rules


def test_registered_direct_read_is_flagged(tmp_path):
    srcs = _sources_from(
        tmp_path, "daft_tpu/foo.py",
        'import os\nv = os.environ["DAFT_TPU_MAX_RETRIES"]\n')
    rules = [f.rule for f in rule_knobs.check(srcs)]
    assert "knob-direct-read" in rules


def test_accessor_type_mismatch_is_flagged(tmp_path):
    srcs = _sources_from(
        tmp_path, "daft_tpu/foo.py",
        'from daft_tpu.analysis import knobs\n'
        'v = knobs.env_int("DAFT_TPU_SHUFFLE_COMPRESSION")\n')
    rules = [f.rule for f in rule_knobs.check(srcs)]
    assert "knob-type-mismatch" in rules


def test_correct_accessor_read_is_clean(tmp_path):
    srcs = _sources_from(
        tmp_path, "daft_tpu/foo.py",
        'from daft_tpu.analysis import knobs\n'
        'v = knobs.env_int("DAFT_TPU_MAX_RETRIES")\n'
        'w = knobs.env_str("DAFT_TPU_SHUFFLE_COMPRESSION")\n')
    bad = [f for f in rule_knobs.check(srcs)
           if f.rule in ("knob-direct-read", "knob-type-mismatch",
                         "knob-unregistered")]
    assert bad == []


def test_env_write_is_not_a_read(tmp_path):
    srcs = _sources_from(
        tmp_path, "daft_tpu/foo.py",
        'import os\nos.environ["DAFT_TPU_MAX_RETRIES"] = "5"\n')
    assert [f for f in rule_knobs.check(srcs)
            if f.rule == "knob-direct-read"] == []


def test_pragma_with_reason_suppresses(tmp_path):
    code = ('import os\n'
            'v = os.environ.get("DAFT_TPU_MAX_RETRIES")  '
            + PRAGMA + 'allow(knob-direct-read) -- bootstrap read\n')
    p = tmp_path / "daft_tpu" / "foo.py"
    p.parent.mkdir(parents=True)
    p.write_text(code)
    findings = run_analysis(str(tmp_path), subdirs=("daft_tpu",),
                            contracts=False, readme=False, baseline=[])
    # knob-unused fires for the whole registry on a one-file tree; the
    # rules under test here are the read-site ones
    assert [f for f in findings
            if f.rule in ("knob-direct-read", "pragma-missing-reason")] == []


def test_pragma_without_reason_is_itself_a_finding(tmp_path):
    code = ('import os\n'
            'v = os.environ.get("DAFT_TPU_MAX_RETRIES")  '
            + PRAGMA + 'allow(knob-direct-read)\n')
    p = tmp_path / "daft_tpu" / "foo.py"
    p.parent.mkdir(parents=True)
    p.write_text(code)
    findings = run_analysis(str(tmp_path), subdirs=("daft_tpu",),
                            contracts=False, readme=False, baseline=[])
    rules = [f.rule for f in findings]
    assert "pragma-missing-reason" in rules
    # and the reason-less pragma does NOT suppress the underlying finding
    assert "knob-direct-read" in rules


# ------------------------------------------------ rule: knob round-trip

def test_every_knob_in_the_tree_is_registered():
    """Live-scan round trip: every DAFT_TPU_* name mentioned anywhere in
    the engine/tests/bench/README must be a registered knob (this is the
    check that caught the phantom DAFT_TPU_ENABLE_AQE doc knob)."""
    pat = re.compile(r"DAFT_TPU_[A-Z0-9_]+")
    mentioned = set()
    for sub in ("daft_tpu", "tests", "bench.py", "README.md"):
        base = os.path.join(REPO, sub)
        paths = [base] if os.path.isfile(base) else [
            os.path.join(dp, fn) for dp, dns, fns in os.walk(base)
            if "__pycache__" not in dp
            for fn in fns if fn.endswith((".py", ".md"))]
        for path in paths:
            if path.endswith("test_analysis.py"):
                continue    # this file's fixtures are split, but be safe
            with open(path, encoding="utf-8", errors="ignore") as f:
                mentioned.update(pat.findall(f.read()))
    unregistered = sorted(m for m in mentioned if m not in knobs.REGISTRY)
    assert unregistered == [], \
        f"mentioned but not in the knob registry: {unregistered}"


def test_every_registered_knob_is_used():
    srcs = walk_sources(REPO, DEFAULT_SUBDIRS)
    unused = [f for f in rule_knobs.check(srcs) if f.rule == "knob-unused"]
    assert unused == [], [f.message for f in unused]


def test_stale_registry_entry_is_flagged(tmp_path, monkeypatch):
    """knob-unused actually bites: a registered knob nothing reads."""
    ghost = knobs.Knob("DAFT_TPU_" + "GHOST", "int", 1,
                       "daft_tpu/x.py", "core", "phantom")
    monkeypatch.setitem(knobs.REGISTRY, ghost.name, ghost)
    srcs = _sources_from(tmp_path, "daft_tpu/foo.py", "x = 1\n")
    assert any(f.rule == "knob-unused" and "GHOST" in f.message
               for f in rule_knobs.check(srcs))


def test_unused_prefix_knob_not_masked_by_longer_name(tmp_path):
    """Usage matching is full-token: mentioning DAFT_TPU_DEVICE_FORCE
    must not count as a use of DAFT_TPU_DEVICE (review find: the
    substring match made prefix knobs un-flaggable)."""
    srcs = _sources_from(tmp_path, "daft_tpu/foo.py",
                         'x = "DAFT_TPU_DEVICE_FORCE"\n')
    unused = {f.message.split()[0] for f in rule_knobs.check(srcs)
              if f.rule == "knob-unused"}
    assert "DAFT_TPU_DEVICE" in unused
    assert "DAFT_TPU_DEVICE_FORCE" not in unused


def test_device_force_accepts_documented_spellings(monkeypatch):
    """The registry table documents 1/device and 0/host; the parse site
    must accept exactly those (review find: doc drift introduced by the
    registry meant to prevent it)."""
    from daft_tpu.device import costmodel
    for v, want in [("1", True), ("device", True), ("DEVICE", True),
                    ("0", False), ("host", False), ("unknown", None)]:
        monkeypatch.setenv("DAFT_TPU_DEVICE_FORCE", v)
        assert costmodel._forced() is want, (v, want)
    monkeypatch.delenv("DAFT_TPU_DEVICE_FORCE")
    assert costmodel._forced() is None


def test_registry_types_parse_their_defaults():
    for name, k in knobs.REGISTRY.items():
        assert k.type in ("int", "float", "bool", "str", "bytes"), name
        assert k.doc and k.module and k.group, name
        if k.default is not None and k.type in ("int", "float", "bool"):
            parsed = knobs.parse(name, str(
                int(k.default) if k.type != "float" else k.default))
            assert parsed == k.default or k.type == "bool", name


def test_accessors_parse_and_type_check(monkeypatch):
    monkeypatch.setenv("DAFT_TPU_MAX_RETRIES", "7")
    assert knobs.env_int("DAFT_TPU_MAX_RETRIES") == 7
    monkeypatch.delenv("DAFT_TPU_MAX_RETRIES")
    assert knobs.env_int("DAFT_TPU_MAX_RETRIES") == 3  # registry default
    monkeypatch.setenv("DAFT_TPU_IO_COALESCE_GAP", "2MiB")
    assert knobs.env_bytes("DAFT_TPU_IO_COALESCE_GAP") == 2 << 20
    monkeypatch.setenv("DAFT_TPU_CHAOS_SERIALIZE", "off")
    assert knobs.env_bool("DAFT_TPU_CHAOS_SERIALIZE") is False
    with pytest.raises(knobs.UnknownKnobError):
        knobs.env_int(NOT_A_KNOB)
    with pytest.raises(TypeError):
        knobs.env_int("DAFT_TPU_SHUFFLE_COMPRESSION")  # registered str


# ----------------------------------------------------- rule: determinism

_CRITICAL = "daft_tpu/distributed/worker.py"

def test_unseeded_random_flagged_in_replay_critical(tmp_path):
    srcs = _sources_from(tmp_path, _CRITICAL,
                         "import random\nx = random.random()\n")
    assert [f.rule for f in rule_determinism.check(srcs)] \
        == ["unseeded-random"]


def test_seeded_rng_not_flagged(tmp_path):
    srcs = _sources_from(
        tmp_path, _CRITICAL,
        "import numpy as np\nrng = np.random.default_rng(0)\n")
    assert rule_determinism.check(srcs) == []


def test_wallclock_decision_flagged(tmp_path):
    srcs = _sources_from(
        tmp_path, _CRITICAL,
        "import time\ndeadline = 5\n"
        "def f():\n"
        "    if time.monotonic() > deadline:\n"
        "        return 1\n")
    assert [f.rule for f in rule_determinism.check(srcs)] \
        == ["wallclock-decision"]


def test_wallclock_metric_not_flagged(tmp_path):
    srcs = _sources_from(
        tmp_path, _CRITICAL,
        "import time\n"
        "def f():\n"
        "    t0 = time.perf_counter()\n"
        "    work()\n"
        "    return time.perf_counter() - t0\n")
    assert rule_determinism.check(srcs) == []


def test_as_completed_flagged(tmp_path):
    srcs = _sources_from(
        tmp_path, _CRITICAL,
        "import concurrent.futures as cf\n"
        "def f(futs):\n"
        "    return [x.result() for x in cf.as_completed(futs)]\n")
    assert "unordered-pool-iteration" in \
        [f.rule for f in rule_determinism.check(srcs)]


def test_noncritical_module_exempt(tmp_path):
    srcs = _sources_from(tmp_path, "daft_tpu/somewhere_else.py",
                         "import random\nx = random.random()\n")
    assert rule_determinism.check(srcs) == []


# ----------------------------------------------------------- rule: locks

def test_sleep_under_lock_flagged(tmp_path):
    srcs = _sources_from(
        tmp_path, "daft_tpu/foo.py",
        "import threading, time\n_lock = threading.Lock()\n"
        "def f():\n"
        "    with _lock:\n"
        "        time.sleep(1)\n")
    assert [f.rule for f in rule_locks.check(srcs)] \
        == ["blocking-under-lock"]


def test_blocking_helper_called_under_lock_flagged(tmp_path):
    srcs = _sources_from(
        tmp_path, "daft_tpu/foo.py",
        "import threading\n_lock = threading.Lock()\n"
        "def helper(p):\n"
        "    with open(p) as f:\n"
        "        return f.read()\n"
        "def f(p):\n"
        "    with _lock:\n"
        "        return helper(p)\n")
    found = rule_locks.check(srcs)
    assert [f.rule for f in found] == ["blocking-under-lock"]
    assert "helper" in found[0].message


def test_string_join_under_lock_not_flagged(tmp_path):
    srcs = _sources_from(
        tmp_path, "daft_tpu/foo.py",
        "import threading, os\n_lock = threading.Lock()\n"
        "def f(parts):\n"
        "    with _lock:\n"
        "        return ', '.join(parts) + os.path.join('a', 'b')\n")
    assert rule_locks.check(srcs) == []


def test_unguarded_global_rebind_flagged(tmp_path):
    srcs = _sources_from(
        tmp_path, "daft_tpu/foo.py",
        "_POOL = None\n"
        "def pool():\n"
        "    global _POOL\n"
        "    if _POOL is None:\n"
        "        _POOL = object()\n"
        "    return _POOL\n")
    assert [f.rule for f in rule_locks.check(srcs)] \
        == ["unguarded-global-mutation"]


def test_lock_guarded_global_rebind_clean(tmp_path):
    srcs = _sources_from(
        tmp_path, "daft_tpu/foo.py",
        "import threading\n_POOL = None\n_lock = threading.Lock()\n"
        "def pool():\n"
        "    global _POOL\n"
        "    with _lock:\n"
        "        if _POOL is None:\n"
        "            _POOL = object()\n"
        "        return _POOL\n")
    assert rule_locks.check(srcs) == []


# ------------------------------------------------------------- rule: jit

def test_host_effect_and_np_on_traced_flagged(tmp_path):
    srcs = _sources_from(
        tmp_path, "daft_tpu/device/foo.py",
        "import jax\nimport numpy as np\nfrom functools import partial\n"
        "@partial(jax.jit)\n"
        "def k(x):\n"
        "    print('tracing')\n"
        "    return np.sum(x)\n")
    rules = sorted(f.rule for f in rule_jit.check(srcs))
    assert rules == ["host-effect-in-jit", "np-in-jit"]


def test_static_np_metadata_in_jit_allowed(tmp_path):
    srcs = _sources_from(
        tmp_path, "daft_tpu/device/foo.py",
        "import jax\nimport numpy as np\nfrom functools import partial\n"
        "@partial(jax.jit, static_argnames=('d',))\n"
        "def k(x, d):\n"
        "    bits = np.iinfo(np.int64).bits\n"
        "    n = np.zeros(4)\n"     # untainted np is trace-time constant
        "    return x\n")
    assert rule_jit.check(srcs) == []


def test_wrap_site_jit_detected(tmp_path):
    srcs = _sources_from(
        tmp_path, "daft_tpu/device/foo.py",
        "import jax\n"
        "def impl(x):\n"
        "    print('boom')\n"
        "    return x\n"
        "kernel = jax.jit(impl)\n")
    assert [f.rule for f in rule_jit.check(srcs)] == ["host-effect-in-jit"]


def test_dispatch_contracts_hold():
    """PR 1's kernel contracts re-proven from freshly-built jaxprs."""
    assert rule_jit.check_dispatch_contracts() == []


# -------------------------------------------------------- lock sanitizer

def test_cycle_detection_two_threads_inverted_order():
    san = lock_sanitizer.LockOrderSanitizer()
    la = san.track(threading.Lock(), "daft_tpu/a.py:1")
    lb = san.track(threading.Lock(), "daft_tpu/b.py:1")
    order_ab = threading.Event()

    def t1():
        with la:
            with lb:
                pass
        order_ab.set()

    def t2():
        order_ab.wait(5)
        with lb:
            with la:
                pass

    th1, th2 = threading.Thread(target=t1), threading.Thread(target=t2)
    th1.start(); th2.start(); th1.join(5); th2.join(5)
    s = san.summary()
    assert len(s["cycles"]) == 1
    assert "daft_tpu/a.py:1" in s["cycles"][0] \
        and "daft_tpu/b.py:1" in s["cycles"][0]
    assert "POTENTIAL DEADLOCK" in san.report()


def test_consistent_order_reports_no_cycle():
    san = lock_sanitizer.LockOrderSanitizer()
    la = san.track(threading.Lock(), "daft_tpu/a.py:1")
    lb = san.track(threading.Lock(), "daft_tpu/b.py:1")
    for _ in range(3):
        with la:
            with lb:
                pass
    s = san.summary()
    assert s["cycles"] == [] and s["edges"] == 1 and s["locks"] == 2


def test_rlock_reentrance_is_not_an_edge():
    san = lock_sanitizer.LockOrderSanitizer()
    lr = san.track(threading.RLock(), "daft_tpu/r.py:1")
    with lr:
        with lr:
            pass
    assert san.summary()["edges"] == 0


def test_contention_is_counted():
    san = lock_sanitizer.LockOrderSanitizer()
    lock = san.track(threading.Lock(), "daft_tpu/c.py:1")
    acquired = threading.Event()
    release = threading.Event()

    def holder():
        with lock:
            acquired.set()
            release.wait(5)

    th = threading.Thread(target=holder)
    th.start()
    acquired.wait(5)
    waiter = threading.Thread(target=lambda: lock.acquire() or
                              lock.release())
    waiter.start()
    time.sleep(0.05)   # let the waiter hit the contended probe
    release.set()
    th.join(5); waiter.join(5)
    assert san.summary()["contended"] >= 1


def test_enabled_sanitizer_tracks_engine_locks_and_blocking():
    """enable() wraps locks created by engine code (allocation site under
    daft_tpu/) and records sleep-while-held; foreign locks (created here,
    in tests/) stay untracked."""
    was_enabled = lock_sanitizer.is_enabled()
    lock_sanitizer.enable()
    try:
        from daft_tpu.observability import OperatorStats
        before = lock_sanitizer.counters_snapshot()
        st = OperatorStats("probe")      # engine-created → tracked
        assert type(st.lock).__name__ == "_TrackedLock"
        foreign = threading.Lock()       # test-created → real lock
        assert type(foreign).__name__ != "_TrackedLock"
        with st.lock:
            time.sleep(0.001)
        after = lock_sanitizer.counters_snapshot()
        assert after["acquisitions"] > before["acquisitions"]
        assert after["blocking_while_held"] > before["blocking_while_held"]
    finally:
        if not was_enabled:
            lock_sanitizer.disable()


def test_observability_renders_sanitizer_block():
    was_enabled = lock_sanitizer.is_enabled()
    lock_sanitizer.enable()
    try:
        from daft_tpu.observability import RuntimeStatsContext
        ctx = RuntimeStatsContext()
        from daft_tpu.observability import OperatorStats
        st = OperatorStats("probe")
        with st.lock:
            pass
        ctx.finish()
        out = ctx.render()
        assert "concurrency (lock sanitizer):" in out
        assert "lock sites" in out
    finally:
        if not was_enabled:
            lock_sanitizer.disable()


def test_queue_condition_compat_under_sanitizer():
    """queue.Queue builds Conditions over the (possibly wrapped) lock —
    the proxy must keep put/get working. Regression for the
    _release_save forwarding hazard."""
    was_enabled = lock_sanitizer.is_enabled()
    lock_sanitizer.enable()
    try:
        import queue
        q = queue.Queue(maxsize=2)
        q.put(1); q.put(2)
        assert q.get() == 1 and q.get() == 2
    finally:
        if not was_enabled:
            lock_sanitizer.disable()


# ----------------------------------------------------- repo-level gates

def test_baseline_is_empty():
    """Grandfathering is banned: fix it or pragma-justify it."""
    assert load_baseline() == []


def test_readme_knob_tables_in_sync():
    with open(os.path.join(REPO, "README.md"), encoding="utf-8") as f:
        text = f.read()
    assert knobs.readme_drift(text) == []
    # and a stale edit IS caught (the drift test actually bites)
    broken = text.replace("`DAFT_TPU_SHUFFLE_COMPRESSION`",
                          "`DAFT_TPU_SHUFFLE_" + "COMPRESSON`", 1)
    assert knobs.readme_drift(broken) != []


def test_linter_clean_on_repo_tree():
    """THE tier-1 gate: `python -m daft_tpu.analysis` is clean — every
    finding fixed or pragma-justified, baseline empty, README generated
    tables fresh, dispatch contracts proven."""
    findings = run_analysis(REPO, contracts=True, readme=True)
    assert findings == [], "\n".join(f.render() for f in findings)


# ------------------------------------- burn-down fix regression tests
# genuine findings the linter surfaced, fixed in this PR — these pin the
# fixes down

def test_executor_pool_creation_is_single_under_race():
    """daft-lint unguarded-global-mutation find: two racing first callers
    each built a ThreadPoolExecutor and the loser's worker threads leaked
    for the process lifetime. Creation is lock-guarded now."""
    from daft_tpu.execution import executor as ex
    old = ex._POOL
    ex._POOL = None
    try:
        barrier = threading.Barrier(8)
        got = []

        def go():
            barrier.wait(5)
            got.append(ex._pool())

        threads = [threading.Thread(target=go) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(10)
        assert len(got) == 8 and len({id(p) for p in got}) == 1
    finally:
        created = ex._POOL
        ex._POOL = old
        if created is not None and created is not old:
            created.shutdown(wait=False)


def test_session_singleton_is_single_under_race():
    """daft-lint unguarded-global-mutation find: two racing first callers
    each built a Session — attachments made through the loser silently
    vanished. Creation is lock-guarded now."""
    from daft_tpu import session as se
    old = se._SESSION
    se._SESSION = None
    try:
        barrier = threading.Barrier(8)
        got = []

        def go():
            barrier.wait(5)
            got.append(se._session())

        threads = [threading.Thread(target=go) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(10)
        assert len(got) == 8 and len({id(s) for s in got}) == 1
    finally:
        se._SESSION = old


def test_orphan_sweep_runs_exactly_once_under_race(monkeypatch):
    """daft-lint unguarded-global-mutation find: the startup orphan sweep
    was check-then-set; concurrent first servers each ran the glob+stat
    walk. Now flag-flip is atomic."""
    from daft_tpu.distributed import shuffle_service as ss
    calls = []
    monkeypatch.setattr(ss, "sweep_orphaned_shuffles",
                        lambda: calls.append(1))
    monkeypatch.setattr(ss, "FlightShuffleServer",
                        lambda *a, **k: object(), raising=False)
    monkeypatch.setattr(ss, "ShuffleServer", lambda *a, **k: object())
    monkeypatch.setattr(ss, "_swept_once", False)
    barrier = threading.Barrier(8)

    def go():
        barrier.wait(5)
        ss.make_shuffle_server()

    threads = [threading.Thread(target=go) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(10)
    assert len(calls) == 1


def test_mesh_size_memo_is_reentrant():
    """mesh._size is now computed under the module lock; the lock became
    re-entrant because get_mesh() already holds it around mesh_size()."""
    from daft_tpu.parallel import mesh
    n1 = mesh.mesh_size()
    n2 = mesh.mesh_size()
    assert n1 == n2


def test_cli_knob_docs_prints_all_groups(capsys):
    from daft_tpu.analysis.__main__ import main
    assert main(["--knob-docs"]) == 0
    out = capsys.readouterr().out
    for group in knobs.GROUPS:
        assert f"### {group}" in out
    assert "DAFT_TPU_SANITIZE" in out
