"""PySpark SparkSession shim (reference: ``daft/pyspark``): boots the
embedded connect server; the pyspark client itself is optional, so without
it the builder must fail actionably AFTER standing up a working server."""

import grpc
import pytest

from daft_tpu.pyspark import SparkSession, SparkSessionBuilder


def test_builder_is_fresh_per_access():
    assert SparkSession.builder is not SparkSession.builder
    assert isinstance(SparkSession.builder, SparkSessionBuilder)


def test_local_builder_boots_connect_server():
    b = SparkSession.builder.local()
    try:
        assert b._remote.startswith("sc://127.0.0.1:")
        # the endpoint is a live Spark Connect service
        import daft_tpu.connect.spark_connect_subset_pb2 as pb
        host = b._remote[len("sc://"):]
        ch = grpc.insecure_channel(host)
        stub = ch.unary_unary(
            "/spark.connect.SparkConnectService/AnalyzePlan",
            request_serializer=pb.AnalyzePlanRequest.SerializeToString,
            response_deserializer=pb.AnalyzePlanResponse.FromString)
        resp = stub(pb.AnalyzePlanRequest(
            session_id="s",
            spark_version=pb.AnalyzePlanRequest.SparkVersion()))
        assert "daft-tpu" in resp.spark_version.version
        ch.close()
    finally:
        b._server.stop()


def test_get_or_create_without_pyspark_errors_actionably():
    try:
        import pyspark  # noqa: F401
        pytest.skip("pyspark installed; gate not reachable")
    except ImportError:
        pass
    b = SparkSession.builder.local()
    try:
        with pytest.raises(ImportError, match="pyspark"):
            b.getOrCreate()
    finally:
        b._server.stop()
