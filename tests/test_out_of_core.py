"""Out-of-core execution tests: grace hash join + spill-partitioned
aggregation (execution/out_of_core.py) — bit-parity of spilled vs
in-memory answers under tiny DAFT_TPU_MEMORY_LIMIT budgets, forced
recursion, skewed/NULL keys, admission release on cancellation, spill
compression, deterministic lifecycle, and the spill stats block."""

import os
import threading

import numpy as np
import pytest

import daft_tpu as daft
from daft_tpu import col
from daft_tpu.device import costmodel
from daft_tpu.execution import memory, out_of_core as ooc
from daft_tpu.micropartition import MicroPartition
from daft_tpu.recordbatch import RecordBatch


def _sorted_pydict(d):
    keys = list(d.keys())
    rows = sorted(zip(*[d[k] for k in keys]),
                  key=lambda r: tuple((v is None, v) for v in r))
    return {k: [r[i] for r in rows] for i, k in enumerate(keys)}


def _join_dfs(n=60_000, ndv=20_000, nulls=False):
    k = (np.arange(n) % ndv).astype(object)
    if nulls:
        k = k.copy()
        k[::97] = None
    left = daft.from_pydict({"k": list(k), "v": list(range(n))})
    right = daft.from_pydict({"k": list(k[: n // 2]),
                              "w": [i * 3 for i in range(n // 2)]})
    return left, right


@pytest.fixture
def tiny_budget(monkeypatch):
    monkeypatch.setenv("DAFT_TPU_MEMORY_LIMIT", "400KB")
    yield


# ----------------------------------------------------------- grace join

def test_grace_join_parity_vs_in_memory(tiny_budget, monkeypatch):
    """Spilled (partitioned + recursing) join answers are bit-identical
    to the unbounded in-memory run."""
    left, right = _join_dfs()
    spilled = _sorted_pydict(
        left.join(right, on="k", strategy="hash").to_pydict())
    monkeypatch.delenv("DAFT_TPU_MEMORY_LIMIT")
    monkeypatch.setenv("DAFT_TPU_SPILL_JOIN", "0")  # legacy reference
    ref = _sorted_pydict(
        left.join(right, on="k", strategy="hash").to_pydict())
    assert spilled == ref


def test_grace_join_partitions_and_recurses(tiny_budget):
    left, right = _join_dfs()
    b0 = memory.spill_counters_snapshot()
    left.join(right, on="k", strategy="hash").to_pydict()
    d = memory.spill_counters_delta(b0)
    assert d.get("joins_partitioned", 0) >= 1
    assert d.get("bytes_written", 0) > 0
    assert d.get("bytes_read", 0) > 0


def test_forced_recursion_depth(tiny_budget, monkeypatch):
    """DAFT_TPU_SPILL_PARTITIONS=2 under-partitions on purpose so the
    first radix level leaves oversized buckets → rotated-radix
    recursion must kick in (and the answer must not change)."""
    monkeypatch.setenv("DAFT_TPU_SPILL_PARTITIONS", "2")
    left, right = _join_dfs()
    b0 = memory.spill_counters_snapshot()
    spilled = _sorted_pydict(
        left.join(right, on="k", strategy="hash").to_pydict())
    d = memory.spill_counters_delta(b0)
    assert d.get("recursions", 0) >= 1
    assert any(k.startswith("recursions_d") for k in d)
    monkeypatch.delenv("DAFT_TPU_MEMORY_LIMIT")
    monkeypatch.delenv("DAFT_TPU_SPILL_PARTITIONS")
    ref = _sorted_pydict(
        left.join(right, on="k", strategy="hash").to_pydict())
    assert spilled == ref


def test_skewed_key_exhausts_depth_not_memory(tiny_budget, monkeypatch):
    """One all-duplicate key redominates every radix level: the depth
    bound trips (counted) and the bucket joins in memory anyway —
    bounded recursion, correct answer."""
    monkeypatch.setenv("DAFT_TPU_SPILL_PARTITIONS", "2")
    monkeypatch.setenv("DAFT_TPU_SPILL_MAX_DEPTH", "1")
    n = 40_000
    left = daft.from_pydict({"k": [7] * n, "v": list(range(n))})
    right = daft.from_pydict({"k": [7] * 4, "w": [1, 2, 3, 4]})
    b0 = memory.spill_counters_snapshot()
    out = left.join(right, on="k", strategy="hash").to_pydict()
    d = memory.spill_counters_delta(b0)
    assert len(out["v"]) == n * 4
    assert d.get("depth_exhausted", 0) >= 1


def test_null_keys_never_match_all_join_types(tiny_budget, monkeypatch):
    left, right = _join_dfs(n=30_000, ndv=10_000, nulls=True)
    for how in ("inner", "left", "outer", "semi", "anti"):
        spilled = _sorted_pydict(
            left.join(right, on="k", how=how, strategy="hash").to_pydict())
        monkeypatch.setenv("DAFT_TPU_SPILL_JOIN", "0")
        monkeypatch.delenv("DAFT_TPU_MEMORY_LIMIT")
        ref = _sorted_pydict(
            left.join(right, on="k", how=how, strategy="hash").to_pydict())
        monkeypatch.setenv("DAFT_TPU_MEMORY_LIMIT", "400KB")
        monkeypatch.delenv("DAFT_TPU_SPILL_JOIN")
        assert spilled == ref, how


def test_copartitioned_pair_skew_guard(tiny_budget):
    """The statically co-partitioned (exchange-fed) join re-partitions
    an oversized partition pair instead of joining it whole."""
    # the LEFT side alone trips the pair budget (5 hot keys x 12k rows
    # per key >> 100KB); the right stays tiny so the joined output is
    # 600k rows, not 180M — the guard keys on pair INPUT bytes
    n = 60_000
    left = daft.from_pydict({"k": [i % 5 for i in range(n)],
                             "v": list(range(n))}).repartition(4, "k")
    right = daft.from_pydict({"k": [i % 5 for i in range(50)],
                              "w": list(range(50))}).repartition(4, "k")
    b0 = memory.spill_counters_snapshot()
    out = left.join(right, on="k", strategy="hash").groupby("k") \
        .agg(col("v").count()).sort("k").to_pydict()
    d = memory.spill_counters_delta(b0)
    assert len(out["k"]) == 5
    assert d.get("recursions", 0) >= 1  # skewed pairs re-partitioned


def test_small_join_gathers(monkeypatch):
    """Without memory pressure the observed totals fit the pair budget:
    spill_plan_wins declines partitioned execution and ONE gathered
    join runs."""
    monkeypatch.delenv("DAFT_TPU_MEMORY_LIMIT", raising=False)
    left = daft.from_pydict({"k": [1, 2, 3], "v": [10, 20, 30]})
    right = daft.from_pydict({"k": [2, 3, 4], "w": [5, 6, 7]})
    b0 = memory.spill_counters_snapshot()
    out = left.join(right, on="k", strategy="hash").sort("k").to_pydict()
    d = memory.spill_counters_delta(b0)
    assert out["k"] == [2, 3]
    assert d.get("joins_gathered", 0) >= 1
    assert d.get("joins_partitioned", 0) == 0
    assert not d.get("bytes_written")


def test_spill_plan_wins_pricing():
    assert costmodel.spill_plan_wins(100 << 20, 1 << 20)
    assert not costmodel.spill_plan_wins(1 << 10, 1 << 20)
    assert "spill_plan" in costmodel.decision_counts


# ------------------------------------------------ spill-partitioned agg

def _agg_df(n=120_000, ndv=None):
    ndv = ndv or n  # near-unique keys: unbounded-NDV shape
    return daft.from_pydict({
        "k": [i % ndv for i in range(n)],
        "v": [float(i % 97) for i in range(n)],
        "c": [i % 7 for i in range(n)],
    })


def test_spilled_agg_parity(tiny_budget, monkeypatch):
    """Forced spilling reducer vs in-memory reducer: identical grouped
    answers on a near-unique key (the shape the fused reducer used to
    decline)."""
    monkeypatch.setenv("DAFT_TPU_SPILL_AGG", "1")
    df = _agg_df()
    q = lambda d: _sorted_pydict(
        d.groupby("k").agg(col("v").sum(), col("c").max()).to_pydict())
    b0 = memory.spill_counters_snapshot()
    spilled = q(df)
    d = memory.spill_counters_delta(b0)
    assert d.get("agg_buckets_merged", 0) > 0
    monkeypatch.delenv("DAFT_TPU_MEMORY_LIMIT")
    monkeypatch.setenv("DAFT_TPU_SPILL_AGG", "0")
    assert spilled == q(df)


def test_spilled_agg_auto_under_budget(tiny_budget):
    """auto mode: a group state the budget can't hold takes the
    spilling reducer instead of declining the fusion (rows-estimate
    evidence is absent for in-memory sources, so this exercises the
    inadmissible-by-budget path only when evidence exists — force via
    the knob-free shape: tiny budget + near-unique keys + footerless
    source still must produce correct answers)."""
    df = _agg_df(n=60_000)
    out = _sorted_pydict(
        df.groupby("k").agg(col("v").sum()).to_pydict())
    assert len(out["k"]) == 60_000


def test_spilled_agg_skewed_recursion(tiny_budget, monkeypatch):
    """Skewed group keys (one giant group + near-unique tail) with a
    forced-small fanout: the overflowing state bucket recursively
    re-partitions and the merged answer stays exact."""
    monkeypatch.setenv("DAFT_TPU_SPILL_AGG", "1")
    monkeypatch.setenv("DAFT_TPU_SPILL_PARTITIONS", "2")
    n = 100_000
    df = daft.from_pydict({
        "k": [0 if i % 2 else i for i in range(n)],
        "v": [1.0] * n,
    })
    spilled = _sorted_pydict(
        df.groupby("k").agg(col("v").sum()).to_pydict())
    monkeypatch.delenv("DAFT_TPU_MEMORY_LIMIT")
    monkeypatch.setenv("DAFT_TPU_SPILL_AGG", "0")
    monkeypatch.delenv("DAFT_TPU_SPILL_PARTITIONS")
    ref = _sorted_pydict(df.groupby("k").agg(col("v").sum()).to_pydict())
    assert spilled == ref


def test_spilled_agg_null_keys(tiny_budget, monkeypatch):
    monkeypatch.setenv("DAFT_TPU_SPILL_AGG", "1")
    df = daft.from_pydict({"k": [None if i % 5 == 0 else i % 1000
                                 for i in range(20_000)],
                           "v": list(range(20_000))})
    spilled = _sorted_pydict(df.groupby("k").agg(col("v").sum())
                             .to_pydict())
    monkeypatch.delenv("DAFT_TPU_MEMORY_LIMIT")
    monkeypatch.setenv("DAFT_TPU_SPILL_AGG", "0")
    ref = _sorted_pydict(df.groupby("k").agg(col("v").sum()).to_pydict())
    assert spilled == ref


# ---------------------------------------------- cancellation + admission

def test_cancellation_mid_partition_releases_admission(tiny_budget):
    """Cancelling a grace join mid-drain unwinds the pair loop and
    releases every admitted byte (the r11 leak invariant)."""
    from daft_tpu.execution import cancellation as cxl
    from daft_tpu.execution.executor import LocalExecutor

    left, right = _join_dfs(n=40_000, ndv=40_000)
    tok = cxl.CancelToken()
    holder = {}
    orig = ooc._join_pair

    def cancel_after_first(*args, **kwargs):
        out = orig(*args, **kwargs)
        tok.set("test")
        return out

    ooc._join_pair = cancel_after_first
    try:
        with cxl.cancel_scope(tok):
            ex = LocalExecutor()
            holder["ex"] = ex
            builder = left.join(right, on="k", strategy="hash")._builder
            opt = builder.optimize()
            from daft_tpu.physical.translate import translate
            plan = translate(opt._plan)
            with pytest.raises(cxl.QueryCancelled):
                for _ in ex.run(plan):
                    pass
    finally:
        ooc._join_pair = orig
    assert holder["ex"].mem.outstanding == 0


# ------------------------------------------------------------ lifecycle

def test_context_managers_close_deterministically(tmp_path, monkeypatch):
    monkeypatch.setenv("DAFT_TPU_SPILL_DIR", str(tmp_path))
    memory._spill_dir = None
    rb = RecordBatch.from_pydict({"x": list(range(2000))})
    with memory.PartitionedSpillStore(4, budget=1) as store:
        store.push(0, rb)
        store.push(1, rb)
        store.finalize()
        assert any(e.startswith("pstore_") for e in os.listdir(tmp_path))
    assert not any(os.listdir(os.path.join(tmp_path, e))
                   for e in os.listdir(tmp_path)
                   if os.path.isdir(os.path.join(tmp_path, e)))
    with memory.SpillBuffer(budget=1) as buf:
        buf.append(MicroPartition.from_recordbatch(rb))
        assert buf.bytes_spilled > 0
    assert not any(f.endswith(".arrow") for f in os.listdir(tmp_path))
    memory._spill_dir = None


def test_no_spill_dirs_leak_after_query(tmp_path, monkeypatch):
    """After a spilling grace join completes, its spill directory holds
    no bucket files — deterministic close(), not GC."""
    monkeypatch.setenv("DAFT_TPU_SPILL_DIR", str(tmp_path))
    monkeypatch.setenv("DAFT_TPU_MEMORY_LIMIT", "400KB")
    memory._spill_dir = None
    left, right = _join_dfs(n=30_000, ndv=10_000)
    left.join(right, on="k", strategy="hash").to_pydict()
    leftovers = []
    for root, _dirs, files in os.walk(tmp_path):
        leftovers.extend(os.path.join(root, f) for f in files)
    assert leftovers == []
    memory._spill_dir = None


# ---------------------------------------------------------- compression

@pytest.mark.parametrize("codec", ["lz4", "zstd", "none"])
def test_spill_codec_roundtrip(tmp_path, monkeypatch, codec):
    monkeypatch.setenv("DAFT_TPU_SPILL_DIR", str(tmp_path))
    monkeypatch.setenv("DAFT_TPU_SHUFFLE_COMPRESSION", codec)
    memory._spill_dir = None
    memory._spill_ipc_cache.clear()
    rb = RecordBatch.from_pydict(
        {"x": list(range(5000)), "s": ["val%d" % (i % 50)
                                       for i in range(5000)]})
    store = memory.PartitionedSpillStore(2, budget=1)
    store.push(0, rb)
    store.push(1, rb)
    store.finalize()
    got = store.bucket_batches(0)
    assert sum(len(b) for b in got) == 5000
    assert got[0].to_pydict() == rb.to_pydict()
    store.close()
    buf = memory.SpillBuffer(budget=1)
    buf.append(MicroPartition.from_recordbatch(rb))
    assert buf[0].to_pydict() == rb.to_pydict()
    buf.close()
    memory._spill_ipc_cache.clear()
    memory._spill_dir = None


def test_spill_compression_shrinks_disk_bytes(tmp_path, monkeypatch):
    """lz4 spill files are smaller on disk than uncompressed ones for
    compressible data (the counters track LOGICAL bytes either way)."""
    monkeypatch.setenv("DAFT_TPU_SPILL_DIR", str(tmp_path))
    memory._spill_dir = None
    rb = RecordBatch.from_pydict({"x": [1] * 50_000})

    def disk_bytes(codec):
        monkeypatch.setenv("DAFT_TPU_SHUFFLE_COMPRESSION", codec)
        memory._spill_ipc_cache.clear()
        store = memory.PartitionedSpillStore(1, budget=1)
        store.push(0, rb)
        store.finalize()
        total = sum(os.path.getsize(os.path.join(r, f))
                    for r, _d, fs in os.walk(tmp_path) for f in fs)
        store.close()
        return total

    try:
        compressed = disk_bytes("lz4")
    except Exception:
        pytest.skip("lz4 codec not built into this pyarrow")
    plain = disk_bytes("none")
    assert compressed < plain
    memory._spill_ipc_cache.clear()
    memory._spill_dir = None


# ------------------------------------------------- determinism + stats

def test_chaos_serialize_spilled_run_deterministic(tiny_budget,
                                                   monkeypatch):
    """Spilled execution is deterministic by construction; under
    DAFT_TPU_CHAOS_SERIALIZE=1 two runs are bit-identical."""
    monkeypatch.setenv("DAFT_TPU_CHAOS_SERIALIZE", "1")
    left, right = _join_dfs(n=20_000, ndv=5_000)
    q = lambda: left.join(right, on="k", strategy="hash") \
        .groupby("k").agg(col("v").sum(), col("w").sum()) \
        .sort("k").to_pydict()
    assert q() == q()


def test_spill_stats_block_in_explain_analyze(tiny_budget):
    from daft_tpu import observability as obs
    left, right = _join_dfs(n=30_000, ndv=10_000)
    left.join(right, on="k", strategy="hash").to_pydict()
    stats = obs.last_query_stats_local() or obs.last_query_stats()
    assert stats is not None and stats.spill
    rendered = stats.render()
    assert "spill (out-of-core tier):" in rendered
    assert "written" in rendered


def test_spill_counters_at_metrics_endpoint(tiny_budget):
    from daft_tpu import tracing
    left, right = _join_dfs(n=20_000, ndv=5_000)
    left.join(right, on="k", strategy="hash").to_pydict()
    text = tracing.prometheus_text()
    assert "daft_tpu_spill_bytes_written_total" in text


# ------------------------------------------------------------- helpers

def test_rotated_radix_decorrelates():
    """Depth-1 sub-partitioning of one depth-0 bucket must spread rows
    across sub-buckets (the naive ``h % m`` of a ``h % n`` residue class
    collapses when gcd(n, m) > 1)."""
    rb = RecordBatch.from_pydict({"k": list(range(100_000))})
    d0 = ooc.radix_split(rb, [col("k")], 8, 0)
    bucket = d0[3]
    d1 = ooc.radix_split(bucket, [col("k")], 8, 1)
    sizes = [len(p) for p in d1]
    assert sum(sizes) == len(bucket)
    assert all(s > 0 for s in sizes)
    lo, hi = min(sizes), max(sizes)
    assert hi < 2 * lo  # roughly uniform


def test_radix_depth0_matches_partition_by_hash():
    rb = RecordBatch.from_pydict({"k": list(range(10_000))})
    a = ooc.radix_split(rb, [col("k")], 8, 0)
    b = rb.partition_by_hash([col("k")], 8)
    for x, y in zip(a, b):
        assert x.to_pydict() == y.to_pydict()


def test_plan_partitions_from_evidence():
    assert ooc.plan_partitions(None) == ooc._DEFAULT_PARTITIONS
    big = ooc.plan_partitions(10 << 30, budget=1 << 30)
    assert 2 <= big <= ooc._MAX_PARTITIONS
    assert ooc.plan_partitions(1, budget=1 << 30) == 2
