"""Device-tier join index generation (the three-phase
sort/searchsorted/expand kernels) produces the same pair sets and counts
as the host merge, end-to-end through DataFrame joins (opt-in via
DAFT_TPU_DEVICE_JOIN)."""

import numpy as np
import pytest

import daft_tpu
from daft_tpu.joins import _device_match_indices, match_indices


@pytest.fixture
def keys():
    rng = np.random.default_rng(3)
    lk = rng.integers(0, 50, 400)
    rk = rng.integers(0, 50, 150)
    lv = rng.random(400) > 0.1  # some null keys
    rv = rng.random(150) > 0.1
    return lk, rk, lv, rv


def _pairs(li, ri):
    return sorted(zip(li.tolist(), ri.tolist()))


def test_device_indices_match_host(keys):
    lk, rk, lv, rv = keys
    hli, hri, hcnt = match_indices(lk, rk, lv, rv)
    out = _device_match_indices(lk, rk, lv, rv)
    assert out is not None
    dli, dri, dcnt = out
    assert _pairs(dli, dri) == _pairs(hli, hri)
    assert np.array_equal(dcnt, hcnt)


def test_right_side_larger_than_left_capacity():
    """Regression: the expand phase must clip right slots against the
    RIGHT capacity — a tiny left side joined to a big right side used to
    remap high right rows onto wrong indices."""
    lk = np.array([180, 5], dtype=np.int64)
    rk = np.arange(200, dtype=np.int64)
    lv = np.ones(2, bool)
    rv = np.ones(200, bool)
    hli, hri, hcnt = match_indices(lk, rk, lv, rv)
    dli, dri, dcnt = _device_match_indices(lk, rk, lv, rv)
    assert _pairs(dli, dri) == _pairs(hli, hri) == [(0, 180), (1, 5)]


def test_empty_sides():
    e = np.array([], dtype=np.int64)
    eb = np.array([], dtype=bool)
    out = _device_match_indices(e, e, eb, eb)
    assert out is not None
    li, ri, cnt = out
    assert len(li) == 0 and len(cnt) == 0
    lk = np.array([1, 2], dtype=np.int64)
    lv = np.ones(2, bool)
    li, ri, cnt = _device_match_indices(lk, e, lv, eb)
    assert len(li) == 0 and list(cnt) == [0, 0]


def test_dataframe_join_through_device_path(keys, monkeypatch):
    monkeypatch.setenv("DAFT_TPU_DEVICE_JOIN", "1")
    lk, rk, _, _ = keys
    left = daft_tpu.from_pydict({"k": lk.tolist(), "lv": list(range(400))})
    right = daft_tpu.from_pydict({"k": rk.tolist(), "rv": list(range(150))})
    dev = left.join(right, on="k").to_pydict()
    monkeypatch.delenv("DAFT_TPU_DEVICE_JOIN")
    host = left.join(right, on="k").to_pydict()
    assert sorted(zip(dev["k"], dev["lv"], dev["rv"])) == \
        sorted(zip(host["k"], host["lv"], host["rv"]))


def test_outer_join_counts_drive_unmatched_rows(monkeypatch):
    monkeypatch.setenv("DAFT_TPU_DEVICE_JOIN", "1")
    left = daft_tpu.from_pydict({"k": [1, 2, 3], "lv": [10, 20, 30]})
    right = daft_tpu.from_pydict({"k": [2, 4], "rv": ["b", "d"]})
    out = left.join(right, on="k", how="outer").to_pydict()
    rows = sorted(zip(out["k"], out["lv"], out["rv"]),
                  key=lambda t: (t[0] is None, t[0] or 0))
    assert (2, 20, "b") in rows
    assert any(k == 1 and rv is None for k, lv, rv in rows)
    assert any(k == 4 and lv is None for k, lv, rv in rows)
