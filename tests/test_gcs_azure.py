"""Native GCS + Azure Blob sources against in-process mock servers (same
pattern as the S3 suite / the reference's moto-based remote-IO tests:
stdlib HTTP servers speaking just enough of each REST API — ranged GET,
PUT, stat, and paginated listing)."""

import base64
import http.server
import json
import threading
import urllib.parse

import pyarrow.parquet as pa_pq
import pyarrow as pa
import pytest

import daft_tpu
from daft_tpu.io.azure import AzureBlobSource, _parse_az_url
from daft_tpu.io.gcs import GCSSource
from daft_tpu.io.object_io import AzureConfig, GCSConfig, IOStatsContext


# ---------------------------------------------------------------- GCS mock

class _MockGCSHandler(http.server.BaseHTTPRequestHandler):
    store = {}

    def log_message(self, *a):
        pass

    def _send(self, status, body=b"", ctype="application/json"):
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self):
        u = urllib.parse.urlparse(self.path)
        q = urllib.parse.parse_qs(u.query)
        # /upload/storage/v1/b/<bucket>/o?uploadType=media&name=<key>
        parts = u.path.strip("/").split("/")
        assert parts[:4] == ["upload", "storage", "v1", "b"], parts
        bucket = parts[4]
        key = urllib.parse.unquote(q["name"][0])
        n = int(self.headers.get("Content-Length", 0))
        self.store[(bucket, key)] = self.rfile.read(n)
        self._send(200, b"{}")

    def do_GET(self):
        u = urllib.parse.urlparse(self.path)
        q = urllib.parse.parse_qs(u.query)
        parts = u.path.strip("/").split("/", 4)
        # /storage/v1/b/<bucket>/o[/<key>]
        bucket = parts[3]
        rest = parts[4] if len(parts) > 4 else "o"
        if rest == "o":  # list
            prefix = q.get("prefix", [""])[0]
            token = q.get("pageToken", [None])[0]
            keys = sorted(k for (b, k) in self.store
                          if b == bucket and k.startswith(prefix))
            page = 2  # force pagination
            start = keys.index(token) if token else 0
            chunk = keys[start:start + page]
            payload = {"items": [
                {"name": k, "size": str(len(self.store[(bucket, k)]))}
                for k in chunk]}
            if start + page < len(keys):
                payload["nextPageToken"] = keys[start + page]
            self._send(200, json.dumps(payload).encode())
            return
        key = urllib.parse.unquote(rest[2:])  # strip "o/"
        data = self.store.get((bucket, key))
        if data is None:
            self._send(404, b"{}")
            return
        if q.get("alt", [None])[0] == "media":
            rng = self.headers.get("Range")
            if rng:
                spec = rng.split("=")[1]
                s, e = spec.split("-")
                chunk = data[int(s):int(e) + 1]
                self._send(206, chunk, "application/octet-stream")
                return
            self._send(200, data, "application/octet-stream")
            return
        self._send(200, json.dumps({"name": key,
                                    "size": str(len(data))}).encode())


# -------------------------------------------------------------- Azure mock

class _MockAzureHandler(http.server.BaseHTTPRequestHandler):
    store = {}
    seen_auth = []

    def log_message(self, *a):
        pass

    def _parse(self):
        u = urllib.parse.urlparse(self.path)
        # path-style /<account>/<container>[/<blob>]
        parts = u.path.lstrip("/").split("/", 2)
        account, container = parts[0], parts[1] if len(parts) > 1 else ""
        blob = urllib.parse.unquote(parts[2]) if len(parts) > 2 else ""
        return account, container, blob, urllib.parse.parse_qs(u.query)

    def _send(self, status, body=b"", headers=()):
        self.send_response(status)
        for k, v in headers:
            self.send_header(k, v)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_PUT(self):
        _, container, blob, _ = self._parse()
        self.seen_auth.append(self.headers.get("Authorization", ""))
        n = int(self.headers.get("Content-Length", 0))
        self.store[(container, blob)] = self.rfile.read(n)
        self._send(201)

    def do_HEAD(self):
        _, container, blob, _ = self._parse()
        data = self.store.get((container, blob))
        if data is None:
            self._send(404)
            return
        # HEAD: Content-Length carries the blob size, no body follows
        self.send_response(200)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()

    def do_GET(self):
        _, container, blob, q = self._parse()
        self.seen_auth.append(self.headers.get("Authorization", ""))
        if q.get("comp", [None])[0] == "list":
            prefix = q.get("prefix", [""])[0]
            marker = q.get("marker", [None])[0]
            keys = sorted(k for (c, k) in self.store
                          if c == container and k.startswith(prefix))
            page = 2
            start = keys.index(marker) if marker else 0
            chunk = keys[start:start + page]
            blobs = "".join(
                f"<Blob><Name>{k}</Name><Properties><Content-Length>"
                f"{len(self.store[(container, k)])}</Content-Length>"
                f"</Properties></Blob>" for k in chunk)
            nxt = keys[start + page] if start + page < len(keys) else ""
            body = (f"<?xml version='1.0'?><EnumerationResults>"
                    f"<Blobs>{blobs}</Blobs><NextMarker>{nxt}</NextMarker>"
                    f"</EnumerationResults>").encode()
            self._send(200, body)
            return
        data = self.store.get((container, blob))
        if data is None:
            self._send(404)
            return
        rng = self.headers.get("Range") or self.headers.get("range")
        if rng:
            spec = rng.split("=")[1]
            s, e = spec.split("-")
            self._send(206, data[int(s):int(e) + 1])
            return
        self._send(200, data)


def _serve(handler):
    handler.store = {}
    server = http.server.ThreadingHTTPServer(("127.0.0.1", 0), handler)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    return server


@pytest.fixture(scope="module")
def gcs():
    server = _serve(_MockGCSHandler)
    src = GCSSource(GCSConfig(
        endpoint_url=f"http://127.0.0.1:{server.server_port}",
        access_token="test-token"))
    yield src
    server.shutdown()


@pytest.fixture(scope="module")
def az():
    server = _serve(_MockAzureHandler)
    # base64 key so SharedKey signing round-trips
    key = base64.b64encode(b"secret-key-bytes").decode()
    src = AzureBlobSource(AzureConfig(
        storage_account="acct", access_key=key,
        endpoint_url=f"http://127.0.0.1:{server.server_port}"))
    yield src
    server.shutdown()


# ------------------------------------------------------------------- tests

def test_gcs_put_get_roundtrip(gcs):
    gcs.put("gs://bkt/dir/x.bin", b"gcs bytes")
    assert gcs.get("gs://bkt/dir/x.bin") == b"gcs bytes"
    assert gcs.get_size("gs://bkt/dir/x.bin") == 9


def test_gcs_range_get(gcs):
    gcs.put("gs://bkt/r.bin", b"0123456789")
    assert gcs.get("gs://bkt/r.bin", byte_range=(2, 6)) == b"2345"


def test_gcs_glob_with_pagination(gcs):
    for i in range(5):
        gcs.put(f"gs://bkt/part/{i}.parquet", b"x" * (i + 1))
    gcs.put("gs://bkt/part/readme.txt", b"no")
    stats = IOStatsContext()
    hits = gcs.glob("gs://bkt/part/*.parquet", stats=stats)
    assert hits == [f"gs://bkt/part/{i}.parquet" for i in range(5)]
    assert stats.num_lists >= 3  # paginated (2 per page)


def test_gcs_missing_raises(gcs):
    with pytest.raises(FileNotFoundError):
        gcs.get("gs://bkt/absent")


def test_gcs_read_parquet_end_to_end(gcs, monkeypatch, tmp_path):
    t = pa.table({"a": [1, 2, 3], "b": ["x", "y", "z"]})
    local = tmp_path / "t.parquet"
    pa_pq.write_table(t, local)
    gcs.put("gs://data/t.parquet", local.read_bytes())
    monkeypatch.setenv("GCS_ENDPOINT_URL", gcs.config.endpoint_url)
    monkeypatch.setenv("GCS_ACCESS_TOKEN", "test-token")
    from daft_tpu.io import object_io
    monkeypatch.setattr(object_io, "_default_client", None)
    df = daft_tpu.read_parquet("gs://data/t.parquet")
    assert df.to_pydict() == {"a": [1, 2, 3], "b": ["x", "y", "z"]}


def test_az_url_forms():
    assert _parse_az_url("az://cont/a/b.txt") == (None, "cont", "a/b.txt")
    assert _parse_az_url(
        "abfss://cont@acct.dfs.core.windows.net/a/b.txt") == \
        ("acct", "cont", "a/b.txt")


def test_az_put_get_roundtrip_sharedkey(az):
    az.put("az://cont/dir/y.bin", b"azure bytes")
    assert az.get("az://cont/dir/y.bin") == b"azure bytes"
    # SharedKey Authorization header was actually sent
    assert any(a.startswith("SharedKey acct:")
               for a in _MockAzureHandler.seen_auth)


def test_az_range_get(az):
    az.put("az://cont/r.bin", b"abcdefghij")
    assert az.get("az://cont/r.bin", byte_range=(1, 4)) == b"bcd"


def test_az_glob_with_pagination(az):
    for i in range(5):
        az.put(f"az://cont/part/{i}.parquet", b"y" * (i + 1))
    az.put("az://cont/part/notes.md", b"no")
    hits = az.glob("az://cont/part/*.parquet")
    assert hits == [f"az://cont/part/{i}.parquet" for i in range(5)]


def test_az_missing_raises(az):
    with pytest.raises(FileNotFoundError):
        az.get("az://cont/absent")


def test_az_read_parquet_end_to_end(az, monkeypatch, tmp_path):
    t = pa.table({"k": [10, 20], "v": [0.5, 1.5]})
    local = tmp_path / "t.parquet"
    pa_pq.write_table(t, local)
    az.put("az://data/t.parquet", local.read_bytes())
    monkeypatch.setenv("AZURE_ENDPOINT_URL", az.config.endpoint_url)
    monkeypatch.setenv("AZURE_STORAGE_ACCOUNT", "acct")
    monkeypatch.setenv("AZURE_STORAGE_KEY",
                       base64.b64encode(b"secret-key-bytes").decode())
    from daft_tpu.io import object_io
    monkeypatch.setattr(object_io, "_default_client", None)
    df = daft_tpu.read_parquet("az://data/t.parquet")
    assert df.to_pydict() == {"k": [10, 20], "v": [0.5, 1.5]}
