"""Dispatch cost model: measured-link decisions, bounded investment,
persisted link profile, decision logging.

Reference seam: the per-operator dispatch decision the reference makes
implicitly by construction (CUDA ops run where the data lives); here the
tunnel/local-chip split forces an explicit model (SURVEY.md §7 hard-part
#2, ``daft_tpu/device/costmodel.py``)."""

import json
import os

import pytest

from daft_tpu.device import costmodel as cm


@pytest.fixture
def slow_link(monkeypatch):
    """A 10 MB/s, 80 ms RTT tunnel — the r5 measured worst case."""
    monkeypatch.setenv("DAFT_TPU_LINK_RTT_MS", "80")
    monkeypatch.setenv("DAFT_TPU_LINK_UP_MBPS", "10")
    monkeypatch.setenv("DAFT_TPU_LINK_DOWN_MBPS", "10")
    cm.reset_for_tests()
    yield
    cm.reset_for_tests()


@pytest.fixture
def fast_link(monkeypatch):
    """A ~100 MB/s link — the r4 good-day tunnel."""
    monkeypatch.setenv("DAFT_TPU_LINK_RTT_MS", "40")
    monkeypatch.setenv("DAFT_TPU_LINK_UP_MBPS", "100")
    monkeypatch.setenv("DAFT_TPU_LINK_DOWN_MBPS", "100")
    cm.reset_for_tests()
    yield
    cm.reset_for_tests()


def test_invest_refused_on_slow_link(slow_link):
    """A 210 MB cache fill at 10 MB/s is ~21 s against a ~1.1 s host pass
    (ratio ~19): no workload re-runs the scan 19 times, so the bounded
    investment rule must refuse (r4's 64× bound let these through and
    one-shot suites never amortized them)."""
    assert not cm.agg_upload_wins(
        bytes_up=210e6, bytes_down=1e5, cacheable=True,
        host_bytes=336e6)


def test_invest_accepted_on_fast_link(fast_link):
    """Same fill at 100 MB/s is ~2 s (ratio ~2): residency repays within
    a couple of queries — invest."""
    assert cm.agg_upload_wins(
        bytes_up=210e6, bytes_down=1e5, cacheable=True,
        host_bytes=336e6)


def test_noncacheable_upload_must_beat_host_outright(fast_link):
    # 210MB upload at 100MB/s = 2.1s vs 1.1s host pass: refuse
    assert not cm.agg_upload_wins(
        bytes_up=210e6, bytes_down=1e5, cacheable=False, host_bytes=336e6)


def test_rtt_bound_tiny_aggregates_stay_host(slow_link):
    """TPC-H Q22 shape: tiny per-task aggregates are RTT-bound even when
    resident — the resident-pays check must refuse investment."""
    assert not cm.agg_upload_wins(
        bytes_up=2e5, bytes_down=1e5, cacheable=True,
        round_trips=2.0, host_bytes=3e5)


def test_host_bytes_defaults_to_bytes_up(fast_link):
    a = cm.agg_upload_wins(1e6, 1e4, cacheable=False)
    b = cm.agg_upload_wins(1e6, 1e4, cacheable=False, host_bytes=1e6)
    assert a == b


def test_decision_counts_and_jsonl_log(tmp_path, slow_link, monkeypatch):
    log = tmp_path / "dispatch.jsonl"
    monkeypatch.setenv("DAFT_TPU_DISPATCH_LOG", str(log))
    cm.row_output_op_wins(1e6, 1e6)
    cm.agg_upload_wins(1e6, 1e4, cacheable=True, host_bytes=1e6)
    cm.join_wins(1000, 1000, 1e5, 1e5)
    assert cm.decision_counts["row_output"]["host"] == 1
    recs = [json.loads(x) for x in log.read_text().splitlines()]
    assert [r["kind"] for r in recs] == \
        ["row_output", "agg_upload_invest", "join"]
    assert all({"device", "host_s", "dev_s"} <= set(r) for r in recs)


def test_link_profile_persistence_roundtrip(tmp_path, monkeypatch):
    monkeypatch.setenv("DAFT_TPU_LINK_CACHE_PATH",
                       str(tmp_path / "link.json"))
    p = cm.LinkProfile(rtt_s=0.05, up_bps=2e7, down_bps=1e7)
    cm._store("tpu", p)
    got, age = cm._load_stored("tpu")
    assert got == p and age is not None and age < 5
    # backend mismatch → miss
    assert cm._load_stored("other") == (None, None)


def test_link_profile_cpu_is_shared_memory(monkeypatch):
    for k in ("DAFT_TPU_LINK_RTT_MS", "DAFT_TPU_LINK_UP_MBPS",
              "DAFT_TPU_LINK_DOWN_MBPS"):
        monkeypatch.delenv(k, raising=False)
    cm.reset_for_tests()
    lp = cm.link_profile()  # tests run on the CPU backend
    assert lp.rtt_s == 0.0 and lp.up_bps == float("inf")
    cm.reset_for_tests()


def test_encoded_nbytes_compacts_f64():
    import daft_tpu as dt
    from daft_tpu.device import column as dcol
    from daft_tpu.recordbatch import RecordBatch
    rb = RecordBatch.from_pydict({
        "f": [1.0] * 1000, "s": ["ab"] * 1000, "i": [1] * 1000})
    enc = dcol.encoded_nbytes(rb, ["f", "s", "i"])
    cap = dcol.bucket_capacity(1000)
    # f64→f32 (4) on f64-less chips or 8 locally; strings→codes (4);
    # i64 stays 8; +1 validity each
    f_item = 4 if not dcol.supports_f64() else 8
    assert enc == cap * ((f_item + 1) + (4 + 1) + (8 + 1))


def test_mfu_report_shape():
    """Kernel-efficiency report: correct families/fields on any backend
    (values are only meaningful on a real chip; the bench records those)."""
    from daft_tpu.device import mfu
    r = mfu.report(n=1 << 12)
    assert "error" not in r, r
    # a CPU backend rounds the percentages to ~0 — assert presence and
    # positivity of the raw throughputs instead
    assert r["grouped_agg"]["mfu_pct"] >= 0
    # rounded fields can floor to 0.0 on a slow CPU — assert the raw
    # inputs instead
    assert r["grouped_agg"]["time_s"] > 0 and r["grouped_agg"]["flops"] > 0
    assert r["join"]["bytes"] > 0 and r["join"]["time_s"] > 0
    assert r["argsort"]["bytes"] > 0 and r["argsort"]["time_s"] > 0
    assert {"roofline_pct", "time_s", "achieved_gbps"} <= set(r["join"])
    assert r["grouped_agg"]["flops"] == 2.0 * (1 << 12) * 256 * 3


def test_image_resize_gate(slow_link, monkeypatch):
    # 50MB batch over a 10MB/s tunnel (~5s) vs PIL (~0.6s): host keeps it
    assert not cm.image_resize_wins(50e6, 12.5e6)


def test_image_resize_gate_local_chip(monkeypatch):
    # shared-memory link: the batched device resize wins by orders of mag
    monkeypatch.setenv("DAFT_TPU_LINK_RTT_MS", "0.01")
    monkeypatch.setenv("DAFT_TPU_LINK_UP_MBPS", "50000")
    monkeypatch.setenv("DAFT_TPU_LINK_DOWN_MBPS", "50000")
    cm.reset_for_tests()
    try:
        assert cm.image_resize_wins(50e6, 12.5e6)
    finally:
        cm.reset_for_tests()
