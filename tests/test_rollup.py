"""ROLLUP / CUBE / GROUPING SETS lowering (union-of-groupbys with typed
super-aggregate NULLs and per-branch GROUPING() literals).

Reference: ``src/daft-sql/src/planner.rs:390-401`` handles ROLLUP in the
SQL frontend; grouping-null semantics follow the SQL spec.
"""

import daft_tpu as dt


def _t():
    return dt.from_pydict({
        "cat": ["a", "a", "b", "b", "b"],
        "cls": ["x", "y", "x", "x", "y"],
        "v": [1.0, 2.0, 3.0, 4.0, 5.0],
    })


def test_rollup_hierarchy_and_grouping_fn():
    out = dt.sql(
        "SELECT cat, cls, SUM(v) AS s, "
        "GROUPING(cat) + GROUPING(cls) AS lvl "
        "FROM t GROUP BY ROLLUP(cat, cls) ORDER BY lvl, cat, cls",
        t=_t()).to_pydict()
    rows = list(zip(out["cat"], out["cls"], out["s"], out["lvl"]))
    assert rows == [
        ("a", "x", 1.0, 0), ("a", "y", 2.0, 0),
        ("b", "x", 7.0, 0), ("b", "y", 5.0, 0),
        ("a", None, 3.0, 1), ("b", None, 12.0, 1),
        (None, None, 15.0, 2)]


def test_cube_all_subsets():
    out = dt.sql("SELECT cat, cls, SUM(v) AS s FROM t "
                 "GROUP BY CUBE(cat, cls) ORDER BY s",
                 t=_t()).to_pydict()
    # 4 detail + 2 cat supers + 2 cls supers + 1 grand total
    assert len(out["s"]) == 9
    assert max(out["s"]) == 15.0
    assert out["cat"].count(None) == 3  # (cls-only) x2 + grand total


def test_grouping_sets_explicit():
    out = dt.sql("SELECT cat, cls, SUM(v) AS s FROM t "
                 "GROUP BY GROUPING SETS ((cat), (cls), ()) ORDER BY s",
                 t=_t()).to_pydict()
    assert sorted(s for s in out["s"]) == [3.0, 7.0, 8.0, 12.0, 15.0]
    # the () set contributes the grand total with both keys NULL
    i = out["s"].index(15.0)
    assert out["cat"][i] is None and out["cls"][i] is None


def test_rollup_with_plain_key_cross_product():
    out = dt.sql("SELECT cat, cls, COUNT(*) AS n FROM t "
                 "GROUP BY cat, ROLLUP(cls) ORDER BY cat, cls",
                 t=_t()).to_pydict()
    # per-(cat,cls) rows plus one (cat, NULL) subtotal per cat
    assert out["cls"].count(None) == 2
    total = sum(n for n, c in zip(out["n"], out["cls"]) if c is None)
    assert total == 5


def test_rollup_having_applies_per_branch():
    out = dt.sql("SELECT cat, SUM(v) AS s FROM t "
                 "GROUP BY ROLLUP(cat) HAVING SUM(v) > 4 ORDER BY s",
                 t=_t()).to_pydict()
    assert out["s"] == [12.0, 15.0]


def test_super_aggregate_counts_real_rows():
    """Aggregating a column that is ALSO a rollup key: the grand-total row
    counts real rows (the substitution must stop at agg boundaries)."""
    out = dt.sql("SELECT cat, COUNT(cat) AS c FROM t "
                 "GROUP BY ROLLUP(cat) ORDER BY cat", t=_t()).to_pydict()
    assert out["c"] == [2, 3, 5]
    assert out["cat"] == ["a", "b", None]


def test_plain_group_by_unchanged():
    out = dt.sql("SELECT cat, SUM(v) AS s FROM t GROUP BY cat ORDER BY cat",
                 t=_t()).to_pydict()
    assert out == {"cat": ["a", "b"], "s": [3.0, 12.0]}
