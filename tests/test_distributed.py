"""Distributed layer: stage splitting, scheduler policies with mock workers
(no hardware — the reference tests flotilla's scheduler the same way,
``src/daft-distributed/src/scheduling/tests.rs``), and end-to-end parity of
the distributed runner against the local runner on a multi-stage join+agg
query (TPC-H Q5 shape)."""

import concurrent.futures as cf
from typing import List

import pytest

import daft_tpu
from daft_tpu import col
from daft_tpu.distributed import (InProcessWorker, LeastLoadedScheduler,
                                  RoundRobinScheduler, StagePlan, StageRunner,
                                  StageTask, Worker, WorkerManager)
from daft_tpu.distributed.worker import WorkerState
from daft_tpu.micropartition import MicroPartition
from daft_tpu.physical import plan as pp
from daft_tpu.physical.translate import translate
from daft_tpu.runners.distributed_runner import DistributedRunner


# ---------------------------------------------------------------- mocks
class MockWorker(Worker):
    def __init__(self, worker_id, num_slots=2, fail_times=0):
        self.id = worker_id
        self.num_slots = num_slots
        self.submitted: List[StageTask] = []
        self.fail_times = fail_times

    def submit(self, task):
        self.submitted.append(task)
        fut = cf.Future()
        if self.fail_times > 0:
            self.fail_times -= 1
            fut.set_exception(RuntimeError("mock worker down"))
        else:
            fut.set_result([MicroPartition.from_pydict({"x": [task.task_idx]})])
        return fut


def _mock_task(i=0, preferred=None):
    plan = pp.InMemorySource([], None)
    return StageTask(0, plan, {}, task_idx=i, preferred_worker=preferred)


# ------------------------------------------------------------- policies
def test_round_robin_spreads():
    ws = [WorkerState(MockWorker(f"w{i}")) for i in range(3)]
    s = RoundRobinScheduler()
    picks = [s.pick(_mock_task(i), ws) for i in range(6)]
    assert picks == ["w0", "w1", "w2", "w0", "w1", "w2"]


def test_least_loaded_prefers_idle():
    ws = [WorkerState(MockWorker("w0")), WorkerState(MockWorker("w1"))]
    ws[0].active = 2
    s = LeastLoadedScheduler()
    assert s.pick(_mock_task(), ws) == "w1"


def test_least_loaded_soft_affinity():
    ws = [WorkerState(MockWorker("w0")), WorkerState(MockWorker("w1"))]
    s = LeastLoadedScheduler()
    assert s.pick(_mock_task(preferred="w1"), ws) == "w1"
    ws[1].active = 99  # affinity target saturated → spill to least loaded
    assert s.pick(_mock_task(preferred="w1"), ws) == "w0"


def test_failed_task_retries_on_other_worker():
    bad = MockWorker("bad", fail_times=1)
    good = MockWorker("good")
    mgr = WorkerManager([bad, good])

    class PickBadFirst:
        def __init__(self):
            self.calls = 0

        def pick(self, task, states):
            self.calls += 1
            ids = [s.worker.id for s in states]
            return "bad" if "bad" in ids and self.calls == 1 else ids[0]

    runner = StageRunner(mgr, PickBadFirst())
    stage_plan = StagePlan.from_physical(
        pp.InMemorySource([MicroPartition.from_pydict({"x": [1]})], None))
    parts = list(runner.run(stage_plan))
    assert len(bad.submitted) == 1
    assert len(good.submitted) == 1  # retried away from the failed worker
    assert parts and parts[0].to_pydict() == {"x": [0]}


# -------------------------------------------------------- stage planning
def _stage_plan_for(df) -> StagePlan:
    return StagePlan.from_physical(translate(df._builder.optimize().plan))


def test_stage_split_at_exchanges(tmp_path):
    # a join between two scans hash-exchanges both sides → ≥3 stages
    import pyarrow as pa
    import pyarrow.parquet as pq
    lp = str(tmp_path / "l.parquet")
    rp = str(tmp_path / "r.parquet")
    pq.write_table(pa.table({"k": list(range(100)),
                             "a": list(range(100))}), lp)
    pq.write_table(pa.table({"k": list(range(100)),
                             "b": [i * 2 for i in range(100)]}), rp)
    left = daft_tpu.read_parquet(lp).into_partitions(4)
    right = daft_tpu.read_parquet(rp).into_partitions(4)
    df = left.join(right, on="k")
    sp = _stage_plan_for(df)
    assert len(sp.stages) >= 3
    # root stage consumes StageInputs, upstream stages are exchange-free
    kinds = [b.kind for s in sp.stages for b in s.boundaries]
    assert "hash" in kinds or "split" in kinds

    def has_exchange(n):
        return isinstance(n, pp.Exchange) or any(has_exchange(c)
                                                 for c in n.children)

    for s in sp.stages:
        assert not has_exchange(s.plan)


def test_map_like_scan_stage_shards_across_workers(tmp_path):
    import pyarrow as pa
    import pyarrow.parquet as pq
    d = tmp_path / "t"
    d.mkdir()
    for i in range(6):
        pq.write_table(pa.table({"x": list(range(i * 10, i * 10 + 10))}),
                       str(d / f"{i}.parquet"))
    df = daft_tpu.read_parquet(str(d / "*.parquet")).where(col("x") % 2 == 0)
    # force a downstream exchange so the scan becomes its own stage
    df = df.repartition(2, col("x"))
    from daft_tpu.context import execution_config_ctx
    with execution_config_ctx(scan_tasks_min_size_bytes=1):
        sp = _stage_plan_for(df)
    scan_stage = next(s for s in sp.stages if s.scan_source() is not None)
    assert scan_stage.is_map_like()

    workers = [MockWorker("w0"), MockWorker("w1")]
    mgr = WorkerManager(workers)
    runner = StageRunner(mgr, RoundRobinScheduler())
    tasks = runner._make_tasks(scan_stage, {})
    assert len(tasks) == 2
    seen = [len(t.plan.tasks) if isinstance(t.plan, pp.ScanSource)
            else len(t.plan.children[0].tasks) for t in tasks]
    assert sum(seen) == len(scan_stage.scan_source().tasks)


# ------------------------------------------------------------ end-to-end
def test_distributed_runner_matches_local_on_join_agg():
    import numpy as np
    rng = np.random.default_rng(5)
    n = 2000
    orders = daft_tpu.from_pydict({
        "okey": list(range(n)),
        "cust": rng.integers(0, 50, n).tolist(),
        "price": rng.uniform(1, 100, n).round(2).tolist(),
    }).into_partitions(4)
    customers = daft_tpu.from_pydict({
        "cust": list(range(50)),
        "region": rng.integers(0, 5, 50).tolist(),
    }).into_partitions(2)

    def q(df_o, df_c):
        return (df_o.join(df_c, on="cust")
                .groupby("region").agg(col("price").sum().alias("rev"),
                                       col("okey").count().alias("cnt"))
                .sort("region").to_pydict())

    local = q(orders, customers)

    runner = DistributedRunner(num_workers=3)
    import daft_tpu.context as ctx
    old = ctx.get_context()._runner
    ctx.get_context().set_runner(runner)
    try:
        dist = q(orders, customers)
    finally:
        ctx.get_context().set_runner(old)
    assert dist["region"] == local["region"]
    assert dist["cnt"] == local["cnt"]
    for a, b in zip(dist["rev"], local["rev"]):
        assert a == pytest.approx(b, rel=1e-9)


def test_distributed_runner_multi_stage_count(tmp_path):
    import pyarrow as pa
    import pyarrow.parquet as pq
    p = str(tmp_path / "t.parquet")
    pq.write_table(pa.table({"k": [i % 7 for i in range(1000)],
                             "v": [float(i) for i in range(1000)]}), p)
    df = (daft_tpu.read_parquet(p).into_partitions(4)
          .groupby("k").agg(col("v").sum().alias("s")).sort("k"))
    sp = _stage_plan_for(df)
    assert len(sp.stages) >= 2  # ≥2 stages through the shuffle


def test_remote_worker_runs_stage_over_http():
    """The Worker seam is transport-blind: a RemoteWorker posting fragments
    to a WorkerServer (another executor behind HTTP, flotilla's
    RaySwordfishActor shape) produces the same results as local workers."""
    from daft_tpu.distributed.remote_worker import RemoteWorker, WorkerServer
    from daft_tpu.distributed import (LeastLoadedScheduler, StagePlan,
                                      StageRunner, WorkerManager)
    from daft_tpu.physical.translate import translate

    srv = WorkerServer()
    try:
        df = (daft_tpu.from_pydict({"k": [i % 7 for i in range(500)],
                                    "v": [float(i) for i in range(500)]})
              .into_partitions(3)
              .groupby("k").agg(col("v").sum().alias("s")).sort("k"))
        local = df.to_pydict()

        sp = StagePlan.from_physical(translate(df._builder.optimize().plan))
        mgr = WorkerManager([RemoteWorker("remote-0", srv.address)])
        runner = StageRunner(mgr, LeastLoadedScheduler())
        parts = list(runner.run(sp))
        got = {}
        for p in parts:
            d = p.to_pydict()
            for k, s in zip(d.get("k", []), d.get("s", [])):
                got[k] = s
        assert got == dict(zip(local["k"], local["s"]))
    finally:
        srv.shutdown()


def test_flight_shuffle_backed_boundaries(monkeypatch):
    """Hash boundaries route through the shuffle service: map tasks return
    ShuffleResults, reduce tasks fan out per partition — and the answers
    match the driver-materializing mode exactly."""
    import numpy as np
    from daft_tpu.distributed import StagePlan, StageRunner, WorkerManager
    from daft_tpu.distributed.worker import InProcessWorker, ShuffleResult
    from daft_tpu.physical.translate import translate

    # host exchange path: with the device tier on, this groupby would ride
    # the mesh-collective DeviceExchangeAgg instead of a hash Exchange
    monkeypatch.setenv("DAFT_TPU_DEVICE", "0")
    rng = np.random.default_rng(11)
    df = (daft_tpu.from_pydict({"k": rng.integers(0, 9, 3000).tolist(),
                                "v": [float(i) for i in range(3000)]})
          .into_partitions(4)
          .groupby("k").agg(col("v").sum().alias("s")).sort("k"))
    sp = StagePlan.from_physical(translate(df._builder.optimize().plan))

    shuffle_results = []
    orig_collect = StageRunner._collect

    def spy_collect(self, tasks):
        out = orig_collect(self, tasks)
        shuffle_results.extend(r for r in out
                               if isinstance(r, ShuffleResult))
        return out

    monkeypatch.setattr(StageRunner, "_collect", spy_collect)

    def run_mode(mode):
        monkeypatch.setenv("DAFT_TPU_DISTRIBUTED_SHUFFLE", mode)
        mgr = WorkerManager([InProcessWorker(f"w{i}") for i in range(3)])
        runner = StageRunner(mgr)
        rows = {}
        for p in runner.run(sp):
            d = p.to_pydict()
            for k, s in zip(d.get("k", []), d.get("s", [])):
                rows[k] = s
        return rows

    flight = run_mode("flight")
    assert shuffle_results, "no map task produced a ShuffleResult"
    driver = run_mode("driver")
    assert flight == driver and len(flight) == 9


def test_fanout_guard_keeps_global_ops_correct(monkeypatch):
    """A Limit above a user hash-repartition must NOT fan out per
    partition (it would multiply the limit); the fanout_safe guard keeps
    it on the driver path and the row count stays exact."""
    monkeypatch.setenv("DAFT_TPU_DISTRIBUTED_SHUFFLE", "flight")
    runner = DistributedRunner(num_workers=3)
    import daft_tpu.context as ctx
    old = ctx.get_context()._runner
    ctx.get_context().set_runner(runner)
    try:
        df = daft_tpu.from_pydict({"k": list(range(100))}) \
            .repartition(4, col("k")).limit(5)
        out = df.to_pydict()
    finally:
        ctx.get_context().set_runner(old)
    assert len(out["k"]) == 5


def test_remote_worker_shuffles_over_flight(monkeypatch):
    """Map-side shuffle on a REMOTE worker: the reduce fetch crosses the
    process boundary through the worker's shuffle server."""
    import numpy as np
    from daft_tpu.distributed.remote_worker import RemoteWorker, WorkerServer
    from daft_tpu.distributed import (LeastLoadedScheduler, StagePlan,
                                      StageRunner, WorkerManager)
    from daft_tpu.physical.translate import translate

    monkeypatch.setenv("DAFT_TPU_DISTRIBUTED_SHUFFLE", "flight")
    monkeypatch.setenv("DAFT_TPU_DEVICE", "0")  # host hash exchange
    srv = WorkerServer()
    try:
        df = (daft_tpu.from_pydict({"k": [i % 5 for i in range(800)],
                                    "v": [float(i) for i in range(800)]})
              .into_partitions(3)
              .groupby("k").agg(col("v").sum().alias("s")).sort("k"))
        local = df.to_pydict()
        sp = StagePlan.from_physical(translate(df._builder.optimize().plan))
        mgr = WorkerManager([RemoteWorker("remote-0", srv.address)])
        runner = StageRunner(mgr, LeastLoadedScheduler())
        got = {}
        for p in runner.run(sp):
            d = p.to_pydict()
            for k, s in zip(d.get("k", []), d.get("s", [])):
                got[k] = s
        assert got == dict(zip(local["k"], local["s"]))
    finally:
        srv.shutdown()


def test_transient_fetch_failure_recovers(monkeypatch):
    """Regression: a reduce-side fetch that fails transiently (network
    blip, serving worker briefly unreachable) must be retried by the
    resilience plane — not abort the query — and the recovery must be
    counted."""
    from daft_tpu.distributed import resilience as rz
    from daft_tpu.distributed import shuffle_service as ss

    monkeypatch.setenv("DAFT_TPU_DISTRIBUTED_SHUFFLE", "flight")
    monkeypatch.setenv("DAFT_TPU_DEVICE", "0")
    monkeypatch.setenv("DAFT_TPU_RETRY_BACKOFF", "0.01")
    rz.reset_for_tests()

    df = (daft_tpu.from_pydict({"k": [i % 6 for i in range(900)],
                                "v": [float(i) for i in range(900)]})
          .into_partitions(3)
          .groupby("k").agg(col("v").sum().alias("s")).sort("k"))
    local = df.to_pydict()

    orig = ss.fetch_partition
    state = {"failed": False}

    def flaky(address, shuffle_id, partition, fault_key=None):
        if not state["failed"]:
            state["failed"] = True
            raise rz.ShuffleFetchError(address, shuffle_id, partition,
                                       detail="transient blip")
        return orig(address, shuffle_id, partition, fault_key=fault_key)

    monkeypatch.setattr(ss, "fetch_partition", flaky)
    runner = DistributedRunner(num_workers=3)
    import daft_tpu.context as ctx
    old = ctx.get_context()._runner
    ctx.get_context().set_runner(runner)
    try:
        fresh = (daft_tpu.from_pydict({"k": [i % 6 for i in range(900)],
                                       "v": [float(i) for i in range(900)]})
                 .into_partitions(3)
                 .groupby("k").agg(col("v").sum().alias("s")).sort("k"))
        dist = fresh.to_pydict()
    finally:
        ctx.get_context().set_runner(old)
    assert state["failed"], "the flaky fetch was never exercised"
    assert dist == local
    c = rz.counters_snapshot()
    assert c.get("fetch_failures", 0) >= 1, c
    assert c.get("retries", 0) >= 1, c
    rz.reset_for_tests()


def test_sort_merge_join_not_fanned_out(monkeypatch):
    """Regression: a sort_merge-strategy join has NO co-partitioning
    exchanges, so fanning its stage out per hash partition would re-run
    the embedded side per task and duplicate outer unmatched rows — the
    safety rule must route it through the driver."""
    monkeypatch.setenv("DAFT_TPU_DISTRIBUTED_SHUFFLE", "flight")
    monkeypatch.setenv("DAFT_TPU_DEVICE", "0")
    left = daft_tpu.from_pydict({"k": [1, 2, 3], "lv": [10, 20, 30]})
    right = daft_tpu.from_pydict({"k": [2, 9], "rv": ["b", "z"]})
    runner = DistributedRunner(num_workers=3)
    import daft_tpu.context as ctx
    old = ctx.get_context()._runner
    ctx.get_context().set_runner(runner)
    try:
        out = left.repartition(2, col("k")) \
            .join(right, on="k", how="outer",
                  strategy="sort_merge").to_pydict()
    finally:
        ctx.get_context().set_runner(old)
    # exactly one row for right's unmatched k=9, not one per partition
    assert sum(1 for k in out["k"] if k == 9) == 1
    assert len(out["k"]) == 4  # 1,2,3 plus unmatched 9


def test_distributed_sort_stays_off_driver(monkeypatch):
    """Global sort under the flight shuffle runs the worker-side range
    protocol: driver sees samples/boundaries/receipts, never the rows
    (VERDICT r2 item 3 done-criterion). The sorted result still matches
    the local runner exactly."""
    import numpy as np

    from daft_tpu.distributed import scheduler as sched_mod
    monkeypatch.setenv("DAFT_TPU_DEVICE", "0")  # host hash exchange path
    rng = np.random.default_rng(11)
    n = 5000
    data = {"k": rng.integers(0, 40, n).tolist(),
            "v": rng.uniform(0, 1000, n).round(3).tolist()}

    def q(frame):
        return (frame.groupby("k").agg(col("v").sum().alias("s"))
                .sort("s", desc=True).to_pydict())

    def fresh():
        # a fresh frame per run: a collected result would otherwise cache
        # its partitions and the second plan would skip the exchanges
        return daft_tpu.from_pydict(data).into_partitions(4)

    local = q(fresh())

    calls = {"range_sort": 0}
    orig = sched_mod.StageRunner._range_sort_remainder

    def spy(self, *a, **kw):
        calls["range_sort"] += 1
        return orig(self, *a, **kw)

    monkeypatch.setattr(sched_mod.StageRunner, "_range_sort_remainder", spy)

    def no_driver_fetch(srcs, n):
        raise AssertionError("sort routed rows through the driver")

    monkeypatch.setattr(sched_mod.StageRunner, "_driver_fetch",
                        staticmethod(no_driver_fetch))

    runner = DistributedRunner(num_workers=3)
    import daft_tpu.context as ctx
    old = ctx.get_context()._runner
    ctx.get_context().set_runner(runner)
    try:
        dist = q(fresh())
    finally:
        ctx.get_context().set_runner(old)
    assert calls["range_sort"] == 1
    assert dist["k"] == local["k"]
    for a, b in zip(dist["s"], local["s"]):
        assert a == pytest.approx(b, rel=1e-9)
