"""Stateful-UDF process actor pools (reference:
``daft/execution/actor_pool_udf.py`` + ``tests/actor_pool/``): concurrency=N
must run N distinct OS processes with independent instances; unpicklable
UDFs fall back to the shared in-process instance."""

import os

import pytest

import daft_tpu
from daft_tpu import DataType, col, udf


@udf(return_dtype=DataType.int64(), concurrency=3)
class PidReporter:
    def __init__(self):
        self.pid = os.getpid()

    def __call__(self, x):
        return [self.pid] * len(x)


@udf(return_dtype=DataType.int64())
class Counter:
    def __init__(self, start=0):
        self.n = start

    def __call__(self, x):
        self.n += len(x)
        return [self.n] * len(x)


def test_actor_pool_uses_distinct_processes():
    df = daft_tpu.from_pydict({"x": list(range(64))}).into_partitions(8)
    out = df.select(PidReporter(col("x")).alias("pid")).to_pydict()
    pids = set(out["pid"])
    assert os.getpid() not in pids  # ran OUT of process
    assert len(pids) >= 2           # and across multiple actors


def test_actor_state_persists_within_actor():
    df = daft_tpu.from_pydict({"x": list(range(10))})
    out = df.select(Counter.with_init_args(100)(col("x")).alias("n")) \
        .to_pydict()
    # one partition → one actor call sees all 10 rows
    assert out["n"] == [110] * 10


def test_unpicklable_falls_back_in_process():
    import threading

    @udf(return_dtype=DataType.int64(), concurrency=2)
    class Unpicklable:
        def __init__(self, lock):
            self.lock = lock  # a live lock cannot cross process boundaries
            self.pid = os.getpid()

        def __call__(self, x):
            return [self.pid] * len(x)

    df = daft_tpu.from_pydict({"x": [1, 2, 3]})
    bound = Unpicklable.with_init_args(threading.Lock())
    out = df.select(bound(col("x")).alias("pid")).to_pydict()
    assert set(out["pid"]) == {os.getpid()}


def test_pool_disabled_by_env(monkeypatch):
    monkeypatch.setenv("DAFT_TPU_ACTOR_POOL", "0")

    @udf(return_dtype=DataType.int64(), concurrency=2)
    class Local:
        def __init__(self):
            self.pid = os.getpid()

        def __call__(self, x):
            return [self.pid] * len(x)

    df = daft_tpu.from_pydict({"x": [1, 2]})
    out = df.select(Local(col("x")).alias("pid")).to_pydict()
    assert set(out["pid"]) == {os.getpid()}


def test_stateless_udf_stays_in_process():
    @udf(return_dtype=DataType.int64())
    def double(x):
        return [v * 2 for v in x.to_pylist()]

    df = daft_tpu.from_pydict({"x": [1, 2, 3]})
    assert df.select(double(col("x")).alias("y")).to_pydict() == \
        {"y": [2, 4, 6]}
