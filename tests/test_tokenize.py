"""Tokenize expressions: BPE over tiktoken-format vocabs + builtin
byte-level fallback (reference: ``src/daft-functions-tokenize``)."""

import base64

import pytest

import daft_tpu
from daft_tpu import col
from daft_tpu.functions.tokenize import BPETokenizer, get_tokenizer


def _vocab_file(tmp_path):
    """Tiny tiktoken-format vocab: 256 byte tokens + merges for 'he',
    'll', 'hell', 'hello'."""
    ranks = {bytes([i]): i for i in range(256)}
    for i, tok in enumerate([b"he", b"ll", b"hell", b"hello", b" wo",
                             b"rld", b" world"]):
        ranks[tok] = 256 + i
    p = tmp_path / "vocab.tiktoken"
    lines = [base64.b64encode(t).decode() + " " + str(r)
             for t, r in ranks.items()]
    p.write_text("\n".join(lines))
    return str(p), ranks


def test_bpe_merges_greedily_by_rank(tmp_path):
    path, ranks = _vocab_file(tmp_path)
    tk = get_tokenizer(path)
    # 'hello' merges all the way to the single token
    assert tk.encode("hello") == [ranks[b"hello"]]
    assert tk.encode("hello world") == [ranks[b"hello"], ranks[b" world"]]
    # unseen text falls back to byte tokens
    assert tk.encode("xy") == [ord("x"), ord("y")]


def test_encode_decode_roundtrip(tmp_path):
    path, _ = _vocab_file(tmp_path)
    tk = get_tokenizer(path)
    for text in ("hello world", "héllo wörld", "a\nb\tc", ""):
        assert tk.decode(tk.encode(text)) == text


def test_bytes_builtin_roundtrip():
    tk = get_tokenizer("bytes")
    text = "daft🚀"
    ids = tk.encode(text)
    assert ids == list(text.encode("utf-8"))
    assert tk.decode(ids) == text


def test_expression_encode_decode(tmp_path):
    path, ranks = _vocab_file(tmp_path)
    df = daft_tpu.from_pydict({"t": ["hello", "hello world", None]})
    out = df.with_column("ids", col("t").str.tokenize_encode(path)) \
            .with_column("back", col("ids").str.tokenize_decode(path)) \
            .to_pydict()
    assert out["ids"][0] == [ranks[b"hello"]]
    assert out["ids"][2] is None
    assert out["back"] == ["hello", "hello world", None]


def test_expression_default_bytes_tokenizer():
    df = daft_tpu.from_pydict({"t": ["ab"]})
    out = df.select(col("t").str.tokenize_encode()).to_pydict()
    assert out["t"] == [[97, 98]]


def test_native_and_python_merges_identical(tmp_path):
    """The C++ merge loop and the pure-python fallback must be
    bit-identical over random text."""
    import numpy as np

    from daft_tpu import native
    if not native.AVAILABLE:
        pytest.skip("native library unavailable")
    path, ranks = _vocab_file(tmp_path)
    tk = get_tokenizer(path)
    assert tk._native is not None
    rng = np.random.default_rng(0)
    alphabet = "helo wrd xyz\n\t"
    for _ in range(50):
        text = "".join(rng.choice(list(alphabet), rng.integers(0, 40)))
        native_ids = tk.encode(text)
        python_ids = []
        for m in tk._rx.finditer(text):
            python_ids.extend(tk._bpe(m.group().encode("utf-8")))
        assert native_ids == python_ids, text
        assert tk.decode(native_ids) == text


def test_unknown_token_id_raises():
    tk = BPETokenizer({b"a": 0})
    with pytest.raises(ValueError):
        tk.decode([5])
