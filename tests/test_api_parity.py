"""API-surface parity additions (closing the audited gaps vs the
reference's DataFrame/Expression public methods)."""

import math

import pyarrow as pa
import pytest

import daft_tpu
from daft_tpu import DataType, col


def test_dataframe_aliases_and_pipe():
    df = daft_tpu.from_pydict({"x": [1, 2, 2, 3]})
    assert df.filter(col("x") > 1).count_rows() == 3
    assert sorted(df.unique().to_pydict()["x"]) == [1, 2, 3]
    assert df.melt is not None  # unpivot alias
    out = df.pipe(lambda d, n: d.limit(n), 2).to_pydict()
    assert out["x"] == [1, 2]


def test_drop_nan_and_drop_null():
    df = daft_tpu.from_pydict(
        {"f": [1.0, float("nan"), 3.0, None], "s": ["a", "b", None, "d"]})
    assert df.drop_nan().count_rows() == 3       # nan gone, null stays
    assert df.drop_null("s").count_rows() == 3
    assert df.drop_null().count_rows() == 2


def test_union_by_name_reorders_columns():
    a = daft_tpu.from_pydict({"x": [1], "y": ["a"]})
    b = daft_tpu.from_pydict({"y": ["b"], "x": [2]})  # same names, swapped
    out = a.union_all_by_name(b).sort("x").to_pydict()
    assert out == {"x": [1, 2], "y": ["a", "b"]}
    with pytest.raises(ValueError, match="column sets differ"):
        a.union_by_name(daft_tpu.from_pydict({"z": [1]}))


def test_agg_set():
    df = daft_tpu.from_pydict({"g": [1, 1, 2], "v": [5, 5, 7]})
    out = df.groupby("g").agg_set("v").sort("g").to_pydict()
    assert [sorted(s) for s in out["v"]] == [[5], [7]]


def test_to_arrow_iter_streams_batches():
    df = daft_tpu.from_pydict({"x": list(range(100))}).into_partitions(4)
    batches = list(df.to_arrow_iter())
    assert all(isinstance(b, pa.RecordBatch) for b in batches)
    assert sum(b.num_rows for b in batches) == 100


def test_gated_bridges_error_actionably(tmp_path):
    df = daft_tpu.from_pydict({"x": [1]})
    with pytest.raises(ImportError, match="ray"):
        df.to_ray_dataset()
    with pytest.raises(ImportError, match="dask"):
        df.to_dask_dataframe()
    # lance is native now (io/lance.py): a real write round-trips
    df.write_lance(str(tmp_path / "ds"))
    assert daft_tpu.read_lance(str(tmp_path / "ds")).to_pydict() == \
        {"x": [1]}


def test_extended_math_functions():
    df = daft_tpu.from_pydict({"x": [0.5]})
    out = df.select(
        col("x").arcsinh().alias("asinh"),
        (col("x") + 1).arccosh().alias("acosh"),
        col("x").arctanh().alias("atanh"),
        col("x").cot().alias("cot"),
        col("x").csc().alias("csc"),
        col("x").sec().alias("sec"),
        col("x").expm1().alias("em1"),
        col("x").log1p().alias("l1p"),
        col("x").signum().alias("sg"),
        col("x").negative().alias("neg"),
    ).to_pydict()
    assert out["asinh"][0] == pytest.approx(math.asinh(0.5))
    assert out["acosh"][0] == pytest.approx(math.acosh(1.5))
    assert out["atanh"][0] == pytest.approx(math.atanh(0.5))
    assert out["cot"][0] == pytest.approx(1 / math.tan(0.5))
    assert out["csc"][0] == pytest.approx(1 / math.sin(0.5))
    assert out["sec"][0] == pytest.approx(1 / math.cos(0.5))
    assert out["em1"][0] == pytest.approx(math.expm1(0.5))
    assert out["l1p"][0] == pytest.approx(math.log1p(0.5))
    assert out["sg"][0] == 1
    assert out["neg"][0] == -0.5


def test_bitwise_ops():
    df = daft_tpu.from_pydict({"a": [0b1100], "b": [0b1010]})
    out = df.select(
        col("a").bitwise_and(col("b")).alias("and_"),
        col("a").bitwise_or(col("b")).alias("or_"),
        col("a").bitwise_xor(col("b")).alias("xor_"),
    ).to_pydict()
    assert out == {"and_": [0b1000], "or_": [0b1110], "xor_": [0b0110]}


def test_toplevel_codec_and_serde():
    df = daft_tpu.from_pydict({"b": [b"hello"]})
    out = df.select(col("b").encode("zlib").decode("zlib")).to_pydict()
    assert out["b"] == [b"hello"]
    bad = daft_tpu.from_pydict({"b": [b"not-zlib"]})
    assert bad.select(col("b").try_decode("zlib")).to_pydict()["b"] == [None]

    js = daft_tpu.from_pydict({"j": ['{"a": 1}', "oops", None]})
    out = js.select(col("j").try_deserialize(
        "json", DataType.struct({"a": DataType.int64()}))).to_pydict()
    assert out["j"][0] == {"a": 1}
    assert out["j"][1] is None and out["j"][2] is None
    with pytest.raises(Exception):
        js.select(col("j").deserialize(
            "json", DataType.struct({"a": DataType.int64()}))).to_pydict()


def test_deserialize_enforces_declared_dtype():
    """Parsed-but-mismatched JSON must not leak through a typed schema
    (regression: '\"abc\"' survived under an Int64 schema)."""
    js = daft_tpu.from_pydict({"j": ['"abc"', "5"]})
    out = js.select(col("j").try_deserialize(
        "json", DataType.int64())).to_pydict()
    assert out["j"] == [None, 5]
    with pytest.raises(Exception):
        js.select(col("j").deserialize("json",
                                       DataType.int64())).to_pydict()


def test_jq_alias():
    df = daft_tpu.from_pydict({"j": ['{"a": {"b": 7}}']})
    out = df.select(col("j").jq(".a.b")).to_pydict()
    assert out["j"] == ["7"]
