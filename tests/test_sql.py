"""SQL frontend tests (reference model: ``tests/sql/``)."""

import datetime

import pytest

import daft_tpu as dt
from daft_tpu import col
from daft_tpu.sql import SQLCatalog, sql, sql_expr


@pytest.fixture
def catalog():
    t = dt.from_pydict({
        "a": [1, 2, 3, 4], "b": ["x", "y", "x", "z"],
        "c": [10.0, 20.0, 30.0, 40.0],
        "d": [datetime.date(2020, 1, 1), datetime.date(2021, 6, 1),
              datetime.date(2022, 3, 1), datetime.date(2020, 12, 25)]})
    u = dt.from_pydict({"b": ["x", "y"], "w": [100, 200]})
    return SQLCatalog({"t": t, "u": u})


def test_basic_select(catalog):
    out = sql("SELECT a, c FROM t WHERE a > 1 ORDER BY a", catalog)
    assert out.to_pydict() == {"a": [2, 3, 4], "c": [20.0, 30.0, 40.0]}


def test_select_star(catalog):
    out = sql("SELECT * FROM t LIMIT 2", catalog)
    assert out.column_names == ["a", "b", "c", "d"]
    assert len(out.to_pydict()["a"]) == 2


def test_arithmetic_and_alias(catalog):
    out = sql("SELECT a + 1 AS a1, c * 2 AS c2 FROM t ORDER BY a1", catalog)
    assert out.to_pydict() == {"a1": [2, 3, 4, 5], "c2": [20.0, 40.0, 60.0, 80.0]}


def test_group_by_agg(catalog):
    out = sql("SELECT b, sum(c) AS s, count(*) AS n FROM t GROUP BY b "
              "ORDER BY b", catalog)
    assert out.to_pydict() == {"b": ["x", "y", "z"], "s": [40.0, 20.0, 40.0],
                               "n": [2, 1, 1]}


def test_having(catalog):
    out = sql("SELECT b, sum(c) AS s FROM t GROUP BY b HAVING sum(c) > 25 "
              "ORDER BY b", catalog)
    assert out.to_pydict()["b"] == ["x", "z"]


def test_join(catalog):
    out = sql("SELECT t.a, u.w FROM t JOIN u ON t.b = u.b ORDER BY a", catalog)
    assert out.to_pydict() == {"a": [1, 2, 3], "w": [100, 200, 100]}


def test_left_join_and_using(catalog):
    out = sql("SELECT a, w FROM t LEFT JOIN u USING (b) ORDER BY a", catalog)
    assert out.to_pydict()["w"] == [100, 200, 100, None]


def test_case_when(catalog):
    out = sql("SELECT CASE WHEN a >= 3 THEN 'big' ELSE 'small' END AS sz "
              "FROM t ORDER BY a", catalog)
    assert out.to_pydict() == {"sz": ["small", "small", "big", "big"]}


def test_between_in_like(catalog):
    assert sql("SELECT a FROM t WHERE a BETWEEN 2 AND 3 ORDER BY a",
               catalog).to_pydict() == {"a": [2, 3]}
    assert sql("SELECT a FROM t WHERE b IN ('x', 'z') ORDER BY a",
               catalog).to_pydict() == {"a": [1, 3, 4]}
    assert sql("SELECT a FROM t WHERE b LIKE 'x%' ORDER BY a",
               catalog).to_pydict() == {"a": [1, 3]}


def test_date_literal_and_extract(catalog):
    out = sql("SELECT a FROM t WHERE d >= DATE '2021-01-01' ORDER BY a",
              catalog)
    assert out.to_pydict() == {"a": [2, 3]}
    out2 = sql("SELECT EXTRACT(year FROM d) AS y FROM t ORDER BY y", catalog)
    assert out2.to_pydict() == {"y": [2020, 2020, 2021, 2022]}


def test_cast(catalog):
    out = sql("SELECT CAST(a AS double) AS f FROM t LIMIT 1", catalog)
    assert out.to_pydict() == {"f": [1.0]}


def test_cte_and_subquery(catalog):
    out = sql("WITH big AS (SELECT a, c FROM t WHERE a > 2) "
              "SELECT sum(c) AS s FROM big", catalog)
    assert out.to_pydict() == {"s": [70.0]}
    out2 = sql("SELECT s.a FROM (SELECT a FROM t WHERE a < 3) s ORDER BY a",
               catalog)
    assert out2.to_pydict() == {"a": [1, 2]}


def test_union(catalog):
    out = sql("SELECT a FROM t WHERE a < 2 UNION ALL SELECT a FROM t "
              "WHERE a > 3", catalog)
    assert sorted(out.to_pydict()["a"]) == [1, 4]


def test_distinct_and_functions(catalog):
    out = sql("SELECT DISTINCT upper(b) AS ub FROM t ORDER BY ub", catalog)
    assert out.to_pydict() == {"ub": ["X", "Y", "Z"]}
    out2 = sql("SELECT count(DISTINCT b) AS n FROM t", catalog)
    assert out2.to_pydict() == {"n": [3]}


def test_scalar_select_no_from(catalog):
    out = sql("SELECT 1 + 2 AS x", catalog)
    assert out.to_pydict() == {"x": [3]}


def test_sql_expr():
    e = sql_expr("a + 1 > 3")
    df = dt.from_pydict({"a": [1, 5]})
    assert df.where(e).to_pydict() == {"a": [5]}


def test_dataframe_where_string():
    df = dt.from_pydict({"a": [1, 5, 10]})
    assert df.where("a >= 5").to_pydict() == {"a": [5, 10]}


def test_sql_binds_local_dataframes():
    mytable = dt.from_pydict({"x": [1, 2, 3]})
    out = sql("SELECT sum(x) AS s FROM mytable")
    assert out.to_pydict() == {"s": [6]}


def test_sql_tpch_q1_shape(catalog):
    q = """
    SELECT b, sum(c * (1 + a)) AS weighted, avg(c) AS avg_c
    FROM t WHERE d <= DATE '2022-01-01'
    GROUP BY b ORDER BY b
    """
    out = sql(q, catalog)
    assert out.column_names == ["b", "weighted", "avg_c"]
    assert out.to_pydict()["b"] == ["x", "y", "z"]


# -- window functions (reference: src/daft-sql/src/modules/window.rs) -------

def test_sql_window_rank_family():
    df = dt.from_pydict({"g": ["a", "a", "b", "b", "b"],
                           "v": [3.0, 1.0, 5.0, 2.0, 4.0]})
    out = dt.sql("""
        SELECT g, v,
               ROW_NUMBER() OVER (PARTITION BY g ORDER BY v) AS rn,
               RANK() OVER (PARTITION BY g ORDER BY v DESC) AS rk,
               DENSE_RANK() OVER (PARTITION BY g ORDER BY v) AS dr
        FROM df ORDER BY g, v""", df=df).to_pydict()
    assert out["rn"] == [1, 2, 1, 2, 3]
    assert out["rk"] == [2, 1, 3, 2, 1]
    assert out["dr"] == [1, 2, 1, 2, 3]


def test_sql_window_aggregates_and_frames():
    df = dt.from_pydict({"g": ["a", "a", "b", "b", "b"],
                           "v": [3.0, 1.0, 5.0, 2.0, 4.0]})
    out = dt.sql("""
        SELECT g, v,
               SUM(v) OVER (PARTITION BY g) AS total,
               SUM(v) OVER (PARTITION BY g ORDER BY v
                            ROWS BETWEEN UNBOUNDED PRECEDING
                            AND CURRENT ROW) AS running,
               AVG(v) OVER (PARTITION BY g) AS m
        FROM df ORDER BY g, v""", df=df).to_pydict()
    assert out["total"] == [4.0, 4.0, 11.0, 11.0, 11.0]
    assert out["running"] == [1.0, 4.0, 2.0, 6.0, 11.0]
    assert out["m"][0] == pytest.approx(2.0)


def test_sql_window_lag_lead():
    df = dt.from_pydict({"g": ["a", "a", "a"], "v": [1.0, 2.0, 3.0]})
    out = dt.sql("""
        SELECT v,
               LAG(v, 1) OVER (PARTITION BY g ORDER BY v) AS prev,
               LEAD(v, 1) OVER (PARTITION BY g ORDER BY v) AS nxt,
               LAG(v, 1, 0.0) OVER (PARTITION BY g ORDER BY v) AS prev0
        FROM df ORDER BY v""", df=df).to_pydict()
    assert out["prev"] == [None, 1.0, 2.0]
    assert out["nxt"] == [2.0, 3.0, None]
    assert out["prev0"] == [0.0, 1.0, 2.0]


def test_sql_string_function_breadth():
    df = dt.from_pydict({"s": ["hello world"]})
    out = dt.sql("""
        SELECT regexp_extract(s, '(\\w+)') AS w, lpad(s, 13, '.') AS p,
               reverse(s) AS r, left(s, 5) AS l,
               starts_with(s, 'hello') AS sw
        FROM df""", df=df).to_pydict()
    assert out["w"] == ["hello"]
    assert out["p"] == ["..hello world"]
    assert out["r"] == ["dlrow olleh"]
    assert out["l"] == ["hello"]
    assert out["sw"] == [True]


def test_implicit_select_alias():
    """AS-less output aliases (``SELECT x total``) must name the output —
    they silently vanished before r4 (the projection span re-parse never
    saw the trailing ident)."""
    df = dt.from_pydict({"k": [1, 2, 1], "v": [10.0, 20.0, 30.0]})
    out = dt.sql("SELECT k customer_id, v total FROM df", df=df)
    assert out.column_names == ["customer_id", "total"]
    out = dt.sql(
        "SELECT k grp, SUM(v) total, 's' tag FROM df GROUP BY k", df=df)
    assert out.column_names == ["grp", "total", "tag"]


def test_set_op_positional_schema():
    """SQL set operations match columns by position, not name."""
    a = dt.from_pydict({"x": [1, 2]})
    b = dt.from_pydict({"y": [3]})
    out = dt.sql("SELECT x FROM a UNION ALL SELECT y FROM b", a=a, b=b)
    assert out.column_names == ["x"]
    assert sorted(out.to_pydict()["x"]) == [1, 2, 3]


def test_window_over_aggregate_single_select():
    """SUM(SUM(x)) OVER and RANK() OVER (ORDER BY SUM(x)) in ONE select
    (no manual CTE decomposition)."""
    df = dt.from_pydict({"g": ["a", "a", "b", "b"], "c": ["x", "y", "x", "y"],
                         "v": [1.0, 2.0, 3.0, 4.0]})
    out = dt.sql(
        "SELECT g, SUM(v) s, SUM(SUM(v)) OVER () tot, "
        "RANK() OVER (ORDER BY SUM(v) DESC) r "
        "FROM df GROUP BY g ORDER BY g", df=df).to_pydict()
    assert out["s"] == [3.0, 7.0]
    assert out["tot"] == [10.0, 10.0]
    assert out["r"] == [2, 1]


def test_rollup_grouping_in_window_partition():
    """GROUPING() inside a window PARTITION BY (TPC-DS Q70/Q86 shape)
    ranks within each rollup hierarchy level."""
    df = dt.from_pydict({"cat": ["a", "a", "b"], "cls": ["p", "q", "p"],
                         "v": [1.0, 2.0, 4.0]})
    out = dt.sql(
        "SELECT SUM(v) total, cat, cls, "
        "GROUPING(cat)+GROUPING(cls) lochierarchy, "
        "RANK() OVER (PARTITION BY GROUPING(cat)+GROUPING(cls), "
        "CASE WHEN GROUPING(cls) = 0 THEN cat END "
        "ORDER BY SUM(v) DESC) rank_within_parent "
        "FROM df GROUP BY ROLLUP(cat, cls) "
        "ORDER BY lochierarchy DESC, cat, cls", df=df).to_pandas()
    grand = out[out.lochierarchy == 2]
    assert list(grand.total) == [7.0]
    lvl0_a = out[(out.lochierarchy == 0) & (out.cat == "a")]
    assert sorted(lvl0_a.rank_within_parent) == [1, 2]


def test_self_join_bare_qualified_refs_keep_planning():
    """Regression (r5 advisory): the bare-name alias of an unaliased
    qualified ref must NOT apply when it collides with another SELECT
    item's output name — ``SELECT a.x, b.x FROM t a JOIN t b`` plans as
    x / right.x instead of raising a duplicate-column error."""
    t = dt.from_pydict({"x": [1, 2, 3], "k": [1, 1, 2]})
    out = sql("SELECT a.x, b.x FROM t a JOIN t b ON a.k = b.k",
              t=t).to_pydict()
    assert sorted(out.keys()) == ["right.x", "x"]
    rows = sorted(zip(out["x"], out["right.x"]))
    # k=1 rows {1,2} self-join → 4 pairs; k=2 row {3} → 1 pair
    assert rows == [(1, 1), (1, 2), (2, 1), (2, 2), (3, 3)]


def test_unaliased_qualified_ref_still_gets_bare_name():
    """The non-colliding case keeps its SQL-standard bare output name."""
    t = dt.from_pydict({"customer_id": [7], "k": [1]})
    out = sql("SELECT c.customer_id FROM t c", t=t).to_pydict()
    assert list(out.keys()) == ["customer_id"]


def test_scalar_subquery_over_empty_relation_yields_null():
    """Latent host-path bug exposed by the mesh admission gate: the
    single-row guard's count surfaces as NULL (not 0) for an empty
    subquery relation through the exchange path — must read as 0."""
    t = dt.from_pydict({"x": [1, 2]})
    e = dt.from_pydict({"y": [5]})
    out = sql("SELECT x, (SELECT y FROM e WHERE y > 100) m FROM t "
              "ORDER BY x", t=t, e=e).to_pydict()
    assert out["x"] == [1, 2]
    assert out["m"] == [None, None]


def test_order_by_limit_over_empty_stream():
    """TopN over a child that yields NO morsels (not just empty ones)
    must produce an empty result, not IndexError (TPC-DS q8 shape)."""
    t = dt.from_pydict({"x": [1, 2, 3]})
    out = sql("SELECT x FROM t WHERE x > 100 ORDER BY x LIMIT 5",
              t=t).to_pydict()
    assert out["x"] == []
