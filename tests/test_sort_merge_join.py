"""Distributed sort-merge join strategy (reference: SortMergeJoin physical
op with aligned-boundary sorting): both sides range-partition on one shared
boundary set, then merge pairwise."""

import numpy as np
import pytest

import daft_tpu
from daft_tpu import col


@pytest.fixture
def sides():
    rng = np.random.default_rng(7)
    lk = rng.integers(0, 500, 4000)
    rk = rng.integers(0, 500, 1500)
    left = daft_tpu.from_pydict(
        {"k": lk.tolist(), "lv": np.arange(4000).tolist()}).into_partitions(5)
    right = daft_tpu.from_pydict(
        {"k": rk.tolist(), "rv": np.arange(1500).tolist()}).into_partitions(3)
    return left, right


def _canon(d):
    return sorted(zip(d["k"], d["lv"], d["rv"]))


def test_matches_hash_join(sides):
    left, right = sides
    hash_out = left.join(right, on="k", strategy="hash").to_pydict()
    sm_out = left.join(right, on="k", strategy="sort_merge").to_pydict()
    assert _canon(sm_out) == _canon(hash_out)


def test_output_is_range_clustered(sides):
    left, right = sides
    df = left.join(right, on="k", strategy="sort_merge")
    parts = [p.combined().to_arrow_table() for p in df.iter_partitions()]
    assert len(parts) > 1
    # co-ranged: per-partition key ranges do not interleave
    ranges = [(min(t.column("k").to_pylist()), max(t.column("k").to_pylist()))
              for t in parts if t.num_rows]
    for (lo1, hi1), (lo2, hi2) in zip(ranges, ranges[1:]):
        assert hi1 <= lo2


def test_plan_has_no_hash_exchanges(sides):
    left, right = sides
    from daft_tpu.physical import plan as pp, translate as pt
    df = left.join(right, on="k", strategy="sort_merge")
    phys = pt.translate(df._builder.optimize().plan)

    def exchanges(n):
        out = []
        if isinstance(n, pp.Exchange):
            out.append(n.kind)
        for c in n.children:
            out.extend(exchanges(c))
        return out

    assert "hash" not in exchanges(phys)


def test_left_join_and_empty_side(sides):
    left, right = sides
    out = left.join(right, on="k", how="left",
                    strategy="sort_merge").to_pydict()
    hash_out = left.join(right, on="k", how="left",
                         strategy="hash").to_pydict()
    key = lambda d: sorted((k, lv, rv if rv is not None else -1)
                           for k, lv, rv in zip(d["k"], d["lv"], d["rv"]))
    assert key(out) == key(hash_out)

    empty = daft_tpu.from_pydict({"k": [], "rv": []})
    out2 = left.join(empty, on="k", strategy="sort_merge").to_pydict()
    assert out2["k"] == []
