"""Test harness configuration.

Mirrors the reference's runner-matrix trick (``tests/conftest.py:32-38`` there:
one behavioral corpus, N backends): here the matrix axis is the device tier —
the full suite runs against a virtual 8-device CPU mesh
(``xla_force_host_platform_device_count``) so multi-chip sharding logic is
exercised without TPU hardware, and ``DAFT_TPU_DEVICE=0`` in the environment
reruns everything on the pure host tier.

``DAFT_TPU_REAL_DEVICE=1`` flips the suite onto the REAL accelerator
backend instead (no CPU forcing, no virtual mesh): an opt-in pass that
catches TPU-only numerics (f32 accumulation, int64 emulation) the CPU
backend hides. Budget warning: first compiles of each shape are remote
(10–160 s; amortized across processes by the persistent XLA compilation
cache, ``daft_tpu/device/backend.py``) — the standard opt-in set
(round 5: widened with the distributed runner, shuffle service, and
image/function kernels; 122 passed / 13 mesh-skips warm) is::

    DAFT_TPU_REAL_DEVICE=1 pytest tests/test_tpch.py \
        tests/test_exchange.py tests/test_device_join.py \
        tests/test_bigint_device.py tests/test_window_device.py \
        tests/test_datatypes.py tests/test_distributed.py \
        tests/test_shuffle_service.py tests/test_functions.py
"""

import os

# must run before any jax backend initializes. NOTE: this machine's site
# customization re-registers a TPU plugin and overrides the JAX_PLATFORMS env
# var, so we force the platform through jax.config instead.
_REAL = os.environ.get("DAFT_TPU_REAL_DEVICE") == "1"
if not _REAL:
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = \
            flags + " --xla_force_host_platform_device_count=8"

import jax

if not _REAL:
    jax.config.update("jax_platforms", "cpu")

import gc

import numpy as np
import pyarrow as pa
import pytest

# The full suite accumulates several GB of long-lived engine state
# (compile caches, result caches, answer tables) — with the default
# gen2 threshold (10) CPython walks that entire live set every ~70k
# allocations, which makes the tail of a 1200-test serial run ~2x
# slower than the same tests in isolation. Suppress full collections
# for the run (gen0/gen1 still reclaim short-lived cycles; long-lived
# garbage just stays resident, which a test box can afford) and move
# the import-time baseline to the permanent generation so even
# explicit gc.collect() calls in tests don't re-walk it.
gc.set_threshold(700, 10, 100_000)
gc.freeze()

# importing daft_tpu ALSO arms the runtime lock-order sanitizer when
# DAFT_TPU_SANITIZE=1 (daft_tpu/__init__.py patches the lock factories
# before any engine module creates its module-level locks)
import daft_tpu
from daft_tpu import DataType, col
from daft_tpu.analysis import lock_sanitizer as _lock_sanitizer
from daft_tpu.analysis import plan_sanitizer as _plan_sanitizer
from daft_tpu.analysis import retrace_sanitizer as _retrace_sanitizer


@pytest.fixture(params=[False, True], ids=["host", "device"])
def device_tier(request, monkeypatch):
    """Parametrize a test over host-only and device execution tiers."""
    if request.param:
        monkeypatch.setenv("DAFT_TPU_DEVICE", "1")
    else:
        monkeypatch.setenv("DAFT_TPU_DEVICE", "0")
    return request.param


def make_df(data):
    return daft_tpu.from_pydict(data)


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: excluded from the tier-1 `-m 'not slow'` run")


def pytest_collection_modifyitems(config, items):
    """Under the DAFT_TPU_REAL_DEVICE=1 opt-in pass, tests that require a
    multi-device mesh skip on single-chip boxes instead of failing."""
    if not _REAL:
        return
    if jax.device_count() >= 2:
        return
    skip = pytest.mark.skip(
        reason="real-device pass on a single chip: no multi-device mesh")
    for item in items:
        if "exchange" in item.nodeid or "multichip" in item.nodeid:
            item.add_marker(skip)


def pytest_sessionfinish(session, exitstatus):
    """DAFT_TPU_SANITIZE=1: print the lock-order sanitizer report at
    session end and FAIL the session on any acquisition-order cycle (a
    potential deadlock two threads haven't hit yet).  With
    DAFT_TPU_SANITIZE_RETRACE also armed, print the retrace-sanitizer
    report and FAIL on any retrace-budget violation (a dispatch site
    that traced twice for one declared signature — the recompile tax)."""
    if _plan_sanitizer.is_enabled():
        print("\n" + _plan_sanitizer.report())
        if _plan_sanitizer.summary().get("violations"):
            print("daft-lint plan sanitizer: plan-contract violations "
                  "detected — failing the session")
            session.exitstatus = 1
    if _retrace_sanitizer.is_enabled():
        print("\n" + _retrace_sanitizer.report())
        if _retrace_sanitizer.summary().get("violations"):
            print("daft-lint retrace sanitizer: retrace-budget "
                  "violations detected — failing the session")
            session.exitstatus = 1
    if not _lock_sanitizer.is_enabled():
        return
    print("\n" + _lock_sanitizer.report())
    if _lock_sanitizer.summary()["cycles"]:
        print("daft-lint lock sanitizer: acquisition-order cycles "
              "detected — failing the session")
        session.exitstatus = 1
