"""Query-wide tracing plane tests: span propagation, deterministic ids
under chaos, Chrome/OTLP export, /metrics scrape, flight recorder."""

import glob
import json
import os
import threading
import time

import pytest

import daft_tpu as daft
from daft_tpu import col, tracing
from daft_tpu import observability as obs


@pytest.fixture(autouse=True)
def _clean_tracing():
    tracing.reset_for_tests()
    yield
    tracing.reset_for_tests()


def _run_distributed(monkeypatch, n_workers=2, fault_spec=None, seed="7"):
    """One distributed grouped-agg query; returns (answer, recorder)."""
    import daft_tpu.context as dctx
    from daft_tpu.distributed import resilience as rz
    from daft_tpu.runners.distributed_runner import DistributedRunner

    monkeypatch.setenv("DAFT_TPU_TRACE", "1")
    monkeypatch.setenv("DAFT_TPU_CHAOS_SERIALIZE", "1")
    monkeypatch.setenv("DAFT_TPU_RETRY_BACKOFF", "0.01")
    if fault_spec:
        monkeypatch.setenv("DAFT_TPU_FAULT_SPEC", fault_spec)
        monkeypatch.setenv("DAFT_TPU_FAULT_SEED", seed)
    rz.reset_for_tests()
    runner = DistributedRunner(num_workers=n_workers)
    old = dctx.get_context()._runner
    dctx.get_context().set_runner(runner)
    try:
        df = (daft.from_pydict({"k": [i % 7 for i in range(4000)],
                                "v": [float(i) for i in range(4000)]})
              .into_partitions(3)
              .groupby("k").agg(col("v").sum().alias("s")))
        out = df.to_pydict()
    finally:
        dctx.get_context().set_runner(old)
        if runner._manager is not None:
            runner._manager.shutdown()
        rz.reset_for_tests()
    stats = obs.last_query_stats()
    assert stats is not None and stats.trace_ctx is not None
    rows = sorted(zip(out["k"], [round(s, 6) for s in out["s"]]))
    return rows, stats.trace_ctx.recorder


# ------------------------------------------------------------ gating

def test_tracing_off_by_default():
    df = daft.from_pydict({"x": [1, 2, 3]}).where(col("x") > 1)
    df.collect()
    stats = obs.last_query_stats()
    assert stats.trace_ctx is None
    assert stats.trace_summary == {}
    # span sites are no-ops on untraced threads
    assert tracing.current() is None
    sp = tracing.span("anything")
    assert sp is tracing._NOOP


def test_sampling_zero_traces_nothing(monkeypatch):
    monkeypatch.setenv("DAFT_TPU_TRACE", "1")
    monkeypatch.setenv("DAFT_TPU_TRACE_SAMPLE", "0.0")
    daft.from_pydict({"x": [1, 2, 3]}).where(col("x") > 1).collect()
    assert obs.last_query_stats().trace_ctx is None


def test_span_ids_are_pure_functions_of_keys():
    assert tracing.span_id_from("task:s0.t1") == \
        tracing.span_id_from("task:s0.t1")
    assert tracing.span_id_from("task:s0.t1") != \
        tracing.span_id_from("task:s0.t2")
    assert len(tracing.span_id_from("x")) == 16


def test_recorder_bounded(monkeypatch):
    rec = tracing.SpanRecorder("t" * 32, max_spans=5)
    for i in range(10):
        rec.add("s", tracing.span_id_from(f"k{i}"), None, i, 1)
    assert len(rec.spans()) == 5
    assert rec.dropped == 5
    assert rec.summary()["dropped"] == 5


# ----------------------------------------------------- local tracing

def test_local_query_trace_exports_chrome(tmp_path, monkeypatch):
    monkeypatch.setenv("DAFT_TPU_TRACE", "1")
    monkeypatch.setenv("DAFT_TPU_TRACE_DIR", str(tmp_path))
    df = (daft.from_pydict({"x": list(range(500)),
                            "g": [i % 5 for i in range(500)]})
          .where(col("x") > 10).groupby("g").agg(col("x").sum().alias("s")))
    df.collect()
    stats = obs.last_query_stats()
    assert stats.trace_ctx is not None
    assert stats.trace_summary.get("spans", 0) > 0
    files = glob.glob(str(tmp_path / "trace_*.json"))
    assert files, "no chrome trace exported"
    doc = json.load(open(files[0]))
    assert tracing.validate_chrome_trace(doc) == []
    names = {e["name"] for e in doc["traceEvents"] if e["ph"] == "X"}
    assert "query" in names
    assert "plan:optimize" in names and "plan:translate" in names
    assert any(n.startswith("op:") for n in names)
    # explain(analyze=True) renders the trace line
    assert "trace: id=" in stats.render()


def test_trace_registry_unregisters_after_export(tmp_path, monkeypatch):
    monkeypatch.setenv("DAFT_TPU_TRACE", "1")
    daft.from_pydict({"x": [1, 2, 3]}).where(col("x") > 1).collect()
    rec = obs.last_query_stats().trace_ctx.recorder
    assert rec.exported
    assert tracing.recorder_for(rec.trace_id) is None


# ------------------------------------------------- distributed chaos

def test_chaos_trace_deterministic_and_complete(monkeypatch):
    """The satellite contract: a seeded chaotic distributed query yields
    a merged trace where every retry/lineage-recompute is a child of its
    task span, span ids replay bit-identically across two runs, and no
    span is orphaned."""
    spec = "task:0.1,fetch:0.1,crash:0.1"
    rows1, rec1 = _run_distributed(monkeypatch, fault_spec=spec)
    rows2, rec2 = _run_distributed(monkeypatch, fault_spec=spec)
    assert rows1 == rows2

    # bit-identical span ids across runs
    assert sorted(rec1.span_ids()) == sorted(rec2.span_ids())

    # no orphans: every parent id resolves
    assert tracing.orphan_spans(rec1) == []

    spans = rec1.spans()
    kinds = {s["name"] for s in spans}
    # the merged trace covers driver, stage, worker-task and fetch tiers
    for want in ("query", "stage", "task", "task:run", "shuffle:fetch"):
        assert want in kinds, (want, sorted(kinds))
    # chaos actually fired: retries and/or recomputes present…
    assert "task:retry" in kinds
    # …and every retry / recompute hangs off a task span
    by_id = {s["span_id"]: s for s in spans}
    for s in spans:
        if s["name"] in ("task:retry", "lineage:recompute"):
            parent = by_id.get(s["parent_id"])
            assert parent is not None and parent["name"] == "task", s
        if s["name"] == "task:run":
            parent = by_id.get(s["parent_id"])
            assert parent is not None and parent["name"] == "task", s
    # chrome export of the merged trace validates
    assert tracing.validate_chrome_trace(
        tracing.chrome_trace_json(rec1)) == []


def test_faultfree_distributed_trace(monkeypatch):
    rows, rec = _run_distributed(monkeypatch)
    kinds = {s["name"] for s in rec.spans()}
    assert "task:run" in kinds and "stage" in kinds
    assert tracing.orphan_spans(rec) == []


def test_remote_worker_ships_spans_cross_process(monkeypatch):
    """A worker in ANOTHER process buffers its spans and ships them back
    with the task result; the driver merges them with clock-offset
    correction into the one query trace."""
    import subprocess
    import sys

    from daft_tpu.distributed import (LeastLoadedScheduler, StagePlan,
                                      StageRunner, WorkerManager)
    from daft_tpu.distributed.remote_worker import RemoteWorker
    from daft_tpu.physical.translate import translate

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("DAFT_TPU_TRACE", None)
    proc = subprocess.Popen(
        [sys.executable, "-m", "daft_tpu.distributed.remote_worker",
         "--port", "0", "--host", "127.0.0.1"],
        stdout=subprocess.PIPE, text=True, env=env, cwd=repo)
    try:
        line = proc.stdout.readline()  # "daft-tpu worker on http://…"
        addr = line.strip().split()[-1]
        assert addr.startswith("http://"), line
        monkeypatch.setenv("DAFT_TPU_TRACE", "1")
        tctx = tracing.maybe_start_trace("xproc")
        assert tctx is not None
        df = (daft.from_pydict({"k": [i % 5 for i in range(300)],
                                "v": [float(i) for i in range(300)]})
              .into_partitions(2)
              .groupby("k").agg(col("v").sum().alias("s")))
        with tracing.attach(tctx):
            sp = StagePlan.from_physical(
                translate(df._builder.optimize().plan))
            mgr = WorkerManager([RemoteWorker("remote-0", addr)])
            runner = StageRunner(mgr, LeastLoadedScheduler())
            parts = list(runner.run(sp))
        got = {}
        for p in parts:
            d = p.to_pydict()
            for k, s in zip(d.get("k", []), d.get("s", [])):
                got[k] = s
        assert set(got) == {0, 1, 2, 3, 4}
        rec = tctx.recorder
        kinds = {s["name"] for s in rec.spans()}
        assert "rpc:post" in kinds
        assert "task:run" in kinds, sorted(kinds)
        # the worker's spans really crossed the wire: worker-lane spans
        # exist and a clock offset was measured for the worker address
        assert any(s["lane"].startswith("worker:")
                   for s in rec.spans() if s["name"] == "task:run")
        assert addr in rec.summary().get("clock_offsets_us", {})
        assert tracing.orphan_spans(rec) == []
    finally:
        proc.terminate()
        proc.wait(timeout=10)


# ------------------------------------------------------ wire context

def test_wire_headers_roundtrip():
    rec = tracing.SpanRecorder("ab" * 16)
    tracing.register_recorder(rec)
    ctx = tracing.SpanContext(rec, rec.root_id)
    hdrs = tracing.wire_headers(ctx)
    assert hdrs["X-Daft-Trace-Id"] == rec.trace_id
    back = tracing.context_from_headers(hdrs)
    assert back is not None
    assert back.recorder is rec and back.span_id == rec.root_id
    # unknown trace (other process) → None
    tracing.unregister_recorder(rec.trace_id)
    assert tracing.context_from_headers(hdrs) is None
    assert tracing.context_from_headers({}) is None


def test_remote_span_merge_applies_clock_offset():
    rec = tracing.SpanRecorder("cd" * 16)
    remote = [{"name": "task:run", "span_id": tracing.span_id_from("r"),
               "parent_id": rec.root_id, "ts_us": 1_000_000,
               "dur_us": 5, "lane": "worker:w9"}]
    rec.add_remote(remote, offset_us=250, worker="http://w9:1")
    s = rec.spans()[0]
    assert s["ts_us"] == 1_000_250
    assert rec.summary()["clock_offsets_us"] == {"http://w9:1": 250}
    # malformed remote spans are counted, not raised
    rec.add_remote([{"nope": 1}], 0, "w")
    assert rec.dropped == 1


# ------------------------------------------------------ chrome schema

def test_chrome_validator_catches_bad_traces():
    assert tracing.validate_chrome_trace({}) == \
        ["traceEvents is not a list"]
    bad_phase = {"traceEvents": [
        {"name": "x", "ph": "Q", "pid": 1, "tid": 1}]}
    assert tracing.validate_chrome_trace(bad_phase)
    neg_ts = {"traceEvents": [
        {"name": "x", "ph": "X", "pid": 1, "tid": 1, "ts": -5, "dur": 1}]}
    assert tracing.validate_chrome_trace(neg_ts)
    non_monotonic = {"traceEvents": [
        {"name": "a", "ph": "X", "pid": 1, "tid": 1, "ts": 10, "dur": 1},
        {"name": "b", "ph": "X", "pid": 1, "tid": 1, "ts": 5, "dur": 1}]}
    assert any("non-monotonic" in p
               for p in tracing.validate_chrome_trace(non_monotonic))
    unmatched = {"traceEvents": [
        {"name": "a", "ph": "B", "pid": 1, "tid": 1, "ts": 1}]}
    assert any("unmatched B" in p
               for p in tracing.validate_chrome_trace(unmatched))
    ok = {"traceEvents": [
        {"name": "a", "ph": "B", "pid": 1, "tid": 1, "ts": 1},
        {"name": "a", "ph": "E", "pid": 1, "tid": 1, "ts": 2}]}
    assert tracing.validate_chrome_trace(ok) == []


# ---------------------------------------------------------- /metrics

def test_prometheus_text_parses_strictly():
    text = tracing.prometheus_text()
    metrics = tracing.parse_prometheus_text(text)
    assert "daft_tpu_flight_recorder_queries_total" in metrics
    assert "daft_tpu_traces_active" in metrics
    for bad in ("no value\n", "0badname 1\n", "m 1 2 3\n", "m notanum\n",
                "# TYPE m sometype\n"):
        with pytest.raises(ValueError):
            tracing.parse_prometheus_text(bad)


def test_metrics_endpoint_and_serving_gauges(monkeypatch):
    import urllib.request

    from daft_tpu import dashboard, serving

    sched = serving.QueryScheduler(concurrency=1)
    monkeypatch.setattr(serving, "_shared", sched)
    port = dashboard.launch(0)
    try:
        df = daft.from_pydict({"x": list(range(100)),
                               "g": [i % 4 for i in range(100)]}) \
            .groupby("g").agg(col("x").sum().alias("s"))
        sched.submit(df).result(timeout=60)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
            assert r.headers["Content-Type"].startswith("text/plain")
            text = r.read().decode()
        metrics = tracing.parse_prometheus_text(text)
        assert metrics.get("daft_tpu_serving_completed_total", 0) >= 1
        assert "daft_tpu_serving_queue_depth" in metrics
        assert "daft_tpu_serving_running" in metrics
    finally:
        dashboard.shutdown()
        monkeypatch.setattr(serving, "_shared", None)
        sched.shutdown()


# ----------------------------------------------------- flight recorder

def test_flight_recorder_records_and_rotates(tmp_path, monkeypatch):
    path = str(tmp_path / "queries.jsonl")
    monkeypatch.setenv("DAFT_TPU_QUERY_LOG", path)
    monkeypatch.setenv("DAFT_TPU_QUERY_LOG_BYTES", "4000")
    monkeypatch.setenv("DAFT_TPU_SLOW_QUERY_MS", "0.000001")
    daft.from_pydict({"x": list(range(50))}).where(col("x") > 5).collect()
    entries = tracing.flight_history()
    assert entries, "no flight-recorder entry for the query"
    e = entries[0]
    assert e["wall_us"] > 0 and "operators" in e
    assert e["slow"] is True  # any query beats a 1ns threshold
    # rotation: write entries past the byte cap
    for i in range(100):
        tracing.flight_record({"i": i, "pad": "x" * 128})
    assert os.path.exists(path + ".1"), "no rotated generation"
    assert os.path.getsize(path) <= 4000
    # history reads across generations, newest first
    hist = tracing.flight_history(limit=10)
    assert len(hist) == 10 and hist[0]["i"] == 99


def test_flight_recorder_history_endpoint(tmp_path, monkeypatch):
    import urllib.request

    from daft_tpu import dashboard

    monkeypatch.setenv("DAFT_TPU_QUERY_LOG",
                       str(tmp_path / "queries.jsonl"))
    daft.from_pydict({"x": [1, 2, 3]}).where(col("x") > 1).collect()
    port = dashboard.launch(0)
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/api/history", timeout=10) as r:
            hist = json.loads(r.read())
        assert hist and "wall_us" in hist[0]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/", timeout=10) as r:
            page = r.read().decode()
        assert "flight recorder" in page
    finally:
        dashboard.shutdown()


# ------------------------------------------------- dashboard history cap

def test_dashboard_history_bounded_by_count_and_bytes(monkeypatch):
    from daft_tpu import dashboard

    monkeypatch.setattr(dashboard, "_history", [])
    monkeypatch.setattr(dashboard, "_history_bytes", [])
    monkeypatch.setattr(dashboard, "_MAX_HISTORY", 10)
    monkeypatch.setattr(dashboard, "_MAX_HISTORY_BYTES", 3000)

    class FakeStats:
        def as_dict(self):
            return {"Op": {"rows_out": 1}}

        def render(self, plan=None):
            return "explain " + "y" * 400  # ~420B entries

    for _ in range(50):
        dashboard.broadcast_query(FakeStats())
    assert len(dashboard._history) <= 10
    assert sum(dashboard._history_bytes) <= 3000
    # byte cap binds before the count cap with these sizes
    assert len(dashboard._history) < 10
    # the newest entry always survives
    assert dashboard._history[-1]["explain"].startswith("explain")


# -------------------------------------------------- otlp hardening

class _StubCollector:
    """OTLP collector stub: mode 'ok' | 'hang' | '500'."""

    def __init__(self, mode):
        import http.server

        stub = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_POST(self):
                body = self.rfile.read(
                    int(self.headers.get("Content-Length", 0)))
                if stub.mode == "hang":
                    stub.hung.wait(20)
                    return
                stub.received.append((self.path, json.loads(body)))
                code = 500 if stub.mode == "500" else 200
                self.send_response(code)
                self.end_headers()
                self.wfile.write(b"{}")
                stub.got.set()

            def log_message(self, *a):
                pass

        import http.server as hs
        self.mode = mode
        self.received = []
        self.got = threading.Event()
        self.hung = threading.Event()
        self.srv = hs.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        threading.Thread(target=self.srv.serve_forever,
                         daemon=True).start()

    @property
    def endpoint(self):
        return f"http://127.0.0.1:{self.srv.server_port}"

    def shutdown(self):
        self.hung.set()
        self.srv.shutdown()


def test_otlp_hung_collector_never_stalls_query(monkeypatch):
    stub = _StubCollector("hang")
    try:
        monkeypatch.setenv("DAFT_TPU_OTLP_ENDPOINT", stub.endpoint)
        monkeypatch.setenv("DAFT_TPU_OTLP_TIMEOUT", "0.3")
        before = obs.obs_counters_snapshot().get("otlp_export_errors", 0)
        t0 = time.monotonic()
        out = daft.from_pydict({"x": [1, 2, 3]}).where(col("x") > 1) \
            .count_rows()
        elapsed = time.monotonic() - t0
        assert out == 2
        # the query path never blocks on the hung POST
        assert elapsed < 10
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if obs.obs_counters_snapshot().get(
                    "otlp_export_errors", 0) > before:
                break
            time.sleep(0.05)
        assert obs.obs_counters_snapshot().get(
            "otlp_export_errors", 0) > before
    finally:
        stub.shutdown()


def test_otlp_500_counted_not_raised(monkeypatch):
    stub = _StubCollector("500")
    try:
        monkeypatch.setenv("DAFT_TPU_OTLP_ENDPOINT", stub.endpoint)
        before = obs.obs_counters_snapshot().get("otlp_export_errors", 0)
        daft.from_pydict({"x": [1, 2, 3]}).where(col("x") > 1).collect()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if obs.obs_counters_snapshot().get(
                    "otlp_export_errors", 0) > before:
                break
            time.sleep(0.05)
        assert obs.obs_counters_snapshot().get(
            "otlp_export_errors", 0) > before
    finally:
        stub.shutdown()


def test_otlp_spans_posted_for_traced_query(monkeypatch):
    stub = _StubCollector("ok")
    try:
        monkeypatch.setenv("DAFT_TPU_OTLP_ENDPOINT", stub.endpoint)
        monkeypatch.setenv("DAFT_TPU_TRACE", "1")
        daft.from_pydict({"x": [1, 2, 3]}).where(col("x") > 1).collect()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if any(p == "/v1/traces" for p, _ in stub.received):
                break
            time.sleep(0.05)
        traces = [b for p, b in stub.received if p == "/v1/traces"]
        assert traces, [p for p, _ in stub.received]
        scope = traces[0]["resourceSpans"][0]["scopeSpans"][0]
        names = {s["name"] for s in scope["spans"]}
        assert "query" in names
        # metrics still export beside spans
        assert any(p == "/v1/metrics" for p, _ in stub.received)
    finally:
        stub.shutdown()


# ------------------------------------------------------- serving plane

def test_serving_trace_has_queue_and_run_spans(monkeypatch):
    from daft_tpu import serving

    monkeypatch.setenv("DAFT_TPU_TRACE", "1")
    sched = serving.QueryScheduler(concurrency=1)
    try:
        df = daft.from_pydict({"x": list(range(200)),
                               "g": [i % 3 for i in range(200)]}) \
            .groupby("g").agg(col("x").sum().alias("s"))
        h = sched.submit(df, session="traced")
        h.result(timeout=60)
        assert h.trace_ctx is not None
        rec = h.trace_ctx.recorder
        assert rec.exported  # finalized by the scheduler, once
        kinds = {s["name"] for s in rec.spans()}
        assert "serve:queue" in kinds and "serve:run" in kinds
        assert "plan:fingerprint" in kinds
        q = next(s for s in rec.spans() if s["name"] == "serve:queue")
        assert q["attrs"]["session"] == "traced"
        assert tracing.orphan_spans(rec) == []
        # the handle's stats carry the summary for explain/history
        assert h.stats.trace_summary.get("trace_id") == rec.trace_id
    finally:
        sched.shutdown()


def test_serving_failed_query_still_exported(tmp_path, monkeypatch):
    """A FAILED serving query is the one an operator most needs: it must
    still land in the flight recorder (with the error) and export its
    trace with error status — only rejected/cancelled queries skip."""
    from daft_tpu import DataType, serving, udf

    monkeypatch.setenv("DAFT_TPU_TRACE", "1")
    monkeypatch.setenv("DAFT_TPU_QUERY_LOG", str(tmp_path / "q.jsonl"))
    monkeypatch.setenv("DAFT_TPU_TRACE_DIR", str(tmp_path))

    @udf(return_dtype=DataType.int64())
    def boom(x):
        raise RuntimeError("intentional test failure")

    sched = serving.QueryScheduler(concurrency=1)
    try:
        df = daft.from_pydict({"x": [1, 2, 3]}).select(boom(col("x")))
        h = sched.submit(df)
        with pytest.raises(Exception):
            h.result(timeout=60)
        assert h.state == "failed"
        entries = [e for e in tracing.flight_history()
                   if (e.get("serving") or {}).get("state") == "failed"]
        assert entries, tracing.flight_history()
        assert "intentional test failure" in entries[0]["serving"]["error"]
        if h.trace_ctx is not None:
            rec = h.trace_ctx.recorder
            assert rec.exported
            root = next(s for s in rec.spans() if s["name"] == "query")
            assert root.get("status") == "error"
            assert glob.glob(str(tmp_path / "trace_*.json"))
    finally:
        sched.shutdown()


def test_worker_concurrent_tasks_one_trace_no_span_loss(monkeypatch):
    """Two tasks of ONE trace running concurrently on the same
    cross-process worker: the per-trace ship-back buffer is refcounted
    and drained, so neither task's run span is lost (the regression was
    the loser of the check-then-register race vanishing into an
    unregistered recorder)."""
    import subprocess
    import sys

    from daft_tpu.distributed.remote_worker import RemoteWorker
    from daft_tpu.distributed.worker import StageTask
    from daft_tpu.micropartition import MicroPartition
    from daft_tpu.physical import plan as pp
    from daft_tpu.recordbatch import RecordBatch

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen(
        [sys.executable, "-m", "daft_tpu.distributed.remote_worker",
         "--port", "0", "--host", "127.0.0.1", "--slots", "2"],
        stdout=subprocess.PIPE, text=True, env=env, cwd=repo)
    rw = None
    try:
        addr = proc.stdout.readline().strip().split()[-1]
        rec = tracing.SpanRecorder("ee" * 16)
        tracing.register_recorder(rec)
        rw = RemoteWorker("r0", addr, num_slots=2)
        mp = MicroPartition.from_recordbatch(
            RecordBatch.from_pydict({"x": list(range(50))}))
        schema = mp.schema

        def mk_task(i):
            return StageTask(
                0, pp.InMemorySource([mp], schema), {}, task_idx=i,
                fault_key=f"s0.t{i}",
                trace_ctx=(rec.trace_id,
                           tracing.span_id_from(f"run:s0.t{i}"),
                           rec.root_id))

        futs = [rw.submit(mk_task(i)) for i in range(2)]
        for f in futs:
            assert f.result(timeout=120)
        runs = {s["span_id"] for s in rec.spans()
                if s["name"] == "task:run"}
        assert tracing.span_id_from("run:s0.t0") in runs
        assert tracing.span_id_from("run:s0.t1") in runs
        tracing.unregister_recorder(rec.trace_id)
    finally:
        if rw is not None:
            rw.shutdown()
        proc.terminate()
        proc.wait(timeout=10)


def test_serving_cancel_event_and_trace_close(monkeypatch):
    from daft_tpu import serving

    monkeypatch.setenv("DAFT_TPU_TRACE", "1")
    sched = serving.QueryScheduler(concurrency=1)
    try:
        blocker = threading.Event()

        class SlowStats:
            pass

        # a queued query cancelled before it runs
        df = daft.from_pydict({"x": [1]}).where(col("x") > 0)
        h1 = sched.submit(df)       # will run
        h2 = sched.submit(df)       # may queue behind h1
        h2.cancel("test cancel")
        try:
            h2.result(timeout=30)
        except Exception:
            pass
        blocker.set()
        if h2.state == "cancelled" and h2.trace_ctx is not None:
            rec = h2.trace_ctx.recorder
            assert rec.exported  # closed, not leaked
            assert tracing.recorder_for(rec.trace_id) is None
    finally:
        sched.shutdown()


def test_planner_failure_aborts_and_unregisters_trace(monkeypatch):
    """r14 regression (found by daft-lint trace-recorder-leak): a
    translate/optimize failure between maybe_start_trace and the
    executor's stats-context adoption left the recorder registered for
    the process lifetime, with the trace silently lost."""
    monkeypatch.setenv("DAFT_TPU_TRACE", "1")

    def boom(plan):
        raise RuntimeError("translate exploded")

    monkeypatch.setattr("daft_tpu.runners.native_runner.translate", boom)
    df = daft.from_pydict({"x": [1, 2, 3]}).where(col("x") > 1)
    with pytest.raises(RuntimeError, match="translate exploded"):
        df.to_pydict()
    # the aborted trace closed and left the registry
    with tracing._reg_lock:
        assert dict(tracing._recorders) == {}


def test_abort_trace_is_idempotent_and_none_safe():
    tracing.abort_trace(None)  # no-op
    rec = tracing.SpanRecorder("t" * 32)
    tracing.register_recorder(rec)
    ctx = tracing.SpanContext(rec, rec.root_id)
    tracing.abort_trace(ctx)
    tracing.abort_trace(ctx)  # second call: already exported, no-op
    assert rec.exported and rec.status == "error"
    with tracing._reg_lock:
        assert rec.trace_id not in tracing._recorders
