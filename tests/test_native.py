"""Native C++ kernel library tests (hashing, fanout, minhash, HLL, probe).

Mirrors the reference's Rust unit tests for daft-hash / daft-minhash /
hyperloglog and the recordbatch partition kernels.
"""

import numpy as np
import pyarrow as pa
import pytest

import daft_tpu as daft
from daft_tpu import native
from daft_tpu.series import Series


requires_native = pytest.mark.skipif(not native.AVAILABLE,
                                     reason="native lib unavailable")


@requires_native
def test_xxh64_known_vectors():
    # spec test vectors for xxh64 (seed 0): empty and "Hello, world!"
    import ctypes
    empty = np.empty(0, dtype=np.uint8)
    h_empty = native._lib.dn_xxh64(
        empty.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), 0, 0)
    assert h_empty == 0xEF46DB3751D8E999
    msg = np.frombuffer(b"Hello, world!", dtype=np.uint8)
    h = native._lib.dn_xxh64(
        msg.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), len(msg), 0)
    assert h == 0xF58336A78B6F9476


@requires_native
def test_hash_var_and_fixed_consistency():
    off = np.array([0, 3, 6, 9], dtype=np.int64)
    data = np.frombuffer(b"abcxyzabc", dtype=np.uint8)
    h = native.hash_var(off, data, None)
    assert h[0] == h[2] and h[0] != h[1]
    hf = native.hash_fixed(np.array([7, 8, 7], dtype=np.int64), None)
    assert hf[0] == hf[2] and hf[0] != hf[1]
    # null rows hash to the null marker regardless of payload
    valid = np.array([1, 0, 1], dtype=np.uint8)
    h2 = native.hash_var(off, data, valid)
    assert h2[0] == h[0] and h2[1] != h[1]


@requires_native
def test_fanout_hash_covers_all_rows():
    h = native.hash_fixed(np.arange(1000, dtype=np.int64), None)
    counts, idx = native.fanout_hash(h, 7)
    assert counts.sum() == 1000
    assert sorted(idx.tolist()) == list(range(1000))
    # same key -> same partition
    h2 = native.hash_fixed(np.array([5, 5], dtype=np.int64), None)
    c2, _ = native.fanout_hash(h2, 7)
    assert (c2 > 0).sum() == 1


def test_series_hash_groups_equal_values():
    s = Series.from_pylist(["foo", "bar", "foo", None, None], "s")
    h = s.hash().to_pylist()
    assert h[0] == h[2] and h[0] != h[1]
    assert h[3] == h[4]


def test_partition_by_hash_recordbatch():
    b = daft.RecordBatch.from_pydict(
        {"k": ["a", "b", "a", "c", "b", "a"], "v": [1, 2, 3, 4, 5, 6]})
    from daft_tpu import col
    parts = b.partition_by_hash([col("k")], 4)
    assert sum(len(p) for p in parts) == 6
    # all rows of one key land in one partition
    for key in ("a", "b", "c"):
        holders = [i for i, p in enumerate(parts)
                   if key in p.to_pydict()["k"]]
        assert len(holders) == 1


def test_minhash_series_and_expression():
    s = Series.from_pylist(
        ["the quick brown fox", "the quick brown fox", "lorem ipsum dolor",
         None], "txt")
    sig = s.minhash(num_hashes=16, ngram_size=2)
    assert sig.datatype() == daft.DataType.fixed_size_list(
        daft.DataType.uint32(), 16)
    rows = sig.to_pylist()
    assert rows[0] == rows[1]        # identical text -> identical signature
    assert rows[0] != rows[2]
    assert rows[3] is None           # null in -> null out
    # expression surface
    from daft_tpu import col
    df = daft.from_pydict({"t": ["a b c", "a b c", "x y z"]})
    out = df.select(col("t").minhash(num_hashes=8, ngram_size=1)).to_pydict()
    assert out["t"][0] == out["t"][1]
    assert out["t"][0] != out["t"][2]


@requires_native
def test_minhash_jaccard_correlation():
    # signature agreement should track true jaccard similarity
    a = "w1 w2 w3 w4 w5 w6 w7 w8"
    b = "w1 w2 w3 w4 w5 w6 xx yy"   # high overlap
    c = "z1 z2 z3 z4 z5 z6 z7 z8"   # no overlap
    s = Series.from_pylist([a, b, c], "t")
    m = np.array(s.minhash(num_hashes=128, ngram_size=1).to_pylist())
    sim_ab = (m[0] == m[1]).mean()
    sim_ac = (m[0] == m[2]).mean()
    assert sim_ab > 0.4
    assert sim_ac < 0.15


@requires_native
def test_hyperloglog_accuracy_and_merge():
    h1 = native.hash_fixed(np.arange(0, 60000, dtype=np.int64), None)
    h2 = native.hash_fixed(np.arange(40000, 100000, dtype=np.int64), None)
    a = native.HyperLogLog().add_hashes(h1)
    b = native.HyperLogLog().add_hashes(h2)
    est_a = a.estimate()
    assert abs(est_a - 60000) / 60000 < 0.03
    a.merge(b)
    est = a.estimate()
    assert abs(est - 100000) / 100000 < 0.03


def test_approx_count_distinct_agg():
    import random
    random.seed(0)
    vals = [random.randrange(5000) for _ in range(20000)]
    truth = len(set(vals))
    df = daft.from_pydict({"x": vals})
    from daft_tpu import col
    out = df.agg(col("x").approx_count_distinct()).to_pydict()
    est = out["x"][0]
    assert abs(est - truth) / truth < 0.05


@requires_native
def test_probe_table_pairs():
    build = np.array([1, 2, 3, 2], dtype=np.int64)
    probe = np.array([2, 4, 1], dtype=np.int64)
    pt = native.ProbeTable(native.hash_fixed(build, None))
    pi, bi = pt.probe(native.hash_fixed(probe, None))
    pairs = sorted(zip(pi.tolist(), bi.tolist()))
    assert pairs == [(0, 1), (0, 3), (2, 0)]


@requires_native
def test_murmur3_known_vector():
    import ctypes
    msg = np.frombuffer(b"hello", dtype=np.uint8)
    h = native._lib.dn_murmur3_32(
        msg.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), 5, 0)
    assert h == 0x248BFA47  # public murmur3_x86_32 test vector


def test_minhash_fallback_matches_native():
    """Mixed-fleet invariant: a worker without the native lib must produce
    bit-identical signatures to one with it."""
    import numpy as np
    from daft_tpu import native
    from daft_tpu.series import _minhash_fallback
    if not native.AVAILABLE:
        import pytest
        pytest.skip("native lib unavailable")
    vals = ["the quick brown fox", "a  b", "a b", "", None, "single",
            "x " * 40 + "tail", "\tmulti\nline  text\r"]
    bufs = [(v.encode("utf-8") if v is not None else b"") for v in vals]
    offsets = np.cumsum([0] + [len(x) for x in bufs]).astype(np.int64)
    data = np.frombuffer(b"".join(bufs), dtype=np.uint8)
    valid = np.array([v is not None for v in vals])
    for nh, ng, sd in [(16, 2, 7), (4, 1, 1), (8, 3, 42)]:
        nat = np.asarray(native.minhash(offsets, data, valid, nh, ng, sd))
        assert np.array_equal(nat, _minhash_fallback(vals, nh, ng, sd))
