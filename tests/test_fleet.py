"""Fleet plane: consistent-hash session affinity, replica kill/drain
lifecycle with re-routing, gossiped learned state (idempotent +
commutative merges, cold-replica inheritance), the cross-replica cache
tier, and the calibration-generation token in plan fingerprints."""

import threading

import pyarrow as pa
import pytest

import daft_tpu as dt
from daft_tpu import DataType, col, udf
from daft_tpu.execution.cancellation import QueryCancelled
from daft_tpu.device import calibration as cal
from daft_tpu.fleet import cache_tier, state_sync
from daft_tpu.fleet.router import (FleetRouter, InProcessReplica,
                                   ReplicaUnavailable)
from daft_tpu.logical.fingerprint import fingerprint
from daft_tpu.serving import AdmissionRejected, QueryScheduler


def mkdf(d):
    return dt.from_pydict(d)


@pytest.fixture(autouse=True)
def _fresh_fleet_state():
    state_sync.reset_for_tests()
    cache_tier.install(None)
    cal.reset_for_tests()
    yield
    state_sync.reset_for_tests()
    cache_tier.install(None)
    cal.reset_for_tests()


@pytest.fixture
def parquet_table(tmp_path):
    root = tmp_path / "t"
    mkdf({"k": list(range(2000)),
          "g": [i % 7 for i in range(2000)],
          "v": [float(i % 31) for i in range(2000)]}) \
        .write_parquet(str(root))
    return str(root / "*.parquet")


def _agg_query(glob):
    return dt.read_parquet(glob).groupby("g") \
        .agg(col("v").sum().alias("s")).sort("g")


def _gated_query(gate: threading.Event, started: threading.Event = None):
    @udf(return_dtype=DataType.int64())
    def block(s):
        if started is not None:
            started.set()
        gate.wait(30)
        return s.to_pylist()

    return mkdf({"a": [1]}).select(block(col("a")))


@pytest.fixture
def fleet():
    hub = cache_tier.InProcessCacheTier()
    reps = [InProcessReplica(f"r{i}", cache_tier=hub) for i in range(3)]
    router = FleetRouter(reps)
    yield router, reps
    router.shutdown()


# ---------------------------------------------------------------- routing

def test_session_affinity_and_spread(fleet, parquet_table):
    """Same session → same replica every time; many sessions spread over
    >1 of the 3 replicas; results stay correct through the router."""
    router, _ = fleet
    expected = _agg_query(parquet_table).to_pydict()
    owners = set()
    for _ in range(5):
        h = router.submit(_agg_query(parquet_table), session="sticky")
        assert h.result(60).to_recordbatch().to_pydict() == expected
        owners.add(router.route("sticky").name)
    assert len(owners) == 1
    spread = {router.route(f"s-{i}").name for i in range(24)}
    assert len(spread) >= 2


def test_kill_reroutes_and_cancels_inflight(fleet, parquet_table):
    """Replica death: its in-flight query is cooperatively cancelled and
    the session's next submit lands on (and succeeds at) a live peer."""
    router, reps = fleet
    gate, started = threading.Event(), threading.Event()
    h = router.submit(_gated_query(gate, started), session="doomed")
    assert started.wait(20)
    owner = router.route("doomed").name
    router.kill(owner)
    gate.set()  # morsel finishes; executor sees the cancel token next
    with pytest.raises(QueryCancelled):
        h.result(60)
    assert h.state == "cancelled"
    h2 = router.submit(_agg_query(parquet_table), session="doomed")
    h2.result(60)
    assert router.route("doomed").name != owner
    assert state_sync.counters_snapshot().get("kill") == 1
    # dead replica rejects direct submits with a routable error
    dead = next(r for r in reps if r.name == owner)
    with pytest.raises(ReplicaUnavailable):
        dead.submit(_agg_query(parquet_table), session="x")


def test_drain_rehomes_sessions_and_rejects_draining(fleet, parquet_table):
    """Graceful drain: in-flight queries finish inside the grace window,
    the drained replica's sessions are released and re-homed, and a
    direct submit to it is rejected ``draining`` (which the router
    treats as re-routable)."""
    router, reps = fleet
    h = router.submit(_agg_query(parquet_table), session="moving")
    h.result(60)
    owner = router.route("moving").name
    stats = router.drain(owner)
    assert stats["finished_in_time"] is True
    rep = next(r for r in reps if r.name == owner)
    assert rep.scheduler.draining
    direct = rep.scheduler.submit(_agg_query(parquet_table), session="x")
    with pytest.raises(AdmissionRejected) as ei:
        direct.result(10)
    assert ei.value.kind == "draining"
    # the session re-routes through the front door and still works
    h2 = router.submit(_agg_query(parquet_table), session="moving")
    h2.result(60)
    assert router.route("moving").name != owner
    assert rep.scheduler.counters_snapshot().get("sessions_released", 0) >= 1


# ------------------------------------------------------------ state sync

def _snap(origin, gen, calib=None, admission=None):
    return {"origin": origin, "gen": gen, "calib": calib or {},
            "admission": admission or {}}


def test_gossip_merge_idempotent_and_commutative():
    """Re-delivery is a no-op; ingest order cannot change the merged
    view; a replica's own slot never regresses from an echoed snapshot."""
    a1 = _snap("a", 1, {"DEV_AGG_BPS": {"value": 1e9, "samples": 10}})
    a2 = _snap("a", 2, {"DEV_AGG_BPS": {"value": 2e9, "samples": 30}})
    b1 = _snap("b", 1, {"DEV_AGG_BPS": {"value": 6e9, "samples": 10}})
    x, y = state_sync.StateStore("x"), state_sync.StateStore("y")
    for s in (a1, a2, b1):
        assert x.ingest(dict(s))
    # reversed delivery order, with the stale a1 arriving last
    assert y.ingest(dict(b1)) and y.ingest(dict(a2))
    assert not y.ingest(dict(a1))           # stale gen: rejected
    assert not x.ingest(dict(a2))           # re-delivery: idempotent
    assert x.merged_calibration("DEV_AGG_BPS") == \
        y.merged_calibration("DEV_AGG_BPS")
    v, n = x.merged_calibration("DEV_AGG_BPS")
    assert n == 40 and v == pytest.approx(3e9)  # 30/40·2e9 + 10/40·6e9
    # echo of x's own (empty) slot must not apply
    x.publish_local({}, {})
    assert not x.ingest(_snap("x", 99))
    assert x.generation("x") == 1


def test_sample_weighted_admission_merge():
    x = state_sync.StateStore("x")
    x.ingest(_snap("a", 1, admission={
        "k": {"bytes": 4e6, "wall_us": 900.0, "samples": 3}}))
    x.ingest(_snap("b", 1, admission={"k": (8e6, 1300.0, 1.0)}))
    b, w, n = x.merged_admission("k")
    assert n == 4 and b == pytest.approx(5e6) and w == pytest.approx(1000.0)
    assert x.merged_admission("unknown") is None


def test_cold_replica_inherits_calibration(monkeypatch):
    """Satellite: a cold replica's ``calibration.const`` prices from the
    gossiped fleet view (≠ the hard-coded default) before it has any
    local observations."""
    monkeypatch.setenv("DAFT_TPU_CALIBRATION", "1")
    monkeypatch.setenv("DAFT_TPU_CALIBRATION_MIN_SAMPLES", "3")
    store = state_sync.StateStore("cold")
    store.ingest(_snap("warm", 5, {
        "DEV_AGG_BPS": {"value": 1.5e9, "samples": 40}}))
    state_sync.install(store)
    assert cal.const("DEV_AGG_BPS", 4e9) == pytest.approx(1.5e9)
    assert state_sync.counters_snapshot().get("calibration_fleet_reads") == 1
    # below the fleet's own sample floor the default still wins
    store2 = state_sync.StateStore("cold2")
    store2.ingest(_snap("warm", 6, {
        "DEV_SORT_ROWS_PER_S": {"value": 9e6, "samples": 2}}))
    state_sync.install(store2)
    assert cal.const("DEV_SORT_ROWS_PER_S", 50e6) == 50e6


def test_cold_replica_admission_seeded_from_fleet(monkeypatch,
                                                 parquet_table):
    """A cold scheduler with a blind cost model prices a repeat workload
    from gossiped admission history (counter ``est_seeded_fleet``), not
    the flat 64 MiB default."""
    from daft_tpu.logical import stats as lstats
    from daft_tpu.serving import scheduler as sched_mod
    monkeypatch.setattr(lstats, "estimate",
                        lambda plan: lstats.Stats(None, None))
    warm_store = state_sync.StateStore("warm")
    warm = QueryScheduler(concurrency=1, result_cache_bytes=0,
                          fleet_state=warm_store, name="warm")
    cold_store = state_sync.StateStore("cold")
    cold = QueryScheduler(concurrency=1, result_cache_bytes=0,
                          fleet_state=cold_store, name="cold")
    try:
        h1 = warm.submit(_agg_query(parquet_table))
        h1.result(60)
        assert h1._fp_hist_key is not None
        warm_store.publish_from_engine(warm)
        assert cold_store.ingest_all(warm_store.snapshot_all()) == 1
        h2 = cold.submit(_agg_query(parquet_table))
        h2.result(60)
        assert cold.counters_snapshot().get("est_seeded_fleet") == 1
        est = h2.stats.serving["admitted_bytes"]
        assert 0 < est < sched_mod._DEFAULT_EST_BYTES
    finally:
        warm.shutdown()
        cold.shutdown()


# ------------------------------------------------------------ cache tier

def test_fleet_result_cache_hit_across_replicas(fleet, parquet_table):
    """A repeat query landing on a DIFFERENT replica than its first run
    hits the shared tier (``result_cache: fleet_hit``) and promotes the
    result into the landing replica's local cache."""
    router, reps = fleet
    expected = _agg_query(parquet_table).to_pydict()
    h1 = router.submit(_agg_query(parquet_table), session="first")
    h1.result(60)
    first = router.route("first").name
    other = next(f"o-{i}" for i in range(200)
                 if router.route(f"o-{i}").name != first)
    h2 = router.submit(_agg_query(parquet_table), session=other)
    assert h2.result(60).to_recordbatch().to_pydict() == expected
    assert h2.stats.serving["result_cache"] == "fleet_hit"
    landing = next(r for r in reps
                   if r.name == router.route(other).name)
    assert landing.scheduler.counters_snapshot() \
        .get("result_cache_fleet_hits") == 1
    # promoted: the SAME replica's next repeat is a plain local hit
    h3 = router.submit(_agg_query(parquet_table), session=other)
    h3.result(60)
    assert h3.stats.serving["result_cache"] == "hit"


def test_sidecar_cache_tier_roundtrip():
    """Arrow-IPC result round-trip through a live sidecar store; misses
    and hits count; a dead sidecar degrades to a miss, never raises."""
    from daft_tpu.logical.fingerprint import PlanFingerprint
    from daft_tpu.micropartition import MicroPartition
    from daft_tpu.runners.runner import PartitionSet
    from daft_tpu.schema import Schema
    t = pa.table({"g": [0, 1, 2], "s": [10.0, 11.0, 12.0]})
    ps = PartitionSet([MicroPartition.from_arrow_table(t)],
                      Schema.from_arrow(t.schema))
    fp = PlanFingerprint("deadbeef", ("p",), ("src",), "deadbeef")
    sc = cache_tier.CacheSidecar(budget_bytes=8 << 20)
    addr = sc.start()
    try:
        tier = cache_tier.SidecarCacheTier(addr)
        assert tier.get_result(fp) is None          # cold: miss
        tier.put_result(fp, ps)
        got = tier.get_result(fp)
        assert got is not None
        assert got.to_recordbatch().to_pydict() == \
            ps.to_recordbatch().to_pydict()
        assert tier.get_plan(fp) is None            # plans never cross
        c = state_sync.counters_snapshot()
        assert c.get("cache_tier_misses") == 1
        assert c.get("cache_tier_puts") == 1
        assert c.get("cache_tier_hits") == 1
    finally:
        sc.stop()
    dead = cache_tier.SidecarCacheTier(addr, timeout_s=0.2)
    assert dead.get_result(fp) is None
    dead.put_result(fp, ps)  # must not raise
    assert state_sync.counters_snapshot().get("cache_tier_errors", 0) >= 1


# ------------------------------------------- fingerprint calibration token

def test_fingerprint_calibration_token_invalidates_plans(monkeypatch,
                                                         parquet_table):
    """Satellite regression: a calibrated constant crossing the sample
    floor changes the plan-cache key (stale pre-calibration plans die)
    but NOT the admission-history key (history survives the flip and
    matches across differently-calibrated replicas)."""
    from daft_tpu.context import get_context
    cfg = get_context().execution_config
    plan = _agg_query(parquet_table)._builder.plan
    f_off = fingerprint(plan, cfg)
    assert f_off.structure == f_off.history_structure  # common path
    monkeypatch.setenv("DAFT_TPU_CALIBRATION", "1")
    monkeypatch.setenv("DAFT_TPU_CALIBRATION_MIN_SAMPLES", "2")
    f_cold = fingerprint(plan, cfg)
    assert f_cold.key == f_off.key        # nothing active yet: no churn
    cal.observe("DEV_AGG_BPS", 1e9)
    cal.observe("DEV_AGG_BPS", 1e9)       # crosses the floor
    assert cal.plan_token() != ""
    f_warm = fingerprint(plan, cfg)
    assert f_warm.structure != f_off.structure
    assert f_warm.key != f_off.key
    assert f_warm.history_structure == f_off.history_structure
    # EWMA nudges within quantization don't churn the token
    cal.observe("DEV_AGG_BPS", 1.001e9)
    assert fingerprint(plan, cfg).structure == f_warm.structure
    # fleet-inherited constants flip the token the same way local ones do
    cal.reset_for_tests()
    store = state_sync.StateStore("me")
    store.ingest(_snap("peer", 3, {
        "DEV_AGG_BPS": {"value": 1e9, "samples": 40}}))
    state_sync.install(store)
    f_fleet = fingerprint(plan, cfg)
    assert f_fleet.structure == f_warm.structure  # same quantized value
    assert f_fleet.history_structure == f_off.history_structure


def test_fingerprint_remote_source_version_token(parquet_table):
    """Satellite regression: a remote (http) source is cacheable iff the
    store exposes a version signal. The ETag rides in the fingerprint's
    source token (so an object change busts the key without changing the
    structure); a store with no ETag/Last-Modified leaves the plan
    uncacheable (fail-safe)."""
    import glob as globmod
    import http.server

    from daft_tpu.context import get_context

    pq = sorted(globmod.glob(parquet_table))[0]
    with open(pq, "rb") as f:
        data = f.read()
    etag = {"value": '"v1"', "send": True}

    class Handler(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def _serve(self, head_only):
            body, code = data, 200
            rng = self.headers.get("Range")
            if rng and rng.startswith("bytes="):
                a, _, b = rng[len("bytes="):].partition("-")
                start = int(a or 0)
                end = min(int(b) + 1 if b else len(data), len(data))
                body, code = data[start:end], 206
            self.send_response(code)
            self.send_header("Content-Length", str(len(body)))
            if etag["send"]:
                self.send_header("ETag", etag["value"])
            self.end_headers()
            if not head_only:
                self.wfile.write(body)

        def do_GET(self):
            self._serve(False)

        def do_HEAD(self):
            self._serve(True)

    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    srv.daemon_threads = True
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{srv.server_address[1]}/t.parquet"
    cfg = get_context().execution_config
    try:
        f1 = fingerprint(dt.read_parquet(url)._builder.plan, cfg)
        assert f1 is not None
        tokens = [v for (_op, vers) in f1.sources for v in vers]
        assert tokens == [(url, "http", len(data), '"v1"')]
        # stable across identical plan builds
        f2 = fingerprint(dt.read_parquet(url)._builder.plan, cfg)
        assert f2.key == f1.key
        # object changed server-side (new ETag): key busts, shape doesn't
        etag["value"] = '"v2"'
        f3 = fingerprint(dt.read_parquet(url)._builder.plan, cfg)
        assert f3.key != f1.key
        assert f3.structure == f1.structure
        # the admission-history key ignores version tokens entirely
        from daft_tpu.serving.scheduler import _history_key_from_fp
        assert _history_key_from_fp(f3) == _history_key_from_fp(f1)
        # no version signal at all → uncacheable, caches bypassed
        etag["send"] = False
        assert fingerprint(dt.read_parquet(url)._builder.plan, cfg) is None
    finally:
        srv.shutdown()
        srv.server_close()


# ------------------------------------------------------------- aggregate

def test_gauges_scale_signal_and_gossip_round(fleet, parquet_table):
    router, reps = fleet
    h = router.submit(_agg_query(parquet_table), session="g")
    h.result(60)
    g = router.gauges()
    agg = g["aggregate"]
    assert agg["replicas"] == 3 and agg["replicas_admitting"] == 3
    assert agg["concurrency"] == sum(
        r.gauges()["concurrency"] for r in reps)
    sig = router.scale_signal()
    assert 1 <= sig["desired_replicas"] <= 4
    # pull-merge-push: every replica ends up holding every origin
    router.gossip_round()
    for r in reps:
        assert set(r.store.origins()) == {"r0", "r1", "r2"}
    from daft_tpu.analysis import lock_sanitizer
    if lock_sanitizer.is_enabled():
        assert int(lock_sanitizer.counters_snapshot()
                   .get("graph_cycles", 0)) == 0


def test_scheduler_release_session_cancels_queued():
    """Router handoff path: releasing a session finishes its queued
    handles as cancelled and drops the session queue."""
    sched = QueryScheduler(concurrency=1, queue_timeout_s=60.0)
    try:
        gate, started = threading.Event(), threading.Event()
        blocker = sched.submit(_gated_query(gate, started), session="keep")
        assert started.wait(20)
        queued = sched.submit(mkdf({"a": [1]}).select(col("a")),
                              session="gone")
        assert sched.release_session("gone") is True
        with pytest.raises(QueryCancelled):
            queued.result(10)
        assert queued.state == "cancelled"
        assert sched.release_session("gone") is False  # already gone
        gate.set()
        blocker.result(60)
        assert sched.admission.outstanding == 0
    finally:
        sched.shutdown()
