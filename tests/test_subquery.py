"""SQL subqueries: scalar / IN / EXISTS parsing + unnest-to-join rewrites
(VERDICT r2 item 4 done-criterion: TPC-H Q4/Q17/Q20/Q22 run as SQL text and
match the DataFrame results).

Reference seam: ``Expr::Subquery/InSubquery/Exists``
(``src/daft-dsl/src/expr/mod.rs:213-292``) +
``optimization/rules/unnest_subquery.rs``; here
``daft_tpu/logical/subquery.py`` + the SQL planner's correlated scopes."""

import pytest

import daft_tpu as dt


@pytest.fixture(scope="module")
def shop():
    """Handcrafted data where every subquery shape has non-empty output."""
    cust = dt.from_pydict({
        "c_id": [1, 2, 3, 4],
        "c_name": ["ann", "bob", "cat", "dan"],
        "c_bal": [100.0, 5.0, 60.0, 40.0],
    })
    orders = dt.from_pydict({
        "o_id": [10, 11, 12, 13, 14],
        "o_cust": [1, 1, 2, 3, 3],
        "o_total": [20.0, 30.0, 7.0, 55.0, 5.0],
    })
    return {"cust": cust, "orders": orders}


def test_exists_correlated(shop):
    out = dt.sql(
        "SELECT c_name FROM cust WHERE EXISTS "
        "(SELECT * FROM orders WHERE o_cust = c_id) ORDER BY c_name",
        **shop).to_pydict()
    assert out == {"c_name": ["ann", "bob", "cat"]}


def test_not_exists_correlated(shop):
    out = dt.sql(
        "SELECT c_name FROM cust WHERE NOT EXISTS "
        "(SELECT * FROM orders WHERE o_cust = c_id)",
        **shop).to_pydict()
    assert out == {"c_name": ["dan"]}


def test_exists_with_inner_filter(shop):
    out = dt.sql(
        "SELECT c_name FROM cust WHERE EXISTS "
        "(SELECT * FROM orders WHERE o_cust = c_id AND o_total > 25) "
        "ORDER BY c_name", **shop).to_pydict()
    assert out == {"c_name": ["ann", "cat"]}


def test_in_subquery_uncorrelated(shop):
    out = dt.sql(
        "SELECT c_name FROM cust WHERE c_id IN "
        "(SELECT o_cust FROM orders WHERE o_total > 25) ORDER BY c_name",
        **shop).to_pydict()
    assert out == {"c_name": ["ann", "cat"]}


def test_not_in_subquery(shop):
    out = dt.sql(
        "SELECT c_name FROM cust WHERE c_id NOT IN "
        "(SELECT o_cust FROM orders)", **shop).to_pydict()
    assert out == {"c_name": ["dan"]}


def test_scalar_uncorrelated(shop):
    out = dt.sql(
        "SELECT c_name FROM cust WHERE c_bal > "
        "(SELECT avg(c_bal) FROM cust) ORDER BY c_name",
        **shop).to_pydict()
    assert out == {"c_name": ["ann", "cat"]}  # avg = 51.25


def test_scalar_correlated_groupby_join(shop):
    # customers whose balance exceeds twice their average order value
    out = dt.sql(
        "SELECT c_name FROM cust WHERE c_bal > "
        "(SELECT 2 * avg(o_total) FROM orders WHERE o_cust = c_id) "
        "ORDER BY c_name", **shop).to_pydict()
    # ann: 100 > 2*25 ✓; bob: 5 > 2*7 ✗; cat: 60 > 2*30 ✗;
    # dan: no orders → NULL → comparison false (SQL semantics)
    assert out == {"c_name": ["ann"]}


def test_scalar_in_arithmetic(shop):
    out = dt.sql(
        "SELECT c_name FROM cust WHERE c_bal / 2 > "
        "(SELECT min(c_bal) FROM cust) ORDER BY c_name", **shop).to_pydict()
    # min = 5: ann 50 ✓, bob 2.5 ✗, cat 30 ✓, dan 20 ✓
    assert out == {"c_name": ["ann", "cat", "dan"]}


def test_nested_in_with_correlated_scalar(shop):
    # Q20 shape: IN-subquery whose WHERE holds a correlated scalar subquery
    out = dt.sql(
        "SELECT c_name FROM cust WHERE c_id IN ("
        "  SELECT o_cust FROM orders WHERE o_total > "
        "    (SELECT avg(o_total) FROM orders)"
        ") ORDER BY c_name", **shop).to_pydict()
    # avg(o_total) = 23.4; orders above: 30 (ann), 55 (cat)
    assert out == {"c_name": ["ann", "cat"]}


def test_subquery_in_select_list(shop):
    out = dt.sql("SELECT c_name, (SELECT max(o_total) FROM orders) AS m "
                 "FROM cust ORDER BY c_name", **shop).to_pydict()
    assert out["m"] == [55.0] * 4
    assert out["c_name"] == ["ann", "bob", "cat", "dan"]


def test_correlated_subquery_in_select_list(shop):
    out = dt.sql(
        "SELECT c_name, (SELECT SUM(o_total) FROM orders "
        "WHERE o_cust = c_id) AS t FROM cust ORDER BY c_name",
        **shop).to_pydict()
    assert out["t"] == [50.0, 7.0, 60.0, None]


def test_subquery_in_select_list_of_aggregate(shop):
    out = dt.sql(
        "SELECT COUNT(*) AS n, (SELECT max(o_total) FROM orders) AS m "
        "FROM cust", **shop).to_pydict()
    assert out == {"n": [4], "m": [55.0]}


def test_having_subquery(shop):
    out = dt.sql(
        "SELECT o_cust, SUM(o_total) AS s FROM orders GROUP BY o_cust "
        "HAVING SUM(o_total) > (SELECT AVG(o_total) FROM orders) "
        "ORDER BY o_cust", **shop).to_pydict()
    assert out == {"o_cust": [1, 3], "s": [50.0, 60.0]}


def test_exists_with_nonequality_residual(shop):
    # another order by the SAME customer with a different total
    out = dt.sql(
        "SELECT o_id FROM orders o1 WHERE EXISTS ("
        "SELECT 1 FROM orders o2 WHERE o2.o_cust = o1.o_cust "
        "AND o2.o_total <> o1.o_total) ORDER BY o_id",
        **shop).to_pydict()
    assert out["o_id"] == [10, 11, 13, 14]


def test_exists_nested_in_or_mark_join(shop):
    """EXISTS inside a disjunction lowers to a mark join (TPC-DS Q10/Q35
    shape): customers with a high balance OR at least one order."""
    out = dt.sql(
        "SELECT c_name FROM cust WHERE c_bal > 50 OR EXISTS "
        "(SELECT * FROM orders WHERE o_cust = c_id) ORDER BY c_name",
        **shop).to_pydict()
    # ann (bal+orders), bob (orders), cat (bal+orders); dan has neither
    assert out["c_name"] == ["ann", "bob", "cat"]


def test_two_exists_disjunction(shop):
    out = dt.sql(
        "SELECT c_name FROM cust WHERE EXISTS "
        "(SELECT * FROM orders WHERE o_cust = c_id AND o_total > 50) "
        "OR EXISTS (SELECT * FROM orders WHERE o_cust = c_id AND "
        "o_total < 10) ORDER BY c_name",
        **shop).to_pydict()
    # bob: 7.0 < 10; cat: 55.0 > 50 and 5.0 < 10; ann: neither branch
    assert out["c_name"] == ["bob", "cat"]


def test_in_subquery_nested_in_or(shop):
    out = dt.sql(
        "SELECT c_name FROM cust WHERE c_bal < 10 OR c_id IN "
        "(SELECT o_cust FROM orders WHERE o_total > 50) ORDER BY c_name",
        **shop).to_pydict()
    assert out["c_name"] == ["bob", "cat"]


# ----------------------------- three-valued logic for IN marks (r4 advice)

@pytest.fixture(scope="module")
def nullish():
    t = dt.from_pydict({
        "k": [1, 2, None, 4],
        "p": [False, False, False, True],
        "name": ["one", "two", "nul", "four"],
    })
    s = dt.from_pydict({"v": [1, 3], "vn": [1, None],
                        "g": [1, 1]})
    return {"t": t, "s": s}


def test_negated_disjunction_in_mark_null_lhs(nullish):
    """NOT (p OR k IN (S)): a NULL k yields NULL (not FALSE) for the IN,
    so the whole predicate is NULL and the row is dropped — fill_null(False)
    used to keep it (r4 advisor repro)."""
    out = dt.sql(
        "SELECT name FROM t WHERE NOT (p OR k IN (SELECT v FROM s))",
        **nullish).to_pydict()
    # k=1 matches (TRUE→drop), k=2 no match (keep), k=NULL → NULL (drop),
    # k=4 has p TRUE (drop)
    assert out["name"] == ["two"]


def test_negated_disjunction_in_mark_null_in_set(nullish):
    """Set contains NULL: any non-matching k gets NULL, not FALSE."""
    out = dt.sql(
        "SELECT name FROM t WHERE NOT (p OR k IN (SELECT vn FROM s))",
        **nullish).to_pydict()
    # k=1 matches (drop); k=2/NULL → NULL (drop); k=4 p TRUE (drop)
    assert out["name"] == []


def test_negated_disjunction_in_mark_empty_set(nullish):
    """Empty set: k IN () is FALSE for every k incl. NULL → rows kept."""
    out = dt.sql(
        "SELECT name FROM t WHERE NOT (p OR k IN "
        "(SELECT v FROM s WHERE v > 100)) ORDER BY name",
        **nullish).to_pydict()
    assert out["name"] == ["nul", "one", "two"]


def test_positive_disjunction_in_mark_null_unchanged(nullish):
    """Under a plain WHERE (no negation) NULL and FALSE filter alike —
    the null-aware mark must not change the positive-path results."""
    out = dt.sql(
        "SELECT name FROM t WHERE p OR k IN (SELECT vn FROM s) "
        "ORDER BY name", **nullish).to_pydict()
    assert out["name"] == ["four", "one"]


# ---------------------------------------------------------- TPC-H parity

@pytest.fixture(scope="module")
def tpch(tmp_path_factory):
    from benchmarking.tpch.datagen import generate_tpch
    root = tmp_path_factory.mktemp("tpch_subq")
    generate_tpch(str(root), 0.05, 2)

    def get_df(name):
        return dt.read_parquet(f"{root}/{name}/*.parquet")
    return get_df


@pytest.mark.parametrize("qname", ["q4", "q17", "q20", "q22"])
def test_tpch_subquery_sql_matches_dataframe(tpch, qname):
    from benchmarking.tpch import queries as Q
    from benchmarking.tpch.sql_queries import SUBQUERY_QUERIES
    tables = {t: tpch(t) for t in ("orders", "lineitem", "part", "partsupp",
                                   "supplier", "customer", "nation")}
    got = dt.sql(SUBQUERY_QUERIES[qname], **tables).to_pydict()
    want = getattr(Q, qname)(tpch).to_pydict()
    assert set(got) == set(want)
    for k in want:
        gv, wv = got[k], want[k]
        assert len(gv) == len(wv), (k, len(gv), len(wv))
        for a, b in zip(gv, wv):
            if isinstance(b, float):
                assert a == pytest.approx(b, rel=1e-9)
            else:
                assert a == b
