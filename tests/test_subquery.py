"""SQL subqueries: scalar / IN / EXISTS parsing + unnest-to-join rewrites
(VERDICT r2 item 4 done-criterion: TPC-H Q4/Q17/Q20/Q22 run as SQL text and
match the DataFrame results).

Reference seam: ``Expr::Subquery/InSubquery/Exists``
(``src/daft-dsl/src/expr/mod.rs:213-292``) +
``optimization/rules/unnest_subquery.rs``; here
``daft_tpu/logical/subquery.py`` + the SQL planner's correlated scopes."""

import pytest

import daft_tpu as dt


@pytest.fixture(scope="module")
def shop():
    """Handcrafted data where every subquery shape has non-empty output."""
    cust = dt.from_pydict({
        "c_id": [1, 2, 3, 4],
        "c_name": ["ann", "bob", "cat", "dan"],
        "c_bal": [100.0, 5.0, 60.0, 40.0],
    })
    orders = dt.from_pydict({
        "o_id": [10, 11, 12, 13, 14],
        "o_cust": [1, 1, 2, 3, 3],
        "o_total": [20.0, 30.0, 7.0, 55.0, 5.0],
    })
    return {"cust": cust, "orders": orders}


def test_exists_correlated(shop):
    out = dt.sql(
        "SELECT c_name FROM cust WHERE EXISTS "
        "(SELECT * FROM orders WHERE o_cust = c_id) ORDER BY c_name",
        **shop).to_pydict()
    assert out == {"c_name": ["ann", "bob", "cat"]}


def test_not_exists_correlated(shop):
    out = dt.sql(
        "SELECT c_name FROM cust WHERE NOT EXISTS "
        "(SELECT * FROM orders WHERE o_cust = c_id)",
        **shop).to_pydict()
    assert out == {"c_name": ["dan"]}


def test_exists_with_inner_filter(shop):
    out = dt.sql(
        "SELECT c_name FROM cust WHERE EXISTS "
        "(SELECT * FROM orders WHERE o_cust = c_id AND o_total > 25) "
        "ORDER BY c_name", **shop).to_pydict()
    assert out == {"c_name": ["ann", "cat"]}


def test_in_subquery_uncorrelated(shop):
    out = dt.sql(
        "SELECT c_name FROM cust WHERE c_id IN "
        "(SELECT o_cust FROM orders WHERE o_total > 25) ORDER BY c_name",
        **shop).to_pydict()
    assert out == {"c_name": ["ann", "cat"]}


def test_not_in_subquery(shop):
    out = dt.sql(
        "SELECT c_name FROM cust WHERE c_id NOT IN "
        "(SELECT o_cust FROM orders)", **shop).to_pydict()
    assert out == {"c_name": ["dan"]}


def test_scalar_uncorrelated(shop):
    out = dt.sql(
        "SELECT c_name FROM cust WHERE c_bal > "
        "(SELECT avg(c_bal) FROM cust) ORDER BY c_name",
        **shop).to_pydict()
    assert out == {"c_name": ["ann", "cat"]}  # avg = 51.25


def test_scalar_correlated_groupby_join(shop):
    # customers whose balance exceeds twice their average order value
    out = dt.sql(
        "SELECT c_name FROM cust WHERE c_bal > "
        "(SELECT 2 * avg(o_total) FROM orders WHERE o_cust = c_id) "
        "ORDER BY c_name", **shop).to_pydict()
    # ann: 100 > 2*25 ✓; bob: 5 > 2*7 ✗; cat: 60 > 2*30 ✗;
    # dan: no orders → NULL → comparison false (SQL semantics)
    assert out == {"c_name": ["ann"]}


def test_scalar_in_arithmetic(shop):
    out = dt.sql(
        "SELECT c_name FROM cust WHERE c_bal / 2 > "
        "(SELECT min(c_bal) FROM cust) ORDER BY c_name", **shop).to_pydict()
    # min = 5: ann 50 ✓, bob 2.5 ✗, cat 30 ✓, dan 20 ✓
    assert out == {"c_name": ["ann", "cat", "dan"]}


def test_nested_in_with_correlated_scalar(shop):
    # Q20 shape: IN-subquery whose WHERE holds a correlated scalar subquery
    out = dt.sql(
        "SELECT c_name FROM cust WHERE c_id IN ("
        "  SELECT o_cust FROM orders WHERE o_total > "
        "    (SELECT avg(o_total) FROM orders)"
        ") ORDER BY c_name", **shop).to_pydict()
    # avg(o_total) = 23.4; orders above: 30 (ann), 55 (cat)
    assert out == {"c_name": ["ann", "cat"]}


def test_subquery_in_select_list(shop):
    out = dt.sql("SELECT c_name, (SELECT max(o_total) FROM orders) AS m "
                 "FROM cust ORDER BY c_name", **shop).to_pydict()
    assert out["m"] == [55.0] * 4
    assert out["c_name"] == ["ann", "bob", "cat", "dan"]


def test_correlated_subquery_in_select_list(shop):
    out = dt.sql(
        "SELECT c_name, (SELECT SUM(o_total) FROM orders "
        "WHERE o_cust = c_id) AS t FROM cust ORDER BY c_name",
        **shop).to_pydict()
    assert out["t"] == [50.0, 7.0, 60.0, None]


def test_subquery_in_select_list_of_aggregate(shop):
    out = dt.sql(
        "SELECT COUNT(*) AS n, (SELECT max(o_total) FROM orders) AS m "
        "FROM cust", **shop).to_pydict()
    assert out == {"n": [4], "m": [55.0]}


def test_having_subquery(shop):
    out = dt.sql(
        "SELECT o_cust, SUM(o_total) AS s FROM orders GROUP BY o_cust "
        "HAVING SUM(o_total) > (SELECT AVG(o_total) FROM orders) "
        "ORDER BY o_cust", **shop).to_pydict()
    assert out == {"o_cust": [1, 3], "s": [50.0, 60.0]}


def test_exists_with_nonequality_residual(shop):
    # another order by the SAME customer with a different total
    out = dt.sql(
        "SELECT o_id FROM orders o1 WHERE EXISTS ("
        "SELECT 1 FROM orders o2 WHERE o2.o_cust = o1.o_cust "
        "AND o2.o_total <> o1.o_total) ORDER BY o_id",
        **shop).to_pydict()
    assert out["o_id"] == [10, 11, 13, 14]


def test_exists_nested_in_or_mark_join(shop):
    """EXISTS inside a disjunction lowers to a mark join (TPC-DS Q10/Q35
    shape): customers with a high balance OR at least one order."""
    out = dt.sql(
        "SELECT c_name FROM cust WHERE c_bal > 50 OR EXISTS "
        "(SELECT * FROM orders WHERE o_cust = c_id) ORDER BY c_name",
        **shop).to_pydict()
    # ann (bal+orders), bob (orders), cat (bal+orders); dan has neither
    assert out["c_name"] == ["ann", "bob", "cat"]


def test_two_exists_disjunction(shop):
    out = dt.sql(
        "SELECT c_name FROM cust WHERE EXISTS "
        "(SELECT * FROM orders WHERE o_cust = c_id AND o_total > 50) "
        "OR EXISTS (SELECT * FROM orders WHERE o_cust = c_id AND "
        "o_total < 10) ORDER BY c_name",
        **shop).to_pydict()
    # bob: 7.0 < 10; cat: 55.0 > 50 and 5.0 < 10; ann: neither branch
    assert out["c_name"] == ["bob", "cat"]


def test_in_subquery_nested_in_or(shop):
    out = dt.sql(
        "SELECT c_name FROM cust WHERE c_bal < 10 OR c_id IN "
        "(SELECT o_cust FROM orders WHERE o_total > 50) ORDER BY c_name",
        **shop).to_pydict()
    assert out["c_name"] == ["bob", "cat"]


# ----------------------------- three-valued logic for IN marks (r4 advice)

@pytest.fixture(scope="module")
def nullish():
    t = dt.from_pydict({
        "k": [1, 2, None, 4],
        "p": [False, False, False, True],
        "name": ["one", "two", "nul", "four"],
    })
    s = dt.from_pydict({"v": [1, 3], "vn": [1, None],
                        "g": [1, 1]})
    return {"t": t, "s": s}


def test_negated_disjunction_in_mark_null_lhs(nullish):
    """NOT (p OR k IN (S)): a NULL k yields NULL (not FALSE) for the IN,
    so the whole predicate is NULL and the row is dropped — fill_null(False)
    used to keep it (r4 advisor repro)."""
    out = dt.sql(
        "SELECT name FROM t WHERE NOT (p OR k IN (SELECT v FROM s))",
        **nullish).to_pydict()
    # k=1 matches (TRUE→drop), k=2 no match (keep), k=NULL → NULL (drop),
    # k=4 has p TRUE (drop)
    assert out["name"] == ["two"]


def test_negated_disjunction_in_mark_null_in_set(nullish):
    """Set contains NULL: any non-matching k gets NULL, not FALSE."""
    out = dt.sql(
        "SELECT name FROM t WHERE NOT (p OR k IN (SELECT vn FROM s))",
        **nullish).to_pydict()
    # k=1 matches (drop); k=2/NULL → NULL (drop); k=4 p TRUE (drop)
    assert out["name"] == []


def test_negated_disjunction_in_mark_empty_set(nullish):
    """Empty set: k IN () is FALSE for every k incl. NULL → rows kept."""
    out = dt.sql(
        "SELECT name FROM t WHERE NOT (p OR k IN "
        "(SELECT v FROM s WHERE v > 100)) ORDER BY name",
        **nullish).to_pydict()
    assert out["name"] == ["nul", "one", "two"]


def test_positive_disjunction_in_mark_null_unchanged(nullish):
    """Under a plain WHERE (no negation) NULL and FALSE filter alike —
    the null-aware mark must not change the positive-path results."""
    out = dt.sql(
        "SELECT name FROM t WHERE p OR k IN (SELECT vn FROM s) "
        "ORDER BY name", **nullish).to_pydict()
    assert out["name"] == ["four", "one"]


# ------------------------------ correlated agg subqueries with GROUP BY

def test_correlated_agg_subquery_with_group_by_scalar(shop):
    """Scalar comparison against a correlated aggregating subquery whose
    GROUP BY equals the correlation key (the common shape): one row per
    outer row, no duplication (r4 fence at sql/planner.py:581)."""
    out = dt.sql(
        "SELECT c_name FROM cust WHERE c_bal > "
        "(SELECT sum(o_total) FROM orders WHERE o_cust = c_id "
        " GROUP BY o_cust) ORDER BY c_name", **shop).to_pydict()
    # ann: 100 > 50; bob: 5 > 7 no; cat: 60 <= 60 no; dan: no orders → NULL
    assert out == {"c_name": ["ann"]}


def test_correlated_agg_subquery_group_by_finer_raises(shop):
    """GROUP BY finer than the correlation can yield several rows per
    outer row — SQL's scalar-cardinality error, raised at runtime rather
    than silently duplicating outer rows."""
    with pytest.raises(Exception, match="more than one row"):
        dt.sql(
            "SELECT c_name FROM cust WHERE c_bal > "
            "(SELECT sum(o_total) FROM orders WHERE o_cust = c_id "
            " GROUP BY o_id)", **shop).to_pydict()


def test_correlated_agg_subquery_group_by_in(shop):
    """IN against a correlated aggregating subquery with GROUP BY: each
    (correlation, group) cell contributes a candidate value."""
    out = dt.sql(
        "SELECT c_name FROM cust WHERE c_bal IN "
        "(SELECT sum(o_total) * 2 FROM orders WHERE o_cust = c_id "
        " GROUP BY o_id) ORDER BY c_name", **shop).to_pydict()
    # per-order doubled sums: ann {40,60}, bob {14}, cat {110,10}, dan {}
    # balances: ann 100, bob 5, cat 60, dan 40 → only ann's 100? no —
    # ann: 100 not in {40,60}; bob: 5 not in {14}; cat: 60 not in {110,10}
    assert out == {"c_name": []}


def test_correlated_agg_subquery_group_by_in_match(shop):
    out = dt.sql(
        "SELECT c_name FROM cust WHERE c_bal IN "
        "(SELECT sum(o_total) FROM orders WHERE o_cust = c_id "
        " GROUP BY o_cust) ORDER BY c_name", **shop).to_pydict()
    # totals: ann 50, bob 7, cat 60, dan none → cat's 60 matches c_bal 60
    assert out == {"c_name": ["cat"]}


# ----------------------------------------- theta residuals on outer joins

@pytest.fixture(scope="module")
def theta():
    t1 = dt.from_pydict({"a": [1, 2, 3, 4], "x": [10, 20, 30, 40]})
    t2 = dt.from_pydict({"b": [1, 2, 3, 5], "y": [5, 25, 35, 55]})
    return {"t1": t1, "t2": t2}


def test_left_join_residual_on_preserved_side(theta):
    """LEFT JOIN ... ON a = b AND x > 15: the residual touches the
    PRESERVED side, so it filters the match, not the rows — rows with
    x <= 15 keep a NULL right side (r4 fence at sql/planner.py:1175)."""
    out = dt.sql(
        "SELECT a, x, y FROM t1 LEFT JOIN t2 ON a = b AND x > 15 "
        "ORDER BY a", **theta).to_pydict()
    assert out["a"] == [1, 2, 3, 4]
    assert out["y"] == [None, 25, 35, None]  # a=1 fails x>15, a=4 no match


def test_left_join_residual_both_sides(theta):
    out = dt.sql(
        "SELECT a, x, y FROM t1 LEFT JOIN t2 ON a = b AND x > y "
        "ORDER BY a", **theta).to_pydict()
    # a=1: 10 > 5 match; a=2: 20 > 25 no; a=3: 30 > 35 no; a=4: no b
    assert out["a"] == [1, 2, 3, 4]
    assert out["y"] == [5, None, None, None]


def test_right_join_residual_on_preserved_side(theta):
    out = dt.sql(
        "SELECT a, x, y FROM t1 RIGHT JOIN t2 ON a = b AND y > 20 "
        "ORDER BY y", **theta).to_pydict()
    # preserved right rows: y=5 (no match, y>20 false), 25→a=2, 35→a=3,
    # 55 (no match)
    assert out["y"] == [5, 25, 35, 55]
    assert out["a"] == [None, 2, 3, None]


def test_right_join_theta_same_named_key():
    """The preserved side's key must survive with its own values — the
    merged-key scope remap would resolve it to the NULL left copy."""
    t1 = dt.from_pydict({"k": [1, 2], "v": [10, 20]})
    t2 = dt.from_pydict({"k": [1, 3], "w": [5, 30]})
    out = dt.sql(
        "SELECT t2.k AS kk, w FROM t1 RIGHT JOIN t2 "
        "ON t1.k = t2.k AND v > w ORDER BY w", t1=t1, t2=t2).to_pydict()
    # k=1: 10 > 5 matches; k=3: preserved with no match
    assert out == {"kk": [1, 3], "w": [5, 30]}


def test_correlated_agg_group_by_guard_only_referenced_keys():
    """The cardinality guard applies per OUTER row: inner keys no outer
    row references must not trip it (r5 review finding)."""
    o = dt.from_pydict({"k": [1], "name": ["only"]})
    t = dt.from_pydict({"k": [1, 2, 2], "g": [1, 1, 2],
                        "v": [7.0, 1.0, 2.0]})
    out = dt.sql(
        "SELECT name FROM o WHERE 5 < "
        "(SELECT sum(v) FROM t WHERE t.k = o.k GROUP BY t.g)",
        o=o, t=t).to_pydict()
    # k=1 has ONE (g=1) group with sum 7 > 5; k=2's two groups are never
    # referenced by an outer row and must not raise
    assert out == {"name": ["only"]}


def test_full_outer_join_keeps_both_key_sides(theta):
    """SQL ON-join semantics: a right-only row has NULL LEFT keys (the
    DataFrame tier coalesces outer keys like the reference — SQL must
    not). TPC-DS Q97's channel buckets depend on this."""
    out = dt.sql(
        "SELECT t1.a AS la, t2.b AS rb FROM t1 FULL OUTER JOIN t2 "
        "ON a = b ORDER BY rb", **theta).to_pydict()
    assert out["rb"] == [1, 2, 3, 5, None]
    assert out["la"] == [1, 2, 3, None, 4]


def test_full_outer_join_residual_both_sides(theta):
    out = dt.sql(
        "SELECT a, x, y FROM t1 FULL OUTER JOIN t2 ON a = b AND x > y "
        "ORDER BY a, y", **theta).to_pydict()
    # matches: only a=1/b=1 (10>5). Unmatched left: 2,3,4; right: 25,35,55
    rows = sorted(zip(out["a"], out["x"], out["y"]),
                  key=lambda r: (r[0] is None, r[0] or 0, r[2] or 0))
    assert (1, 10, 5) in rows
    assert sum(1 for a, _, y in rows if a is None) == 3  # right-only
    assert sum(1 for a, _, y in rows if y is None and a is not None) == 3


# ---------------------------------------------------------- TPC-H parity

@pytest.fixture(scope="module")
def tpch(tmp_path_factory):
    from benchmarking.tpch.datagen import generate_tpch
    root = tmp_path_factory.mktemp("tpch_subq")
    generate_tpch(str(root), 0.05, 2)

    def get_df(name):
        return dt.read_parquet(f"{root}/{name}/*.parquet")
    return get_df


@pytest.mark.parametrize("qname", ["q4", "q17", "q20", "q22"])
def test_tpch_subquery_sql_matches_dataframe(tpch, qname):
    from benchmarking.tpch import queries as Q
    from benchmarking.tpch.sql_queries import SUBQUERY_QUERIES
    tables = {t: tpch(t) for t in ("orders", "lineitem", "part", "partsupp",
                                   "supplier", "customer", "nation")}
    got = dt.sql(SUBQUERY_QUERIES[qname], **tables).to_pydict()
    want = getattr(Q, qname)(tpch).to_pydict()
    assert set(got) == set(want)
    for k in want:
        gv, wv = got[k], want[k]
        assert len(gv) == len(wv), (k, len(gv), len(wv))
        for a, b in zip(gv, wv):
            if isinstance(b, float):
                assert a == pytest.approx(b, rel=1e-9)
            else:
                assert a == b


def test_full_outer_using_coalesces_key(theta):
    """USING's contract is the opposite of ON's: one merged key column,
    COALESCE(l.k, r.k) — right-only rows show the right value."""
    t1 = dt.from_pydict({"k": [1, 2, 3], "x": [10, 20, 30]})
    t2 = dt.from_pydict({"k": [2, 3, 4], "y": [200, 300, 400]})
    out = dt.sql("SELECT k, x, y FROM t1 FULL OUTER JOIN t2 USING (k) "
                 "ORDER BY k", t1=t1, t2=t2).to_pydict()
    assert out["k"] == [1, 2, 3, 4]
    assert out["x"] == [10, 20, 30, None]
    assert out["y"] == [None, 200, 300, 400]
