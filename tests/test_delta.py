"""Native Delta Lake round-trip: log replay (commits + checkpoint),
time travel, overwrite semantics (reference surface:
``daft/io/_deltalake.py`` + ``DataFrame.write_deltalake``)."""

import json
import os

import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import daft_tpu
from daft_tpu import col
from daft_tpu.io.delta import (DeltaScanOperator, read_deltalake,
                               write_deltalake)


def test_write_read_roundtrip(tmp_path):
    uri = str(tmp_path / "tbl")
    df = daft_tpu.from_pydict({"k": [1, 2, 3], "v": ["a", "b", "c"]})
    res = write_deltalake(df, uri)
    assert res["version"] == 0 and res["rows_written"] == 3
    back = read_deltalake(uri).sort("k").to_pydict()
    assert back == {"k": [1, 2, 3], "v": ["a", "b", "c"]}


def test_append_and_overwrite(tmp_path):
    uri = str(tmp_path / "tbl")
    write_deltalake(daft_tpu.from_pydict({"x": [1, 2]}), uri)
    write_deltalake(daft_tpu.from_pydict({"x": [3]}), uri, mode="append")
    assert sorted(read_deltalake(uri).to_pydict()["x"]) == [1, 2, 3]
    write_deltalake(daft_tpu.from_pydict({"x": [9]}), uri, mode="overwrite")
    assert read_deltalake(uri).to_pydict()["x"] == [9]
    # time travel to v1 still sees the pre-overwrite snapshot
    assert sorted(read_deltalake(uri, version=1).to_pydict()["x"]) == \
        [1, 2, 3]


def test_query_pushdown_into_delta_scan(tmp_path):
    uri = str(tmp_path / "tbl")
    write_deltalake(daft_tpu.from_pydict(
        {"k": list(range(100)), "v": [float(i) for i in range(100)]}), uri)
    out = read_deltalake(uri).where(col("k") >= 95) \
        .groupby(daft_tpu.lit(1).alias("g")) \
        .agg(col("v").sum().alias("s")).to_pydict() \
        if hasattr(daft_tpu, "lit") else None
    got = read_deltalake(uri).where(col("k") >= 95).sort("k").to_pydict()
    assert got["k"] == [95, 96, 97, 98, 99]


def test_partitioned_table_reads_partition_values(tmp_path):
    """Hand-built partitioned Delta table (partition col absent from the
    data files, as the protocol requires)."""
    uri = tmp_path / "ptbl"
    (uri / "_delta_log").mkdir(parents=True)
    (uri / "p=1").mkdir()
    (uri / "p=2").mkdir()
    pq.write_table(pa.table({"v": [10, 11]}), str(uri / "p=1" / "a.parquet"))
    pq.write_table(pa.table({"v": [20]}), str(uri / "p=2" / "b.parquet"))
    schema_string = json.dumps({"type": "struct", "fields": [
        {"name": "v", "type": "long", "nullable": True, "metadata": {}},
        {"name": "p", "type": "integer", "nullable": True, "metadata": {}}]})
    actions = [
        json.dumps({"protocol": {"minReaderVersion": 1,
                                 "minWriterVersion": 2}}),
        json.dumps({"metaData": {"id": "t", "format": {
            "provider": "parquet", "options": {}},
            "schemaString": schema_string, "partitionColumns": ["p"],
            "configuration": {}}}),
        json.dumps({"add": {"path": "p=1/a.parquet",
                            "partitionValues": {"p": "1"}, "size": 1,
                            "modificationTime": 0, "dataChange": True}}),
        json.dumps({"add": {"path": "p=2/b.parquet",
                            "partitionValues": {"p": "2"}, "size": 1,
                            "modificationTime": 0, "dataChange": True}}),
    ]
    with open(uri / "_delta_log" / f"{0:020d}.json", "w") as f:
        f.write("\n".join(actions))
    out = read_deltalake(str(uri)).sort("v").to_pydict()
    assert out == {"v": [10, 11, 20], "p": [1, 1, 2]}


def test_checkpoint_replay(tmp_path):
    """Snapshot state from a checkpoint parquet + newer JSON commits."""
    uri = tmp_path / "ctbl"
    (uri / "_delta_log").mkdir(parents=True)
    pq.write_table(pa.table({"v": [1]}), str(uri / "f0.parquet"))
    pq.write_table(pa.table({"v": [2]}), str(uri / "f1.parquet"))
    schema_string = json.dumps({"type": "struct", "fields": [
        {"name": "v", "type": "long", "nullable": True, "metadata": {}}]})
    # checkpoint at v1 holds metaData + f0 (f_removed was added+removed)
    cp = pa.table({
        "metaData": [{"id": "t", "schemaString": schema_string,
                      "partitionColumns": []}, None],
        "add": [None, {"path": "f0.parquet", "size": 1}],
        "remove": [{"path": "gone.parquet"}, None],
    })
    pq.write_table(cp, str(uri / "_delta_log" /
                           f"{1:020d}.checkpoint.parquet"))
    with open(uri / "_delta_log" / "_last_checkpoint", "w") as f:
        f.write(json.dumps({"version": 1}))
    # v2 commit adds f1
    with open(uri / "_delta_log" / f"{2:020d}.json", "w") as f:
        f.write(json.dumps({"add": {"path": "f1.parquet",
                                    "partitionValues": {}, "size": 1,
                                    "modificationTime": 0,
                                    "dataChange": True}}) + "\n")
    op = DeltaScanOperator(str(uri))
    assert op.version == 2
    out = read_deltalake(str(uri)).sort("v").to_pydict()
    assert out == {"v": [1, 2]}


def test_gated_readers_error_actionably():
    # iceberg + hudi are native now (io/iceberg.py, io/hudi.py):
    # missing tables → clear errors
    with pytest.raises(FileNotFoundError, match="Iceberg metadata"):
        daft_tpu.read_iceberg("whatever")
    with pytest.raises(FileNotFoundError):
        daft_tpu.read_hudi("whatever")
    with pytest.raises(FileNotFoundError, match="lance"):
        daft_tpu.read_lance("whatever")  # native now (io/lance.py)


def test_read_sql_over_sqlite():
    import sqlite3
    import tempfile
    path = tempfile.mktemp(suffix=".db")
    c = sqlite3.connect(path)
    c.execute("CREATE TABLE t (a INTEGER, b TEXT)")
    c.executemany("INSERT INTO t VALUES (?, ?)", [(1, "x"), (2, "y")])
    c.commit()
    c.close()
    df = daft_tpu.read_sql("SELECT * FROM t ORDER BY a",
                           lambda: sqlite3.connect(path))
    assert df.to_pydict() == {"a": [1, 2], "b": ["x", "y"]}
    os.unlink(path)
