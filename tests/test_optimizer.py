"""Optimizer plan-shape tests: stats estimation, cross-join elimination,
join reordering (reference rule set:
``src/daft-logical-plan/src/optimization/optimizer.rs:94-215``,
``rules/reorder_joins/``, ``stats.rs``)."""

import numpy as np
import pytest

import daft_tpu
from daft_tpu import col
from daft_tpu.logical import plan as lp, stats as lstats
from daft_tpu.logical.optimizer import Optimizer


def _optimized(df) -> lp.LogicalPlan:
    return Optimizer().optimize(df._builder.plan)


def _find_all(node, t):
    out = []

    def walk(n):
        if isinstance(n, t):
            out.append(n)
        for c in n.children:
            walk(c)

    walk(node)
    return out


def _rel(n_rows, prefix):
    return daft_tpu.from_pydict({
        f"{prefix}_k": list(range(n_rows)),
        f"{prefix}_v": [float(i) for i in range(n_rows)]})


# ----------------------------------------------------------------- stats
def test_stats_source_and_filter():
    df = _rel(1000, "a")
    s = lstats.estimate(df._builder.plan)
    assert s.rows == 1000
    filtered = df.where(col("a_v") > 10.0)
    s2 = lstats.estimate(filtered._builder.plan)
    assert s2.rows == pytest.approx(1000 * lstats.FILTER_SELECTIVITY)
    eq = df.where(col("a_k") == 7)
    s3 = lstats.estimate(eq._builder.plan)
    assert s3.rows == pytest.approx(1000 * lstats.EQ_FILTER_SELECTIVITY)


def test_stats_join_and_agg():
    big = _rel(10000, "f")
    small = _rel(100, "d")
    j = big.join(small, left_on="f_k", right_on="d_k")
    s = lstats.estimate(j._builder.plan)
    assert s.rows == 10000  # PK-FK: fact-side cardinality
    agg = j.groupby("d_k").agg(col("f_v").sum())
    sa = lstats.estimate(agg._builder.plan)
    assert sa.rows < 10000


# ------------------------------------------------- cross join elimination
def test_eliminate_cross_join():
    a = _rel(100, "a")
    b = _rel(100, "b")
    crossed = a.join(b, how="cross").where(
        (col("a_k") == col("b_k")) & (col("a_v") > 5.0))
    plan = _optimized(crossed)
    joins = _find_all(plan, lp.Join)
    assert len(joins) == 1
    assert joins[0].how == "inner"
    assert [e.name() for e in joins[0].left_on] == ["a_k"]
    assert [e.name() for e in joins[0].right_on] == ["b_k"]
    # and the residual predicate must have been pushed toward the source
    out = crossed.sort("a_k").to_pydict()
    assert out["a_k"] == list(range(6, 100))


# --------------------------------------------------------- join reorder
def test_reorder_joins_smallest_first():
    """fact ⋈ dim1 ⋈ dim2 written fact-first must reorder so the smallest
    relation anchors the left-deep chain."""
    fact = _rel(20000, "f")
    dim_mid = _rel(500, "m")
    dim_small = daft_tpu.from_pydict({
        "s_k": list(range(50)), "s_v": [float(i) for i in range(50)]})
    df = (fact
          .join(dim_mid, left_on="f_k", right_on="m_k")
          .join(dim_small, left_on="f_k", right_on="s_k"))
    plan = _optimized(df)
    joins = _find_all(plan, lp.Join)
    assert len(joins) == 2
    # innermost (deepest) join should start from the smallest relation
    deepest = joins[-1]
    rels = [c.schema().column_names for c in deepest.children]
    anchored = {tuple(sorted(r)) for r in rels}
    assert any("s_k" in r for r in rels), plan.repr_ascii()

    # correctness is preserved under reordering
    out = df.sort("f_k").to_pydict()
    assert out["f_k"] == list(range(50))


def test_reorder_preserves_column_order():
    fact = _rel(5000, "f")
    d1 = _rel(100, "x")
    d2 = _rel(10, "y")
    df = (fact.join(d1, left_on="f_k", right_on="x_k")
          .join(d2, left_on="x_k", right_on="y_k"))
    cols_before = df.column_names
    plan = _optimized(df)
    assert plan.schema().column_names == cols_before
    out = df.sort("f_k").to_pydict()
    assert list(out) == cols_before
    assert out["f_k"] == list(range(10))


def test_reorder_skips_name_collisions():
    a = daft_tpu.from_pydict({"k": [1, 2], "v": [1.0, 2.0]})
    b = daft_tpu.from_pydict({"k": [1, 2], "w": [3.0, 4.0]})
    c = daft_tpu.from_pydict({"k": [1, 2], "z": [5.0, 6.0]})
    df = a.join(b, on="k").join(c, on="k")
    # shared key names → reorder must decline, plan still runs correctly
    out = df.sort("k").to_pydict()
    assert out["k"] == [1, 2]
    assert out["w"] == [3.0, 4.0]
    assert out["z"] == [5.0, 6.0]


# ---------------------------------------------- r3 join-rule additions

def _optimized(df):
    from daft_tpu.logical.optimizer import Optimizer
    return Optimizer().optimize(df._builder._plan)


def _find_nodes(plan, cls):
    from daft_tpu.logical import plan as lp
    out = []

    def walk(n):
        if isinstance(n, cls):
            out.append(n)
        for c in n.children:
            walk(c)
    walk(plan)
    return out


def test_simplify_null_filtered_join_strengthens_to_inner():
    from daft_tpu.logical import plan as lp
    l = daft_tpu.from_pydict({"k": [1, 2], "a": [10, 20]})
    r = daft_tpu.from_pydict({"k": [1], "b": [5]})
    df = l.join(r, on="k", how="left").where(col("b") > 0)
    joins = _find_nodes(_optimized(df), lp.Join)
    assert joins and all(j.how == "inner" for j in joins)
    # null-tolerant predicate must NOT strengthen
    df2 = l.join(r, on="k", how="left").where(col("b").is_null())
    joins2 = _find_nodes(_optimized(df2), lp.Join)
    assert joins2 and all(j.how == "left" for j in joins2)
    # results stay correct
    assert df.to_pydict()["k"] == [1]
    assert sorted(df2.to_pydict()["k"]) == [2]


def test_filter_null_join_key_inserts_not_null():
    from daft_tpu.logical import plan as lp
    l = daft_tpu.from_pydict({"k": [1, None, 2], "a": [1, 2, 3]})
    r = daft_tpu.from_pydict({"k": [1, None], "b": [5, 6]})
    df = l.join(r, on="k")
    plan = _optimized(df)
    filters = _find_nodes(plan, lp.Filter)
    nn = [f for f in filters if "not_null" in repr(f.predicate)]
    assert len(nn) >= 2, [repr(f.predicate) for f in filters]
    assert df.to_pydict()["k"] == [1]  # nulls never match


def test_push_down_anti_semi_join_below_project_and_sort():
    from daft_tpu.logical import plan as lp
    l = daft_tpu.from_pydict({"k": [3, 1, 2], "a": [30, 10, 20]})
    r = daft_tpu.from_pydict({"k": [2]})

    def probe(df):
        plan = _optimized(df)
        joins = _find_nodes(plan, lp.Join)
        assert joins
        j = joins[0]
        # the semi/anti join sank below: its parent chain from the root
        # contains the Sort/Project, i.e. the join's left child is not one
        assert not isinstance(j.children[0], (lp.Sort,)), plan.repr_ascii()
        return plan

    semi = l.sort("a").join(r, on="k", how="semi")
    probe(semi)
    assert semi.to_pydict() == {"k": [2], "a": [20]}
    anti = l.sort("a").join(r, on="k", how="anti")
    probe(anti)
    assert anti.to_pydict() == {"k": [1, 3], "a": [10, 30]}


def test_push_down_join_predicate_transfers_key_filter():
    from daft_tpu.logical import plan as lp
    big = daft_tpu.from_pydict({"k": list(range(100)),
                                "v": list(range(100))})
    small = daft_tpu.from_pydict({"k": list(range(100)),
                                  "w": list(range(100))})
    df = big.where(col("k") < 5).join(small, on="k")
    plan = _optimized(df)
    joins = _find_nodes(plan, lp.Join)
    assert joins

    def side_has_key_filter(side):
        # the transferred k<5 lands either as a Filter or inside the
        # in-memory source path as a Filter node
        return any("col(k) < lit(5)" in repr(f.predicate)
                   for f in _find_nodes(side, lp.Filter))

    j = joins[0]
    assert side_has_key_filter(j.children[0])
    assert side_has_key_filter(j.children[1]), plan.repr_ascii()
    out = df.sort("k").to_pydict()
    assert out["k"] == [0, 1, 2, 3, 4]


def test_semi_join_reduction_fires_and_preserves_results(monkeypatch, tmp_path):
    """Join(A, Distinct(S)) with S >> A: the rule pre-filters S with a
    semi join on A's distinct keys; results must be identical and the
    optimized plan must contain the inserted semi join."""
    from daft_tpu.logical.optimizer import SemiJoinReduction
    monkeypatch.setattr(SemiJoinReduction, "MIN_ROWS", 10)
    import pyarrow.parquet as pq
    import pyarrow as pa
    # parquet-backed so stats.estimate has real row counts
    s = pa.table({"k": list(range(1000)) * 2,
                  "v": [i % 7 for i in range(2000)]})
    a = pa.table({"k": [1, 2, 3], "w": [10.0, 20.0, 30.0]})
    pq.write_table(s, str(tmp_path / "s.parquet"))
    pq.write_table(a, str(tmp_path / "a.parquet"))
    S = daft_tpu.read_parquet(str(tmp_path / "s.parquet"))
    A = daft_tpu.read_parquet(str(tmp_path / "a.parquet"))
    joined = A.join(S.select(col("k").alias("sk"), col("v")).distinct(),
                    left_on="k", right_on="sk").sort([col("k"), col("v")])
    plan = joined._builder.optimize()._plan
    semis = []

    def walk(n):
        if isinstance(n, lp.Join) and n.how == "semi":
            semis.append(n)
        for c in n.children:
            walk(c)
    walk(plan)
    assert semis, "SemiJoinReduction did not fire"
    got = joined.to_pydict()
    monkeypatch.setattr(SemiJoinReduction, "apply",
                        lambda self, p: p)
    exp = joined.to_pydict()
    assert got == exp
    assert sorted(set(got["k"])) == [1, 2, 3]
    # each key k appears at rows i=k and i=1000+k, giving v values k%7
    # and (k+6)%7 — two distinct v per key
    assert len(got["v"]) == 3 * 2
