"""Property-based invariants over random dataframes (reference:
``tests/property_based_testing/`` — hypothesis strategies over dtypes and
sort-correctness invariants, run in their own CI workflow)."""

import math

import pytest

pytest.importorskip(
    "hypothesis",
    reason="hypothesis not installed in this environment — the seeded "
           "random property sweep in test_device_kernels.py still runs")

import hypothesis.strategies as st
from hypothesis import HealthCheck, given, settings

import daft_tpu
from daft_tpu import col

SETTINGS = dict(max_examples=25, deadline=None,
                suppress_health_check=[HealthCheck.too_slow])

# columns: int64 with nulls, float64 with nan/inf, strings with nulls, bools
_ints = st.lists(st.one_of(st.integers(-2**40, 2**40), st.none()),
                 min_size=1, max_size=60)
_floats = st.lists(st.one_of(st.floats(allow_nan=False), st.none()),
                   min_size=1, max_size=60)
_strs = st.lists(st.one_of(st.text(max_size=8), st.none()),
                 min_size=1, max_size=60)


@st.composite
def frames(draw):
    n = draw(st.integers(1, 50))
    ints = draw(st.lists(st.one_of(st.integers(-2**40, 2**40), st.none()),
                         min_size=n, max_size=n))
    floats = draw(st.lists(
        st.one_of(st.floats(allow_nan=False, allow_infinity=False),
                  st.none()), min_size=n, max_size=n))
    strs = draw(st.lists(st.one_of(st.text(max_size=8), st.none()),
                         min_size=n, max_size=n))
    return {"i": ints, "f": floats, "s": strs}


def _null_last_key(v):
    return (v is None, v)


@settings(**SETTINGS)
@given(data=frames())
def test_sort_matches_python_sorted(data):
    df = daft_tpu.from_pydict(data).sort("i")
    got = df.to_pydict()["i"]
    assert got == sorted(data["i"], key=_null_last_key)


@settings(**SETTINGS)
@given(data=frames(), desc=st.booleans())
def test_sort_permutes_rows_together(data, desc):
    df = daft_tpu.from_pydict(data).sort("i", desc=desc)
    out = df.to_pydict()
    orig = set(zip(data["i"], data["s"]))
    assert set(zip(out["i"], out["s"])) == orig


@settings(**SETTINGS)
@given(data=frames(), n=st.integers(1, 8))
def test_hash_partitions_form_a_disjoint_cover(data, n):
    df = daft_tpu.from_pydict(data).repartition(n, col("i"))
    parts = [p.combined().to_arrow_table().to_pydict()
             for p in df.iter_partitions()]
    rows = []
    for p in parts:
        rows.extend(zip(p["i"], p["s"]))
    assert sorted(rows, key=lambda t: (t[0] is None, t[0] or 0,
                                       t[1] is None, t[1] or "")) == \
        sorted(zip(data["i"], data["s"]),
               key=lambda t: (t[0] is None, t[0] or 0,
                              t[1] is None, t[1] or ""))
    # same key → same partition
    seen = {}
    for idx, p in enumerate(parts):
        for k in p["i"]:
            assert seen.setdefault(k, idx) == idx


@settings(**SETTINGS)
@given(data=frames())
def test_filter_then_count_consistent(data):
    df = daft_tpu.from_pydict(data)
    kept = df.where(col("i") > 0)
    expect = [v for v in data["i"] if v is not None and v > 0]
    assert sorted(kept.to_pydict()["i"]) == sorted(expect)


@settings(**{**SETTINGS, "max_examples": 10})  # device compiles are slow
@given(data=frames())
def test_groupby_sum_matches_python(data):
    df = daft_tpu.from_pydict(data)
    mod = df.with_column("g", col("i") % 3)
    out = mod.groupby("g").agg(col("f").sum().alias("s")).to_pydict()
    expect = {}
    for i, f in zip(data["i"], data["f"]):
        g = None if i is None else i % 3
        if f is not None:
            expect[g] = expect.get(g, 0.0) + f
    got = dict(zip(out["g"], out["s"]))
    assert set(got) == {None if i is None else i % 3 for i in data["i"]}
    for g, s in expect.items():
        assert got[g] == pytest.approx(s, rel=1e-9, abs=1e-9)


@settings(**SETTINGS)
@given(data=frames())
def test_arrow_roundtrip_identity(data):
    df = daft_tpu.from_pydict(data)
    back = daft_tpu.from_arrow(df.to_arrow()).to_pydict()
    assert back == df.to_pydict()


@settings(**SETTINGS)
@given(data=frames(), k=st.integers(0, 60))
def test_limit_is_prefix(data, k):
    df = daft_tpu.from_pydict(data)
    got = df.limit(k).to_pydict()["i"]
    assert got == data["i"][:k]


@settings(**SETTINGS)
@given(data=frames())
def test_distinct_is_set_of_rows(data):
    df = daft_tpu.from_pydict(data).select("i").distinct()
    got = df.to_pydict()["i"]
    assert sorted(got, key=_null_last_key) == \
        sorted(set(data["i"]), key=_null_last_key)
