"""Kernel-level tests (reference model: ``tests/recordbatch/``), run on both
execution tiers via the ``device_tier`` fixture."""

import math

import numpy as np
import pyarrow as pa
import pytest

from daft_tpu import DataType, RecordBatch, Series, col, lit


@pytest.fixture
def batch():
    return RecordBatch.from_pydict({
        "a": [1, 2, 3, 4, None, 6],
        "b": [10.0, 20.0, None, 40.0, 50.0, 60.0],
        "s": ["x", "y", "x", None, "z", "y"],
        "flag": [True, False, True, True, None, False],
    })


def test_project_arith(batch, device_tier):
    out = batch.eval_expression_list([
        (col("a") + 1).alias("a1"),
        (col("a") * col("b")).alias("ab"),
        (col("b") / 2).alias("half"),
    ])
    assert out.to_pydict() == {
        "a1": [2, 3, 4, 5, None, 7],
        "ab": [10.0, 40.0, None, 160.0, None, 360.0],
        "half": [5.0, 10.0, None, 20.0, 25.0, 30.0],
    }


def test_compare_and_filter(batch, device_tier):
    out = batch.filter((col("a") >= 2) & (col("b") < 60.0))
    assert out.to_pydict()["a"] == [2, 4]


def test_string_compare(batch, device_tier):
    assert batch.filter(col("s") == "x").to_pydict()["a"] == [1, 3]
    assert batch.filter(col("s") != "x").to_pydict()["a"] == [2, None, 6]
    assert batch.filter(col("s") <= "x").to_pydict()["a"] == [1, 3]
    assert batch.filter(col("s") > "x").to_pydict()["a"] == [2, None, 6]
    assert batch.filter(col("s") < "a").to_pydict()["a"] == []


def test_is_null_fill_null(batch, device_tier):
    out = batch.eval_expression_list([
        col("a").is_null().alias("n"),
        col("a").fill_null(0).alias("f"),
    ])
    assert out.to_pydict() == {"n": [False, False, False, False, True, False],
                               "f": [1, 2, 3, 4, 0, 6]}


def test_if_else_between_isin(batch, device_tier):
    out = batch.eval_expression_list([
        (col("a") > 2).if_else(col("a"), 0).alias("ie"),
        col("a").between(2, 4).alias("bt"),
        col("a").is_in([1, 4]).alias("ii"),
    ])
    d = out.to_pydict()
    assert d["ie"] == [0, 0, 3, 4, None, 6]
    assert d["bt"] == [False, True, True, True, None, False]
    assert d["ii"] == [True, False, False, True, None, False]


def test_global_agg(batch, device_tier):
    out = batch.agg([
        col("a").sum().alias("sum"),
        col("a").mean().alias("mean"),
        col("a").count().alias("cnt"),
        col("b").min().alias("min"),
        col("b").max().alias("max"),
    ])
    d = out.to_pydict()
    assert d == {"sum": [16], "mean": [3.2], "cnt": [5],
                 "min": [10.0], "max": [60.0]}


def test_grouped_agg(batch, device_tier):
    out = batch.agg(
        [col("a").sum().alias("sum"), col("b").mean().alias("mean"),
         col("a").count().alias("cnt")],
        [col("s")])
    out = out.sort([col("s")])
    d = out.to_pydict()
    # groups: None, x, y, z — null group position depends on sort, check content
    rows = dict(zip(d["s"], zip(d["sum"], d["mean"], d["cnt"])))
    assert rows["x"] == (4, 10.0, 2)
    assert rows["y"] == (8, 40.0, 2)
    assert rows["z"] == (None, 50.0, 0)
    assert rows[None] == (4, 40.0, 1)


def test_grouped_agg_multi_key(device_tier):
    b = RecordBatch.from_pydict({
        "k1": ["a", "a", "b", "b", "a"],
        "k2": [1, 2, 1, 1, 1],
        "v": [10, 20, 30, 40, 50],
    })
    out = b.agg([col("v").sum()], [col("k1"), col("k2")]).sort(
        [col("k1"), col("k2")])
    assert out.to_pydict() == {"k1": ["a", "a", "b"], "k2": [1, 2, 1],
                               "v": [60, 20, 70]}


def test_sort_multi(device_tier):
    b = RecordBatch.from_pydict({
        "x": [2, 1, 2, None, 1],
        "y": [1.0, 5.0, 0.0, 2.0, None],
    })
    # reference defaults: nulls_first = descending (nulls sort as greatest)
    out = b.sort([col("x"), col("y")], descending=[False, True])
    assert out.to_pydict()["x"] == [1, 1, 2, 2, None]
    assert out.to_pydict()["y"] == [None, 5.0, 1.0, 0.0, 2.0]


def test_sort_stability(device_tier):
    b = RecordBatch.from_pydict({"k": [1, 1, 1, 0, 0], "i": [0, 1, 2, 3, 4]})
    out = b.sort([col("k")])
    assert out.to_pydict()["i"] == [3, 4, 0, 1, 2]


def test_joins(device_tier):
    l = RecordBatch.from_pydict({"k": [1, 2, 3, None], "v": [10, 20, 30, 40]})
    r = RecordBatch.from_pydict({"k": [2, 2, 4, None], "w": [1.0, 2.0, 3.0, 4.0]})
    inner = l.hash_join(r, [col("k")], [col("k")], "inner").sort([col("w")])
    assert inner.to_pydict() == {"k": [2, 2], "v": [20, 20], "w": [1.0, 2.0]}
    left = l.hash_join(r, [col("k")], [col("k")], "left")
    assert len(left) == 5  # 2 matches + 3 unmatched left (incl. null key)
    semi = l.hash_join(r, [col("k")], [col("k")], "semi")
    assert semi.to_pydict()["v"] == [20]
    anti = l.hash_join(r, [col("k")], [col("k")], "anti")
    assert sorted(anti.to_pydict()["v"]) == [10, 30, 40]
    outer = l.hash_join(r, [col("k")], [col("k")], "outer")
    assert len(outer) == 7
    ks = outer.to_pydict()["k"]
    assert 4 in ks  # right-side key coalesced in


def test_multi_key_join(device_tier):
    l = RecordBatch.from_pydict({"a": [1, 1, 2], "b": ["x", "y", "x"], "v": [1, 2, 3]})
    r = RecordBatch.from_pydict({"a": [1, 2], "b": ["y", "x"], "w": [100, 200]})
    out = l.hash_join(r, [col("a"), col("b")], [col("a"), col("b")], "inner")
    out = out.sort([col("v")])
    assert out.to_pydict() == {"a": [1, 2], "b": ["y", "x"], "v": [2, 3],
                               "w": [100, 200]}


def test_explode(device_tier):
    b = RecordBatch.from_pydict({"id": [1, 2, 3], "l": [[1, 2], [], [3]]})
    out = b.explode([col("l").explode()])
    assert out.to_pydict() == {"id": [1, 1, 2, 3], "l": [1, 2, None, 3]}


def test_partition_by_hash(device_tier):
    b = RecordBatch.from_pydict({"k": list(range(100)), "v": list(range(100))})
    parts = b.partition_by_hash([col("k")], 4)
    assert len(parts) == 4
    assert sum(len(p) for p in parts) == 100
    all_k = sorted(sum((p.to_pydict()["k"] for p in parts), []))
    assert all_k == list(range(100))


def test_distinct(device_tier):
    b = RecordBatch.from_pydict({"a": [1, 1, 2, 2, 3], "b": ["x", "x", "y", "z", "x"]})
    out = b.distinct().sort([col("a"), col("b")])
    assert out.to_pydict() == {"a": [1, 2, 2, 3], "b": ["x", "y", "z", "x"]}


def test_concat_and_slice(device_tier):
    b1 = RecordBatch.from_pydict({"a": [1, 2]})
    b2 = RecordBatch.from_pydict({"a": [3]})
    out = RecordBatch.concat([b1, b2])
    assert out.to_pydict() == {"a": [1, 2, 3]}
    assert out.slice(1, 3).to_pydict() == {"a": [2, 3]}


def test_unpivot(device_tier):
    b = RecordBatch.from_pydict({"id": [1, 2], "x": [10, 20], "y": [30, 40]})
    out = b.unpivot([col("id")], [col("x"), col("y")])
    assert len(out) == 4
    assert set(out.to_pydict()["variable"]) == {"x", "y"}


def test_pivot(device_tier):
    b = RecordBatch.from_pydict({
        "g": ["a", "a", "b"], "p": ["x", "y", "x"], "v": [1, 2, 3]})
    out = b.pivot([col("g")], col("p"), col("v"), ["x", "y"])
    out = out.sort([col("g")])
    assert out.to_pydict() == {"g": ["a", "b"], "x": [1, 3], "y": [2, None]}


def test_str_functions(device_tier):
    b = RecordBatch.from_pydict({"s": ["Hello", "world", None]})
    out = b.eval_expression_list([
        col("s").str.upper().alias("u"),
        col("s").str.contains("orl").alias("c"),
        col("s").str.length().alias("n"),
    ])
    assert out.to_pydict() == {"u": ["HELLO", "WORLD", None],
                               "c": [False, True, None],
                               "n": [5, 5, None]}


def test_dt_functions(device_tier):
    import datetime
    b = RecordBatch.from_pydict(
        {"d": [datetime.date(2024, 3, 15), datetime.date(1999, 12, 31), None]})
    out = b.eval_expression_list([
        col("d").dt.year().alias("y"),
        col("d").dt.month().alias("m"),
        col("d").dt.day().alias("dd"),
    ])
    assert out.to_pydict() == {"y": [2024, 1999, None], "m": [3, 12, None],
                               "dd": [15, 31, None]}


def test_date_compare(device_tier):
    import datetime
    b = RecordBatch.from_pydict(
        {"d": [datetime.date(2024, 3, 15), datetime.date(1999, 12, 31)]})
    out = b.filter(col("d") <= lit(datetime.date(2000, 1, 1)))
    assert out.to_pydict()["d"] == [datetime.date(1999, 12, 31)]


def test_cast(device_tier):
    b = RecordBatch.from_pydict({"a": [1, 2, 3]})
    out = b.eval_expression_list([col("a").cast(DataType.float64()).alias("f"),
                                  col("a").cast(DataType.string()).alias("s")])
    assert out.to_pydict() == {"f": [1.0, 2.0, 3.0], "s": ["1", "2", "3"]}


def test_stddev_var(device_tier):
    b = RecordBatch.from_pydict({"g": ["a", "a", "a", "b"],
                                 "v": [1.0, 2.0, 3.0, 5.0]})
    out = b.agg([col("v").stddev().alias("sd"), col("v").var().alias("vr")],
                [col("g")]).sort([col("g")])
    d = out.to_pydict()
    assert d["sd"][0] == pytest.approx(math.sqrt(2.0 / 3.0))
    assert d["vr"][0] == pytest.approx(2.0 / 3.0)
    assert d["sd"][1] == pytest.approx(0.0)


def test_pyobject_column(device_tier):
    b = RecordBatch.from_pydict({"o": Series.from_pyobjects([{"x": 1}, [2], None]),
                                 "k": [1, 2, 3]})
    out = b.filter(col("k") > 1)
    assert out.to_pydict()["o"] == [[2], None]
    t = b.take(np.array([2, 0]))
    assert t.to_pydict()["o"] == [None, {"x": 1}]
