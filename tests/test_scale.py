"""Scale-proof tests (r23): spill-plane fast path + memory governor.

Codec parity — every spill write/read path (grace join partitions, agg
partial states, recursive re-partition at depth >= 2) round-trips
bit-identical under ``lz4``, ``zstd``, and ``none``, including nullable
int/string/date columns. Writer-pool ordering/error/backpressure
contracts, prefetch-piped reads, the post-codec disk-byte counters, and
the governor's hysteresis/throttle/action surface.
"""

import datetime
import os
import threading
import time

import pytest

import daft_tpu as daft
from daft_tpu import col
from daft_tpu.execution import governor, memory, spill_io
from daft_tpu.recordbatch import RecordBatch

CODECS = ["lz4", "zstd", "none"]


def _sorted_pydict(d):
    keys = list(d.keys())
    rows = sorted(zip(*[d[k] for k in keys]),
                  key=lambda r: tuple((v is None, str(type(v)), v)
                                      for v in r))
    return {k: [r[i] for r in rows] for i, k in enumerate(keys)}


def _typed_df(n=40_000, ndv=8_000):
    """Nullable int/string/date payload on a spill-forcing key."""
    base = datetime.date(2024, 1, 1)
    return daft.from_pydict({
        "k": [None if i % 101 == 0 else i % ndv for i in range(n)],
        "v": [None if i % 7 == 0 else i for i in range(n)],
        "s": [None if i % 11 == 0 else "name-%d" % (i % 997)
              for i in range(n)],
        "d": [None if i % 13 == 0 else base + datetime.timedelta(i % 366)
              for i in range(n)],
    })


@pytest.fixture(autouse=True)
def _clean_governor():
    governor._reset_for_tests()
    yield
    governor._reset_for_tests()


@pytest.fixture
def spill_env(tmp_path, monkeypatch):
    monkeypatch.setenv("DAFT_TPU_SPILL_DIR", str(tmp_path))
    memory._spill_dir = None
    memory._spill_ipc_cache.clear()
    yield tmp_path
    memory._spill_ipc_cache.clear()
    memory._spill_dir = None


# ------------------------------------------------------------ codec parity

@pytest.mark.parametrize("codec", CODECS)
def test_grace_join_codec_parity(spill_env, monkeypatch, codec):
    """Spilled grace join under each codec is bit-identical to the
    unbounded in-memory answer — nullable int/string/date payload."""
    left = _typed_df()
    right = daft.from_pydict({"k": list(range(4_000)),
                              "w": [i * 2 for i in range(4_000)]})
    ref = _sorted_pydict(
        left.join(right, on="k", strategy="hash").to_pydict())
    monkeypatch.setenv("DAFT_TPU_MEMORY_LIMIT", "400KB")
    monkeypatch.setenv("DAFT_TPU_SPILL_COMPRESSION", codec)
    memory._spill_ipc_cache.clear()
    b0 = memory.spill_counters_snapshot()
    got = _sorted_pydict(
        left.join(right, on="k", strategy="hash").to_pydict())
    d = memory.spill_counters_delta(b0)
    assert d.get("joins_partitioned", 0) >= 1  # the spill path really ran
    assert got == ref


@pytest.mark.parametrize("codec", CODECS)
def test_spilled_agg_codec_parity(spill_env, monkeypatch, codec):
    """Agg partial states spill/merge under each codec bit-identically."""
    df = _typed_df(n=60_000, ndv=60_000)
    q = lambda d: _sorted_pydict(
        d.groupby("k").agg(col("v").sum(), col("s").count(),
                           col("d").max()).to_pydict())
    ref = q(df)
    monkeypatch.setenv("DAFT_TPU_MEMORY_LIMIT", "400KB")
    monkeypatch.setenv("DAFT_TPU_SPILL_AGG", "1")
    monkeypatch.setenv("DAFT_TPU_SPILL_COMPRESSION", codec)
    memory._spill_ipc_cache.clear()
    b0 = memory.spill_counters_snapshot()
    got = q(df)
    d = memory.spill_counters_delta(b0)
    assert d.get("agg_buckets_merged", 0) > 0
    assert got == ref


@pytest.mark.parametrize("codec", CODECS)
def test_recursive_repartition_codec_parity(spill_env, monkeypatch, codec):
    """Forced under-partitioning (2-way) drives rotated-radix recursion
    to depth >= 2; the re-partitioned spill files round-trip under every
    codec and the joined answer doesn't change."""
    left = _typed_df(n=60_000, ndv=6_000)
    right = daft.from_pydict({"k": [i % 6_000 for i in range(30_000)],
                              "w": list(range(30_000))})
    ref = _sorted_pydict(
        left.join(right, on="k", strategy="hash").to_pydict())
    monkeypatch.setenv("DAFT_TPU_MEMORY_LIMIT", "200KB")
    monkeypatch.setenv("DAFT_TPU_SPILL_PARTITIONS", "2")
    monkeypatch.setenv("DAFT_TPU_SPILL_COMPRESSION", codec)
    memory._spill_ipc_cache.clear()
    b0 = memory.spill_counters_snapshot()
    got = _sorted_pydict(
        left.join(right, on="k", strategy="hash").to_pydict())
    d = memory.spill_counters_delta(b0)
    assert d.get("recursions", 0) >= 1
    depths = [int(k[len("recursions_d"):]) for k in d
              if k.startswith("recursions_d")]
    assert depths and max(depths) >= 2, d
    assert got == ref


@pytest.mark.parametrize("codec", CODECS)
def test_store_roundtrip_bit_identical(spill_env, monkeypatch, codec):
    """Direct PartitionedSpillStore round-trip: the typed batch read
    back from disk equals the batch pushed, per codec, async writers on."""
    monkeypatch.setenv("DAFT_TPU_SPILL_COMPRESSION", codec)
    monkeypatch.setenv("DAFT_TPU_SPILL_IO_PARALLELISM", "4")
    memory._spill_ipc_cache.clear()
    base = datetime.date(2023, 6, 15)
    rb = RecordBatch.from_pydict({
        "v": [None if i % 5 == 0 else i for i in range(5_000)],
        "s": [None if i % 3 == 0 else "s%d" % i for i in range(5_000)],
        "d": [None if i % 4 == 0 else base + datetime.timedelta(i % 200)
              for i in range(5_000)],
    })
    with memory.PartitionedSpillStore(2, budget=1) as store:
        store.push(0, rb)
        store.push(1, rb)
        store.finalize()
        for i in (0, 1):
            got = store.bucket_batches(i)
            assert sum(len(b) for b in got) == 5_000
            assert got[0].to_pydict() == rb.to_pydict()


# ------------------------------------------------------------- writer pool

def test_writer_pool_preserves_push_order(spill_env, monkeypatch):
    """Concurrent per-bucket chains: many small pushes into 4 buckets
    read back in exact push order within each bucket."""
    monkeypatch.setenv("DAFT_TPU_SPILL_IO_PARALLELISM", "8")
    with memory.PartitionedSpillStore(4, budget=1) as store:
        for seq in range(40):
            for b in range(4):
                store.push(b, RecordBatch.from_pydict(
                    {"seq": [seq] * 50, "b": [b] * 50}))
        store.finalize()
        for b in range(4):
            seqs = []
            for batch in store.bucket_batches(b):
                d = batch.to_pydict()
                assert set(d["b"]) == {b}
                seqs.extend(sorted(set(d["seq"])))
            assert seqs == sorted(seqs)
            assert set(seqs) == set(range(40))


def test_writer_group_drain_raises_first_error():
    g = spill_io.SpillWriterGroup(pending_cap=1 << 20)

    def boom():
        raise RuntimeError("disk gone")

    g.submit("a", boom, 10)
    with pytest.raises(RuntimeError, match="disk gone"):
        g.drain()
    g.close()  # close() after error must not raise


def test_writer_group_single_huge_request_admitted():
    """One oversize submit with nothing pending never deadlocks (the
    MemoryManager single-huge-request rule)."""
    g = spill_io.SpillWriterGroup(pending_cap=100)
    done = threading.Event()
    g.submit("a", done.set, 10_000_000)  # 100000x the cap
    assert done.wait(5.0)
    g.drain()


def test_writer_group_backpressures_at_cap():
    """A second over-cap submit waits until the first drains."""
    g = spill_io.SpillWriterGroup(pending_cap=1 << 20)  # floor: 1MB
    release = threading.Event()
    g.submit("a", lambda: release.wait(5.0), 900_000)
    t0 = time.monotonic()

    def unblock():
        time.sleep(0.2)
        release.set()

    threading.Thread(target=unblock, daemon=True).start()
    g.submit("b", lambda: None, 200_000)  # must wait for a's drain
    assert time.monotonic() - t0 >= 0.15
    g.drain()


def test_prefetch_ordered_yields_in_order():
    """Out-of-order completion, in-order yield; window<=0 is serial."""
    def thunk(i):
        def run():
            time.sleep(0.05 if i == 0 else 0.0)  # first finishes last
            return i
        return run

    assert list(spill_io.prefetch_ordered(
        (thunk(i) for i in range(6)), window=3)) == list(range(6))
    assert list(spill_io.prefetch_ordered(
        (thunk(i) for i in range(6)), window=0)) == list(range(6))


def test_chaos_serialize_forces_serial_spill_io(monkeypatch):
    monkeypatch.setenv("DAFT_TPU_SPILL_IO_PARALLELISM", "8")
    monkeypatch.setenv("DAFT_TPU_CHAOS_SERIALIZE", "1")
    assert spill_io.spill_io_parallelism() == 0
    assert spill_io.read_prefetch_window() == 0


def test_spill_io_parallelism_knob(monkeypatch):
    monkeypatch.setenv("DAFT_TPU_SPILL_IO_PARALLELISM", "3")
    assert spill_io.spill_io_parallelism() == 3
    monkeypatch.setenv("DAFT_TPU_SPILL_IO_PARALLELISM", "99")
    assert spill_io.spill_io_parallelism() == spill_io._MAX_POOL
    monkeypatch.setenv("DAFT_TPU_SPILL_IO_PARALLELISM", "0")
    assert spill_io.spill_io_parallelism() == 0


# --------------------------------------------------------- disk-byte plane

def test_disk_bytes_track_codec(spill_env, monkeypatch):
    """Post-codec ``disk_bytes_written`` lands under the logical
    ``bytes_written`` for compressible data under lz4, and reads count
    ``disk_bytes_read``."""
    monkeypatch.setenv("DAFT_TPU_SPILL_COMPRESSION", "lz4")
    memory._spill_ipc_cache.clear()
    rb = RecordBatch.from_pydict({"x": [7] * 40_000})
    b0 = memory.spill_counters_snapshot()
    with memory.PartitionedSpillStore(1, budget=1) as store:
        store.push(0, rb)
        store.finalize()
        store.bucket_batches(0)
    d = memory.spill_counters_delta(b0)
    assert 0 < d["disk_bytes_written"] < d["bytes_written"]
    # reads see the whole file incl. the EOS marker written at seal, so
    # read bytes land at-or-just-above the summed write deltas
    assert d.get("disk_bytes_read", 0) >= d["disk_bytes_written"]
    assert d["disk_bytes_read"] < d["bytes_written"]


# --------------------------------------------------------------- governor

@pytest.fixture
def fake_rss(monkeypatch):
    """Governor sees a controllable RSS; 100MB limit; fresh state."""
    val = {"rss": 10 << 20}
    monkeypatch.setattr(governor, "_read_rss", lambda: val["rss"])
    monkeypatch.setenv("DAFT_TPU_MEMORY_LIMIT", "100MB")
    governor._reset_for_tests()
    yield val
    governor._reset_for_tests()


def _set_rss(val, mb):
    val["rss"] = mb << 20
    governor.rss_bytes(refresh=True)


def test_governor_hysteresis(fake_rss):
    lim = 100 * 1000 * 1000  # parse_bytes("100MB") is decimal
    assert governor.enabled()
    assert governor.watermarks() == (0.85, 0.70)
    assert not governor.under_pressure()
    fake_rss["rss"] = int(lim * 0.90)
    governor.rss_bytes(refresh=True)
    b0 = governor.counters_snapshot()
    assert governor.under_pressure()
    fake_rss["rss"] = int(lim * 0.80)  # between low and high: still on
    governor.rss_bytes(refresh=True)
    assert governor.under_pressure()
    fake_rss["rss"] = int(lim * 0.60)  # below low: clears
    governor.rss_bytes(refresh=True)
    assert not governor.under_pressure()
    d = governor.counters_delta(b0)
    assert d.get("pressure_episodes") == 1
    assert d.get("gc_collects") == 1


def test_governor_actions_under_pressure(fake_rss):
    fake_rss["rss"] = 95 << 20
    governor.rss_bytes(refresh=True)
    assert governor.budget_scale() == 0.5
    assert governor.prefetch_window(4) == 1
    assert governor.prefetch_window(1) == 1  # never below 1
    fake_rss["rss"] = 10 << 20
    governor.rss_bytes(refresh=True)
    assert not governor.under_pressure()
    assert governor.budget_scale() == 1.0
    assert governor.prefetch_window(4) == 4


def test_governor_throttle_bounded(fake_rss):
    """The throttle is sliced and capped — never a hard gate."""
    fake_rss["rss"] = 95 << 20
    governor.rss_bytes(refresh=True)
    b0 = governor.counters_snapshot()
    t0 = time.monotonic()
    waited = governor.throttle("test")
    wall = time.monotonic() - t0
    assert 0.0 < waited <= governor._THROTTLE_MAX_S + 0.1
    # the logical wait above is the tight bound; wall clock only gets a
    # sanity ceiling — each 50ms sleep slice can overshoot arbitrarily
    # under full-suite load on a 1-core box
    assert wall < 5.0
    d = governor.counters_delta(b0)
    assert d.get("throttle_waits") == 1
    assert d.get("throttle_test") == 1
    assert d.get("throttle_wait_us", 0) > 0


def test_governor_throttle_releases_early(fake_rss):
    """RSS dropping below the low watermark releases a throttler before
    the cap."""
    fake_rss["rss"] = 95 << 20
    governor.rss_bytes(refresh=True)
    assert governor.under_pressure()

    def drop():
        time.sleep(0.07)
        fake_rss["rss"] = 10 << 20
        governor.rss_bytes(refresh=True)

    threading.Thread(target=drop, daemon=True).start()
    waited = governor.throttle("early")
    assert waited < governor._THROTTLE_MAX_S


def test_governor_inert_without_limit(monkeypatch):
    monkeypatch.delenv("DAFT_TPU_MEMORY_LIMIT", raising=False)
    governor._reset_for_tests()
    assert not governor.enabled()
    assert not governor.under_pressure()
    assert governor.budget_scale() == 1.0
    assert governor.prefetch_window(4) == 4
    assert governor.throttle() == 0.0
    assert governor.pressure() == 0.0


def test_governor_frozen_under_chaos(fake_rss, monkeypatch):
    """Chaos-determinism contract: replayed plans must not depend on the
    recording machine's RSS."""
    monkeypatch.setenv("DAFT_TPU_CHAOS_SERIALIZE", "1")
    fake_rss["rss"] = 99 << 20
    governor.rss_bytes(refresh=True)
    assert not governor.enabled()
    assert governor.budget_scale() == 1.0


def test_governor_off_switch(fake_rss, monkeypatch):
    monkeypatch.setenv("DAFT_TPU_GOVERNOR", "0")
    fake_rss["rss"] = 99 << 20
    governor.rss_bytes(refresh=True)
    assert not governor.enabled()
    assert governor.budget_scale() == 1.0


def test_governor_watermark_knobs(monkeypatch):
    monkeypatch.setenv("DAFT_TPU_MEMORY_LIMIT", "100MB")
    monkeypatch.setenv("DAFT_TPU_GOVERNOR_HIGH", "0.5")
    monkeypatch.setenv("DAFT_TPU_GOVERNOR_LOW", "0.9")  # inverted on purpose
    high, low = governor.watermarks()
    assert high == 0.5
    assert low < high  # clamped — the band never inverts


def test_governor_peak_rss_tracking(fake_rss):
    governor.reset_peak()
    _set_rss(fake_rss, 40)
    _set_rss(fake_rss, 20)
    assert governor.peak_rss_bytes() == 40 << 20
    base = governor.reset_peak()
    assert base == 20 << 20
    assert governor.peak_rss_bytes() == 20 << 20
    snap = governor.snapshot()
    assert snap["rss_peak_bytes"] == float(20 << 20)
    assert snap["limit_bytes"] == 100 * 1000 * 1000.0


def test_real_rss_probe_sane():
    """The /proc probe reads this process's actual RSS: nonzero, and
    bigger than a few MB (we have pyarrow loaded)."""
    rss = governor.rss_bytes(refresh=True)
    assert rss > 4 << 20


def test_governor_budget_scale_shrinks_pair_budget(fake_rss):
    from daft_tpu.execution import out_of_core as ooc
    fake_rss["rss"] = 10 << 20
    governor.rss_bytes(refresh=True)
    unpressured = ooc.pair_budget_bytes(1 << 20)
    fake_rss["rss"] = 95 << 20
    governor.rss_bytes(refresh=True)
    pressured = ooc.pair_budget_bytes(1 << 20)
    assert pressured < unpressured


# ---------------------------------------------------------- observability

def test_governor_block_in_explain_analyze(spill_env, monkeypatch):
    from daft_tpu import observability as obs
    monkeypatch.setenv("DAFT_TPU_MEMORY_LIMIT", "400KB")
    left = _typed_df(n=20_000, ndv=5_000)
    right = daft.from_pydict({"k": list(range(2_000)),
                              "w": list(range(2_000))})
    left.join(right, on="k", strategy="hash").to_pydict()
    stats = obs.last_query_stats_local() or obs.last_query_stats()
    assert stats is not None
    rendered = stats.render()
    assert "memory governor:" in rendered
    assert "rss: peak" in rendered
    assert stats.governor.get("rss_peak_bytes", 0) > 0
    assert stats.governor.get("rss_limit_bytes") == 400 * 1000.0


def test_spill_codec_line_in_explain_analyze(spill_env, monkeypatch):
    from daft_tpu import observability as obs
    monkeypatch.setenv("DAFT_TPU_MEMORY_LIMIT", "400KB")
    monkeypatch.setenv("DAFT_TPU_SPILL_COMPRESSION", "lz4")
    memory._spill_ipc_cache.clear()
    left = _typed_df(n=20_000, ndv=5_000)
    right = daft.from_pydict({"k": list(range(2_000)),
                              "w": list(range(2_000))})
    left.join(right, on="k", strategy="hash").to_pydict()
    stats = obs.last_query_stats_local() or obs.last_query_stats()
    rendered = stats.render()
    assert "on disk" in rendered
    assert "compression" in rendered


def test_rss_gauges_at_metrics_endpoint(monkeypatch):
    from daft_tpu import tracing
    monkeypatch.setenv("DAFT_TPU_MEMORY_LIMIT", "100MB")
    governor.rss_bytes(refresh=True)
    text = tracing.prometheus_text()
    assert "daft_tpu_rss_bytes" in text
    assert "daft_tpu_rss_peak_bytes" in text
    assert "daft_tpu_memory_limit_bytes" in text
    assert "daft_tpu_governor_pressured" in text


def test_governor_plane_in_flight_recorder(fake_rss, tmp_path,
                                           monkeypatch):
    from daft_tpu import observability as obs
    rec = tmp_path / "flight.jsonl"
    monkeypatch.setenv("DAFT_TPU_QUERY_LOG", str(rec))
    fake_rss["rss"] = 95 << 20
    governor.rss_bytes(refresh=True)
    left = daft.from_pydict({"k": [1, 2, 3], "v": [1, 2, 3]})
    left.select(col("v") + 1).to_pydict()
    import json
    entries = [json.loads(l) for l in rec.read_text().splitlines() if l]
    assert entries
    assert any("governor" in e for e in entries)
    gov = [e["governor"] for e in entries if e.get("governor")]
    assert gov and gov[-1].get("rss_peak_bytes", 0) > 0
