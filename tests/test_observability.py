"""Runtime stats / chrome trace / explain-analyze tests
(reference model: runtime_stats.rs, common/tracing, tests/observability/)."""

import json
import os

import pytest

import daft_tpu as daft
from daft_tpu import col
from daft_tpu import observability as obs


def test_runtime_stats_collected():
    df = (daft.from_pydict({"x": list(range(1000)), "g": [i % 10 for i in range(1000)]})
          .where(col("x") > 99)
          .groupby("g").agg(col("x").sum().alias("s")))
    df.collect()
    stats = obs.last_query_stats()
    assert stats is not None
    d = stats.as_dict()
    assert stats.wall_us is not None and stats.wall_us > 0
    # source emits all 1000 rows; final agg emits 10 groups
    src = [v for k, v in d.items() if "Source" in k]
    assert src and src[0]["rows_out"] == 1000
    root = [v for k, v in d.items() if "Agg" in k]
    assert any(v["rows_out"] == 10 for v in root)


def test_runtime_stats_unfused_filter():
    df = daft.from_pydict({"x": list(range(1000))}).where(col("x") > 99)
    df.collect()
    d = obs.last_query_stats().as_dict()
    filters = [v for k, v in d.items() if k.startswith("Filter")]
    assert filters and filters[0]["rows_out"] == 900


def test_explain_analyze_renders(capsys):
    df = daft.from_pydict({"x": [1, 2, 3]}).where(col("x") > 1)
    df.explain(analyze=True)
    out = capsys.readouterr().out
    assert "rows_out=2" in out
    assert "query wall time" in out


def test_chrome_trace_written(tmp_path, monkeypatch):
    path = str(tmp_path / "trace.json")
    monkeypatch.setenv("DAFT_TPU_CHROME_TRACE", path)
    df = daft.from_pydict({"x": list(range(100))}).where(col("x") % 2 == 0)
    df.collect()
    with open(path) as f:
        trace = json.load(f)
    names = {e["name"] for e in trace["traceEvents"]}
    assert "Filter" in names
    for e in trace["traceEvents"]:
        assert {"name", "ph", "ts", "dur", "pid", "tid"} <= set(e)


def test_stats_exclusive_time_nonneg():
    df = daft.from_pydict({"x": list(range(500))}).with_column(
        "y", col("x") * 2).where(col("y") > 10)
    df.collect()
    stats = obs.last_query_stats()
    for v in stats.as_dict().values():
        assert v["exclusive_us"] >= 0
        assert v["inclusive_us"] >= v["exclusive_us"]


def test_explain_analyze_not_stale(capsys):
    df1 = daft.from_pydict({"x": [1, 2, 3]}).where(col("x") > 1)
    df1.collect()
    # another query runs afterwards…
    daft.from_pydict({"y": list(range(50))}).where(col("y") > 10).collect()
    # …but df1's analysis must show df1's stats (2 rows), not the later query's
    df1.explain(analyze=True)
    out = capsys.readouterr().out
    assert "rows_out=2" in out and "rows_out=39" not in out


def test_aqe_coalesces_small_shuffles(monkeypatch):
    """With AQE on, an engine-inserted shuffle over tiny data coalesces to
    fewer partitions, sized by actual materialized bytes (reference:
    AdaptivePlanner next_stage/update_stats)."""
    import daft_tpu
    from daft_tpu import col
    from daft_tpu.context import execution_config_ctx
    from daft_tpu.physical import adaptive

    monkeypatch.setenv("DAFT_TPU_DEVICE", "0")  # host exchange path
    df = daft_tpu.from_pydict({"k": [i % 5 for i in range(100)],
                               "v": [float(i) for i in range(100)]})
    df = df.into_partitions(8)
    # count_distinct is non-decomposable → single-stage agg over a real
    # engine-inserted hash exchange (the fused partitioned-agg dispatcher
    # handles mergeable finals without materializing a shuffle at all)
    with execution_config_ctx(enable_aqe=True,
                              target_partition_size_bytes=1 << 30):
        out = df.groupby("k").agg(col("v").count_distinct().alias("s")) \
            .sort("k").to_pydict()
    assert out["k"] == [0, 1, 2, 3, 4]
    planner = adaptive.last_planner()
    assert planner is not None and planner.history
    # tiny data against a 1GB target → coalesced to 1 partition
    assert planner.history[-1].partitions == 1
    assert "→1 parts" in planner.history[-1].decision
    # user-visible explain
    assert "Adaptive execution" in planner.explain_analyze()


def test_aqe_records_fused_partitioned_agg(monkeypatch):
    """Mergeable grouped aggs skip the shuffle entirely via the fused
    partitioned-agg dispatcher; with AQE on, that elision is recorded in
    the adaptive history so explain_analyze shows why no exchange ran."""
    import daft_tpu
    from daft_tpu import col
    from daft_tpu.context import execution_config_ctx
    from daft_tpu.physical import adaptive

    monkeypatch.setenv("DAFT_TPU_DEVICE", "0")
    df = daft_tpu.from_pydict({"k": [i % 5 for i in range(100)],
                               "v": [float(i) for i in range(100)]})
    df = df.into_partitions(8)
    with execution_config_ctx(enable_aqe=True,
                              target_partition_size_bytes=1 << 30):
        out = df.groupby("k").agg(col("v").sum().alias("s")) \
            .sort("k").to_pydict()
    assert out["k"] == [0, 1, 2, 3, 4]
    assert out["s"] == [sum(float(i) for i in range(100) if i % 5 == k)
                        for k in range(5)]
    planner = adaptive.last_planner()
    assert planner is not None and planner.history
    assert any("fused partitioned agg" in s.decision
               for s in planner.history)


def test_aqe_demotes_hash_join_to_broadcast(monkeypatch):
    """With AQE on, a planned hash-hash join whose measured build side fits
    the broadcast threshold skips both shuffles and broadcasts it
    (reference: AdaptivePlanner re-planning joins from materialized
    stats)."""
    import daft_tpu
    from daft_tpu import col
    from daft_tpu.context import execution_config_ctx
    from daft_tpu.physical import adaptive

    monkeypatch.setenv("DAFT_TPU_DEVICE", "0")
    big = daft_tpu.from_pydict(
        {"k": [i % 10 for i in range(20_000)],
         "v": list(range(20_000))}).into_partitions(4)
    # a highly selective filter: the static planner's 20%-of-input size
    # heuristic (~tens of KB) exceeds the threshold so it plans hash-hash,
    # but the MEASURED bytes (a handful of rows) fit — exactly the
    # mis-estimate AQE corrects by demoting to broadcast
    small = daft_tpu.from_pydict(
        {"k": [i % 1000 for i in range(10_000)],
         "w": [f"n{i % 1000}" for i in range(10_000)]}) \
        .into_partitions(4).where(col("k") == 0)
    with execution_config_ctx(enable_aqe=True,
                              broadcast_join_size_bytes_threshold=4096):
        out = big.join(small, on="k").groupby("w") \
            .agg(col("v").sum().alias("s")).sort("w").to_pydict()
    # k==0 survives the filter 10 times; each match contributes big's v
    # sum over k==0
    assert out["w"] == ["n0"]
    assert out["s"] == [sum(range(0, 20_000, 10)) * 10]
    def final_strategies(planner):
        from daft_tpu.physical import plan as pp
        out = []

        def walk(n):
            if isinstance(n, pp.HashJoin):
                out.append(n.strategy)
            for c in n.children:
                walk(c)
        walk(planner.final_plan)
        return out

    planner = adaptive.last_planner()
    assert planner is not None
    # the adaptive runner materialized the join input and re-planned with
    # ACTUAL bytes: the tiny measured side now broadcasts
    decisions = [h.decision for h in planner.history
                 if "join input" in h.decision]
    assert decisions, planner.explain_analyze()
    assert any(s in ("broadcast_right", "broadcast_left")
               for s in final_strategies(planner)), planner.final_plan

    # same query with a zero threshold keeps the hash-hash plan
    with execution_config_ctx(enable_aqe=True,
                              broadcast_join_size_bytes_threshold=0):
        out2 = big.join(small, on="k").groupby("w") \
            .agg(col("v").sum().alias("s")).sort("w").to_pydict()
    assert out2 == out
    planner = adaptive.last_planner()
    assert all(s == "hash" for s in final_strategies(planner)), \
        final_strategies(planner)


def test_user_repartition_not_adapted():
    import daft_tpu
    from daft_tpu import col
    from daft_tpu.context import execution_config_ctx

    df = daft_tpu.from_pydict({"k": list(range(50))})
    with execution_config_ctx(enable_aqe=True,
                              target_partition_size_bytes=1 << 30):
        out = df.repartition(6, col("k"))
        assert out.num_partitions() == 6
        got = out.to_pydict()
    assert sorted(got["k"]) == list(range(50))


def test_dashboard_serves_query_history():
    import urllib.request
    import daft_tpu
    from daft_tpu import col, dashboard

    port = dashboard.launch(0)
    try:
        df = daft_tpu.from_pydict({"x": [1, 2, 3]})
        df.select((col("x") * 2).alias("y")).to_pydict()
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/") as r:
            page = r.read().decode()
        assert "daft-tpu queries" in page
        assert "query 1" in page
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/api/queries") as r:
            import json
            data = json.loads(r.read())
        assert data and "operators" in data[0]
    finally:
        dashboard.shutdown()


def test_cli_version_and_dashboard_entry(capsys):
    from daft_tpu.cli import main
    assert main(["version"]) == 0
    out = capsys.readouterr().out.strip()
    assert out == daft_tpu_version()


def daft_tpu_version():
    import daft_tpu
    return daft_tpu.__version__


def test_xplane_trace_captures_per_query(tmp_path, monkeypatch):
    """DAFT_TPU_XPLANE_DIR captures a jax profiler trace around query
    execution (the TPU-native analogue of the reference's chrome-trace
    layer) without disturbing results."""
    import os
    import daft_tpu
    from daft_tpu import col

    monkeypatch.setenv("DAFT_TPU_XPLANE_DIR", str(tmp_path))
    out = daft_tpu.from_pydict({"x": list(range(100))}) \
        .where(col("x") % 2 == 0).count_rows()
    assert out == 50
    # a profile directory materialized with at least one artifact
    found = []
    for root, _, files in os.walk(tmp_path):
        found.extend(files)
    assert found, "no xplane trace artifacts written"


def test_otlp_export_posts_operator_counters(monkeypatch):
    """Per-op counters export as OTLP/HTTP JSON metrics when
    DAFT_TPU_OTLP_ENDPOINT is set (reference: common/tracing OTLP export,
    runtime_stats.rs)."""
    import http.server
    import json
    import threading

    import daft_tpu
    from daft_tpu import col

    received = []
    done = threading.Event()

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            body = self.rfile.read(int(self.headers["Content-Length"]))
            received.append((self.path, json.loads(body)))
            self.send_response(200)
            self.end_headers()
            self.wfile.write(b"{}")
            done.set()

        def log_message(self, *a):
            pass

    srv = http.server.HTTPServer(("127.0.0.1", 0), Handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        monkeypatch.setenv("DAFT_TPU_OTLP_ENDPOINT",
                           f"http://127.0.0.1:{srv.server_port}")
        out = (daft_tpu.from_pydict({"k": [1, 1, 2], "v": [1.0, 2.0, 3.0]})
               .groupby("k").agg(col("v").sum().alias("s"))
               .sort("k").to_pydict())
        assert out["k"] == [1, 2]
        assert done.wait(10), "no OTLP POST arrived"
    finally:
        srv.shutdown()
    path, payload = received[0]
    assert path == "/v1/metrics"
    scope = payload["resourceMetrics"][0]["scopeMetrics"][0]
    names = {m["name"] for m in scope["metrics"]}
    assert names == {"daft_tpu.operator.rows_out",
                     "daft_tpu.operator.batches_out",
                     "daft_tpu.operator.cpu_us"}
    rows = next(m for m in scope["metrics"]
                if m["name"] == "daft_tpu.operator.rows_out")
    ops = {a["value"]["stringValue"]
           for p in rows["sum"]["dataPoints"]
           for a in p["attributes"] if a["key"] == "operator"}
    assert any("Aggregate" in o or "Agg" in o for o in ops), ops
