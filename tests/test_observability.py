"""Runtime stats / chrome trace / explain-analyze tests
(reference model: runtime_stats.rs, common/tracing, tests/observability/)."""

import json
import os

import pytest

import daft_tpu as daft
from daft_tpu import col
from daft_tpu import observability as obs


def test_runtime_stats_collected():
    df = (daft.from_pydict({"x": list(range(1000)), "g": [i % 10 for i in range(1000)]})
          .where(col("x") > 99)
          .groupby("g").agg(col("x").sum().alias("s")))
    df.collect()
    stats = obs.last_query_stats()
    assert stats is not None
    d = stats.as_dict()
    assert stats.wall_us is not None and stats.wall_us > 0
    # source emits all 1000 rows; final agg emits 10 groups
    src = [v for k, v in d.items() if "Source" in k]
    assert src and src[0]["rows_out"] == 1000
    root = [v for k, v in d.items() if "Agg" in k]
    assert any(v["rows_out"] == 10 for v in root)


def test_runtime_stats_unfused_filter():
    df = daft.from_pydict({"x": list(range(1000))}).where(col("x") > 99)
    df.collect()
    d = obs.last_query_stats().as_dict()
    filters = [v for k, v in d.items() if k.startswith("Filter")]
    assert filters and filters[0]["rows_out"] == 900


def test_explain_analyze_renders(capsys):
    df = daft.from_pydict({"x": [1, 2, 3]}).where(col("x") > 1)
    df.explain(analyze=True)
    out = capsys.readouterr().out
    assert "rows_out=2" in out
    assert "query wall time" in out


def test_chrome_trace_written(tmp_path, monkeypatch):
    path = str(tmp_path / "trace.json")
    monkeypatch.setenv("DAFT_TPU_CHROME_TRACE", path)
    df = daft.from_pydict({"x": list(range(100))}).where(col("x") % 2 == 0)
    df.collect()
    with open(path) as f:
        trace = json.load(f)
    names = {e["name"] for e in trace["traceEvents"]}
    assert "Filter" in names
    for e in trace["traceEvents"]:
        assert {"name", "ph", "ts", "dur", "pid", "tid"} <= set(e)


def test_stats_exclusive_time_nonneg():
    df = daft.from_pydict({"x": list(range(500))}).with_column(
        "y", col("x") * 2).where(col("y") > 10)
    df.collect()
    stats = obs.last_query_stats()
    for v in stats.as_dict().values():
        assert v["exclusive_us"] >= 0
        assert v["inclusive_us"] >= v["exclusive_us"]


def test_explain_analyze_not_stale(capsys):
    df1 = daft.from_pydict({"x": [1, 2, 3]}).where(col("x") > 1)
    df1.collect()
    # another query runs afterwards…
    daft.from_pydict({"y": list(range(50))}).where(col("y") > 10).collect()
    # …but df1's analysis must show df1's stats (2 rows), not the later query's
    df1.explain(analyze=True)
    out = capsys.readouterr().out
    assert "rows_out=2" in out and "rows_out=39" not in out
