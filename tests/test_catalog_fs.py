"""Filesystem lake catalog: auto-detected Iceberg/Delta/Hudi/parquet tables
under a warehouse root, attached to a Session and queryable by SQL name
(reference capability: the catalog adapters in ``daft/catalog/``)."""

import pytest

import daft_tpu
from daft_tpu import Session, col
from daft_tpu.catalog import NotFoundError
from daft_tpu.catalog_fs import FilesystemCatalog


@pytest.fixture
def warehouse(tmp_path):
    root = tmp_path / "wh"
    (root / "sales").mkdir(parents=True)
    daft_tpu.from_pydict({"k": [1, 2], "v": [10.0, 20.0]}) \
        .write_iceberg(str(root / "sales" / "orders"))
    from daft_tpu.io.delta import write_deltalake
    write_deltalake(daft_tpu.from_pydict({"c": ["a", "b"]}),
                    str(root / "sales" / "customers"))
    daft_tpu.from_pydict({"p": [7]}) \
        .write_parquet(str(root / "raw_events"))
    return root


def test_list_and_detect_formats(warehouse):
    from daft_tpu.catalog import Identifier
    cat = FilesystemCatalog(str(warehouse), name="lake")
    tables = {str(t) for t in cat._list_tables()}
    assert tables == {"sales.orders", "sales.customers", "raw_events"}
    t = cat._get_table(Identifier("sales", "orders"))
    assert t.format == "iceberg"


def test_read_through_catalog(warehouse):
    from daft_tpu.catalog import Identifier
    cat = FilesystemCatalog(str(warehouse))
    t = cat._get_table(Identifier("sales", "orders"))
    assert t.read().sort("k").to_pydict() == {"k": [1, 2], "v": [10.0, 20.0]}
    t2 = cat._get_table(Identifier("sales", "customers"))
    assert t2.format == "delta"
    assert sorted(t2.read().to_pydict()["c"]) == ["a", "b"]
    t3 = cat._get_table(Identifier("raw_events"))
    assert t3.format == "parquet"
    assert t3.read().to_pydict() == {"p": [7]}


def test_sql_over_attached_catalog(warehouse):
    sess = Session()
    sess.attach(FilesystemCatalog(str(warehouse), name="lake"))
    out = sess.sql("SELECT k, v * 2 AS v2 FROM lake.sales.orders "
                   "ORDER BY k").to_pydict()
    assert out == {"k": [1, 2], "v2": [20.0, 40.0]}


def test_create_append_drop_roundtrip(warehouse):
    from daft_tpu.catalog import Identifier
    from daft_tpu.schema import Field, Schema
    from daft_tpu.datatype import DataType
    cat = FilesystemCatalog(str(warehouse))
    ident = Identifier("sales", "new_tbl")
    t = cat._create_table(ident, Schema([Field("x", DataType.int64())]))
    assert t.format == "iceberg"
    t.append(daft_tpu.from_pydict({"x": [5, 6]}))
    assert sorted(cat._get_table(ident).read().to_pydict()["x"]) == [5, 6]
    t.overwrite(daft_tpu.from_pydict({"x": [9]}))
    assert cat._get_table(ident).read().to_pydict()["x"] == [9]
    cat._drop_table(ident)
    with pytest.raises(NotFoundError):
        cat._get_table(ident)


def test_namespaces(warehouse):
    from daft_tpu.catalog import Identifier
    cat = FilesystemCatalog(str(warehouse))
    assert Identifier("sales") in cat._list_namespaces()
    cat._create_namespace(Identifier("marketing"))
    assert cat._has_namespace(Identifier("marketing"))
    cat._drop_namespace(Identifier("marketing"))
    assert not cat._has_namespace(Identifier("marketing"))
