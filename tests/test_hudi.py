"""Native Hudi CoW snapshot reader (reference: ``daft/io/_hudi.py``): the
fixture writes Hudi's on-disk anatomy by hand — .hoodie timeline, base-file
naming — so the reader's timeline filtering, file-slice resolution and
replacecommit handling are exercised without the SDK."""

import json

import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import daft_tpu
from daft_tpu.io.hudi import snapshot_files


def _write_base_file(root, partition, file_id, instant, table):
    d = root / partition if partition else root
    d.mkdir(parents=True, exist_ok=True)
    pq.write_table(table, d / f"{file_id}_0-1-0_{instant}.parquet")


def _commit(root, instant, action="commit", body=None):
    h = root / ".hoodie"
    h.mkdir(parents=True, exist_ok=True)
    (h / f"{instant}.{action}").write_text(json.dumps(body or {}))


def _props(root, ttype="COPY_ON_WRITE"):
    h = root / ".hoodie"
    h.mkdir(parents=True, exist_ok=True)
    (h / "hoodie.properties").write_text(
        f"hoodie.table.name=t\nhoodie.table.type={ttype}\n")


def test_latest_file_slice_per_group(tmp_path):
    root = tmp_path / "tbl"
    _props(root)
    _write_base_file(root, "", "fg1", "100", pa.table({"x": [1, 2]}))
    _commit(root, "100")
    # fg1 rewritten at instant 200 (upsert): only the newer slice is live
    _write_base_file(root, "", "fg1", "200", pa.table({"x": [1, 2, 3]}))
    _write_base_file(root, "", "fg2", "200", pa.table({"x": [9]}))
    _commit(root, "200")
    files = snapshot_files(str(root))
    assert sorted(f["file_id"] for f in files) == ["fg1", "fg2"]
    assert {f["file_id"]: f["instant"] for f in files}["fg1"] == "200"
    out = daft_tpu.read_hudi(str(root)).to_pydict()
    assert sorted(out["x"]) == [1, 2, 3, 9]


def test_uncommitted_files_invisible(tmp_path):
    root = tmp_path / "tbl"
    _props(root)
    _write_base_file(root, "", "fg1", "100", pa.table({"x": [1]}))
    _commit(root, "100")
    # instant 200 wrote a file but never committed (crashed writer)
    _write_base_file(root, "", "fg1", "200", pa.table({"x": [666]}))
    out = daft_tpu.read_hudi(str(root)).to_pydict()
    assert out["x"] == [1]


def test_partitioned_table(tmp_path):
    root = tmp_path / "tbl"
    _props(root)
    _write_base_file(root, "dt=2024-01-01", "a", "100",
                     pa.table({"x": [1], "dt": ["2024-01-01"]}))
    _write_base_file(root, "dt=2024-01-02", "b", "100",
                     pa.table({"x": [2], "dt": ["2024-01-02"]}))
    _commit(root, "100")
    files = snapshot_files(str(root))
    assert {f["partition"] for f in files} == \
        {"dt=2024-01-01", "dt=2024-01-02"}
    out = daft_tpu.read_hudi(str(root)).to_pydict()
    assert sorted(out["x"]) == [1, 2]


def test_replacecommit_retires_file_groups(tmp_path):
    root = tmp_path / "tbl"
    _props(root)
    _write_base_file(root, "", "old1", "100", pa.table({"x": [1]}))
    _write_base_file(root, "", "old2", "100", pa.table({"x": [2]}))
    _commit(root, "100")
    # clustering: old1+old2 replaced by one new file group
    _write_base_file(root, "", "newc", "200", pa.table({"x": [1, 2]}))
    _commit(root, "200", action="replacecommit",
            body={"partitionToReplaceFileIds": {"": ["old1", "old2"]}})
    files = snapshot_files(str(root))
    assert [f["file_id"] for f in files] == ["newc"]
    out = daft_tpu.read_hudi(str(root)).to_pydict()
    assert sorted(out["x"]) == [1, 2]


def test_binary_log_format_rejected_clearly(tmp_path):
    """The documented subset: real HoodieLogFormat binary framing raises
    a clear error instead of mis-parsing."""
    root = tmp_path / "tbl"
    _props(root, ttype="MERGE_ON_READ")
    (root / ".hoodie").mkdir(parents=True, exist_ok=True)
    (root / ".hoodie" / "hoodie.properties").write_text(
        "hoodie.table.name=t\nhoodie.table.type=MERGE_ON_READ\n"
        "hoodie.table.recordkey.fields=id\n")
    _write_base_file(root, "", "fg1", "100", pa.table({"id": [1]}))
    _commit(root, "100")
    (root / ".fg1_100.log.1_0-1-0").write_bytes(b"#HUDI#" + b"\x00" * 32)
    _commit(root, "200", action="deltacommit")
    with pytest.raises(NotImplementedError, match="HoodieLogFormat"):
        daft_tpu.read_hudi(str(root)).to_pydict()


# -------------------------------------------------------- Merge-on-Read

def _props_mor(root, record_key="id"):
    h = root / ".hoodie"
    h.mkdir(parents=True, exist_ok=True)
    (h / "hoodie.properties").write_text(
        "hoodie.table.name=t\nhoodie.table.type=MERGE_ON_READ\n"
        f"hoodie.table.recordkey.fields={record_key}\n")


def _write_log_file(root, partition, file_id, base_instant, version, table):
    d = root / partition if partition else root
    d.mkdir(parents=True, exist_ok=True)
    p = d / f".{file_id}_{base_instant}.log.{version}_0-1-0"
    pq.write_table(table, p)


def test_mor_snapshot_merges_log_upserts_and_deletes(tmp_path):
    root = tmp_path / "mor"
    _props_mor(root)
    base = pa.table({"id": [1, 2, 3], "v": ["a", "b", "c"],
                     "_hoodie_is_deleted": [False] * 3})
    _write_base_file(root, "", "fg1", "100", base)
    _commit(root, "100")
    # deltacommit 200: upsert id=2, delete id=3, insert id=4
    log1 = pa.table({"id": [2, 3, 4], "v": ["B", "c", "d"],
                     "_hoodie_is_deleted": [False, True, False]})
    _write_log_file(root, "", "fg1", "100", 1, log1)
    _commit(root, "200", action="deltacommit")
    # deltacommit 300: re-upsert id=2 again (later log wins)
    log2 = pa.table({"id": [2], "v": ["B2"],
                     "_hoodie_is_deleted": [False]})
    _write_log_file(root, "", "fg1", "100", 2, log2)
    _commit(root, "300", action="deltacommit")

    out = daft_tpu.read_hudi(str(root)).sort("id").to_pydict()
    assert out["id"] == [1, 2, 4]
    assert out["v"] == ["a", "B2", "d"]

    ro = daft_tpu.read_hudi(str(root), query_type="read_optimized") \
        .sort("id").to_pydict()
    assert ro["id"] == [1, 2, 3]  # base files only
    assert ro["v"] == ["a", "b", "c"]


def test_mor_log_only_file_group(tmp_path):
    root = tmp_path / "mor2"
    _props_mor(root)
    base = pa.table({"id": [1], "v": ["a"]})
    _write_base_file(root, "", "fg1", "100", base)
    _commit(root, "100")
    # a file group born from inserts that has no base file yet
    log = pa.table({"id": [10, 11], "v": ["x", "y"]})
    _write_log_file(root, "", "fg9", "100", 1, log)
    _commit(root, "200", action="deltacommit")
    out = daft_tpu.read_hudi(str(root)).sort("id").to_pydict()
    assert out["id"] == [1, 10, 11]
    assert out["v"] == ["a", "x", "y"]


def test_mor_avro_log_blocks(tmp_path):
    from daft_tpu.io.avro import write_avro
    root = tmp_path / "mor3"
    _props_mor(root)
    base = pa.table({"id": [1, 2], "v": ["a", "b"]})
    _write_base_file(root, "", "fg1", "100", base)
    _commit(root, "100")
    schema = {"type": "record", "name": "r", "fields": [
        {"name": "id", "type": "long"},
        {"name": "v", "type": ["null", "string"]},
        {"name": "_hoodie_is_deleted", "type": "boolean"}]}
    blob = write_avro(schema, [
        {"id": 1, "v": "A", "_hoodie_is_deleted": False},
        {"id": 2, "v": None, "_hoodie_is_deleted": True}])
    p = root / ".fg1_100.log.1_0-1-0"
    p.write_bytes(blob)
    _commit(root, "200", action="deltacommit")
    out = daft_tpu.read_hudi(str(root)).sort("id").to_pydict()
    assert out["id"] == [1]
    assert out["v"] == ["A"]


def test_mor_uncommitted_log_invisible(tmp_path):
    """A log file whose deltacommit never completed (crashed writer) must
    not leak into the snapshot."""
    root = tmp_path / "mor4"
    _props_mor(root)
    _write_base_file(root, "", "fg1", "100",
                     pa.table({"id": [1], "v": ["a"]}))
    _commit(root, "100")
    # log written, but the 200.deltacommit never landed
    _write_log_file(root, "", "fg1", "100", 1,
                    pa.table({"id": [2], "v": ["DIRTY"]}))
    out = daft_tpu.read_hudi(str(root)).to_pydict()
    assert out == {"id": [1], "v": ["a"]}


def test_mor_write_stats_filter_logs_precisely(tmp_path):
    """With partitionToWriteStats in the commit metadata, only listed log
    files are live — even when a later unrelated deltacommit completed."""
    root = tmp_path / "mor5"
    _props_mor(root)
    _write_base_file(root, "", "fg1", "100",
                     pa.table({"id": [1], "v": ["a"]}))
    _commit(root, "100", body={"partitionToWriteStats": {
        "": [{"path": "fg1_0-1-0_100.parquet"}]}})
    _write_log_file(root, "", "fg1", "100", 1,
                    pa.table({"id": [2], "v": ["ok"]}))
    _commit(root, "200", action="deltacommit",
            body={"partitionToWriteStats": {
                "": [{"path": ".fg1_100.log.1_0-1-0"}]}})
    # crashed writer's log, never referenced by any commit
    _write_log_file(root, "", "fg1", "100", 2,
                    pa.table({"id": [3], "v": ["DIRTY"]}))
    out = daft_tpu.read_hudi(str(root)).sort("id").to_pydict()
    assert out == {"id": [1, 2], "v": ["a", "ok"]}
