"""Native Hudi CoW snapshot reader (reference: ``daft/io/_hudi.py``): the
fixture writes Hudi's on-disk anatomy by hand — .hoodie timeline, base-file
naming — so the reader's timeline filtering, file-slice resolution and
replacecommit handling are exercised without the SDK."""

import json

import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import daft_tpu
from daft_tpu.io.hudi import snapshot_files


def _write_base_file(root, partition, file_id, instant, table):
    d = root / partition if partition else root
    d.mkdir(parents=True, exist_ok=True)
    pq.write_table(table, d / f"{file_id}_0-1-0_{instant}.parquet")


def _commit(root, instant, action="commit", body=None):
    h = root / ".hoodie"
    h.mkdir(parents=True, exist_ok=True)
    (h / f"{instant}.{action}").write_text(json.dumps(body or {}))


def _props(root, ttype="COPY_ON_WRITE"):
    h = root / ".hoodie"
    h.mkdir(parents=True, exist_ok=True)
    (h / "hoodie.properties").write_text(
        f"hoodie.table.name=t\nhoodie.table.type={ttype}\n")


def test_latest_file_slice_per_group(tmp_path):
    root = tmp_path / "tbl"
    _props(root)
    _write_base_file(root, "", "fg1", "100", pa.table({"x": [1, 2]}))
    _commit(root, "100")
    # fg1 rewritten at instant 200 (upsert): only the newer slice is live
    _write_base_file(root, "", "fg1", "200", pa.table({"x": [1, 2, 3]}))
    _write_base_file(root, "", "fg2", "200", pa.table({"x": [9]}))
    _commit(root, "200")
    files = snapshot_files(str(root))
    assert sorted(f["file_id"] for f in files) == ["fg1", "fg2"]
    assert {f["file_id"]: f["instant"] for f in files}["fg1"] == "200"
    out = daft_tpu.read_hudi(str(root)).to_pydict()
    assert sorted(out["x"]) == [1, 2, 3, 9]


def test_uncommitted_files_invisible(tmp_path):
    root = tmp_path / "tbl"
    _props(root)
    _write_base_file(root, "", "fg1", "100", pa.table({"x": [1]}))
    _commit(root, "100")
    # instant 200 wrote a file but never committed (crashed writer)
    _write_base_file(root, "", "fg1", "200", pa.table({"x": [666]}))
    out = daft_tpu.read_hudi(str(root)).to_pydict()
    assert out["x"] == [1]


def test_partitioned_table(tmp_path):
    root = tmp_path / "tbl"
    _props(root)
    _write_base_file(root, "dt=2024-01-01", "a", "100",
                     pa.table({"x": [1], "dt": ["2024-01-01"]}))
    _write_base_file(root, "dt=2024-01-02", "b", "100",
                     pa.table({"x": [2], "dt": ["2024-01-02"]}))
    _commit(root, "100")
    files = snapshot_files(str(root))
    assert {f["partition"] for f in files} == \
        {"dt=2024-01-01", "dt=2024-01-02"}
    out = daft_tpu.read_hudi(str(root)).to_pydict()
    assert sorted(out["x"]) == [1, 2]


def test_replacecommit_retires_file_groups(tmp_path):
    root = tmp_path / "tbl"
    _props(root)
    _write_base_file(root, "", "old1", "100", pa.table({"x": [1]}))
    _write_base_file(root, "", "old2", "100", pa.table({"x": [2]}))
    _commit(root, "100")
    # clustering: old1+old2 replaced by one new file group
    _write_base_file(root, "", "newc", "200", pa.table({"x": [1, 2]}))
    _commit(root, "200", action="replacecommit",
            body={"partitionToReplaceFileIds": {"": ["old1", "old2"]}})
    files = snapshot_files(str(root))
    assert [f["file_id"] for f in files] == ["newc"]
    out = daft_tpu.read_hudi(str(root)).to_pydict()
    assert sorted(out["x"]) == [1, 2]


def test_merge_on_read_rejected(tmp_path):
    root = tmp_path / "tbl"
    _props(root, ttype="MERGE_ON_READ")
    with pytest.raises(NotImplementedError, match="Copy-on-Write"):
        snapshot_files(str(root))
