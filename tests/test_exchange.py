"""Mesh-collective exchange: correctness of the ICI all_to_all shuffle paths
on the virtual 8-device CPU mesh (reference seam: the four ShuffleExchange
strategies, ``src/daft-physical-plan/src/ops/shuffle_exchange.rs:41-58``).

These run through the public DataFrame API so the plan-time gating
(``physical/translate.py:_try_mesh_exchange_agg``) and the executor paths
(``_exec_DeviceExchangeAgg`` / ``_mesh_hash_repartition``) are what's under
test, with host-tier runs as the oracle.
"""

import os

import numpy as np
import pytest

import daft_tpu
from daft_tpu import col
from daft_tpu.parallel import exchange, mesh as pmesh
from daft_tpu.physical import plan as pp, translate as pt


@pytest.fixture(autouse=True)
def _device_on(monkeypatch):
    monkeypatch.setenv("DAFT_TPU_DEVICE", "1")
    # these tests exist to exercise the mesh path at toy sizes; disable
    # the row-count admission gate that would (correctly) route tiny
    # aggregations to the host exchange in production
    monkeypatch.setenv("DAFT_TPU_MESH_MIN_ROWS", "0")
    yield


def _oracle(df_fn):
    """Run the same query host-tier (mesh disabled) as the oracle."""
    os.environ["DAFT_TPU_DEVICE"] = "0"
    try:
        return df_fn()
    finally:
        os.environ["DAFT_TPU_DEVICE"] = "1"


def _sorted_pydict(df, keys):
    out = df.sort([col(k) for k in keys]).to_pydict()
    return out


def test_mesh_is_up():
    assert pmesh.mesh_size() >= 8


def test_plan_chooses_device_exchange_agg():
    df = daft_tpu.from_pydict(
        {"k": list(range(100)), "v": [float(i) for i in range(100)]})
    builder = df.groupby("k").agg(col("v").sum())._builder.optimize()
    phys = pt.translate(builder.plan)

    def find(node, t):
        if isinstance(node, t):
            return node
        for c in node.children:
            r = find(c, t)
            if r is not None:
                return r
        return None

    assert find(phys, pp.DeviceExchangeAgg) is not None


def test_groupby_sum_through_mesh_exchange():
    rng = np.random.default_rng(7)
    n = 5000
    keys = rng.integers(0, 37, n)
    vals = rng.uniform(-100, 100, n)
    df = daft_tpu.from_pydict({"k": keys.tolist(), "v": vals.tolist()})
    got = _sorted_pydict(
        df.groupby("k").agg(col("v").sum().alias("s"),
                            col("v").min().alias("lo"),
                            col("v").max().alias("hi")), ["k"])
    expect = {}
    for k, v in zip(keys, vals):
        e = expect.setdefault(int(k), [0.0, np.inf, -np.inf])
        e[0] += v
        e[1] = min(e[1], v)
        e[2] = max(e[2], v)
    assert got["k"] == sorted(expect)
    for i, k in enumerate(got["k"]):
        assert got["s"][i] == pytest.approx(expect[k][0], rel=1e-9)
        assert got["lo"][i] == pytest.approx(expect[k][1])
        assert got["hi"][i] == pytest.approx(expect[k][2])


def test_groupby_mean_count_through_mesh_exchange():
    rng = np.random.default_rng(11)
    n = 3000
    keys = rng.integers(0, 11, n)
    vals = rng.uniform(0, 10, n)
    nulls = rng.random(n) < 0.1
    vlist = [None if m else float(v) for v, m in zip(vals, nulls)]
    df = daft_tpu.from_pydict({"k": keys.tolist(), "v": vlist})
    q = lambda d: _sorted_pydict(
        d.groupby("k").agg(col("v").mean().alias("m"),
                           col("v").count().alias("c")), ["k"])
    got = q(df)
    want = _oracle(lambda: q(df))
    assert got["k"] == want["k"]
    assert got["c"] == want["c"]
    for a, b in zip(got["m"], want["m"]):
        assert a == pytest.approx(b, rel=1e-9)


def test_groupby_multi_key_through_mesh_exchange():
    rng = np.random.default_rng(3)
    n = 2000
    k1 = rng.integers(0, 5, n)
    k2 = rng.integers(0, 7, n)
    v = rng.integers(0, 1000, n)
    df = daft_tpu.from_pydict({"a": k1.tolist(), "b": k2.tolist(),
                               "v": v.tolist()})
    q = lambda d: _sorted_pydict(
        d.groupby("a", "b").agg(col("v").sum().alias("s")), ["a", "b"])
    got = q(df)
    want = _oracle(lambda: q(df))
    assert got == want


def test_string_keys_through_mesh_exchange():
    """String group keys ride SHARED-dictionary codes through the mesh
    exchange (r5): the executor concats all partitions into one batch
    before encoding, so codes are comparable — and rank-ordered — across
    shards. The plan must choose DeviceExchangeAgg and match the host."""
    df = daft_tpu.from_pydict({"k": ["x", "y", "x", "z", None] * 50,
                               "v": list(range(250))})
    builder = df.groupby("k").agg(col("v").sum().alias("s")) \
        ._builder.optimize()
    phys = pt.translate(builder.plan)

    def has(node, t):
        return isinstance(node, t) or any(has(c, t) for c in node.children)
    assert has(phys, pp.DeviceExchangeAgg), \
        "string keys no longer lower onto the mesh exchange"
    q = lambda d: _sorted_pydict(
        d.groupby("k").agg(col("v").sum().alias("s")), ["k"])
    got = q(df)
    want = _oracle(lambda: q(df))
    assert got == want


def test_string_min_max_through_mesh_exchange():
    """min/max over STRING VALUES: dictionary codes are rank codes over
    the sorted dictionary, so code order is lexicographic order."""
    df = daft_tpu.from_pydict({
        "g": [i % 4 for i in range(200)],
        "s": [f"w{i % 23:03d}" for i in range(200)]})
    q = lambda d: _sorted_pydict(
        d.groupby("g").agg(col("s").min().alias("lo"),
                           col("s").max().alias("hi")), ["g"])
    got = q(df)
    want = _oracle(lambda: q(df))
    assert got == want


def test_mesh_range_partitioned_sort():
    """Range repartition = the same routing collective fed a
    searchsorted(boundaries) pid plane; local sort per shard must yield a
    globally ordered concatenation (the distributed sort composition)."""
    import jax
    mesh = pmesh.get_mesh()
    n = pmesh.mesh_size()
    rng = np.random.default_rng(11)
    C = 64
    skeys = rng.uniform(0, 1000, n * C)
    boundaries = np.quantile(skeys, [i / n for i in range(1, n)])
    pid = np.searchsorted(boundaries, skeys).astype(np.int32)
    ones = np.ones(n * C, dtype=bool)
    (pk2,), _, m2 = exchange.sharded_hash_repartition(
        mesh, (exchange.shard_blocks(mesh, skeys),),
        (exchange.shard_blocks(mesh, ones),),
        exchange.shard_blocks(mesh, ones),
        exchange.shard_blocks(mesh, pid))
    pk2, m2 = map(np.asarray, jax.device_get((pk2, m2)))
    shard_len = pk2.shape[0] // n
    merged = np.concatenate([
        np.sort(pk2[i * shard_len:(i + 1) * shard_len]
                [m2[i * shard_len:(i + 1) * shard_len]])
        for i in range(n)])
    assert merged.shape[0] == n * C
    assert np.all(np.diff(merged) >= 0)
    np.testing.assert_allclose(merged, np.sort(skeys))


def test_broadcast_join_collective():
    """Sharded probe side × replicated build side, no all_to_all."""
    import jax
    import jax.numpy as jnp
    mesh = pmesh.get_mesh()
    n = pmesh.mesh_size()
    rng = np.random.default_rng(5)
    C = 32
    lkeys = rng.integers(0, 16, n * C).astype(np.int64)
    rkeys = np.arange(0, 16, 2, dtype=np.int64)
    ones_l = np.ones(n * C, dtype=bool)
    ones_r = np.ones(rkeys.shape[0], dtype=bool)
    out_cap = 2 * C
    li, ri, ok = map(np.asarray, jax.device_get(
        exchange.sharded_broadcast_join(
            mesh, exchange.shard_blocks(mesh, lkeys),
            exchange.shard_blocks(mesh, ones_l),
            exchange.shard_blocks(mesh, ones_l),
            jnp.asarray(rkeys), jnp.asarray(ones_r), jnp.asarray(ones_r),
            out_cap)))
    matched = 0
    for i in range(n):
        sl = slice(i * out_cap, (i + 1) * out_cap)
        for lo, ro, good in zip(li[sl], ri[sl], ok[sl]):
            if good:
                assert lkeys[i * C + lo] == rkeys[ro]
                matched += 1
    assert matched == int(np.isin(lkeys, rkeys).sum())


def test_window_over_mesh_exchange():
    """partition_by repartition rides the mesh all_to_all, then the window
    runs per partition — engine path with a repartition spy."""
    from daft_tpu.execution import executor as ex_mod
    n = pmesh.mesh_size()
    rng = np.random.default_rng(9)
    keys = rng.integers(0, 8, 400)
    vals = rng.uniform(0, 10, 400)
    calls = {"n": 0}
    orig = ex_mod.LocalExecutor._mesh_hash_repartition

    def spy(self, parts, by, k):
        out = orig(self, parts, by, k)
        if out is not None:
            calls["n"] += 1
        return out
    ex_mod.LocalExecutor._mesh_hash_repartition = spy
    try:
        df = daft_tpu.from_pydict({"k": keys.tolist(), "v": vals.tolist()}) \
            .repartition(n, "k")
        out = df.select(
            col("k"), col("v"),
            col("v").sum().over(daft_tpu.Window().partition_by("k"))
            .alias("tot")).sort([col("k"), col("v")]).to_pydict()
    finally:
        ex_mod.LocalExecutor._mesh_hash_repartition = orig
    assert calls["n"] >= 1
    expect = {}
    for k, v in zip(keys, vals):
        expect[int(k)] = expect.get(int(k), 0.0) + float(v)
    for k, tot in zip(out["k"], out["tot"]):
        assert tot == pytest.approx(expect[k])


def test_repartition_hash_through_mesh():
    n = pmesh.mesh_size()
    df = daft_tpu.from_pydict({"k": list(range(1000)),
                               "v": [i * 0.5 for i in range(1000)]})
    parts = df.repartition(n, col("k"))
    assert parts.num_partitions() == n
    out = parts.to_pydict()
    assert sorted(out["k"]) == list(range(1000))
    # same key → same partition: groupby after repartition stays correct
    got = _sorted_pydict(
        parts.groupby("k").agg(col("v").sum().alias("s")), ["k"])
    assert got["k"] == list(range(1000))
    assert got["s"] == [i * 0.5 for i in range(1000)]


def test_all_to_all_by_hash_collective():
    """Direct kernel-level check of the all_to_all bucket exchange."""
    import jax
    from functools import partial
    from jax.sharding import PartitionSpec as P
    import jax.numpy as jnp
    from daft_tpu.parallel.exchange import shard_map_compat

    mesh = pmesh.get_mesh()
    n = pmesh.mesh_size()
    rng = np.random.default_rng(0)
    C = 32
    keys = rng.integers(0, 1000, n * C).astype(np.int32)
    vals = (keys * 10).astype(np.int32)
    mask = np.ones(n * C, dtype=bool)

    @partial(shard_map_compat, mesh=mesh, in_specs=(P("data"),) * 3,
             out_specs=(P("data"),) * 3, check_vma=False)
    def run(k, v, m):
        k, v, m = k.reshape(-1), v.reshape(-1), m.reshape(-1)
        k2, (v2,), m2 = exchange.all_to_all_by_hash(k, (v,), m, n, "data")
        return k2, v2, m2

    k2, v2, m2 = map(np.asarray, jax.device_get(run(
        exchange.shard_blocks(mesh, keys), exchange.shard_blocks(mesh, vals),
        exchange.shard_blocks(mesh, mask))))
    # every live row survives exactly once, payload stays aligned
    assert m2.sum() == n * C
    assert sorted(k2[m2].tolist()) == sorted(keys.tolist())
    assert (v2[m2] == k2[m2] * 10).all()
    # rows are routed by hash(key) % n
    shard_len = len(k2) // n
    for i in range(n):
        sl = slice(i * shard_len, (i + 1) * shard_len)
        got_keys = k2[sl][m2[sl]]
        h = np.asarray(jax.device_get(
            exchange._hash_u32(jnp.asarray(got_keys)))) % n
        assert (h == i).all()
