"""SQL DDL/DML statements through the session (reference:
``src/daft-sql``'s statement layer + ``exec.rs``: CREATE TABLE AS,
INSERT INTO, DROP TABLE, SHOW TABLES, DESCRIBE, USE)."""

import pytest

import daft_tpu
from daft_tpu import Session, col
from daft_tpu.catalog_fs import FilesystemCatalog


@pytest.fixture
def sess():
    return Session()


def test_create_temp_table_as_and_query(sess):
    sess.create_temp_table("src", daft_tpu.from_pydict(
        {"x": [1, 2, 3, 4]}))
    sess.sql("CREATE TEMP TABLE doubled AS SELECT x * 2 AS y FROM src")
    out = sess.sql("SELECT SUM(y) AS s FROM doubled").to_pydict()
    assert out["s"] == [20]
    # plain CREATE TEMP TABLE on an existing name errors; OR REPLACE works
    with pytest.raises(ValueError, match="already exists"):
        sess.sql("CREATE TEMP TABLE doubled AS SELECT 1 AS a")
    sess.sql("CREATE OR REPLACE TEMP TABLE doubled AS SELECT 1 AS a")
    assert sess.sql("SELECT * FROM doubled").to_pydict() == {"a": [1]}


def test_create_temp_if_not_exists_is_noop(sess):
    sess.sql("CREATE TEMP TABLE t AS SELECT 1 AS x UNION ALL SELECT 2 AS x")
    # IF NOT EXISTS preserves the existing table (regression: it used to
    # silently overwrite)
    sess.sql("CREATE TEMP TABLE IF NOT EXISTS t AS SELECT 99 AS x")
    out = sess.sql("SELECT x FROM t ORDER BY x").to_pydict()
    assert out["x"] == [1, 2]


def test_show_tables_like_wildcards(sess):
    sess.sql("CREATE TEMP TABLE foo_log AS SELECT 1 AS x")
    sess.sql("CREATE TEMP TABLE bar AS SELECT 1 AS x")
    got = sess.sql("SHOW TABLES LIKE '%log'").to_pydict()["table"]
    assert got == ["foo_log"]


def test_insert_into_temp_table(sess):
    sess.sql("CREATE TEMP TABLE t AS SELECT 1 AS x")
    sess.sql("INSERT INTO t SELECT 2 AS x")
    out = sess.sql("SELECT x FROM t ORDER BY x").to_pydict()
    assert out["x"] == [1, 2]


def test_drop_and_show_tables(sess):
    sess.sql("CREATE TEMP TABLE a AS SELECT 1 AS x")
    sess.sql("CREATE TEMP TABLE b AS SELECT 2 AS x")
    names = sess.sql("SHOW TABLES").to_pydict()["table"]
    assert set(names) >= {"a", "b"}
    sess.sql("DROP TABLE a")
    assert "a" not in sess.sql("SHOW TABLES").to_pydict()["table"]
    with pytest.raises(Exception):
        sess.sql("DROP TABLE a")
    sess.sql("DROP TABLE IF EXISTS a")  # no error


def test_describe(sess):
    sess.sql("CREATE TEMP TABLE t AS SELECT 1 AS x, 'a' AS s")
    out = sess.sql("DESCRIBE t").to_pydict()
    assert out["column"] == ["x", "s"]
    assert "int" in out["type"][0].lower()


def test_catalog_create_insert_roundtrip(tmp_path, sess):
    (tmp_path / "wh").mkdir()
    sess.attach(FilesystemCatalog(str(tmp_path / "wh"), name="lake"))
    sess.create_temp_table("src", daft_tpu.from_pydict(
        {"k": [1, 2], "v": [10.0, 20.0]}))
    sess.sql("CREATE TABLE lake.sales AS SELECT * FROM src")
    sess.sql("INSERT INTO lake.sales SELECT 3 AS k, 30.0 AS v")
    out = sess.sql("SELECT k, v FROM lake.sales ORDER BY k").to_pydict()
    assert out == {"k": [1, 2, 3], "v": [10.0, 20.0, 30.0]}
    # it is a real iceberg table on disk
    assert (tmp_path / "wh" / "sales" / "metadata").is_dir()


def test_use_statement(tmp_path, sess):
    (tmp_path / "wh").mkdir()
    sess.attach(FilesystemCatalog(str(tmp_path / "wh"), name="lake"))
    sess.sql("CREATE TABLE lake.t AS SELECT 5 AS x")
    sess.sql("USE lake")
    out = sess.sql("SELECT x FROM t").to_pydict()
    assert out["x"] == [5]


def test_module_level_sql_statements():
    """daft_tpu.sql routes statements through the ambient session."""
    import uuid
    name = f"tmp_{uuid.uuid4().hex[:8]}"
    daft_tpu.sql(f"CREATE TEMP TABLE {name} AS SELECT 42 AS answer")
    out = daft_tpu.sql(f"SELECT answer FROM {name}").to_pydict()
    assert out["answer"] == [42]
    daft_tpu.sql(f"DROP TABLE {name}")
