"""Round-6 kernel contracts: packed-key argsort (≤3 sort operands, exact
host agreement), the fused single-dispatch join, and the per-dispatch MFU
ledger.

The argsort parity sweep is property-based in the seeded-random style
(hypothesis is not guaranteed in every environment): ~60 random
configurations over mixed dtypes × descending × nulls_first × null
density, each asserting EXACT permutation agreement with the pyarrow
host path (both sides are stable sorts, so ties must agree too).
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import daft_tpu
from daft_tpu.analysis import rule_jit
from daft_tpu.device import costmodel, kernels as K
from daft_tpu.recordbatch import RecordBatch


# ---------------------------------------------------------------- argsort

def _random_frame(rng, n, dtypes):
    """pydict of random columns (with nulls) for the requested dtypes."""
    data = {}
    for i, dt in enumerate(dtypes):
        nulls = rng.random(n) < rng.choice([0.0, 0.15, 0.5])
        if dt == "int":
            v = rng.integers(-2**40, 2**40, n).tolist()
        elif dt == "small_int":
            v = rng.integers(-3, 3, n).tolist()  # heavy ties
        elif dt == "float":
            v = np.round(rng.uniform(-1e6, 1e6, n), 3).tolist()
        elif dt == "bool":
            v = (rng.random(n) > 0.5).tolist()
        else:  # string
            v = ["s" + str(rng.integers(0, 8)) for _ in range(n)]
        data[f"c{i}"] = [None if m else x for x, m in zip(v, nulls)]
    return data


@pytest.mark.parametrize("seed", range(12))
def test_argsort_device_matches_host_property(seed, monkeypatch):
    """Exact permutation agreement between the packed-key device argsort
    and the pyarrow host path over random frames/orderings."""
    rng = np.random.default_rng(seed)
    n = int(rng.integers(2, 80))
    n_keys = int(rng.integers(1, 4))
    dtypes = [rng.choice(["int", "small_int", "float", "bool", "string"])
              for _ in range(n_keys)]
    data = _random_frame(rng, n, dtypes)
    rb = RecordBatch.from_pydict(data)
    keys = [daft_tpu.col(f"c{i}") for i in range(n_keys)]
    for trial in range(5):
        desc = [bool(rng.integers(0, 2)) for _ in range(n_keys)]
        nf = [bool(rng.integers(0, 2)) for _ in range(n_keys)]
        monkeypatch.delenv("DAFT_TPU_DEVICE_FORCE", raising=False)
        monkeypatch.setenv("DAFT_TPU_DEVICE", "0")
        host = rb.argsort(keys, desc, nf)
        monkeypatch.setenv("DAFT_TPU_DEVICE", "1")
        monkeypatch.setenv("DAFT_TPU_DEVICE_FORCE", "1")
        dev = rb.argsort(keys, desc, nf)
        assert list(dev) == list(host), (dtypes, desc, nf)


def test_argsort_f32_codes_match_reference():
    """f32 value codes (the TPU backend's float plane — f64 rides f32
    there) order exactly like the float values, including -0.0."""
    vals = np.array([0.0, -0.0, 1.5, -1.5, np.inf, -np.inf, 3e-9],
                    np.float32)
    C = 16
    k = np.zeros(C, np.float32)
    k[:len(vals)] = vals
    mask = np.zeros(C, bool)
    mask[:len(vals)] = True
    ones = np.ones(C, bool)
    for desc in (False, True):
        perm = np.asarray(K.argsort_kernel(
            (jnp.asarray(k),), (jnp.asarray(ones),), jnp.asarray(mask),
            (desc,), (False,)))[:len(vals)]
        got = [vals[i] for i in perm]
        # IEEE total order (what lax.sort uses too): -0.0 before 0.0
        ref = sorted(list(vals),
                     key=lambda v: (v, not np.signbit(v)), reverse=desc)
        assert [str(x) for x in got] == [str(x) for x in ref], (desc, got)


# the jaxpr walk + contract numbers are single-sourced in the jit-hygiene
# lint rule (daft_tpu/analysis/rule_jit.py) — tests and
# `python -m daft_tpu.analysis` prove the SAME contracts


@pytest.mark.parametrize("n_keys,dtype", rule_jit.ARGSORT_CASES)
def test_argsort_compiles_with_at_most_3_sort_operands(n_keys, dtype):
    """The operand-count cliff contract: ≤3 operands per lax.sort for ANY
    key count (the 2k+1-plane formulation hit >5-minute TPU compiles)."""
    jaxpr = rule_jit.argsort_jaxpr(n_keys, dtype)
    assert rule_jit.max_sort_operands(jaxpr.jaxpr) \
        <= rule_jit.ARGSORT_MAX_SORT_OPERANDS


def test_grouped_agg_sorts_stay_under_operand_cliff():
    """The grouped-agg kernels ride the same packed sort: ≤3 operands
    regardless of key count."""
    jaxpr = rule_jit.grouped_agg_jaxpr(n_keys=5)
    assert rule_jit.max_sort_operands(jaxpr.jaxpr) \
        <= rule_jit.ARGSORT_MAX_SORT_OPERANDS


def test_fused_join_jaxpr_has_no_host_callbacks():
    """The single-dispatch contract, statically: the fused join program
    contains zero host-callback primitives (a host round-trip inside the
    fused program would silently reintroduce the per-phase transfers)."""
    jx = rule_jit.join_fused_jaxpr()
    for prim in rule_jit.FORBIDDEN_IN_FUSED_JOIN:
        assert rule_jit.count_primitive(jx.jaxpr, prim) == 0


def test_lint_dispatch_contract_checker_is_clean():
    """The lint rule's own contract re-verification (what CI runs via
    `python -m daft_tpu.analysis`) agrees with the tests above."""
    assert rule_jit.check_dispatch_contracts() == []


def test_argsort_radix_passes_scale_with_key_bits():
    assert K.argsort_pack_plan([np.float32]) == [1]       # 34 bits
    assert K.argsort_pack_plan([np.float32] * 2) == [2]   # 67 bits
    assert K.argsort_pack_plan([np.int64]) == [2]         # 66 bits
    # 3 x 65-bit keys = 196 bits → two passes
    assert len(K.argsort_pack_plan([np.int64] * 3)) == 2


# ------------------------------------------------------------- fused join

def _join_keys(seed=3):
    rng = np.random.default_rng(seed)
    lk = rng.integers(0, 50, 400)
    rk = rng.integers(0, 50, 150)
    lv = rng.random(400) > 0.1
    rv = rng.random(150) > 0.1
    return lk, rk, lv, rv


def test_fused_join_is_one_dispatch_with_host_identical_indices(
        monkeypatch):
    """The fused kernel must be dispatched EXACTLY once per build/probe
    pair (no per-phase dispatches, no host round-trips between phases),
    and its indices must match the host merge exactly."""
    from daft_tpu import joins
    lk, rk, lv, rv = _join_keys()
    calls = {"n": 0}
    real = K.join_fused_kernel

    def counting(*a, **kw):
        calls["n"] += 1
        return real(*a, **kw)

    monkeypatch.setattr(K, "join_fused_kernel", counting)
    out = joins._device_match_indices(lk, rk, lv, rv)
    assert out is not None
    assert calls["n"] == 1, f"expected ONE dispatch, saw {calls['n']}"
    dli, dri, dcnt = out
    monkeypatch.setenv("DAFT_TPU_DEVICE_JOIN", "0")
    hli, hri, hcnt = joins.match_indices(lk, rk, lv, rv)
    assert sorted(zip(dli.tolist(), dri.tolist())) == \
        sorted(zip(hli.tolist(), hri.tolist()))
    assert np.array_equal(dcnt, hcnt)


def test_fused_join_overflow_redispatches_once(monkeypatch):
    """A many-to-many blowup past the FK-shaped output estimate re-runs
    at the fitting bucket — two dispatches, still correct."""
    from daft_tpu import joins
    n = 1200  # 1200*1200 pairs ≫ bucket_capacity(1200)=2048 slots
    lk = np.zeros(n, np.int64)
    rk = np.zeros(n, np.int64)
    ones = np.ones(n, bool)
    calls = {"n": 0}
    real = K.join_fused_kernel

    def counting(*a, **kw):
        calls["n"] += 1
        return real(*a, **kw)

    monkeypatch.setattr(K, "join_fused_kernel", counting)
    dli, dri, dcnt = joins._device_match_indices(lk, rk, ones, ones)
    assert calls["n"] == 2
    assert len(dli) == n * n
    assert dcnt.tolist() == [n] * n


# ------------------------------------------------------------- MFU ledger

def test_ledger_records_and_derives():
    costmodel.ledger_reset()
    costmodel.ledger_record("argsort", rows=100, nbytes=1e9, seconds=0.5)
    costmodel.ledger_record("argsort", rows=50, nbytes=1e9, seconds=0.5)
    snap = costmodel.ledger_snapshot()
    d = snap["argsort"]
    assert d["dispatches"] == 2 and d["rows"] == 150
    assert d["achieved_gbps"] == 2.0
    assert d["roofline_pct"] == pytest.approx(
        100.0 * 2e9 / costmodel.hbm_bps(), rel=1e-6)
    costmodel.ledger_reset()
    assert costmodel.ledger_snapshot() == {}


def test_ledger_delta_isolates_a_query():
    costmodel.ledger_reset()
    costmodel.ledger_record("join", rows=10, nbytes=100.0, seconds=0.1)
    before = costmodel.ledger_snapshot(raw=True)
    costmodel.ledger_record("join", rows=7, nbytes=50.0, seconds=0.1)
    costmodel.ledger_record("grouped_agg", rows=3, nbytes=10.0,
                            flops=1e12, seconds=0.2)
    delta = costmodel.ledger_delta(before,
                                   costmodel.ledger_snapshot(raw=True))
    assert delta["join"]["rows"] == 7
    assert delta["grouped_agg"]["mfu_pct"] > 0
    costmodel.ledger_reset()


def test_real_dispatches_feed_the_ledger(monkeypatch):
    """try_argsort and the device join both account their dispatches."""
    costmodel.ledger_reset()
    monkeypatch.setenv("DAFT_TPU_DEVICE_FORCE", "1")
    rb = RecordBatch.from_pydict({"a": [3, 1, 2, None, 5]})
    rb.argsort([daft_tpu.col("a")], [False], [False])
    from daft_tpu import joins
    lk, rk, lv, rv = _join_keys()
    joins._device_match_indices(lk, rk, lv, rv)
    snap = costmodel.ledger_snapshot()
    assert snap["argsort"]["dispatches"] == 1
    assert snap["argsort"]["rows"] == 5
    assert snap["join"]["dispatches"] == 1
    assert snap["join"]["bytes"] > 0 and snap["join"]["seconds"] > 0
    costmodel.ledger_reset()


def test_query_stats_carry_ledger_delta(monkeypatch):
    """observability: a query's RuntimeStatsContext reports the device
    dispatches IT caused, and render() prints them."""
    from daft_tpu import observability as obs
    costmodel.ledger_reset()
    ctx = obs.new_query_stats()
    costmodel.ledger_record("argsort", rows=9, nbytes=1e6, seconds=0.01)
    ctx.finish()
    assert ctx.device_kernels["argsort"]["rows"] == 9
    assert "argsort" in ctx.render()
    # a later query must not re-report the same work
    ctx2 = obs.new_query_stats()
    ctx2.finish()
    assert ctx2.device_kernels == {}
    costmodel.ledger_reset()


def test_mfu_report_embeds_ledger():
    from daft_tpu.device import mfu
    costmodel.ledger_reset()
    costmodel.ledger_record("join", rows=4, nbytes=1.0, seconds=0.1)
    r = mfu.report(n=1 << 10)
    assert "error" not in r, r
    assert r["ledger"]["join"]["dispatches"] == 1
    assert r["argsort"]["sort_passes"] == 1
    costmodel.ledger_reset()


def test_dispatch_log_appends_are_serialized(tmp_path, monkeypatch):
    """Concurrent decision logging must never interleave JSONL lines."""
    import json
    import threading
    log = tmp_path / "d.jsonl"
    monkeypatch.setenv("DAFT_TPU_DISPATCH_LOG", str(log))
    monkeypatch.setenv("DAFT_TPU_LINK_RTT_MS", "10")
    monkeypatch.setenv("DAFT_TPU_LINK_UP_MBPS", "50")
    monkeypatch.setenv("DAFT_TPU_LINK_DOWN_MBPS", "50")
    costmodel.reset_for_tests()

    def spam():
        for _ in range(200):
            costmodel.row_output_op_wins(1e6, 1e6, host_bytes=2e6)

    threads = [threading.Thread(target=spam) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    lines = log.read_text().splitlines()
    assert len(lines) == 1600
    for ln in lines:
        json.loads(ln)  # every line parses — no interleaving
    costmodel.reset_for_tests()


# ------------------------------------------------- fused-agg group gate

def test_fused_gate_falls_back_to_row_estimate():
    from daft_tpu.execution import pipeline as pl

    class Node:
        group_by = ("k",)
        aggs = ("s",)

    n = Node()
    n.group_ndv = None
    n.group_rows_est = None
    assert pl._fused_groups_admissible(n)          # no evidence: default
    n.group_rows_est = pl._FUSE_MAX_GROUPS + 1
    assert not pl._fused_groups_admissible(n)      # row estimate declines
    n.group_ndv = 1000.0                           # footer evidence wins
    assert pl._fused_groups_admissible(n)


def test_fused_gate_respects_memory_budget(monkeypatch):
    from daft_tpu.execution import pipeline as pl

    class Node:
        group_by = ("k",)
        aggs = ("a", "b")

    n = Node()
    n.group_ndv = 10_000_000.0  # under the group cap …
    n.group_rows_est = None
    monkeypatch.setenv("DAFT_TPU_MEMORY_LIMIT", "64MB")
    assert not pl._fused_groups_admissible(n)  # … but not under 64MB
    monkeypatch.setenv("DAFT_TPU_MEMORY_LIMIT", "64GB")
    assert pl._fused_groups_admissible(n)


# ------------------------------------------- hash kernels (round 12)
#
# The hash grouped-agg / hash join are STRATEGY swaps for the sort
# kernels above: same argument shapes, same return contracts, same
# overflow discipline. Parity is proven three ways — kernel-vs-kernel
# (hash vs sort over seeded random configurations), kernel-vs-numpy
# (an independent host reference), and engine-vs-host (forced-hash
# queries against the pure host path). On this CPU tier every Pallas
# program runs under the interpreter (`interpret=True`), which is
# itself a tested contract: tier-1 proves parity without silicon.

from daft_tpu.device import mfu, pallas_kernels as pk  # noqa: E402


def _agg_args(rng, C, nk, nv, null_keys=True):
    """Random [C]-padded kernel inputs with a live-row prefix mask."""
    n = int(rng.integers(3, C))
    mask = np.zeros(C, bool)
    mask[:n] = True
    keys, kvalids = [], []
    for _ in range(nk):
        dt = rng.choice(["int64", "int32", "float32", "bool"])
        if dt == "bool":
            k = rng.integers(0, 2, C).astype(bool)
        elif dt == "float32":
            k = rng.integers(-4, 5, C).astype(np.float32)
        else:
            k = rng.integers(-6, 7, C).astype(dt)
        kv = np.ones(C, bool) if not null_keys \
            else rng.random(C) > rng.choice([0.0, 0.3])
        keys.append(jnp.asarray(k))
        kvalids.append(jnp.asarray(kv))
    vals, vvalids, ops = [], [], []
    for _ in range(nv):
        vals.append(jnp.asarray(
            np.round(rng.uniform(-50, 50, C), 2).astype(np.float32)))
        vvalids.append(jnp.asarray(rng.random(C) > 0.2))
        ops.append(rng.choice(["sum", "count", "min", "max", "mean"]))
    return (tuple(keys), tuple(kvalids), tuple(vals), tuple(vvalids),
            jnp.asarray(mask), tuple(ops))


def _agg_map(out, nk, nv):
    """{group key tuple: value tuple} for the live groups of a kernel
    result — strategy-order-insensitive (hash emits slot order, sort
    emits key order; engine-wide, grouped output order is unspecified)."""
    ok, okv, ov, ovv, g = out
    g = int(np.asarray(jax.device_get(g)))
    ok = [np.asarray(k) for k in ok]
    okv = [np.asarray(k) for k in okv]
    ov = [np.asarray(v) for v in ov]
    ovv = [np.asarray(v) for v in ovv]
    m = {}
    for i in range(g):
        key = tuple(k[i].item() if kv[i] else None
                    for k, kv in zip(ok, okv))
        m[key] = tuple(v[i].item() if vv[i] else None
                       for v, vv in zip(ov, ovv))
    return m


def _maps_close(a, b):
    assert set(a) == set(b), (sorted(a, key=repr), sorted(b, key=repr))
    for k in a:
        for x, y in zip(a[k], b[k]):
            if x is None or y is None:
                assert x == y, (k, a[k], b[k])
            else:
                assert x == pytest.approx(y, rel=1e-4, abs=1e-4), \
                    (k, a[k], b[k])


@pytest.mark.parametrize("seed", range(10))
def test_hash_agg_matches_sort_kernel_property(seed):
    """Seeded-property parity: the one-pass hash table and the
    sort+segment-reduce formulation agree on every group and every
    aggregate over random dtypes × null densities × op mixes."""
    rng = np.random.default_rng(seed)
    C = int(rng.choice([64, 128, 256]))
    nk = int(rng.integers(1, 3))
    nv = int(rng.integers(1, 3))
    keys, kvalids, vals, vvalids, mask, ops = _agg_args(rng, C, nk, nv)
    if pk.hash_pack_words([k.dtype for k in keys]) is None:
        pytest.skip("key set too wide for the hash budget")
    out_cap = C
    hashed = pk.hash_grouped_agg_impl(
        keys, kvalids, vals, vvalids, mask, ops, out_cap,
        interpret=True, block=int(rng.choice([16, 32, C])))
    sorted_ = K.grouped_agg_block_impl(
        keys, kvalids, vals, vvalids, mask, ops, out_cap)
    _maps_close(_agg_map(hashed, nk, nv), _agg_map(sorted_, nk, nv))


def test_hash_agg_matches_numpy_reference():
    """Independent host reference: sums/counts/min over known data with
    NULL keys and NULL values, computed with numpy, no engine code."""
    C = 64
    k = np.array([1, 2, 1, 3, 2, 1, 0, 3] + [0] * (C - 8), np.int64)
    kv = np.array([1, 1, 1, 1, 1, 0, 1, 1] + [1] * (C - 8), bool)
    v = np.arange(C, dtype=np.float32)
    vv = np.array([1, 1, 0, 1, 1, 1, 1, 1] + [1] * (C - 8), bool)
    mask = np.zeros(C, bool)
    mask[:8] = True
    out = pk.hash_grouped_agg_impl(
        (jnp.asarray(k),), (jnp.asarray(kv),), (jnp.asarray(v),),
        (jnp.asarray(vv),), jnp.asarray(mask), ("sum",), C,
        interpret=True, block=16)
    got = _agg_map(out, 1, 1)
    ref = {}
    for i in range(8):
        key = int(k[i]) if kv[i] else None
        ref.setdefault(key, []).append(float(v[i]) if vv[i] else None)
    want = {(key,): (sum(x for x in xs if x is not None)
                     if any(x is not None for x in xs) else None,)
            for key, xs in ref.items()}
    _maps_close(got, want)


def test_hash_agg_all_duplicate_and_all_unique_keys():
    """Adversarial cardinalities: one group total, and one group per
    row (the table at its load-factor ceiling)."""
    C = 128
    ones = jnp.ones(C, bool)
    dup = pk.hash_grouped_agg_impl(
        (jnp.full(C, 7, jnp.int64),), (ones,),
        (jnp.ones(C, jnp.float32),), (ones,), ones, ("sum",), C,
        interpret=True, block=32)
    assert int(np.asarray(dup[-1])) == 1
    assert np.asarray(dup[2][0])[0] == C
    uniq = pk.hash_grouped_agg_impl(
        (jnp.arange(C, dtype=jnp.int64),), (ones,),
        (jnp.ones(C, jnp.float32),), (ones,), ones, ("count",), C,
        interpret=True, block=32)
    assert int(np.asarray(uniq[-1])) == C
    m = _agg_map(uniq, 1, 1)
    assert len(m) == C and all(v == (1,) for v in m.values())


def test_hash_agg_overflow_signals_and_redispatch_recovers():
    """More groups than ``out_cap``: the returned group count exceeds the
    bucket (the r6 overflow contract — the caller re-dispatches at a
    grown bucket), and the re-dispatch at a fitting bucket is complete
    and sort-parity."""
    C = 256
    ndv = 200
    ones = jnp.ones(C, bool)
    keys = (jnp.asarray(np.arange(C) % ndv, jnp.int64),)
    vals = (jnp.ones(C, jnp.float32),)
    args = (keys, (ones,), vals, (ones,), ones, ("sum",))
    small = pk.hash_grouped_agg_impl(*args, out_cap=128, interpret=True,
                                     block=64)
    assert int(np.asarray(small[-1])) > 128  # overflow signalled
    big = pk.hash_grouped_agg_impl(*args, out_cap=256, interpret=True,
                                   block=64)
    ref = K.grouped_agg_block_impl(*args, out_cap=256)
    _maps_close(_agg_map(big, 1, 1), _agg_map(ref, 1, 1))


def test_hash_agg_wide_key_sets_raise_and_route_to_sort():
    """>128-bit packed key sets: ``hash_pack_words`` declines (the
    dispatch-site routing signal) and the kernel itself raises — wide
    keys always run as the sort path's LSD radix."""
    assert pk.hash_pack_words([np.dtype(d) for d in
                               rule_jit.HASH_UNFIT_KEY_DTYPES]) is None
    C = 32
    ones = jnp.ones(C, bool)
    k = jnp.asarray(np.arange(C), jnp.int64)
    with pytest.raises(ValueError):
        pk.hash_grouped_agg_impl(
            (k, k, k), (ones,) * 3, (jnp.ones(C, jnp.float32),), (ones,),
            ones, ("sum",), C, interpret=True, block=16)
    # the strategy model never picks hash for them, even when forced
    s, _ = costmodel.groupby_strategy(
        1000, 10.0, [np.dtype("int64")] * 3, 128, log=False)
    assert s == "sort"


def test_interpreter_mode_is_the_cpu_default():
    """Tier-1 runs every Pallas program under the interpreter: the CPU
    backend auto-selects it, and the knob force-overrides both ways."""
    assert pk.interpret_default() is True  # JAX_PLATFORMS=cpu in tier-1
    os.environ["DAFT_TPU_KERNEL_INTERPRET"] = "0"
    try:
        assert pk.interpret_default() is False
    finally:
        del os.environ["DAFT_TPU_KERNEL_INTERPRET"]


# --------------------------------------------------- hash join (round 12)

def _join_pairs(packed, n_l):
    """(pairs list, counts) from the packed [3, W] result matrix."""
    counts = packed[2, :n_l]
    total = int(counts.sum())
    return list(zip(packed[0, :total].tolist(),
                    packed[1, :total].tolist())), counts


@pytest.mark.parametrize("seed", range(6))
def test_hash_join_matches_sort_kernel_property(seed):
    """Pair-exact parity between the Pallas hash build/probe and the
    fused sort join — including pair ORDER (left-major, ascending right
    row), the contract that makes the strategies drop-in swaps."""
    rng = np.random.default_rng(seed)
    C = int(rng.choice([64, 128]))
    lk = jnp.asarray(rng.integers(0, C // 3, C).astype(np.int64))
    rk = jnp.asarray(rng.integers(0, C // 3, C).astype(np.int64))
    lv = jnp.asarray(rng.random(C) > 0.15)
    rv = jnp.asarray(rng.random(C) > 0.15)
    lm = jnp.asarray(np.arange(C) < int(rng.integers(4, C)))
    rm = jnp.asarray(np.arange(C) < int(rng.integers(4, C)))
    cap = 4 * C
    hashed = np.asarray(pk.hash_join_impl(lk, lv, lm, rk, rv, rm, cap,
                                          interpret=True, block=32))
    sorted_ = np.asarray(K.join_fused_impl(lk, lv, lm, rk, rv, rm, cap))
    hp, hc = _join_pairs(hashed, C)
    sp, sc = _join_pairs(sorted_, C)
    assert int(hc.sum()) <= cap, "grow the cap for this seed"
    assert hp == sp
    assert hc.tolist() == sc.tolist()


def test_hash_join_null_keys_never_match():
    """NULL-keyed rows (validity False) on either side produce no pairs,
    even when their padded key words are bit-equal."""
    C = 16
    k = jnp.asarray(np.full(C, 5, np.int64))
    valid_l = jnp.asarray(np.arange(C) == 0)   # one live left row
    valid_r = jnp.asarray(np.arange(C) < 2)    # two live right rows
    ones = jnp.ones(C, bool)
    packed = np.asarray(pk.hash_join_impl(
        k, valid_l, ones, k, valid_r, ones, 64, interpret=True, block=16))
    pairs, counts = _join_pairs(packed, C)
    assert pairs == [(0, 0), (0, 1)]
    assert counts.tolist() == [2] + [0] * (C - 1)


def test_engine_join_hash_single_dispatch_matches_host(monkeypatch):
    """`DAFT_TPU_KERNEL_JOIN=hash` routes `_device_match_indices` through
    the Pallas kernel — exactly ONE dispatch, host-identical indices."""
    from daft_tpu import joins
    monkeypatch.setenv("DAFT_TPU_DEVICE_FORCE", "1")
    monkeypatch.setenv("DAFT_TPU_KERNEL_JOIN", "hash")
    lk, rk, lv, rv = _join_keys()
    calls = {"n": 0}
    real = pk.hash_join_kernel

    def counting(*a, **kw):
        calls["n"] += 1
        return real(*a, **kw)

    monkeypatch.setattr(pk, "hash_join_kernel", counting)
    costmodel.ledger_reset()
    out = joins._device_match_indices(lk, rk, lv, rv)
    assert out is not None
    assert calls["n"] == 1, f"expected ONE dispatch, saw {calls['n']}"
    dli, dri, dcnt = out
    monkeypatch.setenv("DAFT_TPU_DEVICE_JOIN", "0")
    hli, hri, hcnt = joins.match_indices(lk, rk, lv, rv)
    assert sorted(zip(dli.tolist(), dri.tolist())) == \
        sorted(zip(hli.tolist(), hri.tolist()))
    assert np.array_equal(dcnt, hcnt)
    snap = costmodel.ledger_snapshot()
    assert snap["join"]["strategy"] == "hash"
    assert 0 < snap["join"]["load_factor"] <= 0.5  # 2x-capacity table
    costmodel.ledger_reset()


def test_engine_join_hash_overflow_redispatches_once(monkeypatch):
    """A many-to-many blowup past the FK-shaped output estimate re-runs
    the HASH kernel at the fitting bucket — two dispatches, correct."""
    from daft_tpu import joins
    monkeypatch.setenv("DAFT_TPU_DEVICE_FORCE", "1")
    monkeypatch.setenv("DAFT_TPU_KERNEL_JOIN", "hash")
    n = 400  # 400*400 pairs >> bucket_capacity(400) slots
    lk = np.zeros(n, np.int64)
    rk = np.zeros(n, np.int64)
    ones = np.ones(n, bool)
    calls = {"n": 0}
    real = pk.hash_join_kernel

    def counting(*a, **kw):
        calls["n"] += 1
        return real(*a, **kw)

    monkeypatch.setattr(pk, "hash_join_kernel", counting)
    dli, dri, dcnt = joins._device_match_indices(lk, rk, ones, ones)
    assert calls["n"] == 2
    assert len(dli) == n * n
    assert dcnt.tolist() == [n] * n


# ------------------------------------- strategy model + ledger (round 12)

def test_groupby_strategy_decision_rule(monkeypatch):
    """The hash-vs-sort decision ladder: silicon-only in auto, forced by
    the knob, NDV-fraction decline, table-ceiling decline."""
    dts = [np.dtype("int64")]
    # CPU backend in auto mode: the interpreter exists for parity, not
    # speed — stays on sort
    assert costmodel.groupby_strategy(10_000, 64.0, dts, 128,
                                      log=False)[0] == "sort"
    monkeypatch.setenv("DAFT_TPU_KERNEL_GROUPBY", "hash")
    s, lf = costmodel.groupby_strategy(10_000, 64.0, dts, 128, log=False)
    assert s == "hash" and 0 < lf <= 1.0
    monkeypatch.setenv("DAFT_TPU_KERNEL_GROUPBY", "sort")
    assert costmodel.groupby_strategy(10_000, 64.0, dts, 128,
                                      log=False)[0] == "sort"
    # auto + silicon: hash at aggregation-shaped NDV …
    monkeypatch.setenv("DAFT_TPU_KERNEL_GROUPBY", "auto")
    monkeypatch.setattr(costmodel, "_hash_capable_backend", lambda: True)
    assert costmodel.groupby_strategy(10_000, 64.0, dts, 128,
                                      log=False)[0] == "hash"
    # … sort on near-unique keys (the table grows as large as the data)
    assert costmodel.groupby_strategy(10_000, 9_000.0, dts, 16384,
                                      log=False)[0] == "sort"
    # … sort when the table exceeds the on-chip slot ceiling
    monkeypatch.setenv("DAFT_TPU_KERNEL_MAX_TABLE", "256")
    assert costmodel.groupby_strategy(10_000, 64.0, dts, 4096,
                                      log=False)[0] == "sort"


def test_join_strategy_decision_rule(monkeypatch):
    assert costmodel.join_strategy(1000, 1000) == "sort"  # CPU auto
    monkeypatch.setenv("DAFT_TPU_KERNEL_JOIN", "hash")
    assert costmodel.join_strategy(1000, 1000) == "hash"
    monkeypatch.setenv("DAFT_TPU_KERNEL_JOIN", "auto")
    monkeypatch.setattr(costmodel, "_hash_capable_backend", lambda: True)
    assert costmodel.join_strategy(1000, 1000) == "hash"
    monkeypatch.setenv("DAFT_TPU_KERNEL_MAX_TABLE", "256")
    assert costmodel.join_strategy(100_000, 100_000) == "sort"


def test_ledger_carries_strategy_and_load_factor():
    """`strategy`/`load_factor` ride the same per-family ledger rows the
    stats block and dashboard render."""
    costmodel.ledger_reset()
    costmodel.ledger_record("grouped_agg", rows=10, nbytes=1e6,
                            seconds=0.1, strategy="hash", load_factor=0.4)
    snap = costmodel.ledger_snapshot()
    assert snap["grouped_agg"]["strategy"] == "hash"
    assert snap["grouped_agg"]["load_factor"] == 0.4
    costmodel.ledger_record("grouped_agg", rows=5, nbytes=1e6,
                            seconds=0.1, strategy="sort")
    snap = costmodel.ledger_snapshot()
    assert snap["grouped_agg"]["strategy"] == "mixed"
    assert snap["grouped_agg"]["strategy_hash"] == 1
    assert snap["grouped_agg"]["strategy_sort"] == 1
    costmodel.ledger_reset()


def test_query_stats_render_strategy(monkeypatch):
    """The per-query device_kernels block shows the chosen strategy."""
    from daft_tpu import observability as obs
    costmodel.ledger_reset()
    ctx = obs.new_query_stats()
    costmodel.ledger_record("grouped_agg", rows=9, nbytes=1e6,
                            seconds=0.01, strategy="hash",
                            load_factor=0.25)
    ctx.finish()
    assert ctx.device_kernels["grouped_agg"]["strategy"] == "hash"
    assert ctx.device_kernels["grouped_agg"]["load_factor"] == 0.25
    assert "strategy=hash" in ctx.render()
    assert "load=0.25" in ctx.render()
    costmodel.ledger_reset()


def test_hash_byte_models_beat_sort_at_agg_shapes():
    """The pricing the strategy model acts on: at aggregation-shaped NDV
    the one-pass hash model touches fewer bytes than the multi-pass sort
    model; both are positive."""
    rows, out_cap = 1 << 20, 256
    table = pk.table_capacity(out_cap)
    _, sort_b = mfu.grouped_agg_models(rows, out_cap, 1, 2)
    _, hash_b = mfu.hash_agg_models(rows, out_cap, table, 1, 2)
    assert 0 < hash_b < sort_b
    assert mfu.hash_join_bytes_model(1 << 16, 1 << 16, 1 << 16) > 0


# -------------------------------------- engine end-to-end (forced hash)

def _host_groupby(data, keys, aggs, monkeypatch):
    import daft_tpu as dtpu
    monkeypatch.setenv("DAFT_TPU_DEVICE", "0")
    monkeypatch.delenv("DAFT_TPU_DEVICE_FORCE", raising=False)
    df = dtpu.from_pydict(data)
    return df.groupby(*keys).agg(*aggs).sort(list(keys)).to_pydict()


def _device_groupby(data, keys, aggs, monkeypatch, strategy="hash"):
    import daft_tpu as dtpu
    monkeypatch.setenv("DAFT_TPU_DEVICE", "1")
    monkeypatch.setenv("DAFT_TPU_DEVICE_FORCE", "1")
    monkeypatch.setenv("DAFT_TPU_KERNEL_GROUPBY", strategy)
    df = dtpu.from_pydict(data)
    return df.groupby(*keys).agg(*aggs).sort(list(keys)).to_pydict()


def _pydicts_close(a, b):
    assert set(a) == set(b)
    for c in a:
        for x, y in zip(a[c], b[c]):
            if isinstance(x, float) and isinstance(y, float):
                assert x == pytest.approx(y, rel=1e-5), c
            else:
                assert x == y, c


def test_engine_groupby_forced_hash_matches_host(monkeypatch):
    """Whole-engine parity: a forced-hash grouped aggregation (NULL keys
    included) agrees with the pure host path, and the query's ledger row
    says the hash strategy really ran."""
    rng = np.random.default_rng(11)
    n = 500
    data = {
        "k": [None if rng.random() < 0.1 else int(x)
              for x in rng.integers(0, 40, n)],
        "v": rng.uniform(-10, 10, n).round(3).tolist(),
    }
    aggs = (daft_tpu.col("v").sum().alias("s"),
            daft_tpu.col("v").mean().alias("m"),
            daft_tpu.col("v").count().alias("c"))
    host = _host_groupby(data, ("k",), aggs, monkeypatch)
    costmodel.ledger_reset()
    dev = _device_groupby(data, ("k",), aggs, monkeypatch)
    snap = costmodel.ledger_snapshot()
    _pydicts_close(dev, host)
    assert snap["grouped_agg"]["strategy"] == "hash"
    assert snap["grouped_agg"]["load_factor"] > 0
    costmodel.ledger_reset()


def test_engine_groupby_hash_overflow_grows_bucket(monkeypatch):
    """More groups than the first packed-output bucket (128) but fewer
    than the first hash TABLE's slots: the fused path re-dispatches the
    HASH program at a grown bucket and the answer is still host-exact.
    (NDV past the table size saturates it and switches the ladder to
    sort — covered by test_saturated_hash_overflow_switches_to_sort.)"""
    n = 2000
    ndv = 200  # > _OUT_CAP0, < table_capacity(_OUT_CAP0) so never saturated
    data = {"k": [int(i % ndv) for i in range(n)],
            "v": [float(i) for i in range(n)]}
    aggs = (daft_tpu.col("v").sum().alias("s"),)
    host = _host_groupby(data, ("k",), aggs, monkeypatch)
    costmodel.ledger_reset()
    dev = _device_groupby(data, ("k",), aggs, monkeypatch)
    snap = costmodel.ledger_snapshot()
    _pydicts_close(dev, host)
    assert snap["grouped_agg"]["strategy"] == "hash"
    costmodel.ledger_reset()


def test_engine_groupby_wide_keys_fall_back_to_sort(monkeypatch):
    """Three i64 key columns pack past the 128-bit hash budget: even
    forced-hash queries route to the sort path and stay host-exact."""
    rng = np.random.default_rng(5)
    n = 300
    big = 1 << 60
    data = {
        "a": (rng.integers(-big, big, n)).tolist(),
        "b": (rng.integers(-big, big, n) | 1).tolist(),
        "c": rng.integers(0, 3, n).tolist(),
        "v": rng.uniform(0, 10, n).round(2).tolist(),
    }
    # only 3 distinct (a, b, c) triples → grouping is real
    for col_ in ("a", "b"):
        data[col_] = [data[col_][i % 3] for i in range(n)]
    aggs = (daft_tpu.col("v").sum().alias("s"),)
    host = _host_groupby(data, ("a", "b", "c"), aggs, monkeypatch)
    costmodel.ledger_reset()
    dev = _device_groupby(data, ("a", "b", "c"), aggs, monkeypatch)
    snap = costmodel.ledger_snapshot()
    _pydicts_close(dev, host)
    assert snap["grouped_agg"]["strategy"] == "sort"
    costmodel.ledger_reset()


# ------------------------------------------ hash dispatch contracts

def test_hash_agg_jaxpr_contracts():
    """Single-sourced with the lint rule: ONE pallas_call (the table
    build), slot compaction within the ≤3-operand sort budget, zero
    host callbacks."""
    jx = rule_jit.hash_agg_jaxpr()
    assert rule_jit.count_primitive(jx.jaxpr, "pallas_call") \
        == rule_jit.HASH_AGG_PALLAS_CALLS
    assert rule_jit.max_sort_operands(jx.jaxpr) \
        <= rule_jit.ARGSORT_MAX_SORT_OPERANDS
    for prim in rule_jit.FORBIDDEN_IN_FUSED_JOIN:
        assert rule_jit.count_primitive(jx.jaxpr, prim) == 0


def test_hash_join_jaxpr_contracts():
    """TWO pallas_calls (build + probe) fused in one jit program, NO
    lax.sort anywhere, zero host callbacks."""
    jx = rule_jit.hash_join_jaxpr()
    assert rule_jit.count_primitive(jx.jaxpr, "pallas_call") \
        == rule_jit.HASH_JOIN_PALLAS_CALLS
    assert rule_jit.max_sort_operands(jx.jaxpr) \
        <= rule_jit.HASH_JOIN_MAX_SORT_OPERANDS
    for prim in rule_jit.FORBIDDEN_IN_FUSED_JOIN:
        assert rule_jit.count_primitive(jx.jaxpr, prim) == 0


def test_mfu_report_has_hash_rows_with_strategy():
    """`mfu.report()` times the hash kernels in-jit too (shrunk smoke
    size under the interpreter) and tags every row with its strategy."""
    r = mfu.report(n=1 << 10)
    assert "hash_error" not in r, r.get("hash_error")
    assert r["grouped_agg_hash"]["strategy"] == "hash"
    assert r["grouped_agg_hash"]["interpret"] is True
    assert r["join_hash"]["strategy"] == "hash"
    assert r["grouped_agg"]["strategy"] == "sort"
    assert r["join"]["strategy"] == "sort"


# ----------------------------------- review-hardening regressions (r12)

def test_load_factor_one_cannot_silently_drop_groups(monkeypatch):
    """`DAFT_TPU_KERNEL_HASH_LOAD=1.0` used to make the table exactly
    `out_cap` slots — it filled silently instead of signalling
    `group_count > out_cap`, truncating the answer. The clamp now keeps
    the table strictly larger than the group budget, so overflow always
    signals."""
    monkeypatch.setenv("DAFT_TPU_KERNEL_HASH_LOAD", "1.0")
    assert pk.table_capacity(128) > 128
    C, ndv = 256, 200
    ones = jnp.ones(C, bool)
    out = pk.hash_grouped_agg_impl(
        (jnp.asarray(np.arange(C) % ndv, jnp.int64),), (ones,),
        (jnp.ones(C, jnp.float32),), (ones,), ones, ("sum",), 128,
        interpret=True, block=64)
    assert int(np.asarray(out[-1])) > 128  # overflow signalled, not eaten


def test_saturated_hash_overflow_switches_to_sort(monkeypatch):
    """A completely FULL hash table reports only a lower bound on the
    group count, so the overflow re-dispatch switches to the sort
    strategy (whose header is exact) instead of doubling the hash
    bucket one full row pass at a time: hash@128 (saturated) →
    sort (true count) → hash at the fitting bucket = 3 dispatches."""
    from daft_tpu.aggs import split_agg_expr
    from daft_tpu.device import fragment
    monkeypatch.setenv("DAFT_TPU_DEVICE", "1")
    monkeypatch.setenv("DAFT_TPU_DEVICE_FORCE", "1")
    monkeypatch.setenv("DAFT_TPU_KERNEL_GROUPBY", "hash")
    n, ndv = 2048, 1500
    rb = RecordBatch.from_pydict(
        {"k": [int(i % ndv) for i in range(n)],
         "v": [float(i % 7) for i in range(n)]})
    agg = daft_tpu.col("v").sum().alias("s")
    op, child, name, _pred = split_agg_expr(agg)
    gexprs = [daft_tpu.col("k")]
    prog = fragment.get_fused_agg(
        gexprs, [(child if child is not None else daft_tpu.lit(True))
                 .alias("__v0__")], (op,), None, rb.schema)
    assert prog is not None
    host = rb.agg([agg], gexprs)
    costmodel.ledger_reset()
    out = fragment.run_fused_agg(prog, rb, gexprs, [daft_tpu.col(name)],
                                 host.schema)
    snap = costmodel.ledger_snapshot()
    costmodel.ledger_reset()
    assert out is not None
    got = dict(zip(out.to_pydict()["k"], out.to_pydict()["s"]))
    want = dict(zip(host.to_pydict()["k"], host.to_pydict()["s"]))
    assert len(got) == ndv
    for k in want:
        assert got[k] == pytest.approx(want[k], rel=1e-5)
    assert snap["grouped_agg"]["dispatches"] == 3, snap["grouped_agg"]


def test_interpret_knob_auto_means_autodetect(monkeypatch):
    """Exporting the knob's documented default spelling (`auto`) must
    mean backend autodetection, not force-the-emulator — on silicon that
    would silently run every hash kernel as a python-level emulation."""
    from daft_tpu.device import backend
    monkeypatch.setenv("DAFT_TPU_KERNEL_INTERPRET", "auto")
    monkeypatch.setattr(backend, "backend_name", lambda: "tpu")
    assert pk.interpret_default() is False   # autodetect follows silicon
    monkeypatch.setenv("DAFT_TPU_KERNEL_INTERPRET", "1")
    assert pk.interpret_default() is True    # explicit force still wins
    monkeypatch.setattr(backend, "backend_name", lambda: "cpu")
    monkeypatch.setenv("DAFT_TPU_KERNEL_INTERPRET", "0")
    assert pk.interpret_default() is False


def test_join_overflow_past_table_ceiling_switches_to_sort(monkeypatch):
    """A many-to-many blowup whose grown output bucket exceeds the
    on-chip slot ceiling re-dispatches on the SORT kernel (the hash
    probe pins two cap-sized index planes on-chip; XLA's buffers live in
    HBM) — and the ledger accounts each strategy's dispatch separately."""
    from daft_tpu import joins
    monkeypatch.setenv("DAFT_TPU_DEVICE_FORCE", "1")
    monkeypatch.setenv("DAFT_TPU_KERNEL_JOIN", "hash")
    monkeypatch.setenv("DAFT_TPU_KERNEL_MAX_TABLE", "2048")
    n = 400  # 400*400 pairs → bucket_capacity(160000) >> 2048 slots
    lk = np.zeros(n, np.int64)
    rk = np.zeros(n, np.int64)
    ones = np.ones(n, bool)
    calls = {"hash": 0, "sort": 0}
    real_h, real_s = pk.hash_join_kernel, K.join_fused_kernel

    def counting_h(*a, **kw):
        calls["hash"] += 1
        return real_h(*a, **kw)

    def counting_s(*a, **kw):
        calls["sort"] += 1
        return real_s(*a, **kw)

    monkeypatch.setattr(pk, "hash_join_kernel", counting_h)
    monkeypatch.setattr(K, "join_fused_kernel", counting_s)
    costmodel.ledger_reset()
    dli, dri, dcnt = joins._device_match_indices(lk, rk, ones, ones)
    snap = costmodel.ledger_snapshot()
    costmodel.ledger_reset()
    assert calls == {"hash": 1, "sort": 1}
    assert len(dli) == n * n
    assert dcnt.tolist() == [n] * n
    assert snap["join"]["strategy"] == "mixed"
    assert snap["join"]["strategy_hash"] == 1
    assert snap["join"]["strategy_sort"] == 1
    assert snap["join"]["dispatches"] == 2


def test_join_strategy_declines_oversized_probe_output(monkeypatch):
    """Auto mode declines hash when the FIRST dispatch's output bucket
    (sized from the larger side) already exceeds the slot ceiling — the
    probe kernel's cap-sized output planes must fit on-chip like the
    build table."""
    monkeypatch.setattr(costmodel, "_hash_capable_backend", lambda: True)
    monkeypatch.delenv("DAFT_TPU_KERNEL_JOIN", raising=False)
    monkeypatch.setenv("DAFT_TPU_KERNEL_MAX_TABLE", "2048")
    assert costmodel._join_strategy(128, 128) == "hash"
    assert costmodel._join_strategy(100_000, 128) == "sort"


def test_mfu_hash_join_measures_admissible_config(monkeypatch):
    """measure_hash_join clamps its row count so the measured config is
    one the strategy model would dispatch: the 2× build table must stay
    within the slot ceiling (an inadmissible config fails to lower on
    silicon and would erase the roofline row)."""
    monkeypatch.setenv("DAFT_TPU_KERNEL_MAX_TABLE", "512")
    out = mfu.measure_hash_join(1 << 20)
    assert out["rows"] == 256
    assert out["table_slots"] <= 512


def test_hash_join_kernel_block_knob_retrace(monkeypatch):
    """The block size is resolved OUTSIDE the trace and passed into the
    jitted program (jit hygiene): changing `DAFT_TPU_KERNEL_BLOCK`
    re-traces at the new block and the answer is unchanged."""
    rng = np.random.default_rng(11)
    C = 64
    lk = jnp.asarray(rng.integers(0, 8, C).astype(np.int64))
    rk = jnp.asarray(rng.integers(0, 8, C).astype(np.int64))
    ones = jnp.ones(C, bool)
    monkeypatch.setenv("DAFT_TPU_KERNEL_BLOCK", "32")
    a = np.asarray(pk.hash_join_kernel(lk, ones, ones, rk, ones, ones,
                                       out_capacity=1024))
    monkeypatch.setenv("DAFT_TPU_KERNEL_BLOCK", "16")
    b = np.asarray(pk.hash_join_kernel(lk, ones, ones, rk, ones, ones,
                                       out_capacity=1024))
    assert np.array_equal(a, b)


def test_fused_agg_strategy_counts_tally_dispatches(monkeypatch):
    """decision_counts describes what DISPATCHED: one fused forced-hash
    group-by tallies exactly its acted-on dispatches (strategy_for is a
    pure ask — the old pre-dispatch logging double-counted re-asks and
    missed width-gate fallbacks entirely)."""
    n, ndv = 1000, 64  # fits the first bucket: no overflow ladder
    data = {"k": [int(i % ndv) for i in range(n)],
            "v": [float(i) for i in range(n)]}
    aggs = (daft_tpu.col("v").sum().alias("s"),)
    host = _host_groupby(data, ("k",), aggs, monkeypatch)
    with costmodel._counts_lock:
        costmodel.decision_counts.pop("groupby_strategy", None)
    costmodel.ledger_reset()
    dev = _device_groupby(data, ("k",), aggs, monkeypatch)
    snap = costmodel.ledger_snapshot()
    costmodel.ledger_reset()
    _pydicts_close(dev, host)
    counts = costmodel.decision_counts.get("groupby_strategy")
    assert counts["host"] == 0  # forced hash: no sort decision tallied
    assert counts["device"] == snap["grouped_agg"]["dispatches"], \
        (counts, snap["grouped_agg"])
