"""Round-6 kernel contracts: packed-key argsort (≤3 sort operands, exact
host agreement), the fused single-dispatch join, and the per-dispatch MFU
ledger.

The argsort parity sweep is property-based in the seeded-random style
(hypothesis is not guaranteed in every environment): ~60 random
configurations over mixed dtypes × descending × nulls_first × null
density, each asserting EXACT permutation agreement with the pyarrow
host path (both sides are stable sorts, so ties must agree too).
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import daft_tpu
from daft_tpu.analysis import rule_jit
from daft_tpu.device import costmodel, kernels as K
from daft_tpu.recordbatch import RecordBatch


# ---------------------------------------------------------------- argsort

def _random_frame(rng, n, dtypes):
    """pydict of random columns (with nulls) for the requested dtypes."""
    data = {}
    for i, dt in enumerate(dtypes):
        nulls = rng.random(n) < rng.choice([0.0, 0.15, 0.5])
        if dt == "int":
            v = rng.integers(-2**40, 2**40, n).tolist()
        elif dt == "small_int":
            v = rng.integers(-3, 3, n).tolist()  # heavy ties
        elif dt == "float":
            v = np.round(rng.uniform(-1e6, 1e6, n), 3).tolist()
        elif dt == "bool":
            v = (rng.random(n) > 0.5).tolist()
        else:  # string
            v = ["s" + str(rng.integers(0, 8)) for _ in range(n)]
        data[f"c{i}"] = [None if m else x for x, m in zip(v, nulls)]
    return data


@pytest.mark.parametrize("seed", range(12))
def test_argsort_device_matches_host_property(seed, monkeypatch):
    """Exact permutation agreement between the packed-key device argsort
    and the pyarrow host path over random frames/orderings."""
    rng = np.random.default_rng(seed)
    n = int(rng.integers(2, 80))
    n_keys = int(rng.integers(1, 4))
    dtypes = [rng.choice(["int", "small_int", "float", "bool", "string"])
              for _ in range(n_keys)]
    data = _random_frame(rng, n, dtypes)
    rb = RecordBatch.from_pydict(data)
    keys = [daft_tpu.col(f"c{i}") for i in range(n_keys)]
    for trial in range(5):
        desc = [bool(rng.integers(0, 2)) for _ in range(n_keys)]
        nf = [bool(rng.integers(0, 2)) for _ in range(n_keys)]
        monkeypatch.delenv("DAFT_TPU_DEVICE_FORCE", raising=False)
        monkeypatch.setenv("DAFT_TPU_DEVICE", "0")
        host = rb.argsort(keys, desc, nf)
        monkeypatch.setenv("DAFT_TPU_DEVICE", "1")
        monkeypatch.setenv("DAFT_TPU_DEVICE_FORCE", "1")
        dev = rb.argsort(keys, desc, nf)
        assert list(dev) == list(host), (dtypes, desc, nf)


def test_argsort_f32_codes_match_reference():
    """f32 value codes (the TPU backend's float plane — f64 rides f32
    there) order exactly like the float values, including -0.0."""
    vals = np.array([0.0, -0.0, 1.5, -1.5, np.inf, -np.inf, 3e-9],
                    np.float32)
    C = 16
    k = np.zeros(C, np.float32)
    k[:len(vals)] = vals
    mask = np.zeros(C, bool)
    mask[:len(vals)] = True
    ones = np.ones(C, bool)
    for desc in (False, True):
        perm = np.asarray(K.argsort_kernel(
            (jnp.asarray(k),), (jnp.asarray(ones),), jnp.asarray(mask),
            (desc,), (False,)))[:len(vals)]
        got = [vals[i] for i in perm]
        # IEEE total order (what lax.sort uses too): -0.0 before 0.0
        ref = sorted(list(vals),
                     key=lambda v: (v, not np.signbit(v)), reverse=desc)
        assert [str(x) for x in got] == [str(x) for x in ref], (desc, got)


# the jaxpr walk + contract numbers are single-sourced in the jit-hygiene
# lint rule (daft_tpu/analysis/rule_jit.py) — tests and
# `python -m daft_tpu.analysis` prove the SAME contracts


@pytest.mark.parametrize("n_keys,dtype", rule_jit.ARGSORT_CASES)
def test_argsort_compiles_with_at_most_3_sort_operands(n_keys, dtype):
    """The operand-count cliff contract: ≤3 operands per lax.sort for ANY
    key count (the 2k+1-plane formulation hit >5-minute TPU compiles)."""
    jaxpr = rule_jit.argsort_jaxpr(n_keys, dtype)
    assert rule_jit.max_sort_operands(jaxpr.jaxpr) \
        <= rule_jit.ARGSORT_MAX_SORT_OPERANDS


def test_grouped_agg_sorts_stay_under_operand_cliff():
    """The grouped-agg kernels ride the same packed sort: ≤3 operands
    regardless of key count."""
    jaxpr = rule_jit.grouped_agg_jaxpr(n_keys=5)
    assert rule_jit.max_sort_operands(jaxpr.jaxpr) \
        <= rule_jit.ARGSORT_MAX_SORT_OPERANDS


def test_fused_join_jaxpr_has_no_host_callbacks():
    """The single-dispatch contract, statically: the fused join program
    contains zero host-callback primitives (a host round-trip inside the
    fused program would silently reintroduce the per-phase transfers)."""
    jx = rule_jit.join_fused_jaxpr()
    for prim in rule_jit.FORBIDDEN_IN_FUSED_JOIN:
        assert rule_jit.count_primitive(jx.jaxpr, prim) == 0


def test_lint_dispatch_contract_checker_is_clean():
    """The lint rule's own contract re-verification (what CI runs via
    `python -m daft_tpu.analysis`) agrees with the tests above."""
    assert rule_jit.check_dispatch_contracts() == []


def test_argsort_radix_passes_scale_with_key_bits():
    assert K.argsort_pack_plan([np.float32]) == [1]       # 34 bits
    assert K.argsort_pack_plan([np.float32] * 2) == [2]   # 67 bits
    assert K.argsort_pack_plan([np.int64]) == [2]         # 66 bits
    # 3 x 65-bit keys = 196 bits → two passes
    assert len(K.argsort_pack_plan([np.int64] * 3)) == 2


# ------------------------------------------------------------- fused join

def _join_keys(seed=3):
    rng = np.random.default_rng(seed)
    lk = rng.integers(0, 50, 400)
    rk = rng.integers(0, 50, 150)
    lv = rng.random(400) > 0.1
    rv = rng.random(150) > 0.1
    return lk, rk, lv, rv


def test_fused_join_is_one_dispatch_with_host_identical_indices(
        monkeypatch):
    """The fused kernel must be dispatched EXACTLY once per build/probe
    pair (no per-phase dispatches, no host round-trips between phases),
    and its indices must match the host merge exactly."""
    from daft_tpu import joins
    lk, rk, lv, rv = _join_keys()
    calls = {"n": 0}
    real = K.join_fused_kernel

    def counting(*a, **kw):
        calls["n"] += 1
        return real(*a, **kw)

    monkeypatch.setattr(K, "join_fused_kernel", counting)
    out = joins._device_match_indices(lk, rk, lv, rv)
    assert out is not None
    assert calls["n"] == 1, f"expected ONE dispatch, saw {calls['n']}"
    dli, dri, dcnt = out
    monkeypatch.setenv("DAFT_TPU_DEVICE_JOIN", "0")
    hli, hri, hcnt = joins.match_indices(lk, rk, lv, rv)
    assert sorted(zip(dli.tolist(), dri.tolist())) == \
        sorted(zip(hli.tolist(), hri.tolist()))
    assert np.array_equal(dcnt, hcnt)


def test_fused_join_overflow_redispatches_once(monkeypatch):
    """A many-to-many blowup past the FK-shaped output estimate re-runs
    at the fitting bucket — two dispatches, still correct."""
    from daft_tpu import joins
    n = 1200  # 1200*1200 pairs ≫ bucket_capacity(1200)=2048 slots
    lk = np.zeros(n, np.int64)
    rk = np.zeros(n, np.int64)
    ones = np.ones(n, bool)
    calls = {"n": 0}
    real = K.join_fused_kernel

    def counting(*a, **kw):
        calls["n"] += 1
        return real(*a, **kw)

    monkeypatch.setattr(K, "join_fused_kernel", counting)
    dli, dri, dcnt = joins._device_match_indices(lk, rk, ones, ones)
    assert calls["n"] == 2
    assert len(dli) == n * n
    assert dcnt.tolist() == [n] * n


# ------------------------------------------------------------- MFU ledger

def test_ledger_records_and_derives():
    costmodel.ledger_reset()
    costmodel.ledger_record("argsort", rows=100, nbytes=1e9, seconds=0.5)
    costmodel.ledger_record("argsort", rows=50, nbytes=1e9, seconds=0.5)
    snap = costmodel.ledger_snapshot()
    d = snap["argsort"]
    assert d["dispatches"] == 2 and d["rows"] == 150
    assert d["achieved_gbps"] == 2.0
    assert d["roofline_pct"] == pytest.approx(
        100.0 * 2e9 / costmodel.hbm_bps(), rel=1e-6)
    costmodel.ledger_reset()
    assert costmodel.ledger_snapshot() == {}


def test_ledger_delta_isolates_a_query():
    costmodel.ledger_reset()
    costmodel.ledger_record("join", rows=10, nbytes=100.0, seconds=0.1)
    before = costmodel.ledger_snapshot(raw=True)
    costmodel.ledger_record("join", rows=7, nbytes=50.0, seconds=0.1)
    costmodel.ledger_record("grouped_agg", rows=3, nbytes=10.0,
                            flops=1e12, seconds=0.2)
    delta = costmodel.ledger_delta(before,
                                   costmodel.ledger_snapshot(raw=True))
    assert delta["join"]["rows"] == 7
    assert delta["grouped_agg"]["mfu_pct"] > 0
    costmodel.ledger_reset()


def test_real_dispatches_feed_the_ledger(monkeypatch):
    """try_argsort and the device join both account their dispatches."""
    costmodel.ledger_reset()
    monkeypatch.setenv("DAFT_TPU_DEVICE_FORCE", "1")
    rb = RecordBatch.from_pydict({"a": [3, 1, 2, None, 5]})
    rb.argsort([daft_tpu.col("a")], [False], [False])
    from daft_tpu import joins
    lk, rk, lv, rv = _join_keys()
    joins._device_match_indices(lk, rk, lv, rv)
    snap = costmodel.ledger_snapshot()
    assert snap["argsort"]["dispatches"] == 1
    assert snap["argsort"]["rows"] == 5
    assert snap["join"]["dispatches"] == 1
    assert snap["join"]["bytes"] > 0 and snap["join"]["seconds"] > 0
    costmodel.ledger_reset()


def test_query_stats_carry_ledger_delta(monkeypatch):
    """observability: a query's RuntimeStatsContext reports the device
    dispatches IT caused, and render() prints them."""
    from daft_tpu import observability as obs
    costmodel.ledger_reset()
    ctx = obs.new_query_stats()
    costmodel.ledger_record("argsort", rows=9, nbytes=1e6, seconds=0.01)
    ctx.finish()
    assert ctx.device_kernels["argsort"]["rows"] == 9
    assert "argsort" in ctx.render()
    # a later query must not re-report the same work
    ctx2 = obs.new_query_stats()
    ctx2.finish()
    assert ctx2.device_kernels == {}
    costmodel.ledger_reset()


def test_mfu_report_embeds_ledger():
    from daft_tpu.device import mfu
    costmodel.ledger_reset()
    costmodel.ledger_record("join", rows=4, nbytes=1.0, seconds=0.1)
    r = mfu.report(n=1 << 10)
    assert "error" not in r, r
    assert r["ledger"]["join"]["dispatches"] == 1
    assert r["argsort"]["sort_passes"] == 1
    costmodel.ledger_reset()


def test_dispatch_log_appends_are_serialized(tmp_path, monkeypatch):
    """Concurrent decision logging must never interleave JSONL lines."""
    import json
    import threading
    log = tmp_path / "d.jsonl"
    monkeypatch.setenv("DAFT_TPU_DISPATCH_LOG", str(log))
    monkeypatch.setenv("DAFT_TPU_LINK_RTT_MS", "10")
    monkeypatch.setenv("DAFT_TPU_LINK_UP_MBPS", "50")
    monkeypatch.setenv("DAFT_TPU_LINK_DOWN_MBPS", "50")
    costmodel.reset_for_tests()

    def spam():
        for _ in range(200):
            costmodel.row_output_op_wins(1e6, 1e6, host_bytes=2e6)

    threads = [threading.Thread(target=spam) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    lines = log.read_text().splitlines()
    assert len(lines) == 1600
    for ln in lines:
        json.loads(ln)  # every line parses — no interleaving
    costmodel.reset_for_tests()


# ------------------------------------------------- fused-agg group gate

def test_fused_gate_falls_back_to_row_estimate():
    from daft_tpu.execution import pipeline as pl

    class Node:
        group_by = ("k",)
        aggs = ("s",)

    n = Node()
    n.group_ndv = None
    n.group_rows_est = None
    assert pl._fused_groups_admissible(n)          # no evidence: default
    n.group_rows_est = pl._FUSE_MAX_GROUPS + 1
    assert not pl._fused_groups_admissible(n)      # row estimate declines
    n.group_ndv = 1000.0                           # footer evidence wins
    assert pl._fused_groups_admissible(n)


def test_fused_gate_respects_memory_budget(monkeypatch):
    from daft_tpu.execution import pipeline as pl

    class Node:
        group_by = ("k",)
        aggs = ("a", "b")

    n = Node()
    n.group_ndv = 10_000_000.0  # under the group cap …
    n.group_rows_est = None
    monkeypatch.setenv("DAFT_TPU_MEMORY_LIMIT", "64MB")
    assert not pl._fused_groups_admissible(n)  # … but not under 64MB
    monkeypatch.setenv("DAFT_TPU_MEMORY_LIMIT", "64GB")
    assert pl._fused_groups_admissible(n)
