"""Client-driven Spark Connect conformance (VERDICT r2 item 10): the
vendored pyspark-flavored client (``daft_tpu/connect/client.py``) drives
read / filter / agg / join / SQL / write end-to-end over the wire —
pyspark itself is not installable in this environment, so the client
mirrors its request patterns (UserContext + client_type + operation_id,
analyze-then-execute, Arrow-IPC streaming decode)."""

import pyarrow.parquet as pq
import pytest

from daft_tpu.connect import start_server
from daft_tpu.connect.client import col, connect, lit, _agg_fn


@pytest.fixture(scope="module")
def spark():
    server = start_server()
    s = connect(f"127.0.0.1:{server.port}")
    yield s
    s.stop()
    server.stop()


def test_version(spark):
    assert spark.version


def test_range_filter_select_collect(spark):
    rows = (spark.range(100)
            .filter(col("id") >= 95)
            .select((col("id") * 2).alias("x"))
            .sort("x").collect())
    assert [r["x"] for r in rows] == [190, 192, 194, 196, 198]


def test_create_dataframe_groupby_agg(spark):
    df = spark.createDataFrame({"k": ["a", "a", "b"], "v": [1, 2, 10]})
    rows = (df.groupBy("k")
            .agg(_agg_fn("sum", col("v")).alias("s"))
            .sort("k").collect())
    assert rows == [{"k": "a", "s": 3}, {"k": "b", "s": 10}]


def test_join(spark):
    left = spark.createDataFrame({"k": [1, 2, 3], "v": ["x", "y", "z"]})
    right = spark.createDataFrame({"k": [2, 3, 4], "w": [20, 30, 40]})
    rows = left.join(right, on="k").sort("k").collect()
    assert rows == [{"k": 2, "v": "y", "w": 20},
                    {"k": 3, "v": "z", "w": 30}]


def test_sql_and_temp_view(spark):
    df = spark.createDataFrame({"x": [1, 2, 3, 4]})
    df.createOrReplaceTempView("nums")
    rows = spark.sql(
        "SELECT sum(x) AS total FROM nums WHERE x > 1").collect()
    assert rows == [{"total": 9}]


def test_schema_analyze(spark):
    import pyarrow as pa
    s = spark.createDataFrame({"a": [1], "b": ["x"]}).schema
    assert isinstance(s, pa.Schema)
    assert s.names == ["a", "b"]
    assert pa.types.is_integer(s.field("a").type)
    assert pa.types.is_large_string(s.field("b").type) \
        or pa.types.is_string(s.field("b").type)


def test_write_then_read_parquet(spark, tmp_path):
    out = str(tmp_path / "out")
    spark.createDataFrame({"a": [1, 2, 3]}).write.parquet(out)
    back = spark.read_parquet(out + "/*.parquet").sort("a").collect()
    assert [r["a"] for r in back] == [1, 2, 3]


def test_with_column_and_limit(spark):
    rows = (spark.range(10).withColumn("double", col("id") * 2)
            .sort("id").limit(3).collect())
    assert [r["double"] for r in rows] == [0, 2, 4]
