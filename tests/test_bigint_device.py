"""64-bit integrity on the device tier (VERDICT r1 item 6): keys beyond
int32 range and sums beyond 2^31 must round-trip the device kernels exactly.

Policy: x64 is enabled engine-wide (device/column.py); integer aggregation
lanes accumulate in int64 via exact segment sums (kernels.py block path),
and int64 sort keys ride lax.sort's emulated s64 on TPU. Floats without
native f64 (TPU) run in f32 — covered by tolerance-based tests elsewhere;
these tests are about exact integer semantics."""

import numpy as np
import pytest

import daft_tpu
from daft_tpu import col


@pytest.fixture(autouse=True)
def _device_on(monkeypatch):
    monkeypatch.setenv("DAFT_TPU_DEVICE", "1")
    yield


def _host(df_fn, monkeypatch_env=None):
    import os
    os.environ["DAFT_TPU_DEVICE"] = "0"
    try:
        return df_fn()
    finally:
        os.environ["DAFT_TPU_DEVICE"] = "1"


def test_groupby_keys_beyond_int32():
    # TPC-H SF100 orderkeys reach ~6e9: group keys must not truncate
    rng = np.random.default_rng(0)
    base = 6_000_000_000
    keys = (base + rng.integers(0, 5, 5000)).tolist()
    vals = rng.integers(0, 100, 5000).tolist()
    df = daft_tpu.from_pydict({"k": keys, "v": vals})
    q = lambda: df.groupby("k").agg(col("v").sum().alias("s")) \
        .sort("k").to_pydict()
    got = q()
    want = _host(q)
    assert got == want
    assert all(k > 2**31 for k in got["k"])


def test_int_sums_beyond_int32():
    # per-group sums overflow int32 by orders of magnitude: must be exact
    n = 4096
    big = 3_000_000_000
    df = daft_tpu.from_pydict({
        "k": [i % 3 for i in range(n)],
        "v": [big + i for i in range(n)]})
    got = df.groupby("k").agg(col("v").sum().alias("s")).sort("k") \
        .to_pydict()
    expect = {}
    for i in range(n):
        expect[i % 3] = expect.get(i % 3, 0) + big + i
    assert got["s"] == [expect[k] for k in got["k"]]
    assert min(got["s"]) > 2**41  # genuinely wide sums


def test_global_sum_beyond_int32():
    n = 5000
    df = daft_tpu.from_pydict({"v": [2_000_000_000 + i for i in range(n)]})
    got = df.agg(col("v").sum().alias("s")).to_pydict()["s"][0]
    assert got == sum(2_000_000_000 + i for i in range(n))


def test_sort_keys_beyond_int32():
    rng = np.random.default_rng(1)
    keys = (6_000_000_000 + rng.permutation(3000)).tolist()
    df = daft_tpu.from_pydict({"k": keys})
    out = df.sort("k").to_pydict()["k"]
    assert out == sorted(keys)


def test_min_max_at_int64_extremes():
    vals = [2**62, -2**62, 17, 0]
    df = daft_tpu.from_pydict({"k": [1, 1, 1, 1], "v": vals})
    out = df.groupby("k").agg(col("v").min().alias("lo"),
                              col("v").max().alias("hi")).to_pydict()
    assert out["lo"] == [-2**62]
    assert out["hi"] == [2**62]
