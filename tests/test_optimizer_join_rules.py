"""Before/after unit fixtures for the four join rewrite rules the plan
fuzzer stresses hardest: SimplifyNullFilteredJoin, FilterNullJoinKey,
SemiJoinReduction, PushDownJoinPredicate. Each test builds the BEFORE
plan from a dataframe program, applies the single rule, and asserts the
rewrite shape AND that the root schema is preserved (every one of these
is registered schema-preserving in analysis/plan_contracts.py)."""

import pytest

import daft_tpu as dt
from daft_tpu import col
from daft_tpu.logical import plan as lp
from daft_tpu.logical.optimizer import (
    FilterNullJoinKey, PushDownJoinPredicate, SemiJoinReduction,
    SimplifyNullFilteredJoin, split_conjuncts,
)


def walk(plan):
    yield plan
    for c in plan.children:
        yield from walk(c)


def joins(plan):
    return [n for n in walk(plan) if isinstance(n, lp.Join)]


def left_df():
    return dt.from_pydict({"k": [1, 2, 3, None], "v": [10, 20, 30, 40]})


def right_df():
    return dt.from_pydict({"rk": [2, 3, None], "w": [7, 8, 9]})


def apply(rule, df):
    before = df._builder._plan
    after = rule.apply(before)
    assert list(after.schema().fields) == list(before.schema().fields), \
        "rule must preserve the root schema"
    return before, after


# ------------------------------------------------ SimplifyNullFilteredJoin


def test_null_rejecting_filter_strengthens_left_to_inner():
    q = (left_df().join(right_df(), left_on="k", right_on="rk",
                        how="left")
         .where(col("w") > 0))
    _, after = apply(SimplifyNullFilteredJoin(), q)
    assert [j.how for j in joins(after)] == ["inner"]


def test_outer_strengthens_by_rejected_side():
    base = left_df().join(right_df(), left_on="k", right_on="rk",
                          how="outer")
    # rejecting a RIGHT column kills left-unmatched rows → RIGHT join
    _, after = apply(SimplifyNullFilteredJoin(), base.where(col("w") > 0))
    assert [j.how for j in joins(after)] == ["right"]
    # rejecting a LEFT column → LEFT join
    _, after = apply(SimplifyNullFilteredJoin(), base.where(col("v") > 0))
    assert [j.how for j in joins(after)] == ["left"]
    # rejecting both sides → inner
    _, after = apply(SimplifyNullFilteredJoin(),
                     base.where((col("v") > 0) & (col("w") > 0)))
    assert [j.how for j in joins(after)] == ["inner"]


def test_filter_on_preserved_side_does_not_strengthen():
    q = (left_df().join(right_df(), left_on="k", right_on="rk",
                        how="left")
         .where(col("v") > 0))  # left columns are never NULL-padded here
    before, after = apply(SimplifyNullFilteredJoin(), q)
    assert [j.how for j in joins(after)] == ["left"]
    assert after.semantic_id() == before.semantic_id()


def test_null_safe_predicate_does_not_strengthen():
    q = (left_df().join(right_df(), left_on="k", right_on="rk",
                        how="left")
         .where(col("w").is_null()))  # keeps NULL rows: not null-rejecting
    _, after = apply(SimplifyNullFilteredJoin(), q)
    assert [j.how for j in joins(after)] == ["left"]


# ----------------------------------------------------- FilterNullJoinKey


def _null_filter_sides(plan):
    """(left_filtered, right_filtered) for the single join in plan."""
    (j,) = joins(plan)

    def filtered(child, key):
        return (isinstance(child, lp.Filter)
                and any(c._unalias().op == "not_null"
                        and set(c.column_names()) == {key}
                        for c in split_conjuncts(child.predicate)))
    return filtered(j.children[0], "k"), filtered(j.children[1], "rk")


@pytest.mark.parametrize("how,expect", [
    ("inner", (True, True)),
    ("semi", (True, True)),
    ("left", (False, True)),
    ("right", (True, False)),
    ("anti", (False, True)),
])
def test_null_key_prefilter_side_table(how, expect):
    q = left_df().join(right_df(), left_on="k", right_on="rk", how=how)
    _, after = apply(FilterNullJoinKey(), q)
    assert _null_filter_sides(after) == expect


def test_null_key_prefilter_idempotent():
    q = left_df().join(right_df(), left_on="k", right_on="rk",
                       how="inner")
    _, once = apply(FilterNullJoinKey(), q)
    twice = FilterNullJoinKey().apply(once)
    assert twice.semantic_id() == once.semantic_id()


def test_null_key_prefilter_changes_no_answer():
    q = left_df().join(right_df(), left_on="k", right_on="rk",
                       how="inner")
    assert sorted(zip(*q.to_pydict().values())) == \
        sorted([(2, 20, 2, 7), (3, 30, 3, 8)])


# ----------------------------------------------------- SemiJoinReduction


def _small_thresholds(monkeypatch):
    monkeypatch.setattr(SemiJoinReduction, "MIN_ROWS", 10)
    monkeypatch.setattr(SemiJoinReduction, "RATIO", 1.5)


def test_semi_join_reduction_rewrites_distinct_side(monkeypatch):
    _small_thresholds(monkeypatch)
    a = dt.from_pydict({"k": [1, 2, 3], "v": [1, 2, 3]})
    s = dt.from_pydict({"k": [i % 8 for i in range(64)],
                        "x": list(range(64))})
    q = a.join(s.select("k").distinct(), left_on="k", right_on="k",
               how="inner")
    before, after = apply(SemiJoinReduction(), q)
    semis = [j for j in joins(after) if j.how == "semi"]
    assert semis, "expected a semi-join key prefilter under the Distinct"
    # the transferred key projection uses content-derived fresh names
    assert any(n.startswith("__sjr") for j in semis
               for n in (e.name() for e in j.right_on))
    assert len(joins(before)) == 1 and len(joins(after)) == 2


def test_semi_join_reduction_respects_thresholds():
    # default MIN_ROWS=500k: a 64-row side must never churn the plan
    a = dt.from_pydict({"k": [1, 2, 3], "v": [1, 2, 3]})
    s = dt.from_pydict({"k": [i % 8 for i in range(64)],
                        "x": list(range(64))})
    q = a.join(s.select("k").distinct(), left_on="k", right_on="k",
               how="inner")
    before, after = apply(SemiJoinReduction(), q)
    assert after.semantic_id() == before.semantic_id()


def test_semi_join_reduction_preserves_answer(monkeypatch):
    _small_thresholds(monkeypatch)
    a = dt.from_pydict({"k": [1, 2, 3], "v": [1, 2, 3]})
    s = dt.from_pydict({"k": [i % 8 for i in range(64)],
                        "x": list(range(64))})
    q = a.join(s.select("k").distinct(), left_on="k", right_on="k",
               how="inner")
    plain = sorted(zip(*q.to_pydict().values()))
    from daft_tpu.execution.executor import LocalExecutor
    from daft_tpu.physical.translate import translate
    rewritten = SemiJoinReduction().apply(q._builder._plan)
    parts = list(LocalExecutor().run(translate(rewritten)))
    got = {name: [] for name in rewritten.schema().column_names}
    for p in parts:
        for name, vals in p.to_pydict().items():
            got[name].extend(vals)
    assert sorted(zip(*got.values())) == plain


# -------------------------------------------------- PushDownJoinPredicate


def test_key_predicate_transfers_across_join():
    q = (left_df().where(col("k") > 1)
         .join(right_df(), left_on="k", right_on="rk", how="inner"))
    _, after = apply(PushDownJoinPredicate(), q)
    (j,) = joins(after)
    right = j.children[1]
    assert isinstance(right, lp.Filter)
    transferred = [c for c in split_conjuncts(right.predicate)
                   if set(c.column_names()) == {"rk"}]
    assert transferred, "k>1 should clone to the right side as rk>1"


def test_key_predicate_transfers_right_to_left():
    q = left_df().join(right_df().where(col("rk") >= 2),
                       left_on="k", right_on="rk", how="semi")
    _, after = apply(PushDownJoinPredicate(), q)
    (j,) = joins(after)
    left = j.children[0]
    assert isinstance(left, lp.Filter)
    assert any(set(c.column_names()) == {"k"}
               for c in split_conjuncts(left.predicate))


def test_non_key_predicates_do_not_transfer():
    q = (left_df().where(col("v") > 15)  # v is not a join key
         .join(right_df(), left_on="k", right_on="rk", how="inner"))
    before, after = apply(PushDownJoinPredicate(), q)
    assert after.semantic_id() == before.semantic_id()


def test_key_predicate_transfer_idempotent_and_correct():
    q = (left_df().where(col("k") > 1)
         .join(right_df(), left_on="k", right_on="rk", how="inner"))
    _, once = apply(PushDownJoinPredicate(), q)
    twice = PushDownJoinPredicate().apply(once)
    assert twice.semantic_id() == once.semantic_id()
    assert sorted(zip(*q.to_pydict().values())) == \
        sorted([(2, 20, 2, 7), (3, 30, 3, 8)])
