"""Shuffle fast path: map-side combine (Partial Partial Aggregates),
compressed transport (Arrow IPC buffer compression + chunked/incremental
HTTP transfer), and the parallel pipelined reduce-side fetch
(``distributed/shuffle_service.py``, ``distributed/worker.py``,
``distributed/stages.py``)."""

import threading
import time

import numpy as np
import pyarrow as pa
import pytest

import daft_tpu
from daft_tpu import col
from daft_tpu.distributed import shuffle_service as ss
from daft_tpu.distributed.worker import (FetchSpec, _ParallelFetch,
                                         _stream_safe)
from daft_tpu.physical import plan as pp
from daft_tpu.runners.distributed_runner import DistributedRunner


def _run_distributed(df, num_workers=3):
    import daft_tpu.context as ctx
    runner = DistributedRunner(num_workers=num_workers)
    old = ctx.get_context()._runner
    ctx.get_context().set_runner(runner)
    try:
        return df.to_pydict()
    finally:
        ctx.get_context().set_runner(old)


def _frame(n=6000, nkeys=7, parts=4, seed=3):
    rng = np.random.default_rng(seed)
    return daft_tpu.from_pydict({
        "k": rng.integers(0, nkeys, n).tolist(),
        "v": [float(i) for i in range(n)],
        "w": rng.uniform(0, 10, n).round(3).tolist(),
    }).into_partitions(parts)


def _approx_eq(a, b):
    for x, y in zip(a, b):
        assert x == pytest.approx(y, rel=1e-9), (a, b)


# ------------------------------------------------------- map-side combine
def test_combine_parity_on_decomposable_aggs(monkeypatch):
    """Combine forced ON: the distributed answer over every decomposable
    agg family matches the single-node engine exactly, and the wire
    carries fewer rows than entered the combine."""
    monkeypatch.setenv("DAFT_TPU_DEVICE", "0")
    monkeypatch.setenv("DAFT_TPU_DISTRIBUTED_SHUFFLE", "flight")
    monkeypatch.setenv("DAFT_TPU_SHUFFLE_COMBINE", "1")

    def q(df):
        return (df.groupby("k")
                .agg(col("v").sum().alias("s"),
                     col("w").mean().alias("m"),
                     col("v").count().alias("c"),
                     col("w").min().alias("lo"),
                     col("w").max().alias("hi"),
                     col("v").stddev().alias("sd"))
                .sort("k").to_pydict())

    local = q(_frame())
    before = ss.shuffle_counters_snapshot()
    dist = _run_distributed(
        _frame().groupby("k").agg(
            col("v").sum().alias("s"), col("w").mean().alias("m"),
            col("v").count().alias("c"), col("w").min().alias("lo"),
            col("w").max().alias("hi"),
            col("v").stddev().alias("sd")).sort("k"))
    d = ss.shuffle_counters_delta(before)
    assert dist["k"] == local["k"]
    assert dist["c"] == local["c"]
    for name in ("s", "m", "lo", "hi", "sd"):
        _approx_eq(dist[name], local[name])
    assert d.get("combine_rows_in", 0) > 0, d
    assert d.get("combine_rows_out", 0) <= d["combine_rows_in"], d


def test_mixed_decomposable_and_fallback_aggs(monkeypatch):
    """An aggregate set mixing decomposable (sum) with non-decomposable
    (count_distinct) falls back to today's single-stage plan — no combine
    runs, and the answer still matches the single-node engine."""
    monkeypatch.setenv("DAFT_TPU_DEVICE", "0")
    monkeypatch.setenv("DAFT_TPU_DISTRIBUTED_SHUFFLE", "flight")
    monkeypatch.setenv("DAFT_TPU_SHUFFLE_COMBINE", "1")

    def q(df):
        return (df.groupby("k")
                .agg(col("v").sum().alias("s"),
                     col("v").count_distinct().alias("nd"))
                .sort("k").to_pydict())

    local = q(_frame(n=2500))
    before = ss.shuffle_counters_snapshot()
    dist = _run_distributed(
        _frame(n=2500).groupby("k").agg(
            col("v").sum().alias("s"),
            col("v").count_distinct().alias("nd")).sort("k"))
    d = ss.shuffle_counters_delta(before)
    assert dist["k"] == local["k"]
    assert dist["nd"] == local["nd"]
    _approx_eq(dist["s"], local["s"])
    assert d.get("combine_rows_in", 0) == 0, d  # fallback: no combine


def test_combine_escape_hatch_and_wire_reduction(monkeypatch):
    """DAFT_TPU_SHUFFLE_COMBINE=0 disables the combine; the fast path
    (combine on) pushes measurably fewer rows over the wire for the same
    query and both answers agree."""
    monkeypatch.setenv("DAFT_TPU_DEVICE", "0")
    monkeypatch.setenv("DAFT_TPU_DISTRIBUTED_SHUFFLE", "flight")

    def run(combine):
        monkeypatch.setenv("DAFT_TPU_SHUFFLE_COMBINE", combine)
        before = ss.shuffle_counters_snapshot()
        out = _run_distributed(
            _frame(n=8000, nkeys=5).groupby("k")
            .agg(col("v").sum().alias("s")).sort("k"))
        return out, ss.shuffle_counters_delta(before)

    off_out, off_c = run("0")
    on_out, on_c = run("1")
    assert off_out["k"] == on_out["k"]
    _approx_eq(off_out["s"], on_out["s"])
    assert off_c.get("combine_rows_in", 0) == 0
    assert on_c.get("combine_rows_in", 0) > 0
    assert on_c.get("rows_pushed", 0) < off_c.get("rows_pushed", 0), \
        (on_c, off_c)


def test_combine_cost_model_declines_near_unique_keys():
    """The pricing: reductive group-bys combine, near-unique keys (zero
    wire savings, a wasted agg pass) decline, and no evidence defaults to
    combining."""
    from daft_tpu.device import costmodel
    assert costmodel.shuffle_combine_wins(1_000_000, 4, 8)
    assert not costmodel.shuffle_combine_wins(1_000_000, 900_000, 8)
    assert costmodel.shuffle_combine_wins(None, None, 8)
    assert costmodel.shuffle_combine_wins(0, None, 8)


def test_decomposition_table_is_single_sourced():
    """The planner split, the fused pipeline reducer, and the map-side
    combine must agree on what decomposes: every op the pipeline reducer
    merges is a merge op of the table, and the non-decomposable set is
    disjoint from the table."""
    from daft_tpu import aggs
    assert aggs.SELF_MERGE_OPS == frozenset(
        m for _, m in aggs.AGG_DECOMPOSITION.values())
    assert not set(aggs.AGG_DECOMPOSITION) & aggs.NON_DECOMPOSABLE_AGGS
    # merge helper round-trip: final aggs merge to themselves by name
    from daft_tpu.expressions import col as c
    finals = [c("p0").sum().alias("out0"), c("p1").max().alias("out1")]
    m_out = aggs.merge_exprs_for(finals, alias_to="out")
    assert [e.name() for e in m_out] == ["out0", "out1"]
    m_src = aggs.merge_exprs_for(finals, alias_to="source")
    assert [e.name() for e in m_src] == ["p0", "p1"]
    assert aggs.merge_exprs_for(
        [c("p0").mean().alias("x")], alias_to="out") is None


# ---------------------------------------------------- compressed transport
TRANSPORTS = ["http"] + (["flight"] if ss.paflight is not None else [])
CODECS = ["none", "lz4", "zstd"]


def _codec_available(codec):
    if codec == "none":
        return True
    try:
        import pyarrow.ipc as paipc
        paipc.IpcWriteOptions(compression=codec)
        return True
    except Exception:
        return False


@pytest.mark.parametrize("transport", TRANSPORTS)
@pytest.mark.parametrize("codec", CODECS)
def test_compression_roundtrip_with_straggler(monkeypatch, transport,
                                              codec):
    """Every codec round-trips through spill→serve→fetch, including a
    post-seal straggler append (written as its own compressed stream in a
    single write)."""
    if not _codec_available(codec):
        pytest.skip(f"{codec} not built into this pyarrow")
    monkeypatch.setenv("DAFT_TPU_SHUFFLE_COMPRESSION", codec)
    srv = ss.ShuffleServer() if transport == "http" \
        else ss.FlightShuffleServer()
    try:
        cache = ss.ShuffleCache()
        t = pa.table({"x": list(range(20000)),
                      "s": [f"row-{i % 50}" for i in range(20000)]})
        cache.push(0, t.slice(0, 15000))
        cache.push(0, t.slice(15000))
        srv.register(cache)  # seals
        cache.push(0, pa.table({"x": [-1, -2],
                                "s": ["strag", "strag"]}))
        got = ss.fetch_partition(srv.address, cache.shuffle_id, 0)
        assert got.num_rows == 20002
        assert sorted(got.column("x").to_pylist())[:2] == [-2, -1]
    finally:
        srv.shutdown()


def test_compression_reduces_spill_bytes(monkeypatch):
    """lz4 (the default) writes measurably fewer spill/wire bytes than
    'none' on compressible data, and the fallback for an unknown codec is
    uncompressed, never an error."""
    t = pa.table({"x": list(range(200_000)),
                  "s": ["abcdefgh"] * 200_000})
    sizes = {}
    for codec in ("none", "lz4", "bogus"):
        monkeypatch.setenv("DAFT_TPU_SHUFFLE_COMPRESSION", codec)
        c = ss.ShuffleCache()
        c.push(0, t)
        c.close()
        sizes[codec] = c.partition_size(0)
        c.cleanup()
    if _codec_available("lz4"):
        assert sizes["lz4"] < sizes["none"] * 0.7, sizes
    assert sizes["bogus"] == sizes["none"], sizes


def test_chunked_http_send_and_incremental_read(monkeypatch):
    """A multi-megabyte partition round-trips the HTTP transport (chunked
    send, incremental concatenated-IPC reads) byte-exactly, across
    several writer streams."""
    monkeypatch.setenv("DAFT_TPU_SHUFFLE_COMPRESSION", "none")
    srv = ss.ShuffleServer()
    try:
        cache = ss.ShuffleCache()
        rng = np.random.default_rng(0)
        t = pa.table({"x": rng.integers(0, 1 << 40, 400_000),
                      "y": rng.uniform(size=400_000)})
        cache.push(0, t)
        srv.register(cache)
        cache.push(0, t.slice(0, 1000))  # second stream after seal
        assert cache.partition_size(0) > ss._CHUNK_BYTES  # really chunked
        got = ss.fetch_partition(srv.address, cache.shuffle_id, 0)
        assert got.num_rows == 401_000
        assert got.column("x").to_pylist()[:5] == \
            t.column("x").to_pylist()[:5]
    finally:
        srv.shutdown()


def test_http_error_detail_is_explicit():
    """Satellite: urlopen raises HTTPError on any non-200 — the dead
    status-check branch is gone and the error path surfaces the status
    code in ShuffleFetchError.detail."""
    from daft_tpu.distributed.resilience import ShuffleFetchError
    srv = ss.ShuffleServer()
    try:
        with pytest.raises(ShuffleFetchError) as ei:
            ss.fetch_partition(srv.address, "missing", 0)
        assert "HTTP 404" in ei.value.detail, ei.value.detail
    finally:
        srv.shutdown()


# ------------------------------------------------- parallel pipelined fetch
def _serve_sources(k, rows_each=200, parts=1):
    srv = ss.make_shuffle_server()
    caches = []
    for j in range(k):
        c = ss.ShuffleCache()
        c.push(0, pa.table({"x": list(range(j * rows_each,
                                            (j + 1) * rows_each))}))
        srv.register(c)
        caches.append(c)
    return srv, [(srv.address, c.shuffle_id) for c in caches]


def test_parallel_fetch_overlaps_and_preserves_source_order(monkeypatch):
    """The bounded pool overlaps per-source fetches (≥2 genuinely
    in-flight at once — structural, not wall-clock, so suite load can't
    flake it) and still yields tables in SOURCE order even when
    completions land out of order."""
    srv, srcs = _serve_sources(4)
    orig = ss.fetch_partition
    lock = threading.Lock()
    state = {"inflight": 0, "peak": 0}
    gate = threading.Event()

    def slow(address, shuffle_id, partition, fault_key=None):
        with lock:
            state["inflight"] += 1
            state["peak"] = max(state["peak"], state["inflight"])
            if state["inflight"] >= 2:
                gate.set()  # two fetches provably concurrent
        # stall until overlap is observed (or a generous timeout) so a
        # slow-to-spawn second thread still gets counted
        gate.wait(timeout=10.0)
        try:
            return orig(address, shuffle_id, partition,
                        fault_key=fault_key)
        finally:
            with lock:
                state["inflight"] -= 1

    monkeypatch.setattr(ss, "fetch_partition", slow)
    monkeypatch.setenv("DAFT_TPU_SHUFFLE_FETCH_PARALLELISM", "4")
    try:
        pf = _ParallelFetch(FetchSpec(srcs, 0), streaming=True)
        parts = list(pf)
        assert state["peak"] >= 2, state  # overlapped
        # source order: source j holds rows [j*200, (j+1)*200)
        firsts = [p.to_pydict()["x"][0] for p in parts]
        assert firsts == [0, 200, 400, 600]
    finally:
        srv.shutdown()


def test_chaos_serialize_forces_sequential_single_morsel(monkeypatch):
    """Under DAFT_TPU_CHAOS_SERIALIZE=1 the fast path degrades to the
    deterministic pre-PR behavior: eager sequential fetches, one
    concatenated morsel per stage input."""
    from daft_tpu.distributed.worker import resolve_stage_inputs
    monkeypatch.setenv("DAFT_TPU_CHAOS_SERIALIZE", "1")
    srv, srcs = _serve_sources(3)
    calls = []
    orig = ss.fetch_partition

    def spy(address, shuffle_id, partition, fault_key=None):
        calls.append(shuffle_id)
        return orig(address, shuffle_id, partition, fault_key=fault_key)

    monkeypatch.setattr(ss, "fetch_partition", spy)
    try:
        out = resolve_stage_inputs({0: FetchSpec(srcs, 0)})
        assert isinstance(out[0], list) and len(out[0]) == 1
        assert len(out[0][0]) == 600
        assert calls == [sid for _, sid in srcs]  # sequential, in order
    finally:
        srv.shutdown()


def test_stream_safety_rules():
    """Multi-morsel delivery is only enabled where it preserves
    semantics: merge-safe final aggregate, or row-local chain feeding a
    shuffle-out; Dedup/limit/bare-return shapes stay single-morsel."""
    from daft_tpu.expressions import col as c
    schema = daft_tpu.from_pydict({"k": [1], "s": [1.0]}).schema()
    si = pp.StageInput(7, schema)
    agg = pp.Aggregate(si, [c("s").sum().alias("s")], [c("k")], schema,
                       "final")
    assert _stream_safe(agg, 7, has_shuffle_out=False)
    assert _stream_safe(pp.Project(agg, [c("k"), c("s")], schema), 7,
                        False)
    # non-self-merge agg (mean over raw rows) → unsafe
    agg2 = pp.Aggregate(pp.StageInput(7, schema),
                        [c("s").mean().alias("m")], [c("k")], schema,
                        "single")
    assert not _stream_safe(agg2, 7, False)
    # dedup over the input → unsafe either way
    dd = pp.Dedup(pp.StageInput(7, schema), [c("k")])
    assert not _stream_safe(dd, 7, False)
    assert not _stream_safe(dd, 7, True)
    # bare passthrough: only safe when re-partitioned into a shuffle-out
    bare = pp.StageInput(7, schema)
    assert not _stream_safe(bare, 7, False)
    assert _stream_safe(bare, 7, True)
    # row-local chain: safe only with a shuffle-out
    proj = pp.Project(pp.StageInput(7, schema), [c("k")], schema)
    assert not _stream_safe(proj, 7, False)
    assert _stream_safe(proj, 7, True)


def test_streaming_merge_agg_multi_source_parity(monkeypatch):
    """End-to-end: a reduce aggregate over MANY map sources (streamed as
    one morsel per source) equals the local answer — the streaming
    merge-agg must re-merge across morsels, never aggregate them
    independently."""
    monkeypatch.setenv("DAFT_TPU_DEVICE", "0")
    monkeypatch.setenv("DAFT_TPU_DISTRIBUTED_SHUFFLE", "flight")
    monkeypatch.setenv("DAFT_TPU_SHUFFLE_FETCH_PARALLELISM", "8")

    def q(df):
        return (df.groupby("k").agg(col("v").sum().alias("s"),
                                    col("v").count().alias("c"))
                .sort("k").to_pydict())

    local = q(_frame(n=9000, parts=6))
    dist = _run_distributed(
        _frame(n=9000, parts=6).groupby("k")
        .agg(col("v").sum().alias("s"),
             col("v").count().alias("c")).sort("k"),
        num_workers=4)
    assert dist["k"] == local["k"]
    assert dist["c"] == local["c"]
    _approx_eq(dist["s"], local["s"])


# ------------------------------------------------------------ stats plumbing
def test_runtime_stats_shuffle_block(monkeypatch):
    """RuntimeStatsContext.shuffle carries the per-query data-plane delta
    and explain(analyze) renders it."""
    from daft_tpu import observability as obs
    monkeypatch.setenv("DAFT_TPU_DEVICE", "0")
    monkeypatch.setenv("DAFT_TPU_DISTRIBUTED_SHUFFLE", "flight")
    _run_distributed(_frame(n=4000).groupby("k")
                     .agg(col("v").sum().alias("s")).sort("k"))
    stats = obs.last_query_stats()
    assert stats is not None and stats.shuffle, stats and stats.shuffle
    assert stats.shuffle.get("bytes_written", 0) > 0
    assert stats.shuffle.get("fetches", 0) > 0
    r = stats.render()
    assert "shuffle (data plane):" in r
    assert "written:" in r and "fetched:" in r
