"""Native Iceberg support: Avro codec, snapshot read/write/time-travel
(reference: ``daft/io/_iceberg.py`` + ``DataFrame.write_iceberg`` over
pyiceberg; here both sides are SDK-free so the writer fixtures also
exercise the reader's manifest parsing)."""

import json

import pytest

import daft_tpu
from daft_tpu.io.avro import read_avro, write_avro
from daft_tpu.io.iceberg import (data_files, load_table_metadata,
                                 read_iceberg, write_iceberg)


def test_avro_roundtrip_all_types():
    schema = {"type": "record", "name": "t", "fields": [
        {"name": "s", "type": "string"},
        {"name": "l", "type": "long"},
        {"name": "i", "type": "int"},
        {"name": "b", "type": "boolean"},
        {"name": "f", "type": "float"},
        {"name": "d", "type": "double"},
        {"name": "by", "type": "bytes"},
        {"name": "u", "type": ["null", "long"]},
        {"name": "arr", "type": {"type": "array", "items": "string"}},
        {"name": "m", "type": {"type": "map", "values": "long"}},
        {"name": "fx", "type": {"type": "fixed", "name": "f16", "size": 4}},
        {"name": "en", "type": {"type": "enum", "name": "e",
                                "symbols": ["X", "Y"]}},
        {"name": "nested", "type": {"type": "record", "name": "n",
                                    "fields": [{"name": "x",
                                                "type": "long"}]}},
    ]}
    recs = [
        {"s": "héllo", "l": -(1 << 40), "i": 42, "b": True, "f": 0.5,
         "d": 1.25, "by": b"\x00\xff", "u": None, "arr": ["a", "b"],
         "m": {"k": 7}, "fx": b"abcd", "en": "Y", "nested": {"x": 9}},
        {"s": "", "l": 0, "i": -1, "b": False, "f": -2.0, "d": 0.0,
         "by": b"", "u": 123, "arr": [], "m": {}, "fx": b"wxyz",
         "en": "X", "nested": {"x": -9}},
    ]
    for codec in ("null", "deflate"):
        meta, out = read_avro(write_avro(schema, recs, codec=codec))
        assert out == recs
        assert meta["schema"]["name"] == "t"


def test_write_then_read_roundtrip(tmp_path):
    uri = str(tmp_path / "tbl")
    df = daft_tpu.from_pydict({"k": [1, 2, 3], "v": ["a", "b", "c"]})
    write_iceberg(df, uri)
    back = read_iceberg(uri).sort("k").to_pydict()
    assert back == {"k": [1, 2, 3], "v": ["a", "b", "c"]}


def test_append_accumulates_and_overwrite_resets(tmp_path):
    uri = str(tmp_path / "tbl")
    write_iceberg(daft_tpu.from_pydict({"x": [1, 2]}), uri)
    write_iceberg(daft_tpu.from_pydict({"x": [3]}), uri, mode="append")
    assert sorted(read_iceberg(uri).to_pydict()["x"]) == [1, 2, 3]
    write_iceberg(daft_tpu.from_pydict({"x": [9]}), uri, mode="overwrite")
    assert read_iceberg(uri).to_pydict()["x"] == [9]


def test_time_travel_by_snapshot_id(tmp_path):
    uri = str(tmp_path / "tbl")
    write_iceberg(daft_tpu.from_pydict({"x": [1]}), uri)
    meta1 = load_table_metadata(uri)
    first = meta1["current-snapshot-id"]
    write_iceberg(daft_tpu.from_pydict({"x": [2]}), uri, mode="append")
    assert sorted(read_iceberg(uri).to_pydict()["x"]) == [1, 2]
    assert read_iceberg(uri, snapshot_id=first).to_pydict()["x"] == [1]


def test_metadata_versioning_and_hint(tmp_path):
    uri = str(tmp_path / "tbl")
    write_iceberg(daft_tpu.from_pydict({"x": [1]}), uri)
    write_iceberg(daft_tpu.from_pydict({"x": [2]}), uri)
    hint = (tmp_path / "tbl" / "metadata" / "version-hint.text").read_text()
    assert hint == "2"
    meta = json.loads(
        (tmp_path / "tbl" / "metadata" / "v2.metadata.json").read_text())
    assert meta["format-version"] == 1
    assert len(meta["snapshots"]) == 2
    files = data_files(uri)
    assert len(files) == 2
    assert all(f["format"] == "parquet" for f in files)
    assert sum(f["records"] for f in files) == 2


def test_dataframe_write_method_and_query(tmp_path):
    uri = str(tmp_path / "tbl")
    daft_tpu.from_pydict({"k": [1, 1, 2], "v": [10.0, 20.0, 30.0]}) \
        .write_iceberg(uri)
    from daft_tpu import col
    out = daft_tpu.read_iceberg(uri).groupby("k") \
        .agg(col("v").sum().alias("s")).sort("k").to_pydict()
    assert out == {"k": [1, 2], "s": [30.0, 30.0]}


def test_relocated_table_paths_rewritten(tmp_path):
    """Absolute paths in manifests are remapped when the table directory
    moves (the _rewrite_location path)."""
    import shutil
    uri = str(tmp_path / "orig")
    write_iceberg(daft_tpu.from_pydict({"x": [5, 6]}), uri)
    moved = str(tmp_path / "moved")
    shutil.move(uri, moved)
    assert sorted(read_iceberg(moved).to_pydict()["x"]) == [5, 6]


def test_empty_table_schema_only(tmp_path):
    uri = str(tmp_path / "tbl")
    write_iceberg(daft_tpu.from_pydict({"a": [1], "b": ["z"]}), uri)
    # simulate a metadata-only table: drop current snapshot
    meta_path = tmp_path / "tbl" / "metadata" / "v1.metadata.json"
    meta = json.loads(meta_path.read_text())
    meta["current-snapshot-id"] = -1
    meta["snapshots"] = []
    (tmp_path / "tbl" / "metadata" / "v2.metadata.json").write_text(
        json.dumps(meta))
    (tmp_path / "tbl" / "metadata" / "version-hint.text").write_text("2")
    df = read_iceberg(uri)
    assert df.column_names == ["a", "b"]
    assert df.count_rows() == 0
