"""Native Iceberg support: Avro codec, snapshot read/write/time-travel
(reference: ``daft/io/_iceberg.py`` + ``DataFrame.write_iceberg`` over
pyiceberg; here both sides are SDK-free so the writer fixtures also
exercise the reader's manifest parsing)."""

import json

import pytest

import daft_tpu
from daft_tpu.io.avro import read_avro, write_avro
from daft_tpu.io.iceberg import (data_files, load_table_metadata,
                                 read_iceberg, write_iceberg)


def test_avro_roundtrip_all_types():
    schema = {"type": "record", "name": "t", "fields": [
        {"name": "s", "type": "string"},
        {"name": "l", "type": "long"},
        {"name": "i", "type": "int"},
        {"name": "b", "type": "boolean"},
        {"name": "f", "type": "float"},
        {"name": "d", "type": "double"},
        {"name": "by", "type": "bytes"},
        {"name": "u", "type": ["null", "long"]},
        {"name": "arr", "type": {"type": "array", "items": "string"}},
        {"name": "m", "type": {"type": "map", "values": "long"}},
        {"name": "fx", "type": {"type": "fixed", "name": "f16", "size": 4}},
        {"name": "en", "type": {"type": "enum", "name": "e",
                                "symbols": ["X", "Y"]}},
        {"name": "nested", "type": {"type": "record", "name": "n",
                                    "fields": [{"name": "x",
                                                "type": "long"}]}},
    ]}
    recs = [
        {"s": "héllo", "l": -(1 << 40), "i": 42, "b": True, "f": 0.5,
         "d": 1.25, "by": b"\x00\xff", "u": None, "arr": ["a", "b"],
         "m": {"k": 7}, "fx": b"abcd", "en": "Y", "nested": {"x": 9}},
        {"s": "", "l": 0, "i": -1, "b": False, "f": -2.0, "d": 0.0,
         "by": b"", "u": 123, "arr": [], "m": {}, "fx": b"wxyz",
         "en": "X", "nested": {"x": -9}},
    ]
    for codec in ("null", "deflate"):
        meta, out = read_avro(write_avro(schema, recs, codec=codec))
        assert out == recs
        assert meta["schema"]["name"] == "t"


def test_write_then_read_roundtrip(tmp_path):
    uri = str(tmp_path / "tbl")
    df = daft_tpu.from_pydict({"k": [1, 2, 3], "v": ["a", "b", "c"]})
    write_iceberg(df, uri)
    back = read_iceberg(uri).sort("k").to_pydict()
    assert back == {"k": [1, 2, 3], "v": ["a", "b", "c"]}


def test_append_accumulates_and_overwrite_resets(tmp_path):
    uri = str(tmp_path / "tbl")
    write_iceberg(daft_tpu.from_pydict({"x": [1, 2]}), uri)
    write_iceberg(daft_tpu.from_pydict({"x": [3]}), uri, mode="append")
    assert sorted(read_iceberg(uri).to_pydict()["x"]) == [1, 2, 3]
    write_iceberg(daft_tpu.from_pydict({"x": [9]}), uri, mode="overwrite")
    assert read_iceberg(uri).to_pydict()["x"] == [9]


def test_time_travel_by_snapshot_id(tmp_path):
    uri = str(tmp_path / "tbl")
    write_iceberg(daft_tpu.from_pydict({"x": [1]}), uri)
    meta1 = load_table_metadata(uri)
    first = meta1["current-snapshot-id"]
    write_iceberg(daft_tpu.from_pydict({"x": [2]}), uri, mode="append")
    assert sorted(read_iceberg(uri).to_pydict()["x"]) == [1, 2]
    assert read_iceberg(uri, snapshot_id=first).to_pydict()["x"] == [1]


def test_metadata_versioning_and_hint(tmp_path):
    uri = str(tmp_path / "tbl")
    write_iceberg(daft_tpu.from_pydict({"x": [1]}), uri)
    write_iceberg(daft_tpu.from_pydict({"x": [2]}), uri)
    hint = (tmp_path / "tbl" / "metadata" / "version-hint.text").read_text()
    assert hint == "2"
    meta = json.loads(
        (tmp_path / "tbl" / "metadata" / "v2.metadata.json").read_text())
    assert meta["format-version"] == 1
    assert len(meta["snapshots"]) == 2
    files = data_files(uri)
    assert len(files) == 2
    assert all(f["format"] == "parquet" for f in files)
    assert sum(f["records"] for f in files) == 2


def test_dataframe_write_method_and_query(tmp_path):
    uri = str(tmp_path / "tbl")
    daft_tpu.from_pydict({"k": [1, 1, 2], "v": [10.0, 20.0, 30.0]}) \
        .write_iceberg(uri)
    from daft_tpu import col
    out = daft_tpu.read_iceberg(uri).groupby("k") \
        .agg(col("v").sum().alias("s")).sort("k").to_pydict()
    assert out == {"k": [1, 2], "s": [30.0, 30.0]}


def test_relocated_table_paths_rewritten(tmp_path):
    """Absolute paths in manifests are remapped when the table directory
    moves (the _rewrite_location path)."""
    import shutil
    uri = str(tmp_path / "orig")
    write_iceberg(daft_tpu.from_pydict({"x": [5, 6]}), uri)
    moved = str(tmp_path / "moved")
    shutil.move(uri, moved)
    assert sorted(read_iceberg(moved).to_pydict()["x"]) == [5, 6]


def test_empty_table_schema_only(tmp_path):
    uri = str(tmp_path / "tbl")
    write_iceberg(daft_tpu.from_pydict({"a": [1], "b": ["z"]}), uri)
    # simulate a metadata-only table: drop current snapshot
    meta_path = tmp_path / "tbl" / "metadata" / "v1.metadata.json"
    meta = json.loads(meta_path.read_text())
    meta["current-snapshot-id"] = -1
    meta["snapshots"] = []
    (tmp_path / "tbl" / "metadata" / "v2.metadata.json").write_text(
        json.dumps(meta))
    (tmp_path / "tbl" / "metadata" / "version-hint.text").write_text("2")
    df = read_iceberg(uri)
    assert df.column_names == ["a", "b"]
    assert df.count_rows() == 0


# ------------------------------------------------- v2 deletes + evolution

def _fabricate_v2_table(root, data_tables, pos_deletes=None, eq_deletes=None,
                        schema_fields=None):
    """Hand-build an Iceberg v2 table: data files, optional positional /
    equality delete files, sequence-numbered manifests (what pyiceberg or
    Spark would commit; our writer is v1-only by design)."""
    import json
    import os

    import pyarrow as pa
    import pyarrow.parquet as pq

    from daft_tpu.io.avro import write_avro

    os.makedirs(os.path.join(root, "data"), exist_ok=True)
    os.makedirs(os.path.join(root, "metadata"), exist_ok=True)

    entry_schema = {
        "type": "record", "name": "manifest_entry", "fields": [
            {"name": "status", "type": "int", "field-id": 0},
            {"name": "sequence_number", "type": ["null", "long"],
             "field-id": 3},
            {"name": "data_file", "field-id": 2, "type": {
                "type": "record", "name": "r2", "fields": [
                    {"name": "content", "type": "int", "field-id": 134},
                    {"name": "file_path", "type": "string",
                     "field-id": 100},
                    {"name": "file_format", "type": "string",
                     "field-id": 101},
                    {"name": "record_count", "type": "long",
                     "field-id": 103},
                    {"name": "equality_ids", "field-id": 135, "type": [
                        "null", {"type": "array", "items": "int",
                                 "element-id": 136}]},
                ]}},
        ]}
    mlist_schema = {
        "type": "record", "name": "manifest_file", "fields": [
            {"name": "manifest_path", "type": "string", "field-id": 500},
            {"name": "manifest_length", "type": "long", "field-id": 501},
            {"name": "partition_spec_id", "type": "int", "field-id": 502},
            {"name": "content", "type": "int", "field-id": 517},
            {"name": "sequence_number", "type": "long", "field-id": 515},
            {"name": "added_snapshot_id", "type": ["null", "long"],
             "field-id": 503},
        ]}

    manifests = []

    def add_manifest(entries, content, seq):
        blob = write_avro(entry_schema, entries)
        p = os.path.join(root, "metadata", f"m{len(manifests)}.avro")
        open(p, "wb").write(blob)
        manifests.append({"manifest_path": p, "manifest_length": len(blob),
                          "partition_spec_id": 0, "content": content,
                          "sequence_number": seq, "added_snapshot_id": 1})

    data_entries = []
    for i, (t, seq) in enumerate(data_tables):
        p = os.path.join(root, "data", f"d{i}.parquet")
        pq.write_table(t, p)
        data_entries.append(
            {"status": 1, "sequence_number": seq, "data_file": {
                "content": 0, "file_path": p, "file_format": "PARQUET",
                "record_count": t.num_rows, "equality_ids": None}})
    add_manifest(data_entries, 0, max(s for _, s in data_tables))

    del_entries = []
    for i, (t, seq) in enumerate(pos_deletes or []):
        p = os.path.join(root, "data", f"pd{i}.parquet")
        pq.write_table(t, p)
        del_entries.append(
            {"status": 1, "sequence_number": seq, "data_file": {
                "content": 1, "file_path": p, "file_format": "PARQUET",
                "record_count": t.num_rows, "equality_ids": None}})
    for i, (t, seq, ids) in enumerate(eq_deletes or []):
        p = os.path.join(root, "data", f"ed{i}.parquet")
        pq.write_table(t, p)
        del_entries.append(
            {"status": 1, "sequence_number": seq, "data_file": {
                "content": 2, "file_path": p, "file_format": "PARQUET",
                "record_count": t.num_rows, "equality_ids": ids}})
    if del_entries:
        add_manifest(del_entries, 1,
                     max(e["sequence_number"] for e in del_entries))

    mlist_blob = write_avro(mlist_schema, manifests)
    mlist = os.path.join(root, "metadata", "snap-1.avro")
    open(mlist, "wb").write(mlist_blob)

    meta = {
        "format-version": 2, "table-uuid": "t", "location": root,
        "last-updated-ms": 0, "last-column-id": 10,
        "current-schema-id": 0,
        "schemas": [{"type": "struct", "schema-id": 0,
                     "fields": schema_fields or [
                         {"id": 1, "name": "id", "required": False,
                          "type": "long"},
                         {"id": 2, "name": "v", "required": False,
                          "type": "string"}]}],
        "partition-specs": [{"spec-id": 0, "fields": []}],
        "default-spec-id": 0, "properties": {},
        "current-snapshot-id": 1,
        "snapshots": [{"snapshot-id": 1, "timestamp-ms": 0,
                       "manifest-list": mlist, "schema-id": 0,
                       "summary": {"operation": "append"}}],
    }
    open(os.path.join(root, "metadata", "v1.metadata.json"),
         "w").write(json.dumps(meta))
    return root


def test_v2_positional_deletes(tmp_path):
    import pyarrow as pa
    root = str(tmp_path / "v2pos")
    data = pa.table({"id": list(range(10)),
                     "v": [f"r{i}" for i in range(10)]})
    dpath = str(tmp_path / "v2pos" / "data" / "d0.parquet")
    pos = pa.table({"file_path": [dpath, dpath], "pos": [2, 5]})
    _fabricate_v2_table(root, [(data, 1)], pos_deletes=[(pos, 2)])
    out = daft_tpu.read_iceberg(root).sort("id").to_pydict()
    assert out["id"] == [0, 1, 3, 4, 6, 7, 8, 9]


def test_v2_equality_deletes_sequence_aware(tmp_path):
    import pyarrow as pa
    root = str(tmp_path / "v2eq")
    old = pa.table({"id": [1, 2, 3], "v": ["a", "b", "c"]})      # seq 1
    newer = pa.table({"id": [2, 4], "v": ["B2", "d"]})           # seq 3
    eq = pa.table({"id": [2, 3]})                                # seq 2
    _fabricate_v2_table(root, [(old, 1), (newer, 3)],
                        eq_deletes=[(eq, 2, [1])])
    out = daft_tpu.read_iceberg(root).sort("id").to_pydict()
    # seq-2 equality delete removes id 2,3 from the seq-1 file only; the
    # seq-3 file's id=2 row survives (written after the delete)
    assert out["id"] == [1, 2, 4]
    assert out["v"] == ["a", "B2", "d"]


def test_v2_field_id_schema_evolution(tmp_path):
    """A file written under the OLD column name reads under the renamed
    current schema by field id; a column added later reads as null."""
    import pyarrow as pa
    root = str(tmp_path / "v2evo")
    old_file = pa.table({"id": pa.array([1, 2], pa.int64()),
                         "old_name": ["x", "y"]})
    old_schema = pa.schema([
        pa.field("id", pa.int64(),
                 metadata={b"PARQUET:field_id": b"1"}),
        pa.field("old_name", pa.string(),
                 metadata={b"PARQUET:field_id": b"2"}),
    ])
    old_file = old_file.cast(old_schema)
    # current schema renamed old_name→v (same id 2) and added w (id 3);
    # the fabricated table needs ≥1 delete so the remap path engages
    pos = pa.table({"file_path": ["nope"], "pos": [0]})
    _fabricate_v2_table(
        root, [(old_file, 1)], pos_deletes=[(pos, 2)],
        schema_fields=[
            {"id": 1, "name": "id", "required": False, "type": "long"},
            {"id": 2, "name": "v", "required": False, "type": "string"},
            {"id": 3, "name": "w", "required": False, "type": "double"},
        ])
    out = daft_tpu.read_iceberg(root).sort("id").to_pydict()
    assert out == {"id": [1, 2], "v": ["x", "y"], "w": [None, None]}
