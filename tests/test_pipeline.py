"""Push-pipeline machinery: failure propagation, cancellation, order
preservation, the partitioned-agg dispatcher, and interp-executor parity.

Reference seam: Swordfish's pipeline/dispatcher
(``src/daft-local-execution/src/pipeline.rs:100-830``,
``dispatcher.rs:24-60``, ``sinks/grouped_aggregate.rs:54-151``); here
``daft_tpu/execution/pipeline.py``. These paths only fail as rare hangs or
silent truncations in production queries, so they get dedicated tests."""

import threading
import time

import pytest

import daft_tpu as dt
from daft_tpu import col
from daft_tpu.datatype import DataType

_STAGE_PREFIXES = ("drv-", "dsp-", "wrk-", "col-", "red-")


@pytest.fixture(autouse=True)
def small_morsels():
    """8k-row fixtures re-chunk into ~16 real morsels (the default 128k
    morsel would swallow them whole and the stages under test would see a
    single-morsel stream)."""
    with dt.execution_config_ctx(default_morsel_size=500):
        yield


def _stage_threads():
    return [t for t in threading.enumerate()
            if any(t.name.startswith(p) for p in _STAGE_PREFIXES)]


def _wait_stages_exit(timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        alive = [t for t in _stage_threads() if t.is_alive()]
        if not alive:
            return True
        time.sleep(0.05)
    return False


@pytest.fixture
def many_files(tmp_path):
    """16 parquet files → a genuinely multi-morsel streaming source."""
    import pyarrow as pa
    import pyarrow.parquet as pq
    root = tmp_path / "many"
    root.mkdir()
    n = 0
    for i in range(16):
        rows = 500
        pq.write_table(
            pa.table({"id": pa.array(range(n, n + rows), pa.int64()),
                      "g": pa.array([(n + j) % 7 for j in range(rows)],
                                    pa.int64()),
                      "v": pa.array([float(j) for j in range(rows)])}),
            root / f"part-{i:02d}.parquet")
        n += rows
    return str(root / "*.parquet"), n


def test_midstream_failure_surfaces_not_truncates(many_files):
    """A kernel failure deep into the stream must raise at the consumer —
    the fail-before-close ordering in pipeline.py exists so a failing
    query can never end as a clean truncated result."""
    glob, n = many_files

    @dt.udf(return_dtype=DataType.int64())
    def boom(ids):
        vals = ids.to_pylist()
        if any(v == 6500 for v in vals):  # lives in file 13 of 16
            raise RuntimeError("injected mid-stream kernel failure")
        return vals

    df = dt.read_parquet(glob).with_column("x", boom(col("id")))
    with pytest.raises(Exception, match="injected mid-stream"):
        df.to_pydict()
    assert _wait_stages_exit(), \
        f"stage threads leaked: {[t.name for t in _stage_threads()]}"


def test_consumer_drop_cancels_all_stage_threads(many_files):
    """Dropping the output iterator mid-stream must unwind every stage
    thread (dispatcher, workers, collector, drivers) within the poll
    bound — a leak here is a deadlocked query in a server."""
    glob, n = many_files

    @dt.udf(return_dtype=DataType.int64())
    def slow(ids):
        time.sleep(0.3)  # 16 morsels × 0.3 s ≫ time-to-first-output
        return ids.to_pylist()

    df = dt.read_parquet(glob).with_column("x", slow(col("id")))
    it = df.iter_partitions()
    next(it)
    assert len([t for t in _stage_threads() if t.is_alive()]) > 0, \
        "pipeline finished before the drop — slow() not slow enough"
    it.close()  # consumer walks away
    del it
    assert _wait_stages_exit(), \
        f"stage threads leaked: {[t.name for t in _stage_threads()]}"


def test_map_stage_preserves_order(many_files):
    """RoundRobin dispatch + in-order collection: output order equals
    input order even when per-morsel compute time is adversarial."""
    glob, n = many_files

    @dt.udf(return_dtype=DataType.int64())
    def jitter(ids):
        vals = ids.to_pylist()
        # earlier morsels sleep longer: a racy collector would emit
        # later morsels first
        time.sleep(0.05 if vals and vals[0] < 2000 else 0.001)
        return vals

    out = dt.read_parquet(glob).select(jitter(col("id")).alias("id")) \
        .to_pydict()
    assert out["id"] == list(range(n))


def test_error_after_some_output_still_raises(many_files):
    """Consume a few morsels THEN hit the failure: the iterator must
    raise, not stop cleanly (the truncation failure mode)."""
    glob, n = many_files

    @dt.udf(return_dtype=DataType.int64())
    def late_boom(ids):
        vals = ids.to_pylist()
        if any(v >= 7000 for v in vals):
            raise RuntimeError("late failure")
        return vals

    df = dt.read_parquet(glob).with_column("x", late_boom(col("id")))
    it = df.iter_partitions()
    got = 0
    with pytest.raises(Exception, match="late failure"):
        for _ in it:
            got += 1
    assert _wait_stages_exit()


# ------------------------------------------------- partitioned dispatcher

def test_partitioned_agg_matches_interp(many_files, monkeypatch):
    # host tier: with the 8-device CPU mesh up, the grouped agg would
    # otherwise lower onto DeviceExchangeAgg and bypass the dispatcher
    monkeypatch.setenv("DAFT_TPU_DEVICE", "0")
    glob, n = many_files
    df = dt.read_parquet(glob)
    agg = (df.groupby("g").agg(
        col("v").sum().alias("sv"), col("v").mean().alias("mv"),
        col("id").count().alias("c"), col("v").max().alias("hi"))
        .sort("g"))
    push = agg.to_pydict()
    with dt.execution_config_ctx(local_executor="interp"):
        interp = agg.to_pydict()
    assert push == interp
    # the fused stage really ran with >1 reducer
    from daft_tpu import observability as obs
    stats = obs.last_query_stats()
    # note: last stats are from the interp run; re-run under push
    push2 = agg.to_pydict()
    stats = obs.last_query_stats()
    workers = [s.workers for s in stats._ops.values()
               if s.workers and "Aggregate" in s.name]
    assert workers and max(workers) > 1, \
        f"grouped agg did not partition-parallelize: " \
        f"{[(s.name, s.workers) for s in stats._ops.values()]}"
    assert push2 == interp


def test_partitioned_agg_incremental_merge(many_files, monkeypatch):
    """Force the re-agg threshold low so every reducer exercises the
    state-merge path, and check exactness."""
    from daft_tpu.execution import pipeline
    monkeypatch.setattr(pipeline, "_REAGG_ROWS", 256)
    monkeypatch.setenv("DAFT_TPU_DEVICE", "0")
    glob, n = many_files
    out = (dt.read_parquet(glob).groupby("g")
           .agg(col("v").sum().alias("sv"), col("id").count().alias("c"))
           .sort("g").to_pydict())
    assert sum(out["c"]) == n
    expected_sv = {}
    for i in range(n):
        expected_sv[i % 7] = expected_sv.get(i % 7, 0.0) + float(i % 500)
    assert out["sv"] == pytest.approx([expected_sv[g] for g in out["g"]])


def test_partitioned_agg_declines_on_huge_footer_ndv(many_files, monkeypatch):
    """Footer stats predicting more groups than _FUSE_MAX_GROUPS route the
    final agg to the SPILL-PARTITIONED fused reducer (round 19: the state
    streams through a rotated-radix store, merged per bucket on read) —
    and DAFT_TPU_SPILL_AGG=0 restores the legacy decline onto the
    spill-bounded exchange path. Keys without footer evidence (or small
    ranges) keep the in-memory fused default."""
    from daft_tpu.execution import pipeline
    from daft_tpu.physical.translate import translate
    monkeypatch.setenv("DAFT_TPU_DEVICE", "0")
    glob, n = many_files

    def final_agg_node(df):
        phys = translate(df._builder.optimize().plan)
        found = []

        def walk(node):
            if type(node).__name__ == "Aggregate" and node.mode == "final":
                found.append(node)
            for c in node.children:
                walk(c)
        walk(phys)
        assert found, "no final Aggregate in plan"
        return found[0]

    df_wide = dt.read_parquet(glob).groupby("id").agg(
        col("v").sum().alias("s"))
    node = final_agg_node(df_wide)
    assert node.group_ndv == pytest.approx(n)  # dense ids: range == rows
    # n (8000) distinct ids > a forced-low threshold → the fusion now
    # keeps the boundary elided but switches to the spilling reducer
    monkeypatch.setattr(pipeline, "_FUSE_MAX_GROUPS", n // 2)
    info = pipeline._partitioned_agg_info(node)
    assert info is not None and info[3] is True  # spill=True
    # legacy escape hatch: DAFT_TPU_SPILL_AGG=0 declines the fusion
    monkeypatch.setenv("DAFT_TPU_SPILL_AGG", "0")
    assert pipeline._partitioned_agg_info(node) is None
    monkeypatch.delenv("DAFT_TPU_SPILL_AGG")
    # the small-range key keeps the in-memory fused path under the same
    # threshold
    df_small = dt.read_parquet(glob).groupby("g").agg(
        col("v").sum().alias("s"))
    small = final_agg_node(df_small)
    assert small.group_ndv == pytest.approx(7)
    small_info = pipeline._partitioned_agg_info(small)
    assert small_info is not None and small_info[3] is False
    # and both paths still answer correctly end-to-end: the declined
    # (exchange) path must produce every group with the right sums
    out = df_wide.sort("id").to_pydict()
    assert out["id"] == list(range(n))
    assert out["s"] == pytest.approx([float(i % 500) for i in range(n)])
    out_small = df_small.sort("g").to_pydict()
    assert out_small["g"] == list(range(7))
    expected = {}
    for i in range(n):
        expected[i % 7] = expected.get(i % 7, 0.0) + float(i % 500)
    assert out_small["s"] == pytest.approx([expected[g] for g in range(7)])


# --------------------------------------------------- interp executor tier

@pytest.fixture(scope="module")
def shapes_df():
    return dt.from_pydict({
        "k": ["a", "b", "a", "c", "b", "a", "c", "b"],
        "i": [3, 1, 4, 1, 5, 9, 2, 6],
        "f": [0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8],
        "lst": [[1], [2, 3], [], [4], [5, 6], [7], [8], [9]],
    })


def _interp_and_push(build):
    push = build().to_pydict()
    with dt.execution_config_ctx(local_executor="interp"):
        interp = build().to_pydict()
    assert push == interp
    return push


@pytest.mark.parametrize("case", [
    "filter_project", "groupby", "global_agg", "sort", "join", "window",
    "explode", "distinct", "limit", "concat", "sql_subquery", "rollup",
])
def test_interp_executor_parity(case, shapes_df):
    """The interp (pull-generator) executor is reachable config
    (``local_executor="interp"``): every representative plan shape must
    agree with the push default."""
    df = shapes_df
    other = dt.from_pydict({"k": ["a", "b", "z"], "w": [10, 20, 30]})
    builds = {
        "filter_project": lambda: df.where(col("i") > 2)
            .select(col("k"), (col("i") * 2).alias("d")).sort("d"),
        "groupby": lambda: df.groupby("k").agg(
            col("i").sum().alias("s"), col("f").mean().alias("m")).sort("k"),
        "global_agg": lambda: df.agg(col("i").sum().alias("s"),
                                     col("i").count_distinct().alias("nd")),
        "sort": lambda: df.sort(["k", "i"], desc=[False, True]),
        "join": lambda: df.join(other, on="k").sort(["k", "i"]),
        "window": lambda: df.select(
            col("k"), col("i"),
            col("i").sum().over(dt.Window().partition_by("k")
                                .order_by("i")).alias("r")).sort(["k", "i"]),
        "explode": lambda: df.explode(col("lst")).sort(["k", "i"]),
        "distinct": lambda: df.select("k").distinct().sort("k"),
        "limit": lambda: df.sort("i").limit(3),
        "concat": lambda: df.select("k").concat(other.select("k")).sort("k"),
        "sql_subquery": lambda: dt.sql(
            "SELECT k, i FROM t WHERE i > (SELECT avg(i) FROM t) "
            "ORDER BY i", t=df),
        "rollup": lambda: dt.sql(
            "SELECT k, sum(i) AS s FROM t GROUP BY ROLLUP(k) "
            "ORDER BY s", t=df),
    }
    _interp_and_push(builds[case])
