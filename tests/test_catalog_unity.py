"""Unity Catalog REST adapter against an in-process mock server
(reference: ``daft/unity_catalog`` + its catalog adapter; same mock-server
pattern as the S3/GCS/Azure/HF suites)."""

import http.server
import json
import threading
import urllib.parse

import pytest

import daft_tpu
from daft_tpu import Session
from daft_tpu.catalog import Identifier, NotFoundError
from daft_tpu.catalog_unity import UnityCatalog


class _MockUnityHandler(http.server.BaseHTTPRequestHandler):
    tables = {}  # full_name -> {storage_location, data_source_format}
    seen_auth = []

    def log_message(self, *a):
        pass

    def _send(self, status, payload=None):
        body = json.dumps(payload or {}).encode()
        self.send_response(status)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        self.seen_auth.append(self.headers.get("Authorization", ""))
        u = urllib.parse.urlparse(self.path)
        q = urllib.parse.parse_qs(u.query)
        parts = u.path.split("/api/2.1/unity-catalog/", 1)[-1].split("/", 1)
        if parts[0] == "schemas":
            names = sorted({full.split(".")[1]
                            for full in self.tables})
            self._send(200, {"schemas": [{"name": n} for n in names]})
            return
        if parts[0] == "tables" and len(parts) == 1:
            schema = q["schema_name"][0]
            out = [{"name": full.split(".")[2]}
                   for full in sorted(self.tables)
                   if full.split(".")[1] == schema]
            self._send(200, {"tables": out})
            return
        if parts[0] == "tables":
            full = urllib.parse.unquote(parts[1])
            doc = self.tables.get(full)
            if doc is None:
                self._send(404)
                return
            self._send(200, doc)
            return
        self._send(404)


@pytest.fixture(scope="module")
def unity(tmp_path_factory):
    # back the mock tables with REAL native-format tables on disk
    root = tmp_path_factory.mktemp("uc")
    delta_path = str(root / "orders")
    from daft_tpu.io.delta import write_deltalake
    write_deltalake(daft_tpu.from_pydict({"k": [1, 2], "v": [10.0, 20.0]}),
                    delta_path)
    ice_path = str(root / "events")
    daft_tpu.from_pydict({"e": ["a", "b", "c"]}).write_iceberg(ice_path)
    _MockUnityHandler.tables = {
        "unity.sales.orders": {"storage_location": delta_path,
                               "data_source_format": "DELTA"},
        "unity.sales.events": {"storage_location": ice_path,
                               "data_source_format": "ICEBERG"},
    }
    server = http.server.ThreadingHTTPServer(("127.0.0.1", 0),
                                             _MockUnityHandler)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    yield UnityCatalog(f"http://127.0.0.1:{server.server_port}",
                       token="tok-1", catalog="unity", name="uc")
    server.shutdown()


def test_list_namespaces_and_tables(unity):
    assert unity._list_namespaces() == [Identifier("sales")]
    assert unity._list_tables() == [Identifier("sales", "events"),
                                    Identifier("sales", "orders")]


def test_read_delta_and_iceberg_tables(unity):
    t = unity._get_table(Identifier("sales", "orders"))
    assert t.format == "DELTA"
    assert t.read().sort("k").to_pydict() == {"k": [1, 2],
                                              "v": [10.0, 20.0]}
    t2 = unity._get_table(Identifier("sales", "events"))
    assert t2.format == "ICEBERG"
    assert sorted(t2.read().to_pydict()["e"]) == ["a", "b", "c"]
    # bearer token actually sent
    assert any(a == "Bearer tok-1" for a in _MockUnityHandler.seen_auth)


def test_missing_table_raises(unity):
    with pytest.raises(NotFoundError):
        unity._get_table(Identifier("sales", "absent"))


def test_sql_over_attached_unity_catalog(unity):
    sess = Session()
    sess.attach(unity, alias="uc")
    out = sess.sql("SELECT SUM(v) AS s FROM uc.sales.orders").to_pydict()
    assert out["s"] == [30.0]
