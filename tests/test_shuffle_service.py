"""Flight-like shuffle service: map-side spill cache → per-host HTTP server
→ reduce-side fetch (reference: ``src/daft-shuffles`` map/serve/fetch
pipeline)."""

import numpy as np
import pyarrow as pa
import pytest

from daft_tpu.distributed.shuffle_service import (ShuffleCache,
                                                  ShuffleServer,
                                                  fetch_partition)


@pytest.fixture
def server():
    s = ShuffleServer()
    yield s
    s.shutdown()


def test_map_serve_fetch_roundtrip(server):
    cache = ShuffleCache()
    n_parts = 4
    rng = np.random.default_rng(0)
    keys = rng.integers(0, 1000, 10_000)
    vals = rng.random(10_000)
    pids = keys % n_parts
    # map side: morsel-wise pushes (two morsels)
    for lo, hi in ((0, 5000), (5000, 10_000)):
        for p in range(n_parts):
            m = pids[lo:hi] == p
            if m.any():
                cache.push(p, pa.table({"k": keys[lo:hi][m],
                                        "v": vals[lo:hi][m]}))
    server.register(cache)

    # reduce side: every row arrives exactly once, routed correctly
    seen = 0
    for p in range(n_parts):
        t = fetch_partition(server.address, cache.shuffle_id, p)
        assert t is not None
        assert (t.column("k").to_numpy() % n_parts == p).all()
        seen += len(t)
    assert seen == 10_000


def test_empty_partition_and_unknown_shuffle(server):
    cache = ShuffleCache()
    cache.push(0, pa.table({"x": [1]}))
    server.register(cache)
    assert fetch_partition(server.address, cache.shuffle_id, 3) is None
    with pytest.raises(Exception):
        fetch_partition(server.address, "nope", 0)


def test_unregister_cleans_spill_files(server):
    import os
    cache = ShuffleCache()
    cache.push(0, pa.table({"x": list(range(10))}))
    root = cache._root
    server.register(cache)
    assert os.path.isdir(root)
    server.unregister(cache.shuffle_id)
    assert not os.path.isdir(root)
