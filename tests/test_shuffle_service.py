"""Shuffle service: map-side spill cache → per-host server (Arrow Flight
gRPC, with a stdlib-HTTP fallback) → reduce-side fetch (reference:
``src/daft-shuffles`` map/serve/fetch pipeline)."""

import numpy as np
import pyarrow as pa
import pytest

from daft_tpu.distributed.shuffle_service import (FlightShuffleServer,
                                                  ShuffleCache,
                                                  ShuffleServer,
                                                  fetch_partition,
                                                  make_shuffle_server,
                                                  paflight)

TRANSPORTS = ["http"] + (["flight"] if paflight is not None else [])


@pytest.fixture(params=TRANSPORTS)
def server(request):
    s = (FlightShuffleServer() if request.param == "flight"
         else ShuffleServer())
    yield s
    s.shutdown()


def test_make_shuffle_server_prefers_flight(monkeypatch):
    monkeypatch.delenv("DAFT_TPU_SHUFFLE_TRANSPORT", raising=False)
    s = make_shuffle_server()
    try:
        expected = ShuffleServer if paflight is None else FlightShuffleServer
        assert isinstance(s, expected)
    finally:
        s.shutdown()
    monkeypatch.setenv("DAFT_TPU_SHUFFLE_TRANSPORT", "http")
    s = make_shuffle_server()
    try:
        assert isinstance(s, ShuffleServer)
    finally:
        s.shutdown()


def test_map_serve_fetch_roundtrip(server):
    cache = ShuffleCache()
    n_parts = 4
    rng = np.random.default_rng(0)
    keys = rng.integers(0, 1000, 10_000)
    vals = rng.random(10_000)
    pids = keys % n_parts
    # map side: morsel-wise pushes (two morsels)
    for lo, hi in ((0, 5000), (5000, 10_000)):
        for p in range(n_parts):
            m = pids[lo:hi] == p
            if m.any():
                cache.push(p, pa.table({"k": keys[lo:hi][m],
                                        "v": vals[lo:hi][m]}))
    server.register(cache)

    # reduce side: every row arrives exactly once, routed correctly
    seen = 0
    for p in range(n_parts):
        t = fetch_partition(server.address, cache.shuffle_id, p)
        assert t is not None
        assert (t.column("k").to_numpy() % n_parts == p).all()
        seen += len(t)
    assert seen == 10_000


def test_empty_partition_and_unknown_shuffle(server):
    cache = ShuffleCache()
    cache.push(0, pa.table({"x": [1]}))
    server.register(cache)
    assert fetch_partition(server.address, cache.shuffle_id, 3) is None
    with pytest.raises(Exception):
        fetch_partition(server.address, "nope", 0)


def test_straggler_push_after_seal(server):
    cache = ShuffleCache()
    cache.push(0, pa.table({"x": [1, 2, 3]}))
    server.register(cache)  # seals
    cache.push(0, pa.table({"x": [4, 5]}))  # straggler appends a new stream
    t = fetch_partition(server.address, cache.shuffle_id, 0)
    assert sorted(t.column("x").to_pylist()) == [1, 2, 3, 4, 5]


def test_spill_cache_shuffle_strategy_in_queries():
    """The streaming spill-cache hash exchange (reference: FlightShuffle
    map-side cache) produces the same answers as the naive materializing
    exchange, across repartition and groupby."""
    import daft_tpu
    from daft_tpu import col
    from daft_tpu.context import execution_config_ctx

    df = daft_tpu.from_pydict(
        {"k": [i % 7 for i in range(5000)],
         "v": [float(i) for i in range(5000)]}).into_partitions(6)

    def run():
        rep = df.repartition(4, col("k"))
        assert rep.num_partitions() == 4
        parts = [p.combined().to_arrow_table() for p in rep.iter_partitions()]
        assert sum(t.num_rows for t in parts) == 5000
        agg = df.groupby("k").agg(col("v").sum().alias("s")).sort("k")
        return parts, agg.to_pydict()

    with execution_config_ctx(shuffle_algorithm="naive"):
        naive_parts, naive_agg = run()
    with execution_config_ctx(shuffle_algorithm="spill_cache"):
        cache_parts, cache_agg = run()
    assert cache_agg == naive_agg
    # same hash routing → identical per-partition key sets
    for a, b in zip(naive_parts, cache_parts):
        assert sorted(a.column("k").to_pylist()) == \
            sorted(b.column("k").to_pylist())


def test_spill_cache_shuffle_preserves_empty_partitions():
    import daft_tpu
    from daft_tpu import col
    from daft_tpu.context import execution_config_ctx

    df = daft_tpu.from_pydict({"k": [1, 1, 1], "v": ["a", "b", "c"]})
    with execution_config_ctx(shuffle_algorithm="spill_cache"):
        rep = df.into_partitions(2).repartition(5, col("k"))
        parts = [p.combined().to_arrow_table() for p in rep.iter_partitions()]
    assert len(parts) == 5
    assert sum(t.num_rows for t in parts) == 3
    # empties keep the schema
    for t in parts:
        assert t.schema.names == ["k", "v"]


def test_remote_unregister_over_transport(server):
    """Reduce-side cleanup addresses the serving host directly through the
    shuffle transport (HTTP DELETE / Flight do_action)."""
    import os

    from daft_tpu.distributed.shuffle_service import unregister_remote
    cache = ShuffleCache()
    cache.push(0, pa.table({"x": [1, 2]}))
    root = cache._root
    server.register(cache)
    assert fetch_partition(server.address, cache.shuffle_id, 0) is not None
    unregister_remote(server.address, cache.shuffle_id)
    assert not os.path.isdir(root)  # spill files released
    with pytest.raises(Exception):
        fetch_partition(server.address, cache.shuffle_id, 0)


def test_unregister_cleans_spill_files(server):
    import os
    cache = ShuffleCache()
    cache.push(0, pa.table({"x": list(range(10))}))
    root = cache._root
    server.register(cache)
    assert os.path.isdir(root)
    server.unregister(cache.shuffle_id)
    assert not os.path.isdir(root)


def test_failed_shuffle_task_cleans_spill_dir(monkeypatch):
    """r14 regression (found by daft-lint shuffle-cache-leak): a failure
    while draining the task's stream — a fetch fault on a lazily
    resolved input, a partitioning error — orphaned the ShuffleCache's
    spill directory until process exit; ownership only transfers at
    server.register(), so the error path must cleanup() itself."""
    import os

    import pytest

    from daft_tpu import col
    from daft_tpu.distributed import worker as w
    from daft_tpu.distributed.shuffle_service import ShuffleCache
    from daft_tpu.execution.executor import LocalExecutor
    from daft_tpu.micropartition import MicroPartition
    from daft_tpu.physical import plan as pp

    made = []
    orig_init = ShuffleCache.__init__

    def spy_init(self, *a, **k):
        orig_init(self, *a, **k)
        made.append(self)

    monkeypatch.setattr(ShuffleCache, "__init__", spy_init)

    def boom_stream(self, plan, stage_inputs=None):
        def gen():
            yield MicroPartition.from_pydict({"k": [1, 2],
                                              "v": [1.0, 2.0]})
            raise RuntimeError("fetch fault mid-drain")
        return gen()

    monkeypatch.setattr(LocalExecutor, "run", boom_stream)
    task = w.StageTask(
        0, pp.InMemorySource([], None), {},
        shuffle_out=w.ShuffleOutSpec(num_partitions=2, by=(col("k"),)))
    with pytest.raises(RuntimeError, match="fetch fault mid-drain"):
        w._run_task_body(task)
    assert made, "no ShuffleCache constructed"
    # the spill dir was deleted on the error path (first batch HAD been
    # pushed, so the dir existed with a partition file in it)
    assert all(not os.path.isdir(c._root) for c in made)
