import numpy as np
import pyarrow as pa
import pytest

from daft_tpu import DataType, Field, Schema, TimeUnit
from daft_tpu.datatype import ImageMode


def test_simple_roundtrip_arrow():
    for dt in [DataType.bool(), DataType.int8(), DataType.int64(),
               DataType.uint32(), DataType.float32(), DataType.float64(),
               DataType.string(), DataType.binary(), DataType.date(),
               DataType.timestamp(TimeUnit.us), DataType.duration(TimeUnit.ms),
               DataType.decimal128(10, 2), DataType.list(DataType.int64()),
               DataType.fixed_size_list(DataType.float32(), 4),
               DataType.struct({"a": DataType.int64(), "b": DataType.string()}),
               DataType.map(DataType.string(), DataType.int64())]:
        assert DataType.from_arrow_type(dt.to_arrow()) == dt


def test_equality_and_hash():
    assert DataType.int64() == DataType.int64()
    assert DataType.int64() != DataType.int32()
    assert hash(DataType.list(DataType.int8())) == hash(DataType.list(DataType.int8()))


def test_image_physical_lowering():
    # reference: dtype.rs:307-335 — Image -> Struct{data, channel, h, w, mode}
    img = DataType.image("RGB")
    phys = img.to_physical()
    assert phys.is_struct()
    assert set(phys.fields.keys()) == {"data", "channel", "height", "width", "mode"}
    fsi = DataType.fixed_shape_image("RGB", 4, 6)
    assert fsi.to_physical() == DataType.fixed_size_list(DataType.uint8(), 4 * 6 * 3)


def test_tensor_physical_lowering():
    t = DataType.tensor(DataType.float32())
    phys = t.to_physical()
    assert phys.is_struct() and set(phys.fields.keys()) == {"data", "shape"}
    ft = DataType.tensor(DataType.float32(), (2, 3))
    assert ft.to_physical() == DataType.fixed_size_list(DataType.float32(), 6)


def test_embedding():
    e = DataType.embedding(DataType.float32(), 128)
    assert e.is_embedding()
    assert e.to_physical() == DataType.fixed_size_list(DataType.float32(), 128)
    assert e.device_repr() == np.dtype(np.float32)


def test_device_repr():
    assert DataType.int64().device_repr() == np.dtype(np.int64)
    assert DataType.string().device_repr() == np.dtype(np.int32)  # dict codes
    assert DataType.date().device_repr() == np.dtype(np.int32)
    assert DataType.python().device_repr() is None
    assert DataType.list(DataType.int64()).device_repr() is None


def test_schema():
    s = Schema.from_pydict({"a": DataType.int64(), "b": DataType.string()})
    assert s.column_names == ["a", "b"]
    assert s["a"].dtype == DataType.int64()
    assert "b" in s and "c" not in s
    with pytest.raises(ValueError):
        Schema([Field("x", DataType.int64()), Field("x", DataType.int32())])
    u = s.non_distinct_union(Schema.from_pydict({"b": DataType.int8(),
                                                 "c": DataType.bool()}))
    assert u.column_names == ["a", "b", "c"]
    assert u["b"].dtype == DataType.string()  # left wins


def test_schema_arrow_roundtrip():
    s = Schema.from_pydict({"a": DataType.int64(), "b": DataType.string(),
                            "c": DataType.list(DataType.float64())})
    assert Schema.from_arrow(s.to_arrow()) == s
