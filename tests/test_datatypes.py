import numpy as np
import pyarrow as pa
import pytest

from daft_tpu import DataType, Field, Schema, TimeUnit
from daft_tpu.datatype import ImageMode


def test_simple_roundtrip_arrow():
    for dt in [DataType.bool(), DataType.int8(), DataType.int64(),
               DataType.uint32(), DataType.float32(), DataType.float64(),
               DataType.string(), DataType.binary(), DataType.date(),
               DataType.timestamp(TimeUnit.us), DataType.duration(TimeUnit.ms),
               DataType.decimal128(10, 2), DataType.list(DataType.int64()),
               DataType.fixed_size_list(DataType.float32(), 4),
               DataType.struct({"a": DataType.int64(), "b": DataType.string()}),
               DataType.map(DataType.string(), DataType.int64())]:
        assert DataType.from_arrow_type(dt.to_arrow()) == dt


def test_equality_and_hash():
    assert DataType.int64() == DataType.int64()
    assert DataType.int64() != DataType.int32()
    assert hash(DataType.list(DataType.int8())) == hash(DataType.list(DataType.int8()))


def test_image_physical_lowering():
    # reference: dtype.rs:307-335 — Image -> Struct{data, channel, h, w, mode}
    img = DataType.image("RGB")
    phys = img.to_physical()
    assert phys.is_struct()
    assert set(phys.fields.keys()) == {"data", "channel", "height", "width", "mode"}
    fsi = DataType.fixed_shape_image("RGB", 4, 6)
    assert fsi.to_physical() == DataType.fixed_size_list(DataType.uint8(), 4 * 6 * 3)


def test_tensor_physical_lowering():
    t = DataType.tensor(DataType.float32())
    phys = t.to_physical()
    assert phys.is_struct() and set(phys.fields.keys()) == {"data", "shape"}
    ft = DataType.tensor(DataType.float32(), (2, 3))
    assert ft.to_physical() == DataType.fixed_size_list(DataType.float32(), 6)


def test_embedding():
    e = DataType.embedding(DataType.float32(), 128)
    assert e.is_embedding()
    assert e.to_physical() == DataType.fixed_size_list(DataType.float32(), 128)
    assert e.device_repr() == np.dtype(np.float32)


def test_device_repr():
    assert DataType.int64().device_repr() == np.dtype(np.int64)
    assert DataType.string().device_repr() == np.dtype(np.int32)  # dict codes
    assert DataType.date().device_repr() == np.dtype(np.int32)
    assert DataType.python().device_repr() is None
    assert DataType.list(DataType.int64()).device_repr() is None


def test_schema():
    s = Schema.from_pydict({"a": DataType.int64(), "b": DataType.string()})
    assert s.column_names == ["a", "b"]
    assert s["a"].dtype == DataType.int64()
    assert "b" in s and "c" not in s
    with pytest.raises(ValueError):
        Schema([Field("x", DataType.int64()), Field("x", DataType.int32())])
    u = s.non_distinct_union(Schema.from_pydict({"b": DataType.int8(),
                                                 "c": DataType.bool()}))
    assert u.column_names == ["a", "b", "c"]
    assert u["b"].dtype == DataType.string()  # left wins


def test_schema_arrow_roundtrip():
    s = Schema.from_pydict({"a": DataType.int64(), "b": DataType.string(),
                            "c": DataType.list(DataType.float64())})
    assert Schema.from_arrow(s.to_arrow()) == s


def test_multimodal_cast_matrix():
    """The reference's cast matrix between multimodal types
    (``src/daft-core/src/array/ops/cast.rs``): fixed↔variable tensor and
    image, image→tensor, dense↔sparse tensor — all columnar (no
    Python-object fallback), null-preserving, value-exact."""
    import numpy as np
    from daft_tpu.series import Series

    t = Series.from_pylist(
        [np.array([[0., 2.], [5., 0.]], np.float32), None], "t",
        dtype=DataType.tensor(DataType.float32()))
    assert not t.is_pyobject()
    sp = t.cast(DataType.sparse_tensor(DataType.float32()))
    assert sp.to_pylist()[0] == {"values": [2.0, 5.0], "indices": [1, 2],
                                 "shape": [2, 2]}
    assert sp.to_pylist()[1] is None
    back = sp.cast(DataType.tensor(DataType.float32()))
    assert back.to_pylist()[0].tolist() == [[0.0, 2.0], [5.0, 0.0]]
    assert back.to_pylist()[1] is None

    img = Series.from_pylist(
        [np.arange(12, dtype=np.uint8).reshape(2, 2, 3)], "i",
        dtype=DataType.image("RGB"))
    assert not img.is_pyobject()
    it = img.cast(DataType.tensor(DataType.uint8()))
    assert it.to_pylist()[0].shape == (2, 2, 3)
    assert it.to_pylist()[0].ravel().tolist() == list(range(12))

    ft = Series.from_pylist(
        [np.arange(6).reshape(2, 3).astype(np.float32)], "ft",
        dtype=DataType.tensor(DataType.float32(), (2, 3)))
    assert ft.cast(DataType.tensor(
        DataType.float32())).to_pylist()[0].shape == (2, 3)

    fi = Series.from_pylist(
        [np.ones((2, 2, 3), np.uint8)], "fi",
        dtype=DataType.fixed_shape_image("RGB", 2, 2))
    vi = fi.cast(DataType.image("RGB"))
    assert vi.to_pylist()[0].shape == (2, 2, 3)

    emb = Series.from_pylist([[1.0, 2.0, 3.0]], "e",
                             dtype=DataType.embedding(DataType.float32(), 3))
    assert repr(emb.cast(DataType.tensor(DataType.float32())).datatype()) \
        == repr(DataType.tensor(DataType.float32()))
