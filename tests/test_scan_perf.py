"""Scan fast path: byte-range read planner, parallel coalesced fetch,
prefetch-pipelined scans (reference: ``daft-parquet/read_planner`` +
``src/daft-io``).

Covers: planner range math (coalesce gap, request floor,
projection/pruning interaction), ``get_ranges`` parity across
Local/HTTP/S3-stub sources, prefetch ordering + memory admission +
chaos-serialize degradation, the per-query ``io`` stats block, 4xx
no-retry, hive key union, null_count/is_in pruning, head-range schema
inference, and parity of a pruned+projected remote read vs the naive
path."""

import http.server
import os
import threading
import urllib.parse

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import daft_tpu as dt
from daft_tpu import col
from daft_tpu.context import execution_config_ctx
from daft_tpu.io import read_planner as rp
from daft_tpu.io.object_io import (HTTPConfig, HTTPSource, LocalSource,
                                   retry_backoff_s)


# --------------------------------------------------------------- fixtures

class _RangeStore(http.server.BaseHTTPRequestHandler):
    """In-memory object store speaking Range/HEAD/404 + scripted failures;
    every request lands in ``log`` so tests count GETs per path."""

    store = {}
    log = []
    fail_next = []  # status codes consumed one per request

    def log_message(self, *a):
        pass

    def _key(self):
        return urllib.parse.urlparse(self.path).path.lstrip("/")

    def _scripted(self):
        if _RangeStore.fail_next:
            code = _RangeStore.fail_next.pop(0)
            self.send_response(code)
            self.end_headers()
            return True
        return False

    def do_HEAD(self):
        _RangeStore.log.append(("HEAD", self._key()))
        data = self.store.get(self._key())
        if data is None:
            self.send_response(404)
            self.end_headers()
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()

    def do_GET(self):
        _RangeStore.log.append(("GET", self._key()))
        if self._scripted():
            return
        data = self.store.get(self._key())
        if data is None:
            self.send_response(404)
            self.end_headers()
            return
        rng = self.headers.get("Range")
        if rng:
            spec = rng.split("=")[1]
            a, b = spec.split("-")
            start, end = int(a), min(int(b), len(data) - 1)
            chunk = data[start:end + 1]
            self.send_response(206)
        else:
            chunk = data
            self.send_response(200)
        self.send_header("Content-Length", str(len(chunk)))
        self.end_headers()
        self.wfile.write(chunk)


@pytest.fixture(scope="module")
def store():
    _RangeStore.store = {}
    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), _RangeStore)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{srv.server_port}"
    srv.shutdown()


def _parquet_bytes(table, **kw) -> bytes:
    import io as _io
    buf = _io.BytesIO()
    pq.write_table(table, buf, **kw)
    return buf.getvalue()


@pytest.fixture
def remote_dataset(store):
    """4 parquet files × 4 row groups × 4 columns on the HTTP store."""
    urls = []
    for i in range(4):
        n = 400
        t = pa.table({
            "seq": pa.array(range(i * n, (i + 1) * n)),
            "v": pa.array([float(j) for j in range(n)]),
            "pad": pa.array([f"pad-{j % 13}" for j in range(n)]),
            "w": pa.array([j * 2 for j in range(n)]),
        })
        key = f"ds/part-{i}.parquet"
        _RangeStore.store[key] = _parquet_bytes(t, row_group_size=100)
        urls.append(f"{store}/{key}")
    return urls


# --------------------------------------------------------- planner: math

def test_coalesce_gap_merges_within_tolerance():
    ranges = [(0, 10), (15, 30), (200, 210), (205, 260)]
    out = rp.coalesce_ranges(ranges, gap=10, floor=0)
    assert out == [(0, 30), (200, 260)]  # overlap + small hole merge
    # a hole wider than the tolerance stays split (floor off)
    assert rp.coalesce_ranges([(0, 10), (100, 110)], gap=10, floor=0) \
        == [(0, 10), (100, 110)]
    assert rp.coalesce_ranges([], gap=10, floor=0) == []


def test_coalesce_request_floor_batches_small_requests():
    # sub-floor requests absorb neighbors across holes smaller than the
    # floor — scattered small chunks become one RTT-amortizing request
    ranges = [(0, 10), (50, 60), (100, 110)]
    assert rp.coalesce_ranges(ranges, gap=5, floor=1000) == [(0, 110)]
    # two already-large requests split by a hole > gap stay separate
    big = [(0, 2000), (3500, 6000)]
    assert rp.coalesce_ranges(big, gap=5, floor=1000) == big
    # hole >= floor is never absorbed, however small the requests
    assert rp.coalesce_ranges([(0, 10), (5000, 5010)], gap=5, floor=1000) \
        == [(0, 10), (5000, 5010)]


def test_plan_parquet_ranges_projection_and_pruning(tmp_path):
    p = str(tmp_path / "t.parquet")
    t = pa.table({"a": list(range(1000)),
                  "b": [float(i) for i in range(1000)],
                  "c": [f"s{i}" for i in range(1000)]})
    pq.write_table(t, p, row_group_size=250)  # 4 row groups
    md = pq.ParquetFile(p).metadata

    def chunk_span(g, name):
        rg = md.row_group(g)
        for ci in range(rg.num_columns):
            cc = rg.column(ci)
            if cc.path_in_schema == name:
                start = cc.data_page_offset
                if cc.dictionary_page_offset is not None:
                    start = min(start, cc.dictionary_page_offset)
                return (start, start + cc.total_compressed_size)
        raise KeyError(name)

    # projection × pruning: exactly the selected groups' selected chunks
    got = rp.plan_parquet_ranges(md, row_groups=[1, 3], columns=["a"])
    assert got == sorted([chunk_span(1, "a"), chunk_span(3, "a")])
    # all groups, two columns — 8 ranges before normalization
    got = rp.plan_parquet_ranges(md, None, ["a", "b"])
    total = sum(e - s for s, e in got)
    expect = sum(chunk_span(g, c)[1] - chunk_span(g, c)[0]
                 for g in range(4) for c in ("a", "b"))
    assert total == expect  # overlap-merge never loses or double-counts
    assert rp.plan_parquet_ranges(md, [], ["a"]) == []
    # unknown column projects to nothing
    assert rp.plan_parquet_ranges(md, [0], []) == []


def test_range_cache_reads_across_segments():
    cache = rp.RangeCache([((0, 10), bytes(range(10))),
                           ((20, 30), bytes(range(20, 30)))])
    assert cache.read(2, 8) == bytes(range(2, 8))
    assert cache.read(20, 30) == bytes(range(20, 30))
    with pytest.raises(KeyError):
        cache.read(5, 25)  # hole between segments
    with pytest.raises(KeyError):
        cache.read(28, 35)  # runs past a segment


# ------------------------------------------------- get_ranges: parity

def test_get_ranges_parity_across_sources(tmp_path, store, monkeypatch):
    from daft_tpu.io.object_io import S3Config
    from daft_tpu.io.s3 import S3Source

    data = bytes(range(256)) * 40
    ranges = [(0, 100), (5000, 5500), (137, 139), (10000, 10240)]
    expected = [data[s:e] for s, e in ranges]

    lp = tmp_path / "blob.bin"
    lp.write_bytes(data)
    assert LocalSource().get_ranges(str(lp), ranges) == expected

    _RangeStore.store["parity/blob.bin"] = data
    http_src = HTTPSource(HTTPConfig())
    assert http_src.get_ranges(f"{store}/parity/blob.bin", ranges,
                               parallelism=3) == expected

    s3 = S3Source(S3Config(endpoint_url=store, key_id="k", access_key="s",
                           region_name="us-east-1"))
    _RangeStore.store["bkt/blob.bin"] = data
    assert s3.get_ranges("s3://bkt/blob.bin", ranges,
                         parallelism=4) == expected

    # stats thread through: one record per request
    from daft_tpu.io.object_io import IOStatsContext
    st = IOStatsContext("t")
    LocalSource().get_ranges(str(lp), ranges, st)
    assert st.num_gets == len(ranges)
    assert st.bytes_read == sum(len(b) for b in expected)


# ------------------------------------- planned remote reads: end-to-end

def test_planned_remote_read_parity_and_coalescing(remote_dataset,
                                                   monkeypatch):
    monkeypatch.setenv("DAFT_TPU_DEVICE", "0")

    def q():
        with execution_config_ctx(scan_tasks_min_size_bytes=1):
            return (dt.read_parquet(remote_dataset)
                    .where(col("seq") < 800)
                    .select("seq", "v").to_pydict())

    monkeypatch.setenv("DAFT_TPU_IO_PLANNED_READS", "0")
    monkeypatch.setenv("DAFT_TPU_SCAN_PREFETCH", "0")
    before = rp.scan_counters_snapshot()
    naive = q()
    naive_c = rp.scan_counters_delta(before)

    monkeypatch.setenv("DAFT_TPU_IO_PLANNED_READS", "1")
    monkeypatch.setenv("DAFT_TPU_SCAN_PREFETCH", "2")
    before = rp.scan_counters_snapshot()
    fast = q()
    fast_c = rp.scan_counters_delta(before)

    assert sorted(naive["seq"]) == sorted(fast["seq"]) == list(range(800))
    assert naive["v"] and sorted(naive["v"]) == sorted(fast["v"])
    # the whole point: far fewer object GETs for the same read
    assert fast_c.get("gets", 0) < naive_c.get("gets", 0)
    assert fast_c.get("range_requests", 0) < fast_c.get("ranges_planned", 0)
    assert fast_c.get("bytes_used", 0) > 0
    assert not fast_c.get("planned_read_fallbacks")
    assert fast_c.get("prefetch_tasks", 0) > 0


def test_planned_read_row_group_pruning_fetches_less(remote_dataset,
                                                     monkeypatch):
    monkeypatch.setenv("DAFT_TPU_DEVICE", "0")
    monkeypatch.setenv("DAFT_TPU_IO_PLANNED_READS", "1")

    def run(pred):
        with execution_config_ctx(scan_tasks_min_size_bytes=1):
            df = dt.read_parquet(remote_dataset).select("seq", "v")
            if pred is not None:
                df = df.where(pred)
            before = rp.scan_counters_snapshot()
            out = df.to_pydict()
            return out, rp.scan_counters_delta(before)

    full, full_c = run(None)
    pruned, pruned_c = run(col("seq") < 100)  # 1 of 16 row groups
    assert len(full["seq"]) == 1600 and sorted(pruned["seq"]) == \
        list(range(100))
    assert pruned_c.get("bytes_used", 0) < full_c.get("bytes_used", 1)
    assert pruned_c.get("ranges_planned", 0) < full_c.get(
        "ranges_planned", 1)


# ------------------------------------------------ prefetch pipeline

def test_prefetch_preserves_task_order(tmp_path, monkeypatch):
    monkeypatch.setenv("DAFT_TPU_DEVICE", "0")
    monkeypatch.setenv("DAFT_TPU_SCAN_PREFETCH", "3")
    for i in range(6):
        pq.write_table(pa.table({"x": list(range(i * 10, (i + 1) * 10))}),
                       tmp_path / f"p{i}.parquet")
    with execution_config_ctx(scan_tasks_min_size_bytes=1,
                              max_sources_per_scan_task=1):
        out = dt.read_parquet(str(tmp_path) + "/*.parquet").to_pydict()
    # no sort anywhere: order is the glob (task) order
    assert out["x"] == list(range(60))


def test_prefetch_early_limit_abandons_cleanly(tmp_path, monkeypatch):
    """A satisfied limit abandons the scan stream mid-task: the window's
    producers must unblock (dead-stream signal), not wedge the pool."""
    monkeypatch.setenv("DAFT_TPU_DEVICE", "0")
    monkeypatch.setenv("DAFT_TPU_SCAN_PREFETCH", "3")
    for i in range(6):
        pq.write_table(pa.table({"x": list(range(i * 1000, (i + 1) * 1000))}),
                       tmp_path / f"p{i}.parquet")
    with execution_config_ctx(scan_tasks_min_size_bytes=1,
                              max_sources_per_scan_task=1,
                              default_morsel_size=100):
        out = dt.read_parquet(str(tmp_path) + "/*.parquet").limit(150) \
            .to_pydict()
    assert out["x"] == list(range(150))


def test_prefetch_memory_admission(tmp_path, monkeypatch):
    """Prefetched bytes stay under the memory budget: with a budget that
    fits ~one task, the window's producers serialize on admission."""
    from daft_tpu.execution import memory
    from daft_tpu.execution.executor import LocalExecutor
    from daft_tpu.io.scan import GlobScanOperator, Pushdowns
    from daft_tpu.physical import plan as pp

    monkeypatch.setenv("DAFT_TPU_SCAN_PREFETCH", "3")
    for i in range(5):
        pq.write_table(
            pa.table({"x": list(range(2000)),
                      "y": [float(j) for j in range(2000)]}),
            tmp_path / f"p{i}.parquet")

    with execution_config_ctx(scan_tasks_min_size_bytes=1,
                              max_sources_per_scan_task=1):
        op = GlobScanOperator(str(tmp_path) + "/*.parquet", "parquet")
        tasks = op.to_scan_tasks(Pushdowns())
        assert len(tasks) == 5
        sizes = [t.size_bytes() for t in tasks]
        assert all(sizes)
        budget = int(max(sizes) * 1.5)  # roughly one task at a time

        class Tracking(memory.MemoryManager):
            max_held = 0

            def acquire(self, n):
                super().acquire(n)
                with self._cond:
                    Tracking.max_held = max(Tracking.max_held, self._held)

        ex = LocalExecutor()
        ex.mem = Tracking(budget)
        node = pp.ScanSource(tasks, op.schema())
        out = list(ex._exec_ScanSource(node))
        assert sum(len(p) for p in out) == 5 * 2000
        assert 0 < Tracking.max_held <= budget


def test_prefetch_admission_no_deadlock(tmp_path, monkeypatch):
    """Regression: with a budget admitting only ONE task and multi-file
    tasks producing more batches than any queue bound, an out-of-order
    admission must not deadlock the FIFO consumer (review finding: a
    later producer holding admission while blocked on a bounded queue
    starved the head task forever)."""
    from daft_tpu.execution import memory
    from daft_tpu.execution.executor import LocalExecutor
    from daft_tpu.io.scan import Pushdowns, ScanTask
    from daft_tpu.physical import plan as pp
    from daft_tpu.schema import Schema

    monkeypatch.setenv("DAFT_TPU_SCAN_PREFETCH", "2")
    paths = []
    for i in range(12):
        p = str(tmp_path / f"f{i}.parquet")
        pq.write_table(pa.table({"x": list(range(i * 50, (i + 1) * 50))}), p)
        paths.append(p)
    schema = Schema.from_arrow(pq.read_schema(paths[0]))
    # two 6-file tasks (>4 batches each), est sized so only one admits
    tasks = [ScanTask(paths[:6], "parquet", schema, Pushdowns(),
                      size_bytes_hint=800_000),
             ScanTask(paths[6:], "parquet", schema, Pushdowns(),
                      size_bytes_hint=800_000)]
    ex = LocalExecutor()
    ex.mem = memory.MemoryManager(1_000_000)
    node = pp.ScanSource(tasks, schema)
    result = {}

    def drain():
        result["rows"] = sum(len(p)
                             for p in ex._exec_ScanSource(node))

    t = threading.Thread(target=drain, daemon=True)
    t.start()
    t.join(timeout=30)
    assert not t.is_alive(), "prefetch scan deadlocked under admission"
    assert result["rows"] == 600


def test_prefetch_degrades_under_chaos(remote_dataset, monkeypatch):
    """PR 2 contract: an active fault plan or DAFT_TPU_CHAOS_SERIALIZE=1
    forces the pre-fast-path sequential scan loop (prefetch_tasks counter
    stays flat), while the answer is unchanged."""
    monkeypatch.setenv("DAFT_TPU_DEVICE", "0")
    monkeypatch.setenv("DAFT_TPU_SCAN_PREFETCH", "4")

    def q():
        with execution_config_ctx(scan_tasks_min_size_bytes=1):
            return dt.read_parquet(remote_dataset).select("seq") \
                .to_pydict()

    assert rp.scan_sequential_fallback() is False
    monkeypatch.setenv("DAFT_TPU_CHAOS_SERIALIZE", "1")
    assert rp.scan_sequential_fallback() is True
    before = rp.scan_counters_snapshot()
    out = q()
    delta = rp.scan_counters_delta(before)
    assert sorted(out["seq"]) == list(range(1600))
    assert delta.get("prefetch_tasks", 0) == 0

    monkeypatch.delenv("DAFT_TPU_CHAOS_SERIALIZE")
    monkeypatch.setenv("DAFT_TPU_FAULT_SPEC", "task:0")
    from daft_tpu.distributed import resilience as rz
    rz.reset_for_tests()
    assert rp.scan_sequential_fallback() is True
    before = rp.scan_counters_snapshot()
    q()
    assert rp.scan_counters_delta(before).get("prefetch_tasks", 0) == 0
    monkeypatch.delenv("DAFT_TPU_FAULT_SPEC")
    rz.reset_for_tests()


# ------------------------------------------------------- io stats block

def test_io_stats_block_in_explain_analyze(remote_dataset, monkeypatch,
                                           capsys):
    import daft_tpu.observability as obs
    monkeypatch.setenv("DAFT_TPU_DEVICE", "0")
    monkeypatch.setenv("DAFT_TPU_IO_PLANNED_READS", "1")
    monkeypatch.setenv("DAFT_TPU_SCAN_PREFETCH", "2")
    with execution_config_ctx(scan_tasks_min_size_bytes=1):
        df = dt.read_parquet(remote_dataset).where(col("seq") < 800) \
            .select("seq", "v")
        df.explain(analyze=True)
    printed = capsys.readouterr().out
    assert "io (scan plane):" in printed
    assert "range requests" in printed
    st = obs.last_query_stats()
    assert st is not None and st.io.get("gets", 0) > 0
    assert st.io.get("bytes_fetched", 0) > 0
    lines = obs.render_io_block(st.io)
    assert any("prefetch" in ln for ln in lines)


# ------------------------------------------------------ retry satellite

def test_http_4xx_not_retried_5xx_retried(store):
    src = HTTPSource(HTTPConfig(num_tries=4))
    _RangeStore.store["r/x.bin"] = b"payload"

    _RangeStore.log = []
    with pytest.raises(Exception):
        src.get(f"{store}/r/missing.bin")
    # 404 is deterministic: exactly ONE request, not num_tries
    assert len([e for e in _RangeStore.log
                if e[1] == "r/missing.bin"]) == 1

    _RangeStore.fail_next = [500, 503]
    assert src.get(f"{store}/r/x.bin") == b"payload"  # 2 failures + 1 ok


def test_retry_backoff_deterministic_and_bounded():
    a = [retry_backoff_s("s3://b/k", i) for i in range(6)]
    b = [retry_backoff_s("s3://b/k", i) for i in range(6)]
    assert a == b  # deterministic jitter
    assert all(0 < x <= 2.0 for x in a)  # hard cap, jitter included
    assert retry_backoff_s("other", 0) != a[0]  # keyed jitter


# ------------------------------------------------------- hive satellite

def test_hive_union_across_mixed_key_paths(tmp_path):
    (tmp_path / "g=a").mkdir()
    (tmp_path / "g=b" / "h=1").mkdir(parents=True)
    pq.write_table(pa.table({"v": [1, 2]}), tmp_path / "g=a" / "x.parquet")
    pq.write_table(pa.table({"v": [3]}),
                   tmp_path / "g=b" / "h=1" / "y.parquet")
    df = dt.read_parquet(str(tmp_path) + "/**/*.parquet",
                         hive_partitioning=True)
    assert set(df.schema().column_names) == {"v", "g", "h"}
    out = df.sort("v").to_pydict()
    assert out["v"] == [1, 2, 3]
    assert out["g"] == ["a", "a", "b"]
    # missing-key → null fill on the path without h=
    assert out["h"] == [None, None, "1"]


# ---------------------------------------------------- pruning satellite

def test_prune_null_count_and_is_in(tmp_path):
    from daft_tpu.io.readers import _prune_row_groups
    from daft_tpu.schema import Schema

    p = str(tmp_path / "t.parquet")
    t = pa.table({
        # g0: 0..99 no nulls; g1: all nulls; g2: 200..299 some nulls
        "a": pa.array(list(range(100)) + [None] * 100
                      + list(range(200, 290)) + [None] * 10),
    })
    pq.write_table(t, p, row_group_size=100)
    md = pq.ParquetFile(p).metadata
    schema = Schema.from_arrow(pq.read_schema(p))

    # is_null: zero-null groups prune
    assert _prune_row_groups(md, col("a").is_null(), schema) == [1, 2]
    # not_null: the all-null group prunes
    assert _prune_row_groups(md, col("a").not_null(), schema) == [0, 2]
    # is_in: min/max containment (g1 has no min/max → kept conservatively)
    assert _prune_row_groups(md, col("a").is_in([250, 270]), schema) \
        == [1, 2]
    assert _prune_row_groups(md, col("a").is_in([50]), schema) == [0, 1]
    # conjunct composes with the existing comparison bounds
    assert _prune_row_groups(
        md, col("a").is_in([250]) & (col("a") > 240), schema) == [1, 2]
    # end-to-end answers agree with the pruned plan
    out = dt.read_parquet(p).where(col("a").is_in([50, 250])) \
        .to_pydict()
    assert sorted(out["a"]) == [50, 250]
    out = dt.read_parquet(p).where(col("a").is_null()).to_pydict()
    assert len(out["a"]) == 110


# -------------------------------------------------- inference satellite

def test_remote_csv_schema_from_head_range(store, monkeypatch):
    body = ("x,y\n" + "\n".join(f"{i},{i * 0.5}" for i in range(20000))) \
        .encode()
    _RangeStore.store["csv/big.csv"] = body
    monkeypatch.setenv("DAFT_TPU_IO_INFER_BYTES", "4096")
    before = rp.scan_counters_snapshot()
    df = dt.read_csv(f"{store}/csv/big.csv")
    assert df.schema().column_names == ["x", "y"]
    delta = rp.scan_counters_delta(before)
    # inference fetched a bounded head, not the whole object
    assert 0 < delta.get("bytes_fetched", 0) < len(body)
    out = df.to_pydict()
    assert len(out["x"]) == 20000 and out["x"][:3] == [0, 1, 2]


def test_remote_json_head_inference_falls_back_whole(store, monkeypatch):
    # ONE record larger than the head budget: the truncated head can't
    # parse → whole-object fallback still infers correctly
    rec = '{"a": 1, "blob": "%s"}\n' % ("z" * 9000)
    _RangeStore.store["js/one.json"] = rec.encode()
    monkeypatch.setenv("DAFT_TPU_IO_INFER_BYTES", "1024")
    before = rp.scan_counters_snapshot()
    df = dt.read_json(f"{store}/js/one.json")
    assert set(df.schema().column_names) == {"a", "blob"}
    assert rp.scan_counters_delta(before).get("infer_head_fallbacks", 0) \
        >= 0  # truncation without newline skips the parse attempt
    assert df.to_pydict()["a"] == [1]


def test_chunked_stream_reader_exact_bytes(tmp_path):
    data = os.urandom(50_000)
    p = tmp_path / "blob.bin"
    p.write_bytes(data)
    r = rp.ChunkedObjectReader(LocalSource(), str(p), chunk=7_000)
    got = b""
    while True:
        piece = r.read(4_096)
        if not piece:
            break
        got += piece
    assert got == data
