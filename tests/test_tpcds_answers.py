"""TPC-DS answer validation: every query's engine output row-checked
against an independent pandas oracle (``tests/tpcds_oracle.py``).

The checker is LIMIT-and-tie aware: the oracle computes the FULL result
plus the query's ORDER BY spec; the engine rows must (a) have the right
count, (b) match the oracle's sorted key sequence position-by-position
(ties leave the key sequence unambiguous even when row order inside a tie
group is not), and (c) be drawn from the oracle's row multiset.

Reference analogue: ``tests/integration/test_tpch.py`` +
``benchmarking/tpch/answers.py`` (dbgen-derived expected answers)."""

import datetime
import math

import numpy as np
import pandas as pd
import pytest

import daft_tpu as dt
from benchmarking.tpcds import queries as Q
from benchmarking.tpcds.datagen import generate_tpcds

from tpcds_oracle import Tables, sql_sort
import tpcds_oracle as O


@pytest.fixture(scope="module")
def env(tmp_path_factory):
    root = tmp_path_factory.mktemp("tpcds_ans")
    generate_tpcds(str(root), scale=0.04)

    def get_df(name):
        return dt.read_parquet(f"{root}/{name}/*.parquet")

    return get_df, Tables(get_df)


def _norm(v):
    """Comparison-normalize one value: numerics → floats, dates → ISO
    strings, NaN/None → None. Floats keep full precision — equality is
    decided by ``_val_eq``'s tolerance, never by rounding (rounding flips
    at digit boundaries when the two sides sum in different orders)."""
    if v is None:
        return None
    if isinstance(v, (float, np.floating)):
        f = float(v)
        return None if math.isnan(f) else f
    if isinstance(v, (int, np.integer)):
        return float(v)
    if isinstance(v, np.bool_):
        return bool(v)
    if isinstance(v, (pd.Timestamp, datetime.date, datetime.datetime)):
        return str(v)[:10]
    if v is pd.NaT:
        return None
    return v


def _val_eq(a, b):
    a, b = _norm(a), _norm(b)
    if a is None or b is None:
        return a is None and b is None
    if isinstance(a, float) and isinstance(b, float):
        return math.isclose(a, b, rel_tol=1e-6, abs_tol=1e-6)
    return a == b


def _row_eq(g, e):
    return len(g) == len(e) and all(_val_eq(a, b) for a, b in zip(g, e))


def _rows(df, cols):
    return [tuple(row) for row in df[cols].itertuples(index=False)]


def _match_multiset(got_rows, exp_rows):
    """Greedy bipartite match of got rows into the oracle's row pool with
    per-value tolerance. Returns the unmatched got rows."""
    pool = list(exp_rows)
    unmatched = []
    for g in got_rows:
        for i, e in enumerate(pool):
            if _row_eq(g, e):
                pool.pop(i)
                break
        else:
            unmatched.append(g)
    return unmatched


def assert_matches(got: pd.DataFrame, exp: pd.DataFrame, m: dict,
                   qnum: int):
    cols = [c for c in got.columns]
    missing = [c for c in cols if c not in exp.columns]
    assert not missing, f"q{qnum}: oracle lacks columns {missing}"
    limit = m["limit"]
    n_expected = len(exp) if limit is None else min(limit, len(exp))
    assert len(got) == n_expected, \
        f"q{qnum}: row count {len(got)} != expected {n_expected} " \
        f"(oracle total {len(exp)})"
    if n_expected == 0:
        return
    if m.get("unordered") or not m["keys"]:
        bad = _match_multiset(_rows(got, cols), _rows(exp, cols))
        assert not bad, \
            f"q{qnum}: {len(bad)} rows not in the oracle result: {bad[:3]}"
        return
    exp_sorted = sql_sort(exp, m["keys"], m["asc"]).head(n_expected)
    # (b) key sequence must match (with tolerance), position by position
    key_cols = [k for k in m["keys"] if k in cols]
    for k in key_cols:
        gk, ek = list(got[k]), list(exp_sorted[k])
        diffs = [(i, a, b) for i, (a, b) in enumerate(zip(gk, ek))
                 if not _val_eq(a, b)]
        assert not diffs, \
            f"q{qnum}: ORDER BY key {k!r} sequence differs: {diffs[:3]}"
    # (c) full rows must come from the oracle's multiset (tie-safe)
    bad = _match_multiset(_rows(got, cols), _rows(exp, cols))
    assert not bad, \
        f"q{qnum}: {len(bad)} rows not in the oracle result: {bad[:3]}"


@pytest.mark.parametrize("qnum", sorted(Q.ALL))
def test_answers(env, qnum):
    get_df, T = env
    oracle = getattr(O, f"q{qnum}")
    got = Q.run(qnum, get_df).to_pandas()
    exp, m = oracle(T)
    assert_matches(got, exp, m, qnum)
