"""Resilience plane: deterministic fault injection at the three real
failure sites, retry/quarantine policy, Exoshuffle-style lineage
recovery of lost shuffle partitions, speculative execution, and task
deadlines (``daft_tpu/distributed/resilience.py``)."""

import os
import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

import daft_tpu
from daft_tpu import col
from daft_tpu.distributed import resilience as rz
from daft_tpu.distributed import WorkerManager
from daft_tpu.distributed.worker import StageTask, Worker
from daft_tpu.micropartition import MicroPartition
from daft_tpu.physical import plan as pp
from daft_tpu.runners.distributed_runner import DistributedRunner


@pytest.fixture(autouse=True)
def _fresh_resilience_state():
    rz.reset_for_tests()
    yield
    rz.reset_for_tests()


def _run_distributed(df, num_workers=3):
    import daft_tpu.context as ctx
    runner = DistributedRunner(num_workers=num_workers)
    old = ctx.get_context()._runner
    ctx.get_context().set_runner(runner)
    try:
        return df.to_pydict()
    finally:
        ctx.get_context().set_runner(old)


def _q5_shape_frames():
    """Fresh frames per call (a collected result would cache partitions
    and skip the exchanges on the second plan)."""
    rng = np.random.default_rng(5)
    n = 1500
    orders = daft_tpu.from_pydict({
        "okey": list(range(n)),
        "cust": rng.integers(0, 40, n).tolist(),
        "price": rng.uniform(1, 100, n).round(2).tolist(),
    }).into_partitions(4)
    customers = daft_tpu.from_pydict({
        "cust": list(range(40)),
        "region": rng.integers(0, 5, 40).tolist(),
    }).into_partitions(2)
    return orders, customers


def _q5_shape(orders, customers):
    return (orders.join(customers, on="cust")
            .groupby("region").agg(col("price").sum().alias("rev"),
                                   col("okey").count().alias("cnt"))
            .sort("region"))


def _scan_groupby_df(tmp_path, n_files=6):
    import pyarrow as pa
    import pyarrow.parquet as pq
    d = tmp_path / "t"
    if not d.exists():
        d.mkdir()
        for i in range(n_files):
            pq.write_table(
                pa.table({"k": [j % 5 for j in range(i * 100,
                                                     i * 100 + 100)],
                          "v": [float(j) for j in range(100)]}),
                str(d / f"{i}.parquet"))
    return (daft_tpu.read_parquet(str(d / "*.parquet"))
            .groupby("k").agg(col("v").sum().alias("s")).sort("k"))


# ------------------------------------------------------------ fault plan
def test_fault_plan_parse_and_hash_determinism():
    spec = "task:0.5,fetch:0.25:3,crash:1:1"
    a = rz.FaultPlan(spec, seed="11")
    b = rz.FaultPlan(spec, seed="11")
    keys = [f"s0.t{i}" for i in range(64)]
    da = [a.decide("task", k, attempt=0) for k in keys]
    db = [b.decide("task", k, attempt=0) for k in keys]
    assert da == db and any(da) and not all(da)
    c = rz.FaultPlan(spec, seed="12")
    assert [c.decide("task", k, attempt=0) for k in keys] != da
    # caps bound total injections at a site
    capped = rz.FaultPlan("fetch:1:2", seed="0")
    fired = sum(capped.decide("fetch", f"k{i}") for i in range(10))
    assert fired == 2
    with pytest.raises(ValueError):
        rz.FaultPlan("nonsense:1")


def test_sticky_fault_fires_on_every_attempt():
    p = rz.FaultPlan("task:1:sticky", seed="3")
    assert all(p.decide("task", "s0.t0", attempt=i) for i in range(4))
    # transient faults re-roll per attempt: a rate-1.0 transient also
    # always fires, but the injected identity differs per attempt
    t = rz.FaultPlan("task:1", seed="3")
    with pytest.raises(rz.InjectedFault) as e0:
        t.maybe_fail("task", "s0.t0", attempt=0)
    with pytest.raises(rz.InjectedFault) as e1:
        t.maybe_fail("task", "s0.t0", attempt=1)
    assert str(e0.value) != str(e1.value)
    s = rz.FaultPlan("task:1:sticky", seed="3")
    with pytest.raises(rz.InjectedFault) as s0:
        s.maybe_fail("task", "s0.t0", attempt=0)
    with pytest.raises(rz.InjectedFault) as s1:
        s.maybe_fail("task", "s0.t0", attempt=1)
    assert str(s0.value) == str(s1.value)


# ---------------------------------------------------------- retry policy
def _mock_states(*ids):
    return [SimpleNamespace(worker=SimpleNamespace(id=i), active=0)
            for i in ids]


def test_quarantine_opens_and_readmits():
    now = [0.0]
    pol = rz.RetryPolicy(max_retries=3, quarantine_after=2,
                         quarantine_s=10.0, clock=lambda: now[0])
    states = _mock_states("w0", "w1")
    assert not pol.record_failure("w0")
    assert not pol.is_quarantined("w0")
    assert pol.record_failure("w0")  # 2nd consecutive failure opens it
    assert pol.is_quarantined("w0")
    assert [s.worker.id for s in pol.eligible(states)] == ["w1"]
    c = rz.counters_snapshot()
    assert c.get("quarantined") == 1
    now[0] = 10.5  # timed re-admission
    assert not pol.is_quarantined("w0")
    assert [s.worker.id for s in pol.eligible(states)] == ["w0", "w1"]
    assert rz.counters_snapshot().get("readmitted") == 1


def test_eligible_never_empty_when_all_quarantined():
    now = [0.0]
    pol = rz.RetryPolicy(quarantine_after=1, quarantine_s=100.0,
                         clock=lambda: now[0])
    pol.record_failure("w0")
    pol.record_failure("w1")
    states = _mock_states("w0", "w1")
    assert pol.eligible(states)  # forced re-admission beats a deadlock
    assert pol.eligible(states, exclude="w0")


def test_success_resets_consecutive_failures():
    pol = rz.RetryPolicy(quarantine_after=2, quarantine_s=100.0)
    pol.record_failure("w0")
    pol.record_success("w0")
    assert not pol.record_failure("w0")
    assert not pol.is_quarantined("w0")


def test_backoff_is_deterministic_and_bounded():
    pol = rz.RetryPolicy(backoff_base=0.1, backoff_cap=1.0, seed="9")
    a = [pol.backoff_s("s0.t0", i) for i in range(1, 6)]
    b = [pol.backoff_s("s0.t0", i) for i in range(1, 6)]
    assert a == b
    assert all(x <= 1.5 for x in a)  # cap * max jitter
    assert a[1] > a[0] * 0.5  # grows (modulo jitter)


def test_fast_path_layers_do_not_perturb_chaos_replay(monkeypatch,
                                                      tmp_path):
    """PR 3 contract (extended by the PR 4 scan fast path): the shuffle
    fast path (map-side combine, IPC compression, parallel fetch) AND the
    scan fast path (planned coalesced reads, prefetch-pipelined tasks)
    degrade to the deterministic sequential behavior under
    DAFT_TPU_CHAOS_SERIALIZE=1 — the same seeded fault spec replays the
    SAME event sequence and answer across every knob combination,
    including raised fetch-parallelism / scan-prefetch that the serialize
    mode must override."""
    monkeypatch.setenv("DAFT_TPU_DEVICE", "0")
    monkeypatch.setenv("DAFT_TPU_DISTRIBUTED_SHUFFLE", "flight")
    monkeypatch.setenv("DAFT_TPU_FAULT_SPEC",
                       "task:0.06,fetch:0.06,crash:0.06")
    monkeypatch.setenv("DAFT_TPU_FAULT_SEED", "11")
    monkeypatch.setenv("DAFT_TPU_RETRY_BACKOFF", "0.01")
    monkeypatch.setenv("DAFT_TPU_CHAOS_SERIALIZE", "1")
    monkeypatch.setenv("DAFT_TPU_SPECULATIVE_MULTIPLIER", "0")
    from daft_tpu.context import execution_config_ctx

    def one_run(knobs):
        for k, v in knobs.items():
            monkeypatch.setenv(k, v)
        rz.reset_for_tests()
        with execution_config_ctx(scan_tasks_min_size_bytes=1):
            out = _run_distributed(_scan_groupby_df(tmp_path))
        return out, sorted(rz.fault_events())

    out1, ev1 = one_run({"DAFT_TPU_SHUFFLE_COMBINE": "0",
                         "DAFT_TPU_SHUFFLE_COMPRESSION": "none",
                         "DAFT_TPU_SHUFFLE_FETCH_PARALLELISM": "1",
                         "DAFT_TPU_SCAN_PREFETCH": "0",
                         "DAFT_TPU_DEVICE_INFLIGHT": "0",
                         "DAFT_TPU_IO_PLANNED_READS": "0"})
    out2, ev2 = one_run({"DAFT_TPU_SHUFFLE_COMBINE": "1",
                         "DAFT_TPU_SHUFFLE_COMPRESSION": "lz4",
                         "DAFT_TPU_SHUFFLE_FETCH_PARALLELISM": "8",
                         "DAFT_TPU_SCAN_PREFETCH": "8",
                         # r17 async device pipeline: serialize mode must
                         # override a raised in-flight window too
                         "DAFT_TPU_DEVICE_INFLIGHT": "8",
                         "DAFT_TPU_IO_PLANNED_READS": "1"})
    assert ev1, "the fixed spec/seed injected nothing — tune the seed"
    assert ev1 == ev2
    assert out1 == out2


# ------------------------------------------------- chaos: end-to-end
def test_chaos_smoke_fixed_spec(monkeypatch):
    """The CI chaos smoke: one distributed query under a fixed seeded
    fault spec covering all three injection sites — answers must equal
    the fault-free run, recovery events must be visible in the query's
    explain_analyze stats."""
    monkeypatch.setenv("DAFT_TPU_DEVICE", "0")
    monkeypatch.setenv("DAFT_TPU_DISTRIBUTED_SHUFFLE", "flight")
    o, c = _q5_shape_frames()
    expected = _q5_shape(o, c).to_pydict()  # fault-free, local runner

    monkeypatch.setenv("DAFT_TPU_FAULT_SPEC",
                       "task:0.08,fetch:0.08,crash:0.08")
    monkeypatch.setenv("DAFT_TPU_FAULT_SEED", "1")
    monkeypatch.setenv("DAFT_TPU_RETRY_BACKOFF", "0.01")
    o, c = _q5_shape_frames()
    got = _run_distributed(_q5_shape(o, c))
    assert got["region"] == expected["region"]
    assert got["cnt"] == expected["cnt"]
    for a, b in zip(got["rev"], expected["rev"]):
        assert a == pytest.approx(b, rel=1e-9)
    counters = rz.counters_snapshot()
    injected = sum(v for k, v in counters.items()
                   if k.startswith("injected_"))
    assert injected > 0, counters
    assert counters.get("retries", 0) > 0, counters
    # the driver-level stats context renders the recovery ledger
    from daft_tpu import observability as obs
    stats = obs.last_query_stats()
    assert stats is not None and stats.recovery
    assert "resilience (recovery events):" in stats.render()


def test_same_seed_reproduces_same_fault_events(monkeypatch, tmp_path):
    """Replay determinism: two runs of the same query under the same
    seed inject the same fault sequence — all three sites, including
    worker crashes. Decisions hash stable identifiers (never shared RNG
    state); DAFT_TPU_CHAOS_SERIALIZE pins the one remaining freedom,
    the interleaving of concurrent recoveries of a crashed shared
    source."""
    monkeypatch.setenv("DAFT_TPU_DEVICE", "0")
    monkeypatch.setenv("DAFT_TPU_DISTRIBUTED_SHUFFLE", "flight")
    monkeypatch.setenv("DAFT_TPU_FAULT_SPEC",
                       "task:0.06,fetch:0.06,crash:0.06")
    monkeypatch.setenv("DAFT_TPU_FAULT_SEED", "11")
    monkeypatch.setenv("DAFT_TPU_RETRY_BACKOFF", "0.01")
    monkeypatch.setenv("DAFT_TPU_CHAOS_SERIALIZE", "1")
    # speculation is timing-driven (wall-clock medians) and therefore
    # outside the deterministic-replay contract — pin it off here
    monkeypatch.setenv("DAFT_TPU_SPECULATIVE_MULTIPLIER", "0")
    from daft_tpu.context import execution_config_ctx

    def one_run():
        rz.reset_for_tests()
        with execution_config_ctx(scan_tasks_min_size_bytes=1):
            out = _run_distributed(_scan_groupby_df(tmp_path))
        return out, sorted(rz.fault_events())

    out1, ev1 = one_run()
    out2, ev2 = one_run()
    assert ev1, "the fixed spec/seed injected nothing — tune the seed"
    # all three failure sites participated in the replayed sequence
    assert {e.split(":")[0] for e in ev1} == {"task", "fetch", "crash"}
    assert ev1 == ev2
    assert out1 == out2


def test_lost_partition_recomputes_only_producing_map_task(monkeypatch,
                                                           tmp_path):
    """Exoshuffle-style lineage: a crashed serving worker (its shuffle
    data destroyed) triggers re-execution of ONLY the producing map
    task, not the whole map stage."""
    monkeypatch.setenv("DAFT_TPU_DEVICE", "0")
    monkeypatch.setenv("DAFT_TPU_DISTRIBUTED_SHUFFLE", "flight")
    from daft_tpu.context import execution_config_ctx
    expected = _scan_groupby_df(tmp_path).to_pydict()  # fault-free

    monkeypatch.setenv("DAFT_TPU_FAULT_SPEC", "crash:1:1")
    monkeypatch.setenv("DAFT_TPU_FAULT_SEED", "7")
    monkeypatch.setenv("DAFT_TPU_RETRY_BACKOFF", "0.01")
    with execution_config_ctx(scan_tasks_min_size_bytes=1):
        got = _run_distributed(_scan_groupby_df(tmp_path))
    assert got == expected
    c = rz.counters_snapshot()
    assert c.get("injected_crash") == 1, c
    # several map tasks served the shuffle; exactly the lost one re-ran
    assert c.get("recomputed_map_tasks") == 1, c
    assert c.get("fetch_failures", 0) >= 2, c  # fail, refetch-fail, recover


def test_identical_failure_on_two_workers_fails_fast(monkeypatch):
    """A sticky task fault fails the same way wherever it runs: after
    two distinct workers report the identical signature the supervisor
    raises FailFastError instead of burning the retry budget."""
    monkeypatch.setenv("DAFT_TPU_FAULT_SPEC", "task:1:sticky")
    monkeypatch.setenv("DAFT_TPU_FAULT_SEED", "1")
    monkeypatch.setenv("DAFT_TPU_RETRY_BACKOFF", "0.01")
    df = daft_tpu.from_pydict({"x": [1, 2, 3]})
    with pytest.raises(rz.FailFastError):
        _run_distributed(df.select(col("x") + 1))
    c = rz.counters_snapshot()
    assert c.get("fail_fast") == 1, c
    assert c.get("injected_task") == 2, c  # exactly two attempts, then stop


# ---------------------------------------------- supervisor-level mocks
class CannedWorker(Worker):
    """Immediately returns a canned per-task result."""

    def __init__(self, worker_id, delay=0.0, fail_times=0):
        self.id = worker_id
        self.num_slots = 4
        self.delay = delay
        self.fail_times = fail_times
        self.submitted = []

    def submit(self, task):
        import concurrent.futures as cf
        self.submitted.append(task)
        fut = cf.Future()

        def finish():
            if self.fail_times > 0:
                self.fail_times -= 1
                fut.set_exception(RuntimeError("canned failure"))
            else:
                fut.set_result(
                    [MicroPartition.from_pydict({"x": [task.task_idx]})])

        if self.delay:
            t = threading.Timer(self.delay, finish)
            t.daemon = True
            t.start()
        else:
            finish()
        return fut


def _trivial_tasks(n):
    return [StageTask(0, pp.InMemorySource([], None), {}, task_idx=i,
                      fault_key=f"s0.t{i}")
            for i in range(n)]


def test_speculative_backup_wins_over_straggler(monkeypatch):
    """A task running past multiplier×median-of-siblings gets a backup
    on another worker; the first finisher wins."""
    slow = CannedWorker("slow", delay=5.0)
    fast = CannedWorker("fast", delay=0.0)
    mgr = WorkerManager([slow, fast])

    class RouteLastToSlow:
        def pick(self, task, states):
            ids = [s.worker.id for s in states]
            if task.task_idx == 3 and "slow" in ids:
                return "slow"
            return "fast" if "fast" in ids else ids[0]

    pol = rz.RetryPolicy(speculative_multiplier=2.0,
                         speculative_min_s=0.2, task_timeout=0)
    sup = rz.TaskSupervisor(rz.ResilienceContext(policy=pol), mgr,
                            RouteLastToSlow())
    t0 = time.monotonic()
    results = sup.run(_trivial_tasks(4))
    assert time.monotonic() - t0 < 4.0  # did NOT wait out the straggler
    assert [r[0].to_pydict() for r in results] == \
        [{"x": [i]} for i in range(4)]
    c = rz.counters_snapshot()
    assert c.get("speculative_launched") == 1, c
    assert c.get("speculative_wins") == 1, c


def test_task_timeout_is_retried_on_another_worker(monkeypatch):
    """DAFT_TPU_TASK_TIMEOUT: a hung worker can't stall the stage — the
    attempt is abandoned (counted) and redispatched elsewhere."""
    hung = CannedWorker("hung", delay=5.0)
    good = CannedWorker("good", delay=0.0)
    mgr = WorkerManager([hung, good])

    class PickHungFirst:
        def __init__(self):
            self.calls = 0

        def pick(self, task, states):
            self.calls += 1
            ids = [s.worker.id for s in states]
            return "hung" if self.calls == 1 and "hung" in ids else \
                ("good" if "good" in ids else ids[0])

    pol = rz.RetryPolicy(task_timeout=0.3, speculative_multiplier=0,
                         backoff_base=0.01)
    sup = rz.TaskSupervisor(rz.ResilienceContext(policy=pol), mgr,
                            PickHungFirst())
    t0 = time.monotonic()
    results = sup.run(_trivial_tasks(1))
    assert time.monotonic() - t0 < 4.0
    assert results[0][0].to_pydict() == {"x": [0]}
    c = rz.counters_snapshot()
    assert c.get("task_timeouts") == 1, c
    assert c.get("retries") == 1, c
    assert len(good.submitted) == 1


def test_repeated_timeouts_do_not_fail_fast():
    """Timeouts are timing-dependent, not task-deterministic: two
    timeouts on distinct workers must stay on the retry budget, not
    trip the fail-fast classifier."""
    hung = [CannedWorker("hung0", delay=5.0), CannedWorker("hung1",
                                                           delay=5.0)]
    good = CannedWorker("good", delay=0.0)
    mgr = WorkerManager(hung + [good])

    class HungHungGood:
        def __init__(self):
            self.calls = 0

        def pick(self, task, states):
            self.calls += 1
            ids = [s.worker.id for s in states]
            for want in {1: "hung0", 2: "hung1"}.get(self.calls, "good"), \
                    "good":
                if want in ids:
                    return want
            return ids[0]

    pol = rz.RetryPolicy(task_timeout=0.2, max_retries=3,
                         backoff_base=0.01, quarantine_after=99,
                         speculative_multiplier=0)
    sup = rz.TaskSupervisor(rz.ResilienceContext(policy=pol), mgr,
                            HungHungGood())
    results = sup.run(_trivial_tasks(1))
    assert results[0][0].to_pydict() == {"x": [0]}
    c = rz.counters_snapshot()
    assert c.get("task_timeouts") == 2, c
    assert not c.get("fail_fast"), c


def test_retry_budget_exhaustion_raises_original_error():
    always_bad = CannedWorker("bad", fail_times=99)
    mgr = WorkerManager([always_bad])
    pol = rz.RetryPolicy(max_retries=2, backoff_base=0.001,
                         quarantine_after=99, speculative_multiplier=0)

    class PickFirst:
        def pick(self, task, states):
            return states[0].worker.id

    sup = rz.TaskSupervisor(rz.ResilienceContext(policy=pol), mgr,
                            PickFirst())
    with pytest.raises(RuntimeError, match="canned failure"):
        sup.run(_trivial_tasks(1))
    assert len(always_bad.submitted) == 3  # 1 initial + 2 retries


# --------------------------------------------------- remote-worker wire
def test_remote_worker_serializes_true_exception_type():
    """Satellite: the worker serializes the real exception (type +
    traceback) back to the scheduler — a ShuffleFetchError crosses the
    wire intact so lineage recovery can key on it."""
    from daft_tpu.distributed.remote_worker import RemoteWorker, WorkerServer
    srv = WorkerServer()
    try:
        rw = RemoteWorker("r0", srv.address)
        from daft_tpu.distributed.worker import FetchSpec
        schema = daft_tpu.from_pydict({"x": [1]}).schema()
        task = StageTask(
            0, pp.StageInput(0, schema),
            {0: FetchSpec([("http://127.0.0.1:9", "deadbeef")], 0)})
        with pytest.raises(rz.ShuffleFetchError) as ei:
            rw.submit(task).result()
        assert ei.value.shuffle_id == "deadbeef"
        assert getattr(ei.value, "remote_traceback", "")
    finally:
        srv.shutdown()


# ------------------------------------------------------- shuffle sweep
def test_startup_sweep_removes_only_stale_shuffle_dirs(tmp_path):
    from daft_tpu.distributed.shuffle_service import sweep_orphaned_shuffles
    stale = tmp_path / "shuffle_dead"
    stale.mkdir()
    (stale / "part-0.arrow").write_bytes(b"x")
    old = time.time() - 7200
    os.utime(stale, (old, old))
    live = tmp_path / "shuffle_live"
    live.mkdir()
    unrelated = tmp_path / "not_a_shuffle"
    unrelated.mkdir()
    os.utime(unrelated, (old, old))
    removed = sweep_orphaned_shuffles(root=str(tmp_path), ttl_s=3600)
    assert removed == [str(stale)]
    assert not stale.exists()
    assert live.exists() and unrelated.exists()


def test_sweep_scans_sibling_spill_roots_of_crashed_processes(
        tmp_path, monkeypatch):
    """Without DAFT_TPU_SPILL_DIR each process spills into its own
    mkdtemp root; a crashed process's orphans live in a SIBLING root —
    the default sweep must find those too."""
    import tempfile

    from daft_tpu.distributed import shuffle_service as ss
    from daft_tpu.execution import memory
    mine = tmp_path / "daft_tpu_spill_mine"
    mine.mkdir()
    monkeypatch.setattr(memory, "_spill_dir", str(mine))
    monkeypatch.setattr(tempfile, "gettempdir", lambda: str(tmp_path))
    dead = tmp_path / "daft_tpu_spill_crashed" / "shuffle_zzz"
    dead.mkdir(parents=True)
    old = time.time() - 7200
    os.utime(dead, (old, old))
    removed = ss.sweep_orphaned_shuffles(ttl_s=3600)
    assert str(dead) in removed
    assert not dead.exists()
