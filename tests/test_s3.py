"""Native S3 source against an in-process mock S3 server (the reference
tests its native client against a moto server the same way —
``tests/io/mock_aws_server.py`` there; here the mock is a stdlib HTTP
server speaking just enough of the S3 REST API: GET/HEAD/PUT, Range, and
ListObjectsV2 with pagination)."""

import http.server
import threading
import urllib.parse

import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import daft_tpu
from daft_tpu.io import object_io
from daft_tpu.io.s3 import S3ReadableFile, S3Source, _glob_regex
from daft_tpu.io.object_io import S3Config


class _MockS3Handler(http.server.BaseHTTPRequestHandler):
    store = {}
    fail_next = []  # status codes to fail with, consumed per request

    def log_message(self, *a):
        pass

    def _fail_if_scripted(self):
        if self.fail_next:
            code = self.fail_next.pop(0)
            self.send_response(code)
            self.end_headers()
            return True
        return False

    def _parse(self):
        u = urllib.parse.urlparse(self.path)
        parts = u.path.lstrip("/").split("/", 1)
        bucket = parts[0]
        key = urllib.parse.unquote(parts[1]) if len(parts) > 1 else ""
        return bucket, key, urllib.parse.parse_qs(u.query)

    def do_PUT(self):
        if self._fail_if_scripted():
            return
        bucket, key, _ = self._parse()
        n = int(self.headers.get("Content-Length", 0))
        self.store[(bucket, key)] = self.rfile.read(n)
        self.send_response(200)
        self.end_headers()

    def do_HEAD(self):
        bucket, key, _ = self._parse()
        data = self.store.get((bucket, key))
        if data is None:
            self.send_response(404)
            self.end_headers()
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()

    def do_GET(self):
        if self._fail_if_scripted():
            return
        bucket, key, q = self._parse()
        if "list-type" in q:
            prefix = q.get("prefix", [""])[0]
            token = q.get("continuation-token", [None])[0]
            keys = sorted(k for (b, k) in self.store
                          if b == bucket and k.startswith(prefix))
            page = 2  # force pagination
            start = keys.index(token) if token else 0
            chunk = keys[start:start + page]
            truncated = start + page < len(keys)
            items = "".join(
                f"<Contents><Key>{k}</Key>"
                f"<Size>{len(self.store[(bucket, k)])}</Size></Contents>"
                for k in chunk)
            nxt = (f"<NextContinuationToken>{keys[start + page]}"
                   f"</NextContinuationToken>") if truncated else ""
            body = (f"<?xml version='1.0'?><ListBucketResult>"
                    f"<IsTruncated>{'true' if truncated else 'false'}"
                    f"</IsTruncated>{items}{nxt}</ListBucketResult>"
                    ).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        data = self.store.get((bucket, key))
        if data is None:
            self.send_response(404)
            self.end_headers()
            return
        rng = self.headers.get("Range")
        if rng:
            spec = rng.split("=")[1]
            start_s, end_s = spec.split("-")
            start = int(start_s)
            end = min(int(end_s), len(data) - 1)
            chunk = data[start:end + 1]
            self.send_response(206)
            self.send_header("Content-Length", str(len(chunk)))
            self.end_headers()
            self.wfile.write(chunk)
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)


@pytest.fixture(scope="module")
def mock_s3():
    _MockS3Handler.store = {}
    server = http.server.ThreadingHTTPServer(("127.0.0.1", 0),
                                             _MockS3Handler)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{server.server_port}"
    server.shutdown()


@pytest.fixture
def s3(mock_s3, monkeypatch):
    monkeypatch.setenv("AWS_ACCESS_KEY_ID", "test-key")
    monkeypatch.setenv("AWS_SECRET_ACCESS_KEY", "test-secret")
    monkeypatch.setenv("AWS_ENDPOINT_URL", mock_s3)
    monkeypatch.setenv("AWS_REGION", "us-east-1")
    # reset the cached default client so it picks up the env
    monkeypatch.setattr(object_io, "_default_client", None)
    return S3Source(S3Config(endpoint_url=mock_s3, key_id="test-key",
                             access_key="test-secret",
                             region_name="us-east-1"))


def test_put_get_roundtrip(s3):
    s3.put("s3://bkt/a/hello.bin", b"hello world")
    assert s3.get("s3://bkt/a/hello.bin") == b"hello world"
    assert s3.get_size("s3://bkt/a/hello.bin") == 11


def test_range_get(s3):
    s3.put("s3://bkt/range.bin", bytes(range(100)))
    assert s3.get("s3://bkt/range.bin", (10, 20)) == bytes(range(10, 20))


def test_missing_object_raises(s3):
    with pytest.raises(FileNotFoundError):
        s3.get("s3://bkt/nope.bin")


def test_glob_with_pagination(s3):
    for i in range(5):
        s3.put(f"s3://bkt/glob/part-{i}.parquet", b"x" * i)
    s3.put("s3://bkt/glob/skip.csv", b"y")
    s3.put("s3://bkt/glob/sub/deep-0.parquet", b"z")
    hits = s3.glob("s3://bkt/glob/*.parquet")
    assert hits == [f"s3://bkt/glob/part-{i}.parquet" for i in range(5)]
    deep = s3.glob("s3://bkt/glob/**")
    assert "s3://bkt/glob/sub/deep-0.parquet" in deep


def test_retry_on_5xx(s3):
    s3.put("s3://bkt/flaky.bin", b"ok")
    _MockS3Handler.fail_next = [500, 503]
    assert s3.get("s3://bkt/flaky.bin") == b"ok"


def test_ranged_file_reads_parquet(s3):
    t = pa.table({"x": list(range(1000)), "y": [i * 0.5 for i in range(1000)]})
    import io as _io
    buf = _io.BytesIO()
    pq.write_table(t, buf)
    s3.put("s3://bkt/data/t.parquet", buf.getvalue())
    f = S3ReadableFile(s3, "s3://bkt/data/t.parquet")
    got = pq.read_table(pa.PythonFile(f, mode="r"))
    assert got.equals(t)


def test_read_parquet_s3_end_to_end(s3):
    t = pa.table({"k": [1, 2, 3, 1, 2], "v": [1.0, 2.0, 3.0, 4.0, 5.0]})
    import io as _io
    for i in range(2):
        buf = _io.BytesIO()
        pq.write_table(t, buf)
        s3.put(f"s3://bkt/tbl/part-{i}.parquet", buf.getvalue())
    df = daft_tpu.read_parquet("s3://bkt/tbl/*.parquet")
    out = df.groupby("k").agg(daft_tpu.col("v").sum().alias("s")) \
        .sort("k").to_pydict()
    assert out["k"] == [1, 2, 3]
    assert out["s"] == [10.0, 14.0, 6.0]


def test_read_csv_s3_end_to_end(s3):
    s3.put("s3://bkt/csv/a.csv", b"a,b\n1,x\n2,y\n")
    df = daft_tpu.read_csv("s3://bkt/csv/a.csv")
    assert df.to_pydict() == {"a": [1, 2], "b": ["x", "y"]}


def test_glob_regex_segments():
    import re
    assert re.match(_glob_regex("a/*.parquet"), "a/x.parquet")
    assert not re.match(_glob_regex("a/*.parquet"), "a/b/x.parquet")
    assert re.match(_glob_regex("a/**"), "a/b/c.parquet")
    assert re.match(_glob_regex("a/part-?.csv"), "a/part-1.csv")
    assert not re.match(_glob_regex("a/part-?.csv"), "a/part-10.csv")
