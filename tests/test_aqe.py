"""Adaptive re-planning (VERDICT r2 item 5 done-criterion): the initial
plan picks a hash join; AQE materializes the join input, folds ACTUAL
stats into the logical plan, re-runs the optimizer — the re-plan flips the
join to broadcast and reorders the downstream join — and explain_analyze
records it.

Reference: AdaptivePlanner next_stage/update_stats
(``src/daft-physical-plan/src/physical_planner/planner.rs:451-640``)."""

import os

import pytest

import daft_tpu
from daft_tpu import col
from daft_tpu.physical import adaptive, plan as pp


@pytest.fixture()
def tpch_tables(tmp_path, monkeypatch):
    monkeypatch.setenv("DAFT_TPU_DEVICE", "0")
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq
    rng = np.random.default_rng(3)
    n_fact = 60_000
    pq.write_table(pa.table({
        "f_key": rng.integers(0, 2000, n_fact),
        "f_dim": rng.integers(0, 50, n_fact),
        "f_val": rng.uniform(0, 100, n_fact).round(2),
    }), str(tmp_path / "fact.parquet"))
    # dim is big on disk (incompressible pad) but the query filters it to
    # a handful of rows: the ESTIMATE (selectivity heuristic) stays big,
    # the ACTUAL is tiny
    import secrets
    pq.write_table(pa.table({
        "d_key": np.arange(2000),
        "d_cat": rng.integers(0, 400, 2000),
        "d_pad": [secrets.token_hex(200) for _ in range(2000)],
    }), str(tmp_path / "dim.parquet"))
    pq.write_table(pa.table({
        "g_dim": np.arange(50),
        "g_name": [f"g{i}" for i in range(50)],
    }), str(tmp_path / "grp.parquet"))
    return {
        "fact": daft_tpu.read_parquet(str(tmp_path / "fact.parquet")),
        "dim": daft_tpu.read_parquet(str(tmp_path / "dim.parquet")),
        "grp": daft_tpu.read_parquet(str(tmp_path / "grp.parquet")),
    }


def _query(t):
    dim = t["dim"].where(col("d_cat") == 7)  # ~5 of 2000 rows survive
    return (t["fact"]
            .join(dim, left_on="f_key", right_on="d_key")
            .join(t["grp"], left_on="f_dim", right_on="g_dim")
            .groupby("g_name").agg(col("f_val").sum().alias("s"))
            .sort("g_name"))


def _join_strategies(plan) -> list:
    out = []

    def walk(n):
        if isinstance(n, pp.HashJoin):
            out.append(n.strategy)
        for c in n.children:
            walk(c)
    walk(plan)
    return out


def _set_aqe(on: bool, threshold: int):
    daft_tpu.set_execution_config(enable_aqe=on,
                                  broadcast_join_size_bytes_threshold=threshold)


def test_aqe_replans_to_broadcast_and_reorders(tpch_tables):
    from daft_tpu.physical.translate import translate
    # threshold between the tiny ACTUAL filtered-dim size (~5 of 2000
    # rows ≈ 2 KB) and the optimizer's ESTIMATE for it (0.05
    # eq-selectivity × ~800 KB incompressible ≈ 40 KB)
    threshold = 12_000
    _set_aqe(False, threshold)
    try:
        q = _query(tpch_tables)
        initial = translate(q._builder.optimize().plan)
        assert "broadcast_right" not in _join_strategies(initial), \
            "premise: the static plan must NOT already broadcast the dim"
        want = q.to_pydict()

        _set_aqe(True, threshold)
        q2 = _query(tpch_tables)
        got = q2.to_pydict()
        assert got["g_name"] == want["g_name"]
        for a, b in zip(got["s"], want["s"]):
            assert a == pytest.approx(b, rel=1e-9)

        planner = adaptive.last_planner()
        report = planner.explain_analyze()
        assert "materialized join input" in report
        assert "re-optimized" in report
        final = planner.final_plan
        strategies = _join_strategies(final)
        assert any(s in ("broadcast_right", "broadcast_left")
                   for s in strategies), (strategies, report)
    finally:
        _set_aqe(False, 10 * 1024 * 1024)


def test_aqe_materializes_cheapest_input_first_until_resolved(tpch_tables):
    """The adaptive loop picks the cheapest-estimated unresolved join
    input each round (never the fact table first) and terminates with
    every join input measured."""
    from daft_tpu.logical import plan as lp
    from daft_tpu.logical.optimizer import Optimizer
    from daft_tpu.physical.translate import translate
    from daft_tpu.execution.executor import LocalExecutor
    from daft_tpu.runners.native_runner import (_pick_join_input,
                                                _replace_subtree)
    q = _query(tpch_tables)
    plan = Optimizer().optimize(q._builder._plan)

    target = _pick_join_input(plan)
    assert target is not None
    # the huge fact side must not be the first materialization target
    assert "f_val" not in target.schema().column_names

    for _ in range(8):
        target = _pick_join_input(plan)
        if target is None:
            break
        parts = list(LocalExecutor().run(translate(target)))
        src = lp.Source(partitions=parts, schema=target.schema(),
                        num_partitions=max(len(parts), 1))
        plan = Optimizer().optimize(_replace_subtree(plan, target, src))
    assert _pick_join_input(plan) is None  # loop terminates fully measured
