"""Pod-native hierarchical shuffle: the topology-aware exchange planner.

Bit-parity of the three exchange paths (collective / hierarchical /
flight) on identical data over grouped-agg and hash-join boundaries,
the ``DAFT_TPU_CHAOS_SERIALIZE=1`` degradation to the verbatim Flight
path, and the ALL-OR-NOTHING lineage recovery of a collective exchange
group when one participant's served stream dies
(``distributed/topology.py`` + the StageRunner placement layer).
"""

import os

import numpy as np
import pytest

import daft_tpu
import daft_tpu.context as dctx
from daft_tpu import col
from daft_tpu.context import execution_config_ctx
from daft_tpu.distributed import resilience as rz
from daft_tpu.distributed import shuffle_service as ss
from daft_tpu.distributed import topology as tp
from daft_tpu.runners.distributed_runner import DistributedRunner

PATH_ENVS = ("flight", "collective", "hierarchical")
TOPOLOGY_2MESH = "podA=worker-0,worker-1;podB=worker-2"


def _run_distributed(q, monkeypatch, path=None, topology=None,
                     num_workers=3, **env):
    for k, v in env.items():
        monkeypatch.setenv(k, v)
    if path is not None:
        monkeypatch.setenv("DAFT_TPU_EXCHANGE_PATH", path)
    if topology is not None:
        monkeypatch.setenv("DAFT_TPU_WORKER_TOPOLOGY", topology)
    runner = DistributedRunner(num_workers=num_workers)
    old = dctx.get_context()._runner
    dctx.get_context().set_runner(runner)
    before = ss.shuffle_counters_snapshot()
    try:
        out = q()
    finally:
        dctx.get_context().set_runner(old)
        if runner._manager is not None:
            runner._manager.shutdown()
    return out, ss.shuffle_counters_delta(before)


def _canon(d, float_cols=()):
    cols = sorted(d)
    rows = []
    for row in zip(*(d[c] for c in cols)):
        rows.append(tuple(round(v, 6) if c in float_cols else v
                          for c, v in zip(cols, row)))
    return sorted(rows)


# ------------------------------------------------------------- topology

def test_topology_spec_parsing():
    topo = tp.WorkerTopology.from_spec(
        "podA=w0,w1;podB=w2", ["w0", "w1", "w2", "w3"])
    assert topo.n_groups == 3  # podA, podB, singleton w3
    assert topo.group_of("w0").name == "podA"
    assert topo.group_of("w3").workers == ("w3",)
    assert topo.multi_worker_groups() == 1


def test_topology_spec_rejects_duplicates():
    with pytest.raises(ValueError):
        tp.WorkerTopology.from_spec("a=w0;b=w0", ["w0"])


def test_topology_autodetect_single_mesh(monkeypatch):
    monkeypatch.setenv("DAFT_TPU_DEVICE", "1")
    topo = tp.WorkerTopology.detect(["w0", "w1"])
    assert topo.single_mesh()  # in-process workers share the CPU mesh


def test_chaos_serialize_forces_flight_path(monkeypatch):
    monkeypatch.setenv("DAFT_TPU_CHAOS_SERIALIZE", "1")
    monkeypatch.setenv("DAFT_TPU_EXCHANGE_PATH", "collective")
    topo = tp.WorkerTopology.detect(["w0", "w1"])
    # chaos wins over the force: replay must ride the verbatim path
    assert tp.plan_exchange_path(topo, 4) == "flight"


def test_invalid_exchange_path_raises(monkeypatch):
    monkeypatch.setenv("DAFT_TPU_EXCHANGE_PATH", "collectve")  # typo
    topo = tp.WorkerTopology.detect(["w0"])
    with pytest.raises(ValueError, match="unknown exchange path"):
        tp.plan_exchange_path(topo, 4)


def test_active_fault_plan_degrades_auto_to_flight(monkeypatch):
    """Recorded fault keys live on the flight path's task/fetch sites:
    an active fault plan pins the AUTO ladder to flight; an explicit
    force still wins (the fetch-parallelism contract)."""
    monkeypatch.setenv("DAFT_TPU_DEVICE", "1")
    monkeypatch.setenv("DAFT_TPU_FAULT_SPEC", "fetch:0.1")
    rz.reset_for_tests()
    try:
        topo = tp.WorkerTopology.detect(["w0", "w1"])
        assert tp.plan_exchange_path(topo, 4) == "flight"
        monkeypatch.setenv("DAFT_TPU_EXCHANGE_PATH", "hierarchical")
        assert tp.plan_exchange_path(topo, 4) == "hierarchical"
    finally:
        rz.reset_for_tests()


def test_config_field_mirrors_apply(monkeypatch):
    """The registry's config_field contract: with the env vars unset,
    the per-query ExecutionConfig fields drive topology and path."""
    monkeypatch.delenv("DAFT_TPU_EXCHANGE_PATH", raising=False)
    monkeypatch.delenv("DAFT_TPU_WORKER_TOPOLOGY", raising=False)
    monkeypatch.setenv("DAFT_TPU_DEVICE", "1")
    with execution_config_ctx(tpu_exchange_path="flight",
                              tpu_worker_topology="pod=w0,w1"):
        topo = tp.WorkerTopology.detect(["w0", "w1", "w2"])
        assert topo.group_of("w0").name == "pod"
        assert topo.group_of("w2").workers == ("w2",)
        assert tp.plan_exchange_path(topo, 4) == "flight"


def test_collective_lease_gauge_balances():
    k = tp.acquire_collective("t.lease")
    assert tp.collective_inflight() >= 1
    tp.release_collective(k)
    assert tp.collective_inflight() == 0


# ------------------------------------------------- grouped-agg parity

def _groupby_query(data):
    def q():
        df = daft_tpu.from_pydict(data).into_partitions(4)
        return df.groupby("k").agg(col("v").sum().alias("s")).to_pydict()
    return q


def test_exchange_paths_bit_parity_grouped_agg(monkeypatch):
    """The same grouped aggregation through all three exchange paths —
    and the driver-materializing oracle — must agree bit-exactly."""
    monkeypatch.setenv("DAFT_TPU_DEVICE", "0")  # keep a host hash boundary
    rng = np.random.default_rng(7)
    data = {"k": rng.integers(0, 11, 6000).tolist(),
            "v": rng.integers(0, 1000, 6000).tolist()}
    q = _groupby_query(data)
    oracle = _canon(q())
    got = {}
    for path in PATH_ENVS:
        topo = TOPOLOGY_2MESH if path == "hierarchical" else None
        out, delta = _run_distributed(q, monkeypatch, path=path,
                                      topology=topo)
        got[path] = _canon(out)
        assert delta.get(f"exchange_path_{path}", 0) >= 1, \
            (path, delta)
        if path == "hierarchical":
            # ONE stream per mesh (2 meshes host map tasks), not one
            # per worker
            assert 1 <= delta.get("hierarchical_streams", 0) <= 2
    for path, rows in got.items():
        assert rows == oracle, f"{path} diverged from the oracle"


def test_collective_path_rides_ici_on_device_mesh(monkeypatch):
    """With the device mesh up and admission forced, a collective
    repartition boundary moves its bytes over the mesh all_to_all —
    counted as ici_bytes, zero Flight fetches — and stays bit-exact."""
    from daft_tpu.parallel import mesh as pmesh
    if pmesh.mesh_size() < 2:
        pytest.skip("no multi-device mesh")
    monkeypatch.setenv("DAFT_TPU_DEVICE", "1")
    monkeypatch.setenv("DAFT_TPU_MESH_MIN_ROWS", "0")
    n = pmesh.mesh_size()
    rng = np.random.default_rng(5)
    data = {"k": rng.integers(0, 1000, 4096).tolist(),
            "v": rng.integers(0, 10 ** 6, 4096).tolist()}

    def q():
        df = daft_tpu.from_pydict(data).into_partitions(4)
        return df.repartition(n, col("k")).to_pydict()

    oracle = _canon(q())
    out, delta = _run_distributed(q, monkeypatch, path="collective")
    assert _canon(out) == oracle
    assert delta.get("ici_exchanges", 0) >= 1, delta
    assert delta.get("ici_bytes", 0) > 0
    assert delta.get("fetches", 0) == 0  # nothing crossed the wire


# --------------------------------------------------- hash-join parity

def test_exchange_paths_bit_parity_hash_join(monkeypatch):
    """A hash join's co-partitioning boundaries (two hash inputs into one
    consumer stage) through every path: the mesh pid chain and
    ``partition_by_hash`` share the engine xxh64 chain, so mixed-path
    sides still co-partition and results stay identical."""
    monkeypatch.setenv("DAFT_TPU_DEVICE", "0")
    rng = np.random.default_rng(13)
    n = 4000
    left = {"k": rng.integers(0, 40, n).tolist(),
            "lv": rng.integers(0, 100, n).tolist()}
    right = {"k": list(range(40)),
             "rv": rng.integers(0, 9, 40).tolist()}

    def q():
        with execution_config_ctx(broadcast_join_size_bytes_threshold=1):
            lf = daft_tpu.from_pydict(left).into_partitions(3)
            rf = daft_tpu.from_pydict(right).into_partitions(2)
            return lf.join(rf, on="k").to_pydict()

    oracle = _canon(q())
    for path in PATH_ENVS:
        topo = TOPOLOGY_2MESH if path == "hierarchical" else None
        out, delta = _run_distributed(q, monkeypatch, path=path,
                                      topology=topo)
        assert _canon(out) == oracle, f"{path} diverged on the join"


# ------------------------------------------- chaos-serialize degradation

def test_chaos_replay_bit_identical_with_topology(monkeypatch):
    """Under DAFT_TPU_CHAOS_SERIALIZE=1 every boundary degrades to the
    verbatim Flight path, so the injected-fault event log and the answer
    replay bit-identically — with or without a forced topology/path."""
    monkeypatch.setenv("DAFT_TPU_DEVICE", "0")
    monkeypatch.setenv("DAFT_TPU_CHAOS_SERIALIZE", "1")
    monkeypatch.setenv("DAFT_TPU_FAULT_SPEC", "fetch:0.3")
    monkeypatch.setenv("DAFT_TPU_FAULT_SEED", "7")
    monkeypatch.setenv("DAFT_TPU_RETRY_BACKOFF", "0.01")
    rng = np.random.default_rng(23)
    data = {"k": rng.integers(0, 7, 3000).tolist(),
            "v": rng.integers(0, 100, 3000).tolist()}
    q = _groupby_query(data)

    def chaos_run(path, topology):
        rz.reset_for_tests()
        out, delta = _run_distributed(q, monkeypatch, path=path,
                                      topology=topology)
        events = rz.fault_events()
        rz.reset_for_tests()
        return _canon(out), events, delta

    base_rows, base_events, base_delta = chaos_run(None, None)
    coll_rows, coll_events, coll_delta = chaos_run(
        "collective", TOPOLOGY_2MESH)
    assert coll_rows == base_rows
    assert coll_events == base_events, \
        "chaos replay diverged when a topology was configured"
    # the degradation really took the flight rungs, not collective ones
    assert coll_delta.get("exchange_path_collective", 0) == 0
    assert coll_delta.get("ici_exchanges", 0) == 0


# ---------------------------------------- all-or-nothing group recovery

def test_collective_group_recovery_is_all_or_nothing(monkeypatch):
    """Kill one collective participant's served stream (crash fault
    destroys the per-mesh data): lineage recovery must re-execute the
    WHOLE exchange group — every member map task plus the intra-mesh
    collective — and the query must still answer exactly."""
    monkeypatch.setenv("DAFT_TPU_DEVICE", "0")
    monkeypatch.setenv("DAFT_TPU_RETRY_BACKOFF", "0.01")
    rng = np.random.default_rng(31)
    data = {"k": rng.integers(0, 9, 4000).tolist(),
            "v": rng.integers(0, 1000, 4000).tolist()}
    q = _groupby_query(data)
    oracle = _canon(q())

    rz.reset_for_tests()
    monkeypatch.setenv("DAFT_TPU_FAULT_SPEC", "crash:1:1")
    monkeypatch.setenv("DAFT_TPU_FAULT_SEED", "3")
    try:
        out, delta = _run_distributed(q, monkeypatch, path="hierarchical",
                                      topology=TOPOLOGY_2MESH)
        counters = rz.counters_snapshot()
    finally:
        rz.reset_for_tests()
    assert _canon(out) == oracle
    assert counters.get("injected_crash", 0) >= 1, counters
    assert counters.get("collective_group_recoveries", 0) >= 1, \
        "the lost per-mesh stream was not recovered as a whole group"
    # in-flight gauge drained: recovery re-acquired and released leases
    assert tp.collective_inflight() == 0


# ------------------------------------------------- counters surfacing

def test_exchange_counters_surface_in_stats_and_metrics(monkeypatch):
    """Satellite: exchange_cache_counters() + the collective counters
    show up in RuntimeStatsContext / explain(analyze=True) renders and
    the Prometheus /metrics text."""
    from daft_tpu import observability as obs
    from daft_tpu import tracing
    from daft_tpu.parallel import exchange, mesh as pmesh
    if pmesh.mesh_size() < 2:
        pytest.skip("no multi-device mesh")
    monkeypatch.setenv("DAFT_TPU_DEVICE", "1")
    monkeypatch.setenv("DAFT_TPU_MESH_MIN_ROWS", "0")
    ctx = obs.RuntimeStatsContext()
    df = daft_tpu.from_pydict(
        {"k": list(range(2048)), "v": list(range(2048))})
    df.groupby("k").agg(col("v").sum().alias("s")).to_pydict()
    ctx.finish()
    # the mesh exchange traced or re-entered at least one program
    cache = exchange.exchange_cache_counters()
    assert cache["entries"] >= 1
    rendered = ctx.render()
    assert "exchange programs (collective cache):" in rendered \
        or ctx.exchange == {}  # another test may have warmed every program
    text = tracing.prometheus_text()
    assert "daft_tpu_exchange_programs" in text
    assert "daft_tpu_exchange_collective_inflight" in text
    # strict-parse clean (the obs-smoke scrape gate)
    tracing.parse_prometheus_text(text)
