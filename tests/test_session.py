"""Session / Catalog / Identifier / Table tests.

Models the reference's tests/catalog/test_catalogs.py + session semantics
(temp tables shadow catalogs, qualified SQL lookup, attach_function).
"""

import pytest

import daft_tpu as daft
from daft_tpu.catalog import Catalog, Identifier, InMemoryCatalog, NotFoundError, Table
from daft_tpu.session import Session


def test_identifier_basics():
    i = Identifier("a", "b", "c")
    assert len(i) == 3
    assert str(i) == "a.b.c"
    assert i[0] == "a" and i[-1] == "c"
    assert Identifier.from_str("a.b.c") == i
    assert i.drop(1) == Identifier("b", "c")
    assert i + Identifier("d") == Identifier.from_str("a.b.c.d")
    assert Identifier.from_sql('"Quoted".x') == Identifier("Quoted", "x")
    with pytest.raises(ValueError):
        i.drop(3)


def test_catalog_from_pydict_and_verbs():
    cat = Catalog.from_pydict({
        "t1": {"x": [1, 2, 3]},
        "ns.t2": {"y": ["a", "b"]},
    }, name="mycat")
    assert cat.name == "mycat"
    assert cat.has_table("t1")
    assert cat.has_table("ns.t2")
    assert cat.has_namespace("ns")
    assert [str(t) for t in cat.list_tables()] == ["ns.t2", "t1"]
    df = cat.read_table("t1")
    assert df.to_pydict() == {"x": [1, 2, 3]}
    cat.drop_table("t1")
    assert not cat.has_table("t1")
    with pytest.raises(NotFoundError):
        cat.get_table("t1")


def test_catalog_create_table_from_schema_and_df():
    cat = InMemoryCatalog("c")
    df = daft.from_pydict({"a": [1, 2]})
    t = cat.create_table("ns.tbl", df)
    assert t.read().to_pydict() == {"a": [1, 2]}
    t2 = cat.create_table("empty", df.schema())
    assert t2.read().count_rows() == 0
    # write modes on MemTable
    t.append(daft.from_pydict({"a": [3]}))
    assert t.read().to_pydict() == {"a": [1, 2, 3]}
    t.overwrite(daft.from_pydict({"a": [9]}))
    assert t.read().to_pydict() == {"a": [9]}
    assert cat.create_table_if_not_exists("ns.tbl", df) is t


def test_session_attach_and_temp_tables():
    sess = Session()
    cat = Catalog.from_pydict({"t": {"x": [1]}}, name="c1")
    sess.attach(cat)
    assert sess.list_catalogs() == ["c1"]
    assert sess.current_catalog() is cat
    sess.create_temp_table("tmp", {"y": [5, 6]})
    assert sess.has_table("tmp")
    assert sess.get_table("tmp").read().to_pydict() == {"y": [5, 6]}
    # temp shadows catalog
    sess.create_temp_table("t", {"x": [99]})
    assert sess.get_table("t").read().to_pydict() == {"x": [99]}
    sess.drop_table("t")
    assert sess.get_table("t").read().to_pydict() == {"x": [1]}
    # fully-qualified
    assert sess.get_table("c1.t").read().to_pydict() == {"x": [1]}
    sess.detach_catalog("c1")
    assert sess.list_catalogs() == []
    with pytest.raises(NotFoundError):
        sess.get_catalog("c1")


def test_session_namespaces_and_use():
    sess = Session()
    sess.attach_catalog(Catalog.from_pydict(
        {"sales.orders": {"o": [1, 2, 3]}}, name="main"))
    sess.use("main.sales")
    assert str(sess.current_namespace()) == "sales"
    assert sess.get_table("orders").read().count_rows() == 3


def test_session_sql_resolution():
    sess = Session()
    sess.attach_catalog(Catalog.from_pydict({
        "nums": {"v": [1, 2, 3, 4]},
        "ns.qual": {"q": [10, 20]},
    }, name="cat"))
    sess.create_temp_table("tmp", {"v": [100]})
    out = sess.sql("SELECT SUM(v) AS s FROM nums").to_pydict()
    assert out == {"s": [10]}
    out = sess.sql("SELECT v FROM tmp").to_pydict()
    assert out == {"v": [100]}
    out = sess.sql("SELECT q FROM cat.ns.qual ORDER BY q").to_pydict()
    assert out == {"q": [10, 20]}


def test_session_sql_attached_udf():
    sess = Session()
    sess.create_temp_table("t", {"x": [1, 2, 3]})

    @daft.udf(return_dtype=daft.DataType.int64())
    def double(c):
        return [v * 2 for v in c.to_pylist()]

    sess.attach_function(double, "double")
    out = sess.sql("SELECT double(x) AS d FROM t ORDER BY d").to_pydict()
    assert out == {"d": [2, 4, 6]}
    sess.detach_function("double")
    with pytest.raises(ValueError):
        sess.sql("SELECT double(x) AS d FROM t")


def test_sql_empty_cte_and_case_insensitive_session_lookup():
    # empty CTE must not be treated as a missing table (truthiness bug)
    t = daft.from_pydict({"x": [1, 2]})
    out = daft.sql(
        "WITH e AS (SELECT x FROM t WHERE x > 10) SELECT x FROM e", t=t
    ).to_pydict()
    assert out == {"x": []}
    sess = Session()
    sess.create_temp_table("mytab", {"w": [1]})
    assert sess.sql("SELECT w FROM MYTAB").to_pydict() == {"w": [1]}


def test_attached_udf_cannot_shadow_builtin():
    sess = Session()
    sess.create_temp_table("t", {"x": [1, 2, 3]})

    @daft.udf(return_dtype=daft.DataType.int64())
    def bad_sum(c):
        return [0 for _ in c.to_pylist()]

    sess.attach_function(bad_sum, "sum")
    out = sess.sql("SELECT SUM(x) AS s FROM t").to_pydict()
    assert out == {"s": [6]}  # built-in SUM wins


def test_module_level_detach_function():
    @daft.udf(return_dtype=daft.DataType.int64())
    def inc(c):
        return [v + 1 for v in c.to_pylist()]

    daft.attach_function(inc, "inc_fn")
    daft.create_temp_table("dt_t", {"x": [1]})
    assert daft.sql("SELECT inc_fn(x) AS y FROM dt_t").to_pydict() == {"y": [2]}
    daft.detach_function("inc_fn")
    daft.drop_table("dt_t")


def test_table_from_pydict_and_module_verbs():
    t = Table.from_pydict("tt", {"z": [7]})
    assert t.name == "tt"
    assert t.read().to_pydict() == {"z": [7]}
    # module-level ambient session verbs
    daft.create_temp_table("ambient_t", {"w": [1, 2]})
    assert daft.has_table("ambient_t")
    assert daft.read_table("ambient_t").count_rows() == 2
    out = daft.sql("SELECT w FROM ambient_t ORDER BY w DESC").to_pydict()
    assert out == {"w": [2, 1]}
    daft.drop_table("ambient_t")
    assert not daft.has_table("ambient_t")
