"""Spark Connect server: relation translation, Arrow result streaming,
analyze/config RPCs (reference: ``src/daft-connect`` + ``tests/connect``,
which run a Spark Connect client against the embedded server; here a raw
grpc client speaks the same wire protocol)."""

import io

import grpc
import pyarrow as pa
import pytest

import daft_tpu.connect.spark_connect_subset_pb2 as pb
from daft_tpu.connect import start_server

SERVICE = "/spark.connect.SparkConnectService/"


@pytest.fixture(scope="module")
def server():
    s = start_server()
    yield s
    s.stop()


@pytest.fixture(scope="module")
def channel(server):
    ch = grpc.insecure_channel(f"127.0.0.1:{server.port}")
    yield ch
    ch.close()


def _execute(channel, relation, session="sess-1") -> pa.Table:
    stub = channel.unary_stream(
        SERVICE + "ExecutePlan",
        request_serializer=pb.ExecutePlanRequest.SerializeToString,
        response_deserializer=pb.ExecutePlanResponse.FromString)
    req = pb.ExecutePlanRequest(session_id=session,
                                plan=pb.Plan(root=relation))
    tables = []
    complete = False
    for resp in stub(req):
        if resp.WhichOneof("response_type") == "arrow_batch":
            with pa.ipc.open_stream(
                    pa.BufferReader(resp.arrow_batch.data)) as r:
                tables.append(r.read_all())
        elif resp.WhichOneof("response_type") == "result_complete":
            complete = True
    assert complete
    return pa.concat_tables(tables)


def _analyze(channel, session="sess-1", **kwargs) -> pb.AnalyzePlanResponse:
    stub = channel.unary_unary(
        SERVICE + "AnalyzePlan",
        request_serializer=pb.AnalyzePlanRequest.SerializeToString,
        response_deserializer=pb.AnalyzePlanResponse.FromString)
    return stub(pb.AnalyzePlanRequest(session_id=session, **kwargs))


def _attr(name):
    return pb.Expression(unresolved_attribute=
                         pb.Expression.UnresolvedAttribute(
                             unparsed_identifier=name))


def _lit_i(v):
    return pb.Expression(literal=pb.Expression.Literal(long=v))


def _fn(name, *args):
    return pb.Expression(unresolved_function=pb.Expression.UnresolvedFunction(
        function_name=name, arguments=list(args)))


def test_range_collect(channel):
    t = _execute(channel, pb.Relation(range=pb.Range(start=2, end=10,
                                                     step=2)))
    assert t.column("id").to_pylist() == [2, 4, 6, 8]


def test_filter_project_sort(channel):
    rng = pb.Relation(range=pb.Range(end=10, step=1))
    flt = pb.Relation(filter=pb.Filter(
        input=rng, condition=_fn(">", _attr("id"), _lit_i(5))))
    proj = pb.Relation(project=pb.Project(
        input=flt,
        expressions=[pb.Expression(alias=pb.Expression.Alias(
            expr=_fn("*", _attr("id"), _lit_i(10)), name=["x"]))]))
    srt = pb.Relation(sort=pb.Sort(
        input=proj, order=[pb.Expression.SortOrder(
            child=_attr("x"),
            direction=pb.Expression.SortOrder.SORT_DIRECTION_DESCENDING)]))
    t = _execute(channel, srt)
    assert t.column("x").to_pylist() == [90, 80, 70, 60]


def test_aggregate_groupby(channel):
    rng = pb.Relation(range=pb.Range(end=10, step=1))
    grouped = pb.Relation(aggregate=pb.Aggregate(
        input=rng,
        group_type=pb.Aggregate.GROUP_TYPE_GROUPBY,
        grouping_expressions=[pb.Expression(alias=pb.Expression.Alias(
            expr=_fn("%", _attr("id"), _lit_i(2)), name=["parity"]))],
        aggregate_expressions=[pb.Expression(alias=pb.Expression.Alias(
            expr=_fn("sum", _attr("id")), name=["s"]))]))
    srt = pb.Relation(sort=pb.Sort(
        input=grouped, order=[pb.Expression.SortOrder(
            child=_attr("parity"),
            direction=pb.Expression.SortOrder.SORT_DIRECTION_ASCENDING)]))
    t = _execute(channel, srt)
    assert t.column("parity").to_pylist() == [0, 1]
    assert t.column("s").to_pylist() == [20, 25]  # 0+2+4+6+8 / 1+3+5+7+9


def test_count_star(channel):
    rng = pb.Relation(range=pb.Range(end=7, step=1))
    star = pb.Expression(unresolved_star=pb.Expression.UnresolvedStar())
    agg = pb.Relation(aggregate=pb.Aggregate(
        input=rng, group_type=pb.Aggregate.GROUP_TYPE_GROUPBY,
        aggregate_expressions=[_fn("count", star)]))
    t = _execute(channel, agg)
    assert t.column("count").to_pylist() == [7]


def test_local_relation_and_join(channel):
    def ipc(table):
        sink = io.BytesIO()
        with pa.ipc.new_stream(sink, table.schema) as w:
            w.write_table(table)
        return sink.getvalue()

    left = pb.Relation(local_relation=pb.LocalRelation(
        data=ipc(pa.table({"k": [1, 2, 3], "a": ["x", "y", "z"]}))))
    right = pb.Relation(local_relation=pb.LocalRelation(
        data=ipc(pa.table({"k": [2, 3, 4], "b": [20, 30, 40]}))))
    join = pb.Relation(join=pb.Join(
        left=left, right=right, join_type=pb.Join.JOIN_TYPE_INNER,
        using_columns=["k"]))
    srt = pb.Relation(sort=pb.Sort(
        input=join, order=[pb.Expression.SortOrder(
            child=_attr("k"),
            direction=pb.Expression.SortOrder.SORT_DIRECTION_ASCENDING)]))
    t = _execute(channel, srt)
    assert t.column("k").to_pylist() == [2, 3]
    assert t.column("b").to_pylist() == [20, 30]


def test_to_schema_and_schema_only_local_relation(channel):
    rng = pb.Relation(range=pb.Range(end=3, step=1))
    cast = pb.Relation(to_schema=pb.ToSchema(
        input=rng, schema=pb.DataType(struct=pb.DataType.Struct(fields=[
            pb.DataType.StructField(
                name="id", data_type=pb.DataType(
                    integer=pb.DataType.Integer()))]))))
    t = _execute(channel, cast)
    assert t.column("id").to_pylist() == [0, 1, 2]
    assert t.schema.field("id").type == pa.int32()

    empty = pb.Relation(local_relation=pb.LocalRelation(
        schema="a INT, b STRING"))
    t2 = _execute(channel, empty)
    assert t2.schema.names == ["a", "b"] and t2.num_rows == 0


def test_html_string_escapes_markup(channel):
    rng = pb.Relation(range=pb.Range(end=2, step=1))
    h = pb.Relation(html_string=pb.HtmlString(input=rng, num_rows=10,
                                              truncate=20))
    t = _execute(channel, h)
    html = t.column("html_string").to_pylist()[0]
    assert "<table" in html and "<th>id</th>" in html

    # data must never inject markup
    evil = pb.Relation(project=pb.Project(
        input=rng, expressions=[pb.Expression(alias=pb.Expression.Alias(
            expr=pb.Expression(literal=pb.Expression.Literal(
                string="<td>x&y</table>")), name=["s"]))]))
    h2 = pb.Relation(html_string=pb.HtmlString(input=evil, num_rows=5,
                                               truncate=100))
    html2 = _execute(channel, h2).column("html_string").to_pylist()[0]
    assert "<td>&lt;td&gt;x&amp;y&lt;/table&gt;</td>" in html2


def test_sql_command_roundtrip(channel):
    # spark.sql() flow: the SQL arrives as a command; the server hands back
    # a relation which the client then executes.
    stub = channel.unary_stream(
        SERVICE + "ExecutePlan",
        request_serializer=pb.ExecutePlanRequest.SerializeToString,
        response_deserializer=pb.ExecutePlanResponse.FromString)
    cmd = pb.Plan(command=pb.Command(sql_command=pb.SqlCommand(
        sql="SELECT 1 + 1 AS two")))
    rel = None
    for resp in stub(pb.ExecutePlanRequest(session_id="sess-1", plan=cmd)):
        if resp.WhichOneof("response_type") == "sql_command_result":
            rel = resp.sql_command_result.relation
    assert rel is not None
    t = _execute(channel, rel)
    assert t.column("two").to_pylist() == [2]


def test_view_then_sql(channel):
    # createOrReplaceTempView then SQL over it, scoped to the session
    stub = channel.unary_stream(
        SERVICE + "ExecutePlan",
        request_serializer=pb.ExecutePlanRequest.SerializeToString,
        response_deserializer=pb.ExecutePlanResponse.FromString)
    view_cmd = pb.Plan(command=pb.Command(
        create_dataframe_view=pb.CreateDataFrameViewCommand(
            input=pb.Relation(range=pb.Range(end=5, step=1)),
            name="nums", replace=True)))
    list(stub(pb.ExecutePlanRequest(session_id="sess-1", plan=view_cmd)))
    t = _execute(channel, pb.Relation(sql=pb.SQL(
        query="SELECT SUM(id) AS s FROM nums")))
    assert t.column("s").to_pylist() == [10]


def test_analyze_schema_and_version(channel):
    plan = pb.Plan(root=pb.Relation(range=pb.Range(end=3, step=1)))
    resp = _analyze(channel,
                    schema=pb.AnalyzePlanRequest.Schema(plan=plan))
    fields = resp.schema.schema.struct.fields
    assert len(fields) == 1 and fields[0].name == "id"
    assert fields[0].data_type.WhichOneof("kind") == "long"

    resp = _analyze(channel,
                    spark_version=pb.AnalyzePlanRequest.SparkVersion())
    assert "daft-tpu" in resp.spark_version.version


def test_analyze_ddl_parse(channel):
    resp = _analyze(channel, ddl_parse=pb.AnalyzePlanRequest.DDLParse(
        ddl_string="a INT, b STRING, c ARRAY<DOUBLE>"))
    fields = resp.ddl_parse.parsed.struct.fields
    assert [f.name for f in fields] == ["a", "b", "c"]
    assert fields[2].data_type.array.element_type.WhichOneof(
        "kind") == "double"


def test_config_roundtrip(channel):
    stub = channel.unary_unary(
        SERVICE + "Config",
        request_serializer=pb.ConfigRequest.SerializeToString,
        response_deserializer=pb.ConfigResponse.FromString)
    set_op = pb.ConfigRequest.Operation(set=pb.ConfigRequest.Set(
        pairs=[pb.KeyValue(key="spark.sql.shuffle.partitions",
                           value="16")]))
    stub(pb.ConfigRequest(session_id="cfg-sess", operation=set_op))
    get_op = pb.ConfigRequest.Operation(get=pb.ConfigRequest.Get(
        keys=["spark.sql.shuffle.partitions"]))
    resp = stub(pb.ConfigRequest(session_id="cfg-sess", operation=get_op))
    assert resp.pairs[0].value == "16"


def test_unsupported_relation_is_unimplemented(channel):
    stub = channel.unary_stream(
        SERVICE + "ExecutePlan",
        request_serializer=pb.ExecutePlanRequest.SerializeToString,
        response_deserializer=pb.ExecutePlanResponse.FromString)
    with pytest.raises(grpc.RpcError) as ei:
        list(stub(pb.ExecutePlanRequest(session_id="s",
                                        plan=pb.Plan(root=pb.Relation()))))
    assert ei.value.code() == grpc.StatusCode.UNIMPLEMENTED


def test_write_parquet_roundtrip(channel, tmp_path):
    stub = channel.unary_stream(
        SERVICE + "ExecutePlan",
        request_serializer=pb.ExecutePlanRequest.SerializeToString,
        response_deserializer=pb.ExecutePlanResponse.FromString)
    out = str(tmp_path / "out")
    wr = pb.Plan(command=pb.Command(write_operation=pb.WriteOperation(
        input=pb.Relation(range=pb.Range(end=6, step=1)),
        source="parquet", path=out,
        mode=pb.WriteOperation.SAVE_MODE_OVERWRITE)))
    list(stub(pb.ExecutePlanRequest(session_id="s", plan=wr)))
    back = _execute(channel, pb.Relation(read=pb.Read(
        data_source=pb.Read.DataSource(format="parquet", paths=[out]))))
    assert sorted(back.column("id").to_pylist()) == [0, 1, 2, 3, 4, 5]


# ------------------------------------------- operation-lifecycle RPCs (r5)

def _lifecycle_stubs(channel):
    return {
        "execute": channel.unary_stream(
            SERVICE + "ExecutePlan",
            request_serializer=pb.ExecutePlanRequest.SerializeToString,
            response_deserializer=pb.ExecutePlanResponse.FromString),
        "reattach": channel.unary_stream(
            SERVICE + "ReattachExecute",
            request_serializer=pb.ReattachExecuteRequest.SerializeToString,
            response_deserializer=pb.ExecutePlanResponse.FromString),
        "release": channel.unary_unary(
            SERVICE + "ReleaseExecute",
            request_serializer=pb.ReleaseExecuteRequest.SerializeToString,
            response_deserializer=pb.ReleaseExecuteResponse.FromString),
        "interrupt": channel.unary_unary(
            SERVICE + "Interrupt",
            request_serializer=pb.InterruptRequest.SerializeToString,
            response_deserializer=pb.InterruptResponse.FromString),
        "artifacts": channel.stream_unary(
            SERVICE + "AddArtifacts",
            request_serializer=pb.AddArtifactsRequest.SerializeToString,
            response_deserializer=pb.AddArtifactsResponse.FromString),
    }


def _reattachable_req(session, op_id, rel):
    req = pb.ExecutePlanRequest(session_id=session, operation_id=op_id,
                                plan=pb.Plan(root=rel))
    req.request_options.add().reattach_options.reattachable = True
    return req


def test_reattach_replays_buffered_responses(channel):
    """A client that lost its connection reattaches by operation_id and
    receives the buffered stream again — same rows, same terminal
    result_complete."""
    stubs = _lifecycle_stubs(channel)
    rel = pb.Relation(range=pb.Range(start=0, end=50, step=1))
    req = _reattachable_req("life-1", "op-re-1", rel)
    first = list(stubs["execute"](req))
    assert first[-1].WhichOneof("response_type") == "result_complete"
    replay = list(stubs["reattach"](pb.ReattachExecuteRequest(
        session_id="life-1", operation_id="op-re-1")))
    assert [r.response_id for r in replay] == \
        [r.response_id for r in first]
    # resuming mid-stream: last_response_id skips what was delivered
    tail = list(stubs["reattach"](pb.ReattachExecuteRequest(
        session_id="life-1", operation_id="op-re-1",
        last_response_id=first[0].response_id)))
    assert [r.response_id for r in tail] == \
        [r.response_id for r in first[1:]]


def test_release_execute_frees_operation(channel):
    stubs = _lifecycle_stubs(channel)
    rel = pb.Relation(range=pb.Range(start=0, end=10, step=1))
    req = _reattachable_req("life-2", "op-rel-1", rel)
    list(stubs["execute"](req))
    out = stubs["release"](pb.ReleaseExecuteRequest(
        session_id="life-2", operation_id="op-rel-1",
        release_all=pb.ReleaseExecuteRequest.ReleaseAll()))
    assert out.operation_id == "op-rel-1"
    with pytest.raises(grpc.RpcError) as err:
        list(stubs["reattach"](pb.ReattachExecuteRequest(
            session_id="life-2", operation_id="op-rel-1")))
    assert err.value.code() == grpc.StatusCode.NOT_FOUND
    # releasing again is a no-op, not an error
    stubs["release"](pb.ReleaseExecuteRequest(
        session_id="life-2", operation_id="op-rel-1",
        release_all=pb.ReleaseExecuteRequest.ReleaseAll()))


def test_release_until_trims_replay(channel):
    stubs = _lifecycle_stubs(channel)
    rel = pb.Relation(range=pb.Range(start=0, end=10, step=1))
    req = _reattachable_req("life-3", "op-ru-1", rel)
    first = list(stubs["execute"](req))
    stubs["release"](pb.ReleaseExecuteRequest(
        session_id="life-3", operation_id="op-ru-1",
        release_until=pb.ReleaseExecuteRequest.ReleaseUntil(
            response_id=first[0].response_id)))
    replay = list(stubs["reattach"](pb.ReattachExecuteRequest(
        session_id="life-3", operation_id="op-ru-1")))
    assert [r.response_id for r in replay] == \
        [r.response_id for r in first[1:]]


def test_interrupt_completed_and_unknown_ops(channel):
    stubs = _lifecycle_stubs(channel)
    rel = pb.Relation(range=pb.Range(start=0, end=10, step=1))
    req = pb.ExecutePlanRequest(session_id="life-4",
                                operation_id="op-int-1",
                                plan=pb.Plan(root=rel))
    list(stubs["execute"](req))
    T = pb.InterruptRequest.InterruptType
    # a finished operation is not interruptible — empty id list
    out = stubs["interrupt"](pb.InterruptRequest(
        session_id="life-4", interrupt_type=T.INTERRUPT_TYPE_OPERATION_ID,
        operation_id="op-int-1"))
    assert list(out.interrupted_ids) == []
    # unknown id: same, no error
    out = stubs["interrupt"](pb.InterruptRequest(
        session_id="life-4", interrupt_type=T.INTERRUPT_TYPE_OPERATION_ID,
        operation_id="nope"))
    assert list(out.interrupted_ids) == []


def test_add_artifacts_batch_and_chunked(channel):
    import zlib
    stubs = _lifecycle_stubs(channel)
    blob = b"x" * 100

    def reqs():
        a = pb.AddArtifactsRequest(session_id="life-5")
        art = a.batch.artifacts.add()
        art.name = "files/a.txt"
        art.data.data = blob
        art.data.crc = zlib.crc32(blob)
        bad = a.batch.artifacts.add()
        bad.name = "files/bad.txt"
        bad.data.data = blob
        bad.data.crc = 1  # wrong on purpose
        yield a
        b = pb.AddArtifactsRequest(session_id="life-5")
        b.begin_chunk.name = "jars/big.jar"
        b.begin_chunk.num_chunks = 2
        b.begin_chunk.total_bytes = 200
        b.begin_chunk.initial_chunk.data = blob
        b.begin_chunk.initial_chunk.crc = zlib.crc32(blob)
        yield b
        c = pb.AddArtifactsRequest(session_id="life-5")
        c.chunk.data = blob
        c.chunk.crc = zlib.crc32(blob)
        yield c

    out = stubs["artifacts"](reqs())
    got = {s.name: s.is_crc_successful for s in out.artifacts}
    assert got == {"files/a.txt": True, "files/bad.txt": False,
                   "jars/big.jar": True}


def test_plain_execute_is_not_buffered(channel):
    """Without ReattachOptions the server must NOT retain the result
    stream (that would pin every query's bytes in session RAM): a later
    reattach finds nothing."""
    stubs = _lifecycle_stubs(channel)
    rel = pb.Relation(range=pb.Range(start=0, end=10, step=1))
    req = pb.ExecutePlanRequest(session_id="life-6",
                                operation_id="op-plain-1",
                                plan=pb.Plan(root=rel))
    list(stubs["execute"](req))
    with pytest.raises(grpc.RpcError) as err:
        list(stubs["reattach"](pb.ReattachExecuteRequest(
            session_id="life-6", operation_id="op-plain-1")))
    assert err.value.code() == grpc.StatusCode.NOT_FOUND


def test_interrupt_running_operation_cancels_stream(channel):
    """Interrupting a RUNNING execute must surface CANCELLED to the
    consuming client (not INTERNAL), honored between streamed batches."""
    import threading as th
    stubs = _lifecycle_stubs(channel)
    rel = pb.Relation(range=pb.Range(start=0, end=3_000_000, step=1))
    req = _reattachable_req("life-7", "op-int-run", rel)
    it = stubs["execute"](req)
    first = next(it)
    assert first.operation_id == "op-int-run"
    T = pb.InterruptRequest.InterruptType
    out = stubs["interrupt"](pb.InterruptRequest(
        session_id="life-7",
        interrupt_type=T.INTERRUPT_TYPE_OPERATION_ID,
        operation_id="op-int-run"))
    assert list(out.interrupted_ids) == ["op-int-run"]
    with pytest.raises(grpc.RpcError) as err:
        for _ in it:
            pass
    assert err.value.code() == grpc.StatusCode.CANCELLED
