"""Plan-discipline suite: the plan-contract registry round-trip, the
physical-plan estimate-field fixtures (constructor-declared, no hasattr
probing), the runtime plan sanitizer's checks, and the differential
plan fuzzer's determinism + smoke run."""

import pytest

import daft_tpu as dt
from daft_tpu import col
from daft_tpu.analysis import plan_contracts, plan_fuzzer
from daft_tpu.analysis import plan_sanitizer as ps
from daft_tpu.context import execution_config_ctx
from daft_tpu.logical import plan as lp
from daft_tpu.physical import plan as pp
from daft_tpu.physical.translate import translate
from daft_tpu.recordbatch import RecordBatch
from daft_tpu.micropartition import MicroPartition


def pwalk(plan):
    yield plan
    for c in plan.children:
        yield from pwalk(c)


def _mp(data):
    return MicroPartition.from_recordbatch(RecordBatch.from_pydict(data))


# ------------------------------------------------------------- registry


def test_registry_names_resolve():
    """Every registered node names a real class in its layer (the lint
    proves the reverse direction: every class is registered)."""
    for name in plan_contracts.LOGICAL_NODES:
        assert hasattr(lp, name), f"LOGICAL_NODES has stale entry {name}"
    for name in plan_contracts.PHYSICAL_NODES:
        assert hasattr(pp, name), f"PHYSICAL_NODES has stale entry {name}"


def test_replan_mutable_fields_registered():
    from daft_tpu.distributed import replan, stages
    for m in plan_contracts.REPLAN_MUTABLE:
        assert (hasattr(pp, m.cls) or hasattr(stages, m.cls)
                or hasattr(replan, m.cls)), \
            f"REPLAN_MUTABLE stale class {m.cls}"
        assert m.field in plan_contracts.REPLAN_MUTABLE_FIELDS


def test_rule_contracts_cover_default_optimizer():
    from daft_tpu.logical.optimizer import Optimizer
    for batch in Optimizer().batches:
        for rule in batch.rules:
            name = type(rule).__name__
            assert name in plan_contracts.RULE_CONTRACTS, \
                f"optimizer rule {name} missing a RuleContract"


# ------------------------------------- estimate-field constructor fixtures


def test_aggregate_estimate_fields_declared():
    """r20 fixed-point: estimate fields are constructor-declared with
    None defaults — consumers never need hasattr probing."""
    df = dt.from_pydict({"k": [1, 2, 2, 3], "v": [1.0, 2.0, 3.0, 4.0]})
    plan = translate(df.groupby("k").agg(col("v").sum())
                     ._builder.optimize()._plan)
    aggs = [n for n in pwalk(plan) if isinstance(n, pp.Aggregate)]
    assert aggs
    for a in aggs:
        # declared by the constructor (None) and possibly refined by the
        # static planner — never a hasattr-guarded late binding
        assert "group_rows_est" in a.__dict__
        assert "group_ndv" in a.__dict__
        assert a.group_rows_est is None \
            or isinstance(a.group_rows_est, (int, float))
        assert a.group_ndv is None or isinstance(a.group_ndv, (int, float))


def test_hash_join_and_exchange_estimate_fields_declared():
    l = dt.from_pydict({"k": list(range(64)),
                        "v": [float(i) for i in range(64)]})
    r = dt.from_pydict({"rk": list(range(0, 64, 2)), "w": list(range(32))})
    with execution_config_ctx(broadcast_join_size_bytes_threshold=1):
        q = l.into_partitions(4).join(r.into_partitions(4),
                                      left_on="k", right_on="rk")
        plan = translate(q._builder.optimize()._plan)
    joins = [n for n in pwalk(plan) if isinstance(n, pp.HashJoin)]
    exchanges = [n for n in pwalk(plan) if isinstance(n, pp.Exchange)]
    assert joins and exchanges
    for j in joins:
        assert "left_bytes_est" in j.__dict__
        assert "right_bytes_est" in j.__dict__
        assert j.left_bytes_est is None \
            or isinstance(j.left_bytes_est, (int, float))
        assert j.right_bytes_est is None \
            or isinstance(j.right_bytes_est, (int, float))
    for e in exchanges:
        assert "join_side" in e.__dict__


def test_fused_region_estimate_fields_declared():
    from daft_tpu.context import ExecutionConfig
    from daft_tpu.device import runtime as drt
    from daft_tpu.physical import fusion
    if not drt.device_enabled():
        pytest.skip("device tier disabled")
    df = (dt.from_pydict({"k": [1, 2, 3, 4], "v": [1.0, 2.0, 3.0, 4.0]})
          .where(col("k") > 1).select(col("k"),
                                      (col("v") * 2).alias("v2")))
    plan = fusion.fuse_regions(translate(df._builder.optimize()._plan),
                               ExecutionConfig(tpu_fusion="1"))
    regions = [n for n in pwalk(plan) if isinstance(n, pp.FusedRegion)]
    assert regions, "exemplar should fuse into a region"
    for reg in regions:
        assert "group_rows_est" in reg.__dict__
        assert reg.fallback.schema().fields == reg.schema().fields


# ------------------------------------------------------------ sanitizer


def test_check_rule_flags_schema_change():
    s = ps.PlanSanitizer()
    a = dt.from_pydict({"x": [1]}).schema()
    b = dt.from_pydict({"x": [1.5]}).schema()
    s.check_rule("PushDownFilter", a, b)
    assert len(s.summary()["violations"]) == 1
    assert "changed the root schema" in s.summary()["violations"][0]


def test_check_rule_flags_unregistered_rule():
    s = ps.PlanSanitizer()
    sch = dt.from_pydict({"x": [1]}).schema()
    s.check_rule("TotallyNovelRule", sch, sch)
    assert any("not registered" in v for v in s.summary()["violations"])
    # registered, schema-identical: clean
    s2 = ps.PlanSanitizer()
    s2.check_rule("PushDownFilter", sch, sch)
    assert not s2.summary()["violations"]


def test_order_check_flags_unsorted_partition():
    s = ps.PlanSanitizer(sample_rows=16)

    class Stub:
        sort_by = (col("k"),)
        descending = (False,)
        nulls_first = (False,)

    s._check_order(Stub(), _mp({"k": [3, 1, 2]}))
    assert any("unsorted" in v for v in s.summary()["violations"])
    s2 = ps.PlanSanitizer(sample_rows=16)
    s2._check_order(Stub(), _mp({"k": [1, 2, 3]}))
    assert not s2.summary()["violations"]


def test_conservation_flags_row_loss():
    s = ps.PlanSanitizer()

    class Filter:  # names chosen to hit the registry contracts
        children = ()

    class Project:
        def __init__(self, child):
            self.children = [child]

    child = Filter()
    list(s.wrap(child, iter([_mp({"x": [1, 2, 3]})])))
    parent = Project(child)
    list(s.wrap(parent, iter([_mp({"x": [1, 2]})])))  # dropped a row
    viols = s.summary()["violations"]
    assert any("row-conservation" in v for v in viols), viols

    s2 = ps.PlanSanitizer()
    child2 = Filter()
    list(s2.wrap(child2, iter([_mp({"x": [1, 2, 3]})])))
    parent2 = Project(child2)
    list(s2.wrap(parent2, iter([_mp({"x": [1, 2, 3]})])))
    assert not s2.summary()["violations"]


def test_grace_pair_membership_check(monkeypatch):
    part = _mp({"k": [7] * 12, "v": list(range(12))})
    true_bucket = next(
        i for i, p in enumerate(part.partition_by_hash([col("k")], 4))
        if len(p))
    san = ps.PlanSanitizer(sample_rows=16)
    monkeypatch.setattr(ps, "_global", san)
    monkeypatch.setattr(ps, "_enabled", True)
    ps.check_grace_pair(true_bucket, 4, [col("k")], part)
    assert not san.summary()["violations"]
    ps.check_grace_pair((true_bucket + 1) % 4, 4, [col("k")], part)
    assert any("bucket membership" in v
               for v in san.summary()["violations"])


def test_sanitizer_end_to_end_clean_and_counters():
    """Armed sanitizer over real queries: checks run, nothing trips,
    per-query counter deltas carry the absolute violation level."""
    was_enabled = ps.is_enabled()
    ps.enable()
    try:
        before = ps.counters_snapshot()
        l = dt.from_pydict({"k": list(range(256)),
                            "v": [float(i) for i in range(256)]})
        r = dt.from_pydict({"rk": list(range(0, 256, 2)),
                            "w": list(range(128))})
        with execution_config_ctx(broadcast_join_size_bytes_threshold=1):
            out = (l.into_partitions(4).join(r.into_partitions(4),
                                             left_on="k", right_on="rk")
                   .sort("k").to_pydict())
        assert len(out["k"]) == 128
        after = ps.counters_snapshot()
        delta = ps.counters_delta(before, after)
        assert delta["rule_checks"] > 0
        assert delta["membership_parts"] > 0
        assert delta["order_parts"] > 0
        assert delta["violations"] == 0
        assert "total_violations" in delta
        assert not ps.summary()["violations"]
    finally:
        # under DAFT_TPU_SANITIZE_PLAN=1 the sanitizer is armed for the
        # whole session — leave it that way
        if not was_enabled:
            ps.disable()


def test_sanitizer_stale_record_id_reuse_guard():
    """A completed record whose node object died must not be read as a
    child's books by a new node that recycled the id (the AQE replanning
    bug class the weakref guard closes)."""
    s = ps.PlanSanitizer()

    class Filter:
        children = ()

    class Project:
        def __init__(self, child):
            self.children = [child]

    child = Filter()
    list(s.wrap(child, iter([_mp({"x": [1]})])))  # completed: 1 row
    rec = s._records[id(child)]
    fresh = Filter()  # a DIFFERENT object the stale record can't vouch for
    s._records[id(fresh)] = rec  # simulate CPython id reuse
    parent = Project(fresh)
    list(s.wrap(parent, iter([_mp({"x": [1, 2, 3]})])))
    assert not s.summary()["violations"]  # skipped, not misjudged


# ---------------------------------------------------------------- fuzzer


def test_fuzzer_is_deterministic():
    t1, o1 = plan_fuzzer.gen_case(11)
    t2, o2 = plan_fuzzer.gen_case(11)
    assert t1 == t2 and o1 == o2
    t3, o3 = plan_fuzzer.gen_case(12)
    assert (t1, o1) != (t3, o3)


def test_fuzzer_canonical_rows_order_insensitive():
    a = plan_fuzzer.canonical_rows({"x": [1, None, 2], "y": [3.0, 4.0, None]})
    b = plan_fuzzer.canonical_rows({"x": [2, 1, None], "y": [None, 3.0, 4.0]})
    assert a == b
    c = plan_fuzzer.canonical_rows({"x": [2, 1, None], "y": [None, 3.5, 4.0]})
    assert a != c


def test_fuzzer_smoke_local_modes():
    res = plan_fuzzer.run_fuzz(count=2, seed=101,
                               modes=("optimized", "fused", "spilled"))
    assert res.seeds_run == 2
    assert not res.mismatches, [m.repro() for m in res.mismatches]
    assert not res.errors, res.errors


@pytest.mark.slow
def test_fuzzer_smoke_full_matrix():
    res = plan_fuzzer.run_fuzz(count=5, seed=201)
    assert res.seeds_run == 5
    assert not res.mismatches, [m.repro() for m in res.mismatches]
    assert not res.errors, res.errors
