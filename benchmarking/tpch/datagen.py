"""Vectorized, chunked TPC-H data generator (dbgen-compatible schemas).

Row counts and value domains follow the TPC-H specification; value
*distributions* are uniform via seeded numpy, which is sufficient for
correctness tests (validated against an independent pandas implementation of
each query on the same data) and for throughput benchmarking.

Memory-bounded by construction: every table is generated in key-range
chunks (one parquet file per chunk) with a per-chunk RNG seeded by
``[seed, table_id, chunk_id]`` — output is deterministic for a given
``(seed, num_parts)`` pair (chunk boundaries derive from ``num_parts``,
so different part counts are different datasets; regenerate rather than
mixing). String columns are built with pyarrow compute kernels
(``binary_join_element_wise`` / ``utf8_lpad``) instead of Python loops, so
SF100 (~600M lineitem rows) generates in bounded RAM at C speed.
Reference analogue: ``benchmarking/tpch`` data generation pipeline
(the reference shells out to dbgen; we synthesize spec-shaped data).
"""

from __future__ import annotations

import datetime
import os
from typing import Dict

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc
import pyarrow.parquet as pq

_EPOCH = datetime.date(1970, 1, 1)
_START = (datetime.date(1992, 1, 1) - _EPOCH).days
_END = (datetime.date(1998, 12, 1) - _EPOCH).days

REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
NATIONS = [
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1),
    ("EGYPT", 4), ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3),
    ("INDIA", 2), ("INDONESIA", 2), ("IRAN", 4), ("IRAQ", 4),
    ("JAPAN", 2), ("JORDAN", 4), ("KENYA", 0), ("MOROCCO", 0),
    ("MOZAMBIQUE", 0), ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3),
    ("SAUDI ARABIA", 4), ("VIETNAM", 2), ("RUSSIA", 3),
    ("UNITED KINGDOM", 3), ("UNITED STATES", 1),
]
SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "MACHINERY", "HOUSEHOLD"]
PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]
SHIPMODES = ["REG AIR", "AIR", "RAIL", "SHIP", "TRUCK", "MAIL", "FOB"]
INSTRUCTS = ["DELIVER IN PERSON", "COLLECT COD", "NONE", "TAKE BACK RETURN"]
CONTAINERS = [f"{a} {b}" for a in ["SM", "LG", "MED", "JUMBO", "WRAP"]
              for b in ["CASE", "BOX", "BAG", "JAR", "PKG", "PACK", "CAN", "DRUM"]]
TYPES = [f"{a} {b} {c}" for a in ["STANDARD", "SMALL", "MEDIUM", "LARGE",
                                  "ECONOMY", "PROMO"]
         for b in ["ANODIZED", "BURNISHED", "PLATED", "POLISHED", "BRUSHED"]
         for c in ["TIN", "NICKEL", "BRASS", "STEEL", "COPPER"]]
P_NAME_WORDS = ["almond", "antique", "aquamarine", "azure", "beige", "bisque",
                "black", "blanched", "blue", "blush", "brown", "burlywood",
                "burnished", "chartreuse", "chiffon", "chocolate", "coral",
                "cornflower", "cornsilk", "cream", "cyan", "dark", "deep",
                "dim", "dodger", "drab", "firebrick", "floral", "forest",
                "frosted", "gainsboro", "ghost", "goldenrod", "green", "grey",
                "honeydew", "hot", "hazel", "indian", "ivory", "khaki",
                "lace", "lavender", "lawn", "lemon", "light", "lime", "linen"]

# Orders per generation chunk; bounds peak RAM at SF100 to a few GB
# (each chunk carries ~4x lineitem rows).
_CHUNK_ORDERS = 3_000_000


def _dates(rng, n, lo=_START, hi=_END):
    return rng.integers(lo, hi, n).astype("datetime64[D]")


def _money(rng, n, lo, hi):
    return np.round(rng.uniform(lo, hi, n), 2)


def _pick(rng, choices, n):
    """Dictionary-encoded draw from a small choice list (C-speed, compact)."""
    idx = rng.integers(0, len(choices), n).astype(np.int32)
    return pa.DictionaryArray.from_arrays(pa.array(idx), pa.array(choices)).cast(pa.string())


def _comment(rng, n, words=8):
    """n random word-salad comments, built entirely with arrow kernels."""
    w = pa.array(P_NAME_WORDS)
    cols = [pc.take(w, pa.array(rng.integers(0, len(P_NAME_WORDS), n).astype(np.int32)))
            for _ in range(words)]
    return pc.binary_join_element_wise(*cols, " ")


def _tagged(prefix: str, keys: np.ndarray) -> pa.Array:
    """'Prefix#000000123'-style names via arrow lpad (no Python loop)."""
    padded = pc.utf8_lpad(pc.cast(pa.array(keys), pa.string()), 9, "0")
    return pc.binary_join_element_wise(
        pa.nulls(len(keys), pa.string()).fill_null(prefix + "#"), padded, "")


def _phone(rng, n, lo=0) -> pa.Array:
    i = np.arange(lo, lo + n, dtype=np.int64)
    cc = pc.cast(pa.array(rng.integers(10, 35, n)), pa.string())
    p1 = pc.utf8_lpad(pc.cast(pa.array(i % 999), pa.string()), 3, "0")
    p2 = pc.utf8_lpad(pc.cast(pa.array((i * 7) % 999), pa.string()), 3, "0")
    p3 = pc.utf8_lpad(pc.cast(pa.array((i * 13) % 9999), pa.string()), 4, "0")
    return pc.binary_join_element_wise(cc, p1, p2, p3, "-")


def _mark(base: pa.Array, rng, n, prob: float, marker: str) -> pa.Array:
    """Append `marker` to ~prob of the rows (spec'd LIKE-pattern planting)."""
    marks = pa.array(rng.random(n) < prob)
    marked = pc.binary_join_element_wise(base, pa.nulls(n, pa.string()).fill_null(marker), " ")
    return pc.if_else(marks, marked, base)


def _chunks(total: int, per: int):
    lo = 0
    cid = 0
    while lo < total:
        hi = min(lo + per, total)
        yield cid, lo, hi
        lo = hi
        cid += 1


def generate_tpch(root: str, scale_factor: float = 0.01,
                  num_parts: int = 4, seed: int = 42,
                  fmt: str = "parquet", verbose: bool = False) -> Dict[str, str]:
    """Generate all 8 tables under root/<table>/*.parquet; returns paths.

    ``num_parts`` is the *minimum* file count per large table; tables whose
    generation chunks exceed it produce one file per chunk instead (more
    files = more scan partitions, never less).
    """
    os.makedirs(root, exist_ok=True)
    sf = scale_factor
    out: Dict[str, str] = {}

    def _dir(name: str) -> str:
        d = os.path.join(root, name)
        os.makedirs(d, exist_ok=True)
        out[name] = d
        return d

    def write_chunk(name: str, idx: int, table: pa.Table):
        pq.write_table(table, os.path.join(_dir(name), f"{name}.{idx}.parquet"))
        if verbose:
            import sys
            print(f"  {name}.{idx}: {table.num_rows} rows", file=sys.stderr, flush=True)

    def write_parts(name: str, table: pa.Table, parts: int):
        n = table.num_rows
        parts = max(1, min(parts, n or 1))
        step = (n + parts - 1) // parts if n else 1
        for i in range(parts):
            write_chunk(name, i, table.slice(i * step, step))

    rng = np.random.default_rng([seed, 0])

    # region / nation ---------------------------------------------------
    write_parts("region", pa.table({
        "r_regionkey": pa.array(range(5), pa.int64()),
        "r_name": REGIONS,
        "r_comment": _comment(rng, 5),
    }), 1)
    write_parts("nation", pa.table({
        "n_nationkey": pa.array(range(25), pa.int64()),
        "n_name": [n for n, _ in NATIONS],
        "n_regionkey": pa.array([r for _, r in NATIONS], pa.int64()),
        "n_comment": _comment(rng, 25),
    }), 1)

    n_supp = max(int(10_000 * sf), 10)
    n_cust = max(int(150_000 * sf), 30)
    n_part = max(int(200_000 * sf), 40)
    n_ord = max(int(1_500_000 * sf), 150)
    n_clerk = max(int(1000 * sf), 10)

    # supplier -----------------------------------------------------------
    per = max((n_supp + num_parts - 1) // num_parts, 1)
    per = min(per, 10_000_000)
    for cid, lo, hi in _chunks(n_supp, per):
        r = np.random.default_rng([seed, 1, cid])
        sk = np.arange(lo + 1, hi + 1)
        m = hi - lo
        write_chunk("supplier", cid, pa.table({
            "s_suppkey": sk,
            "s_name": _tagged("Supplier", sk),
            "s_address": _comment(r, m, 3),
            "s_nationkey": r.integers(0, 25, m),
            "s_phone": _phone(r, m, lo),
            "s_acctbal": _money(r, m, -999.99, 9999.99),
            # spec'd Q16 "Customer Complaints" marker in ~0.05% of rows
            "s_comment": _mark(_comment(r, m, 6), r, m, 0.0005,
                               "Customer Complaints"),
        }))

    # customer -----------------------------------------------------------
    per = max((n_cust + num_parts - 1) // num_parts, 1)
    per = min(per, 10_000_000)
    for cid, lo, hi in _chunks(n_cust, per):
        r = np.random.default_rng([seed, 2, cid])
        ck = np.arange(lo + 1, hi + 1)
        m = hi - lo
        write_chunk("customer", cid, pa.table({
            "c_custkey": ck,
            "c_name": _tagged("Customer", ck),
            "c_address": _comment(r, m, 3),
            "c_nationkey": r.integers(0, 25, m),
            "c_phone": _phone(r, m, lo),
            "c_acctbal": _money(r, m, -999.99, 9999.99),
            "c_mktsegment": _pick(r, SEGMENTS, m),
            "c_comment": _comment(r, m, 6),
        }))

    # part + partsupp ----------------------------------------------------
    per = max((n_part + num_parts - 1) // num_parts, 1)
    per = min(per, 5_000_000)
    wnames = pa.array(P_NAME_WORDS)
    for cid, lo, hi in _chunks(n_part, per):
        r = np.random.default_rng([seed, 3, cid])
        pk = np.arange(lo + 1, hi + 1)
        m = hi - lo
        name_cols = [pc.take(wnames, pa.array(
            r.integers(0, len(P_NAME_WORDS), m).astype(np.int32)))
            for _ in range(5)]
        brand = pc.binary_join_element_wise(
            pa.nulls(m, pa.string()).fill_null("Brand#"),
            pc.cast(pa.array(r.integers(1, 6, m)), pa.string()),
            pc.cast(pa.array(r.integers(1, 6, m)), pa.string()), "")
        mfgr = pc.binary_join_element_wise(
            pa.nulls(m, pa.string()).fill_null("Manufacturer#"),
            pc.cast(pa.array(r.integers(1, 6, m)), pa.string()), "")
        write_chunk("part", cid, pa.table({
            "p_partkey": pk,
            "p_name": pc.binary_join_element_wise(*name_cols, " "),
            "p_mfgr": mfgr,
            "p_brand": brand,
            "p_type": _pick(r, TYPES, m),
            "p_size": r.integers(1, 51, m),
            "p_container": _pick(r, CONTAINERS, m),
            "p_retailprice": _money(r, m, 900, 2000),
            "p_comment": _comment(r, m, 3),
        }))
        # partsupp rows for this part range (4 suppliers per part,
        # same formula lineitem uses so (l_partkey,l_suppkey) joins hit)
        ps_part = np.repeat(pk, 4)
        n_ps = len(ps_part)
        ps_supp = ((ps_part - 1 + (np.tile(np.arange(4), m)
                                   * (n_supp // 4 + 1))) % n_supp) + 1
        write_chunk("partsupp", cid, pa.table({
            "ps_partkey": ps_part,
            "ps_suppkey": ps_supp,
            "ps_availqty": r.integers(1, 10_000, n_ps),
            "ps_supplycost": _money(r, n_ps, 1.0, 1000.0),
            "ps_comment": _comment(r, n_ps, 10),
        }))

    # orders + lineitem (generated together per chunk so lineitem can
    # derive from its orders' dates without cross-chunk state) ----------
    per = max((n_ord + num_parts - 1) // num_parts, 1)
    per = min(per, _CHUNK_ORDERS)
    today = (datetime.date(1995, 6, 17) - _EPOCH).days
    for cid, lo, hi in _chunks(n_ord, per):
        r = np.random.default_rng([seed, 4, cid])
        m = hi - lo
        ok = (np.arange(lo + 1, hi + 1)) * 4 - 3  # sparse keys like dbgen
        o_date = _dates(r, m, _START, _END - 151)
        write_chunk("orders", cid, pa.table({
            "o_orderkey": ok,
            "o_custkey": r.integers(1, n_cust + 1, m),
            "o_orderstatus": _pick(r, ["F", "O", "P"], m),
            "o_totalprice": _money(r, m, 1000, 500_000),
            "o_orderdate": o_date,
            "o_orderpriority": _pick(r, PRIORITIES, m),
            "o_clerk": _tagged("Clerk", r.integers(1, n_clerk, m)),
            "o_shippriority": np.zeros(m, dtype=np.int32),
            # spec'd Q13 marker: ~1% of orders carry "special requests"
            "o_comment": _mark(_comment(r, m, 6), r, m, 0.01,
                               "special requests"),
        }))

        per_order = r.integers(1, 8, m)
        l_orderkey = np.repeat(ok, per_order)
        l_odate = np.repeat(o_date.astype(np.int64), per_order)
        n_li = len(l_orderkey)
        starts = np.repeat(np.cumsum(per_order) - per_order, per_order)
        linenumber = np.arange(n_li, dtype=np.int64) - starts + 1
        qty = r.integers(1, 51, n_li).astype(np.float64)
        partkey = r.integers(1, n_part + 1, n_li)
        price = np.round(qty * (90_000 + (partkey % 20_001) + 100 *
                                (partkey % 1000)) / 100.0 / 50.0, 2)
        ship_delta = r.integers(1, 122, n_li)
        commit_delta = r.integers(30, 91, n_li)
        receipt_delta = r.integers(1, 31, n_li)
        l_ship = l_odate + ship_delta
        l_receipt = l_ship + receipt_delta
        returnflag = np.where(
            l_receipt <= today,
            np.array(["R", "A"])[r.integers(0, 2, n_li)], "N")
        linestatus = np.where(l_ship > today, "O", "F")
        write_chunk("lineitem", cid, pa.table({
            "l_orderkey": l_orderkey,
            "l_partkey": partkey,
            # spec 4.2.3: a lineitem's supplier is one of its part's FOUR
            # partsupp suppliers (same formula as ps_supp with j = ln % 4);
            # an independent draw made (l_partkey, l_suppkey) match partsupp
            # with probability ~0 and emptied every partsupp⨝lineitem join
            "l_suppkey": ((partkey - 1 + (linenumber % 4)
                           * (n_supp // 4 + 1)) % n_supp) + 1,
            "l_linenumber": linenumber,
            "l_quantity": qty,
            "l_extendedprice": price,
            "l_discount": np.round(r.integers(0, 11, n_li) / 100.0, 2),
            "l_tax": np.round(r.integers(0, 9, n_li) / 100.0, 2),
            "l_returnflag": pa.array(returnflag),
            "l_linestatus": pa.array(linestatus),
            "l_shipdate": l_ship.astype("datetime64[D]"),
            "l_commitdate": (l_odate + commit_delta).astype("datetime64[D]"),
            "l_receiptdate": l_receipt.astype("datetime64[D]"),
            "l_shipinstruct": _pick(r, INSTRUCTS, n_li),
            "l_shipmode": _pick(r, SHIPMODES, n_li),
            "l_comment": _comment(r, n_li, 4),
        }))
    return out


if __name__ == "__main__":
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--root", default="/tmp/tpch")
    ap.add_argument("--sf", type=float, default=0.01)
    ap.add_argument("--parts", type=int, default=4)
    ap.add_argument("--seed", type=int, default=42)
    args = ap.parse_args()
    print(generate_tpch(args.root, args.sf, args.parts, seed=args.seed,
                        verbose=True))
