"""Vectorized TPC-H data generator (dbgen-compatible schemas).

Row counts and value domains follow the TPC-H specification; value
*distributions* are uniform via seeded numpy, which is sufficient for
correctness tests (validated against an independent pandas implementation of
each query on the same data) and for throughput benchmarking.
Reference analogue: ``benchmarking/tpch`` data generation pipeline.
"""

from __future__ import annotations

import datetime
import os
from typing import Dict, Optional

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

_EPOCH = datetime.date(1970, 1, 1)
_START = (datetime.date(1992, 1, 1) - _EPOCH).days
_END = (datetime.date(1998, 12, 1) - _EPOCH).days

REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
NATIONS = [
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1),
    ("EGYPT", 4), ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3),
    ("INDIA", 2), ("INDONESIA", 2), ("IRAN", 4), ("IRAQ", 4),
    ("JAPAN", 2), ("JORDAN", 4), ("KENYA", 0), ("MOROCCO", 0),
    ("MOZAMBIQUE", 0), ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3),
    ("SAUDI ARABIA", 4), ("VIETNAM", 2), ("RUSSIA", 3),
    ("UNITED KINGDOM", 3), ("UNITED STATES", 1),
]
SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "MACHINERY", "HOUSEHOLD"]
PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]
SHIPMODES = ["REG AIR", "AIR", "RAIL", "SHIP", "TRUCK", "MAIL", "FOB"]
INSTRUCTS = ["DELIVER IN PERSON", "COLLECT COD", "NONE", "TAKE BACK RETURN"]
CONTAINERS = [f"{a} {b}" for a in ["SM", "LG", "MED", "JUMBO", "WRAP"]
              for b in ["CASE", "BOX", "BAG", "JAR", "PKG", "PACK", "CAN", "DRUM"]]
TYPES = [f"{a} {b} {c}" for a in ["STANDARD", "SMALL", "MEDIUM", "LARGE",
                                  "ECONOMY", "PROMO"]
         for b in ["ANODIZED", "BURNISHED", "PLATED", "POLISHED", "BRUSHED"]
         for c in ["TIN", "NICKEL", "BRASS", "STEEL", "COPPER"]]
P_NAME_WORDS = ["almond", "antique", "aquamarine", "azure", "beige", "bisque",
                "black", "blanched", "blue", "blush", "brown", "burlywood",
                "burnished", "chartreuse", "chiffon", "chocolate", "coral",
                "cornflower", "cornsilk", "cream", "cyan", "dark", "deep",
                "dim", "dodger", "drab", "firebrick", "floral", "forest",
                "frosted", "gainsboro", "ghost", "goldenrod", "green", "grey",
                "honeydew", "hot", "hazel", "indian", "ivory", "khaki",
                "lace", "lavender", "lawn", "lemon", "light", "lime", "linen"]


def _dates(rng, n, lo=_START, hi=_END):
    return rng.integers(lo, hi, n).astype("datetime64[D]")


def _money(rng, n, lo, hi):
    return np.round(rng.uniform(lo, hi, n), 2)


def _comment(rng, n, words=8):
    w = np.array(P_NAME_WORDS)
    picks = rng.integers(0, len(w), (n, words))
    return [" ".join(row) for row in w[picks]]


def generate_tpch(root: str, scale_factor: float = 0.01,
                  num_parts: int = 4, seed: int = 42,
                  fmt: str = "parquet") -> Dict[str, str]:
    """Generate all 8 tables under root/<table>/*.parquet; returns paths."""
    os.makedirs(root, exist_ok=True)
    rng = np.random.default_rng(seed)
    sf = scale_factor
    out: Dict[str, str] = {}

    def write(name: str, table: pa.Table, parts: int = 1):
        d = os.path.join(root, name)
        os.makedirs(d, exist_ok=True)
        n = table.num_rows
        parts = max(1, min(parts, n or 1))
        step = (n + parts - 1) // parts if n else 1
        for i in range(parts):
            chunk = table.slice(i * step, step)
            pq.write_table(chunk, os.path.join(d, f"{name}.{i}.parquet"))
        out[name] = d

    # region / nation ---------------------------------------------------
    write("region", pa.table({
        "r_regionkey": pa.array(range(5), pa.int64()),
        "r_name": REGIONS,
        "r_comment": _comment(rng, 5),
    }))
    write("nation", pa.table({
        "n_nationkey": pa.array(range(25), pa.int64()),
        "n_name": [n for n, _ in NATIONS],
        "n_regionkey": pa.array([r for _, r in NATIONS], pa.int64()),
        "n_comment": _comment(rng, 25),
    }))

    # supplier -----------------------------------------------------------
    n_supp = max(int(10_000 * sf), 10)
    sk = np.arange(1, n_supp + 1)
    write("supplier", pa.table({
        "s_suppkey": sk,
        "s_name": [f"Supplier#{k:09d}" for k in sk],
        "s_address": _comment(rng, n_supp, 3),
        "s_nationkey": rng.integers(0, 25, n_supp),
        "s_phone": [f"{rng2:02d}-{i % 999:03d}-{(i * 7) % 999:03d}-{(i * 13) % 9999:04d}"
                    for i, rng2 in enumerate(rng.integers(10, 35, n_supp))],
        "s_acctbal": _money(rng, n_supp, -999.99, 9999.99),
        "s_comment": _supplier_comments(rng, n_supp),
    }), num_parts)

    # customer -----------------------------------------------------------
    n_cust = max(int(150_000 * sf), 30)
    ck = np.arange(1, n_cust + 1)
    write("customer", pa.table({
        "c_custkey": ck,
        "c_name": [f"Customer#{k:09d}" for k in ck],
        "c_address": _comment(rng, n_cust, 3),
        "c_nationkey": rng.integers(0, 25, n_cust),
        "c_phone": [f"{p:02d}-{i % 999:03d}-{(i * 3) % 999:03d}-{(i * 11) % 9999:04d}"
                    for i, p in enumerate(rng.integers(10, 35, n_cust))],
        "c_acctbal": _money(rng, n_cust, -999.99, 9999.99),
        "c_mktsegment": np.array(SEGMENTS)[rng.integers(0, 5, n_cust)],
        "c_comment": _customer_comments(rng, n_cust),
    }), num_parts)

    # part ---------------------------------------------------------------
    n_part = max(int(200_000 * sf), 40)
    pk = np.arange(1, n_part + 1)
    wnames = np.array(P_NAME_WORDS)
    name_picks = rng.integers(0, len(wnames), (n_part, 5))
    write("part", pa.table({
        "p_partkey": pk,
        "p_name": [" ".join(r) for r in wnames[name_picks]],
        "p_mfgr": [f"Manufacturer#{m}" for m in rng.integers(1, 6, n_part)],
        "p_brand": [f"Brand#{m}{x}" for m, x in
                    zip(rng.integers(1, 6, n_part), rng.integers(1, 6, n_part))],
        "p_type": np.array(TYPES)[rng.integers(0, len(TYPES), n_part)],
        "p_size": rng.integers(1, 51, n_part),
        "p_container": np.array(CONTAINERS)[rng.integers(0, len(CONTAINERS), n_part)],
        "p_retailprice": _money(rng, n_part, 900, 2000),
        "p_comment": _comment(rng, n_part, 3),
    }), num_parts)

    # partsupp -----------------------------------------------------------
    ps_part = np.repeat(pk, 4)
    n_ps = len(ps_part)
    ps_supp = ((ps_part - 1 + (np.tile(np.arange(4), n_part)
                               * (n_supp // 4 + 1))) % n_supp) + 1
    write("partsupp", pa.table({
        "ps_partkey": ps_part,
        "ps_suppkey": ps_supp,
        "ps_availqty": rng.integers(1, 10_000, n_ps),
        "ps_supplycost": _money(rng, n_ps, 1.0, 1000.0),
        "ps_comment": _comment(rng, n_ps, 10),
    }), num_parts)

    # orders -------------------------------------------------------------
    n_ord = max(int(1_500_000 * sf), 150)
    ok = np.arange(1, n_ord + 1) * 4 - 3  # sparse keys like dbgen
    o_date = _dates(rng, n_ord, _START, _END - 151)
    write("orders", pa.table({
        "o_orderkey": ok,
        "o_custkey": rng.integers(1, n_cust + 1, n_ord),
        "o_orderstatus": np.array(["F", "O", "P"])[rng.integers(0, 3, n_ord)],
        "o_totalprice": _money(rng, n_ord, 1000, 500_000),
        "o_orderdate": o_date,
        "o_orderpriority": np.array(PRIORITIES)[rng.integers(0, 5, n_ord)],
        "o_clerk": [f"Clerk#{c:09d}" for c in rng.integers(1, max(int(1000 * sf), 10), n_ord)],
        "o_shippriority": np.zeros(n_ord, dtype=np.int32),
        "o_comment": _comment(rng, n_ord, 6),
    }), num_parts)

    # lineitem -----------------------------------------------------------
    per_order = rng.integers(1, 8, n_ord)
    l_orderkey = np.repeat(ok, per_order)
    l_odate = np.repeat(o_date.astype(np.int64), per_order)
    n_li = len(l_orderkey)
    linenumber = np.concatenate([np.arange(1, c + 1) for c in per_order])
    qty = rng.integers(1, 51, n_li).astype(np.float64)
    partkey = rng.integers(1, n_part + 1, n_li)
    price = np.round(qty * (90_000 + (partkey % 20_001) + 100 *
                            (partkey % 1000)) / 100.0 / 50.0, 2)
    ship_delta = rng.integers(1, 122, n_li)
    commit_delta = rng.integers(30, 91, n_li)
    receipt_delta = rng.integers(1, 31, n_li)
    l_ship = l_odate + ship_delta
    l_receipt = l_ship + receipt_delta
    today = (datetime.date(1995, 6, 17) - _EPOCH).days
    returnflag = np.where(
        l_receipt <= today,
        np.array(["R", "A"])[rng.integers(0, 2, n_li)], "N")
    linestatus = np.where(l_ship > today, "O", "F")
    write("lineitem", pa.table({
        "l_orderkey": l_orderkey,
        "l_partkey": partkey,
        # spec 4.2.3: a lineitem's supplier is one of its part's FOUR
        # partsupp suppliers (same formula as ps_supp with j = ln % 4);
        # an independent draw made (l_partkey, l_suppkey) match partsupp
        # with probability ~0 and emptied every partsupp⨝lineitem join
        "l_suppkey": ((partkey - 1 + (linenumber % 4)
                       * (n_supp // 4 + 1)) % n_supp) + 1,
        "l_linenumber": linenumber,
        "l_quantity": qty,
        "l_extendedprice": price,
        "l_discount": np.round(rng.integers(0, 11, n_li) / 100.0, 2),
        "l_tax": np.round(rng.integers(0, 9, n_li) / 100.0, 2),
        "l_returnflag": returnflag,
        "l_linestatus": linestatus,
        "l_shipdate": l_ship.astype("datetime64[D]"),
        "l_commitdate": (l_odate + commit_delta).astype("datetime64[D]"),
        "l_receiptdate": l_receipt.astype("datetime64[D]"),
        "l_shipinstruct": np.array(INSTRUCTS)[rng.integers(0, 4, n_li)],
        "l_shipmode": np.array(SHIPMODES)[rng.integers(0, 7, n_li)],
        "l_comment": _comment(rng, n_li, 4),
    }), num_parts)
    return out


def _supplier_comments(rng, n):
    base = _comment(rng, n, 6)
    # plant the spec'd Q16 "Customer Complaints" marker in ~0.05% of rows
    marks = rng.random(n) < 0.0005
    return [(c + " Customer Complaints") if m else c
            for c, m in zip(base, marks)]


def _customer_comments(rng, n):
    base = _comment(rng, n, 6)
    marks = rng.random(n) < 0.01
    return [(c + " special requests") if m else c
            for c, m in zip(base, marks)]


if __name__ == "__main__":
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--root", default="/tmp/tpch")
    ap.add_argument("--sf", type=float, default=0.01)
    ap.add_argument("--parts", type=int, default=4)
    args = ap.parse_args()
    print(generate_tpch(args.root, args.sf, args.parts))
