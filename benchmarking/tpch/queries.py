"""TPC-H Q1–Q22 as daft_tpu DataFrame programs.

Mirrors the role of the reference's ``benchmarking/tpch/answers.py`` (the 22
standard TPC-H queries, which are public specification). Column names are
lowercase (matching our datagen).
"""

from __future__ import annotations

import datetime
from typing import Callable

from daft_tpu import DataFrame, col, lit

GetDF = Callable[[str], DataFrame]


def q1(get_df: GetDF) -> DataFrame:
    li = get_df("lineitem")
    disc_price = col("l_extendedprice") * (1 - col("l_discount"))
    charge = disc_price * (1 + col("l_tax"))
    return (li.where(col("l_shipdate") <= lit(datetime.date(1998, 9, 2)))
            .groupby("l_returnflag", "l_linestatus")
            .agg(col("l_quantity").sum().alias("sum_qty"),
                 col("l_extendedprice").sum().alias("sum_base_price"),
                 disc_price.sum().alias("sum_disc_price"),
                 charge.sum().alias("sum_charge"),
                 col("l_quantity").mean().alias("avg_qty"),
                 col("l_extendedprice").mean().alias("avg_price"),
                 col("l_discount").mean().alias("avg_disc"),
                 col("l_quantity").count().alias("count_order"))
            .sort(["l_returnflag", "l_linestatus"]))


def q2(get_df: GetDF) -> DataFrame:
    region = get_df("region").where(col("r_name") == "EUROPE")
    nation = get_df("nation")
    supplier = get_df("supplier")
    partsupp = get_df("partsupp")
    part = get_df("part").where((col("p_size") == 15)
                                & col("p_type").str.endswith("BRASS"))
    europe = (region
              .join(nation, left_on="r_regionkey", right_on="n_regionkey")
              .join(supplier, left_on="n_nationkey", right_on="s_nationkey")
              .join(partsupp, left_on="s_suppkey", right_on="ps_suppkey"))
    brass = part.join(europe, left_on="p_partkey", right_on="ps_partkey")
    min_cost = brass.groupby("p_partkey").agg(
        col("ps_supplycost").min().alias("min_cost"))
    return (brass.join(min_cost, on="p_partkey")
            .where(col("ps_supplycost") == col("min_cost"))
            .select("s_acctbal", "s_name", "n_name", "p_partkey", "p_mfgr",
                    "s_address", "s_phone", "s_comment")
            .sort(["s_acctbal", "n_name", "s_name", "p_partkey"],
                  desc=[True, False, False, False])
            .limit(100))


def q3(get_df: GetDF) -> DataFrame:
    cust = get_df("customer").where(col("c_mktsegment") == "BUILDING")
    orders = get_df("orders").where(
        col("o_orderdate") < lit(datetime.date(1995, 3, 15)))
    li = get_df("lineitem").where(
        col("l_shipdate") > lit(datetime.date(1995, 3, 15)))
    return (cust.join(orders, left_on="c_custkey", right_on="o_custkey")
            .join(li, left_on="o_orderkey", right_on="l_orderkey")
            .with_column("volume",
                         col("l_extendedprice") * (1 - col("l_discount")))
            .groupby(col("o_orderkey"), col("o_orderdate"),
                     col("o_shippriority"))
            .agg(col("volume").sum().alias("revenue"))
            .sort([col("revenue"), col("o_orderdate")], desc=[True, False])
            .limit(10)
            .select("o_orderkey", "revenue", "o_orderdate", "o_shippriority"))


def q4(get_df: GetDF) -> DataFrame:
    orders = get_df("orders").where(
        (col("o_orderdate") >= lit(datetime.date(1993, 7, 1)))
        & (col("o_orderdate") < lit(datetime.date(1993, 10, 1))))
    late = get_df("lineitem").where(col("l_commitdate") < col("l_receiptdate"))
    return (orders.join(late, left_on="o_orderkey", right_on="l_orderkey",
                        how="semi")
            .groupby("o_orderpriority")
            .agg(col("o_orderkey").count().alias("order_count"))
            .sort("o_orderpriority"))


def q5(get_df: GetDF) -> DataFrame:
    region = get_df("region").where(col("r_name") == "ASIA")
    orders = get_df("orders").where(
        (col("o_orderdate") >= lit(datetime.date(1994, 1, 1)))
        & (col("o_orderdate") < lit(datetime.date(1995, 1, 1))))
    out = (region
           .join(get_df("nation"), left_on="r_regionkey", right_on="n_regionkey")
           .join(get_df("supplier"), left_on="n_nationkey", right_on="s_nationkey")
           .join(get_df("lineitem"), left_on="s_suppkey", right_on="l_suppkey")
           .join(orders, left_on="l_orderkey", right_on="o_orderkey")
           .join(get_df("customer"), left_on=["o_custkey", "s_nationkey"],
                 right_on=["c_custkey", "c_nationkey"]))
    return (out.with_column("volume",
                            col("l_extendedprice") * (1 - col("l_discount")))
            .groupby("n_name")
            .agg(col("volume").sum().alias("revenue"))
            .sort("revenue", desc=True))


def q6(get_df: GetDF) -> DataFrame:
    li = get_df("lineitem")
    return (li.where((col("l_shipdate") >= lit(datetime.date(1994, 1, 1)))
                     & (col("l_shipdate") < lit(datetime.date(1995, 1, 1)))
                     & col("l_discount").between(0.05, 0.07)
                     & (col("l_quantity") < 24))
            .agg((col("l_extendedprice") * col("l_discount")).sum()
                 .alias("revenue")))


def q7(get_df: GetDF) -> DataFrame:
    n1 = get_df("nation").select(col("n_nationkey").alias("supp_nationkey"),
                                 col("n_name").alias("supp_nation"))
    n2 = get_df("nation").select(col("n_nationkey").alias("cust_nationkey"),
                                 col("n_name").alias("cust_nation"))
    li = get_df("lineitem").where(
        (col("l_shipdate") >= lit(datetime.date(1995, 1, 1)))
        & (col("l_shipdate") <= lit(datetime.date(1996, 12, 31))))
    out = (li
           .join(get_df("supplier"), left_on="l_suppkey", right_on="s_suppkey")
           .join(get_df("orders"), left_on="l_orderkey", right_on="o_orderkey")
           .join(get_df("customer"), left_on="o_custkey", right_on="c_custkey")
           .join(n1, left_on="s_nationkey", right_on="supp_nationkey")
           .join(n2, left_on="c_nationkey", right_on="cust_nationkey")
           .where(((col("supp_nation") == "FRANCE")
                   & (col("cust_nation") == "GERMANY"))
                  | ((col("supp_nation") == "GERMANY")
                     & (col("cust_nation") == "FRANCE"))))
    return (out.with_column("l_year", col("l_shipdate").dt.year())
            .with_column("volume",
                         col("l_extendedprice") * (1 - col("l_discount")))
            .groupby("supp_nation", "cust_nation", "l_year")
            .agg(col("volume").sum().alias("revenue"))
            .sort(["supp_nation", "cust_nation", "l_year"]))


def q8(get_df: GetDF) -> DataFrame:
    region = get_df("region").where(col("r_name") == "AMERICA")
    part = get_df("part").where(col("p_type") == "ECONOMY ANODIZED STEEL")
    orders = get_df("orders").where(
        (col("o_orderdate") >= lit(datetime.date(1995, 1, 1)))
        & (col("o_orderdate") <= lit(datetime.date(1996, 12, 31))))
    n2 = get_df("nation").select(col("n_nationkey").alias("supp_nationkey"),
                                 col("n_name").alias("supp_nation"))
    out = (part
           .join(get_df("lineitem"), left_on="p_partkey", right_on="l_partkey")
           .join(orders, left_on="l_orderkey", right_on="o_orderkey")
           .join(get_df("customer"), left_on="o_custkey", right_on="c_custkey")
           .join(get_df("nation"), left_on="c_nationkey", right_on="n_nationkey")
           .join(region, left_on="n_regionkey", right_on="r_regionkey")
           .join(get_df("supplier"), left_on="l_suppkey", right_on="s_suppkey")
           .join(n2, left_on="s_nationkey", right_on="supp_nationkey"))
    out = (out.with_column("o_year", col("o_orderdate").dt.year())
           .with_column("volume",
                        col("l_extendedprice") * (1 - col("l_discount")))
           .with_column("brazil_volume",
                        (col("supp_nation") == "BRAZIL")
                        .if_else(col("volume"), 0.0)))
    return (out.groupby("o_year")
            .agg(col("brazil_volume").sum().alias("brazil"),
                 col("volume").sum().alias("total"))
            .select(col("o_year"),
                    (col("brazil") / col("total")).alias("mkt_share"))
            .sort("o_year"))


def q9(get_df: GetDF) -> DataFrame:
    part = get_df("part").where(col("p_name").str.contains("green"))
    out = (part
           .join(get_df("partsupp"), left_on="p_partkey", right_on="ps_partkey")
           .join(get_df("lineitem"),
                 left_on=["p_partkey", "ps_suppkey"],
                 right_on=["l_partkey", "l_suppkey"])
           .join(get_df("supplier"), left_on="ps_suppkey", right_on="s_suppkey")
           .join(get_df("orders"), left_on="l_orderkey", right_on="o_orderkey")
           .join(get_df("nation"), left_on="s_nationkey", right_on="n_nationkey"))
    amount = (col("l_extendedprice") * (1 - col("l_discount"))
              - col("ps_supplycost") * col("l_quantity"))
    return (out.with_column("o_year", col("o_orderdate").dt.year())
            .with_column("amount", amount)
            .groupby(col("n_name").alias("nation"), col("o_year"))
            .agg(col("amount").sum().alias("sum_profit"))
            .sort(["nation", "o_year"], desc=[False, True]))


def q10(get_df: GetDF) -> DataFrame:
    orders = get_df("orders").where(
        (col("o_orderdate") >= lit(datetime.date(1993, 10, 1)))
        & (col("o_orderdate") < lit(datetime.date(1994, 1, 1))))
    li = get_df("lineitem").where(col("l_returnflag") == "R")
    out = (get_df("customer")
           .join(orders, left_on="c_custkey", right_on="o_custkey")
           .join(li, left_on="o_orderkey", right_on="l_orderkey")
           .join(get_df("nation"), left_on="c_nationkey", right_on="n_nationkey"))
    return (out.with_column("volume",
                            col("l_extendedprice") * (1 - col("l_discount")))
            .groupby("c_custkey", "c_name", "c_acctbal", "c_phone", "n_name",
                     "c_address", "c_comment")
            .agg(col("volume").sum().alias("revenue"))
            .sort([col("revenue"), col("c_custkey")], desc=[True, False])
            .limit(20)
            .select("c_custkey", "c_name", "revenue", "c_acctbal", "n_name",
                    "c_address", "c_phone", "c_comment"))


def q11(get_df: GetDF) -> DataFrame:
    germany = (get_df("nation").where(col("n_name") == "GERMANY")
               .join(get_df("supplier"), left_on="n_nationkey",
                     right_on="s_nationkey")
               .join(get_df("partsupp"), left_on="s_suppkey",
                     right_on="ps_suppkey"))
    germany = germany.with_column(
        "value", col("ps_supplycost") * col("ps_availqty"))
    total = germany.agg((col("value").sum() * 0.0001).alias("threshold"))
    by_part = germany.groupby("ps_partkey").agg(
        col("value").sum().alias("part_value"))
    return (by_part.join(total, how="cross")
            .where(col("part_value") > col("threshold"))
            .select(col("ps_partkey"), col("part_value").alias("value"))
            .sort("value", desc=True))


def q12(get_df: GetDF) -> DataFrame:
    li = get_df("lineitem").where(
        col("l_shipmode").is_in(["MAIL", "SHIP"])
        & (col("l_commitdate") < col("l_receiptdate"))
        & (col("l_shipdate") < col("l_commitdate"))
        & (col("l_receiptdate") >= lit(datetime.date(1994, 1, 1)))
        & (col("l_receiptdate") < lit(datetime.date(1995, 1, 1))))
    out = get_df("orders").join(li, left_on="o_orderkey",
                                right_on="l_orderkey")
    is_high = col("o_orderpriority").is_in(["1-URGENT", "2-HIGH"])
    return (out
            .with_column("high", is_high.if_else(1, 0))
            .with_column("low", is_high.if_else(0, 1))
            .groupby("l_shipmode")
            .agg(col("high").sum().alias("high_line_count"),
                 col("low").sum().alias("low_line_count"))
            .sort("l_shipmode"))


def q13(get_df: GetDF) -> DataFrame:
    orders = get_df("orders").where(
        ~col("o_comment").str.match(".*special.*requests.*"))
    counts = (get_df("customer")
              .join(orders, left_on="c_custkey", right_on="o_custkey",
                    how="left")
              .groupby("c_custkey")
              .agg(col("o_orderkey").count().alias("c_count")))
    return (counts.groupby("c_count")
            .agg(col("c_custkey").count().alias("custdist"))
            .sort(["custdist", "c_count"], desc=[True, True]))


def q14(get_df: GetDF) -> DataFrame:
    li = get_df("lineitem").where(
        (col("l_shipdate") >= lit(datetime.date(1995, 9, 1)))
        & (col("l_shipdate") < lit(datetime.date(1995, 10, 1))))
    out = li.join(get_df("part"), left_on="l_partkey", right_on="p_partkey")
    vol = col("l_extendedprice") * (1 - col("l_discount"))
    promo = col("p_type").str.startswith("PROMO")
    return (out.with_column("volume", vol)
            .with_column("promo_volume", promo.if_else(col("volume"), 0.0))
            .agg(col("promo_volume").sum().alias("promo"),
                 col("volume").sum().alias("total"))
            .select((100.0 * col("promo") / col("total"))
                    .alias("promo_revenue")))


def q15(get_df: GetDF) -> DataFrame:
    li = get_df("lineitem").where(
        (col("l_shipdate") >= lit(datetime.date(1996, 1, 1)))
        & (col("l_shipdate") < lit(datetime.date(1996, 4, 1))))
    revenue = (li.with_column("v", col("l_extendedprice") * (1 - col("l_discount")))
               .groupby(col("l_suppkey").alias("supplier_no"))
               .agg(col("v").sum().alias("total_revenue")))
    top = revenue.agg(col("total_revenue").max().alias("max_revenue"))
    return (revenue.join(top, how="cross")
            .where(col("total_revenue") == col("max_revenue"))
            .join(get_df("supplier"), left_on="supplier_no",
                  right_on="s_suppkey")
            .select(col("supplier_no").alias("s_suppkey"),
                    "s_name", "s_address", "s_phone", "total_revenue")
            .sort("s_suppkey"))


def q16(get_df: GetDF) -> DataFrame:
    part = get_df("part").where(
        (col("p_brand") != "Brand#45")
        & ~col("p_type").str.startswith("MEDIUM POLISHED")
        & col("p_size").is_in([49, 14, 23, 45, 19, 3, 36, 9]))
    bad_supp = get_df("supplier").where(
        col("s_comment").str.match(".*Customer.*Complaints.*"))
    ps = (get_df("partsupp")
          .join(bad_supp, left_on="ps_suppkey", right_on="s_suppkey",
                how="anti"))
    return (part.join(ps, left_on="p_partkey", right_on="ps_partkey")
            .groupby("p_brand", "p_type", "p_size")
            .agg(col("ps_suppkey").count_distinct().alias("supplier_cnt"))
            .sort([col("supplier_cnt"), col("p_brand"), col("p_type"),
                   col("p_size")], desc=[True, False, False, False]))


def q17(get_df: GetDF) -> DataFrame:
    part = get_df("part").where((col("p_brand") == "Brand#23")
                                & (col("p_container") == "MED BOX"))
    li = get_df("lineitem")
    joined = part.join(li, left_on="p_partkey", right_on="l_partkey")
    avg_qty = (joined.groupby("p_partkey")
               .agg((col("l_quantity").mean() * 0.2).alias("avg_qty_threshold")))
    return (joined.join(avg_qty, on="p_partkey")
            .where(col("l_quantity") < col("avg_qty_threshold"))
            .agg((col("l_extendedprice").sum() / 7.0).alias("avg_yearly")))


def q18(get_df: GetDF) -> DataFrame:
    big = (get_df("lineitem").groupby("l_orderkey")
           .agg(col("l_quantity").sum().alias("sum_qty"))
           .where(col("sum_qty") > 300))
    return (get_df("orders")
            .join(big, left_on="o_orderkey", right_on="l_orderkey")
            .join(get_df("customer"), left_on="o_custkey", right_on="c_custkey")
            .select("c_name", "o_custkey", "o_orderkey", "o_orderdate",
                    "o_totalprice", col("sum_qty").alias("total_quantity"))
            .sort([col("o_totalprice"), col("o_orderdate")],
                  desc=[True, False])
            .limit(100))


def q19(get_df: GetDF) -> DataFrame:
    out = get_df("lineitem").join(get_df("part"), left_on="l_partkey",
                                  right_on="p_partkey")
    common = (col("l_shipinstruct") == "DELIVER IN PERSON") \
        & col("l_shipmode").is_in(["AIR", "AIR REG"])
    b1 = ((col("p_brand") == "Brand#12")
          & col("p_container").is_in(["SM CASE", "SM BOX", "SM PACK", "SM PKG"])
          & (col("l_quantity") >= 1) & (col("l_quantity") <= 11)
          & col("p_size").between(1, 5))
    b2 = ((col("p_brand") == "Brand#23")
          & col("p_container").is_in(["MED BAG", "MED BOX", "MED PKG", "MED PACK"])
          & (col("l_quantity") >= 10) & (col("l_quantity") <= 20)
          & col("p_size").between(1, 10))
    b3 = ((col("p_brand") == "Brand#34")
          & col("p_container").is_in(["LG CASE", "LG BOX", "LG PACK", "LG PKG"])
          & (col("l_quantity") >= 20) & (col("l_quantity") <= 30)
          & col("p_size").between(1, 15))
    return (out.where(common & (b1 | b2 | b3))
            .agg((col("l_extendedprice") * (1 - col("l_discount"))).sum()
                 .alias("revenue")))


def q20(get_df: GetDF) -> DataFrame:
    forest_parts = get_df("part").where(
        col("p_name").str.startswith("forest")).select("p_partkey")
    shipped = (get_df("lineitem").where(
        (col("l_shipdate") >= lit(datetime.date(1994, 1, 1)))
        & (col("l_shipdate") < lit(datetime.date(1995, 1, 1))))
        .groupby("l_partkey", "l_suppkey")
        .agg((col("l_quantity").sum() * 0.5).alias("half_qty")))
    eligible_ps = (get_df("partsupp")
                   .join(forest_parts, left_on="ps_partkey",
                         right_on="p_partkey", how="semi")
                   .join(shipped, left_on=["ps_partkey", "ps_suppkey"],
                         right_on=["l_partkey", "l_suppkey"])
                   .where(col("ps_availqty") > col("half_qty")))
    canada = (get_df("supplier")
              .join(get_df("nation").where(col("n_name") == "CANADA"),
                    left_on="s_nationkey", right_on="n_nationkey"))
    return (canada.join(eligible_ps, left_on="s_suppkey",
                        right_on="ps_suppkey", how="semi")
            .select("s_name", "s_address")
            .sort("s_name"))


def q21(get_df: GetDF) -> DataFrame:
    saudi_supp = (get_df("supplier")
                  .join(get_df("nation").where(col("n_name") == "SAUDI ARABIA"),
                        left_on="s_nationkey", right_on="n_nationkey"))
    li = get_df("lineitem")
    l1 = li.where(col("l_receiptdate") > col("l_commitdate"))
    failed_orders = get_df("orders").where(col("o_orderstatus") == "F")
    base = (l1.join(failed_orders, left_on="l_orderkey",
                    right_on="o_orderkey", how="semi")
            .join(saudi_supp, left_on="l_suppkey", right_on="s_suppkey"))
    # exists: another supplier on the same order
    others = (li.select(col("l_orderkey").alias("o2_orderkey"),
                        col("l_suppkey").alias("o2_suppkey"))
              .distinct())
    multi = (base.join(others, left_on="l_orderkey", right_on="o2_orderkey")
             .where(col("o2_suppkey") != col("l_suppkey"))
             .select("l_orderkey", "l_suppkey").distinct())
    base_keys = base.select("l_orderkey", "l_suppkey", "s_name").distinct()
    with_exists = base_keys.join(multi, on=["l_orderkey", "l_suppkey"],
                                 how="semi")
    # not exists: another supplier who ALSO missed the deadline on the order
    late_others = (l1.select(col("l_orderkey").alias("lo_orderkey"),
                             col("l_suppkey").alias("lo_suppkey"))
                   .distinct())
    pairs = (with_exists.join(late_others, left_on="l_orderkey",
                              right_on="lo_orderkey")
             .where(col("lo_suppkey") != col("l_suppkey"))
             .select("l_orderkey", "l_suppkey").distinct())
    final = with_exists.join(pairs, on=["l_orderkey", "l_suppkey"], how="anti")
    return (final.groupby("s_name")
            .agg(col("l_orderkey").count().alias("numwait"))
            .sort([col("numwait"), col("s_name")], desc=[True, False])
            .limit(100))


def q22(get_df: GetDF) -> DataFrame:
    codes = ["13", "31", "23", "29", "30", "18", "17"]
    cust = (get_df("customer")
            .with_column("cntrycode", col("c_phone").str.left(2))
            .where(col("cntrycode").is_in(codes)))
    avg_bal = (cust.where(col("c_acctbal") > 0.0)
               .agg(col("c_acctbal").mean().alias("avg_acctbal")))
    no_orders = cust.join(get_df("orders"), left_on="c_custkey",
                          right_on="o_custkey", how="anti")
    return (no_orders.join(avg_bal, how="cross")
            .where(col("c_acctbal") > col("avg_acctbal"))
            .groupby("cntrycode")
            .agg(col("c_acctbal").count().alias("numcust"),
                 col("c_acctbal").sum().alias("totacctbal"))
            .sort("cntrycode"))


ALL = {i: fn for i, fn in enumerate(
    [q1, q2, q3, q4, q5, q6, q7, q8, q9, q10, q11, q12, q13, q14, q15, q16,
     q17, q18, q19, q20, q21, q22], start=1)}
