"""TPC-H queries expressible only via subqueries, as SQL text.

The DataFrame forms in ``queries.py`` hand-decorrelate these (explicit
joins); these texts exercise the SQL frontend's subquery support —
``Expr::Subquery/InSubquery/Exists`` in the reference
(``src/daft-dsl/src/expr/mod.rs:213-292``, unnested by
``optimization/rules/unnest_subquery.rs``; here ``daft_tpu/logical/
subquery.py``)."""

Q4 = """
SELECT o_orderpriority, count(*) AS order_count
FROM orders
WHERE o_orderdate >= DATE '1993-07-01'
  AND o_orderdate < DATE '1993-10-01'
  AND EXISTS (
    SELECT * FROM lineitem
    WHERE l_orderkey = o_orderkey AND l_commitdate < l_receiptdate)
GROUP BY o_orderpriority
ORDER BY o_orderpriority
"""

Q17 = """
SELECT sum(l_extendedprice) / 7.0 AS avg_yearly
FROM lineitem, part
WHERE p_partkey = l_partkey
  AND p_brand = 'Brand#23'
  AND p_container = 'MED BOX'
  AND l_quantity < (
    SELECT 0.2 * avg(l_quantity) FROM lineitem WHERE l_partkey = p_partkey)
"""

Q20 = """
SELECT s_name, s_address
FROM supplier, nation
WHERE s_suppkey IN (
    SELECT ps_suppkey FROM partsupp
    WHERE ps_partkey IN (
        SELECT p_partkey FROM part WHERE p_name LIKE 'forest%')
      AND ps_availqty > (
        SELECT 0.5 * sum(l_quantity) FROM lineitem
        WHERE l_partkey = ps_partkey AND l_suppkey = ps_suppkey
          AND l_shipdate >= DATE '1994-01-01'
          AND l_shipdate < DATE '1995-01-01'))
  AND s_nationkey = n_nationkey
  AND n_name = 'CANADA'
ORDER BY s_name
"""

Q22 = """
SELECT cntrycode, count(*) AS numcust, sum(c_acctbal) AS totacctbal
FROM (
  SELECT substr(c_phone, 1, 2) AS cntrycode, c_acctbal
  FROM customer
  WHERE substr(c_phone, 1, 2) IN ('13', '31', '23', '29', '30', '18', '17')
    AND c_acctbal > (
      SELECT avg(c_acctbal) FROM customer
      WHERE c_acctbal > 0.00
        AND substr(c_phone, 1, 2) IN ('13', '31', '23', '29', '30', '18',
                                      '17'))
    AND NOT EXISTS (
      SELECT * FROM orders WHERE o_custkey = c_custkey)
) AS custsale
GROUP BY cntrycode
ORDER BY cntrycode
"""

SUBQUERY_QUERIES = {"q4": Q4, "q17": Q17, "q20": Q20, "q22": Q22}
